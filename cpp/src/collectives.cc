// tpunet collectives over the multi-stream transport. See collectives.h for
// the public contract and coll_comm.h for the internal split:
//
// This TU owns the communicator LIFECYCLE (bootstrap rendezvous, codec +
// schedule negotiation, ring/mesh wiring, teardown), the per-call SCHEDULE
// DISPATCH (dispatch.h selector: ring / recursive halving-doubling /
// binomial tree by (collective, payload bytes, world)), the byte-oriented
// collectives that ride the wiring directly (AllToAll, NeighborExchange,
// Barrier), and the async ticket machinery. The algorithms themselves live
// in schedule_{ring,rhd,tree}.cc.
//
// Every ring step posts the irecv before the isend and waits on both — each
// rank sends to (rank+1)%W and receives from (rank-1+W)%W over independent
// full-duplex comms, so the ring cannot deadlock. Mesh steps (rhd/tree)
// follow the same recv-first discipline on per-peer comm pairs.
#include "tpunet/collectives.h"

#include <string.h>

#include <algorithm>
#include <string>
#include <utility>
#include <vector>

#include "coll_comm.h"
#include "dispatch.h"
#include "wire.h"
#include "tpunet/bootstrap.h"
#include "tpunet/mutex.h"
#include "tpunet/telemetry.h"
#include "tpunet/utils.h"

namespace tpunet {

size_t DTypeSize(DType d) {
  switch (d) {
    case DType::kF32:
      return 4;
    case DType::kF64:
      return 8;
    case DType::kBF16:
      return 2;
    case DType::kI32:
      return 4;
    case DType::kI64:
      return 8;
    case DType::kU8:
      return 1;
  }
  return 0;
}

namespace internal {

ScheduledCommunicator::~ScheduledCommunicator() {
  StopAsyncWorker();
  if (net_) {
    for (uint64_t c : mesh_send_) {
      if (c) net_->close_send(c);
    }
    for (uint64_t c : mesh_recv_) {
      if (c) net_->close_recv(c);
    }
    for (RingChannel& ch : channels_) {
      if (ch.send_comm) net_->close_send(ch.send_comm);
      if (ch.recv_comm) net_->close_recv(ch.recv_comm);
    }
    if (listen_comm_) net_->close_listen(listen_comm_);
  }
}

Status ScheduledCommunicator::Init(const std::string& coordinator) {
  net_ = CreateEngine();
  // Every comm this communicator wires (ring channels, mesh pairs, async
  // channels) carries the negotiated traffic class — set before the first
  // connect so the preamble nibble is right from comm zero.
  net_->set_traffic_class(static_cast<int32_t>(cls_));
  // Trace identity: every rank hashes the SAME coordinator string and
  // world size, so (comm_id, coll_seq) tags agree across ranks without a
  // wire round. |1 keeps it nonzero even for a degenerate hash.
  trace_comm_id_ =
      (static_cast<uint64_t>(Crc32c(coordinator.data(), coordinator.size())) |
       (static_cast<uint64_t>(world_) << 32)) | 1ull;
  channels_.resize(1);
  // The offline-tuned dispatch table (busbw_sweep --emit-dispatch) loads
  // per communicator so elastic rebuilds pick up a re-tuned file; a
  // malformed table fails creation loudly rather than silently running the
  // built-in thresholds the operator thought they replaced.
  std::string table_path = GetEnv("TPUNET_DISPATCH_TABLE", "");
  if (!table_path.empty()) {
    Status ts = LoadDispatchTableFile(table_path, &dispatch_);
    if (!ts.ok()) return ts;
  }
  // AllToAll schedule override: TPUNET_A2A_ALGO ("auto" / "pairwise" /
  // "ring" / "hier"; "hier_a2a" accepted as the explicit spelling), with
  // the legacy TPUNET_A2A=ring relay switch folding in as a kRing override.
  // Parsed before the handshake because the byte rides the bootstrap blob.
  {
    std::string a2a_name = GetEnv("TPUNET_A2A_ALGO", "auto");
    CollAlgo a2a;
    if (!ParseCollAlgo(a2a_name, &a2a) ||
        (a2a != CollAlgo::kAuto && a2a != CollAlgo::kPairwise &&
         a2a != CollAlgo::kRing && a2a != CollAlgo::kHier &&
         a2a != CollAlgo::kHierA2a)) {
      return Status::Invalid("unknown a2a algo \"" + a2a_name +
                             "\" (TPUNET_A2A_ALGO expects auto, pairwise, "
                             "ring or hier)");
    }
    if (a2a == CollAlgo::kHier) a2a = CollAlgo::kHierA2a;
    if (a2a == CollAlgo::kAuto && GetEnv("TPUNET_A2A", "pairwise") == "ring") {
      a2a = CollAlgo::kRing;
    }
    a2a_override_ = a2a;
  }
  Status s = Bootstrap::Create(coordinator, rank_, world_, &bootstrap_);
  if (!s.ok()) return s;
  if (world_ == 1) {
    bootstrap_.reset();
    host_ids_.assign(1, HostId());
    return Status::Ok();
  }

  // Schedule-config negotiation, piggybacked on the bootstrap ctrl plane
  // the wiring already rides: one 16-byte AllGather round carrying
  // (wire codec, algo override, dispatch-table CRC32C, QoS class) plus
  // this rank's HOST ID (utils.h HostId() — boot-id/hostname hash or the
  // TPUNET_HOST_ID fake-host override). The config bytes must MATCH on
  // every rank (ALL ranks fail identically on a mismatch — before any comm
  // exists that could mis-decode a payload or run half the world on a
  // different schedule; two schedules deadlock, they don't corrupt); the
  // host ids legitimately differ and become the hierarchical schedule's
  // topology input (host_ids_).
  uint8_t my_blob[kBootstrapBlobLen] = {0};
  my_blob[kBlobOffCodec] = static_cast<uint8_t>(codec_);
  my_blob[kBlobOffAlgo] = static_cast<uint8_t>(algo_override_);
  uint32_t table_crc = dispatch_.loaded ? dispatch_.crc : 0;
  EncodeU32BE(table_crc, my_blob + kBlobOffTableCrc);
  my_blob[kBlobOffQosClass] = static_cast<uint8_t>(cls_);
  my_blob[kBlobOffA2aAlgo] = static_cast<uint8_t>(a2a_override_);
  EncodeU64BE(HostId(), my_blob + kBlobOffHostId);
  std::vector<uint8_t> blobs;
  s = bootstrap_->AllGather(my_blob, sizeof(my_blob), &blobs);
  if (!s.ok()) return s;
  host_ids_.assign(world_, 0);
  for (int r = 0; r < world_; ++r) {
    host_ids_[r] =
        DecodeU64BE(blobs.data() + r * sizeof(my_blob) + kBlobOffHostId);
  }
  for (int r = 0; r < world_; ++r) {
    const uint8_t* theirs = blobs.data() + r * sizeof(my_blob);
    s = CheckPeerBootstrapBlob(my_blob, theirs, rank_, r);
    if (!s.ok()) return s;
  }

  SocketHandle handle;
  s = net_->listen(0, &handle, &listen_comm_);
  if (!s.ok()) return s;
  uint8_t blob[kHandleSize] = {0};
  memcpy(blob, &handle.addr, std::min(sizeof(handle.addr), sizeof(blob)));
  std::vector<uint8_t> all;
  s = bootstrap_->AllGather(blob, kHandleSize, &all);
  if (!s.ok()) return s;

  // Keep every rank's listen handle: the pairwise mesh (AllToAll, rhd, tree)
  // is wired lazily from these on first use (the listeners stay alive for
  // the communicator's lifetime, so no bootstrap round is needed then).
  all_handles_.resize(world_);
  for (int r = 0; r < world_; ++r) {
    memcpy(&all_handles_[r].addr, all.data() + r * kHandleSize, kHandleSize);
    all_handles_[r].addrlen = 0;  // derived from family by the engine
  }

  int next = (rank_ + 1) % world_;
  s = ConnectAndWire(all_handles_[next]);
  if (!s.ok()) return s;
  // The bootstrap's job is done once the ring is wired; dropping it frees
  // the coordinator port and rank 0's W-1 peer sockets so long-lived jobs
  // don't pin fds and another communicator can reuse the address.
  bootstrap_.reset();
  return Status::Ok();
}

Status ScheduledCommunicator::ConnectAndWire(const SocketHandle& next_handle) {
  Status s = net_->connect(0, next_handle, &channels_[0].send_comm);
  if (!s.ok()) return s;
  // Barrier BEFORE accept: once it passes, every rank has connected to its
  // next, so our prev's bundle is already inbound and accept() cannot
  // block forever. A rank that died earlier fails the barrier with a clean
  // error instead of wedging the ring (observed: peer death between
  // bootstrap and connect hung accept indefinitely).
  s = bootstrap_->Barrier();
  if (!s.ok()) return s;
  return net_->accept(listen_comm_, &channels_[0].recv_comm);
}

// ---------------------------------------------------------------------------
// Dispatch.

CollAlgo ScheduledCommunicator::ResolveAlgo(CollKind coll, uint64_t nbytes) {
  // Degenerate calls never reach a schedule (DoAllReduce/Broadcast
  // early-return) — don't let them pollute the selection counters.
  if (world_ <= 1 || nbytes == 0) return CollAlgo::kRing;
  CollAlgo a = SelectCollAlgo(dispatch_, algo_override_, coll, nbytes, world_);
  // Topology post-pass: hier on a flat/irregular topology degrades to
  // ring; built-in auto on a profitable hierarchy upgrades large ring
  // AllReduces to hier. Deterministic from negotiated state (host_ids_ came
  // off the same handshake on every rank), so every rank agrees.
  a = ApplyHierPolicy(a, coll, nbytes, HierUsable(), HierProfitable(),
                      algo_override_ == CollAlgo::kAuto && !dispatch_.loaded);
  // Halving-doubling / hier are AllReduce shapes; a Broadcast pinned (or
  // table-routed) to them runs the ring relay — the counter records what
  // RAN.
  if (coll == CollKind::kBroadcast &&
      (a == CollAlgo::kRhd || a == CollAlgo::kHier)) {
    a = CollAlgo::kRing;
  }
  CountCollAlgoSelected(coll, a);
  flightrec::Record(flightrec::Ev::kCollSubmit, static_cast<uint64_t>(coll),
                    static_cast<uint64_t>(a), nbytes);
  return a;
}

Status ScheduledCommunicator::DoAllReduce(const void* sendbuf, void* recvbuf,
                                          size_t count, DType dtype, RedOp op,
                                          RingChannel& ch, uint64_t seq,
                                          CollAlgo algo) {
  size_t esize = DTypeSize(dtype);
  if (esize == 0) return Status::Invalid("bad dtype");
  if (count == 0) return Status::Ok();
  if (world_ == 1) {
    if (sendbuf != recvbuf) memcpy(recvbuf, sendbuf, count * esize);
    return Status::Ok();
  }
  switch (algo) {
    case CollAlgo::kRhd:
      return DoAllReduceRhd(sendbuf, recvbuf, count, dtype, op, seq);
    case CollAlgo::kTree:
      return DoAllReduceTree(sendbuf, recvbuf, count, dtype, op, seq);
    case CollAlgo::kHier:
      return DoAllReduceHier(sendbuf, recvbuf, count, dtype, op, seq);
    default:
      return DoAllReduceRing(sendbuf, recvbuf, count, dtype, op, ch, seq);
  }
}

// Blocking AllReduce IS IAllReduce + WaitTicket. This is not a
// convenience: the cross-rank matching rule (MPI/NCCL semantics) lets one
// rank call AllReduce where another calls IAllReduce+wait for the same
// collective, so BOTH kinds must consume the same ticket sequence — the
// ticket->channel map is what pairs ring messages across ranks, and a
// blocking call that bypassed it would desync (and never wire channels on
// ranks that only ever call the blocking form). Schedule selection happens
// at submission, identically for both forms.
Status ScheduledCommunicator::AllReduce(const void* sendbuf, void* recvbuf,
                                        size_t count, DType dtype, RedOp op) {
  // Single-channel mode: everything rides channel 0 in submission order,
  // so pairing cannot desync and the caller thread can run the schedule
  // directly (no worker hop) — also the kill switch for the ticketed path.
  if (AsyncChannelCount() == 1) {
    FenceAsync();
    size_t esize = DTypeSize(dtype);
    if (esize == 0) return Status::Invalid("bad dtype");
    CollAlgo algo = ResolveAlgo(CollKind::kAllReduce, count * esize);
    return DoAllReduce(sendbuf, recvbuf, count, dtype, op, channels_[0],
                       ++coll_seq_, algo);
  }
  // Fence first: the documented contract is that a blocking collective
  // orders AFTER all outstanding tickets (callers rely on it for buffer
  // reuse). Fencing consumes no ticket, so it cannot desync pairing.
  FenceAsync();
  uint64_t ticket = 0;
  Status s = IAllReduce(sendbuf, recvbuf, count, dtype, op, &ticket);
  if (!s.ok()) return s;
  return WaitTicket(ticket);
}

Status ScheduledCommunicator::Broadcast(void* buf, size_t nbytes, int root) {
  FenceAsync();
  if (world_ == 1 || nbytes == 0) return Status::Ok();
  if (root < 0 || root >= world_) return Status::Invalid("bad broadcast root");
  CollAlgo algo = ResolveAlgo(CollKind::kBroadcast, nbytes);
  uint64_t seq = ++coll_seq_;
  if (algo == CollAlgo::kTree) return DoBroadcastTree(buf, nbytes, root, seq);
  return DoBroadcastRing(buf, nbytes, root, seq);
}

// ---------------------------------------------------------------------------
// Mesh wiring + the byte-oriented collectives that ride it.

// Accept one inbound comm off the shared listener and read its 8-byte
// identifying hello. On failure the comm (if any) is closed. Shared by
// the two lazy wiring paths (pairwise mesh, async ring channels), which
// differ only in how they encode/validate the hello.
Status ScheduledCommunicator::AcceptHello(uint64_t* rc, uint64_t* hello) {
  *rc = 0;
  Status s = net_->accept(listen_comm_, rc);
  if (!s.ok()) return s;
  uint8_t buf[8] = {0};
  uint64_t req = 0;
  size_t got = 0;
  s = net_->irecv(*rc, buf, sizeof(buf), &req);
  if (s.ok()) s = net_->wait(req, &got);
  if (s.ok() && got != sizeof(buf)) s = Status::Inner("wiring hello truncated");
  if (!s.ok()) {
    net_->close_recv(*rc);
    *rc = 0;
    return s;
  }
  *hello = DecodeU64BE(buf);
  return Status::Ok();
}

// Connect to a peer's listener and identify the new comm with an 8-byte
// hello — the other half of AcceptHello.
Status ScheduledCommunicator::ConnectHello(int peer, uint64_t hello, uint64_t* comm) {
  Status s = net_->connect(0, all_handles_[peer], comm);
  if (!s.ok()) return s;
  uint8_t buf[8];
  EncodeU64BE(hello, buf);
  uint64_t req = 0;
  s = net_->isend(*comm, buf, sizeof(buf), &req);
  if (s.ok()) s = net_->wait(req, nullptr);
  return s;
}

// Lazily wire one send + one recv comm per peer over the listeners whose
// handles Init gathered. Every rank first issues all its connects (TCP
// backlog + buffered preamble mean connect never blocks on the peer
// calling accept), sends an 8-byte rank hello on each new comm, then
// accepts its W-1 inbound comms and reads the hellos to key them by
// peer — no bootstrap round, no cross-rank ordering assumption.
Status ScheduledCommunicator::EnsureMesh() {
  if (!mesh_send_.empty()) return Status::Ok();
  const int W = world_;
  std::vector<uint64_t> msend(W, 0), mrecv(W, 0);
  Status result = Status::Ok();
  for (int p = 0; p < W && result.ok(); ++p) {
    if (p == rank_) continue;
    result = ConnectHello(p, static_cast<uint64_t>(rank_), &msend[p]);
  }
  for (int i = 0; i < W - 1 && result.ok(); ++i) {
    uint64_t rc = 0, peer = 0;
    result = AcceptHello(&rc, &peer);
    if (!result.ok()) break;
    if (peer >= static_cast<uint64_t>(W) || peer == static_cast<uint64_t>(rank_) ||
        mrecv[peer] != 0) {
      net_->close_recv(rc);
      result = Status::Inner("mesh hello names invalid peer rank " +
                             std::to_string(peer));
    } else {
      mrecv[peer] = rc;
    }
  }
  if (!result.ok()) {
    for (uint64_t c : msend) {
      if (c) net_->close_send(c);
    }
    for (uint64_t c : mrecv) {
      if (c) net_->close_recv(c);
    }
    return result;
  }
  mesh_send_ = std::move(msend);
  mesh_recv_ = std::move(mrecv);
  return Status::Ok();
}

// EnsureMesh + one-time quiesce: W-1 one-byte ring steps OVER THE MESH
// COMMS. Completing them implies every rank finished its accept loop (a
// rank can only relay the token once its own mesh is wired), so a rank
// that wires fast cannot run ahead into another listener-touching op
// (EnsureAsyncChannels' channel hellos would be hard errors in a peer's
// mesh accept loop). Riding the mesh instead of channel 0 keeps this —
// and every mesh-schedule job after it — disjoint from the ring channels,
// which is what lets the dedicated mesh worker overlap ring tickets.
Status ScheduledCommunicator::EnsureMeshQuiesced() {
  Status s = EnsureMesh();
  if (!s.ok()) return s;
  if (mesh_quiesced_ || world_ == 1) return Status::Ok();
  const int next = (rank_ + 1) % world_;
  const int prev = (rank_ + world_ - 1) % world_;
  for (int st = 0; st < world_ - 1; ++st) {
    uint8_t token_out = 1, token_in = 0;
    s = MeshShift(next, &token_out, 1, prev, &token_in, 1);
    if (!s.ok()) return s;
  }
  mesh_quiesced_ = true;
  return Status::Ok();
}

// Resolve the AllToAll schedule: negotiated override (TPUNET_A2A_ALGO /
// legacy TPUNET_A2A=ring) > dispatch table (coll="alltoall") > built-in
// pairwise, then the topology post-pass (hier on a profitable hierarchy,
// degrade to pairwise on flat) and the mesh fd/thread budget guard.
// Deterministic from negotiated state, so every rank agrees.
CollAlgo ScheduledCommunicator::ResolveA2aAlgo(uint64_t bytes_per_rank) {
  if (world_ <= 1 || bytes_per_rank == 0) return CollAlgo::kPairwise;
  CollAlgo a = SelectCollAlgo(dispatch_, a2a_override_, CollKind::kAllToAll,
                              static_cast<uint64_t>(world_) * bytes_per_rank,
                              world_);
  a = ApplyHierPolicy(a, CollKind::kAllToAll, bytes_per_rank, HierUsable(),
                      HierProfitable(),
                      a2a_override_ == CollAlgo::kAuto && !dispatch_.loaded);
  // The mesh costs 2*(W-1) comms per rank, each nstreams+1 fds and
  // nstreams+1 threads, so very large worlds fall back to the relay
  // rather than exhausting fds/threads; raise TPUNET_A2A_MESH_MAX_WORLD
  // on hosts provisioned for it (the long-term fix is single-stream
  // mesh comms, which need a per-connect nstreams override in Net).
  static const uint64_t mesh_max_world =
      GetEnvU64("TPUNET_A2A_MESH_MAX_WORLD", 32);
  if ((a == CollAlgo::kPairwise || a == CollAlgo::kHierA2a) &&
      static_cast<uint64_t>(world_) > mesh_max_world) {
    a = CollAlgo::kRing;
  }
  CountCollAlgoSelected(CollKind::kAllToAll, a);
  flightrec::Record(flightrec::Ev::kCollSubmit,
                    static_cast<uint64_t>(CollKind::kAllToAll),
                    static_cast<uint64_t>(a),
                    static_cast<uint64_t>(world_) * bytes_per_rank);
  return a;
}

Status ScheduledCommunicator::AllToAll(const void* sendbuf, void* recvbuf,
                                       size_t bytes_per_rank) {
  FenceAsync();
  CollAlgo algo = ResolveA2aAlgo(bytes_per_rank);
  return DoAllToAll(static_cast<const uint8_t*>(sendbuf),
                    static_cast<uint8_t*>(recvbuf), bytes_per_rank,
                    ++coll_seq_, algo, channels_[0]);
}

// Typed AllToAll (docs/DESIGN.md "Hierarchical AllToAll"): f32 blocks under
// a negotiated codec are encoded ONCE at the source — each (src, dst) block
// encoded independently, so int8 scale blocks restart per block and the
// encoded bytes can forward verbatim through ANY route (pairwise, relay, or
// the two-stage hierarchical transpose) — and decoded ONCE at the
// destination: results are bit-identical across schedules and each block's
// error stays inside the per-hop |err| <= amax/254 bound (one hop total,
// by construction). The self block never crosses a wire and stays exact.
// Every encoded/decoded byte feeds tpunet_codec_bytes_total{codec,dir} and
// the wire-ratio gauge exactly like RS/AG hops (the kernels count), and
// the shipped wire bytes land in tpunet_a2a_bytes_total at the encoded
// size — the DCN-byte cut the codec buys is counter-visible end to end.
Status ScheduledCommunicator::AllToAllTyped(const void* sendbuf, void* recvbuf,
                                            size_t count_per_rank, DType dtype) {
  size_t esize = DTypeSize(dtype);
  if (esize == 0) return Status::Invalid("bad dtype");
  const size_t B = count_per_rank * esize;
  if (!UseCodec(dtype)) return AllToAll(sendbuf, recvbuf, B);
  FenceAsync();
  const int W = world_;
  const size_t n = count_per_rank;
  const float* in_f = static_cast<const float*>(sendbuf);
  float* out_f = static_cast<float*>(recvbuf);
  if (W == 1 || n == 0) {
    if (recvbuf != sendbuf && B > 0) memcpy(recvbuf, sendbuf, B);
    return Status::Ok();
  }
  const size_t w = CodecWireBytes(codec_, n);
  a2a_enc_in_.reserve(static_cast<size_t>(W) * w);
  a2a_enc_out_.reserve(static_cast<size_t>(W) * w);
  for (int j = 0; j < W; ++j) {
    if (j == rank_) continue;  // the self block never crosses a wire
    CodecEncode(codec_, in_f + static_cast<size_t>(j) * n,
                a2a_enc_in_.data() + static_cast<size_t>(j) * w, n);
  }
  // Zero the self slot so the byte core's own-block copy reads initialized
  // memory (the decoded result never looks at it).
  memset(a2a_enc_in_.data() + static_cast<size_t>(rank_) * w, 0, w);
  CollAlgo algo = ResolveA2aAlgo(w);
  Status st = DoAllToAll(a2a_enc_in_.data(), a2a_enc_out_.data(), w,
                         ++coll_seq_, algo, channels_[0]);
  if (!st.ok()) return st;
  for (int j = 0; j < W; ++j) {
    if (j == rank_) continue;
    CodecDecode(codec_, a2a_enc_out_.data() + static_cast<size_t>(j) * w,
                out_f + static_cast<size_t>(j) * n, n);
  }
  if (recvbuf != sendbuf) {
    memcpy(out_f + static_cast<size_t>(rank_) * n,
           in_f + static_cast<size_t>(rank_) * n, B);
  }
  return Status::Ok();
}

// Byte-oriented AllToAll under an already-resolved schedule — the shared
// core of the blocking call, the async ticket job, and the typed wrapper.
Status ScheduledCommunicator::DoAllToAll(const uint8_t* in, uint8_t* out,
                                         size_t B, uint64_t seq, CollAlgo algo,
                                         RingChannel& ch) {
  const int W = world_;
  if (static_cast<const void*>(out) != in) {
    memcpy(out + rank_ * B, in + rank_ * B, B);  // own block stays local
  }
  if (W == 1 || B == 0) return Status::Ok();
  PhaseSpan whole(Telemetry::Get().tracing_enabled(), trace_comm_id_, seq,
                  "all_to_all", -1, static_cast<uint64_t>(W) * B);
  // Two-stage hierarchical transpose on a usable topology; direct pairwise
  // exchange otherwise: O(W*B) bytes on the wire per rank vs the ring
  // relay's O(W^2*B/2) — the difference between usable and quadratic
  // cross-host MoE dispatch / DCN-Ulysses at pod scale. The relay keeps
  // the constant-connection-degree end (TPUNET_A2A=ring, or worlds past
  // the mesh fd budget).
  if (algo == CollAlgo::kHierA2a) return DoAllToAllHier(in, out, B, seq);
  if (algo != CollAlgo::kRing) {
    Status st = PairwiseAllToAll(in, out, B);
    if (st.ok()) {
      CountA2aBytes(2, 0, static_cast<uint64_t>(W - 1) * B);
      CountA2aBytes(2, 1, static_cast<uint64_t>(W - 1) * B);
    }
    return st;
  }

  // Store-and-forward relay. Packet invariant at step s: the packet holds
  // nblk = W-1-s blocks; position p carries the block with nblk-p hops of
  // remaining travel (descending). After one Exchange hop every block's
  // remaining distance drops by one: the last block has arrived (it is the
  // block rank (rank-s-1) addressed to us), the rest forward verbatim next
  // step. Both sides compute identical per-step sizes, so the fixed-size
  // Exchange path (got=nullptr) catches rank disagreement as an error.
  // Scratch lives in the CHANNEL (not the communicator): a relay ticket
  // owns its ring channel for the job's duration, so channel scratch can
  // never race the mesh queue's a2a_* buffers.
  ch.scratch.reserve(2 * static_cast<size_t>(W - 1) * B);
  uint8_t* fwd = ch.scratch.data();
  uint8_t* rcv = ch.scratch.data() + static_cast<size_t>(W - 1) * B;
  for (int p = 0; p < W - 1; ++p) {
    int dest = (rank_ + (W - 1 - p)) % W;
    memcpy(fwd + static_cast<size_t>(p) * B, in + dest * B, B);
  }
  for (int s = 0; s < W - 1; ++s) {
    size_t nblk = static_cast<size_t>(W - 1 - s);
    Status st = Exchange(fwd, nblk * B, rcv, nblk * B, nullptr, ch);
    if (!st.ok()) return st;
    CountA2aBytes(2, 0, nblk * B);
    CountA2aBytes(2, 1, nblk * B);
    int src = (rank_ - s - 1 + W) % W;
    memcpy(out + src * B, rcv + (nblk - 1) * B, B);
    std::swap(fwd, rcv);
  }
  return Status::Ok();
}

// One B-sized message to every peer, one from every peer, all posted
// up-front on dedicated per-peer comms (so no message queues behind
// another), then quiesced recv-first. O(W*B) wire bytes per rank.
Status ScheduledCommunicator::PairwiseAllToAll(const uint8_t* in, uint8_t* out,
                                               size_t B) {
  Status st = EnsureMeshQuiesced();
  if (!st.ok()) return st;
  const int W = world_;
  // In-place callers overwrite recv block p while block p is still being
  // sent to peer p (send/recv blocks coincide in this collective) — stage
  // the outgoing blocks.
  const uint8_t* src = in;
  if (in == out) {
    a2a_fwd_.reserve(static_cast<size_t>(W) * B);
    memcpy(a2a_fwd_.data(), in, static_cast<size_t>(W) * B);
    src = a2a_fwd_.data();
  }
  std::vector<uint64_t> rreqs, sreqs;
  std::vector<int> rpeers, speers;
  Status first = Status::Ok();
  for (int s = 1; s < W; ++s) {
    int to = (rank_ + s) % W;
    int from = (rank_ - s + W) % W;
    uint64_t rreq = 0, sreq = 0;
    Status a = net_->irecv(mesh_recv_[from], out + from * B, B, &rreq);
    if (a.ok()) {
      rreqs.push_back(rreq);
      rpeers.push_back(from);
    } else if (first.ok()) {
      first = a;
    }
    Status b = net_->isend(mesh_send_[to], src + to * B, B, &sreq);
    if (b.ok()) {
      sreqs.push_back(sreq);
      speers.push_back(to);
    } else if (first.ok()) {
      first = b;
    }
  }
  for (size_t i = 0; i < rreqs.size(); ++i) {
    size_t got = 0;
    Status a = net_->wait(rreqs[i], &got);
    if (a.ok() && got != B) {
      a = Status::Inner("all_to_all block from rank " + std::to_string(rpeers[i]) +
                        ": got " + std::to_string(got) + "B, want " + std::to_string(B));
    }
    if (!a.ok() && first.ok()) first = a;
  }
  for (size_t i = 0; i < sreqs.size(); ++i) {
    Status b = net_->wait(sreqs[i], nullptr);
    if (!b.ok() && first.ok()) {
      first = Status{b.kind, "all_to_all send to rank " +
                                 std::to_string(speers[i]) + ": " + b.msg};
    }
  }
  return first;
}

Status ScheduledCommunicator::NeighborExchange(const void* sendbuf, size_t send_nbytes,
                                               void* recvbuf, size_t recv_nbytes,
                                               size_t* got) {
  FenceAsync();
  if (world_ == 1) {
    if (send_nbytes > recv_nbytes) return Status::Invalid("recv buffer too small");
    memcpy(recvbuf, sendbuf, send_nbytes);
    if (got) *got = send_nbytes;
    return Status::Ok();
  }
  PhaseSpan whole(Telemetry::Get().tracing_enabled(), trace_comm_id_, ++coll_seq_,
                  "neighbor_exchange", -1, send_nbytes);
  return Exchange(sendbuf, send_nbytes, recvbuf, recv_nbytes, got, channels_[0]);
}

Status ScheduledCommunicator::Barrier() {
  if (world_ == 1) return Status::Ok();
  barrier_scratch_.resize(world_);
  uint8_t token = 1;
  return AllGather(&token, barrier_scratch_.data(), 1);  // fences via AllGather
}

// ---------------------------------------------------------------------------
// Async worker machinery.

// First async submission: wire the extra ring channels and spawn one worker
// per queue — ring queues 0..C-1 (one per channel) plus the dedicated mesh
// queue C, whose jobs (rhd/tree/hier/a2a) ride the pairwise mesh and never
// touch a ring channel. Safe to touch the listener here — the communicator
// runs one collective program, so every rank reaches its first async
// submission at the same point of it and nothing else is mid-accept.
Status ScheduledCommunicator::EnsureAsyncWorkers() {
  if (worker_started_) return Status::Ok();
  Status s = EnsureAsyncChannels(AsyncChannelCount());
  if (!s.ok()) return s;
  queues_.resize(channels_.size() + 1);
  running_.assign(channels_.size() + 1, 0);
  worker_started_ = true;
  for (size_t c = 0; c < channels_.size() + 1; ++c) {
    workers_.emplace_back([this, c] { AsyncWorkerLoop(c); });
  }
  return Status::Ok();
}

Status ScheduledCommunicator::IAllReduce(const void* sendbuf, void* recvbuf,
                                         size_t count, DType dtype, RedOp op,
                                         uint64_t* ticket) {
  size_t esize = DTypeSize(dtype);
  if (esize == 0) return Status::Invalid("bad dtype");
  MutexLock lk(async_mu_);
  Status s = EnsureAsyncWorkers();
  if (!s.ok()) return s;
  uint64_t t = next_ticket_++;
  // Trace seq is claimed at SUBMISSION (same order on every rank), not at
  // execution, so spans from overlapping tickets keep cross-rank-stable
  // tags.
  uint64_t seq = ++coll_seq_;
  // Schedule is resolved at SUBMISSION, identically on every rank (the
  // selector is deterministic from negotiated state), because it feeds the
  // routing below.
  CollAlgo algo = ResolveAlgo(CollKind::kAllReduce, count * esize);
  // Deterministic ticket→queue map: submission order is already the
  // cross-rank contract for nonblocking collectives, so every rank routes
  // ticket t to the same queue and messages pair up peer-to-peer. Mesh
  // schedules (rhd/tree/hier — and async AllToAlls) ride the dedicated
  // mesh queue: the mesh comms are one shared resource, so mesh jobs must
  // serialize — and do, in submission order, the same on every rank — but
  // they no longer pin ring queue 0, so a mesh ticket and any ring ticket
  // overlap on their disjoint comms.
  const bool ring = algo == CollAlgo::kRing;
  size_t q = ring ? (t - 1) % (queues_.size() - 1) : MeshQueueIndex();
  size_t ch = ring ? q : 0;  // mesh jobs ignore the channel argument
  queues_[q].emplace_back(t, [this, sendbuf, recvbuf, count, dtype, op, ch, seq,
                              algo] {
    return DoAllReduce(sendbuf, recvbuf, count, dtype, op, channels_[ch], seq, algo);
  });
  *ticket = t;
  work_cv_.NotifyAll();
  return Status::Ok();
}

// Nonblocking AllToAll: resolved at submission like IAllReduce. Mesh-routed
// schedules (pairwise / hierarchical) run on the dedicated mesh worker in
// submission order; a relay verdict rides the ring round-robin map with its
// channel (the relay's exchanges are ring-channel traffic). Either way an
// async AllToAll overlaps ring AllReduce tickets on disjoint comms instead
// of serializing behind queue 0 — the PR 6 mesh bottleneck this fixes.
Status ScheduledCommunicator::IAllToAll(const void* sendbuf, void* recvbuf,
                                        size_t bytes_per_rank, uint64_t* ticket) {
  MutexLock lk(async_mu_);
  Status s = EnsureAsyncWorkers();
  if (!s.ok()) return s;
  uint64_t t = next_ticket_++;
  uint64_t seq = ++coll_seq_;
  CollAlgo algo = ResolveA2aAlgo(bytes_per_rank);
  const bool ring = algo == CollAlgo::kRing;
  size_t q = ring ? (t - 1) % (queues_.size() - 1) : MeshQueueIndex();
  size_t ch = ring ? q : 0;
  const uint8_t* in = static_cast<const uint8_t*>(sendbuf);
  uint8_t* out = static_cast<uint8_t*>(recvbuf);
  queues_[q].emplace_back(t, [this, in, out, bytes_per_rank, ch, seq, algo] {
    return DoAllToAll(in, out, bytes_per_rank, seq, algo, channels_[ch]);
  });
  *ticket = t;
  work_cv_.NotifyAll();
  return Status::Ok();
}

Status ScheduledCommunicator::WaitTicket(uint64_t ticket) {
  MutexLock lk(async_mu_);
  if (!TicketLive(ticket)) return Status::Invalid("unknown or already-waited ticket");
  // Also wake if the ticket stops being live without completing (shutdown
  // dropped it, or a racing waiter claimed it) — never sleep forever.
  while (done_.count(ticket) == 0 && TicketLive(ticket)) done_cv_.Wait(async_mu_);
  auto it = done_.find(ticket);
  if (it == done_.end()) {
    return Status::Invalid("ticket abandoned (shutdown or waited elsewhere)");
  }
  Status s = it->second;
  done_.erase(it);
  return s;
}

Status ScheduledCommunicator::TestTicket(uint64_t ticket, bool* done) {
  MutexLock lk(async_mu_);
  auto it = done_.find(ticket);
  if (it != done_.end()) {
    *done = true;
    return Status::Ok();
  }
  if (!TicketLive(ticket)) return Status::Invalid("unknown or already-waited ticket");
  *done = false;
  return Status::Ok();
}

// Number of independent async ring channels (and worker threads). Each
// extra channel is one more comm pair per rank — with two, bucket k+1's
// ring transfer runs while bucket k reduces, and the two transfers share
// the NIC instead of serializing behind a single worker. Must agree across
// ranks (it changes how many wiring connects each peer expects).
size_t ScheduledCommunicator::AsyncChannelCount() {
  static const size_t v = [] {
    uint64_t n = GetEnvU64("TPUNET_ASYNC_CHANNELS", 2);
    return static_cast<size_t>(std::min<uint64_t>(std::max<uint64_t>(n, 1), 8));
  }();
  return v;
}

// Wire ring channels [channels_.size(), nch): connect to next with a
// channel-tagged hello, then accept the matching connects from prev off
// the shared listener. Connect never blocks on the peer's accept (TCP
// backlog + the engine's buffered preamble), so connect-all-then-accept-all
// cannot deadlock; the hello keys each inbound comm to its channel so
// accept-order races cannot cross-wire rings. Runs once, on the caller
// thread of the first IAllReduce, before any worker exists.
Status ScheduledCommunicator::EnsureAsyncChannels(size_t nch) {
  if (!async_wire_status_.ok()) return async_wire_status_;
  if (channels_.size() >= nch || world_ == 1) return Status::Ok();
  const int next = (rank_ + 1) % world_;
  const size_t base = channels_.size();
  channels_.resize(nch);
  Status result = Status::Ok();
  for (size_t c = base; c < nch && result.ok(); ++c) {
    result = ConnectHello(next, kRingHelloTag | c, &channels_[c].send_comm);
  }
  for (size_t i = base; i < nch && result.ok(); ++i) {
    uint64_t rc = 0, h = 0;
    result = AcceptHello(&rc, &h);
    if (!result.ok()) break;
    uint64_t c = h & 0xFFFFFFFFull;
    if ((h & ~0xFFFFFFFFull) != kRingHelloTag || c < base || c >= nch ||
        channels_[c].recv_comm != 0) {
      net_->close_recv(rc);
      result = Status::Inner("unexpected channel hello " + std::to_string(h));
    } else {
      channels_[c].recv_comm = rc;
    }
  }
  // Quiesce before returning: a rank whose wiring completes early (its
  // accepts only need PREV to have started) must not race ahead — its next
  // listener-touching op (EnsureMesh) could reach a peer still blocked in
  // the accept loop above and be mistaken for a channel connect. W-1
  // one-byte ring steps on channel 0: completing them implies every rank
  // entered this quiesce, i.e. finished wiring. Direct Exchange, not
  // Barrier() — that would re-lock async_mu_.
  for (int s = 0; s < world_ - 1 && result.ok(); ++s) {
    uint8_t token_out = 1, token_in = 0;
    result = Exchange(&token_out, 1, &token_in, 1, nullptr, channels_[0]);
  }
  if (!result.ok()) {
    // Peers may have wired a subset — the communicator's channel state is
    // inconsistent across ranks and cannot be retried; fail every later
    // async call the same way. Partially-wired comms close in the dtor.
    async_wire_status_ = result;
  }
  return result;
}

// A ticket is live (waitable) if it is queued, currently executing, or
// completed-but-unclaimed.
bool ScheduledCommunicator::TicketLive(uint64_t ticket) {
  if (done_.count(ticket)) return true;
  for (uint64_t r : running_) {
    if (r == ticket) return true;
  }
  for (const auto& q : queues_) {
    for (const auto& job : q) {
      if (job.first == ticket) return true;
    }
  }
  return false;
}

void ScheduledCommunicator::AsyncWorkerLoop(size_t ch) {
  async_mu_.Lock();
  while (true) {
    while (!stop_ && queues_[ch].empty()) work_cv_.Wait(async_mu_);
    if (stop_) break;
    auto job = std::move(queues_[ch].front());
    queues_[ch].pop_front();
    running_[ch] = job.first;
    async_mu_.Unlock();
    Status s = job.second();  // the collective schedule, off the caller thread
    async_mu_.Lock();
    running_[ch] = 0;
    done_[job.first] = s;
    done_cv_.NotifyAll();  // wakes WaitTicket and FenceAsync
  }
  async_mu_.Unlock();
}

// True when no async job is queued or executing.
bool ScheduledCommunicator::AsyncIdle() {
  for (const auto& q : queues_) {
    if (!q.empty()) return false;
  }
  for (uint64_t r : running_) {
    if (r != 0) return false;
  }
  return true;
}

// Blocking collectives fence behind outstanding async work so the two
// kinds never interleave on the underlying comms.
void ScheduledCommunicator::FenceAsync() {
  MutexLock lk(async_mu_);
  if (!worker_started_) return;
  while (!AsyncIdle()) done_cv_.Wait(async_mu_);
}

void ScheduledCommunicator::StopAsyncWorker() {
  {
    MutexLock lk(async_mu_);
    if (!worker_started_) return;
    // Destroying with queued work is a caller error (peers would be left
    // mid-collective); the running jobs finish, queued jobs fail their
    // tickets so any blocked WaitTicket returns an error instead of
    // sleeping forever.
    stop_ = true;
    for (auto& q : queues_) {
      for (auto& job : q) {
        done_[job.first] = Status::Inner("communicator destroyed with pending collectives");
      }
      q.clear();
    }
    work_cv_.NotifyAll();
    done_cv_.NotifyAll();
  }
  for (std::thread& w : workers_) w.join();
}

}  // namespace internal

// ---------------------------------------------------------------------------
// Construction.

Status Communicator::Create(const std::string& coordinator, int rank, int world_size,
                            std::unique_ptr<Communicator>* out) {
  return Create(coordinator, rank, world_size, "", "", out);
}

Status Communicator::Create(const std::string& coordinator, int rank, int world_size,
                            const std::string& wire_dtype,
                            std::unique_ptr<Communicator>* out) {
  return Create(coordinator, rank, world_size, wire_dtype, "", out);
}

Status Communicator::Create(const std::string& coordinator, int rank, int world_size,
                            const std::string& wire_dtype, const std::string& algo,
                            std::unique_ptr<Communicator>* out) {
  return Create(coordinator, rank, world_size, wire_dtype, algo, "", out);
}

Status Communicator::Create(const std::string& coordinator, int rank, int world_size,
                            const std::string& wire_dtype, const std::string& algo,
                            const std::string& traffic_class,
                            std::unique_ptr<Communicator>* out) {
  if (world_size < 1 || rank < 0 || rank >= world_size) {
    return Status::Invalid("bad rank/world_size");
  }
  std::string name =
      wire_dtype.empty() ? GetEnv("TPUNET_WIRE_DTYPE", "f32") : wire_dtype;
  WireCodec codec;
  if (!ParseWireCodec(name, &codec)) {
    return Status::Invalid("unknown wire_dtype \"" + name +
                           "\" (expected f32, bf16 or int8)");
  }
  std::string algo_name = algo.empty() ? GetEnv("TPUNET_ALGO", "auto") : algo;
  CollAlgo calgo;
  if (!ParseCollAlgo(algo_name, &calgo)) {
    return Status::Invalid("unknown algo \"" + algo_name +
                           "\" (expected auto, ring, rhd, tree or hier)");
  }
  std::string cls_name = traffic_class.empty()
                             ? GetEnv("TPUNET_TRAFFIC_CLASS", "bulk")
                             : traffic_class;
  TrafficClass cls;
  if (!ParseTrafficClass(cls_name, &cls)) {
    return Status::Invalid("unknown traffic_class \"" + cls_name +
                           "\" (expected latency, bulk or control)");
  }
  auto comm = std::make_unique<internal::ScheduledCommunicator>(
      rank, world_size, codec, calgo, cls);
  Status s = comm->Init(coordinator);
  if (!s.ok()) return s;
  *out = std::move(comm);
  return Status::Ok();
}

}  // namespace tpunet
