// tpunet ring collectives over the multi-stream transport. See collectives.h.
//
// Algorithms (chunked ring, the same family NCCL runs above the reference
// plugin — SURVEY §1 L6):
//   AllReduce      = reduce-scatter phase + all-gather phase, 2(W-1) steps,
//                    busbw-optimal 2(W-1)/W bytes per element on the wire.
//   ReduceScatter  = the RS phase alone on W equal blocks.
//   AllGather      = the AG phase alone.
//   Broadcast      = pipelined ring forward from root (1 MiB chunks).
//   Barrier        = 1-byte AllGather.
// Every step posts the irecv before the isend and waits on both — each rank
// sends to (rank+1)%W and receives from (rank-1+W)%W over independent
// full-duplex comms, so the ring cannot deadlock.
#include "tpunet/collectives.h"

#include <string.h>

#include <algorithm>
#include <chrono>
#include <deque>
#include <functional>
#include <map>
#include <thread>
#include <vector>

#include "tpunet/bootstrap.h"
#include "tpunet/mutex.h"
#include "tpunet/telemetry.h"
#include "tpunet/utils.h"

namespace tpunet {

size_t DTypeSize(DType d) {
  switch (d) {
    case DType::kF32:
      return 4;
    case DType::kF64:
      return 8;
    case DType::kBF16:
      return 2;
    case DType::kI32:
      return 4;
    case DType::kI64:
      return 8;
    case DType::kU8:
      return 1;
  }
  return 0;
}

namespace {

constexpr size_t kBcastChunk = 1 << 20;  // broadcast pipeline granularity

// Reduce-phase pipeline granularity: each ring step streams its slice in
// chunks this size so the reduction of chunk i overlaps the wire transfer of
// chunk i+1 (the NCCL pipelining insight — without it a step is strictly
// transfer-then-reduce and the reduce time adds to the critical path).
size_t RingChunkBytes() {
  static const size_t v = GetEnvU64("TPUNET_RING_CHUNKSIZE", 8 << 20);
  return v ? v : (8 << 20);
}

// --------------------------------------------------------------------------
// Reduction: the 3-operand kernels (dst[i] = a[i] op b[i]) live in utils.cc
// as ReduceInto — SIMD with runtime dispatch, fork-join above 4 MiB, and the
// tpunet_reduce_bytes_total counter. The in-place accumulate is the a == dst
// degenerate case; the out-of-place collectives pass a = caller's sendbuf so
// the staging copy never has to exist. This file only maps the public
// DType/RedOp enums onto the wire-layer ones.

WireDType ToWireDType(DType d) {
  switch (d) {
    case DType::kF32:
      return WireDType::kF32;
    case DType::kF64:
      return WireDType::kF64;
    case DType::kBF16:
      return WireDType::kBF16;
    case DType::kI32:
      return WireDType::kI32;
    case DType::kI64:
      return WireDType::kI64;
    case DType::kU8:
      return WireDType::kU8;
  }
  return WireDType::kU8;
}

WireRedOp ToWireRedOp(RedOp op) {
  switch (op) {
    case RedOp::kSum:
      return WireRedOp::kSum;
    case RedOp::kProd:
      return WireRedOp::kProd;
    case RedOp::kMin:
      return WireRedOp::kMin;
    case RedOp::kMax:
      return WireRedOp::kMax;
  }
  return WireRedOp::kSum;
}

void Reduce(void* dst, const void* a, const void* b, size_t n, DType dtype,
            RedOp op) {
  ReduceInto(dst, a, b, n, ToWireDType(dtype), ToWireRedOp(op));
}

// --------------------------------------------------------------------------

// Tag for the 8-byte hello a lazily-wired extra ring channel sends on its
// first message, distinguishing it from a pairwise-mesh hello (a bare rank,
// always < world) on the shared listener.
constexpr uint64_t kRingHelloTag = 0x52494E47ull << 32;  // "RING"

// RAII trace span around one collective phase. Every rank runs the same
// collective program, so (comm_id, coll_seq, phase) names the SAME logical
// phase on every rank — the cross-rank join key telemetry.merge_traces()
// aligns per-rank trace files with. Zero cost when tracing is off (the
// caller passes tracing_enabled() as `on`; no string is built either way
// until the destructor fires with on=true).
class PhaseSpan {
 public:
  PhaseSpan(bool on, uint64_t comm_id, uint64_t seq, const char* kind, int step,
            uint64_t nbytes)
      : on_(on), comm_id_(comm_id), seq_(seq), kind_(kind), step_(step),
        nbytes_(nbytes), start_us_(on ? MonotonicUs() : 0) {}
  ~PhaseSpan() {
    if (!on_) return;
    std::string phase =
        step_ < 0 ? std::string(kind_) : std::string(kind_) + "." + std::to_string(step_);
    Telemetry::Get().OnCollPhase(comm_id_, seq_, phase.c_str(), start_us_,
                                 MonotonicUs() - start_us_, nbytes_);
  }
  PhaseSpan(const PhaseSpan&) = delete;
  PhaseSpan& operator=(const PhaseSpan&) = delete;

 private:
  bool on_;
  uint64_t comm_id_, seq_;
  const char* kind_;
  int step_;
  uint64_t nbytes_;
  uint64_t start_us_;
};

class RingCommunicator : public Communicator {
 public:
  // A channel is one independent ring: a send comm to (rank+1)%W and a recv
  // comm from (rank-1+W)%W, plus the scratch its pipelined reduce uses.
  // Channel 0 is wired at Init and carries every blocking collective; extra
  // channels exist so concurrent async tickets can overlap on the wire
  // (ticket k+1's transfer no longer waits for ticket k's reduce).
  struct RingChannel {
    uint64_t send_comm = 0;
    uint64_t recv_comm = 0;
    ScratchBuf scratch;  // chunk landing slots; aligned, never zero-filled
  };

  RingCommunicator(int rank, int world, WireCodec codec)
      : rank_(rank), world_(world), codec_(codec) {}

  ~RingCommunicator() override {
    StopAsyncWorker();
    if (net_) {
      for (uint64_t c : mesh_send_) {
        if (c) net_->close_send(c);
      }
      for (uint64_t c : mesh_recv_) {
        if (c) net_->close_recv(c);
      }
      for (RingChannel& ch : channels_) {
        if (ch.send_comm) net_->close_send(ch.send_comm);
        if (ch.recv_comm) net_->close_recv(ch.recv_comm);
      }
      if (listen_comm_) net_->close_listen(listen_comm_);
    }
  }

  Status Init(const std::string& coordinator) {
    net_ = CreateEngine();
    // Trace identity: every rank hashes the SAME coordinator string and
    // world size, so (comm_id, coll_seq) tags agree across ranks without a
    // wire round. |1 keeps it nonzero even for a degenerate hash.
    trace_comm_id_ =
        (static_cast<uint64_t>(Crc32c(coordinator.data(), coordinator.size())) |
         (static_cast<uint64_t>(world_) << 32)) | 1ull;
    channels_.resize(1);
    Status s = Bootstrap::Create(coordinator, rank_, world_, &bootstrap_);
    if (!s.ok()) return s;
    if (world_ == 1) {
      bootstrap_.reset();
      return Status::Ok();
    }

    // Wire-codec negotiation, piggybacked on the bootstrap ctrl plane the
    // wiring already rides: one 1-byte AllGather round. Every rank compares
    // the full vector, so ALL ranks fail identically (kCodec) on a mismatch
    // — before any ring comm exists that could mis-decode a payload.
    uint8_t my_codec = static_cast<uint8_t>(codec_);
    std::vector<uint8_t> codecs;
    s = bootstrap_->AllGather(&my_codec, 1, &codecs);
    if (!s.ok()) return s;
    for (int r = 0; r < world_; ++r) {
      if (codecs[r] != my_codec) {
        std::string theirs =
            codecs[r] < kWireCodecCount
                ? std::string(WireCodecName(static_cast<WireCodec>(codecs[r])))
                : "#" + std::to_string(codecs[r]);
        return Status::Codec(
            "wire codec mismatch: rank " + std::to_string(rank_) + " uses " +
            WireCodecName(codec_) + " but rank " + std::to_string(r) + " uses " +
            theirs +
            " (set TPUNET_WIRE_DTYPE / wire_dtype identically on every rank)");
      }
    }

    SocketHandle handle;
    s = net_->listen(0, &handle, &listen_comm_);
    if (!s.ok()) return s;
    uint8_t blob[kHandleSize] = {0};
    memcpy(blob, &handle.addr, std::min(sizeof(handle.addr), sizeof(blob)));
    std::vector<uint8_t> all;
    s = bootstrap_->AllGather(blob, kHandleSize, &all);
    if (!s.ok()) return s;

    // Keep every rank's listen handle: the pairwise AllToAll mesh is wired
    // lazily from these on first use (the listeners stay alive for the
    // communicator's lifetime, so no bootstrap round is needed then).
    all_handles_.resize(world_);
    for (int r = 0; r < world_; ++r) {
      memcpy(&all_handles_[r].addr, all.data() + r * kHandleSize, kHandleSize);
      all_handles_[r].addrlen = 0;  // derived from family by the engine
    }

    int next = (rank_ + 1) % world_;
    s = ConnectAndWire(all_handles_[next]);
    if (!s.ok()) return s;
    // The bootstrap's job is done once the ring is wired; dropping it frees
    // the coordinator port and rank 0's W-1 peer sockets so long-lived jobs
    // don't pin fds and another communicator can reuse the address.
    bootstrap_.reset();
    return Status::Ok();
  }

  Status ConnectAndWire(const SocketHandle& next_handle) {
    Status s = net_->connect(0, next_handle, &channels_[0].send_comm);
    if (!s.ok()) return s;
    // Barrier BEFORE accept: once it passes, every rank has connected to its
    // next, so our prev's bundle is already inbound and accept() cannot
    // block forever. A rank that died earlier fails the barrier with a clean
    // error instead of wedging the ring (observed: peer death between
    // bootstrap and connect hung accept indefinitely).
    s = bootstrap_->Barrier();
    if (!s.ok()) return s;
    return net_->accept(listen_comm_, &channels_[0].recv_comm);
  }

  // Blocking AllReduce IS IAllReduce + WaitTicket. This is not a
  // convenience: the cross-rank matching rule (MPI/NCCL semantics) lets one
  // rank call AllReduce where another calls IAllReduce+wait for the same
  // collective, so BOTH kinds must consume the same ticket sequence — the
  // ticket->channel map is what pairs ring messages across ranks, and a
  // blocking call that bypassed it would desync (and never wire channels on
  // ranks that only ever call the blocking form).
  Status AllReduce(const void* sendbuf, void* recvbuf, size_t count, DType dtype,
                   RedOp op) override {
    // Single-channel mode: everything rides channel 0 in submission order,
    // so pairing cannot desync and the caller thread can run the ring
    // directly (no worker hop) — also the kill switch for the ticketed path.
    if (AsyncChannelCount() == 1) {
      FenceAsync();
      return DoAllReduce(sendbuf, recvbuf, count, dtype, op, channels_[0], ++coll_seq_);
    }
    // Fence first: the documented contract is that a blocking collective
    // orders AFTER all outstanding tickets (callers rely on it for buffer
    // reuse). Fencing consumes no ticket, so it cannot desync pairing.
    FenceAsync();
    uint64_t ticket = 0;
    Status s = IAllReduce(sendbuf, recvbuf, count, dtype, op, &ticket);
    if (!s.ok()) return s;
    return WaitTicket(ticket);
  }

  Status DoAllReduce(const void* sendbuf, void* recvbuf, size_t count, DType dtype,
                     RedOp op, RingChannel& ch, uint64_t seq) {
    size_t esize = DTypeSize(dtype);
    if (esize == 0) return Status::Invalid("bad dtype");
    if (count == 0) return Status::Ok();
    if (world_ == 1) {
      if (sendbuf != recvbuf) memcpy(recvbuf, sendbuf, count * esize);
      return Status::Ok();
    }
    const bool tracing = Telemetry::Get().tracing_enabled();
    PhaseSpan whole(tracing, trace_comm_id_, seq, "allreduce", -1, count * esize);
    const uint8_t* src = static_cast<const uint8_t*>(sendbuf);
    uint8_t* data = static_cast<uint8_t*>(recvbuf);
    // Out-of-place with DISJOINT buffers needs no staging copy at all:
    // round 0 sends from the caller's sendbuf, later rounds send the slice
    // reduced the previous round (already in recvbuf), and every reduce
    // reads its local operand from sendbuf while writing into recvbuf —
    // every recvbuf slice is written (by RS or AG) before anything reads
    // it, so the caller's input never needs to be there. Measured 2x
    // on the 128 MiB out-of-place path (PERF_NOTES round 4): the memcpy
    // plus first-touch faulting of a cold 128 MiB destination was as
    // expensive as the whole ring on a 1-core host. Partially-overlapping
    // buffers (C-ABI callers only; the Python binding never does this)
    // keep the safe copy path.
    bool oop = sendbuf != recvbuf;
    if (oop && src < data + count * esize && data < src + count * esize) {
      // Overlapping: stage (memmove — the ranges provably overlap).
      memmove(recvbuf, sendbuf, count * esize);
      oop = false;
    }
    const int W = world_;
    auto off = [&](int i) { return (count * static_cast<size_t>(i)) / W; };

    // vr relabels the ring so this rank finishes the RS phase owning slice
    // `rank`, which the AG phase then circulates.
    const int vr = (rank_ + W - 1) % W;
    const bool codec_on = UseCodec(dtype);
    size_t ag_slot = 0;
    if (codec_on) {
      // Park the AG phase's two wire slots at the BOTTOM of the channel
      // scratch, before any RS chunk slot: the RS final round's fused
      // handoff writes the owned slice's encoded bytes into AG slot 0, and
      // they must survive the RS rounds' own scratch use.
      ag_slot = CodecWireBytes(codec_, (count + W - 1) / W);
      ch.scratch.reserve(2 * ag_slot +
                         4 * CodecWireBytes(codec_, CodecChunkElems()));
    }
    for (int s = 0; s < W - 1; ++s) {
      int sidx = (vr - s + W) % W;
      int ridx = (vr - s - 1 + W) % W;
      size_t sbytes = (off(sidx + 1) - off(sidx)) * esize;
      size_t rbytes = (off(ridx + 1) - off(ridx)) * esize;
      // Round s sends the slice reduced in round s-1; only round 0's send
      // operand still lives in sendbuf on the no-copy path.
      const uint8_t* sptr =
          ((oop && s == 0) ? src : data) + off(sidx) * esize;
      PhaseSpan step(tracing, trace_comm_id_, seq, "rs", s, sbytes);
      Status st;
      if (codec_on) {
        // Final round reduces into this rank's owned slice (ridx == rank_):
        // fuse the AG-entry quantize+encode into it.
        uint8_t* fused = (s == W - 2) ? ch.scratch.data() : nullptr;
        st = ExchangeReduceCodec(sptr, sbytes, data + off(ridx) * esize,
                                 rbytes, op, ch,
                                 oop ? src + off(ridx) * esize : nullptr,
                                 fused, 2 * ag_slot);
      } else {
        st = ExchangeReduce(sptr, sbytes, data + off(ridx) * esize,
                            rbytes, dtype, op, ch,
                            oop ? src + off(ridx) * esize : nullptr);
      }
      if (!st.ok()) return st;
    }
    if (codec_on) {
      return AgPhaseCodec(reinterpret_cast<float*>(data), count, ch, seq, tracing);
    }
    for (int s = 0; s < W - 1; ++s) {
      int sidx = (rank_ - s + W) % W;
      int ridx = (rank_ - s - 1 + W) % W;
      size_t sbytes = (off(sidx + 1) - off(sidx)) * esize;
      size_t rbytes = (off(ridx + 1) - off(ridx)) * esize;
      PhaseSpan step(tracing, trace_comm_id_, seq, "ag", s, sbytes);
      Status st = Exchange(data + off(sidx) * esize, sbytes, data + off(ridx) * esize,
                           rbytes, nullptr, ch);
      if (!st.ok()) return st;
    }
    return Status::Ok();
  }

  Status ReduceScatter(const void* sendbuf, void* recvbuf, size_t recv_count, DType dtype,
                       RedOp op) override {
    FenceAsync();
    size_t esize = DTypeSize(dtype);
    if (esize == 0) return Status::Invalid("bad dtype");
    if (recv_count == 0) return Status::Ok();
    const int W = world_;
    if (W == 1) {
      if (sendbuf != recvbuf) memcpy(recvbuf, sendbuf, recv_count * esize);
      return Status::Ok();
    }
    size_t block = recv_count * esize;
    const uint8_t* src = static_cast<const uint8_t*>(sendbuf);
    uint8_t* out = static_cast<uint8_t*>(recvbuf);
    const bool tracing = Telemetry::Get().tracing_enabled();
    const uint64_t seq = ++coll_seq_;
    PhaseSpan whole(tracing, trace_comm_id_, seq, "reduce_scatter", -1,
                    static_cast<uint64_t>(W) * block);
    if (out < src + static_cast<size_t>(W) * block && src < out + block) {
      // Overlapping C-ABI buffers: keep the safe full-copy path.
      work_.reserve(static_cast<size_t>(W) * block);
      memcpy(work_.data(), sendbuf, static_cast<size_t>(W) * block);
      const int vr0 = (rank_ + W - 1) % W;
      for (int s = 0; s < W - 1; ++s) {
        int sidx = (vr0 - s + W) % W;
        int ridx = (vr0 - s - 1 + W) % W;
        PhaseSpan step(tracing, trace_comm_id_, seq, "rs", s, block);
        Status st = ExchangeReduce(work_.data() + sidx * block, block,
                                   work_.data() + ridx * block, block, dtype, op, channels_[0]);
        if (!st.ok()) return st;
      }
      memcpy(recvbuf, work_.data() + rank_ * block, block);
      return Status::Ok();
    }
    // No staging copy of the W-block input: each round's reduce reads its
    // local operand from the caller's sendbuf; partials land in a 2-block
    // ping-pong scratch (a round's output is the NEXT round's send
    // operand), and the final round — whose target is this rank's owned
    // block — writes straight into recvbuf. Scratch is 2 blocks instead of
    // the previous W, and the O(W·B) memcpy is gone. W=2's single round
    // goes sendbuf->recvbuf directly and needs no scratch at all (resizing
    // it would zero-fill + fault pages for nothing — the cost class this
    // path exists to avoid).
    uint8_t* pb[2] = {nullptr, nullptr};
    if (W > 2) {
      work_.reserve(2 * block);
      pb[0] = work_.data();
      pb[1] = work_.data() + block;
    }  // W==2: single round goes sendbuf->recvbuf, pb never read
    const int vr = (rank_ + W - 1) % W;
    for (int s = 0; s < W - 1; ++s) {
      int sidx = (vr - s + W) % W;
      int ridx = (vr - s - 1 + W) % W;
      const uint8_t* sptr = (s == 0) ? src + sidx * block : pb[(s - 1) & 1];
      uint8_t* optr = (s == W - 2) ? out : pb[s & 1];
      PhaseSpan step(tracing, trace_comm_id_, seq, "rs", s, block);
      Status st = ExchangeReduce(sptr, block, optr, block, dtype, op,
                                 channels_[0], src + ridx * block);
      if (!st.ok()) return st;
    }
    return Status::Ok();
  }

  Status AllGather(const void* sendbuf, void* recvbuf, size_t bytes_per_rank) override {
    FenceAsync();
    const int W = world_;
    uint8_t* out = static_cast<uint8_t*>(recvbuf);
    if (out + rank_ * bytes_per_rank != sendbuf) {
      memcpy(out + rank_ * bytes_per_rank, sendbuf, bytes_per_rank);
    }
    if (W == 1 || bytes_per_rank == 0) return Status::Ok();
    const bool tracing = Telemetry::Get().tracing_enabled();
    const uint64_t seq = ++coll_seq_;
    PhaseSpan whole(tracing, trace_comm_id_, seq, "all_gather", -1,
                    static_cast<uint64_t>(W) * bytes_per_rank);
    for (int s = 0; s < W - 1; ++s) {
      int sidx = (rank_ - s + W) % W;
      int ridx = (rank_ - s - 1 + W) % W;
      PhaseSpan step(tracing, trace_comm_id_, seq, "ag", s, bytes_per_rank);
      Status st = Exchange(out + sidx * bytes_per_rank, bytes_per_rank,
                           out + ridx * bytes_per_rank, bytes_per_rank, nullptr, channels_[0]);
      if (!st.ok()) return st;
    }
    return Status::Ok();
  }

  Status Broadcast(void* buf, size_t nbytes, int root) override {
    FenceAsync();
    const int W = world_;
    if (W == 1 || nbytes == 0) return Status::Ok();
    if (root < 0 || root >= W) return Status::Invalid("bad broadcast root");
    PhaseSpan whole(Telemetry::Get().tracing_enabled(), trace_comm_id_, ++coll_seq_,
                    "broadcast", -1, nbytes);
    uint8_t* data = static_cast<uint8_t*>(buf);
    int dist = (rank_ - root + W) % W;          // hops from root along the ring
    bool is_tail = dist == W - 1;               // last rank forwards nothing
    size_t nchunks = (nbytes + kBcastChunk - 1) / kBcastChunk;

    // Pipelined forward: receive chunk c, then send it on while chunk c+1 is
    // in flight — the ring streams instead of store-and-forwarding the
    // whole buffer W-1 times.
    std::vector<uint64_t> pending_sends;
    for (size_t c = 0; c < nchunks; ++c) {
      size_t coff = c * kBcastChunk;
      size_t clen = std::min(kBcastChunk, nbytes - coff);
      if (dist != 0) {
        uint64_t rreq = 0;
        Status st = net_->irecv(channels_[0].recv_comm, data + coff, clen, &rreq);
        if (!st.ok()) return DrainSends(pending_sends, st);
        size_t got = 0;
        st = WaitRequest(rreq, &got);
        if (!st.ok()) return DrainSends(pending_sends, st);
        if (got != clen) {
          return DrainSends(pending_sends, Status::Inner("broadcast chunk size mismatch"));
        }
      }
      if (!is_tail) {
        uint64_t sreq = 0;
        Status st = net_->isend(channels_[0].send_comm, data + coff, clen, &sreq);
        if (!st.ok()) return DrainSends(pending_sends, st);
        pending_sends.push_back(sreq);
      }
    }
    return DrainSends(pending_sends, Status::Ok());
  }

  Status AllToAll(const void* sendbuf, void* recvbuf, size_t bytes_per_rank) override {
    FenceAsync();
    const int W = world_;
    const size_t B = bytes_per_rank;
    const uint8_t* in = static_cast<const uint8_t*>(sendbuf);
    uint8_t* out = static_cast<uint8_t*>(recvbuf);
    if (static_cast<const void*>(out) != sendbuf) {
      memcpy(out + rank_ * B, in + rank_ * B, B);  // own block stays local
    }
    if (W == 1 || B == 0) return Status::Ok();
    PhaseSpan whole(Telemetry::Get().tracing_enabled(), trace_comm_id_, ++coll_seq_,
                    "all_to_all", -1, static_cast<uint64_t>(W) * B);
    // Direct pairwise exchange by default: O(W*B) bytes on the wire per
    // rank vs the ring relay's O(W^2*B/2) — the difference between usable
    // and quadratic cross-host MoE dispatch / DCN-Ulysses at pod scale.
    // TPUNET_A2A=ring keeps the relay (no extra comms; fine at tiny W).
    // The mesh costs 2*(W-1) comms per rank, each nstreams+1 fds and
    // nstreams+1 threads, so very large worlds fall back to the relay
    // rather than exhausting fds/threads; raise TPUNET_A2A_MESH_MAX_WORLD
    // on hosts provisioned for it (the long-term fix is single-stream
    // mesh comms, which need a per-connect nstreams override in Net).
    static const bool use_ring = GetEnv("TPUNET_A2A", "pairwise") == "ring";
    static const uint64_t mesh_max_world =
        GetEnvU64("TPUNET_A2A_MESH_MAX_WORLD", 32);
    if (!use_ring && static_cast<uint64_t>(W) <= mesh_max_world) {
      return PairwiseAllToAll(in, out, B);
    }

    // Store-and-forward relay. Packet invariant at step s: the packet holds
    // nblk = W-1-s blocks; position p carries the block with nblk-p hops of
    // remaining travel (descending). After one Exchange hop every block's
    // remaining distance drops by one: the last block has arrived (it is the
    // block rank (rank-s-1) addressed to us), the rest forward verbatim next
    // step. Both sides compute identical per-step sizes, so the fixed-size
    // Exchange path (got=nullptr) catches rank disagreement as an error.
    a2a_fwd_.reserve(static_cast<size_t>(W - 1) * B);
    a2a_rcv_.reserve(static_cast<size_t>(W - 1) * B);
    for (int p = 0; p < W - 1; ++p) {
      int dest = (rank_ + (W - 1 - p)) % W;
      memcpy(a2a_fwd_.data() + static_cast<size_t>(p) * B, in + dest * B, B);
    }
    for (int s = 0; s < W - 1; ++s) {
      size_t nblk = static_cast<size_t>(W - 1 - s);
      Status st = Exchange(a2a_fwd_.data(), nblk * B, a2a_rcv_.data(), nblk * B, nullptr,
                           channels_[0]);
      if (!st.ok()) return st;
      int src = (rank_ - s - 1 + W) % W;
      memcpy(out + src * B, a2a_rcv_.data() + (nblk - 1) * B, B);
      a2a_fwd_.swap(a2a_rcv_);
    }
    return Status::Ok();
  }

  // Accept one inbound comm off the shared listener and read its 8-byte
  // identifying hello. On failure the comm (if any) is closed. Shared by
  // the two lazy wiring paths (pairwise mesh, async ring channels), which
  // differ only in how they encode/validate the hello.
  Status AcceptHello(uint64_t* rc, uint64_t* hello) {
    *rc = 0;
    Status s = net_->accept(listen_comm_, rc);
    if (!s.ok()) return s;
    uint8_t buf[8] = {0};
    uint64_t req = 0;
    size_t got = 0;
    s = net_->irecv(*rc, buf, sizeof(buf), &req);
    if (s.ok()) s = net_->wait(req, &got);
    if (s.ok() && got != sizeof(buf)) s = Status::Inner("wiring hello truncated");
    if (!s.ok()) {
      net_->close_recv(*rc);
      *rc = 0;
      return s;
    }
    *hello = DecodeU64BE(buf);
    return Status::Ok();
  }

  // Connect to a peer's listener and identify the new comm with an 8-byte
  // hello — the other half of AcceptHello.
  Status ConnectHello(int peer, uint64_t hello, uint64_t* comm) {
    Status s = net_->connect(0, all_handles_[peer], comm);
    if (!s.ok()) return s;
    uint8_t buf[8];
    EncodeU64BE(hello, buf);
    uint64_t req = 0;
    s = net_->isend(*comm, buf, sizeof(buf), &req);
    if (s.ok()) s = net_->wait(req, nullptr);
    return s;
  }

  // Lazily wire one send + one recv comm per peer over the listeners whose
  // handles Init gathered. Every rank first issues all its connects (TCP
  // backlog + buffered preamble mean connect never blocks on the peer
  // calling accept), sends an 8-byte rank hello on each new comm, then
  // accepts its W-1 inbound comms and reads the hellos to key them by
  // peer — no bootstrap round, no cross-rank ordering assumption.
  Status EnsureMesh() {
    if (!mesh_send_.empty()) return Status::Ok();
    const int W = world_;
    std::vector<uint64_t> msend(W, 0), mrecv(W, 0);
    Status result = Status::Ok();
    for (int p = 0; p < W && result.ok(); ++p) {
      if (p == rank_) continue;
      result = ConnectHello(p, static_cast<uint64_t>(rank_), &msend[p]);
    }
    for (int i = 0; i < W - 1 && result.ok(); ++i) {
      uint64_t rc = 0, peer = 0;
      result = AcceptHello(&rc, &peer);
      if (!result.ok()) break;
      if (peer >= static_cast<uint64_t>(W) || peer == static_cast<uint64_t>(rank_) ||
          mrecv[peer] != 0) {
        net_->close_recv(rc);
        result = Status::Inner("mesh hello names invalid peer rank " +
                               std::to_string(peer));
      } else {
        mrecv[peer] = rc;
      }
    }
    if (!result.ok()) {
      for (uint64_t c : msend) {
        if (c) net_->close_send(c);
      }
      for (uint64_t c : mrecv) {
        if (c) net_->close_recv(c);
      }
      return result;
    }
    mesh_send_ = std::move(msend);
    mesh_recv_ = std::move(mrecv);
    return Status::Ok();
  }

  // One B-sized message to every peer, one from every peer, all posted
  // up-front on dedicated per-peer comms (so no message queues behind
  // another), then quiesced recv-first. O(W*B) wire bytes per rank.
  Status PairwiseAllToAll(const uint8_t* in, uint8_t* out, size_t B) {
    Status st = EnsureMesh();
    if (!st.ok()) return st;
    const int W = world_;
    // In-place callers overwrite recv block p while block p is still being
    // sent to peer p (send/recv blocks coincide in this collective) — stage
    // the outgoing blocks.
    const uint8_t* src = in;
    if (in == out) {
      a2a_fwd_.reserve(static_cast<size_t>(W) * B);
      memcpy(a2a_fwd_.data(), in, static_cast<size_t>(W) * B);
      src = a2a_fwd_.data();
    }
    std::vector<uint64_t> rreqs, sreqs;
    std::vector<int> rpeers, speers;
    Status first = Status::Ok();
    for (int s = 1; s < W; ++s) {
      int to = (rank_ + s) % W;
      int from = (rank_ - s + W) % W;
      uint64_t rreq = 0, sreq = 0;
      Status a = net_->irecv(mesh_recv_[from], out + from * B, B, &rreq);
      if (a.ok()) {
        rreqs.push_back(rreq);
        rpeers.push_back(from);
      } else if (first.ok()) {
        first = a;
      }
      Status b = net_->isend(mesh_send_[to], src + to * B, B, &sreq);
      if (b.ok()) {
        sreqs.push_back(sreq);
        speers.push_back(to);
      } else if (first.ok()) {
        first = b;
      }
    }
    for (size_t i = 0; i < rreqs.size(); ++i) {
      size_t got = 0;
      Status a = net_->wait(rreqs[i], &got);
      if (a.ok() && got != B) {
        a = Status::Inner("all_to_all block from rank " + std::to_string(rpeers[i]) +
                          ": got " + std::to_string(got) + "B, want " + std::to_string(B));
      }
      if (!a.ok() && first.ok()) first = a;
    }
    for (size_t i = 0; i < sreqs.size(); ++i) {
      Status b = net_->wait(sreqs[i], nullptr);
      if (!b.ok() && first.ok()) {
        first = Status{b.kind, "all_to_all send to rank " +
                                   std::to_string(speers[i]) + ": " + b.msg};
      }
    }
    return first;
  }

  Status NeighborExchange(const void* sendbuf, size_t send_nbytes, void* recvbuf,
                          size_t recv_nbytes, size_t* got) override {
    FenceAsync();
    if (world_ == 1) {
      if (send_nbytes > recv_nbytes) return Status::Invalid("recv buffer too small");
      memcpy(recvbuf, sendbuf, send_nbytes);
      if (got) *got = send_nbytes;
      return Status::Ok();
    }
    PhaseSpan whole(Telemetry::Get().tracing_enabled(), trace_comm_id_, ++coll_seq_,
                    "neighbor_exchange", -1, send_nbytes);
    return Exchange(sendbuf, send_nbytes, recvbuf, recv_nbytes, got, channels_[0]);
  }

  Status Barrier() override {
    if (world_ == 1) return Status::Ok();
    barrier_scratch_.resize(world_);
    uint8_t token = 1;
    return AllGather(&token, barrier_scratch_.data(), 1);  // fences via AllGather
  }

  Status IAllReduce(const void* sendbuf, void* recvbuf, size_t count, DType dtype,
                    RedOp op, uint64_t* ticket) override {
    MutexLock lk(async_mu_);
    if (!worker_started_) {
      // First async collective: wire the extra channels and spawn one worker
      // per channel. Safe to touch the listener here — the communicator runs
      // one collective program, so every rank reaches its first IAllReduce at
      // the same point of it and nothing else is mid-accept.
      Status s = EnsureAsyncChannels(AsyncChannelCount());
      if (!s.ok()) return s;
      queues_.resize(channels_.size());
      running_.assign(channels_.size(), 0);
      worker_started_ = true;
      for (size_t c = 0; c < channels_.size(); ++c) {
        workers_.emplace_back([this, c] { AsyncWorkerLoop(c); });
      }
    }
    uint64_t t = next_ticket_++;
    // Trace seq is claimed at SUBMISSION (same order on every rank), not at
    // execution, so spans from overlapping tickets keep cross-rank-stable
    // tags.
    uint64_t seq = ++coll_seq_;
    // Deterministic ticket→channel map: submission order is already the
    // cross-rank contract for nonblocking collectives, so every rank routes
    // ticket t to the same ring and messages pair up peer-to-peer.
    size_t ch = (t - 1) % queues_.size();
    queues_[ch].emplace_back(t, [this, sendbuf, recvbuf, count, dtype, op, ch, seq] {
      return DoAllReduce(sendbuf, recvbuf, count, dtype, op, channels_[ch], seq);
    });
    *ticket = t;
    work_cv_.NotifyAll();
    return Status::Ok();
  }

  Status WaitTicket(uint64_t ticket) override {
    MutexLock lk(async_mu_);
    if (!TicketLive(ticket)) return Status::Invalid("unknown or already-waited ticket");
    // Also wake if the ticket stops being live without completing (shutdown
    // dropped it, or a racing waiter claimed it) — never sleep forever.
    while (done_.count(ticket) == 0 && TicketLive(ticket)) done_cv_.Wait(async_mu_);
    auto it = done_.find(ticket);
    if (it == done_.end()) {
      return Status::Invalid("ticket abandoned (shutdown or waited elsewhere)");
    }
    Status s = it->second;
    done_.erase(it);
    return s;
  }

  Status TestTicket(uint64_t ticket, bool* done) override {
    MutexLock lk(async_mu_);
    auto it = done_.find(ticket);
    if (it != done_.end()) {
      *done = true;
      return Status::Ok();
    }
    if (!TicketLive(ticket)) return Status::Invalid("unknown or already-waited ticket");
    *done = false;
    return Status::Ok();
  }

  int rank() const override { return rank_; }
  int world_size() const override { return world_; }
  int32_t wire_codec() const override { return static_cast<int32_t>(codec_); }

 private:
  // The codec engages only where elements are KNOWN f32: AllReduce /
  // ReduceScatter payloads and the AG phase inside AllReduce. The
  // byte-oriented collectives (AllGather, Broadcast, AllToAll,
  // NeighborExchange, Barrier) carry opaque bytes — rendezvous handles,
  // tokens, arbitrary dtypes — and are never lossily compressed
  // (docs/DESIGN.md "Compressed collectives").
  bool UseCodec(DType dtype) const {
    return codec_ != WireCodec::kF32 && dtype == DType::kF32 && world_ > 1;
  }
  // One pipelined reduce ring step: send `sendbuf` to next while receiving
  // the same-size slice from prev in chunks, folding each received chunk
  // into `accum` (element count = slice bytes / esize) as soon as it lands —
  // chunk i's Reduce overlaps chunk i+1's transfer. Double-buffered scratch;
  // all in-flight requests are quiesced before returning, even on error.
  // `local` is the left operand of the reduce (accum = local op incoming);
  // nullptr = accum itself (the classic in-place accumulate). A distinct
  // local lets out-of-place collectives read the caller's sendbuf directly
  // and write partials straight into recvbuf — no staging copy anywhere.
  Status ExchangeReduce(const uint8_t* sendbuf, size_t send_nbytes, uint8_t* accum,
                        size_t recv_nbytes, DType dtype, RedOp op, RingChannel& ch,
                        const uint8_t* local = nullptr) {
    if (local == nullptr) local = accum;
    if (UseCodec(dtype)) {
      return ExchangeReduceCodec(sendbuf, send_nbytes, accum, recv_nbytes, op,
                                 ch, local);
    }
    size_t esize = DTypeSize(dtype);
    size_t chunk = RingChunkBytes() / esize * esize;
    if (chunk == 0 || (send_nbytes <= chunk && recv_nbytes <= chunk)) {
      ch.scratch.reserve(recv_nbytes);
      Status st = Exchange(sendbuf, send_nbytes, ch.scratch.data(), recv_nbytes, nullptr, ch);
      if (!st.ok()) return st;
      Reduce(accum, local, ch.scratch.data(), recv_nbytes / esize, dtype, op);
      return Status::Ok();
    }
    // Send and recv slice sizes can differ (ring slices are count*i/W
    // splits); each side chunks ITS byte count with the shared chunk size,
    // which matches what the peer computes for the same bytes. A chunk-size
    // mismatch between ranks surfaces as a size-mismatch error below.
    size_t ns = (send_nbytes + chunk - 1) / chunk;
    size_t nr = (recv_nbytes + chunk - 1) / chunk;
    size_t n = std::max(ns, nr);
    ch.scratch.reserve(2 * chunk);
    auto slen = [&](size_t i) { return std::min(chunk, send_nbytes - i * chunk); };
    auto rlen = [&](size_t i) { return std::min(chunk, recv_nbytes - i * chunk); };

    uint64_t rreq[2] = {0, 0}, sreq[2] = {0, 0};
    bool rlive[2] = {false, false}, slive[2] = {false, false};
    auto post = [&](size_t i) -> Status {
      int slot = i & 1;
      if (i < nr) {
        Status st =
            net_->irecv(ch.recv_comm, ch.scratch.data() + slot * chunk, rlen(i), &rreq[slot]);
        if (!st.ok()) return st;
        rlive[slot] = true;
      }
      if (i < ns) {
        Status st = net_->isend(ch.send_comm, sendbuf + i * chunk, slen(i), &sreq[slot]);
        if (!st.ok()) return st;
        slive[slot] = true;
      }
      return Status::Ok();
    };
    auto quiesce = [&](Status primary) {
      for (int b = 0; b < 2; ++b) {
        if (rlive[b]) WaitRequest(rreq[b], nullptr);
        if (slive[b]) WaitRequest(sreq[b], nullptr);
      }
      return primary;
    };

    Status st = post(0);
    if (!st.ok()) return quiesce(st);
    for (size_t i = 0; i < n; ++i) {
      int slot = i & 1;
      bool has_r = i < nr;
      if (has_r) {
        size_t got = 0;
        st = WaitRequest(rreq[slot], &got);
        rlive[slot] = false;
        if (!st.ok()) return quiesce(st);
        if (got != rlen(i)) {
          return quiesce(Status::Inner(
              "ring step size mismatch: expected " + std::to_string(rlen(i)) +
              "B chunk, got " + std::to_string(got) +
              "B (ranks disagree on collective arguments or TPUNET_RING_CHUNKSIZE?)"));
        }
      }
      if (i + 1 < n) {
        st = post(i + 1);  // keep the wire busy while we reduce chunk i
        if (!st.ok()) return quiesce(st);
      }
      if (has_r) {
        Reduce(accum + i * chunk, local + i * chunk,
               ch.scratch.data() + slot * chunk, rlen(i) / esize, dtype, op);
      }
      if (i < ns) {
        st = WaitRequest(sreq[slot], nullptr);
        slive[slot] = false;
        if (!st.ok()) return quiesce(st);
      }
    }
    return Status::Ok();
  }

  // Codec variant of ExchangeReduce for f32 payloads (docs/DESIGN.md
  // "Compressed collectives"): each chunk is ENCODED into a scratch slot
  // right before its isend and runs a FUSED decode+reduce straight off the
  // recv slot — the accumulator (and the local operand) stay f32, so
  // quantization error enters once per wire hop and never compounds in the
  // running sum. Chunk boundaries are computed over ELEMENT counts exactly
  // like the uncompressed path, so both peers derive identical per-chunk
  // wire sizes from their own payload byte counts; a rank disagreement
  // surfaces as the same size-mismatch error. Double-buffered recv AND send
  // slots (the encode is a staging copy the zero-copy f32 path avoids —
  // that copy is the price of shipping half/quarter the bytes).
  // Payload elements per pipeline chunk, sized so the WIRE chunk — not the
  // payload chunk — lands on the tuned TPUNET_RING_CHUNKSIZE granularity:
  // the ring's per-chunk costs (ctrl frames, request churn, stream
  // scheduling) are paid per chunk regardless of its size, so a compressed
  // chunk must carry as many wire bytes as an uncompressed one or
  // compression halves the bytes but none of the per-chunk overhead
  // (measured: payload-sized bf16 chunks left the whole RS phase at f32
  // speed). int8 chunks stay multiples of the scale block so the per-chunk
  // encoding is byte-identical to a whole-slice encode (the fused RS->AG
  // handoff and the AG receiver both rely on that).
  size_t CodecChunkElems() const {
    size_t ce;
    switch (codec_) {
      case WireCodec::kBF16:
        ce = RingChunkBytes() / 2;  // 2 wire bytes per element
        break;
      case WireCodec::kI8:
        ce = RingChunkBytes() & ~(kI8CodecBlock - 1);  // ~1 wire byte/element
        if (ce < kI8CodecBlock) ce = kI8CodecBlock;
        break;
      default:
        ce = RingChunkBytes() / 4;
        break;
    }
    return std::max<size_t>(ce, 1);
  }

  // `fused_enc` (optional): run the RS->AG handoff kernel on every received
  // chunk — the accumulator comes out QUANTIZED (bit-identical to what peers
  // will decode) and its encoded form lands at fused_enc, laid out exactly
  // like a whole-slice encode, ready to be the AG phase's first send.
  // `scratch_off`: byte offset into ch.scratch below which the caller has
  // staged bytes this call must not clobber.
  Status ExchangeReduceCodec(const uint8_t* sendbuf, size_t send_nbytes,
                             uint8_t* accum, size_t recv_nbytes, RedOp op,
                             RingChannel& ch, const uint8_t* local,
                             uint8_t* fused_enc = nullptr,
                             size_t scratch_off = 0) {
    if (local == nullptr) local = accum;  // classic in-place accumulate
    const float* send_f = reinterpret_cast<const float*>(sendbuf);
    float* acc_f = reinterpret_cast<float*>(accum);
    const float* loc_f = reinterpret_cast<const float*>(local);
    const WireRedOp wop = ToWireRedOp(op);
    const size_t send_n = send_nbytes / 4;
    const size_t recv_n = recv_nbytes / 4;
    const size_t chunk_elems = CodecChunkElems();

    if (send_n <= chunk_elems && recv_n <= chunk_elems) {
      size_t rw = CodecWireBytes(codec_, recv_n);
      size_t sw = CodecWireBytes(codec_, send_n);
      ch.scratch.reserve(scratch_off + rw + sw);
      uint8_t* rbuf = ch.scratch.data() + scratch_off;
      uint8_t* sbuf = rbuf + rw;
      CodecEncode(codec_, send_f, sbuf, send_n);
      Status st = Exchange(sbuf, sw, rbuf, rw, nullptr, ch);
      if (!st.ok()) return st;
      if (fused_enc != nullptr) {
        CodecDecodeReduceQuantize(codec_, acc_f, loc_f, rbuf, fused_enc, recv_n, wop);
      } else {
        CodecDecodeReduce(codec_, acc_f, loc_f, rbuf, recv_n, wop);
      }
      return Status::Ok();
    }

    const size_t ns = (send_n + chunk_elems - 1) / chunk_elems;
    const size_t nr = (recv_n + chunk_elems - 1) / chunk_elems;
    const size_t n = std::max(ns, nr);
    const size_t slot_bytes = CodecWireBytes(codec_, chunk_elems);
    // 2 recv + 2 send wire slots, after whatever the caller staged below
    // scratch_off (DoAllReduce parks the AG slots there — reserve only
    // grows, so their bytes survive this call).
    ch.scratch.reserve(scratch_off + 4 * slot_bytes);
    uint8_t* base = ch.scratch.data() + scratch_off;
    auto rbuf = [&](size_t i) { return base + (i & 1) * slot_bytes; };
    auto sbuf = [&](size_t i) { return base + (2 + (i & 1)) * slot_bytes; };
    auto selems = [&](size_t i) { return std::min(chunk_elems, send_n - i * chunk_elems); };
    auto relems = [&](size_t i) { return std::min(chunk_elems, recv_n - i * chunk_elems); };

    uint64_t rreq[2] = {0, 0}, sreq[2] = {0, 0};
    bool rlive[2] = {false, false}, slive[2] = {false, false};
    auto post = [&](size_t i) -> Status {
      int slot = i & 1;
      if (i < nr) {
        Status st = net_->irecv(ch.recv_comm, rbuf(i),
                                CodecWireBytes(codec_, relems(i)), &rreq[slot]);
        if (!st.ok()) return st;
        rlive[slot] = true;
      }
      if (i < ns) {
        // Encode right before the isend: slot (i&1)'s previous send (i-2)
        // was waited at the tail of iteration i-2, so the staging bytes are
        // free to overwrite, and the encode of chunk i overlaps the wire
        // moving chunk i-1.
        CodecEncode(codec_, send_f + i * chunk_elems, sbuf(i), selems(i));
        Status st = net_->isend(ch.send_comm, sbuf(i),
                                CodecWireBytes(codec_, selems(i)), &sreq[slot]);
        if (!st.ok()) return st;
        slive[slot] = true;
      }
      return Status::Ok();
    };
    auto quiesce = [&](Status primary) {
      for (int b = 0; b < 2; ++b) {
        if (rlive[b]) WaitRequest(rreq[b], nullptr);
        if (slive[b]) WaitRequest(sreq[b], nullptr);
      }
      return primary;
    };

    Status st = post(0);
    if (!st.ok()) return quiesce(st);
    for (size_t i = 0; i < n; ++i) {
      int slot = i & 1;
      bool has_r = i < nr;
      if (has_r) {
        size_t got = 0;
        st = WaitRequest(rreq[slot], &got);
        rlive[slot] = false;
        if (!st.ok()) return quiesce(st);
        if (got != CodecWireBytes(codec_, relems(i))) {
          return quiesce(Status::Inner(
              "ring step size mismatch: expected " +
              std::to_string(CodecWireBytes(codec_, relems(i))) +
              "B encoded chunk, got " + std::to_string(got) +
              "B (ranks disagree on collective arguments, TPUNET_RING_CHUNKSIZE "
              "or TPUNET_WIRE_DTYPE?)"));
        }
      }
      if (i + 1 < n) {
        st = post(i + 1);  // keep the wire busy while we decode+reduce chunk i
        if (!st.ok()) return quiesce(st);
      }
      if (has_r) {
        if (fused_enc != nullptr) {
          // Chunks are block-aligned (CodecChunkElems), so the wire offset
          // of chunk i inside the whole-slice encoding is exact.
          CodecDecodeReduceQuantize(codec_, acc_f + i * chunk_elems,
                                    loc_f + i * chunk_elems, rbuf(i),
                                    fused_enc + CodecWireBytes(codec_, i * chunk_elems),
                                    relems(i), wop);
        } else {
          CodecDecodeReduce(codec_, acc_f + i * chunk_elems, loc_f + i * chunk_elems,
                            rbuf(i), relems(i), wop);
        }
      }
      if (i < ns) {
        st = WaitRequest(sreq[slot], nullptr);
        slive[slot] = false;
        if (!st.ok()) return quiesce(st);
      }
    }
    return Status::Ok();
  }

  // Codec variant of the AllReduce AG phase ("AllGather passthrough":
  // encode-only, no reduce). Slices travel ENCODED, and the encoded bytes
  // are forwarded VERBATIM hop to hop while each rank decodes a private f32
  // copy — so every rank materializes BIT-IDENTICAL values for every slice
  // (the cross-rank determinism trainers assert on) and no hop ever
  // re-quantizes. Precondition: the RS final round's fused handoff
  // (CodecDecodeReduceQuantize) already QUANTIZED the owned slice in `data`
  // and parked its encoded bytes in scratch slot 0 — what the owner keeps
  // equals what every peer decodes, and this phase starts with zero codec
  // passes of its own over the owned slice. Net effect: one quantization of
  // each fully-reduced slice, on top of the RS phase's one-per-hop.
  Status AgPhaseCodec(float* data, size_t count, RingChannel& ch, uint64_t seq,
                      bool tracing) {
    const int W = world_;
    auto off = [&](int i) { return (count * static_cast<size_t>(i)) / W; };
    const size_t max_elems = (count + W - 1) / W;
    const size_t slot_bytes = CodecWireBytes(codec_, max_elems);
    ch.scratch.reserve(2 * slot_bytes);  // no-op: DoAllReduce pre-reserved
    uint8_t* slots[2] = {ch.scratch.data(), ch.scratch.data() + slot_bytes};
    int cur = 0;  // slot 0 holds enc(owned slice), courtesy of the RS fusion
    for (int s = 0; s < W - 1; ++s) {
      int sidx = (rank_ - s + W) % W;
      int ridx = (rank_ - s - 1 + W) % W;
      size_t sw = CodecWireBytes(codec_, off(sidx + 1) - off(sidx));
      size_t relems = off(ridx + 1) - off(ridx);
      size_t rw = CodecWireBytes(codec_, relems);
      PhaseSpan step(tracing, trace_comm_id_, seq, "ag", s, sw);
      // The slice sent at step s+1 is exactly the one received at step s
      // (sidx_{s+1} == ridx_s), so the received wire bytes ping-pong into
      // the next step's send slot untouched.
      Status st = Exchange(slots[cur], sw, slots[1 - cur], rw, nullptr, ch);
      if (!st.ok()) return st;
      CodecDecode(codec_, slots[1 - cur], data + off(ridx), relems);
      cur = 1 - cur;
    }
    return Status::Ok();
  }

  // One ring step: recv from prev into recvbuf while sending sendbuf to
  // next. Posts the irecv first; BOTH requests are waited before returning —
  // even on error — because an abandoned in-flight request would let the
  // caller free a buffer the stream workers still touch. When got==nullptr
  // the step is fixed-size and a short receive (ranks disagreeing on counts)
  // is an error, not silent stale-tail corruption.
  Status Exchange(const void* sendbuf, size_t send_nbytes, void* recvbuf, size_t recv_nbytes,
                  size_t* got, RingChannel& ch) {
    uint64_t rreq = 0, sreq = 0;
    Status st = net_->irecv(ch.recv_comm, recvbuf, recv_nbytes, &rreq);
    if (!st.ok()) return st;
    st = net_->isend(ch.send_comm, sendbuf, send_nbytes, &sreq);
    if (!st.ok()) {
      WaitRequest(rreq, nullptr);  // quiesce the posted recv before unwinding
      return st;
    }
    size_t rgot = 0;
    Status r_st = WaitRequest(rreq, &rgot);
    Status s_st = WaitRequest(sreq, nullptr);
    if (!r_st.ok()) return r_st;
    if (!s_st.ok()) return s_st;
    if (got) {
      *got = rgot;
    } else if (rgot != recv_nbytes) {
      return Status::Inner("ring step size mismatch: expected " + std::to_string(recv_nbytes) +
                           "B from prev rank, got " + std::to_string(rgot) +
                           "B (ranks disagree on collective arguments?)");
    }
    return Status::Ok();
  }

  // Wait out every pending send (ignoring their status) before surfacing
  // `primary` — never abandon in-flight requests that reference caller
  // buffers.
  Status DrainSends(std::vector<uint64_t>& reqs, Status primary) {
    for (uint64_t req : reqs) {
      Status st = WaitRequest(req, nullptr);
      if (primary.ok() && !st.ok()) primary = st;
    }
    reqs.clear();
    return primary;
  }

  // -- async worker machinery ---------------------------------------------

  // Number of independent async ring channels (and worker threads). Each
  // extra channel is one more comm pair per rank — with two, bucket k+1's
  // ring transfer runs while bucket k reduces, and the two transfers share
  // the NIC instead of serializing behind a single worker. Must agree across
  // ranks (it changes how many wiring connects each peer expects).
  static size_t AsyncChannelCount() {
    static const size_t v = [] {
      uint64_t n = GetEnvU64("TPUNET_ASYNC_CHANNELS", 2);
      return static_cast<size_t>(std::min<uint64_t>(std::max<uint64_t>(n, 1), 8));
    }();
    return v;
  }

  // Wire ring channels [channels_.size(), nch): connect to next with a
  // channel-tagged hello, then accept the matching connects from prev off
  // the shared listener. Connect never blocks on the peer's accept (TCP
  // backlog + the engine's buffered preamble), so connect-all-then-accept-all
  // cannot deadlock; the hello keys each inbound comm to its channel so
  // accept-order races cannot cross-wire rings. Runs once, on the caller
  // thread of the first IAllReduce, before any worker exists.
  Status EnsureAsyncChannels(size_t nch) {
    if (!async_wire_status_.ok()) return async_wire_status_;
    if (channels_.size() >= nch || world_ == 1) return Status::Ok();
    const int next = (rank_ + 1) % world_;
    const size_t base = channels_.size();
    channels_.resize(nch);
    Status result = Status::Ok();
    for (size_t c = base; c < nch && result.ok(); ++c) {
      result = ConnectHello(next, kRingHelloTag | c, &channels_[c].send_comm);
    }
    for (size_t i = base; i < nch && result.ok(); ++i) {
      uint64_t rc = 0, h = 0;
      result = AcceptHello(&rc, &h);
      if (!result.ok()) break;
      uint64_t c = h & 0xFFFFFFFFull;
      if ((h & ~0xFFFFFFFFull) != kRingHelloTag || c < base || c >= nch ||
          channels_[c].recv_comm != 0) {
        net_->close_recv(rc);
        result = Status::Inner("unexpected channel hello " + std::to_string(h));
      } else {
        channels_[c].recv_comm = rc;
      }
    }
    // Quiesce before returning: a rank whose wiring completes early (its
    // accepts only need PREV to have started) must not race ahead — its next
    // listener-touching op (EnsureMesh) could reach a peer still blocked in
    // the accept loop above and be mistaken for a channel connect. W-1
    // one-byte ring steps on channel 0: completing them implies every rank
    // entered this quiesce, i.e. finished wiring. Direct Exchange, not
    // Barrier() — that would re-lock async_mu_.
    for (int s = 0; s < world_ - 1 && result.ok(); ++s) {
      uint8_t token_out = 1, token_in = 0;
      result = Exchange(&token_out, 1, &token_in, 1, nullptr, channels_[0]);
    }
    if (!result.ok()) {
      // Peers may have wired a subset — the communicator's channel state is
      // inconsistent across ranks and cannot be retried; fail every later
      // async call the same way. Partially-wired comms close in ~RingComm.
      async_wire_status_ = result;
    }
    return result;
  }

  // A ticket is live (waitable) if it is queued, currently executing, or
  // completed-but-unclaimed.
  bool TicketLive(uint64_t ticket) REQUIRES(async_mu_) {
    if (done_.count(ticket)) return true;
    for (uint64_t r : running_) {
      if (r == ticket) return true;
    }
    for (const auto& q : queues_) {
      for (const auto& job : q) {
        if (job.first == ticket) return true;
      }
    }
    return false;
  }

  void AsyncWorkerLoop(size_t ch) {
    async_mu_.Lock();
    while (true) {
      while (!stop_ && queues_[ch].empty()) work_cv_.Wait(async_mu_);
      if (stop_) break;
      auto job = std::move(queues_[ch].front());
      queues_[ch].pop_front();
      running_[ch] = job.first;
      async_mu_.Unlock();
      Status s = job.second();  // the ring collective, off the caller thread
      async_mu_.Lock();
      running_[ch] = 0;
      done_[job.first] = s;
      done_cv_.NotifyAll();  // wakes WaitTicket and FenceAsync
    }
    async_mu_.Unlock();
  }

  // True when no async job is queued or executing.
  bool AsyncIdle() REQUIRES(async_mu_) {
    for (const auto& q : queues_) {
      if (!q.empty()) return false;
    }
    for (uint64_t r : running_) {
      if (r != 0) return false;
    }
    return true;
  }

  // Blocking collectives fence behind outstanding async work so the two
  // kinds never interleave on the underlying comms.
  void FenceAsync() {
    MutexLock lk(async_mu_);
    if (!worker_started_) return;
    while (!AsyncIdle()) done_cv_.Wait(async_mu_);
  }

  void StopAsyncWorker() {
    {
      MutexLock lk(async_mu_);
      if (!worker_started_) return;
      // Destroying with queued work is a caller error (peers would be left
      // mid-collective); the running jobs finish, queued jobs fail their
      // tickets so any blocked WaitTicket returns an error instead of
      // sleeping forever.
      stop_ = true;
      for (auto& q : queues_) {
        for (auto& job : q) {
          done_[job.first] = Status::Inner("communicator destroyed with pending collectives");
        }
        q.clear();
      }
      work_cv_.NotifyAll();
      done_cv_.NotifyAll();
    }
    for (std::thread& w : workers_) w.join();
  }

  Status WaitRequest(uint64_t req, size_t* nbytes) {
    // Blocking condvar wait — a test() poll loop here competes with the
    // stream worker threads for CPU (catastrophic on few-core hosts).
    return net_->wait(req, nbytes);
  }

  int rank_;
  int world_;
  // Wire compression codec for f32 collectives, fixed at construction and
  // verified equal across ranks by the Init handshake (UseCodec above).
  WireCodec codec_ = WireCodec::kF32;
  std::unique_ptr<Net> net_;
  std::unique_ptr<Bootstrap> bootstrap_;
  uint64_t listen_comm_ = 0;
  // Collective tracing identity: comm_id hashes (coordinator, world) — the
  // same on every rank — and coll_seq_ counts collectives in program order
  // (MPI semantics make the program identical across ranks), so
  // (trace_comm_id_, coll_seq_, phase) tags match rank-to-rank.
  uint64_t trace_comm_id_ = 0;
  uint64_t coll_seq_ = 0;
  // channels_[0] is the Init-wired ring every blocking collective uses;
  // channels_[1..] are wired by EnsureAsyncChannels for overlapping async
  // tickets. Stable after the first IAllReduce (workers capture indices).
  std::vector<RingChannel> channels_;
  // Scratch buffers reused across calls; a Communicator is not thread-safe
  // (one collective at a time, like an MPI communicator).
  // Pairwise-mesh comms for AllToAll, keyed by peer rank (0 = unwired /
  // self). Wired lazily by EnsureMesh from all_handles_.
  std::vector<SocketHandle> all_handles_;
  std::vector<uint64_t> mesh_send_;
  std::vector<uint64_t> mesh_recv_;
  ScratchBuf work_;
  std::vector<uint8_t> barrier_scratch_;
  ScratchBuf a2a_fwd_, a2a_rcv_;
  // Async (nonblocking-collective) state; async_mu_ guards all of it. Worker
  // c is the only place async jobs touch channel c's comms/scratch, and
  // FenceAsync keeps the sync paths out while any job runs. async_mu_ is
  // released before any job executes, so it is never held around engine or
  // request locks (docs/DESIGN.md "Concurrency model").
  Mutex async_mu_;
  CondVar work_cv_, done_cv_;
  std::vector<std::deque<std::pair<uint64_t, std::function<Status()>>>> queues_
      GUARDED_BY(async_mu_);
  std::vector<uint64_t> running_ GUARDED_BY(async_mu_);
  std::map<uint64_t, Status> done_ GUARDED_BY(async_mu_);
  Status async_wire_status_ = Status::Ok();
  uint64_t next_ticket_ GUARDED_BY(async_mu_) = 1;
  bool worker_started_ GUARDED_BY(async_mu_) = false;
  bool stop_ GUARDED_BY(async_mu_) = false;
  // Joined in StopAsyncWorker AFTER async_mu_ is released (a worker must be
  // able to take the lock to observe stop_), so the vector itself cannot be
  // async_mu_-guarded; it only grows under the lock in IAllReduce.
  std::vector<std::thread> workers_;
};

}  // namespace

Status Communicator::Create(const std::string& coordinator, int rank, int world_size,
                            std::unique_ptr<Communicator>* out) {
  return Create(coordinator, rank, world_size, "", out);
}

Status Communicator::Create(const std::string& coordinator, int rank, int world_size,
                            const std::string& wire_dtype,
                            std::unique_ptr<Communicator>* out) {
  if (world_size < 1 || rank < 0 || rank >= world_size) {
    return Status::Invalid("bad rank/world_size");
  }
  std::string name =
      wire_dtype.empty() ? GetEnv("TPUNET_WIRE_DTYPE", "f32") : wire_dtype;
  WireCodec codec;
  if (!ParseWireCodec(name, &codec)) {
    return Status::Invalid("unknown wire_dtype \"" + name +
                           "\" (expected f32, bf16 or int8)");
  }
  auto comm = std::make_unique<RingCommunicator>(rank, world_size, codec);
  Status s = comm->Init(coordinator);
  if (!s.ok()) return s;
  *out = std::move(comm);
  return Status::Ok();
}

}  // namespace tpunet
