// Net boilerplate shared by both transport engines: device enumeration and
// properties, listen-socket management, and env-config parsing (defaults
// per the reference: nstreams=2 nthread:228-231, min_chunksize=1MiB
// nthread:232-235). Engines derive and add only their data path, so the
// NIC/config surface cannot diverge between them.
#ifndef TPUNET_ENGINE_BASE_H_
#define TPUNET_ENGINE_BASE_H_

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cstdio>
#include <string>
#include <vector>

#include "id_map.h"
#include "tpunet/mutex.h"
#include "tpunet/net.h"
#include "tpunet/qos.h"
#include "tpunet/telemetry.h"
#include "tpunet/utils.h"
#include "wire.h"

namespace tpunet {

class EngineBase : public Net {
 public:
  EngineBase()
      : nics_(FindInterfaces()),
        nstreams_(GetEnvU64("TPUNET_NSTREAMS", GetEnvU64("BAGUA_NET_NSTREAMS", 2))),
        min_chunksize_(GetEnvU64("TPUNET_MIN_CHUNKSIZE",
                                 GetEnvU64("BAGUA_NET_MIN_CHUNKSIZE", 1 << 20))),
        crc_(GetEnvU64("TPUNET_CRC", 0) != 0),
        watchdog_ms_(GetEnvU64("TPUNET_PROGRESS_TIMEOUT_MS", 0)) {
    if (nstreams_ == 0) nstreams_ = 1;
    if (nstreams_ > kMaxStreams) nstreams_ = kMaxStreams;
    if (min_chunksize_ == 0) min_chunksize_ = 1;
    // Lane striping (TPUNET_LANES; docs/DESIGN.md "Lanes & adaptive
    // striping"): one lane == one data stream, so a lane spec overrides
    // TPUNET_NSTREAMS with its lane count. A malformed spec warns and runs
    // single-path — Config.from_env() is the loud gate (ValueError naming
    // the var), matching the TPUNET_TRAFFIC_CLASS stance.
    std::string lane_spec = GetEnv("TPUNET_LANES", "");
    if (!lane_spec.empty()) {
      Status ls = ParseLaneSpec(lane_spec, &lanes_);
      if (!ls.ok()) {
        fprintf(stderr, "[tpunet] ignoring TPUNET_LANES: %s\n", ls.msg.c_str());
        lanes_.clear();
      } else if (!lanes_.empty()) {
        lane_mode_ = true;
        nstreams_ = lanes_.size();
        lane_adapt_ = GetEnvU64("TPUNET_LANE_ADAPT", 1) != 0;
        lane_adapt_ms_ = GetEnvU64("TPUNET_LANE_ADAPT_MS", 100);
        if (lane_adapt_ms_ == 0) lane_adapt_ms_ = 100;
      }
    }
    // Engine-default traffic class (every comm this engine CONNECTS carries
    // it; per-communicator overrides arrive via set_traffic_class before
    // wiring). Unknown names fall back to bulk with a stderr warning —
    // Config.from_env() is the loud gate (_env_choice raises).
    TrafficClass tc = TrafficClass::kBulk;
    std::string name = GetEnv("TPUNET_TRAFFIC_CLASS", "bulk");
    if (!ParseTrafficClass(name, &tc)) {
      fprintf(stderr,
              "[tpunet] TPUNET_TRAFFIC_CLASS=%s is not latency|bulk|control; "
              "using bulk\n",
              name.c_str());
      tc = TrafficClass::kBulk;
    }
    traffic_class_.store(static_cast<int32_t>(tc), std::memory_order_relaxed);
  }

  int32_t devices() override { return static_cast<int32_t>(nics_.size()); }

  void set_traffic_class(int32_t cls) override {
    if (cls < 0 || cls >= kTrafficClassCount) cls = 1;  // unknown: bulk
    traffic_class_.store(cls, std::memory_order_relaxed);
  }
  int32_t traffic_class() const override {
    return traffic_class_.load(std::memory_order_relaxed);
  }

  Status get_properties(int32_t dev, NetProperties* props) override {
    Status s = CheckDev(dev);
    if (!s.ok()) return s;
    const NicInfo& nic = nics_[dev];
    props->name = nic.name;
    props->pci_path = nic.pci_path;
    props->guid = static_cast<uint64_t>(dev);
    props->ptr_support = 1;  // host memory only
    props->speed_mbps = nic.speed_mbps;
    props->port = 0;
    props->max_comms = 65536;  // reference: nthread:100
    return Status::Ok();
  }

  Status listen(int32_t dev, SocketHandle* handle, uint64_t* listen_comm) override {
    Status s = CheckDev(dev);
    if (!s.ok()) return s;
    ListenSockPtr lc;
    s = ListenOn(nics_[dev], dev, handle, &lc);
    if (!s.ok()) return s;
    uint64_t id = next_id_.fetch_add(1);
    listen_comms_.Put(id, lc);
    *listen_comm = id;
    return Status::Ok();
  }

  Status close_listen(uint64_t listen_comm) override {
    ListenSockPtr lc;
    if (!listen_comms_.Take(listen_comm, &lc)) {
      return Status::Invalid("unknown listen comm " + std::to_string(listen_comm));
    }
    // Wake any thread parked in accept(); it returns "listen comm closed".
    WakeListen(lc.get());
    return Status::Ok();
  }

 protected:
  // Shared blocking-wait body for engines (their requests_ maps are their
  // own, so they pass it in): park on the request condvar, then consume via
  // the engine's test(). The loop re-parks for the failed-but-not-yet-
  // quiesced window where test() reports not-done.
  //
  // Lock discipline: this function holds NO engine/comm lock — it parks on
  // the request's leaf err_mu (inside WaitSettled*) and calls test(), which
  // takes only IdMap shard locks. See docs/DESIGN.md "Concurrency model &
  // lock hierarchy".
  //
  // Progress watchdog (TPUNET_PROGRESS_TIMEOUT_MS > 0): while parked, the
  // request's (completed, nbytes) pair is sampled; a full window with zero
  // movement means a live-but-stuck peer (desync, scheduler stall, stalled
  // middlebox) that TCP keepalive will never flag. The request gets a typed
  // kTimeout error and its on_stall hook shuts the comm's sockets down so
  // blocked workers quiesce — upstream (train/elastic.py) classifies the
  // timeout exactly like a dead peer and rebuilds the generation.
  Status WaitIn(IdMap<RequestPtr>& requests, uint64_t request, size_t* nbytes) {
    while (true) {
      RequestPtr state;
      if (!requests.Get(request, &state)) {
        return Status::Invalid("unknown request " + std::to_string(request));
      }
      if (watchdog_ms_ == 0) {
        state->WaitSettled();
      } else {
        int slice = static_cast<int>(std::min<uint64_t>(watchdog_ms_, 100));
        uint64_t last_completed = state->completed.load(std::memory_order_acquire);
        uint64_t last_nbytes = state->nbytes.load(std::memory_order_relaxed);
        auto last_move = std::chrono::steady_clock::now();
        while (!state->WaitSettledFor(slice)) {
          uint64_t c = state->completed.load(std::memory_order_acquire);
          uint64_t b = state->nbytes.load(std::memory_order_relaxed);
          if (c != last_completed || b != last_nbytes) {
            last_completed = c;
            last_nbytes = b;
            last_move = std::chrono::steady_clock::now();
            continue;
          }
          if (std::chrono::steady_clock::now() - last_move >=
              std::chrono::milliseconds(watchdog_ms_)) {
            state->SetError(ErrorKind::kTimeout,
                            "progress watchdog: request moved zero bytes for " +
                                std::to_string(watchdog_ms_) +
                                "ms (TPUNET_PROGRESS_TIMEOUT_MS) — peer alive but stuck?");
            if (state->on_stall) state->on_stall();
            break;
          }
        }
      }
      bool done = false;
      Status st = test(request, &done, nbytes);
      if (!st.ok() || done) return st;
    }
  }

  // Stage-latency accounting at the request consumption point (the engine's
  // test() when it reports done; wait() funnels through test via WaitIn).
  // Shared here so the engines cannot diverge on WHEN a request's queue/wire
  // split is folded into the tpunet_req_{queue,wire,total}_us histograms.
  static void RecordRequestStages(const RequestPtr& state) {
    Telemetry::Get().OnRequestStages(
        state->t_post_us, state->t_first_wire_us.load(std::memory_order_relaxed),
        state->t_last_wire_us.load(std::memory_order_relaxed));
  }

  Status CheckDev(int32_t dev) const {
    if (dev < 0 || dev >= static_cast<int32_t>(nics_.size())) {
      return Status::Invalid("bad device index " + std::to_string(dev));
    }
    return Status::Ok();
  }

  // Blocks in the shared bundle-accept loop for the given listen comm.
  Status AcceptBundleOn(uint64_t listen_comm, PartialBundle* b) {
    ListenSockPtr lc;
    if (!listen_comms_.Get(listen_comm, &lc)) {
      return Status::Invalid("unknown listen comm " + std::to_string(listen_comm));
    }
    return AcceptBundle(lc.get(), b);
  }

  // Engine destructors call this so no thread stays parked in accept().
  void WakeAllListens() {
    for (auto& lc : listen_comms_.DrainAll()) WakeListen(lc.get());
  }

  // Preamble flags this engine advertises when connecting (sender's flags
  // win on the far side, like nstreams/min_chunksize). Carries the QoS
  // traffic-class nibble so the receiver's comm adopts the sender's class.
  uint64_t PreambleFlags() const {
    return (crc_ ? kPreambleFlagCrc : 0) | PreambleClassBits(traffic_class()) |
           (lane_mode_ ? kPreambleFlagLanes : 0);
  }

  // Configured (base) lane weights; all-1 when TPUNET_LANES is unset.
  std::vector<uint32_t> LaneBaseWeights() const {
    std::vector<uint32_t> w(nstreams_, 1);
    for (size_t i = 0; i < lanes_.size() && i < w.size(); ++i) {
      w[i] = lanes_[i].weight;
    }
    return w;
  }

  std::vector<NicInfo> nics_;
  uint64_t nstreams_;
  uint64_t min_chunksize_;
  // Lane striping (TPUNET_LANES): per-stream local bind addresses + base
  // weights; lane_mode_ gates the preamble capability bit, the weighted
  // scheduler, and the ctrl WEIGHTS epoch protocol in the engines.
  std::vector<LaneSpec> lanes_;
  bool lane_mode_ = false;
  bool lane_adapt_ = true;       // TPUNET_LANE_ADAPT (lane mode only)
  uint64_t lane_adapt_ms_ = 100; // TPUNET_LANE_ADAPT_MS adaptation tick
  bool crc_;              // TPUNET_CRC=1: per-chunk CRC32C trailers
  uint64_t watchdog_ms_;  // TPUNET_PROGRESS_TIMEOUT_MS (0 = off)
  std::atomic<int32_t> traffic_class_{1};  // TrafficClass int; default bulk
  std::atomic<uint64_t> next_id_{1};
  IdMap<ListenSockPtr> listen_comms_;
};

}  // namespace tpunet

#endif  // TPUNET_ENGINE_BASE_H_
