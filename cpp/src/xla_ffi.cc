// XLA FFI custom-call collectives: the DCN allreduce as a zero-copy CPU
// custom call.
//
// The io_callback bridge (tpunet/interop.py) costs ~3 full-buffer memcpys
// per call on top of the native reduce (measured round 5: identity
// io_callback 0.48 s for 128 MiB where the reduce itself is 0.24 s) —
// XLA stages the callback operand, the host result, and the copy back
// into a device buffer. An XLA FFI handler instead receives the XLA CPU
// buffers DIRECTLY: the ring reads the operand buffer and writes the
// result buffer in place, zero host staging. The handler is header-only
// (xla/ffi/api/ffi.h resolves everything through the call frame's API
// table at runtime), so libtpunet.so gains no link dependency on XLA;
// builds without jaxlib headers simply omit this object (Makefile guard).
//
// The communicator is looked up through the process-default registry
// (tpunet_comm_set_default) at CALL time, not baked into the executable:
// elastic recovery replaces the communicator under the same jitted step
// (tpunet/distributed.initialize re-points the default), and stale ids
// in cached executables would otherwise dereference a destroyed comm.
//
// Reference analogue: none — the reference's torch tier binds NCCL
// through torch.distributed; this is the jax-native equivalent tier.

#include <cstdint>
#include <string>

#include "tpunet/c_api.h"
#include "xla/ffi/api/ffi.h"

namespace ffi = xla::ffi;

namespace {

ffi::Error ToError(int32_t rc, const char* what) {
  if (rc == 0) return ffi::Error::Success();
  // Mirror the ctypes binding's NativeError text ("tpunet native <op>
  // failed (code N): <detail>"): elastic recovery classifies comm
  // failures by that marker in the stringified XlaRuntimeError
  // (tpunet/train/elastic.py is_comm_failure), and the FFI path must
  // stay classifiable the way the io_callback path was.
  const char* detail = tpunet_c_last_error();
  return ffi::Error(ffi::ErrorCode::kInternal,
                    std::string("tpunet native ") + what + " failed (code " +
                        std::to_string(rc) + "): " +
                        (detail ? detail : ""));
}

ffi::Error AllReduceImpl(int64_t dtype, int64_t op, ffi::AnyBuffer x,
                         ffi::Result<ffi::AnyBuffer> out) {
  uintptr_t comm = tpunet_comm_get_default();
  if (comm == 0) {
    return ffi::Error(
        ffi::ErrorCode::kFailedPrecondition,
        "no default communicator: call tpunet.distributed.initialize() "
        "before running FFI collectives");
  }
  const uint64_t n = static_cast<uint64_t>(x.element_count());
  return ToError(
      tpunet_comm_all_reduce(comm, n ? x.untyped_data() : nullptr,
                             n ? out->untyped_data() : nullptr, n,
                             static_cast<int32_t>(dtype),
                             static_cast<int32_t>(op)),
      "all_reduce");
}

}  // namespace

XLA_FFI_DEFINE_HANDLER_SYMBOL(TpunetFfiAllReduce, AllReduceImpl,
                              ffi::Ffi::Bind()
                                  .Attr<int64_t>("dtype")
                                  .Attr<int64_t>("op")
                                  .Arg<ffi::AnyBuffer>()
                                  .Ret<ffi::AnyBuffer>());
