// XLA FFI custom-call collectives: the DCN allreduce as a zero-copy CPU
// custom call.
//
// The io_callback bridge (tpunet/interop.py) costs ~3 full-buffer memcpys
// per call on top of the native reduce (measured round 5: identity
// io_callback 0.48 s for 128 MiB where the reduce itself is 0.24 s) —
// XLA stages the callback operand, the host result, and the copy back
// into a device buffer. An XLA FFI handler instead receives the XLA CPU
// buffers DIRECTLY: the ring reads the operand buffer and writes the
// result buffer in place, zero host staging. The handler is header-only
// (xla/ffi/api/ffi.h resolves everything through the call frame's API
// table at runtime), so libtpunet.so gains no link dependency on XLA;
// builds without jaxlib headers simply omit this object (Makefile guard).
//
// The communicator is looked up through the process-default registry
// (tpunet_comm_set_default) at CALL time, not baked into the executable:
// elastic recovery replaces the communicator under the same jitted step
// (tpunet/distributed.initialize re-points the default), and stale ids
// in cached executables would otherwise dereference a destroyed comm.
//
// Reference analogue: none — the reference's torch tier binds NCCL
// through torch.distributed; this is the jax-native equivalent tier.

#include <cstdint>
#include <cstring>
#include <string>

#include "tpunet/c_api.h"
#include "xla/ffi/api/ffi.h"

namespace ffi = xla::ffi;

namespace {

ffi::Error ToError(int32_t rc, const char* what) {
  if (rc == 0) return ffi::Error::Success();
  // Mirror the ctypes binding's NativeError text ("tpunet native <op>
  // failed (code N): <detail>"): elastic recovery classifies comm
  // failures by that marker in the stringified XlaRuntimeError
  // (tpunet/train/elastic.py is_comm_failure), and the FFI path must
  // stay classifiable the way the io_callback path was.
  const char* detail = tpunet_c_last_error();
  return ffi::Error(ffi::ErrorCode::kInternal,
                    std::string("tpunet native ") + what + " failed (code " +
                        std::to_string(rc) + "): " +
                        (detail ? detail : ""));
}

// Every handler takes trailing "ordering operands" (ffi::RemainingArgs,
// ignored): a data-independent collective that must run AFTER another one
// passes the earlier result as an extra operand (interop's `after=`).
// An operand of an opaque side-effecting custom call is a dependency no
// XLA pass can dissolve — unlike stablehlo.optimization_barrier, which
// the pipeline expanded away and reordered in practice (round-5 bug:
// rank-asymmetric ring traces cross-matched their k/v exchanges).
ffi::Error DefaultComm(uintptr_t* comm);

ffi::Error AllReduceImpl(int64_t dtype, int64_t op, ffi::AnyBuffer x,
                         ffi::RemainingArgs, ffi::Result<ffi::AnyBuffer> out) {
  uintptr_t comm;
  if (auto err = DefaultComm(&comm); err.failure()) return err;
  const uint64_t n = static_cast<uint64_t>(x.element_count());
  return ToError(
      tpunet_comm_all_reduce(comm, n ? x.untyped_data() : nullptr,
                             n ? out->untyped_data() : nullptr, n,
                             static_cast<int32_t>(dtype),
                             static_cast<int32_t>(op)),
      "all_reduce");
}

}  // namespace

XLA_FFI_DEFINE_HANDLER_SYMBOL(TpunetFfiAllReduce, AllReduceImpl,
                              ffi::Ffi::Bind()
                                  .Attr<int64_t>("dtype")
                                  .Attr<int64_t>("op")
                                  .Arg<ffi::AnyBuffer>()
                                  .RemainingArgs()
                                  .Ret<ffi::AnyBuffer>());

namespace {

ffi::Error DefaultComm(uintptr_t* comm) {
  *comm = tpunet_comm_get_default();
  if (*comm == 0) {
    return ffi::Error(
        ffi::ErrorCode::kFailedPrecondition,
        "no default communicator: call tpunet.distributed.initialize() "
        "before running FFI collectives");
  }
  return ffi::Error::Success();
}

// Call-time world for the shape checks below. The buffer SHAPES were baked
// in at trace time, but the communicator resolves at CALL time — after an
// elastic world change a cached executable would silently gather garbage
// (shrink) or overflow the result buffer (grow). Shape-vs-world mismatches
// return kFailedPrecondition naming both numbers so elastic recovery sees a
// loud comm-shaped failure, never corrupted data.
ffi::Error CallTimeWorld(uintptr_t comm, int32_t* world) {
  int32_t rank = 0;
  *world = 0;
  if (auto err = ToError(tpunet_comm_rank(comm, &rank, world), "comm_rank");
      err.failure()) {
    return err;
  }
  if (*world <= 0) {
    return ffi::Error(ffi::ErrorCode::kFailedPrecondition,
                      "tpunet communicator reports non-positive world size");
  }
  return ffi::Error::Success();
}

ffi::Error ShapeWorldMismatch(const char* what, uint64_t got, uint64_t want,
                              int32_t world) {
  return ffi::Error(
      ffi::ErrorCode::kFailedPrecondition,
      std::string("tpunet ") + what + " shape does not match the CALL-TIME "
          "world size " + std::to_string(world) + ": got " +
          std::to_string(got) + ", want " + std::to_string(want) +
          " (executable traced for a different world — elastic change? "
          "re-trace or rebuild the jitted function)");
}

ffi::Error AllGatherImpl(ffi::AnyBuffer x, ffi::RemainingArgs,
                         ffi::Result<ffi::AnyBuffer> out) {
  uintptr_t comm;
  if (auto err = DefaultComm(&comm); err.failure()) return err;
  int32_t world = 0;
  if (auto err = CallTimeWorld(comm, &world); err.failure()) return err;
  const uint64_t want = static_cast<uint64_t>(world) * x.size_bytes();
  if (static_cast<uint64_t>(out->size_bytes()) != want) {
    return ShapeWorldMismatch("all_gather result bytes", out->size_bytes(),
                              want, world);
  }
  return ToError(tpunet_comm_all_gather(comm, x.untyped_data(),
                                        out->untyped_data(), x.size_bytes()),
                 "all_gather");
}

ffi::Error ReduceScatterImpl(int64_t dtype, int64_t op, ffi::AnyBuffer x,
                             ffi::RemainingArgs,
                             ffi::Result<ffi::AnyBuffer> out) {
  uintptr_t comm;
  if (auto err = DefaultComm(&comm); err.failure()) return err;
  int32_t world = 0;
  if (auto err = CallTimeWorld(comm, &world); err.failure()) return err;
  const uint64_t want =
      static_cast<uint64_t>(world) * static_cast<uint64_t>(out->element_count());
  if (static_cast<uint64_t>(x.element_count()) != want) {
    return ShapeWorldMismatch("reduce_scatter operand elements",
                              x.element_count(), want, world);
  }
  return ToError(
      tpunet_comm_reduce_scatter(comm, x.untyped_data(), out->untyped_data(),
                                 out->element_count(),
                                 static_cast<int32_t>(dtype),
                                 static_cast<int32_t>(op)),
      "reduce_scatter");
}

ffi::Error BroadcastImpl(int64_t root, ffi::AnyBuffer x,
                         ffi::RemainingArgs,
                         ffi::Result<ffi::AnyBuffer> out) {
  uintptr_t comm;
  if (auto err = DefaultComm(&comm); err.failure()) return err;
  // The C API broadcasts in place; the result buffer doubles as the
  // working buffer (one memcpy of this rank's payload — still two fewer
  // copies than the io_callback bridge).
  if (x.size_bytes()) {
    std::memcpy(out->untyped_data(), x.untyped_data(), x.size_bytes());
  }
  return ToError(tpunet_comm_broadcast(comm, out->untyped_data(),
                                       x.size_bytes(),
                                       static_cast<int32_t>(root)),
                 "broadcast");
}

ffi::Error AllToAllImpl(ffi::AnyBuffer x, ffi::RemainingArgs,
                        ffi::Result<ffi::AnyBuffer> out) {
  uintptr_t comm;
  if (auto err = DefaultComm(&comm); err.failure()) return err;
  int32_t world = 0;
  if (auto err = CallTimeWorld(comm, &world); err.failure()) return err;
  // The leading axis IS the per-peer block structure; it must equal the
  // call-time world or block j lands on the wrong rank (and the byte count
  // per peer is wrong). A rank-0 scalar payload has no axis to check.
  auto dims = x.dimensions();
  const uint64_t lead = dims.size() > 0 ? static_cast<uint64_t>(dims[0]) : 0;
  if (lead != static_cast<uint64_t>(world)) {
    return ShapeWorldMismatch("all_to_all leading axis", lead,
                              static_cast<uint64_t>(world), world);
  }
  if (x.size_bytes() % world) {
    return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                      "all_to_all payload not divisible by world size");
  }
  return ToError(tpunet_comm_all_to_all(comm, x.untyped_data(),
                                        out->untyped_data(),
                                        x.size_bytes() / world),
                 "all_to_all");
}

ffi::Error NeighborExchangeImpl(ffi::AnyBuffer x, ffi::RemainingArgs,
                                ffi::Result<ffi::AnyBuffer> out) {
  uintptr_t comm;
  if (auto err = DefaultComm(&comm); err.failure()) return err;
  uint64_t got = 0;
  auto err = ToError(
      tpunet_comm_neighbor_exchange(comm, x.untyped_data(), x.size_bytes(),
                                    out->untyped_data(), x.size_bytes(),
                                    &got),
      "neighbor_exchange");
  if (err.failure()) return err;
  if (got != x.size_bytes()) {
    return ffi::Error(ffi::ErrorCode::kInternal,
                      "tpunet native neighbor_exchange failed (short "
                      "message): got " + std::to_string(got) + " of " +
                          std::to_string(x.size_bytes()) + " bytes");
  }
  return ffi::Error::Success();
}

}  // namespace

XLA_FFI_DEFINE_HANDLER_SYMBOL(TpunetFfiAllGather, AllGatherImpl,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::AnyBuffer>()
                                  .RemainingArgs()
                                  .Ret<ffi::AnyBuffer>());

XLA_FFI_DEFINE_HANDLER_SYMBOL(TpunetFfiReduceScatter, ReduceScatterImpl,
                              ffi::Ffi::Bind()
                                  .Attr<int64_t>("dtype")
                                  .Attr<int64_t>("op")
                                  .Arg<ffi::AnyBuffer>()
                                  .RemainingArgs()
                                  .Ret<ffi::AnyBuffer>());

XLA_FFI_DEFINE_HANDLER_SYMBOL(TpunetFfiBroadcast, BroadcastImpl,
                              ffi::Ffi::Bind()
                                  .Attr<int64_t>("root")
                                  .Arg<ffi::AnyBuffer>()
                                  .RemainingArgs()
                                  .Ret<ffi::AnyBuffer>());

XLA_FFI_DEFINE_HANDLER_SYMBOL(TpunetFfiAllToAll, AllToAllImpl,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::AnyBuffer>()
                                  .RemainingArgs()
                                  .Ret<ffi::AnyBuffer>());

XLA_FFI_DEFINE_HANDLER_SYMBOL(TpunetFfiNeighborExchange, NeighborExchangeImpl,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::AnyBuffer>()
                                  .RemainingArgs()
                                  .Ret<ffi::AnyBuffer>());
