// Deterministic fault injection for the transport engines.
//
// The fault-path test suite used to SIGKILL real subprocesses mid-64MiB
// allreduce to exercise failure handling — slow, racy, and unable to target
// a SPECIFIC stream or byte offset. This module makes faults first-class:
// a spec like
//
//   stream=1:after_bytes=1M:action=close
//   stream=*:side=recv:after_bytes=256K:action=delay=50
//
// arms exactly one fault, evaluated on the engines' send/recv hot paths.
// Armed via env TPUNET_FAULT_SPEC (read at engine creation) or at runtime
// through tpunet_c_fault_inject() (c_api.h). Disarmed, the hot-path check
// is a single relaxed atomic load — it compiles to a predicted-not-taken
// branch and costs nothing measurable.
//
// Spec grammar (colon-separated key=value pairs, sizes take K/M/G suffixes):
//   stream=<idx>|*        data-stream index the fault targets (* = any)
//   side=send|recv|*      direction, default *
//   after_bytes=<n>       trigger once this many bytes moved on a matching
//                         (side, stream); default 0 = first IO
//   action=close          shutdown(2) the stream's socket (both halves) —
//                         the canonical stream-loss/failover trigger
//   action=stall          stop moving bytes on the stream while armed (the
//                         live-but-stuck peer the progress watchdog exists
//                         for); releases when disarmed or the comm aborts
//   action=corrupt        flip one byte of the next chunk on the wire
//                         (detected by TPUNET_CRC=1, silent otherwise —
//                         that asymmetry is the point)
//   action=delay=<ms>     sleep <ms> before each matching IO while armed
//
// close and corrupt are one-shot (first matching IO past the threshold
// claims them); stall and delay persist until disarmed. Faults never target
// the ctrl connection — ctrl loss is a poison-the-comm event by design and
// needs no injection subtlety beyond `close` on the last data stream.
//
// CHURN SCRIPTS (docs/DESIGN.md "Elastic churn"): the grammar also accepts
// membership-churn events so whole kill/join sequences are deterministic
// and CI-runnable:
//
//   churn:at_step=4:rank=3:action=kill;churn:at_step=8:rank=4:action=join
//
// A spec is a ';'-separated list of segments; a segment whose first clause
// is the bare token `churn` is a churn event (at_step = first step the
// event fires at, one-shot; rank = the member id it targets, * = any;
// action = kill | join), anything else is the classic single-fault spec
// (at most one per script). Churn events are not applied by the engines:
// the elastic layer polls them at step boundaries (tpunet_c_churn_poll) —
// a `kill` tells the polling rank to die NOW, a `join` tells the
// supervisor/joiner side a new rank should enter the world — so the whole
// churn suite replays bit-identically from one env var.
//
// SWAP SCRIPTS (docs/DESIGN.md "Live weight updates"): the grammar also
// accepts weight-hot-swap chaos events so the publication drills (death
// mid-broadcast, corrupted receiver, scripted publish) are deterministic:
//
//   swap:at_step=6:action=publish;swap:at_step=9:action=die
//
// A segment whose first clause is the bare token `swap` is a swap event
// (at_step = first step the event fires at, one-shot; action = publish |
// corrupt | die). There is no rank clause: each process arms its OWN spec
// via env, so "who corrupts / who dies" is the launcher's choice. Like
// churn, swap events are polled at step boundaries (tpunet_c_swap_poll) —
// `publish` tells the publisher to start a publication NOW, `corrupt`
// tells the polling receiver to damage its received weight bytes before
// digesting (the fleet-wide flip-refusal drill), `die` tells the polling
// rank to SIGKILL itself (mid-broadcast when the step lands there).
#ifndef TPUNET_FAULT_H_
#define TPUNET_FAULT_H_

#include <atomic>
#include <cstddef>
#include <cstdint>
#include <string>
#include <vector>

#include "tpunet/net.h"

namespace tpunet {

enum class FaultAction : int32_t {
  kNone = 0,
  kClose = 1,
  kStall = 2,
  kCorrupt = 3,
  kDelay = 4,
};
constexpr int kFaultActionCount = 5;

struct FaultSpec {
  int64_t stream = -1;       // -1 = any data stream
  int32_t side = 0;          // 0 = any, 1 = send, 2 = recv
  uint64_t after_bytes = 0;  // cumulative bytes on a matching (side, stream)
  FaultAction action = FaultAction::kNone;
  uint64_t delay_ms = 0;     // kDelay only
};

// Scripted membership churn (docs/DESIGN.md "Elastic churn"). Actions are
// advisory verdicts for the elastic layer, never applied by the engines.
enum class ChurnAction : int32_t {
  kNone = 0,
  kKill = 1,  // the polled rank must die at this step (SIGKILL itself)
  kJoin = 2,  // a new rank enters the world at this step (supervisor-side)
};

struct ChurnEvent {
  uint64_t at_step = 0;  // fires at the FIRST poll with step >= at_step
  int64_t rank = -1;     // member id the event targets (-1 = any)
  ChurnAction action = ChurnAction::kNone;
  bool fired = false;    // one-shot latch, set by ChurnPoll
};

// Scripted weight-hot-swap chaos (docs/DESIGN.md "Live weight updates").
// Advisory verdicts for the publication layer, never applied by the
// engines; no rank clause — each process arms its own script via env.
enum class SwapAction : int32_t {
  kNone = 0,
  kPublish = 1,  // publisher: start a weight publication at this step
  kCorrupt = 2,  // receiver: damage received weight bytes before digesting
  kDie = 3,      // polling rank: SIGKILL itself at this step
};

struct SwapEvent {
  uint64_t at_step = 0;  // fires at the FIRST poll with step >= at_step
  SwapAction action = SwapAction::kNone;
  bool fired = false;    // one-shot latch, set by SwapPoll
};

// Parse `spec` into `out`; Invalid status (with the offending token named)
// on malformed input. Pure — no global state touched.
Status ParseFaultSpec(const std::string& spec, FaultSpec* out);

// Parse one churn segment ("churn:at_step=N:rank=K:action=kill|join";
// at_step defaults to 0, rank to *, action is mandatory). Pure.
Status ParseChurnSpec(const std::string& spec, ChurnEvent* out);

// Parse one swap segment ("swap:at_step=N:action=publish|corrupt|die";
// at_step defaults to 0, action is mandatory). Pure.
Status ParseSwapSpec(const std::string& spec, SwapEvent* out);

// Parse a whole ';'-separated script: churn segments collect into `churn`,
// swap segments into `swap`, the (at most one) classic segment into
// `fault`/`has_fault`. Pure.
Status ParseFaultScript(const std::string& spec, FaultSpec* fault,
                        bool* has_fault, std::vector<ChurnEvent>* churn,
                        std::vector<SwapEvent>* swap);

// Arm/disarm the process-wide fault slot (one fault at a time — chaos tests
// arm, run, clear). Arming resets the byte counters and one-shot latches.
void ArmFault(const FaultSpec& spec);
void DisarmFault();
// Arm the process-wide churn script (replaces any previous script and its
// fired latches). DisarmFault()/tpunet_c_fault_clear wipe it too.
void ArmChurnScript(const std::vector<ChurnEvent>& events);
// One-shot poll at a step boundary: the first un-fired event with
// at_step <= step targeting `rank` (or any) fires and returns its action;
// kNone when nothing fires. ">=" rather than "==" so a rank that resumed
// past the scripted step (checkpoint restore) still honors the event.
ChurnAction ChurnPoll(uint64_t step, int64_t rank);
// Events armed but not yet fired (the smoke lane's completeness gate).
int ChurnPending();
// Arm the process-wide swap chaos script (replaces any previous script and
// its fired latches). DisarmFault()/tpunet_c_fault_clear wipe it too.
void ArmSwapScript(const std::vector<SwapEvent>& events);
// One-shot poll at a step boundary: the first un-fired event with
// at_step <= step fires and returns its action; kNone when nothing fires.
SwapAction SwapPoll(uint64_t step);
// Swap events armed but not yet fired.
int SwapPending();
// Arm from TPUNET_FAULT_SPEC if set and parseable (called at engine
// creation); a malformed env spec is reported on stderr and ignored —
// a typo must not take down training.
void ArmFaultFromEnv();

// Hot-path gate. Callers pass the IO they are about to perform; the slow
// path applies side effects in place — kClose shuts the fd down (the IO
// then fails organically), kStall parks in FaultStall until disarm/abort,
// kDelay sleeps — and the return value tells the caller the one action that
// needs its cooperation:
//   kNone     proceed as usual (possibly after an internal stall/delay)
//   kCorrupt  flip a byte of the payload on the wire (send side: in a copy,
//             never the caller's buffer, with the CRC trailer computed over
//             the ORIGINAL bytes so TPUNET_CRC=1 detects the damage; recv
//             side: in the received bytes before CRC verification)
FaultAction FaultPreIO(bool is_send, uint64_t stream_idx, int fd, size_t nbytes);

// Memory-transport variant (the SHM engine's ring has no fd to shutdown):
// identical matching/latching/telemetry, but kClose and kStall are RETURNED
// instead of applied — the caller owns the side effect (close = fail over
// the segment to the TCP ctrl path; stall = park against its own abort
// flag). kDelay still sleeps internally; kCorrupt means flip a byte of the
// ring copy, never the caller's buffer, like the socket path.
FaultAction FaultPreMem(bool is_send, uint64_t stream_idx, size_t nbytes);

extern std::atomic<uint32_t> g_fault_armed;

inline FaultAction FaultCheck(bool is_send, uint64_t stream_idx, int fd, size_t nbytes) {
  // The single disarmed-path branch: one relaxed load, no fences.
  if (g_fault_armed.load(std::memory_order_relaxed) == 0) return FaultAction::kNone;
  return FaultPreIO(is_send, stream_idx, fd, nbytes);
}

// Park while the stall fault holds: sleeps in small slices until the fault
// is disarmed or the fd is shut down (POLLERR/POLLHUP — how a watchdog
// abort or comm teardown releases a stalled worker).
void FaultStall(int fd);

}  // namespace tpunet

#endif  // TPUNET_FAULT_H_
