// tpunet telemetry implementation. See include/tpunet/telemetry.h.
#include "tpunet/telemetry.h"

#include <netdb.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

#include "tpunet/utils.h"

namespace tpunet {
namespace {

uint64_t NowUs() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

int HistBucket(uint64_t nbytes) {
  for (int i = 0; i < kHistBuckets - 1; ++i) {
    if (nbytes <= kHistBounds[i]) return i;
  }
  return kHistBuckets - 1;
}

int64_t RankFromEnv() {
  return static_cast<int64_t>(GetEnvU64("TPUNET_RANK", GetEnvU64("RANK", 0)));
}

// Reference gating: telemetry only for ranks 0-7 with the address var set
// (nthread:108-130).
bool RankGate() {
  int64_t r = RankFromEnv();
  return r >= 0 && r <= 7;
}

std::string Base64(const std::string& in) {
  static const char* tbl = "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/";
  std::string out;
  size_t i = 0;
  while (i + 2 < in.size()) {
    uint32_t v = (uint8_t(in[i]) << 16) | (uint8_t(in[i + 1]) << 8) | uint8_t(in[i + 2]);
    out += tbl[(v >> 18) & 63];
    out += tbl[(v >> 12) & 63];
    out += tbl[(v >> 6) & 63];
    out += tbl[v & 63];
    i += 3;
  }
  if (i + 1 == in.size()) {
    uint32_t v = uint8_t(in[i]) << 16;
    out += tbl[(v >> 18) & 63];
    out += tbl[(v >> 12) & 63];
    out += "==";
  } else if (i + 2 == in.size()) {
    uint32_t v = (uint8_t(in[i]) << 16) | (uint8_t(in[i + 1]) << 8);
    out += tbl[(v >> 18) & 63];
    out += tbl[(v >> 12) & 63];
    out += tbl[(v >> 6) & 63];
    out += "=";
  }
  return out;
}

struct Span {
  bool is_send;
  uint64_t comm;
  uint64_t req;
  uint64_t nbytes;
  uint64_t start_us;
  uint64_t dur_us;
};

// Request ids are engine-local (each instance counts from 1), so open spans
// are keyed by (owner instance tag, request id).
using SpanKey = std::pair<uint64_t, uint64_t>;
struct SpanKeyHash {
  size_t operator()(const SpanKey& k) const {
    return std::hash<uint64_t>()(k.first * 0x9e3779b97f4a7c15ull ^ k.second);
  }
};

}  // namespace

struct Telemetry::Impl {
  // Counters: always on, lock-free.
  std::atomic<uint64_t> isend_count{0}, irecv_count{0};
  std::atomic<uint64_t> isend_bytes{0}, irecv_bytes{0};
  std::atomic<uint64_t> isend_hist[kHistBuckets] = {};
  std::atomic<uint64_t> irecv_hist[kHistBuckets] = {};
  std::atomic<uint64_t> inflight{0};
  std::atomic<uint64_t> failed{0};
  std::atomic<uint64_t> stream_tx[kMaxStreamStats] = {};
  std::atomic<uint64_t> stream_rx[kMaxStreamStats] = {};
  std::atomic<uint64_t> faults_injected[kFaultActionSlots] = {};
  std::atomic<uint64_t> stream_failovers{0};
  std::atomic<uint64_t> crc_errors{0};
  uint64_t start_us = NowUs();
  int64_t rank = RankFromEnv();

  // Span tracking (tracing only).
  std::mutex span_mu;
  std::unordered_map<SpanKey, Span, SpanKeyHash> open_spans;
  std::vector<Span> done_spans;
  std::string trace_path;
  bool trace_header_written = false;

  // Threads do not survive fork(): a mismatch in the child means the pusher
  // pthread never existed here and push_mu/span_mu may have been captured
  // mid-lock at fork — skip the whole shutdown handshake there.
  const uint64_t created_fork_gen = ForkGeneration();

  // Push thread.
  std::thread pusher;
  std::mutex push_mu;
  std::condition_variable push_cv;
  bool stopping = false;
};

Telemetry& Telemetry::Get() {
  static Telemetry* t = new Telemetry();  // leaked on purpose: engines may
  return *t;                              // report during static teardown
}

namespace {
// The leaked singleton's destructor never runs, so final trace flush and
// pusher shutdown are driven by atexit instead (registered only when some
// telemetry sink is enabled).
void TelemetryAtExit() { Telemetry::Get().ShutdownForExit(); }
}  // namespace

Telemetry::Telemetry() : impl_(new Impl()) {
  std::string trace_dir = GetEnv("TPUNET_TRACE_DIR", GetEnv("BAGUA_NET_JAEGER_ADDRESS", ""));
  if (!trace_dir.empty() && RankGate()) {
    // The BAGUA_NET_JAEGER_ADDRESS fallback accepts the reference's env name
    // but writes local Chrome-trace JSON — there is no Jaeger agent here.
    impl_->trace_path =
        trace_dir + "/tpunet-trace-rank" + std::to_string(impl_->rank) + ".json";
    trace_enabled_ = true;
  }

  std::string addr = GetEnv("TPUNET_METRICS_ADDR", GetEnv("TPUNET_PROMETHEUS_ADDRESS",
                            GetEnv("BAGUA_NET_PROMETHEUS_ADDRESS", "")));
  if (trace_enabled_ || (!addr.empty() && RankGate())) {
    std::atexit(TelemetryAtExit);
  }
  if (!addr.empty() && RankGate()) {
    uint64_t interval_ms = GetEnvU64("TPUNET_METRICS_INTERVAL_MS", 1000);
    if (interval_ms == 0) interval_ms = 1000;
    impl_->pusher = std::thread([this, addr, interval_ms] {
      UserPassAddr upa;
      if (!ParseUserPassAndAddr(addr, &upa)) return;
      auto colon = upa.addr.rfind(':');
      if (colon == std::string::npos) return;
      std::string host = upa.addr.substr(0, colon);
      std::string port = upa.addr.substr(colon + 1);
      std::string auth =
          upa.user.empty() ? "" : "Authorization: Basic " + Base64(upa.user + ":" + upa.pass) + "\r\n";
      std::string path = "/metrics/job/tpunet/rank/" + std::to_string(impl_->rank);
      while (true) {
        {
          std::unique_lock<std::mutex> lk(impl_->push_mu);
          impl_->push_cv.wait_for(lk, std::chrono::milliseconds(interval_ms),
                                  [&] { return impl_->stopping; });
          if (impl_->stopping) return;
        }
        std::string body = PrometheusText();
        std::string req = "PUT " + path + " HTTP/1.1\r\nHost: " + host +
                          "\r\nContent-Type: text/plain\r\n" + auth +
                          "Content-Length: " + std::to_string(body.size()) +
                          "\r\nConnection: close\r\n\r\n" + body;
        struct addrinfo hints = {};
        hints.ai_socktype = SOCK_STREAM;
        struct addrinfo* res = nullptr;
        if (getaddrinfo(host.c_str(), port.c_str(), &hints, &res) != 0 || !res) continue;
        int fd = ::socket(res->ai_family, SOCK_STREAM, 0);
        if (fd >= 0) {
          if (::connect(fd, res->ai_addr, res->ai_addrlen) == 0) {
            (void)!::send(fd, req.data(), req.size(), MSG_NOSIGNAL);
            char drain[256];
            (void)!::recv(fd, drain, sizeof(drain), MSG_DONTWAIT);
          }
          ::close(fd);
        }
        freeaddrinfo(res);
      }
    });
  }
}

Telemetry::~Telemetry() { ShutdownForExit(); }

void Telemetry::ShutdownForExit() {
  // Forked child (atexit hooks registered pre-fork still run at its exit()):
  // the pusher pthread never existed here and the mutexes below may have been
  // captured locked at fork — skip the shutdown handshake entirely; the
  // parent owns the final flush.
  if (ForkGeneration() != impl_->created_fork_gen) return;
  if (impl_->pusher.joinable()) {
    {
      std::lock_guard<std::mutex> lk(impl_->push_mu);
      impl_->stopping = true;
    }
    impl_->push_cv.notify_all();
    impl_->pusher.join();
  }
  FlushTrace();
}

void Telemetry::OnRequestStart(uint64_t owner, bool is_send, uint64_t comm, uint64_t req,
                               uint64_t nbytes) {
  Impl* im = impl_.get();
  if (is_send) {
    im->isend_count.fetch_add(1, std::memory_order_relaxed);
    im->isend_bytes.fetch_add(nbytes, std::memory_order_relaxed);
    im->isend_hist[HistBucket(nbytes)].fetch_add(1, std::memory_order_relaxed);
  } else {
    im->irecv_count.fetch_add(1, std::memory_order_relaxed);
    im->irecv_bytes.fetch_add(nbytes, std::memory_order_relaxed);
    im->irecv_hist[HistBucket(nbytes)].fetch_add(1, std::memory_order_relaxed);
  }
  im->inflight.fetch_add(1, std::memory_order_relaxed);
  if (trace_enabled_) {
    std::lock_guard<std::mutex> lk(im->span_mu);
    im->open_spans[SpanKey{owner, req}] = Span{is_send, comm, req, nbytes, NowUs(), 0};
  }
}

void Telemetry::OnRequestDone(uint64_t owner, uint64_t req, bool failed) {
  Impl* im = impl_.get();
  // Clamp-to-zero guard: a done for an unseen request must not wrap the gauge.
  uint64_t cur = im->inflight.load(std::memory_order_relaxed);
  while (cur > 0 &&
         !im->inflight.compare_exchange_weak(cur, cur - 1, std::memory_order_relaxed)) {
  }
  if (failed) im->failed.fetch_add(1, std::memory_order_relaxed);
  if (!trace_enabled_) return;
  bool flush = false;
  {
    std::lock_guard<std::mutex> lk(im->span_mu);
    auto it = im->open_spans.find(SpanKey{owner, req});
    if (it == im->open_spans.end()) return;
    Span s = it->second;
    im->open_spans.erase(it);
    s.dur_us = NowUs() - s.start_us;
    im->done_spans.push_back(s);
    flush = im->done_spans.size() >= 4096;
  }
  if (flush) FlushTrace();
}

void Telemetry::OnStreamBytes(bool is_send, uint64_t stream_idx, uint64_t nbytes) {
  if (stream_idx >= kMaxStreamStats) stream_idx = kMaxStreamStats - 1;
  auto& slot = is_send ? impl_->stream_tx[stream_idx] : impl_->stream_rx[stream_idx];
  slot.fetch_add(nbytes, std::memory_order_relaxed);
}

void Telemetry::OnFaultInjected(int action) {
  if (action < 0 || action >= kFaultActionSlots) return;
  impl_->faults_injected[action].fetch_add(1, std::memory_order_relaxed);
}

void Telemetry::OnStreamFailover() {
  impl_->stream_failovers.fetch_add(1, std::memory_order_relaxed);
}

void Telemetry::OnCrcError() {
  impl_->crc_errors.fetch_add(1, std::memory_order_relaxed);
}

MetricsSnapshot Telemetry::Snapshot() const {
  const Impl* im = impl_.get();
  MetricsSnapshot s;
  for (int i = 0; i < kMaxStreamStats; ++i) {
    s.stream_tx_bytes[i] = im->stream_tx[i].load(std::memory_order_relaxed);
    s.stream_rx_bytes[i] = im->stream_rx[i].load(std::memory_order_relaxed);
  }
  s.isend_count = im->isend_count.load(std::memory_order_relaxed);
  s.irecv_count = im->irecv_count.load(std::memory_order_relaxed);
  s.isend_bytes = im->isend_bytes.load(std::memory_order_relaxed);
  s.irecv_bytes = im->irecv_bytes.load(std::memory_order_relaxed);
  for (int i = 0; i < kHistBuckets; ++i) {
    s.isend_hist[i] = im->isend_hist[i].load(std::memory_order_relaxed);
    s.irecv_hist[i] = im->irecv_hist[i].load(std::memory_order_relaxed);
  }
  s.inflight = im->inflight.load(std::memory_order_relaxed);
  s.failed_requests = im->failed.load(std::memory_order_relaxed);
  for (int i = 0; i < kFaultActionSlots; ++i) {
    s.faults_injected[i] = im->faults_injected[i].load(std::memory_order_relaxed);
  }
  s.stream_failovers = im->stream_failovers.load(std::memory_order_relaxed);
  s.crc_errors = im->crc_errors.load(std::memory_order_relaxed);
  s.uptime_s = (NowUs() - im->start_us) / 1e6;
  return s;
}

std::string Telemetry::PrometheusText() const {
  MetricsSnapshot s = Snapshot();
  char buf[2048];
  std::string out;
  auto emit = [&](const char* fmt, auto... args) {
    snprintf(buf, sizeof(buf), fmt, args...);
    out += buf;
  };
  int64_t rank = impl_->rank;
  // Instrument names follow the reference (isend_nbytes / irecv_nbytes value
  // recorders nthread:172-180, bytes/s observers :343-348, hold_on_request
  // in-flight gauge tokio:184-190).
  emit("# TYPE tpunet_isend_nbytes histogram\n");
  uint64_t cum = 0;
  for (int i = 0; i < kHistBuckets - 1; ++i) {
    cum += s.isend_hist[i];
    emit("tpunet_isend_nbytes_bucket{rank=\"%lld\",le=\"%llu\"} %llu\n", (long long)rank,
         (unsigned long long)kHistBounds[i], (unsigned long long)cum);
  }
  cum += s.isend_hist[kHistBuckets - 1];
  emit("tpunet_isend_nbytes_bucket{rank=\"%lld\",le=\"+Inf\"} %llu\n", (long long)rank,
       (unsigned long long)cum);
  emit("tpunet_isend_nbytes_sum{rank=\"%lld\"} %llu\n", (long long)rank,
       (unsigned long long)s.isend_bytes);
  emit("tpunet_isend_nbytes_count{rank=\"%lld\"} %llu\n", (long long)rank,
       (unsigned long long)s.isend_count);
  emit("# TYPE tpunet_irecv_nbytes histogram\n");
  cum = 0;
  for (int i = 0; i < kHistBuckets - 1; ++i) {
    cum += s.irecv_hist[i];
    emit("tpunet_irecv_nbytes_bucket{rank=\"%lld\",le=\"%llu\"} %llu\n", (long long)rank,
         (unsigned long long)kHistBounds[i], (unsigned long long)cum);
  }
  cum += s.irecv_hist[kHistBuckets - 1];
  emit("tpunet_irecv_nbytes_bucket{rank=\"%lld\",le=\"+Inf\"} %llu\n", (long long)rank,
       (unsigned long long)cum);
  emit("tpunet_irecv_nbytes_sum{rank=\"%lld\"} %llu\n", (long long)rank,
       (unsigned long long)s.irecv_bytes);
  emit("tpunet_irecv_nbytes_count{rank=\"%lld\"} %llu\n", (long long)rank,
       (unsigned long long)s.irecv_count);
  emit("# TYPE tpunet_isend_nbytes_per_second gauge\n");
  emit("tpunet_isend_nbytes_per_second{rank=\"%lld\"} %.1f\n", (long long)rank,
       s.uptime_s > 0 ? s.isend_bytes / s.uptime_s : 0.0);
  emit("# TYPE tpunet_irecv_nbytes_per_second gauge\n");
  emit("tpunet_irecv_nbytes_per_second{rank=\"%lld\"} %.1f\n", (long long)rank,
       s.uptime_s > 0 ? s.irecv_bytes / s.uptime_s : 0.0);
  emit("# TYPE tpunet_stream_tx_bytes counter\n");
  for (int i = 0; i < kMaxStreamStats; ++i) {
    if (s.stream_tx_bytes[i] == 0) continue;
    emit("tpunet_stream_tx_bytes{rank=\"%lld\",stream=\"%d\"} %llu\n", (long long)rank, i,
         (unsigned long long)s.stream_tx_bytes[i]);
  }
  emit("# TYPE tpunet_stream_rx_bytes counter\n");
  for (int i = 0; i < kMaxStreamStats; ++i) {
    if (s.stream_rx_bytes[i] == 0) continue;
    emit("tpunet_stream_rx_bytes{rank=\"%lld\",stream=\"%d\"} %llu\n", (long long)rank, i,
         (unsigned long long)s.stream_rx_bytes[i]);
  }
  emit("# TYPE tpunet_hold_on_request gauge\n");
  emit("tpunet_hold_on_request{rank=\"%lld\"} %llu\n", (long long)rank,
       (unsigned long long)s.inflight);
  emit("# TYPE tpunet_failed_requests counter\n");
  emit("tpunet_failed_requests{rank=\"%lld\"} %llu\n", (long long)rank,
       (unsigned long long)s.failed_requests);
  // Failure-containment counters. faults_injected is labeled by action and
  // emitted only for nonzero slots; the unlabeled totals are always present
  // so dashboards (and the Python parser, which must accept label-less
  // lines) see them even at zero.
  emit("# TYPE tpunet_faults_injected_total counter\n");
  static const char* kActionNames[kFaultActionSlots] = {"none", "close", "stall",
                                                        "corrupt", "delay"};
  uint64_t faults_total = 0;
  for (int i = 1; i < kFaultActionSlots; ++i) {
    faults_total += s.faults_injected[i];
    if (s.faults_injected[i] == 0) continue;
    emit("tpunet_faults_injected_total{rank=\"%lld\",action=\"%s\"} %llu\n", (long long)rank,
         kActionNames[i], (unsigned long long)s.faults_injected[i]);
  }
  emit("tpunet_faults_injected %llu\n", (unsigned long long)faults_total);
  emit("# TYPE tpunet_stream_failovers_total counter\n");
  emit("tpunet_stream_failovers_total{rank=\"%lld\"} %llu\n", (long long)rank,
       (unsigned long long)s.stream_failovers);
  emit("# TYPE tpunet_crc_errors_total counter\n");
  emit("tpunet_crc_errors_total{rank=\"%lld\"} %llu\n", (long long)rank,
       (unsigned long long)s.crc_errors);
  return out;
}

bool Telemetry::FlushTrace() {
  if (!trace_enabled_) return true;
  Impl* im = impl_.get();
  std::vector<Span> spans;
  {
    std::lock_guard<std::mutex> lk(im->span_mu);
    spans.swap(im->done_spans);
  }
  if (spans.empty() && im->trace_header_written) return true;
  std::lock_guard<std::mutex> lk(im->span_mu);  // serialize file writes
  FILE* f = fopen(im->trace_path.c_str(), im->trace_header_written ? "a" : "w");
  if (!f) return false;  // spans dropped; caller surfaces the failure
  if (!im->trace_header_written) {
    // Chrome trace format; Perfetto tolerates a missing closing bracket, so
    // appends stay valid.
    fprintf(f, "[\n");
    fprintf(f,
            "{\"name\":\"tpunet-rank%lld\",\"ph\":\"M\",\"pid\":%lld,"
            "\"args\":{\"kind\":\"process_name\"}},\n",
            (long long)im->rank, (long long)im->rank);
    im->trace_header_written = true;
  }
  for (const Span& s : spans) {
    // Span naming per the reference: "isend-{comm}" / "irecv-{comm}" with id
    // and nbytes attributes (nthread:529-538).
    fprintf(f,
            "{\"name\":\"%s-%llu\",\"ph\":\"X\",\"pid\":%lld,\"tid\":%llu,"
            "\"ts\":%llu,\"dur\":%llu,\"args\":{\"id\":%llu,\"nbytes\":%llu}},\n",
            s.is_send ? "isend" : "irecv", (unsigned long long)s.comm, (long long)im->rank,
            (unsigned long long)s.comm, (unsigned long long)s.start_us,
            (unsigned long long)s.dur_us, (unsigned long long)s.req,
            (unsigned long long)s.nbytes);
  }
  fclose(f);
  return true;
}

// ---------------------------------------------------------------------------

namespace {

class TelemetryNet : public Net {
 public:
  explicit TelemetryNet(std::unique_ptr<Net> inner) : inner_(std::move(inner)) {}

  int32_t devices() override { return inner_->devices(); }
  Status get_properties(int32_t dev, NetProperties* p) override {
    return inner_->get_properties(dev, p);
  }
  Status listen(int32_t dev, SocketHandle* h, uint64_t* lc) override {
    return inner_->listen(dev, h, lc);
  }
  Status connect(int32_t dev, const SocketHandle& h, uint64_t* sc) override {
    return inner_->connect(dev, h, sc);
  }
  Status accept(uint64_t lc, uint64_t* rc) override { return inner_->accept(lc, rc); }

  Status isend(uint64_t comm, const void* data, size_t n, uint64_t* req) override {
    Status s = inner_->isend(comm, data, n, req);
    if (s.ok()) Telemetry::Get().OnRequestStart(Owner(), true, comm, *req, n);
    return s;
  }
  Status irecv(uint64_t comm, void* data, size_t n, uint64_t* req) override {
    Status s = inner_->irecv(comm, data, n, req);
    if (s.ok()) Telemetry::Get().OnRequestStart(Owner(), false, comm, *req, n);
    return s;
  }
  Status test(uint64_t req, bool* done, size_t* nbytes) override {
    Status s = inner_->test(req, done, nbytes);
    if (!s.ok()) {
      // Invalid = unknown/stale id (double-poll, garbage): the request was
      // never tracked here, so neither the failure counter nor the in-flight
      // gauge may move. Real transport errors DO consume the request id.
      if (s.kind != ErrorKind::kInvalidArgument) {
        Telemetry::Get().OnRequestDone(Owner(), req, /*failed=*/true);
      }
    } else if (*done) {
      Telemetry::Get().OnRequestDone(Owner(), req, /*failed=*/false);
    }
    return s;
  }

  Status wait(uint64_t req, size_t* nbytes) override {
    Status s = inner_->wait(req, nbytes);
    if (!s.ok()) {
      if (s.kind != ErrorKind::kInvalidArgument) {
        Telemetry::Get().OnRequestDone(Owner(), req, /*failed=*/true);
      }
    } else {
      Telemetry::Get().OnRequestDone(Owner(), req, /*failed=*/false);
    }
    return s;
  }

  Status close_send(uint64_t c) override { return inner_->close_send(c); }
  Status close_recv(uint64_t c) override { return inner_->close_recv(c); }
  Status close_listen(uint64_t c) override { return inner_->close_listen(c); }

 private:
  uint64_t Owner() const { return reinterpret_cast<uint64_t>(this); }

  std::unique_ptr<Net> inner_;
};

}  // namespace

std::unique_ptr<Net> WrapWithTelemetry(std::unique_ptr<Net> inner) {
  return std::make_unique<TelemetryNet>(std::move(inner));
}

}  // namespace tpunet
