// tpunet telemetry implementation. See include/tpunet/telemetry.h.
#include "tpunet/telemetry.h"

#include <errno.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <stddef.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cstdio>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

#include "dispatch.h"
#include "flightrec.h"
#include "tpunet/mutex.h"
#include "tpunet/utils.h"

namespace tpunet {
namespace {

uint64_t NowUs() { return MonotonicUs(); }

int HistBucket(uint64_t nbytes) {
  for (int i = 0; i < kHistBuckets - 1; ++i) {
    if (nbytes <= kHistBounds[i]) return i;
  }
  return kHistBuckets - 1;
}

int StageBucket(uint64_t us) {
  for (int i = 0; i < kStageHistBuckets - 1; ++i) {
    if (us <= kStageHistBounds[i]) return i;
  }
  return kStageHistBuckets - 1;
}

int64_t RankFromEnv() {
  return static_cast<int64_t>(GetEnvU64("TPUNET_RANK", GetEnvU64("RANK", 0)));
}

// Reference gating: telemetry only for ranks 0-7 with the address var set
// (nthread:108-130).
bool RankGate() {
  int64_t r = RankFromEnv();
  return r >= 0 && r <= 7;
}

std::string Base64(const std::string& in) {
  static const char* tbl = "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/";
  std::string out;
  size_t i = 0;
  while (i + 2 < in.size()) {
    uint32_t v = (uint8_t(in[i]) << 16) | (uint8_t(in[i + 1]) << 8) | uint8_t(in[i + 2]);
    out += tbl[(v >> 18) & 63];
    out += tbl[(v >> 12) & 63];
    out += tbl[(v >> 6) & 63];
    out += tbl[v & 63];
    i += 3;
  }
  if (i + 1 == in.size()) {
    uint32_t v = uint8_t(in[i]) << 16;
    out += tbl[(v >> 18) & 63];
    out += tbl[(v >> 12) & 63];
    out += "==";
  } else if (i + 2 == in.size()) {
    uint32_t v = (uint8_t(in[i]) << 16) | (uint8_t(in[i + 1]) << 8);
    out += tbl[(v >> 18) & 63];
    out += tbl[(v >> 12) & 63];
    out += tbl[(v >> 6) & 63];
    out += "=";
  }
  return out;
}

// Linux UAPI struct tcp_info layout through tcpi_delivery_rate (the glibc
// copy in <netinet/tcp.h> predates the delivery-rate fields on many
// distros). getsockopt fills min(optlen, kernel size) and reports the filled
// length, so reads past what the running kernel provides are guarded by the
// returned length.
struct TcpInfoCompat {
  uint8_t state, ca_state, retransmits, probes, backoff, options, wscale, flags;
  uint32_t rto, ato, snd_mss, rcv_mss;
  uint32_t unacked, sacked, lost, retrans, fackets;
  uint32_t last_data_sent, last_ack_sent, last_data_recv, last_ack_recv;
  uint32_t pmtu, rcv_ssthresh, rtt, rttvar, snd_ssthresh, snd_cwnd, advmss, reordering;
  uint32_t rcv_rtt, rcv_space;
  uint32_t total_retrans;
  uint64_t pacing_rate, max_pacing_rate, bytes_acked, bytes_received;
  uint32_t segs_out, segs_in;
  uint32_t notsent_bytes, min_rtt, data_segs_in, data_segs_out;
  uint64_t delivery_rate;  // bytes/sec
};

struct Span {
  enum class Kind : uint8_t { kReq, kColl, kInstant };
  Kind kind = Kind::kReq;
  bool is_send = false;
  uint64_t comm = 0;    // kReq: comm id | kColl: comm_id | kInstant: stream idx
  uint64_t req = 0;     // kReq: request id | kColl: coll_seq | kInstant: srtt
  uint64_t nbytes = 0;  // kReq/kColl: bytes | kInstant: median srtt
  uint64_t start_us = 0;
  uint64_t dur_us = 0;
  std::string name;     // kColl: phase | kInstant: event name
};

// Request ids are engine-local (each instance counts from 1), so open spans
// are keyed by (owner instance tag, request id).
using SpanKey = std::pair<uint64_t, uint64_t>;
struct SpanKeyHash {
  size_t operator()(const SpanKey& k) const {
    return std::hash<uint64_t>()(k.first * 0x9e3779b97f4a7c15ull ^ k.second);
  }
};

// Per-stream-slot TCP introspection state: the rate limiter plus the last
// sample's gauges, all relaxed atomics (last writer wins is fine for gauges).
struct StreamTcpState {
  std::atomic<uint64_t> next_sample_us{0};
  std::atomic<uint64_t> rtt_us{0};
  std::atomic<uint64_t> srtt_us{0};
  std::atomic<uint64_t> retrans_total{0};
  std::atomic<uint64_t> cwnd{0};
  std::atomic<uint64_t> delivery_rate_bps{0};
  std::atomic<uint64_t> min_rtt_us{0};  // tcpi_min_rtt (per-path RTT floor)
  std::atomic<uint8_t> sampled{0};
  std::atomic<uint8_t> straggling{0};  // hysteresis: count rising edges only
};

struct StageHistAtomic {
  std::atomic<uint64_t> buckets[kStageHistBuckets] = {};
  std::atomic<uint64_t> sum_us{0};
  std::atomic<uint64_t> count{0};

  void Observe(uint64_t us) {
    buckets[StageBucket(us)].fetch_add(1, std::memory_order_relaxed);
    sum_us.fetch_add(us, std::memory_order_relaxed);
    count.fetch_add(1, std::memory_order_relaxed);
  }
  void SnapshotInto(StageHist* out) const {
    for (int i = 0; i < kStageHistBuckets; ++i) {
      out->buckets[i] = buckets[i].load(std::memory_order_relaxed);
    }
    out->sum_us = sum_us.load(std::memory_order_relaxed);
    out->count = count.load(std::memory_order_relaxed);
  }
  void Reset() {
    for (auto& b : buckets) b.store(0, std::memory_order_relaxed);
    sum_us.store(0, std::memory_order_relaxed);
    count.store(0, std::memory_order_relaxed);
  }
};

double BitsToDouble(uint64_t bits) {
  double d;
  memcpy(&d, &bits, sizeof(d));
  return d;
}
uint64_t DoubleToBits(double d) {
  uint64_t bits;
  memcpy(&bits, &d, sizeof(bits));
  return bits;
}

// Jain's fairness index (sum x)^2 / (n * sum x^2) over the nonzero entries;
// 1.0 when nothing moved (vacuously fair).
double JainIndex(const uint64_t* deltas, int n) {
  double sum = 0, sumsq = 0;
  int active = 0;
  for (int i = 0; i < n; ++i) {
    if (deltas[i] == 0) continue;
    double x = static_cast<double>(deltas[i]);
    sum += x;
    sumsq += x * x;
    ++active;
  }
  if (active == 0 || sumsq == 0) return 1.0;
  return (sum * sum) / (active * sumsq);
}

}  // namespace

struct Telemetry::Impl {
  // Counters: always on, lock-free.
  std::atomic<uint64_t> isend_count{0}, irecv_count{0};
  std::atomic<uint64_t> isend_bytes{0}, irecv_bytes{0};
  std::atomic<uint64_t> isend_hist[kHistBuckets] = {};
  std::atomic<uint64_t> irecv_hist[kHistBuckets] = {};
  std::atomic<uint64_t> inflight{0};
  std::atomic<uint64_t> failed{0};
  // Per-(class, stream) byte cells: tpunet_stream_{tx,rx}_bytes sums the
  // class axis, tpunet_qos_bytes_total sums the stream axis, and the
  // class-split Jain windows read the cells directly — one write site
  // feeds all three views.
  std::atomic<uint64_t> stream_tx[kQosClassCount][kMaxStreamStats] = {};
  std::atomic<uint64_t> stream_rx[kQosClassCount][kMaxStreamStats] = {};
  // QoS scheduler accounting: per-class wire-credit queue-wait histograms
  // and the out-of-arrival-order grant (preemption) counters.
  StageHistAtomic qos_wait[kQosClassCount];
  std::atomic<uint64_t> qos_preempts[kQosClassCount] = {};
  std::atomic<uint64_t> faults_injected[kFaultActionSlots] = {};
  std::atomic<uint64_t> stream_failovers{0};
  std::atomic<uint64_t> crc_errors{0};
  std::atomic<uint64_t> start_us{NowUs()};
  int64_t rank = RankFromEnv();

  // Stage-latency histograms (always on; fed by the engines at request
  // consumption).
  StageHistAtomic req_queue, req_wire, req_total;

  // Serving-tier SLO accounting: TTFT/TPOT histograms fed through
  // tpunet_c_serve_observe by the router/decode workers, plus per-tier
  // queue-depth gauges (last writer wins — instantaneous depths).
  StageHistAtomic req_ttft, req_tpot;
  std::atomic<uint64_t> serve_depth[kServeTierCount] = {};

  // Elastic-churn accounting: per-phase rewire duration histograms, churn
  // events by kind, and the last-reported live world size (gauge).
  StageHistAtomic rewire_phase[kRewirePhaseCount];
  std::atomic<uint64_t> churn_events[kChurnKindCount] = {};
  std::atomic<uint64_t> world_size{0};

  // Live weight-update accounting: per-phase swap duration histograms,
  // swap events by kind, and the serving checkpoint version (gauge).
  StageHistAtomic swap_phase[kSwapPhaseCount];
  std::atomic<uint64_t> swap_events[kSwapKindCount] = {};
  std::atomic<uint64_t> weight_version{0};

  // TCP introspection (always on unless TPUNET_TCPINFO_INTERVAL_MS=0).
  uint64_t tcp_interval_us =
      GetEnvU64("TPUNET_TCPINFO_INTERVAL_MS", 100) * 1000;
  uint64_t straggler_factor = GetEnvU64("TPUNET_STRAGGLER_FACTOR", 3);
  // RTT floor below which nothing counts as a straggler — loopback and
  // intra-rack RTTs jitter by whole multiples without meaning anything.
  uint64_t straggler_min_rtt_us = GetEnvU64("TPUNET_STRAGGLER_MIN_RTT_US", 1000);
  StreamTcpState tcp_tx[kMaxStreamStats];
  StreamTcpState tcp_rx[kMaxStreamStats];
  std::atomic<uint64_t> straggler_events{0};

  // Lane-striping state (docs/DESIGN.md "Lanes & adaptive striping"): the
  // stripe scheduler's current per-lane weight / measured service rate
  // (last writer wins across comms), per-lane payload bytes, and published
  // weight-vector epochs. lane_weight 0 = "no lane-mode comm ever reported
  // this slot" (lane weights themselves have floor 1), which is the emit
  // gate for the gauge families.
  std::atomic<uint64_t> lane_weight[kMaxStreamStats] = {};
  std::atomic<uint64_t> lane_rate_bps[kMaxStreamStats] = {};
  std::atomic<uint64_t> lane_bytes[kMaxStreamStats][2] = {};
  std::atomic<uint64_t> restripe_events{0};

  // Intra-host SHM transport: ring payload bytes per direction + futex
  // wake syscalls (shm_engine.cc; docs/DESIGN.md "Intra-host shared
  // memory").
  std::atomic<uint64_t> shm_bytes[2] = {};
  std::atomic<uint64_t> shm_wakeups{0};

  // Fairness window (win_mu): Jain's index over per-stream byte deltas
  // between rolls. Rolled lazily from Snapshot() at most once per
  // TPUNET_FAIRNESS_WINDOW_MS; the first roll covers everything since
  // start/Reset (deterministic for tests). win_mu is a leaf lock.
  Mutex win_mu;
  bool win_init GUARDED_BY(win_mu) = false;
  uint64_t win_last_us GUARDED_BY(win_mu) = 0;
  uint64_t fairness_window_us = GetEnvU64("TPUNET_FAIRNESS_WINDOW_MS", 1000) * 1000;
  uint64_t win_tx[kQosClassCount][kMaxStreamStats] GUARDED_BY(win_mu) = {};
  uint64_t win_rx[kQosClassCount][kMaxStreamStats] GUARDED_BY(win_mu) = {};
  std::atomic<uint64_t> fair_tx_bits[kQosClassCount] = {
      DoubleToBits(1.0), DoubleToBits(1.0), DoubleToBits(1.0)};
  std::atomic<uint64_t> fair_rx_bits[kQosClassCount] = {
      DoubleToBits(1.0), DoubleToBits(1.0), DoubleToBits(1.0)};

  // Span tracking (tracing only). span_mu also serializes trace-file writes
  // (FlushTrace) and the trace target swap (SetTraceDir); leaf lock.
  Mutex span_mu;
  std::unordered_map<SpanKey, Span, SpanKeyHash> open_spans GUARDED_BY(span_mu);
  std::vector<Span> done_spans GUARDED_BY(span_mu);
  std::string trace_path GUARDED_BY(span_mu);
  bool trace_header_written GUARDED_BY(span_mu) = false;

  // Threads do not survive fork(): a mismatch in the child means the pusher
  // pthread never existed here and push_mu/span_mu may have been captured
  // mid-lock at fork — skip the whole shutdown handshake there.
  const uint64_t created_fork_gen = ForkGeneration();

  // Push thread.
  std::thread pusher;
  Mutex push_mu;  // leaf: guards only the stop flag
  CondVar push_cv;
  bool stopping GUARDED_BY(push_mu) = false;

  // Counter-timeseries sampler (TPUNET_TS_INTERVAL_MS > 0): appends one full
  // metric snapshot as a JSONL line per interval to
  // tpunet-ts-rank<R>.jsonl — the measurement history benchmarks/sentry.py
  // and offline regression triage replay. Shares push_mu/push_cv/stopping
  // with the pusher for shutdown.
  std::thread ts_sampler;

  // On-demand /metrics scrape listener (TPUNET_METRICS_PORT). The socket is
  // bound SYNCHRONOUSLY in the constructor so the chosen port (ephemeral
  // when the var is set to 0) is readable the moment the singleton exists.
  std::thread scraper;
  std::atomic<bool> scrape_stop{false};
  std::atomic<int> scrape_bound_port{0};
};

Telemetry& Telemetry::Get() {
  static Telemetry* t = new Telemetry();  // leaked on purpose: engines may
  return *t;                              // report during static teardown
}

namespace {
// The leaked singleton's destructor never runs, so final trace flush and
// pusher/scraper shutdown are driven by atexit instead (registered once,
// when any telemetry sink is enabled).
void TelemetryAtExit() { Telemetry::Get().ShutdownForExit(); }
std::once_flag g_atexit_once;
void RegisterAtExit() {
  std::call_once(g_atexit_once, [] { std::atexit(TelemetryAtExit); });
}
}  // namespace

Telemetry::Telemetry() : impl_(new Impl()) {
  std::string trace_dir = GetEnv("TPUNET_TRACE_DIR", GetEnv("BAGUA_NET_JAEGER_ADDRESS", ""));
  if (!trace_dir.empty() && RankGate()) {
    // The BAGUA_NET_JAEGER_ADDRESS fallback accepts the reference's env name
    // but writes local Chrome-trace JSON — there is no Jaeger agent here.
    impl_->trace_path =
        trace_dir + "/tpunet-trace-rank" + std::to_string(impl_->rank) + ".json";
    trace_enabled_.store(true, std::memory_order_relaxed);
    RegisterAtExit();
  }

  std::string addr = GetEnv("TPUNET_METRICS_ADDR", GetEnv("TPUNET_PROMETHEUS_ADDRESS",
                            GetEnv("BAGUA_NET_PROMETHEUS_ADDRESS", "")));
  if (!addr.empty() && RankGate()) {
    RegisterAtExit();
    uint64_t interval_ms = GetEnvU64("TPUNET_METRICS_INTERVAL_MS", 1000);
    if (interval_ms == 0) interval_ms = 1000;
    impl_->pusher = std::thread([this, addr, interval_ms] {
      UserPassAddr upa;
      if (!ParseUserPassAndAddr(addr, &upa)) return;
      auto colon = upa.addr.rfind(':');
      if (colon == std::string::npos) return;
      std::string host = upa.addr.substr(0, colon);
      std::string port = upa.addr.substr(colon + 1);
      std::string auth =
          upa.user.empty() ? "" : "Authorization: Basic " + Base64(upa.user + ":" + upa.pass) + "\r\n";
      std::string path = "/metrics/job/tpunet/rank/" + std::to_string(impl_->rank);
      while (true) {
        {
          // A spurious wakeup inside the interval just pushes one period
          // early — harmless, so no deadline re-arm loop here.
          MutexLock lk(impl_->push_mu);
          if (!impl_->stopping) {
            impl_->push_cv.WaitFor(impl_->push_mu, static_cast<int>(interval_ms));
          }
          if (impl_->stopping) return;
        }
        std::string body = PrometheusText();
        std::string req = "PUT " + path + " HTTP/1.1\r\nHost: " + host +
                          "\r\nContent-Type: text/plain\r\n" + auth +
                          "Content-Length: " + std::to_string(body.size()) +
                          "\r\nConnection: close\r\n\r\n" + body;
        struct addrinfo hints = {};
        hints.ai_socktype = SOCK_STREAM;
        struct addrinfo* res = nullptr;
        if (getaddrinfo(host.c_str(), port.c_str(), &hints, &res) != 0 || !res) continue;
        int fd = ::socket(res->ai_family, SOCK_STREAM, 0);
        if (fd >= 0) {
          if (::connect(fd, res->ai_addr, res->ai_addrlen) == 0) {
            (void)!::send(fd, req.data(), req.size(), MSG_NOSIGNAL);
            char drain[256];
            (void)!::recv(fd, drain, sizeof(drain), MSG_DONTWAIT);
          }
          ::close(fd);
        }
        freeaddrinfo(res);
      }
    });
  }

  // On-demand Prometheus scrape endpoint: GET http://host:PORT/metrics.
  // Each rank needs its own port; the pusher and the listener are
  // independent — either or both may be on. An UNSET (or empty/garbage)
  // var means no listener; an explicit TPUNET_METRICS_PORT=0 binds an
  // EPHEMERAL port — the disaggregated-serving loopback case, where
  // several tiers on one box each need their own listener without port
  // bookkeeping — readable afterwards via tpunet_c_metrics_port(). The
  // bind happens HERE (synchronously) so the chosen port exists the
  // moment the singleton does.
  std::string scrape_env = GetEnv("TPUNET_METRICS_PORT", "");
  char* scrape_end = nullptr;
  uint64_t scrape_port =
      scrape_env.empty() ? 0 : strtoull(scrape_env.c_str(), &scrape_end, 10);
  bool scrape_numeric = !scrape_env.empty() && scrape_end != nullptr &&
                        *scrape_end == '\0';
  if (scrape_numeric && scrape_port < 65536 && RankGate()) {
    int lfd = ::socket(AF_INET, SOCK_STREAM | SOCK_CLOEXEC, 0);
    if (lfd >= 0) {
      int one = 1;
      ::setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
      sockaddr_in sa = {};
      sa.sin_family = AF_INET;
      sa.sin_port = htons(static_cast<uint16_t>(scrape_port));
      sa.sin_addr.s_addr = htonl(INADDR_ANY);
      sockaddr_in got = {};
      socklen_t got_len = sizeof(got);
      if (::bind(lfd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)) != 0 ||
          ::listen(lfd, 16) != 0 ||
          ::getsockname(lfd, reinterpret_cast<sockaddr*>(&got), &got_len) != 0) {
        fprintf(stderr, "[tpunet] /metrics listener: cannot bind port %llu: %s\n",
                (unsigned long long)scrape_port, strerror(errno));
        ::close(lfd);
        lfd = -1;
      }
      if (lfd >= 0) {
        impl_->scrape_bound_port.store(ntohs(got.sin_port),
                                       std::memory_order_release);
        RegisterAtExit();
        impl_->scraper = std::thread([this, lfd] { ScrapeLoop(lfd); });
      }
    }
  }

  // Counter-timeseries sampler (docs/DESIGN.md §6c): every
  // TPUNET_TS_INTERVAL_MS, append the full Prometheus exposition as one
  // JSONL line ({"t_us":...,"exposition":"..."}) so perf claims have a
  // HISTORY, not just a final scrape. Off by default (0). One final sample
  // is taken at shutdown so runs shorter than one interval still record.
  uint64_t ts_interval_ms = GetEnvU64("TPUNET_TS_INTERVAL_MS", 0);
  if (ts_interval_ms > 0 && RankGate()) {
    RegisterAtExit();
    std::string ts_dir = GetEnv("TPUNET_TRACE_DIR", ".");
    if (ts_dir.empty()) ts_dir = ".";
    std::string ts_path =
        ts_dir + "/tpunet-ts-rank" + std::to_string(impl_->rank) + ".jsonl";
    impl_->ts_sampler = std::thread([this, ts_path, ts_interval_ms] {
      FILE* f = fopen(ts_path.c_str(), "a");
      if (!f) return;
      auto sample = [&] {
        std::string expo = PrometheusText();
        std::string esc;
        esc.reserve(expo.size() + expo.size() / 8);
        for (char ch : expo) {
          if (ch == '"' || ch == '\\') {
            esc += '\\';
            esc += ch;
          } else if (ch == '\n') {
            esc += "\\n";
          } else {
            esc += ch;
          }
        }
        fprintf(f, "{\"t_us\":%llu,\"exposition\":\"%s\"}\n",
                (unsigned long long)NowUs(), esc.c_str());
        fflush(f);
      };
      while (true) {
        {
          MutexLock lk(impl_->push_mu);
          if (!impl_->stopping) {
            impl_->push_cv.WaitFor(impl_->push_mu,
                                   static_cast<int>(ts_interval_ms));
          }
          if (impl_->stopping) break;
        }
        sample();
      }
      sample();
      fclose(f);
    });
  }
}

void Telemetry::ScrapeLoop(int lfd) {
  while (!impl_->scrape_stop.load(std::memory_order_acquire)) {
    struct pollfd pfd = {lfd, POLLIN, 0};
    int pr = ::poll(&pfd, 1, 200);
    if (pr <= 0) continue;
    int cfd = ::accept(lfd, nullptr, nullptr);
    if (cfd < 0) continue;
    // Drain whatever request line arrived. GET /healthz gets a tiny liveness
    // 200 (the serving tier's probe endpoint); every other path gets the
    // exposition — a scraper that sends nothing within the poll window
    // still gets it.
    char reqbuf[1024];
    ssize_t rn = 0;
    struct pollfd cpfd = {cfd, POLLIN, 0};
    if (::poll(&cpfd, 1, 250) > 0) {
      rn = ::recv(cfd, reqbuf, sizeof(reqbuf) - 1, MSG_DONTWAIT);
    }
    if (rn < 0) rn = 0;
    reqbuf[rn] = '\0';
    bool healthz = strncmp(reqbuf, "GET /healthz", 12) == 0;
    std::string body = healthz ? "ok\n" : PrometheusText();
    std::string resp =
        std::string("HTTP/1.1 200 OK\r\nContent-Type: ") +
        (healthz ? "text/plain" : "text/plain; version=0.0.4") +
        "\r\nContent-Length: " + std::to_string(body.size()) +
        "\r\nConnection: close\r\n\r\n" + body;
    (void)!::send(cfd, resp.data(), resp.size(), MSG_NOSIGNAL);
    ::close(cfd);
  }
  ::close(lfd);
}

Telemetry::~Telemetry() { ShutdownForExit(); }

void Telemetry::ShutdownForExit() {
  // Forked child (atexit hooks registered pre-fork still run at its exit()):
  // the pusher/scraper pthreads never existed here and the mutexes below may
  // have been captured locked at fork — skip the shutdown handshake
  // entirely; the parent owns the final flush.
  if (ForkGeneration() != impl_->created_fork_gen) return;
  if (impl_->pusher.joinable() || impl_->ts_sampler.joinable()) {
    {
      MutexLock lk(impl_->push_mu);
      impl_->stopping = true;
    }
    impl_->push_cv.NotifyAll();
    if (impl_->pusher.joinable()) impl_->pusher.join();
    if (impl_->ts_sampler.joinable()) impl_->ts_sampler.join();
  }
  if (impl_->scraper.joinable()) {
    impl_->scrape_stop.store(true, std::memory_order_release);
    impl_->scraper.join();
  }
  FlushTrace();
}

bool Telemetry::SetTraceDir(const std::string& dir) {
  // Flush under the old target first so no buffered span lands in the wrong
  // file (or is lost on disable).
  FlushTrace();
  Impl* im = impl_.get();
  MutexLock lk(im->span_mu);
  if (dir.empty()) {
    trace_enabled_.store(false, std::memory_order_relaxed);
    im->open_spans.clear();
    return true;
  }
  im->trace_path = dir + "/tpunet-trace-rank" + std::to_string(im->rank) + ".json";
  im->trace_header_written = false;
  trace_enabled_.store(true, std::memory_order_relaxed);
  RegisterAtExit();
  return true;
}

void Telemetry::OnRequestStart(uint64_t owner, bool is_send, uint64_t comm, uint64_t req,
                               uint64_t nbytes) {
  Impl* im = impl_.get();
  if (is_send) {
    im->isend_count.fetch_add(1, std::memory_order_relaxed);
    im->isend_bytes.fetch_add(nbytes, std::memory_order_relaxed);
    im->isend_hist[HistBucket(nbytes)].fetch_add(1, std::memory_order_relaxed);
  } else {
    im->irecv_count.fetch_add(1, std::memory_order_relaxed);
    im->irecv_bytes.fetch_add(nbytes, std::memory_order_relaxed);
    im->irecv_hist[HistBucket(nbytes)].fetch_add(1, std::memory_order_relaxed);
  }
  im->inflight.fetch_add(1, std::memory_order_relaxed);
  flightrec::Record(flightrec::Ev::kReqStart, comm, req, nbytes,
                    is_send ? 1u : 0u);
  if (tracing_enabled()) {
    Span s;
    s.kind = Span::Kind::kReq;
    s.is_send = is_send;
    s.comm = comm;
    s.req = req;
    s.nbytes = nbytes;
    s.start_us = NowUs();
    MutexLock lk(im->span_mu);
    im->open_spans[SpanKey{owner, req}] = std::move(s);
  }
}

void Telemetry::OnRequestDone(uint64_t owner, uint64_t req, bool failed) {
  Impl* im = impl_.get();
  // Clamp-to-zero guard: a done for an unseen request must not wrap the gauge.
  uint64_t cur = im->inflight.load(std::memory_order_relaxed);
  while (cur > 0 &&
         !im->inflight.compare_exchange_weak(cur, cur - 1, std::memory_order_relaxed)) {
  }
  if (failed) im->failed.fetch_add(1, std::memory_order_relaxed);
  flightrec::Record(flightrec::Ev::kReqDone, req, 0, 0, failed ? 1u : 0u);
  if (!tracing_enabled()) return;
  bool flush = false;
  {
    MutexLock lk(im->span_mu);
    auto it = im->open_spans.find(SpanKey{owner, req});
    if (it == im->open_spans.end()) return;
    Span s = it->second;
    im->open_spans.erase(it);
    s.dur_us = NowUs() - s.start_us;
    im->done_spans.push_back(std::move(s));
    flush = im->done_spans.size() >= 4096;
  }
  if (flush) FlushTrace();
}

void Telemetry::OnStreamBytes(bool is_send, uint64_t stream_idx, uint64_t nbytes,
                              int cls) {
  if (stream_idx >= kMaxStreamStats) stream_idx = kMaxStreamStats - 1;
  if (cls < 0 || cls >= kQosClassCount) cls = 1;  // unknown class: bulk
  auto& slot = is_send ? impl_->stream_tx[cls][stream_idx]
                       : impl_->stream_rx[cls][stream_idx];
  slot.fetch_add(nbytes, std::memory_order_relaxed);
  flightrec::Record(is_send ? flightrec::Ev::kWireSend : flightrec::Ev::kWireRecv,
                    stream_idx, nbytes, 0, static_cast<uint32_t>(cls));
}

void Telemetry::OnQosQueueWait(int cls, uint64_t wait_us) {
  if (cls < 0 || cls >= kQosClassCount) return;
  impl_->qos_wait[cls].Observe(wait_us);
  flightrec::Record(flightrec::Ev::kQosWait, static_cast<uint64_t>(cls), wait_us);
}

void Telemetry::OnQosPreempt(int cls) {
  if (cls < 0 || cls >= kQosClassCount) return;
  impl_->qos_preempts[cls].fetch_add(1, std::memory_order_relaxed);
  flightrec::Record(flightrec::Ev::kQosPreempt, static_cast<uint64_t>(cls));
}

void Telemetry::MaybeSampleStream(bool is_send, uint64_t stream_idx, int fd) {
  Impl* im = impl_.get();
  if (im->tcp_interval_us == 0 || fd < 0) return;
  if (stream_idx >= kMaxStreamStats) stream_idx = kMaxStreamStats - 1;
  StreamTcpState* slots = is_send ? im->tcp_tx : im->tcp_rx;
  StreamTcpState& slot = slots[stream_idx];
  uint64_t now = NowUs();
  uint64_t due = slot.next_sample_us.load(std::memory_order_relaxed);
  if (now < due) return;
  // One sampler per slot per window: losing the CAS means a sibling thread
  // is already doing this window's getsockopt.
  if (!slot.next_sample_us.compare_exchange_strong(due, now + im->tcp_interval_us,
                                                   std::memory_order_relaxed)) {
    return;
  }
  TcpInfoCompat ti = {};
  socklen_t len = sizeof(ti);
  if (::getsockopt(fd, IPPROTO_TCP, TCP_INFO, &ti, &len) != 0) return;
  if (len < offsetof(TcpInfoCompat, total_retrans) + sizeof(uint32_t)) return;
  uint64_t rtt = ti.rtt;  // µs already
  slot.rtt_us.store(rtt, std::memory_order_relaxed);
  uint64_t old_srtt = slot.srtt_us.load(std::memory_order_relaxed);
  uint64_t srtt = old_srtt == 0 ? rtt : (3 * old_srtt + rtt) / 4;
  slot.srtt_us.store(srtt, std::memory_order_relaxed);
  slot.retrans_total.store(ti.total_retrans, std::memory_order_relaxed);
  slot.cwnd.store(ti.snd_cwnd, std::memory_order_relaxed);
  if (len >= offsetof(TcpInfoCompat, delivery_rate) + sizeof(uint64_t)) {
    slot.delivery_rate_bps.store(ti.delivery_rate * 8, std::memory_order_relaxed);
  }
  if (len >= offsetof(TcpInfoCompat, min_rtt) + sizeof(uint32_t)) {
    slot.min_rtt_us.store(ti.min_rtt, std::memory_order_relaxed);
  }
  slot.sampled.store(1, std::memory_order_relaxed);

  // Straggler check: this stream's smoothed RTT vs the median across the
  // active same-direction streams. Hysteresis (rising edge only) keeps a
  // persistently slow stream from inflating the counter every sample.
  if (srtt < im->straggler_min_rtt_us || im->straggler_factor == 0) {
    slot.straggling.store(0, std::memory_order_relaxed);
    return;
  }
  std::vector<uint64_t> srtts;
  srtts.reserve(kMaxStreamStats);
  for (int i = 0; i < kMaxStreamStats; ++i) {
    if (slots[i].sampled.load(std::memory_order_relaxed)) {
      srtts.push_back(slots[i].srtt_us.load(std::memory_order_relaxed));
    }
  }
  if (srtts.size() < 2) return;
  std::nth_element(srtts.begin(), srtts.begin() + srtts.size() / 2, srtts.end());
  uint64_t median = srtts[srtts.size() / 2];
  if (median > 0 && srtt > im->straggler_factor * median) {
    if (!slot.straggling.exchange(1, std::memory_order_relaxed)) {
      im->straggler_events.fetch_add(1, std::memory_order_relaxed);
      if (tracing_enabled()) {
        Span s;
        s.kind = Span::Kind::kInstant;
        s.is_send = is_send;
        s.comm = stream_idx;
        s.req = srtt;
        s.nbytes = median;
        s.start_us = now;
        s.name = "straggler-stream" + std::to_string(stream_idx);
        MutexLock lk(im->span_mu);
        im->done_spans.push_back(std::move(s));
      }
    }
  } else {
    slot.straggling.store(0, std::memory_order_relaxed);
  }
}

bool Telemetry::StreamStraggling(bool is_send, uint64_t stream_idx) const {
  if (stream_idx >= kMaxStreamStats) stream_idx = kMaxStreamStats - 1;
  const StreamTcpState* slots = is_send ? impl_->tcp_tx : impl_->tcp_rx;
  return slots[stream_idx].straggling.load(std::memory_order_relaxed) != 0;
}

void Telemetry::OnLaneWeight(uint64_t lane, uint64_t weight) {
  if (lane >= kMaxStreamStats) lane = kMaxStreamStats - 1;
  impl_->lane_weight[lane].store(weight, std::memory_order_relaxed);
}

void Telemetry::OnLaneRate(uint64_t lane, uint64_t bps) {
  if (lane >= kMaxStreamStats) lane = kMaxStreamStats - 1;
  impl_->lane_rate_bps[lane].store(bps, std::memory_order_relaxed);
}

void Telemetry::OnLaneBytes(bool is_send, uint64_t lane, uint64_t nbytes) {
  if (lane >= kMaxStreamStats) lane = kMaxStreamStats - 1;
  impl_->lane_bytes[lane][is_send ? 0 : 1].fetch_add(nbytes,
                                                     std::memory_order_relaxed);
}

void Telemetry::OnRestripe() {
  impl_->restripe_events.fetch_add(1, std::memory_order_relaxed);
  flightrec::Record(flightrec::Ev::kRestripe, 0);
}

void Telemetry::OnShmBytes(bool is_send, uint64_t nbytes) {
  impl_->shm_bytes[is_send ? 0 : 1].fetch_add(nbytes, std::memory_order_relaxed);
}

void Telemetry::OnShmWakeup() {
  impl_->shm_wakeups.fetch_add(1, std::memory_order_relaxed);
}

void Telemetry::OnRequestStages(uint64_t post_us, uint64_t first_wire_us,
                                uint64_t last_wire_us) {
  if (post_us == 0) return;  // engine predates stamping / synthetic request
  Impl* im = impl_.get();
  uint64_t done_us = NowUs();
  if (done_us < post_us) return;
  im->req_total.Observe(done_us - post_us);
  if (last_wire_us == 0) return;  // zero-byte message: no wire stage
  if (first_wire_us == 0 || first_wire_us < post_us) first_wire_us = last_wire_us;
  if (first_wire_us < post_us || last_wire_us < first_wire_us) return;
  im->req_queue.Observe(first_wire_us - post_us);
  im->req_wire.Observe(last_wire_us - first_wire_us);
}

void Telemetry::OnCollPhase(uint64_t comm_id, uint64_t coll_seq, const char* phase,
                            uint64_t start_us, uint64_t dur_us, uint64_t nbytes) {
  if (!tracing_enabled()) return;
  Impl* im = impl_.get();
  Span s;
  s.kind = Span::Kind::kColl;
  s.comm = comm_id;
  s.req = coll_seq;
  s.nbytes = nbytes;
  s.start_us = start_us;
  s.dur_us = dur_us;
  s.name = phase;
  bool flush = false;
  {
    MutexLock lk(im->span_mu);
    im->done_spans.push_back(std::move(s));
    flush = im->done_spans.size() >= 4096;
  }
  if (flush) FlushTrace();
}

void Telemetry::OnFaultInjected(int action) {
  if (action < 0 || action >= kFaultActionSlots) return;
  impl_->faults_injected[action].fetch_add(1, std::memory_order_relaxed);
  flightrec::Record(flightrec::Ev::kFault, static_cast<uint64_t>(action));
}

void Telemetry::OnStreamFailover() {
  impl_->stream_failovers.fetch_add(1, std::memory_order_relaxed);
  flightrec::Record(flightrec::Ev::kFailover, 0);
}

void Telemetry::OnCrcError() {
  impl_->crc_errors.fetch_add(1, std::memory_order_relaxed);
  flightrec::Record(flightrec::Ev::kCrcError, 0);
}

void Telemetry::OnServeLatency(int kind, uint64_t us) {
  if (kind == 0) {
    impl_->req_ttft.Observe(us);
  } else if (kind == 1) {
    impl_->req_tpot.Observe(us);
  }
}

void Telemetry::OnServeQueueDepth(int tier, uint64_t depth) {
  if (tier < 0 || tier >= kServeTierCount) return;
  impl_->serve_depth[tier].store(depth, std::memory_order_relaxed);
}

void Telemetry::OnRewirePhase(int phase, uint64_t us) {
  if (phase < 0 || phase >= kRewirePhaseCount) return;
  impl_->rewire_phase[phase].Observe(us);
  flightrec::Record(flightrec::Ev::kRewirePhase, static_cast<uint64_t>(phase), us);
}

void Telemetry::OnChurnEvent(int kind) {
  if (kind < 0 || kind >= kChurnKindCount) return;
  impl_->churn_events[kind].fetch_add(1, std::memory_order_relaxed);
}

void Telemetry::OnWorldSize(uint64_t world) {
  impl_->world_size.store(world, std::memory_order_relaxed);
}

void Telemetry::OnSwapPhase(int phase, uint64_t us) {
  if (phase < 0 || phase >= kSwapPhaseCount) return;
  impl_->swap_phase[phase].Observe(us);
  flightrec::Record(flightrec::Ev::kSwapPhase, static_cast<uint64_t>(phase), us);
}

void Telemetry::OnSwapEvent(int kind) {
  if (kind < 0 || kind >= kSwapKindCount) return;
  impl_->swap_events[kind].fetch_add(1, std::memory_order_relaxed);
}

void Telemetry::OnWeightVersion(uint64_t version) {
  impl_->weight_version.store(version, std::memory_order_relaxed);
}

int Telemetry::MetricsPort() const {
  return impl_->scrape_bound_port.load(std::memory_order_acquire);
}

void Telemetry::Reset() {
  Impl* im = impl_.get();
  im->isend_count.store(0, std::memory_order_relaxed);
  im->irecv_count.store(0, std::memory_order_relaxed);
  im->isend_bytes.store(0, std::memory_order_relaxed);
  im->irecv_bytes.store(0, std::memory_order_relaxed);
  for (int i = 0; i < kHistBuckets; ++i) {
    im->isend_hist[i].store(0, std::memory_order_relaxed);
    im->irecv_hist[i].store(0, std::memory_order_relaxed);
  }
  // inflight is deliberately NOT reset: it tracks live requests whose done
  // events will still arrive — zeroing it would make them wrap the clamp.
  im->failed.store(0, std::memory_order_relaxed);
  for (int c = 0; c < kQosClassCount; ++c) {
    for (int i = 0; i < kMaxStreamStats; ++i) {
      im->stream_tx[c][i].store(0, std::memory_order_relaxed);
      im->stream_rx[c][i].store(0, std::memory_order_relaxed);
    }
    im->qos_wait[c].Reset();
    im->qos_preempts[c].store(0, std::memory_order_relaxed);
  }
  for (int i = 0; i < kMaxStreamStats; ++i) {
    for (StreamTcpState* slots : {im->tcp_tx, im->tcp_rx}) {
      slots[i].rtt_us.store(0, std::memory_order_relaxed);
      slots[i].srtt_us.store(0, std::memory_order_relaxed);
      slots[i].retrans_total.store(0, std::memory_order_relaxed);
      slots[i].cwnd.store(0, std::memory_order_relaxed);
      slots[i].delivery_rate_bps.store(0, std::memory_order_relaxed);
      slots[i].min_rtt_us.store(0, std::memory_order_relaxed);
      slots[i].sampled.store(0, std::memory_order_relaxed);
      slots[i].straggling.store(0, std::memory_order_relaxed);
      slots[i].next_sample_us.store(0, std::memory_order_relaxed);
    }
    im->lane_weight[i].store(0, std::memory_order_relaxed);
    im->lane_rate_bps[i].store(0, std::memory_order_relaxed);
    im->lane_bytes[i][0].store(0, std::memory_order_relaxed);
    im->lane_bytes[i][1].store(0, std::memory_order_relaxed);
  }
  im->restripe_events.store(0, std::memory_order_relaxed);
  im->shm_bytes[0].store(0, std::memory_order_relaxed);
  im->shm_bytes[1].store(0, std::memory_order_relaxed);
  im->shm_wakeups.store(0, std::memory_order_relaxed);
  for (int i = 0; i < kFaultActionSlots; ++i) {
    im->faults_injected[i].store(0, std::memory_order_relaxed);
  }
  im->stream_failovers.store(0, std::memory_order_relaxed);
  im->crc_errors.store(0, std::memory_order_relaxed);
  im->straggler_events.store(0, std::memory_order_relaxed);
  ResetIoSyscallCounts();
  ResetReduceBytesTotal();
  ResetCodecBytesTotals();
  ResetCollDispatchCounters();
  im->req_queue.Reset();
  im->req_wire.Reset();
  im->req_total.Reset();
  im->req_ttft.Reset();
  im->req_tpot.Reset();
  for (auto& d : im->serve_depth) d.store(0, std::memory_order_relaxed);
  for (auto& h : im->rewire_phase) h.Reset();
  for (auto& c : im->churn_events) c.store(0, std::memory_order_relaxed);
  im->world_size.store(0, std::memory_order_relaxed);
  for (auto& h : im->swap_phase) h.Reset();
  for (auto& c : im->swap_events) c.store(0, std::memory_order_relaxed);
  im->weight_version.store(0, std::memory_order_relaxed);
  {
    MutexLock lk(im->win_mu);
    im->win_init = false;
    im->win_last_us = 0;
    memset(im->win_tx, 0, sizeof(im->win_tx));
    memset(im->win_rx, 0, sizeof(im->win_rx));
    for (int c = 0; c < kQosClassCount; ++c) {
      im->fair_tx_bits[c].store(DoubleToBits(1.0), std::memory_order_relaxed);
      im->fair_rx_bits[c].store(DoubleToBits(1.0), std::memory_order_relaxed);
    }
  }
  im->start_us.store(NowUs(), std::memory_order_relaxed);
}

MetricsSnapshot Telemetry::Snapshot() const {
  Impl* im = impl_.get();
  MetricsSnapshot s;
  uint64_t cls_tx[kQosClassCount][kMaxStreamStats];
  uint64_t cls_rx[kQosClassCount][kMaxStreamStats];
  for (int c = 0; c < kQosClassCount; ++c) {
    for (int i = 0; i < kMaxStreamStats; ++i) {
      cls_tx[c][i] = im->stream_tx[c][i].load(std::memory_order_relaxed);
      cls_rx[c][i] = im->stream_rx[c][i].load(std::memory_order_relaxed);
      s.stream_tx_bytes[i] += cls_tx[c][i];
      s.stream_rx_bytes[i] += cls_rx[c][i];
      s.qos_bytes[c][0] += cls_tx[c][i];
      s.qos_bytes[c][1] += cls_rx[c][i];
    }
    im->qos_wait[c].SnapshotInto(&s.qos_wait_us[c]);
    s.qos_preempts[c] = im->qos_preempts[c].load(std::memory_order_relaxed);
  }
  // Fairness window roll: at most once per TPUNET_FAIRNESS_WINDOW_MS so two
  // back-to-back scrapes don't compute Jain over an empty delta. The first
  // roll covers everything since start/Reset. Each traffic class rolls its
  // OWN per-stream deltas: the gauge answers "is striping fair WITHIN this
  // class" — cross-class weighting is the scheduler's job, not skew.
  {
    MutexLock lk(im->win_mu);
    uint64_t now = NowUs();
    if (!im->win_init || now - im->win_last_us >= im->fairness_window_us) {
      bool moved_any = false;
      for (int c = 0; c < kQosClassCount; ++c) {
        uint64_t dtx[kMaxStreamStats], drx[kMaxStreamStats];
        uint64_t tot_tx = 0, tot_rx = 0;
        for (int i = 0; i < kMaxStreamStats; ++i) {
          dtx[i] = cls_tx[c][i] - im->win_tx[c][i];
          drx[i] = cls_rx[c][i] - im->win_rx[c][i];
          tot_tx += dtx[i];
          tot_rx += drx[i];
        }
        // Only move the gauge when bytes moved (else keep the last verdict).
        if (tot_tx > 0) {
          im->fair_tx_bits[c].store(
              DoubleToBits(JainIndex(dtx, kMaxStreamStats)),
              std::memory_order_relaxed);
        }
        if (tot_rx > 0) {
          im->fair_rx_bits[c].store(
              DoubleToBits(JainIndex(drx, kMaxStreamStats)),
              std::memory_order_relaxed);
        }
        if (tot_tx > 0 || tot_rx > 0) {
          memcpy(im->win_tx[c], cls_tx[c], sizeof(im->win_tx[c]));
          memcpy(im->win_rx[c], cls_rx[c], sizeof(im->win_rx[c]));
          moved_any = true;
        }
      }
      if (!im->win_init || moved_any) {
        if (!im->win_init) {
          memcpy(im->win_tx, cls_tx, sizeof(im->win_tx));
          memcpy(im->win_rx, cls_rx, sizeof(im->win_rx));
        }
        im->win_init = true;
        im->win_last_us = now;
      }
    }
  }
  for (int c = 0; c < kQosClassCount; ++c) {
    s.fairness_tx[c] =
        BitsToDouble(im->fair_tx_bits[c].load(std::memory_order_relaxed));
    s.fairness_rx[c] =
        BitsToDouble(im->fair_rx_bits[c].load(std::memory_order_relaxed));
  }
  for (int i = 0; i < kMaxStreamStats; ++i) {
    for (auto [slots, out] : {std::pair<StreamTcpState*, StreamTcpSample*>{
                                  im->tcp_tx, s.stream_tcp_tx},
                              {im->tcp_rx, s.stream_tcp_rx}}) {
      out[i].sampled = slots[i].sampled.load(std::memory_order_relaxed) != 0;
      out[i].rtt_us = slots[i].rtt_us.load(std::memory_order_relaxed);
      out[i].srtt_us = slots[i].srtt_us.load(std::memory_order_relaxed);
      out[i].retrans_total = slots[i].retrans_total.load(std::memory_order_relaxed);
      out[i].cwnd = slots[i].cwnd.load(std::memory_order_relaxed);
      out[i].delivery_rate_bps =
          slots[i].delivery_rate_bps.load(std::memory_order_relaxed);
      out[i].min_rtt_us = slots[i].min_rtt_us.load(std::memory_order_relaxed);
    }
    s.lane_weight[i] = im->lane_weight[i].load(std::memory_order_relaxed);
    s.lane_rate_bps[i] = im->lane_rate_bps[i].load(std::memory_order_relaxed);
    s.lane_bytes[i][0] = im->lane_bytes[i][0].load(std::memory_order_relaxed);
    s.lane_bytes[i][1] = im->lane_bytes[i][1].load(std::memory_order_relaxed);
  }
  s.restripe_events = im->restripe_events.load(std::memory_order_relaxed);
  s.shm_bytes[0] = im->shm_bytes[0].load(std::memory_order_relaxed);
  s.shm_bytes[1] = im->shm_bytes[1].load(std::memory_order_relaxed);
  s.shm_wakeups = im->shm_wakeups.load(std::memory_order_relaxed);
  s.straggler_events = im->straggler_events.load(std::memory_order_relaxed);
  s.isend_count = im->isend_count.load(std::memory_order_relaxed);
  s.irecv_count = im->irecv_count.load(std::memory_order_relaxed);
  s.isend_bytes = im->isend_bytes.load(std::memory_order_relaxed);
  s.irecv_bytes = im->irecv_bytes.load(std::memory_order_relaxed);
  for (int i = 0; i < kHistBuckets; ++i) {
    s.isend_hist[i] = im->isend_hist[i].load(std::memory_order_relaxed);
    s.irecv_hist[i] = im->irecv_hist[i].load(std::memory_order_relaxed);
  }
  s.inflight = im->inflight.load(std::memory_order_relaxed);
  s.failed_requests = im->failed.load(std::memory_order_relaxed);
  for (int i = 0; i < kFaultActionSlots; ++i) {
    s.faults_injected[i] = im->faults_injected[i].load(std::memory_order_relaxed);
  }
  s.stream_failovers = im->stream_failovers.load(std::memory_order_relaxed);
  s.crc_errors = im->crc_errors.load(std::memory_order_relaxed);
  im->req_queue.SnapshotInto(&s.req_queue_us);
  im->req_wire.SnapshotInto(&s.req_wire_us);
  im->req_total.SnapshotInto(&s.req_total_us);
  im->req_ttft.SnapshotInto(&s.req_ttft_us);
  im->req_tpot.SnapshotInto(&s.req_tpot_us);
  for (int p = 0; p < kRewirePhaseCount; ++p) {
    im->rewire_phase[p].SnapshotInto(&s.rewire_us[p]);
  }
  for (int k = 0; k < kChurnKindCount; ++k) {
    s.churn_events[k] = im->churn_events[k].load(std::memory_order_relaxed);
  }
  s.world_size = im->world_size.load(std::memory_order_relaxed);
  for (int p = 0; p < kSwapPhaseCount; ++p) {
    im->swap_phase[p].SnapshotInto(&s.swap_us[p]);
  }
  for (int k = 0; k < kSwapKindCount; ++k) {
    s.swap_events[k] = im->swap_events[k].load(std::memory_order_relaxed);
  }
  s.weight_version = im->weight_version.load(std::memory_order_relaxed);
  for (int t = 0; t < kServeTierCount; ++t) {
    s.serve_queue_depth[t] = im->serve_depth[t].load(std::memory_order_relaxed);
  }
  for (int i = 0; i < kIoOpCount; ++i) {
    s.engine_syscalls[i] = IoSyscallCount(static_cast<IoOp>(i));
  }
  s.reduce_bytes = ReduceBytesTotal();
  for (int c = 0; c < 2; ++c) {
    for (int d = 0; d < 2; ++d) {
      // Snapshot slot c maps to WireCodec c+1 (kF32 passthrough is uncounted).
      s.codec_bytes[c][d] = CodecBytesTotal(static_cast<WireCodec>(c + 1), d);
    }
  }
  for (int d = 0; d < 2; ++d) s.codec_payload_bytes[d] = CodecPayloadBytesTotal(d);
  for (int a = 0; a < 3; ++a) {
    // Snapshot slot a maps to CollAlgo a+1 (kAuto never executes a step).
    s.coll_steps[a] = CollStepsTotal(static_cast<CollAlgo>(a + 1));
  }
  // Hierarchical schedules: their stages count separately (slots 3/4 =
  // hier.intra/hier.inter, 5/6 = a2a.intra/a2a.inter) — the DCN-round
  // shrinkage IS the claim.
  s.coll_steps[3] = HierStepsTotal(false);
  s.coll_steps[4] = HierStepsTotal(true);
  s.coll_steps[5] = A2aStepsTotal(false);
  s.coll_steps[6] = A2aStepsTotal(true);
  for (int a = 0; a < 6; ++a) {
    for (int k = 0; k < kCollKindCount; ++k) {
      s.coll_algo_selected[k][a] =
          CollAlgoSelectedTotal(static_cast<CollKind>(k), static_cast<CollAlgo>(a + 1));
    }
  }
  for (int st = 0; st < kA2aStageCount; ++st) {
    for (int d = 0; d < 2; ++d) s.a2a_bytes[st][d] = A2aBytesTotal(st, d);
  }
  s.uptime_s = (NowUs() - im->start_us.load(std::memory_order_relaxed)) / 1e6;
  return s;
}

std::string Telemetry::PrometheusText() const {
  MetricsSnapshot s = Snapshot();
  char buf[2048];
  std::string out;
  auto emit = [&](const char* fmt, auto... args) {
    snprintf(buf, sizeof(buf), fmt, args...);
    out += buf;
  };
  // One # HELP + # TYPE header per family, immediately before its samples,
  // so the exposition passes a Prometheus text-format lint.
  auto family = [&](const char* name, const char* type, const char* help) {
    emit("# HELP %s %s\n# TYPE %s %s\n", name, help, name, type);
  };
  int64_t rank = impl_->rank;
  // Instrument names follow the reference (isend_nbytes / irecv_nbytes value
  // recorders nthread:172-180, bytes/s observers :343-348, hold_on_request
  // in-flight gauge tokio:184-190).
  auto size_hist = [&](const char* name, const char* help, const uint64_t* hist,
                       uint64_t sum, uint64_t count) {
    family(name, "histogram", help);
    uint64_t cum = 0;
    for (int i = 0; i < kHistBuckets - 1; ++i) {
      cum += hist[i];
      emit("%s_bucket{rank=\"%lld\",le=\"%llu\"} %llu\n", name, (long long)rank,
           (unsigned long long)kHistBounds[i], (unsigned long long)cum);
    }
    cum += hist[kHistBuckets - 1];
    emit("%s_bucket{rank=\"%lld\",le=\"+Inf\"} %llu\n", name, (long long)rank,
         (unsigned long long)cum);
    emit("%s_sum{rank=\"%lld\"} %llu\n", name, (long long)rank, (unsigned long long)sum);
    emit("%s_count{rank=\"%lld\"} %llu\n", name, (long long)rank,
         (unsigned long long)count);
  };
  size_hist("tpunet_isend_nbytes", "Posted isend message sizes in bytes.",
            s.isend_hist, s.isend_bytes, s.isend_count);
  size_hist("tpunet_irecv_nbytes", "Posted irecv message sizes in bytes.",
            s.irecv_hist, s.irecv_bytes, s.irecv_count);
  family("tpunet_isend_nbytes_per_second", "gauge",
         "Mean outbound payload rate since start (bytes/s).");
  emit("tpunet_isend_nbytes_per_second{rank=\"%lld\"} %.1f\n", (long long)rank,
       s.uptime_s > 0 ? s.isend_bytes / s.uptime_s : 0.0);
  family("tpunet_irecv_nbytes_per_second", "gauge",
         "Mean inbound payload rate since start (bytes/s).");
  emit("tpunet_irecv_nbytes_per_second{rank=\"%lld\"} %.1f\n", (long long)rank,
       s.uptime_s > 0 ? s.irecv_bytes / s.uptime_s : 0.0);
  family("tpunet_stream_tx_bytes", "counter",
         "Payload bytes sent per data-stream index (all comms aggregated).");
  for (int i = 0; i < kMaxStreamStats; ++i) {
    if (s.stream_tx_bytes[i] == 0) continue;
    emit("tpunet_stream_tx_bytes{rank=\"%lld\",stream=\"%d\"} %llu\n", (long long)rank, i,
         (unsigned long long)s.stream_tx_bytes[i]);
  }
  family("tpunet_stream_rx_bytes", "counter",
         "Payload bytes received per data-stream index (all comms aggregated).");
  for (int i = 0; i < kMaxStreamStats; ++i) {
    if (s.stream_rx_bytes[i] == 0) continue;
    emit("tpunet_stream_rx_bytes{rank=\"%lld\",stream=\"%d\"} %llu\n", (long long)rank, i,
         (unsigned long long)s.stream_rx_bytes[i]);
  }
  // Per-stream TCP introspection gauges (TCP_INFO sampler). Only sampled
  // slots are emitted; dir distinguishes the send-side and recv-side sockets
  // of the same stream index.
  struct TcpGaugeDef {
    const char* name;
    const char* type;
    const char* help;
    uint64_t StreamTcpSample::*field;
  };
  static const TcpGaugeDef kTcpGauges[] = {
      {"tpunet_stream_rtt_us", "gauge",
       "Last-sampled TCP round-trip time per data stream (tcpi_rtt, microseconds).",
       &StreamTcpSample::rtt_us},
      {"tpunet_stream_retrans_total", "counter",
       "TCP retransmitted segments of the last-sampled socket per data stream "
       "(tcpi_total_retrans).",
       &StreamTcpSample::retrans_total},
      {"tpunet_stream_cwnd", "gauge",
       "TCP congestion window per data stream (tcpi_snd_cwnd, segments).",
       &StreamTcpSample::cwnd},
      {"tpunet_stream_delivery_rate_bps", "gauge",
       "TCP delivery rate per data stream (tcpi_delivery_rate, bits/s; 0 on old kernels).",
       &StreamTcpSample::delivery_rate_bps},
      {"tpunet_stream_min_rtt_us", "gauge",
       "TCP minimum observed round-trip time per data stream (tcpi_min_rtt, "
       "microseconds; 0 on old kernels) — the per-path RTT floor the "
       "straggler detector's static TPUNET_STRAGGLER_MIN_RTT_US knob "
       "approximates.",
       &StreamTcpSample::min_rtt_us},
  };
  for (const TcpGaugeDef& g : kTcpGauges) {
    family(g.name, g.type, g.help);
    for (auto [samples, dir] : {std::pair<const StreamTcpSample*, const char*>{
                                    s.stream_tcp_tx, "tx"},
                                {s.stream_tcp_rx, "rx"}}) {
      for (int i = 0; i < kMaxStreamStats; ++i) {
        if (!samples[i].sampled) continue;
        emit("%s{rank=\"%lld\",stream=\"%d\",dir=\"%s\"} %llu\n", g.name,
             (long long)rank, i, dir, (unsigned long long)(samples[i].*(g.field)));
      }
    }
  }
  static const char* kQosClassNames[kQosClassCount] = {"latency", "bulk",
                                                       "control"};
  family("tpunet_stream_fairness_jain", "gauge",
         "Jain's fairness index over windowed per-stream bytes, per traffic "
         "class (1.0 = perfectly fair striping within the class).");
  for (int c = 0; c < kQosClassCount; ++c) {
    emit("tpunet_stream_fairness_jain{rank=\"%lld\",dir=\"tx\",class=\"%s\"} %.6f\n",
         (long long)rank, kQosClassNames[c], s.fairness_tx[c]);
    emit("tpunet_stream_fairness_jain{rank=\"%lld\",dir=\"rx\",class=\"%s\"} %.6f\n",
         (long long)rank, kQosClassNames[c], s.fairness_rx[c]);
  }
  // QoS families (docs/DESIGN.md "Transport QoS"). Every class x dir series
  // emits even at zero so the two-tenant bench/smoke never look up a
  // missing series.
  family("tpunet_qos_bytes_total", "counter",
         "Payload bytes moved per traffic class and direction (receivers "
         "learn the class from the preamble nibble).");
  for (int c = 0; c < kQosClassCount; ++c) {
    emit("tpunet_qos_bytes_total{rank=\"%lld\",class=\"%s\",dir=\"tx\"} %llu\n",
         (long long)rank, kQosClassNames[c],
         (unsigned long long)s.qos_bytes[c][0]);
    emit("tpunet_qos_bytes_total{rank=\"%lld\",class=\"%s\",dir=\"rx\"} %llu\n",
         (long long)rank, kQosClassNames[c],
         (unsigned long long)s.qos_bytes[c][1]);
  }
  family("tpunet_qos_queue_wait_us", "histogram",
         "Time data chunks waited for QoS wire credit in the DRR scheduler, "
         "per traffic class (microseconds; empty when no wire window is "
         "configured).");
  for (int c = 0; c < kQosClassCount; ++c) {
    const StageHist& h = s.qos_wait_us[c];
    uint64_t cum = 0;
    for (int i = 0; i < kStageHistBuckets - 1; ++i) {
      cum += h.buckets[i];
      emit("tpunet_qos_queue_wait_us_bucket{rank=\"%lld\",class=\"%s\",le=\"%llu\"} %llu\n",
           (long long)rank, kQosClassNames[c],
           (unsigned long long)kStageHistBounds[i], (unsigned long long)cum);
    }
    cum += h.buckets[kStageHistBuckets - 1];
    emit("tpunet_qos_queue_wait_us_bucket{rank=\"%lld\",class=\"%s\",le=\"+Inf\"} %llu\n",
         (long long)rank, kQosClassNames[c], (unsigned long long)cum);
    emit("tpunet_qos_queue_wait_us_sum{rank=\"%lld\",class=\"%s\"} %llu\n",
         (long long)rank, kQosClassNames[c], (unsigned long long)h.sum_us);
    emit("tpunet_qos_queue_wait_us_count{rank=\"%lld\",class=\"%s\"} %llu\n",
         (long long)rank, kQosClassNames[c], (unsigned long long)h.count);
  }
  family("tpunet_qos_preempts_total", "counter",
         "QoS wire-credit grants that jumped ahead of an older waiter of "
         "another class (strict control priority / DRR weighting at work).");
  for (int c = 0; c < kQosClassCount; ++c) {
    emit("tpunet_qos_preempts_total{rank=\"%lld\",class=\"%s\"} %llu\n",
         (long long)rank, kQosClassNames[c],
         (unsigned long long)s.qos_preempts[c]);
  }
  family("tpunet_straggler_events_total", "counter",
         "Streams whose smoothed RTT newly exceeded k x the comm median "
         "(TPUNET_STRAGGLER_FACTOR).");
  emit("tpunet_straggler_events_total{rank=\"%lld\"} %llu\n", (long long)rank,
       (unsigned long long)s.straggler_events);
  // Lane-striping families (docs/DESIGN.md "Lanes & adaptive striping").
  // Gauges emit only for lanes a lane-mode comm has reported (weight floor
  // is 1, so weight 0 means "slot never used"); the bytes counter emits
  // only nonzero cells like the per-stream byte counters.
  family("tpunet_lane_weight", "gauge",
         "Current stripe weight per lane in the weighted chunk scheduler "
         "(TPUNET_LANES; floor 1, demoted lanes decay toward it).");
  for (int i = 0; i < kMaxStreamStats; ++i) {
    if (s.lane_weight[i] == 0) continue;
    emit("tpunet_lane_weight{rank=\"%lld\",lane=\"%d\"} %llu\n", (long long)rank, i,
         (unsigned long long)s.lane_weight[i]);
  }
  family("tpunet_lane_rate_bps", "gauge",
         "Measured per-lane delivery rate the stripe weights chase (EWMA of "
         "payload bytes over wire-service time, bits/s).");
  for (int i = 0; i < kMaxStreamStats; ++i) {
    if (s.lane_rate_bps[i] == 0) continue;
    emit("tpunet_lane_rate_bps{rank=\"%lld\",lane=\"%d\"} %llu\n", (long long)rank, i,
         (unsigned long long)s.lane_rate_bps[i]);
  }
  family("tpunet_lane_bytes_total", "counter",
         "Payload bytes moved per lane and direction on lane-mode comms "
         "(the byte-share convergence signal).");
  for (int d = 0; d < 2; ++d) {
    for (int i = 0; i < kMaxStreamStats; ++i) {
      if (s.lane_bytes[i][d] == 0) continue;
      emit("tpunet_lane_bytes_total{rank=\"%lld\",lane=\"%d\",dir=\"%s\"} %llu\n",
           (long long)rank, i, d == 0 ? "tx" : "rx",
           (unsigned long long)s.lane_bytes[i][d]);
    }
  }
  family("tpunet_restripe_events_total", "counter",
         "Weight-vector epochs published by the adaptive stripe scheduler "
         "(each re-stripes subsequent messages on both sides).");
  emit("tpunet_restripe_events_total{rank=\"%lld\"} %llu\n", (long long)rank,
       (unsigned long long)s.restripe_events);
  // Intra-host SHM transport families (docs/DESIGN.md "Intra-host shared
  // memory"). Both dir series emit even at zero so the shm smoke lane can
  // assert "TCP moved, SHM did not" (and vice versa) without missing-series
  // special cases.
  family("tpunet_shm_bytes_total", "counter",
         "Payload bytes moved through intra-host shared-memory ring "
         "segments, by direction (TPUNET_SHM=1; never counted into the TCP "
         "stream/QoS byte families).");
  emit("tpunet_shm_bytes_total{rank=\"%lld\",dir=\"tx\"} %llu\n", (long long)rank,
       (unsigned long long)s.shm_bytes[0]);
  emit("tpunet_shm_bytes_total{rank=\"%lld\",dir=\"rx\"} %llu\n", (long long)rank,
       (unsigned long long)s.shm_bytes[1]);
  family("tpunet_shm_wakeups_total", "counter",
         "Futex wake syscalls issued by the SHM ring protocol (bytes/wakeup "
         "is the ring's syscalls/MiB analogue — steady-state streaming "
         "should wake rarely).");
  emit("tpunet_shm_wakeups_total{rank=\"%lld\"} %llu\n", (long long)rank,
       (unsigned long long)s.shm_wakeups);
  // Request stage-latency histograms: queueing delay separable from wire time.
  auto stage_hist = [&](const char* name, const char* help, const StageHist& h) {
    family(name, "histogram", help);
    uint64_t cum = 0;
    for (int i = 0; i < kStageHistBuckets - 1; ++i) {
      cum += h.buckets[i];
      emit("%s_bucket{rank=\"%lld\",le=\"%llu\"} %llu\n", name, (long long)rank,
           (unsigned long long)kStageHistBounds[i], (unsigned long long)cum);
    }
    cum += h.buckets[kStageHistBuckets - 1];
    emit("%s_bucket{rank=\"%lld\",le=\"+Inf\"} %llu\n", name, (long long)rank,
         (unsigned long long)cum);
    emit("%s_sum{rank=\"%lld\"} %llu\n", name, (long long)rank,
         (unsigned long long)h.sum_us);
    emit("%s_count{rank=\"%lld\"} %llu\n", name, (long long)rank,
         (unsigned long long)h.count);
  };
  stage_hist("tpunet_req_queue_us",
             "Request post to first wire byte (queueing delay, microseconds).",
             s.req_queue_us);
  stage_hist("tpunet_req_wire_us",
             "Request first to last wire byte (wire time, microseconds).",
             s.req_wire_us);
  stage_hist("tpunet_req_total_us",
             "Request post to completion (total latency, microseconds).",
             s.req_total_us);
  // Serving-tier SLO families (docs/DESIGN.md "Serving tier"): per-request
  // TTFT/TPOT fed by the router/decode workers, and instantaneous per-tier
  // queue depths. Every tier series emits even at zero so dashboards (and
  // the serve smoke lane) never look up a missing series.
  stage_hist("tpunet_req_ttft_us",
             "Serving-tier request admission to first generated token "
             "(microseconds).",
             s.req_ttft_us);
  stage_hist("tpunet_req_tpot_us",
             "Serving-tier mean time per output token after the first "
             "(microseconds).",
             s.req_tpot_us);
  family("tpunet_serve_queue_depth", "gauge",
         "Requests queued or held per serving tier (router admission queue, "
         "prefill backlog, decode pending+live slots).");
  static const char* kTierNames[kServeTierCount] = {"router", "prefill",
                                                    "decode"};
  for (int t = 0; t < kServeTierCount; ++t) {
    emit("tpunet_serve_queue_depth{rank=\"%lld\",tier=\"%s\"} %llu\n",
         (long long)rank, kTierNames[t],
         (unsigned long long)s.serve_queue_depth[t]);
  }
  // Elastic-churn families (docs/DESIGN.md "Elastic churn"). Every phase /
  // kind series emits even at zero so the churn smoke lane's "non-empty for
  // EVERY phase" gate never has to special-case a missing series.
  family("tpunet_rewire_duration_us", "histogram",
         "Elastic rewire duration per recovery phase (detect, quiesce, "
         "rendezvous, rewire — microseconds).");
  static const char* kRewirePhases[kRewirePhaseCount] = {
      "detect", "quiesce", "rendezvous", "rewire"};
  for (int p = 0; p < kRewirePhaseCount; ++p) {
    const StageHist& h = s.rewire_us[p];
    uint64_t cum = 0;
    for (int i = 0; i < kStageHistBuckets - 1; ++i) {
      cum += h.buckets[i];
      emit("tpunet_rewire_duration_us_bucket{rank=\"%lld\",phase=\"%s\",le=\"%llu\"} %llu\n",
           (long long)rank, kRewirePhases[p],
           (unsigned long long)kStageHistBounds[i], (unsigned long long)cum);
    }
    cum += h.buckets[kStageHistBuckets - 1];
    emit("tpunet_rewire_duration_us_bucket{rank=\"%lld\",phase=\"%s\",le=\"+Inf\"} %llu\n",
         (long long)rank, kRewirePhases[p], (unsigned long long)cum);
    emit("tpunet_rewire_duration_us_sum{rank=\"%lld\",phase=\"%s\"} %llu\n",
         (long long)rank, kRewirePhases[p], (unsigned long long)h.sum_us);
    emit("tpunet_rewire_duration_us_count{rank=\"%lld\",phase=\"%s\"} %llu\n",
         (long long)rank, kRewirePhases[p], (unsigned long long)h.count);
  }
  family("tpunet_churn_events_total", "counter",
         "Membership-churn events survived, by kind (kill, join, shrink, "
         "grow, readmit).");
  static const char* kChurnKinds[kChurnKindCount] = {"kill", "join", "shrink",
                                                     "grow", "readmit"};
  for (int k = 0; k < kChurnKindCount; ++k) {
    emit("tpunet_churn_events_total{rank=\"%lld\",kind=\"%s\"} %llu\n",
         (long long)rank, kChurnKinds[k],
         (unsigned long long)s.churn_events[k]);
  }
  family("tpunet_world_size", "gauge",
         "Live communicator world size as this rank last reported it (0 "
         "until a churn-aware job reports).");
  emit("tpunet_world_size{rank=\"%lld\"} %llu\n", (long long)rank,
       (unsigned long long)s.world_size);
  // Live weight-update families (docs/DESIGN.md "Live weight updates").
  // Same every-series-even-at-zero discipline as the churn families: the
  // swap smoke lane gates on "every phase non-empty".
  family("tpunet_weight_swap_duration_us", "histogram",
         "Live weight-swap duration per publication phase (announce, "
         "broadcast, verify, flip — microseconds).");
  static const char* kSwapPhases[kSwapPhaseCount] = {"announce", "broadcast",
                                                     "verify", "flip"};
  for (int p = 0; p < kSwapPhaseCount; ++p) {
    const StageHist& h = s.swap_us[p];
    uint64_t cum = 0;
    for (int i = 0; i < kStageHistBuckets - 1; ++i) {
      cum += h.buckets[i];
      emit("tpunet_weight_swap_duration_us_bucket{rank=\"%lld\",phase=\"%s\",le=\"%llu\"} %llu\n",
           (long long)rank, kSwapPhases[p],
           (unsigned long long)kStageHistBounds[i], (unsigned long long)cum);
    }
    cum += h.buckets[kStageHistBuckets - 1];
    emit("tpunet_weight_swap_duration_us_bucket{rank=\"%lld\",phase=\"%s\",le=\"+Inf\"} %llu\n",
         (long long)rank, kSwapPhases[p], (unsigned long long)cum);
    emit("tpunet_weight_swap_duration_us_sum{rank=\"%lld\",phase=\"%s\"} %llu\n",
         (long long)rank, kSwapPhases[p], (unsigned long long)h.sum_us);
    emit("tpunet_weight_swap_duration_us_count{rank=\"%lld\",phase=\"%s\"} %llu\n",
         (long long)rank, kSwapPhases[p], (unsigned long long)h.count);
  }
  family("tpunet_swap_events_total", "counter",
         "Weight-swap events, by kind (publish, commit, abort, retry, "
         "mismatch).");
  static const char* kSwapKinds[kSwapKindCount] = {"publish", "commit",
                                                   "abort", "retry",
                                                   "mismatch"};
  for (int k = 0; k < kSwapKindCount; ++k) {
    emit("tpunet_swap_events_total{rank=\"%lld\",kind=\"%s\"} %llu\n",
         (long long)rank, kSwapKinds[k],
         (unsigned long long)s.swap_events[k]);
  }
  family("tpunet_weight_version", "gauge",
         "Checkpoint version this rank is serving (0 until a versioned "
         "serving tier reports; the swap lane's per-rank flip gate).");
  emit("tpunet_weight_version{rank=\"%lld\"} %llu\n", (long long)rank,
       (unsigned long long)s.weight_version);
  family("tpunet_hold_on_request", "gauge",
         "Requests posted but not yet test()ed done (in flight).");
  emit("tpunet_hold_on_request{rank=\"%lld\"} %llu\n", (long long)rank,
       (unsigned long long)s.inflight);
  family("tpunet_failed_requests", "counter", "Requests that completed with an error.");
  emit("tpunet_failed_requests{rank=\"%lld\"} %llu\n", (long long)rank,
       (unsigned long long)s.failed_requests);
  // Failure-containment counters. faults_injected is labeled by action and
  // emitted only for nonzero slots; the unlabeled totals are always present
  // so dashboards (and the Python parser, which must accept label-less
  // lines) see them even at zero.
  family("tpunet_faults_injected_total", "counter",
         "Deterministic fault injections fired, by action (chaos testing).");
  static const char* kActionNames[kFaultActionSlots] = {"none", "close", "stall",
                                                        "corrupt", "delay"};
  uint64_t faults_total = 0;
  for (int i = 1; i < kFaultActionSlots; ++i) {
    faults_total += s.faults_injected[i];
    if (s.faults_injected[i] == 0) continue;
    emit("tpunet_faults_injected_total{rank=\"%lld\",action=\"%s\"} %llu\n", (long long)rank,
         kActionNames[i], (unsigned long long)s.faults_injected[i]);
  }
  family("tpunet_faults_injected", "counter",
         "Deterministic fault injections fired, all actions (label-less total).");
  emit("tpunet_faults_injected %llu\n", (unsigned long long)faults_total);
  family("tpunet_stream_failovers_total", "counter",
         "Data-stream failures survived via single-stream failover.");
  emit("tpunet_stream_failovers_total{rank=\"%lld\"} %llu\n", (long long)rank,
       (unsigned long long)s.stream_failovers);
  family("tpunet_crc_errors_total", "counter",
         "Per-chunk CRC32C mismatches detected (TPUNET_CRC=1).");
  emit("tpunet_crc_errors_total{rank=\"%lld\"} %llu\n", (long long)rank,
       (unsigned long long)s.crc_errors);
  // Zero-copy data-path counters. All four op slots emit even at zero so
  // syscalls/MiB derivations never divide by a missing series.
  family("tpunet_engine_syscalls_total", "counter",
         "Wire send/recv-family syscalls issued on the engines' data paths, "
         "by syscall op and direction.");
  static const struct {
    const char* op;
    const char* dir;
  } kIoOpLabels[kIoOpCount] = {
      {"send", "tx"}, {"recv", "rx"}, {"sendmsg", "tx"}, {"recvmsg", "rx"}};
  for (int i = 0; i < kIoOpCount; ++i) {
    emit("tpunet_engine_syscalls_total{rank=\"%lld\",op=\"%s\",dir=\"%s\"} %llu\n",
         (long long)rank, kIoOpLabels[i].op, kIoOpLabels[i].dir,
         (unsigned long long)s.engine_syscalls[i]);
  }
  family("tpunet_reduce_bytes_total", "counter",
         "Bytes produced by the collective reduction kernels (output side).");
  emit("tpunet_reduce_bytes_total{rank=\"%lld\"} %llu\n", (long long)rank,
       (unsigned long long)s.reduce_bytes);
  // Compressed-collectives counters. Every codec x dir series emits even at
  // zero so wire-ratio derivations (perf smoke, busbw_sweep) never divide by
  // a missing series.
  family("tpunet_codec_bytes_total", "counter",
         "Encoded bytes produced (tx) and consumed (rx) by the collective "
         "wire codecs, by codec.");
  static const char* kCodecNames[2] = {"bf16", "int8"};
  static const char* kCodecDirs[2] = {"tx", "rx"};
  for (int c = 0; c < 2; ++c) {
    for (int d = 0; d < 2; ++d) {
      emit("tpunet_codec_bytes_total{rank=\"%lld\",codec=\"%s\",dir=\"%s\"} %llu\n",
           (long long)rank, kCodecNames[c], kCodecDirs[d],
           (unsigned long long)s.codec_bytes[c][d]);
    }
  }
  family("tpunet_codec_wire_ratio", "gauge",
         "Encoded wire bytes per f32 payload byte over the compressed "
         "collective paths (1.0 when nothing was compressed).");
  uint64_t codec_encoded = 0, codec_payload = 0;
  for (int c = 0; c < 2; ++c) {
    for (int d = 0; d < 2; ++d) codec_encoded += s.codec_bytes[c][d];
  }
  for (int d = 0; d < 2; ++d) codec_payload += s.codec_payload_bytes[d];
  emit("tpunet_codec_wire_ratio{rank=\"%lld\"} %.6f\n", (long long)rank,
       codec_payload > 0 ? (double)codec_encoded / (double)codec_payload : 1.0);
  // Schedule-dispatch counters (docs/DESIGN.md "Schedules & algorithm
  // selection"). Every algo series emits even at zero so step-budget
  // assertions (perf smoke) can pin "ring executed NO steps" directly.
  // Step slots 3/4 are the hierarchical schedule's two stages: the claim is
  // precisely that hier.inter (the DCN wire rounds) shrinks by ~R x while
  // hier.intra rides shared memory.
  static const char* kAlgoNames[7] = {"ring",       "rhd",       "tree",
                                      "hier.intra", "hier.inter", "a2a.intra",
                                      "a2a.inter"};
  static const char* kSelAlgoNames[6] = {"ring", "rhd",      "tree",
                                         "hier", "hier_a2a", "pairwise"};
  static const char* kCollNames[3] = {"allreduce", "broadcast", "alltoall"};
  family("tpunet_coll_steps_total", "counter",
         "Sequential collective wire rounds executed by this rank, per "
         "schedule (ring AllReduce = 2(W-1); rhd = 2*log2(W'); tree <= "
         "2*ceil(log2 W); hier = 2(R-1) intra-host + 2(H-1) inter-host; "
         "hier AllToAll = R-1 intra + H-1 inter).");
  for (int a = 0; a < 7; ++a) {
    emit("tpunet_coll_steps_total{rank=\"%lld\",algo=\"%s\"} %llu\n",
         (long long)rank, kAlgoNames[a], (unsigned long long)s.coll_steps[a]);
  }
  family("tpunet_coll_algo_selected_total", "counter",
         "Collective dispatch decisions, by collective and RESOLVED "
         "schedule (override > TPUNET_DISPATCH_TABLE > built-ins).");
  for (int k = 0; k < 3; ++k) {
    for (int a = 0; a < 6; ++a) {
      emit("tpunet_coll_algo_selected_total{rank=\"%lld\",coll=\"%s\",algo=\"%s\"} %llu\n",
           (long long)rank, kCollNames[k], kSelAlgoNames[a],
           (unsigned long long)s.coll_algo_selected[k][a]);
    }
  }
  // AllToAll byte accounting per stage (docs/DESIGN.md "Hierarchical
  // AllToAll"). All stage x dir series emit even at zero so the exact-byte
  // gates (tests/test_a2a.py, moe_smoke) never look up a missing series.
  static const char* kA2aStageNames[3] = {"intra", "inter", "flat"};
  family("tpunet_a2a_bytes_total", "counter",
         "AllToAll wire bytes per stage and direction: intra = same-host "
         "regroup hops (SHM-cheap), inter = the one-rank-per-host DCN "
         "transpose, flat = the pairwise mesh / ring relay baseline.");
  for (int st = 0; st < 3; ++st) {
    emit("tpunet_a2a_bytes_total{rank=\"%lld\",stage=\"%s\",dir=\"tx\"} %llu\n",
         (long long)rank, kA2aStageNames[st],
         (unsigned long long)s.a2a_bytes[st][0]);
    emit("tpunet_a2a_bytes_total{rank=\"%lld\",stage=\"%s\",dir=\"rx\"} %llu\n",
         (long long)rank, kA2aStageNames[st],
         (unsigned long long)s.a2a_bytes[st][1]);
  }
  return out;
}

bool Telemetry::FlushTrace() {
  if (!tracing_enabled()) return true;
  Impl* im = impl_.get();
  std::vector<Span> spans;
  {
    MutexLock lk(im->span_mu);
    spans.swap(im->done_spans);
  }
  MutexLock lk(im->span_mu);  // serialize file writes
  if (spans.empty() && im->trace_header_written) return true;
  // The file is VALID JSON after every flush: the array's closing "\n]" is
  // rewritten in place on each append (r+ / seek −2), so json.load and
  // Perfetto both accept it at any point, including mid-run.
  //
  // Guarded state is copied to locals around the write_header lambda: TSA
  // analyzes a lambda as a separate unannotated function, so direct guarded
  // accesses inside it would (falsely) warn even with span_mu held here.
  const std::string path = im->trace_path;
  bool header_written = im->trace_header_written;
  FILE* f = nullptr;
  auto write_header = [&]() -> FILE* {
    FILE* nf = fopen(path.c_str(), "w");
    if (!nf) return nullptr;
    fprintf(nf,
            "[\n{\"name\":\"process_name\",\"ph\":\"M\",\"pid\":%lld,"
            "\"args\":{\"name\":\"tpunet-rank%lld\"}}",
            (long long)im->rank, (long long)im->rank);
    header_written = true;
    return nf;
  };
  if (!header_written) {
    f = write_header();
  } else {
    f = fopen(path.c_str(), "r+");
    if (f) {
      if (fseek(f, -2, SEEK_END) != 0) {
        fclose(f);
        f = nullptr;
      }
    }
    if (!f) f = write_header();  // file deleted/truncated underneath: restart
  }
  if (!f) return false;  // spans dropped; caller surfaces the failure
  im->trace_header_written = header_written;
  for (const Span& s : spans) {
    switch (s.kind) {
      case Span::Kind::kReq:
        // Span naming per the reference: "isend-{comm}" / "irecv-{comm}" with
        // id and nbytes attributes (nthread:529-538).
        fprintf(f,
                ",\n{\"name\":\"%s-%llu\",\"ph\":\"X\",\"pid\":%lld,\"tid\":%llu,"
                "\"ts\":%llu,\"dur\":%llu,\"args\":{\"id\":%llu,\"nbytes\":%llu}}",
                s.is_send ? "isend" : "irecv", (unsigned long long)s.comm,
                (long long)im->rank, (unsigned long long)s.comm,
                (unsigned long long)s.start_us, (unsigned long long)s.dur_us,
                (unsigned long long)s.req, (unsigned long long)s.nbytes);
        break;
      case Span::Kind::kColl:
        // Collective phase span: (comm_id, coll_seq, name) is the cross-rank
        // join key merge_traces() aligns per-rank timelines with. The host
        // tag (utils.h HostId(), hex string so JSON consumers never round
        // a 64-bit id) lets merge_traces() group same-host ranks under ONE
        // Perfetto track group instead of interleaving them.
        fprintf(f,
                ",\n{\"name\":\"%s\",\"ph\":\"X\",\"pid\":%lld,\"tid\":%llu,"
                "\"ts\":%llu,\"dur\":%llu,\"args\":{\"comm_id\":%llu,"
                "\"coll_seq\":%llu,\"nbytes\":%llu,\"host\":\"%016llx\"}}",
                s.name.c_str(), (long long)im->rank,
                (unsigned long long)(s.comm & 0xffff),
                (unsigned long long)s.start_us, (unsigned long long)s.dur_us,
                (unsigned long long)s.comm, (unsigned long long)s.req,
                (unsigned long long)s.nbytes, (unsigned long long)HostId());
        break;
      case Span::Kind::kInstant:
        fprintf(f,
                ",\n{\"name\":\"%s\",\"ph\":\"i\",\"s\":\"p\",\"pid\":%lld,"
                "\"tid\":%llu,\"ts\":%llu,\"args\":{\"stream\":%llu,"
                "\"srtt_us\":%llu,\"median_srtt_us\":%llu,\"dir\":\"%s\"}}",
                s.name.c_str(), (long long)im->rank, (unsigned long long)s.comm,
                (unsigned long long)s.start_us, (unsigned long long)s.comm,
                (unsigned long long)s.req, (unsigned long long)s.nbytes,
                s.is_send ? "tx" : "rx");
        break;
    }
  }
  fprintf(f, "\n]");
  fclose(f);
  return true;
}

// ---------------------------------------------------------------------------

namespace {

class TelemetryNet : public Net {
 public:
  explicit TelemetryNet(std::unique_ptr<Net> inner) : inner_(std::move(inner)) {}

  int32_t devices() override { return inner_->devices(); }
  Status get_properties(int32_t dev, NetProperties* p) override {
    return inner_->get_properties(dev, p);
  }
  Status listen(int32_t dev, SocketHandle* h, uint64_t* lc) override {
    return inner_->listen(dev, h, lc);
  }
  Status connect(int32_t dev, const SocketHandle& h, uint64_t* sc) override {
    return inner_->connect(dev, h, sc);
  }
  Status accept(uint64_t lc, uint64_t* rc) override { return inner_->accept(lc, rc); }

  Status isend(uint64_t comm, const void* data, size_t n, uint64_t* req) override {
    Status s = inner_->isend(comm, data, n, req);
    if (s.ok()) Telemetry::Get().OnRequestStart(Owner(), true, comm, *req, n);
    return s;
  }
  Status irecv(uint64_t comm, void* data, size_t n, uint64_t* req) override {
    Status s = inner_->irecv(comm, data, n, req);
    if (s.ok()) Telemetry::Get().OnRequestStart(Owner(), false, comm, *req, n);
    return s;
  }
  Status test(uint64_t req, bool* done, size_t* nbytes) override {
    Status s = inner_->test(req, done, nbytes);
    if (!s.ok()) {
      // Invalid = unknown/stale id (double-poll, garbage): the request was
      // never tracked here, so neither the failure counter nor the in-flight
      // gauge may move. Real transport errors DO consume the request id.
      if (s.kind != ErrorKind::kInvalidArgument) {
        Telemetry::Get().OnRequestDone(Owner(), req, /*failed=*/true);
      }
    } else if (*done) {
      Telemetry::Get().OnRequestDone(Owner(), req, /*failed=*/false);
    }
    return s;
  }

  Status wait(uint64_t req, size_t* nbytes) override {
    Status s = inner_->wait(req, nbytes);
    if (!s.ok()) {
      if (s.kind != ErrorKind::kInvalidArgument) {
        Telemetry::Get().OnRequestDone(Owner(), req, /*failed=*/true);
      }
    } else {
      Telemetry::Get().OnRequestDone(Owner(), req, /*failed=*/false);
    }
    return s;
  }

  Status close_send(uint64_t c) override { return inner_->close_send(c); }
  Status close_recv(uint64_t c) override { return inner_->close_recv(c); }
  Status close_listen(uint64_t c) override { return inner_->close_listen(c); }
  void set_traffic_class(int32_t cls) override {
    inner_->set_traffic_class(cls);
  }
  int32_t traffic_class() const override { return inner_->traffic_class(); }

 private:
  uint64_t Owner() const { return reinterpret_cast<uint64_t>(this); }

  std::unique_ptr<Net> inner_;
};

}  // namespace

std::unique_ptr<Net> WrapWithTelemetry(std::unique_ptr<Net> inner) {
  return std::make_unique<TelemetryNet>(std::move(inner));
}

}  // namespace tpunet
