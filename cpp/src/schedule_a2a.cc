// Hierarchical two-stage AllToAll over the pairwise mesh (docs/DESIGN.md
// "Hierarchical AllToAll"; the collective-communication-at-100k-GPUs shape:
// MoE expert dispatch is AllToAll-bound with small, skewed shards, and a
// flat W^2 exchange collapses first in connection count and message rate).
//
//   1. INTRA-HOST REGROUP (R-1 rounds, H*B bytes each — SHM segments under
//      TPUNET_SHM=1): the R ranks sharing a host exchange blocks grouped by
//      DESTINATION LOCAL INDEX. After the stage, local rank li holds — for
//      every host h and every local source j — the block
//      (src = local[j]  ->  dst = hosts[h][li]).
//   2. INTER-HOST TRANSPOSE (H-1 rounds, R*B bytes each — the ONLY DCN
//      hops): the H ranks with local index li (one per host, the same
//      "column" construction as the hierarchical AllReduce's inter stage)
//      exchange their per-destination-host bundles. The bundle received
//      from host h scatters straight into the output: it holds the R blocks
//      (src = hosts[h][j] -> dst = me).
//
// Wire accounting per rank: intra (R-1)*H*B bytes, inter (H-1)*R*B bytes —
// vs the flat pairwise mesh's (W-1)*B all-DCN bytes. The inter stage is
// exactly the cross-host payload lower bound; what the hierarchy buys on
// top of the SHM routing is AGGREGATION: H-1 DCN messages of R*B instead
// of R*(H-1) messages of B, and H-1 DCN connections instead of R*(H-1) —
// the latency/connection levers for small, skewed MoE dispatch shards.
// Under the typed-A2A codec wrapper (collectives.cc AllToAllTyped) B is
// already the ENCODED block size, so the DCN bytes shrink by the codec
// ratio on top. Counters carry every claim: a2a.intra/a2a.inter rounds in
// tpunet_coll_steps_total, stage bytes in tpunet_a2a_bytes_total — gated
// in tests/test_a2a.py and the moe_smoke CI lane, never by wall-clock.
//
// Topology comes from host_ids_ (the Init handshake blob) via
// BuildHierTopo — identical on every rank, so the stages pair up with no
// extra negotiation. Usable = >= 2 hosts AND uniform ranks/host; anything
// else resolves back to the pairwise mesh in ApplyHierPolicy.
#include <string.h>

#include <algorithm>
#include <vector>

#include "coll_comm.h"

namespace tpunet {
namespace internal {

Status ScheduledCommunicator::DoAllToAllHier(const uint8_t* in, uint8_t* out,
                                             size_t B, uint64_t seq) {
  HierTopo t = BuildHierTopo(rank_, host_ids_);
  if (t.H < 2 || !t.uniform) {
    // ApplyHierPolicy keeps this unreachable; belt-and-braces for an
    // explicit override racing an exotic topology.
    return Status::Inner("hier a2a schedule on a non-hierarchical topology");
  }
  Status s = EnsureMeshQuiesced();
  if (!s.ok()) return s;
  const size_t R = t.R, H = t.H;
  const bool tracing = Telemetry::Get().tracing_enabled();

  // Staging layout: slot (j, h) = block (src = local[j] -> dst =
  // hosts[h][li]) at offset (j*H + h)*B. Stage-1 receives land contiguous
  // (one j-run per peer); stage-2 sends gather one h-column per peer.
  a2a_stage_.reserve(R * H * B);
  auto slot = [&](size_t j, size_t h) {
    return a2a_stage_.data() + (j * H + h) * B;
  };
  // My own contribution: the blocks I address to local index li on every
  // host (contiguous j = li run).
  for (size_t h = 0; h < H; ++h) {
    memcpy(slot(t.li, h), in + static_cast<size_t>(t.hosts[h][t.li]) * B, B);
  }

  // ---- Stage 1: intra-host regroup, R-1 symmetric shifted rounds. Round
  // s sends to local[(li+s)%R] the H blocks addressed to ITS local index
  // and receives the H blocks addressed to MINE from local[(li-s+R)%R] —
  // recv-first inside MeshShift, sizes identical on both sides.
  a2a_fwd_.reserve(std::max(H, R) * B);  // stage-1 sends H*B, stage-2 R*B
  for (size_t st = 1; st < R; ++st) {
    const size_t to_li = (t.li + st) % R;
    const size_t from_li = (t.li + R - st) % R;
    const int to = t.local[to_li];
    const int from = t.local[from_li];
    for (size_t h = 0; h < H; ++h) {
      memcpy(a2a_fwd_.data() + h * B,
             in + static_cast<size_t>(t.hosts[h][to_li]) * B, B);
    }
    PhaseSpan sp(tracing, trace_comm_id_, seq, "a2a.intra",
                 static_cast<int>(st - 1), H * B);
    CountA2aSteps(/*inter=*/false);
    s = MeshShift(to, a2a_fwd_.data(), H * B, from, slot(from_li, 0), H * B);
    if (!s.ok()) return s;
    CountA2aBytes(0, 0, H * B);
    CountA2aBytes(0, 1, H * B);
  }

  // ---- Stage 2: inter-host column transpose, H-1 symmetric shifted
  // rounds among the one-rank-per-host column. The bundle for host h is
  // the h-column of the staging area (R blocks, one per local source); the
  // bundle received from host h scatters into the output by source rank.
  a2a_rcv_.reserve(R * B);
  for (size_t st = 1; st < H; ++st) {
    const size_t to_h = (t.hi + st) % H;
    const size_t from_h = (t.hi + H - st) % H;
    const int to = t.inter[to_h];
    const int from = t.inter[from_h];
    for (size_t j = 0; j < R; ++j) {
      memcpy(a2a_fwd_.data() + j * B, slot(j, to_h), B);
    }
    PhaseSpan sp(tracing, trace_comm_id_, seq, "a2a.inter",
                 static_cast<int>(st - 1), R * B);
    CountA2aSteps(/*inter=*/true);
    s = MeshShift(to, a2a_fwd_.data(), R * B, from, a2a_rcv_.data(), R * B);
    if (!s.ok()) return s;
    CountA2aBytes(1, 0, R * B);
    CountA2aBytes(1, 1, R * B);
    for (size_t j = 0; j < R; ++j) {
      memcpy(out + static_cast<size_t>(t.hosts[from_h][j]) * B,
             a2a_rcv_.data() + j * B, B);
    }
  }

  // Own-host column: the blocks (src = local[j] -> dst = me) landed in
  // stage 1 (j = li came from the local copy above) — scatter them out.
  for (size_t j = 0; j < R; ++j) {
    memcpy(out + static_cast<size_t>(t.local[j]) * B, slot(j, t.hi), B);
  }
  return Status::Ok();
}

}  // namespace internal
}  // namespace tpunet
