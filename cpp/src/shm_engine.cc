// tpunet SHM engine — intra-host shared-memory transport (TPUNET_SHM=1).
//
// A TPU-host pod runs R ranks per host; the TCP engines make same-host
// pairs pay full loopback cost (two kernel copies plus syscalls per chunk).
// This engine fronts a TCP engine on ONE listen socket and gives same-host
// pairs a mmap'd per-pair ring segment instead of TCP data streams:
//
//   * Rendezvous is unchanged: listen() binds the usual TCP listener whose
//     sockaddr is the 64-byte handle. connect() checks whether the handle's
//     address belongs to this host; if so it opens an SHM HELLO bundle —
//     the normal preamble with nstreams=0 and kPreambleFlagShm, so the one
//     connection doubles as the comm's ctrl stream — and negotiates the
//     segment (host id + ring size + shm_open name) on it. The receiver
//     compares HOST IDS (utils.h HostId(): TPUNET_HOST_ID override /
//     boot-id / hostname hash — the id every rank also publishes in the
//     collective bootstrap blob): equal → ack 1, map, ring engaged;
//     different (fake-host split, shared NAT address) or unmappable → ack 0
//     and BOTH sides run the comm in ctrl-TCP mode (the failover data path
//     below, engaged from byte zero) — the transparent fallback. The ack
//     rides back asynchronously: connect() returns right after the hello
//     (TCP semantics — a connect must not require the peer to be inside
//     accept(), or the collectives' connect-all-then-accept-all wiring
//     would deadlock) and the comm's scheduler thread consumes the ack
//     before the first payload byte. Cross-host handles skip all of this
//     and go straight to the inner engine.
//
//   * The data path preserves the TCP comms' LEN-frame semantics exactly:
//     every message's 8-byte big-endian length frame rides the ctrl
//     connection, chunk boundaries derive from (len, chunk size) on both
//     sides with no per-chunk metadata, and CRC32C trailers follow each
//     chunk in the ring when negotiated (kPreambleFlagCrc, sender wins).
//     Chunks move through a lock-free SPSC byte ring in the segment:
//     free-running head/tail cursors, futex parking on seq words with
//     waiter counts so a streaming steady state issues ~zero wake syscalls
//     (tpunet_shm_wakeups_total counts the ones it does), and every payload
//     byte feeds tpunet_shm_bytes_total{dir} — NOT the TCP stream/QoS byte
//     counters, which is what lets tests prove "intra-host stage moved zero
//     TCP bytes" straight off the counters.
//
//   * Failure containment composes unchanged: fault injection acts on the
//     segment (fault.h FaultPreMem — corrupt flips a ring byte under the
//     original-bytes CRC, stall parks against the abort flag, delay
//     sleeps), a `close` fault FAILS THE SEGMENT OVER TO TCP — the sender
//     marks the ring dead, emits the PR-1 0xFE FAILOVER marker on ctrl and
//     ships the remaining chunks (and all later messages) over the ctrl
//     TCP connection, receiver mirroring from the marker point — and peer
//     death is detected from the ctrl socket (EOF) inside every futex wait
//     slice, so "never a hang" holds even without the progress watchdog
//     (which also works: the abort hook poisons the segment like a socket
//     shutdown). QoS admission + wire credit account exactly like the TCP
//     engines (admission at isend, credit per chunk, release at
//     consumption), and the wire codec composes untouched above the engine.
#include <fcntl.h>
#include <ifaddrs.h>
#include <linux/futex.h>
#include <poll.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/syscall.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <deque>
#include <memory>
#include <set>
#include <string>
#include <thread>
#include <vector>

#include "engine_base.h"
#include "fault.h"
#include "id_map.h"
#include "tpunet/mutex.h"
#include "tpunet/net.h"
#include "tpunet/qos.h"
#include "tpunet/telemetry.h"
#include "tpunet/utils.h"
#include "wire.h"

namespace tpunet {
namespace {

constexpr uint64_t kShmMagic = 0x74707573686d3031ull;  // "tpushm01"
constexpr uint64_t kShmHdrFlagCrc = 1ull << 0;
constexpr size_t kShmRingOffset = 4096;  // header page, then ring bytes
constexpr uint32_t kSegLive = 0;
constexpr uint32_t kSegFailover = 1;  // ring dead; payload rides ctrl TCP
constexpr uint32_t kSegClosed = 2;    // comm shut down / poisoned

// Segment header. Producer-written and consumer-written state live on
// separate cache lines; the seq words are the futex parking spots (shared
// futexes — the segment is mapped by two processes).
struct ShmSegHdr {
  uint64_t magic;
  uint64_t ring_bytes;
  uint64_t flags;
  alignas(64) std::atomic<uint64_t> head;  // bytes produced (free-running)
  alignas(64) std::atomic<uint64_t> tail;  // bytes consumed (free-running)
  alignas(64) std::atomic<uint32_t> data_seq;
  std::atomic<uint32_t> data_waiters;
  alignas(64) std::atomic<uint32_t> space_seq;
  std::atomic<uint32_t> space_waiters;
  alignas(64) std::atomic<uint32_t> state;  // kSegLive / kSegFailover / kSegClosed
};
static_assert(sizeof(ShmSegHdr) <= kShmRingOffset, "header must fit its page");
static_assert(std::atomic<uint64_t>::is_always_lock_free,
              "cross-process ring cursors must be lock-free");

int FutexWait(std::atomic<uint32_t>* addr, uint32_t expect, int timeout_ms) {
  struct timespec ts = {timeout_ms / 1000, (timeout_ms % 1000) * 1000000L};
  return static_cast<int>(syscall(SYS_futex, reinterpret_cast<uint32_t*>(addr),
                                  FUTEX_WAIT, expect, &ts, nullptr, 0));
}

void FutexWakeAll(std::atomic<uint32_t>* addr) {
  syscall(SYS_futex, reinterpret_cast<uint32_t*>(addr), FUTEX_WAKE, INT32_MAX,
          nullptr, nullptr, 0);
  Telemetry::Get().OnShmWakeup();
}

// A mapped segment; the creator (sender) also owns unlinking on aborted
// handshakes — after a successful handshake the receiver has unlinked the
// name and the mapping is the only reference.
struct ShmSeg {
  ShmSegHdr* hdr = nullptr;
  uint8_t* ring = nullptr;
  size_t ring_bytes = 0;
  size_t map_bytes = 0;

  ~ShmSeg() { Release(); }
  void Release() {
    if (hdr != nullptr) ::munmap(hdr, map_bytes);
    hdr = nullptr;
    ring = nullptr;
    ring_bytes = 0;
    map_bytes = 0;
  }
  uint64_t avail() const {
    return hdr->head.load(std::memory_order_acquire) -
           hdr->tail.load(std::memory_order_acquire);
  }
  uint64_t free_bytes() const { return ring_bytes - avail(); }

  // Wrap-aware copy in/out at a free-running cursor.
  void CopyIn(uint64_t at, const uint8_t* src, size_t n) {
    size_t off = static_cast<size_t>(at % ring_bytes);
    size_t first = std::min(n, ring_bytes - off);
    memcpy(ring + off, src, first);
    if (n > first) memcpy(ring, src + first, n - first);
  }
  void CopyOut(uint64_t at, uint8_t* dst, size_t n) {
    size_t off = static_cast<size_t>(at % ring_bytes);
    size_t first = std::min(n, ring_bytes - off);
    memcpy(dst, ring + off, first);
    if (n > first) memcpy(dst + first, ring, n - first);
  }
  uint8_t ByteAt(uint64_t at) const {
    return ring[static_cast<size_t>(at % ring_bytes)];
  }
  void SetByteAt(uint64_t at, uint8_t v) {
    ring[static_cast<size_t>(at % ring_bytes)] = v;
  }

  void Publish(uint64_t new_head) {
    hdr->head.store(new_head, std::memory_order_release);
    hdr->data_seq.fetch_add(1, std::memory_order_release);
    if (hdr->data_waiters.load(std::memory_order_acquire) != 0) {
      FutexWakeAll(&hdr->data_seq);
    }
  }
  void Consume(uint64_t new_tail) {
    hdr->tail.store(new_tail, std::memory_order_release);
    hdr->space_seq.fetch_add(1, std::memory_order_release);
    if (hdr->space_waiters.load(std::memory_order_acquire) != 0) {
      FutexWakeAll(&hdr->space_seq);
    }
  }
  void MarkState(uint32_t st) {
    uint32_t cur = hdr->state.load(std::memory_order_acquire);
    // closed is terminal; failover never downgrades it.
    while (cur < st && !hdr->state.compare_exchange_weak(
                           cur, st, std::memory_order_acq_rel)) {
    }
    FutexWakeAll(&hdr->data_seq);
    FutexWakeAll(&hdr->space_seq);
  }
  uint32_t State() const { return hdr->state.load(std::memory_order_acquire); }
};

struct ShmMsg {
  uint8_t* data = nullptr;
  size_t len = 0;
  RequestPtr state;
};

// Blocking FIFO identical in spirit to the BASIC engine's Queue.
class ShmQueue {
 public:
  bool Push(ShmMsg m) {
    {
      MutexLock lk(mu_);
      if (closed_) return false;
      q_.push_back(std::move(m));
    }
    cv_.NotifyOne();
    return true;
  }
  bool Pop(ShmMsg* out) {
    MutexLock lk(mu_);
    while (!closed_ && q_.empty()) cv_.Wait(mu_);
    if (q_.empty()) return false;
    *out = std::move(q_.front());
    q_.pop_front();
    return true;
  }
  // Nonblocking pop (the pre-verdict phase multiplexes the queue against
  // the handshake-ack socket, so it cannot park in Pop).
  bool TryPop(ShmMsg* out) {
    MutexLock lk(mu_);
    if (q_.empty()) return false;
    *out = std::move(q_.front());
    q_.pop_front();
    return true;
  }
  void Close() {
    {
      MutexLock lk(mu_);
      closed_ = true;
    }
    cv_.NotifyAll();
  }

 private:
  Mutex mu_;  // leaf
  CondVar cv_;
  std::deque<ShmMsg> q_ GUARDED_BY(mu_);
  bool closed_ GUARDED_BY(mu_) = false;
};

// One direction of a same-host pair: ctrl TCP connection + the ring. The
// single scheduler thread owns ALL ctrl and ring IO for its side, so LEN
// frames, failover markers, and chunk payloads are trivially totally
// ordered — no fo_mu/ctrl_mu machinery is needed.
struct ShmComm {
  bool is_send = false;
  int ctrl_fd = -1;
  size_t chunk = 1 << 20;  // derived from (min_chunksize, ring) on BOTH sides
  bool crc = false;
  TrafficClass cls = TrafficClass::kBulk;
  ShmSeg seg;
  ShmQueue msgs;
  std::unique_ptr<std::thread> scheduler;
  std::atomic<bool> aborted{false};
  bool shm_failed = false;  // scheduler-thread-private: ring failed over /
                            // negotiated ctrl-TCP mode (nacked handshake)
  // Send side: the receiver's 1-byte handshake ack is consumed by the
  // scheduler thread (never by connect() — see the file header on why).
  // Until it arrives, messages complete OPTIMISTICALLY into the ring with
  // their LEN frames deferred (a send must complete without any peer
  // participation — the TCP kernel-buffer property the collectives'
  // connect-all-then-accept-all wiring depends on; the ring plays the
  // kernel buffer's role). The verdict then either flushes the deferred
  // LEN frames (ack: receiver drains the ring) or replays the ring content
  // interleaved with them over ctrl (nack: ctrl-TCP mode). seg_name is
  // kept so a nack can unlink the segment the receiver never opened.
  bool await_ack = false;
  std::string seg_name;
  struct Deferred {
    uint64_t len = 0;         // message length (the deferred LEN frame)
    uint64_t ring_start = 0;  // chunk-stream extent in ring cumulative bytes
    uint64_t ring_end = 0;
  };
  std::vector<Deferred> deferred;  // scheduler-thread-private
  const uint64_t fork_gen = ForkGeneration();

  const std::atomic<bool>* aborted_flag() const { return &aborted; }

  // Socket-shutdown analogue: poison the segment AND the ctrl connection so
  // both sides' parked waits (futex slices, blocking ctrl reads) fail fast.
  void Abort() {
    if (aborted.exchange(true)) return;
    if (seg.hdr != nullptr) seg.MarkState(kSegClosed);
    if (ctrl_fd >= 0) ::shutdown(ctrl_fd, SHUT_RDWR);
  }

  ~ShmComm() { Shutdown(); }

  void Shutdown() {
    if (shut_) return;
    shut_ = true;
    if (ForkGeneration() != fork_gen) {
      // Forked child: the scheduler pthread never existed here — leak the
      // stale handle (any pthread call on it is UB) and only close fds.
      (void)scheduler.release();
      if (ctrl_fd >= 0) ::close(ctrl_fd);
      ctrl_fd = -1;
      return;
    }
    msgs.Close();
    Abort();
    if (scheduler && scheduler->joinable()) scheduler->join();
    if (ctrl_fd >= 0) ::close(ctrl_fd);
    ctrl_fd = -1;
    // Sender teardown backstop: a comm shut down (poison, watchdog abort,
    // plain close) before its handshake ack resolved would otherwise leak
    // the named segment in /dev/shm forever — tmpfs is RAM. Unlinking is
    // idempotent: the receiver unlinks right after mapping (ack path) and
    // the nack path unlinks in ResolveShmVerdict, so this is ENOENT noise
    // at worst.
    if (is_send && !seg_name.empty()) ::shm_unlink(seg_name.c_str());
  }

 private:
  bool shut_ = false;
};
using ShmCommPtr = std::shared_ptr<ShmComm>;

// Both sides derive the chunk size from (sender's min_chunksize, ring
// bytes) alone — like the TCP chunk map, the ring carries no per-chunk
// metadata. A chunk plus its CRC trailer must fit in half the ring so the
// producer can stay a full chunk ahead of the consumer.
size_t ShmChunkBytes(size_t min_chunksize, size_t ring_bytes) {
  size_t cap = ring_bytes / 2 > 8 ? ring_bytes / 2 - 8 : 1;
  return std::max<size_t>(1, std::min(min_chunksize, cap));
}

// Peer-death probe on the ctrl connection, run inside futex wait slices. A
// ctrl EOF/reset means the peer process is gone — the one condition a
// memory ring cannot observe on its own. Readable DATA is normal (pipelined
// LEN frames on the recv side) and not a verdict.
bool CtrlPeerDead(int fd) {
  char b;
  ssize_t r = ::recv(fd, &b, 1, MSG_PEEK | MSG_DONTWAIT);
  if (r == 0) return true;
  if (r < 0 && errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR) return true;
  return false;
}

void FailShmMsg(ShmComm* c, const RequestPtr& state, ErrorKind kind,
                const std::string& msg) {
  state->SetError(kind, msg);
  state->completed.fetch_add(1, std::memory_order_acq_rel);
  state->NotifyIfSettled();
  (void)c;
}

// Poison: fail the current message (if any), drain + fail everything
// queued, and abort the comm.
void PoisonShm(ShmComm* c, const std::string& why) {
  c->Abort();
  c->msgs.Close();
  ShmMsg m;
  while (c->msgs.Pop(&m)) {
    FailShmMsg(c, m.state, ErrorKind::kInnerError,
               "comm broken by earlier error: " + why);
  }
}

// ---------------------------------------------------------------------------
// Send side.

// Wait for `need` bytes of ring space. kOk on success; error status when the
// comm aborted / peer died / segment closed. state==kSegFailover cannot
// happen here (only the sender sets it, and then stops calling this).
Status WaitRingSpace(ShmComm* c, uint64_t need) {
  while (true) {
    if (c->aborted.load(std::memory_order_acquire) ||
        c->seg.State() == kSegClosed) {
      return Status::IO("shm segment closed");
    }
    if (c->seg.free_bytes() >= need) return Status::Ok();
    c->seg.hdr->space_waiters.fetch_add(1, std::memory_order_acq_rel);
    uint32_t s = c->seg.hdr->space_seq.load(std::memory_order_acquire);
    if (c->seg.free_bytes() < need && c->seg.State() == kSegLive &&
        !c->aborted.load(std::memory_order_acquire)) {
      FutexWait(&c->seg.hdr->space_seq, s, 100);
    }
    c->seg.hdr->space_waiters.fetch_sub(1, std::memory_order_acq_rel);
    // Progress first, verdicts second: a consumer that frees the space and
    // THEN closes (orderly teardown) must not read as a death.
    if (c->seg.free_bytes() >= need) return Status::Ok();
    if (CtrlPeerDead(c->ctrl_fd)) {
      return Status::IO("shm peer died (ctrl connection reset mid-transfer)");
    }
  }
}

// One chunk over the ctrl TCP connection (post-failover path, both the
// marker batch and later messages). Wire layout matches a TCP data chunk:
// [payload | crc32c?] — the PR-1 retransmit framing without the seq/len
// header (chunk boundaries are deterministic on both sides).
Status SendChunkCtrl(ShmComm* c, const uint8_t* data, size_t n, bool corrupt) {
  if (!corrupt) {
    if (!c->crc) return WriteAll(c->ctrl_fd, data, n);
    uint8_t crcb[4];
    EncodeU32BE(Crc32c(data, n), crcb);
    struct iovec iov[2] = {{const_cast<uint8_t*>(data), n}, {crcb, sizeof(crcb)}};
    return WritevAll(c->ctrl_fd, iov, 2);
  }
  std::vector<uint8_t> dup(data, data + n);
  if (!dup.empty()) dup[dup.size() / 2] ^= 0x01;
  if (!c->crc) return WriteAll(c->ctrl_fd, dup.data(), dup.size());
  uint8_t crcb[4];
  EncodeU32BE(Crc32c(data, n), crcb);  // CRC over the ORIGINAL bytes
  struct iovec iov[2] = {{dup.data(), dup.size()}, {crcb, sizeof(crcb)}};
  return WritevAll(c->ctrl_fd, iov, 2);
}

// One message, sender side: LEN frame on ctrl, then chunks through the ring
// (or ctrl after a segment failover). Completion accounting is simple by
// construction: the scheduler is the only worker, so the request completes
// exactly when this returns.
Status SendOneShmMsg(ShmComm* c, const ShmMsg& m) {
  QosScheduler& qos = QosScheduler::Get();
  const bool gated = qos.wire_gate_enabled();
  uint8_t hdr8[8];
  EncodeU64BE(m.len, hdr8);
  Status s = WriteAll(c->ctrl_fd, hdr8, sizeof(hdr8));
  if (!s.ok()) return s;
  size_t nchunks = ChunkCount(m.len, c->chunk);
  size_t off = 0;
  for (size_t i = 0; i < nchunks; ++i) {
    size_t n = std::min(c->chunk, m.len - off);
    size_t wire_len = n + (c->crc ? 4 : 0);
    // Memory-transport fault gate (close/stall are RETURNED for us to
    // apply — there is no fd to shut down). Disarmed cost: one relaxed load.
    FaultAction fa = g_fault_armed.load(std::memory_order_relaxed) == 0
                         ? FaultAction::kNone
                         : FaultPreMem(true, 0, n);
    if (fa == FaultAction::kStall) {
      // Live-but-stuck: park until disarm or abort — exactly what the
      // progress watchdog exists to catch.
      while (g_fault_armed.load(std::memory_order_acquire) != 0 &&
             !c->aborted.load(std::memory_order_acquire)) {
        std::this_thread::sleep_for(std::chrono::milliseconds(5));
      }
      if (c->aborted.load(std::memory_order_acquire)) {
        return Status::IO("comm aborted during injected stall");
      }
      fa = FaultAction::kNone;
    }
    if (fa == FaultAction::kClose && c->shm_failed) {
      // Already on the ctrl path (post-failover or negotiated ctrl-TCP
      // mode): losing it is last-stream loss — poison, like the socket
      // engines' verdict.
      ::shutdown(c->ctrl_fd, SHUT_RDWR);
      return Status::IO("injected close on the shm comm's last (ctrl) path");
    }
    if (fa == FaultAction::kClose && !c->shm_failed) {
      // Segment loss: fail over to the ctrl TCP connection. Chunks [0, i)
      // of THIS message are fully in the ring (the consumer drains them
      // from shared memory unharmed); the 0xFE marker tells the receiver
      // the first chunk index that rides ctrl instead. Later messages go
      // all-ctrl. Same containment counter as a TCP stream failover.
      c->seg.MarkState(kSegFailover);
      uint8_t fr[8];
      EncodeU64BE(PackCtrlFrame(kCtrlFrameFailover, 0, i), fr);
      s = WriteAll(c->ctrl_fd, fr, sizeof(fr));
      if (!s.ok()) return s;
      c->shm_failed = true;
      Telemetry::Get().OnStreamFailover();
    }
    bool corrupt = fa == FaultAction::kCorrupt;
    if (gated && !qos.AcquireWire(c->cls, wire_len, c->aborted_flag())) {
      return Status::IO("comm aborted while awaiting QoS wire credit");
    }
    m.state->MarkWireStart(MonotonicUs());
    if (c->shm_failed) {
      s = SendChunkCtrl(c, m.data + off, n, corrupt);
      if (gated) qos.ReleaseWire(c->cls, wire_len);
      if (!s.ok()) return s;
      Telemetry::Get().OnStreamBytes(true, 0, n, static_cast<int>(c->cls));
    } else {
      s = WaitRingSpace(c, wire_len);
      if (!s.ok()) {
        if (gated) qos.ReleaseWire(c->cls, wire_len);
        return s;
      }
      uint64_t head = c->seg.hdr->head.load(std::memory_order_relaxed);
      c->seg.CopyIn(head, m.data + off, n);
      if (corrupt && n > 0) {
        // Damage the RING copy, never the caller's buffer; the trailer is
        // computed over the original bytes so TPUNET_CRC=1 catches it.
        c->seg.SetByteAt(head + n / 2, c->seg.ByteAt(head + n / 2) ^ 0x01);
      }
      if (c->crc) {
        uint8_t crcb[4];
        EncodeU32BE(Crc32c(m.data + off, n), crcb);
        c->seg.CopyIn(head + n, crcb, 4);
      }
      c->seg.Publish(head + wire_len);
      if (gated) qos.ReleaseWire(c->cls, wire_len);
      Telemetry::Get().OnShmBytes(true, n);
    }
    m.state->nbytes.fetch_add(n, std::memory_order_relaxed);
    m.state->MarkWireEnd(MonotonicUs());
    off += n;
  }
  return Status::Ok();
}

// Pre-verdict send: the whole message goes into the ring (its LEN frame is
// deferred), so completion needs no peer participation — the property the
// connect-all-then-accept-all wiring layers depend on. Returns with
// *needs_verdict set (and the message untouched) when the ring cannot hold
// it; the caller then blocks for the ack first (only the verdict can make
// room: ack → the receiver drains, nack → ctrl replay).
Status SendPreAckMsg(ShmComm* c, const ShmMsg& m, bool* needs_verdict) {
  *needs_verdict = false;
  size_t nchunks = ChunkCount(m.len, c->chunk);
  uint64_t wire_total = m.len + (c->crc ? 4 * nchunks : 0);
  if (wire_total > c->seg.free_bytes()) {
    *needs_verdict = true;
    return Status::Ok();
  }
  ShmComm::Deferred d;
  d.len = m.len;
  d.ring_start = c->seg.hdr->head.load(std::memory_order_relaxed);
  size_t off = 0;
  for (size_t i = 0; i < nchunks; ++i) {
    size_t n = std::min(c->chunk, m.len - off);
    FaultAction fa = g_fault_armed.load(std::memory_order_relaxed) == 0
                         ? FaultAction::kNone
                         : FaultPreMem(true, 0, n);
    if (fa == FaultAction::kStall) {
      while (g_fault_armed.load(std::memory_order_acquire) != 0 &&
             !c->aborted.load(std::memory_order_acquire)) {
        std::this_thread::sleep_for(std::chrono::milliseconds(5));
      }
      if (c->aborted.load(std::memory_order_acquire)) {
        return Status::IO("comm aborted during injected stall");
      }
      fa = FaultAction::kNone;
    }
    if (fa == FaultAction::kClose) {
      // No failover target exists before the verdict (the ctrl path's
      // framing depends on it) — poison, the pre-wiring corner chaos
      // matrices don't exercise.
      return Status::IO("injected close on shm segment before handshake ack");
    }
    uint64_t head = c->seg.hdr->head.load(std::memory_order_relaxed);
    c->seg.CopyIn(head, m.data + off, n);
    if (fa == FaultAction::kCorrupt && n > 0) {
      c->seg.SetByteAt(head + n / 2, c->seg.ByteAt(head + n / 2) ^ 0x01);
    }
    if (c->crc) {
      uint8_t crcb[4];
      EncodeU32BE(Crc32c(m.data + off, n), crcb);
      c->seg.CopyIn(head + n, crcb, 4);
    }
    c->seg.Publish(head + n + (c->crc ? 4 : 0));
    m.state->MarkWireStart(MonotonicUs());
    m.state->nbytes.fetch_add(n, std::memory_order_relaxed);
    m.state->MarkWireEnd(MonotonicUs());
    off += n;
  }
  d.ring_end = c->seg.hdr->head.load(std::memory_order_relaxed);
  c->deferred.push_back(d);
  return Status::Ok();
}

// Apply the handshake verdict: flush the deferred LEN frames (ack — the
// ring content is live, byte accounting lands on the SHM counters), or
// replay [LEN | ring chunk stream] per deferred message over ctrl and drop
// the segment (nack — ctrl-TCP mode; the bytes were TCP bytes after all).
Status ResolveShmVerdict(ShmComm* c, uint8_t ack) {
  Status s;
  if (ack == 1) {
    for (const ShmComm::Deferred& d : c->deferred) {
      uint8_t hdr8[8];
      EncodeU64BE(d.len, hdr8);
      s = WriteAll(c->ctrl_fd, hdr8, sizeof(hdr8));
      if (!s.ok()) return s;
      Telemetry::Get().OnShmBytes(true, d.len);
    }
    c->deferred.clear();
    return Status::Ok();
  }
  // Nack: negotiation, not a failure — no failover counter. The receiver
  // never opened the segment, so the name is ours to unlink.
  uint8_t buf[64 << 10];
  for (const ShmComm::Deferred& d : c->deferred) {
    uint8_t hdr8[8];
    EncodeU64BE(d.len, hdr8);
    s = WriteAll(c->ctrl_fd, hdr8, sizeof(hdr8));
    if (!s.ok()) return s;
    for (uint64_t at = d.ring_start; at < d.ring_end;) {
      size_t n = static_cast<size_t>(
          std::min<uint64_t>(sizeof(buf), d.ring_end - at));
      c->seg.CopyOut(at, buf, n);
      s = WriteAll(c->ctrl_fd, buf, n);
      if (!s.ok()) return s;
      at += n;
    }
    Telemetry::Get().OnStreamBytes(true, 0, d.len, static_cast<int>(c->cls));
  }
  c->deferred.clear();
  ::shm_unlink(c->seg_name.c_str());
  c->seg.Release();
  c->shm_failed = true;
  return Status::Ok();
}

// Multiplex the pre-verdict phase: serve queued sends into the ring while
// watching the ctrl socket for the receiver's 1-byte ack. `block` demands a
// resolution (ring full / queue drained into it) — the poll then parks until
// the ack (or peer death) arrives.
Status AwaitAckStep(ShmComm* c, bool block, bool* resolved) {
  *resolved = false;
  struct pollfd pfd = {c->ctrl_fd, POLLIN, 0};
  int pr = ::poll(&pfd, 1, block ? 20 : 0);
  if (pr < 0 && errno != EINTR) {
    return Status::IO("ctrl poll failed awaiting shm handshake ack");
  }
  if (pr <= 0) return Status::Ok();
  uint8_t ack = 0;
  Status s = ReadExact(c->ctrl_fd, &ack, 1);
  if (!s.ok()) return Status::IO("shm handshake ack never arrived: " + s.msg);
  s = ResolveShmVerdict(c, ack);
  if (!s.ok()) return s;
  *resolved = true;
  return Status::Ok();
}

void ShmSendLoop(ShmComm* c) {
  // Phase 1 (handshake pending): optimistic ring sends + ack multiplexing.
  Status ps = Status::Ok();
  while (c->await_ack) {
    bool resolved = false;
    ps = AwaitAckStep(c, /*block=*/false, &resolved);
    if (!ps.ok()) break;
    if (resolved) {
      c->await_ack = false;
      break;
    }
    if (c->aborted.load(std::memory_order_acquire)) {
      ps = Status::IO("comm aborted awaiting shm handshake ack");
      break;
    }
    ShmMsg m;
    if (c->msgs.TryPop(&m)) {
      bool needs_verdict = false;
      ps = SendPreAckMsg(c, m, &needs_verdict);
      if (ps.ok() && needs_verdict) {
        // Ring cannot hold it: park for the verdict, then send normally.
        while (ps.ok() && !resolved &&
               !c->aborted.load(std::memory_order_acquire)) {
          ps = AwaitAckStep(c, /*block=*/true, &resolved);
        }
        if (ps.ok() && resolved) {
          c->await_ack = false;
          ps = SendOneShmMsg(c, m);
        } else if (ps.ok()) {
          ps = Status::IO("comm aborted awaiting shm handshake ack");
        }
      }
      if (!ps.ok()) {
        FailShmMsg(c, m.state, ps.kind, ps.msg);
        break;
      }
      m.state->completed.fetch_add(1, std::memory_order_acq_rel);
      m.state->NotifyIfSettled();
    } else {
      bool r2 = false;
      ps = AwaitAckStep(c, /*block=*/true, &r2);
      if (ps.ok() && r2) c->await_ack = false;
    }
  }
  if (!ps.ok()) {
    PoisonShm(c, ps.msg);
    return;
  }
  // Phase 2: the steady-state loop.
  ShmMsg m;
  while (c->msgs.Pop(&m)) {
    Status s = SendOneShmMsg(c, m);
    if (!s.ok()) {
      FailShmMsg(c, m.state, s.kind, s.msg);
      PoisonShm(c, s.msg);
      return;
    }
    m.state->completed.fetch_add(1, std::memory_order_acq_rel);
    m.state->NotifyIfSettled();
  }
}

// ---------------------------------------------------------------------------
// Recv side.

// Wait until `need` ring bytes are available, watching for the sender's
// failover signal and peer death. *failover is set when the ring went into
// failover before producing these bytes — the caller reads the 0xFE marker
// from ctrl and switches.
Status WaitRingData(ShmComm* c, uint64_t need, bool* failover) {
  *failover = false;
  while (true) {
    if (c->seg.avail() >= need) return Status::Ok();
    if (c->aborted.load(std::memory_order_acquire) ||
        c->seg.State() == kSegClosed) {
      return Status::IO("shm segment closed");
    }
    if (c->seg.State() == kSegFailover) {
      // The sender stopped producing; everything it DID produce has been
      // consumed (chunks are published whole, so a shortfall here means
      // the missing chunk was never written).
      *failover = true;
      return Status::Ok();
    }
    c->seg.hdr->data_waiters.fetch_add(1, std::memory_order_acq_rel);
    uint32_t s = c->seg.hdr->data_seq.load(std::memory_order_acquire);
    if (c->seg.avail() < need && c->seg.State() == kSegLive &&
        !c->aborted.load(std::memory_order_acquire)) {
      FutexWait(&c->seg.hdr->data_seq, s, 100);
    }
    c->seg.hdr->data_waiters.fetch_sub(1, std::memory_order_acq_rel);
    // Progress first, verdicts second: a producer that publishes the final
    // chunks and THEN closes (orderly teardown — its requests all tested
    // done, the NCCL contract) must not read as a death; the ring bytes
    // outlive its ctrl FIN exactly like kernel socket buffers do.
    if (c->seg.avail() >= need) return Status::Ok();
    if (CtrlPeerDead(c->ctrl_fd)) {
      return Status::IO("shm peer died (ctrl connection reset mid-transfer)");
    }
  }
}

Status RecvChunkCtrl(ShmComm* c, uint8_t* data, size_t n, uint32_t* wire_crc) {
  if (!c->crc) return ReadExact(c->ctrl_fd, data, n);
  uint8_t crcb[4];
  struct iovec iov[2] = {{data, n}, {crcb, sizeof(crcb)}};
  Status s = ReadvExact(c->ctrl_fd, iov, 2);
  if (s.ok()) *wire_crc = DecodeU32BE(crcb);
  return s;
}

Status RecvOneShmMsg(ShmComm* c, const ShmMsg& m) {
  uint8_t hdr8[8];
  Status s = ReadExact(c->ctrl_fd, hdr8, sizeof(hdr8));
  if (!s.ok()) return s;
  uint64_t target = DecodeU64BE(hdr8);
  if (target >= kMaxCtrlLen) {
    return Status::Inner("bogus shm ctrl frame — peer desynchronized");
  }
  if (target > m.len) {
    return Status::Inner("incoming message (" + std::to_string(target) +
                         "B) exceeds posted recv buffer (" +
                         std::to_string(m.len) + "B)");
  }
  size_t len = static_cast<size_t>(target);
  size_t nchunks = ChunkCount(len, c->chunk);
  size_t off = 0;
  for (size_t i = 0; i < nchunks; ++i) {
    size_t n = std::min(c->chunk, len - off);
    size_t wire_len = n + (c->crc ? 4 : 0);
    FaultAction fa = g_fault_armed.load(std::memory_order_relaxed) == 0
                         ? FaultAction::kNone
                         : FaultPreMem(false, 0, n);
    if (fa == FaultAction::kStall) {
      while (g_fault_armed.load(std::memory_order_acquire) != 0 &&
             !c->aborted.load(std::memory_order_acquire)) {
        std::this_thread::sleep_for(std::chrono::milliseconds(5));
      }
      if (c->aborted.load(std::memory_order_acquire)) {
        return Status::IO("comm aborted during injected stall");
      }
      fa = FaultAction::kNone;
    }
    if (fa == FaultAction::kClose) {
      // Receiver-side segment loss has no failover lever (the sender drives
      // the ring) — poison, the socket engines' last-stream verdict.
      return Status::IO("injected close on shm segment (receive side)");
    }
    uint32_t wire_crc = 0;
    bool from_ring = !c->shm_failed;
    if (from_ring) {
      bool failover = false;
      s = WaitRingData(c, wire_len, &failover);
      if (!s.ok()) return s;
      if (failover) {
        // The 0xFE marker names the first chunk index riding ctrl; chunks
        // before it were fully published (and already consumed above).
        uint8_t fr[8];
        s = ReadExact(c->ctrl_fd, fr, sizeof(fr));
        if (!s.ok()) return s;
        uint64_t frame = DecodeU64BE(fr);
        if ((frame >> 56) != kCtrlFrameFailover ||
            (frame & 0xffffffffffffull) != i) {
          return Status::Inner(
              "shm failover marker mismatch (protocol desync)");
        }
        c->shm_failed = true;
        from_ring = false;
      }
    }
    m.state->MarkWireStart(MonotonicUs());
    if (from_ring) {
      uint64_t tail = c->seg.hdr->tail.load(std::memory_order_relaxed);
      c->seg.CopyOut(tail, m.data + off, n);
      if (c->crc) {
        uint8_t crcb[4];
        c->seg.CopyOut(tail + n, crcb, 4);
        wire_crc = DecodeU32BE(crcb);
      }
      c->seg.Consume(tail + wire_len);
    } else {
      s = RecvChunkCtrl(c, m.data + off, n, &wire_crc);
      if (!s.ok()) return s;
    }
    if (fa == FaultAction::kCorrupt && n > 0) {
      m.data[off + n / 2] ^= 0x01;  // wire damage before verification
    }
    if (c->crc && wire_crc != Crc32c(m.data + off, n)) {
      // Integrity failure is a REQUEST error, not a disconnect: the chunk
      // framing is intact (exactly chunk+trailer was consumed), so the
      // comm keeps working for subsequent messages — the socket engines'
      // contract, preserved on the ring.
      Telemetry::Get().OnCrcError();
      m.state->SetError(ErrorKind::kCorruption,
                        "CRC32C mismatch on shm segment: payload corrupted "
                        "in transit");
    } else if (from_ring) {
      Telemetry::Get().OnShmBytes(false, n);
    } else {
      Telemetry::Get().OnStreamBytes(false, 0, n, static_cast<int>(c->cls));
    }
    m.state->nbytes.fetch_add(n, std::memory_order_relaxed);
    m.state->MarkWireEnd(MonotonicUs());
    off += n;
  }
  return Status::Ok();
}

void ShmRecvLoop(ShmComm* c) {
  ShmMsg m;
  while (c->msgs.Pop(&m)) {
    Status s = RecvOneShmMsg(c, m);
    if (!s.ok()) {
      FailShmMsg(c, m.state, s.kind, s.msg);
      PoisonShm(c, s.msg);
      return;
    }
    m.state->completed.fetch_add(1, std::memory_order_acq_rel);
    m.state->NotifyIfSettled();
  }
}

// ---------------------------------------------------------------------------
// Engine.

// Every address this host owns (including loopback): the connect-side
// locality test. The final verdict is the handshake's host-id comparison —
// this set only decides whether attempting the handshake is worth a
// connection (NAT'd or routed handles that LOOK local get nacked there).
std::set<std::string> LocalAddressSet() {
  std::set<std::string> out;
  struct ifaddrs* ifa = nullptr;
  if (getifaddrs(&ifa) != 0) return out;
  for (struct ifaddrs* p = ifa; p != nullptr; p = p->ifa_next) {
    if (p->ifa_addr == nullptr) continue;
    int fam = p->ifa_addr->sa_family;
    if (fam != AF_INET && fam != AF_INET6) continue;
    sockaddr_storage ss = {};
    memcpy(&ss, p->ifa_addr,
           fam == AF_INET ? sizeof(sockaddr_in) : sizeof(sockaddr_in6));
    out.insert(SockaddrToString(ss, AddrLenForFamily(ss)));
  }
  freeifaddrs(ifa);
  return out;
}

std::string AddrOnly(const sockaddr_storage& ss) {
  // SockaddrToString prints host:port; strip the port so listener handles
  // (ephemeral ports) compare against interface addresses (port 0).
  std::string s = SockaddrToString(ss, AddrLenForFamily(ss));
  size_t colon = s.rfind(':');
  return colon == std::string::npos ? s : s.substr(0, colon);
}

// Inner-engine ids are tagged with this bit in the ids we hand out, so every
// call dispatches to the right owner without a lookup table.
constexpr uint64_t kInnerIdBit = 1ull << 62;

class ShmEngine : public EngineBase {
 public:
  explicit ShmEngine(std::unique_ptr<Net> inner)
      : inner_(std::move(inner)),
        adopter_(dynamic_cast<BundleAdopter*>(inner_.get())),
        ring_bytes_(GetEnvU64("TPUNET_SHM_RING_BYTES", 8 << 20)) {
    if (ring_bytes_ < (64 << 10)) ring_bytes_ = 64 << 10;
    if (ring_bytes_ > (1ull << 30)) ring_bytes_ = 1ull << 30;
    for (const std::string& a : LocalAddressSet()) {
      size_t colon = a.rfind(':');
      local_addrs_.insert(colon == std::string::npos ? a : a.substr(0, colon));
    }
  }

  ~ShmEngine() override {
    for (auto& c : send_comms_.DrainAll()) c->Shutdown();
    for (auto& c : recv_comms_.DrainAll()) c->Shutdown();
    WakeAllListens();
  }

  void set_traffic_class(int32_t cls) override {
    EngineBase::set_traffic_class(cls);
    inner_->set_traffic_class(cls);  // inner connects carry the class too
  }

  Status connect(int32_t dev, const SocketHandle& handle, uint64_t* send_comm) override {
    Status sdev = CheckDev(dev);
    if (!sdev.ok()) return sdev;
    if (adopter_ == nullptr || local_addrs_.count(AddrOnly(handle.addr)) == 0) {
      return InnerConnect(dev, handle, send_comm);
    }
    // SHM attempt: one preamble'd connection (nstreams=0 + the SHM flag)
    // that becomes the comm's ctrl stream, then the segment handshake on
    // it. ANY nack or handshake failure falls back to plain TCP — locality
    // looked right but the peer knows better (fake-host split, TPUNET_SHM
    // disabled remotely is a config error caught elsewhere).
    std::vector<int> data_fds;
    int ctrl_fd = -1;
    Status s = ConnectBundle(nics_, dev, handle, 0, min_chunksize_,
                             PreambleFlags() | kPreambleFlagShm, &data_fds, &ctrl_fd);
    if (!s.ok()) return InnerConnect(dev, handle, send_comm);
    std::string name = "/tpunet-" + std::to_string(::getpid()) + "-" +
                       std::to_string(next_id_.fetch_add(1)) + "-" +
                       std::to_string(RandomBundleId() & 0xffffff);
    auto comm = std::make_shared<ShmComm>();
    comm->is_send = true;
    comm->ctrl_fd = ctrl_fd;
    comm->crc = crc_;
    comm->cls = static_cast<TrafficClass>(traffic_class());
    comm->chunk = ShmChunkBytes(min_chunksize_, ring_bytes_);
    s = CreateSegment(name, comm->crc, &comm->seg);
    if (!s.ok()) {
      ::close(ctrl_fd);
      comm->ctrl_fd = -1;
      return InnerConnect(dev, handle, send_comm);
    }
    // Hello: [host_id u64 | ring_bytes u64 | name_len u64 | name]. The ack
    // comes back ASYNCHRONOUSLY (read by the scheduler thread) — a connect
    // must not require the peer to be inside accept() already, or the
    // collectives' connect-all-then-accept-all wiring would deadlock.
    std::vector<uint8_t> hello(24 + name.size());
    EncodeU64BE(HostId(), hello.data());
    EncodeU64BE(ring_bytes_, hello.data() + 8);
    EncodeU64BE(name.size(), hello.data() + 16);
    memcpy(hello.data() + 24, name.data(), name.size());
    s = WriteAll(ctrl_fd, hello.data(), hello.size());
    if (!s.ok()) {
      ::shm_unlink(name.c_str());
      ::close(ctrl_fd);
      comm->ctrl_fd = -1;
      return InnerConnect(dev, handle, send_comm);
    }
    comm->await_ack = true;
    comm->seg_name = name;
    comm->scheduler = std::make_unique<std::thread>(ShmSendLoop, comm.get());
    uint64_t id = next_id_.fetch_add(1);
    send_comms_.Put(id, comm);
    *send_comm = id;
    return Status::Ok();
  }

  Status accept(uint64_t listen_comm, uint64_t* recv_comm) override {
    while (true) {
      PartialBundle b;
      Status s = AcceptBundleOn(listen_comm, &b);
      if (!s.ok()) return s;
      if ((b.flags & kPreambleFlagShm) == 0) {
        if (adopter_ == nullptr) {
          b.CloseAll();
          return Status::Inner("inner engine cannot adopt TCP bundles");
        }
        uint64_t inner_id = 0;
        s = adopter_->AdoptBundle(b, &inner_id);
        if (!s.ok()) return s;
        *recv_comm = inner_id | kInnerIdBit;
        return Status::Ok();
      }
      // SHM hello on our listener. A nack (host mismatch, bad segment)
      // keeps accepting — the sender redials over TCP and that bundle
      // lands here next.
      int fd = b.ctrl_fd;
      b.ctrl_fd = -1;
      b.CloseAll();
      int hs_ms = static_cast<int>(GetEnvU64("TPUNET_HANDSHAKE_TIMEOUT_MS", 10000));
      uint8_t hdr24[24];
      s = ReadExactDeadline(fd, hdr24, sizeof(hdr24), hs_ms);
      if (!s.ok()) {
        ::close(fd);
        continue;
      }
      uint64_t peer_host = DecodeU64BE(hdr24);
      uint64_t ring_bytes = DecodeU64BE(hdr24 + 8);
      uint64_t name_len = DecodeU64BE(hdr24 + 16);
      if (name_len == 0 || name_len > 255) {
        ::close(fd);
        continue;
      }
      std::string name(name_len, '\0');
      s = ReadExactDeadline(fd, &name[0], name_len, hs_ms);
      if (!s.ok()) {
        ::close(fd);
        continue;
      }
      auto comm = std::make_shared<ShmComm>();
      uint8_t ack = 0;
      if (peer_host == HostId() &&
          MapSegment(name, ring_bytes, &comm->seg).ok()) {
        ack = 1;
      }
      Status ws = WriteAll(fd, &ack, 1);
      if (!ws.ok()) {
        ::close(fd);
        continue;  // peer died mid-handshake; keep serving the listener
      }
      comm->is_send = false;
      comm->ctrl_fd = fd;
      // Nacked (fake-host split / unmappable segment): both sides run the
      // comm in ctrl-TCP mode from byte zero — the transparent fallback the
      // forced-split tests exercise. The sender unlinks the segment.
      comm->shm_failed = ack != 1;
      // Sender's chunk-map inputs win, like the TCP preamble contract
      // (its CRC flag and min_chunksize ride the preamble; the ring size
      // rode the hello), so both modes derive identical chunk geometry.
      comm->crc = (b.flags & kPreambleFlagCrc) != 0;
      comm->cls = static_cast<TrafficClass>(PreambleClassOf(b.flags));
      comm->chunk = ShmChunkBytes(b.min_chunksize, static_cast<size_t>(ring_bytes));
      comm->scheduler = std::make_unique<std::thread>(ShmRecvLoop, comm.get());
      uint64_t id = next_id_.fetch_add(1);
      recv_comms_.Put(id, comm);
      *recv_comm = id;
      return Status::Ok();
    }
  }

  Status isend(uint64_t send_comm, const void* data, size_t nbytes, uint64_t* request) override {
    if (send_comm & kInnerIdBit) {
      Status s = inner_->isend(send_comm & ~kInnerIdBit, data, nbytes, request);
      if (s.ok()) *request |= kInnerIdBit;
      return s;
    }
    ShmCommPtr c;
    if (!send_comms_.Get(send_comm, &c)) {
      return Status::Invalid("unknown send comm " + std::to_string(send_comm));
    }
    if (ForkGeneration() != c->fork_gen) {
      return Status::Inner("send comm created before fork(); its threads do not exist here");
    }
    uint64_t admitted = 0;
    Status as = QosScheduler::Get().AdmitMessage(c->cls, nbytes, &admitted);
    if (!as.ok()) return as;
    auto state = std::make_shared<RequestState>();
    state->qos_cls = static_cast<uint8_t>(c->cls);
    state->qos_admitted = admitted;
    state->t_post_us = MonotonicUs();
    state->total.store(1, std::memory_order_release);  // one completion unit
    ArmWatchdog(state, c);
    uint64_t id = next_id_.fetch_add(1);
    requests_.Put(id, state);
    if (!c->msgs.Push(ShmMsg{const_cast<uint8_t*>(static_cast<const uint8_t*>(data)),
                             nbytes, state})) {
      FailShmMsg(c.get(), state, ErrorKind::kInnerError, "send comm is poisoned");
    }
    *request = id;
    return Status::Ok();
  }

  Status irecv(uint64_t recv_comm, void* data, size_t nbytes, uint64_t* request) override {
    if (recv_comm & kInnerIdBit) {
      Status s = inner_->irecv(recv_comm & ~kInnerIdBit, data, nbytes, request);
      if (s.ok()) *request |= kInnerIdBit;
      return s;
    }
    ShmCommPtr c;
    if (!recv_comms_.Get(recv_comm, &c)) {
      return Status::Invalid("unknown recv comm " + std::to_string(recv_comm));
    }
    if (ForkGeneration() != c->fork_gen) {
      return Status::Inner("recv comm created before fork(); its threads do not exist here");
    }
    auto state = std::make_shared<RequestState>();
    state->t_post_us = MonotonicUs();
    state->total.store(1, std::memory_order_release);
    ArmWatchdog(state, c);
    uint64_t id = next_id_.fetch_add(1);
    requests_.Put(id, state);
    if (!c->msgs.Push(ShmMsg{static_cast<uint8_t*>(data), nbytes, state})) {
      FailShmMsg(c.get(), state, ErrorKind::kInnerError, "recv comm is poisoned");
    }
    *request = id;
    return Status::Ok();
  }

  Status test(uint64_t request, bool* done, size_t* nbytes) override {
    if (request & kInnerIdBit) return inner_->test(request & ~kInnerIdBit, done, nbytes);
    RequestPtr state;
    if (!requests_.Get(request, &state)) {
      return Status::Invalid("unknown request " + std::to_string(request));
    }
    if (state->failed.load(std::memory_order_acquire)) {
      if (!state->Done()) {
        *done = false;
        return Status::Ok();
      }
      state->ReleaseQosAdmission();
      requests_.Erase(request);
      return Status{state->ErrKind(), "request failed: " + state->ErrorMsg()};
    }
    *done = state->Done();
    if (*done) {
      if (nbytes) *nbytes = state->nbytes.load(std::memory_order_acquire);
      RecordRequestStages(state);
      state->ReleaseQosAdmission();
      requests_.Erase(request);
    }
    return Status::Ok();
  }

  Status wait(uint64_t request, size_t* nbytes) override {
    if (request & kInnerIdBit) return inner_->wait(request & ~kInnerIdBit, nbytes);
    return WaitIn(requests_, request, nbytes);
  }

  Status close_send(uint64_t send_comm) override {
    if (send_comm & kInnerIdBit) return inner_->close_send(send_comm & ~kInnerIdBit);
    ShmCommPtr c;
    if (!send_comms_.Take(send_comm, &c)) {
      return Status::Invalid("unknown send comm " + std::to_string(send_comm));
    }
    c->Shutdown();
    return Status::Ok();
  }

  Status close_recv(uint64_t recv_comm) override {
    if (recv_comm & kInnerIdBit) return inner_->close_recv(recv_comm & ~kInnerIdBit);
    ShmCommPtr c;
    if (!recv_comms_.Take(recv_comm, &c)) {
      return Status::Invalid("unknown recv comm " + std::to_string(recv_comm));
    }
    c->Shutdown();
    return Status::Ok();
  }

 private:
  Status InnerConnect(int32_t dev, const SocketHandle& handle, uint64_t* send_comm) {
    uint64_t inner_id = 0;
    Status s = inner_->connect(dev, handle, &inner_id);
    if (!s.ok()) return s;
    *send_comm = inner_id | kInnerIdBit;
    return Status::Ok();
  }

  void ArmWatchdog(const RequestPtr& state, const ShmCommPtr& c) {
    if (watchdog_ms_ == 0) return;
    std::weak_ptr<ShmComm> wc = c;
    state->on_stall = [wc] {
      if (auto p = wc.lock()) p->Abort();
    };
  }

  Status CreateSegment(const std::string& name, bool crc, ShmSeg* seg) {
    int fd = ::shm_open(name.c_str(), O_CREAT | O_EXCL | O_RDWR, 0600);
    if (fd < 0) {
      return Status::IO("shm_open(" + name + "): " + strerror(errno));
    }
    size_t total = kShmRingOffset + static_cast<size_t>(ring_bytes_);
    if (::ftruncate(fd, static_cast<off_t>(total)) != 0) {
      ::close(fd);
      ::shm_unlink(name.c_str());
      return Status::IO("ftruncate shm segment: " + std::string(strerror(errno)));
    }
    void* p = ::mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
    ::close(fd);
    if (p == MAP_FAILED) {
      ::shm_unlink(name.c_str());
      return Status::IO("mmap shm segment: " + std::string(strerror(errno)));
    }
    memset(p, 0, kShmRingOffset);
    seg->hdr = new (p) ShmSegHdr();
    seg->hdr->magic = kShmMagic;
    seg->hdr->ring_bytes = ring_bytes_;
    seg->hdr->flags = crc ? kShmHdrFlagCrc : 0;
    seg->ring = static_cast<uint8_t*>(p) + kShmRingOffset;
    seg->ring_bytes = static_cast<size_t>(ring_bytes_);
    seg->map_bytes = total;
    return Status::Ok();
  }

  Status MapSegment(const std::string& name, uint64_t ring_bytes, ShmSeg* seg) {
    if (ring_bytes < (64 << 10) || ring_bytes > (1ull << 30)) {
      return Status::Invalid("shm ring size out of range");
    }
    int fd = ::shm_open(name.c_str(), O_RDWR, 0600);
    if (fd < 0) {
      return Status::IO("shm_open(" + name + "): " + strerror(errno));
    }
    struct stat st = {};
    size_t total = kShmRingOffset + static_cast<size_t>(ring_bytes);
    if (::fstat(fd, &st) != 0 || static_cast<size_t>(st.st_size) < total) {
      ::close(fd);
      return Status::IO("shm segment smaller than advertised");
    }
    void* p = ::mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
    ::close(fd);
    if (p == MAP_FAILED) {
      return Status::IO("mmap shm segment: " + std::string(strerror(errno)));
    }
    // The name's job is done: unlink now so the segment dies with the last
    // mapping and a crashed pair never leaks /dev/shm entries.
    ::shm_unlink(name.c_str());
    seg->hdr = static_cast<ShmSegHdr*>(p);
    seg->ring = static_cast<uint8_t*>(p) + kShmRingOffset;
    seg->ring_bytes = static_cast<size_t>(ring_bytes);
    seg->map_bytes = total;
    if (seg->hdr->magic != kShmMagic || seg->hdr->ring_bytes != ring_bytes) {
      ::munmap(p, total);
      seg->hdr = nullptr;
      seg->ring = nullptr;
      return Status::IO("shm segment header mismatch");
    }
    return Status::Ok();
  }

  std::unique_ptr<Net> inner_;
  BundleAdopter* adopter_;
  uint64_t ring_bytes_;
  std::set<std::string> local_addrs_;
  IdMap<ShmCommPtr> send_comms_;
  IdMap<ShmCommPtr> recv_comms_;
  IdMap<RequestPtr> requests_;
};

}  // namespace

std::unique_ptr<Net> CreateShmEngine(std::unique_ptr<Net> inner) {
  return std::make_unique<ShmEngine>(std::move(inner));
}

}  // namespace tpunet
