// Deterministic fault injection. See fault.h for the spec grammar.
#include "fault.h"

#include <poll.h>
#include <sys/socket.h>

#include <chrono>
#include <thread>

#include "tpunet/mutex.h"
#include "tpunet/telemetry.h"
#include "tpunet/utils.h"

namespace tpunet {

std::atomic<uint32_t> g_fault_armed{0};

namespace {

// The armed slot. `g_mu` guards the spec: arm/disarm swap it under the
// lock, and FaultPreIO copies it under the lock too. (It used to read the
// plain fields through a release/acquire handshake on g_fault_armed — a
// pattern the thread-safety analysis cannot express and tsan flagged as a
// race whenever a chaos test re-armed mid-traffic. The lock only costs on
// the slow path: the disarmed hot path is still the single relaxed load in
// FaultCheck.) g_mu is a leaf lock.
Mutex g_mu;
FaultSpec g_spec GUARDED_BY(g_mu);
std::atomic<uint64_t> g_bytes{0};     // bytes seen on matching (side, stream)
std::atomic<uint32_t> g_latched{0};   // one-shot claim for close/corrupt

// The armed churn script (docs/DESIGN.md "Elastic churn"). Polled at step
// boundaries only — never on the IO hot path — so a plain mutex is fine.
Mutex g_churn_mu;
std::vector<ChurnEvent> g_churn GUARDED_BY(g_churn_mu);

// The armed swap chaos script (docs/DESIGN.md "Live weight updates").
// Same off-hot-path polling discipline as churn.
Mutex g_swap_mu;
std::vector<SwapEvent> g_swap GUARDED_BY(g_swap_mu);

bool ParseSize(const std::string& v, uint64_t* out) {
  if (v.empty()) return false;
  size_t i = 0;
  uint64_t n = 0;
  while (i < v.size() && v[i] >= '0' && v[i] <= '9') {
    n = n * 10 + static_cast<uint64_t>(v[i] - '0');
    ++i;
  }
  if (i == 0) return false;
  if (i + 1 == v.size()) {
    switch (v[i] | 0x20) {
      case 'k': n <<= 10; ++i; break;
      case 'm': n <<= 20; ++i; break;
      case 'g': n <<= 30; ++i; break;
      default: return false;
    }
  }
  if (i != v.size()) return false;
  *out = n;
  return true;
}

}  // namespace

Status ParseFaultSpec(const std::string& spec, FaultSpec* out) {
  FaultSpec f;
  bool saw_action = false;
  size_t pos = 0;
  while (pos <= spec.size()) {
    size_t end = spec.find(':', pos);
    if (end == std::string::npos) end = spec.size();
    std::string item = spec.substr(pos, end - pos);
    pos = end + 1;
    if (item.empty()) {
      if (end == spec.size()) break;
      return Status::Invalid("fault spec: empty clause in '" + spec + "'");
    }
    size_t eq = item.find('=');
    if (eq == std::string::npos) {
      return Status::Invalid("fault spec: clause '" + item + "' is not key=value");
    }
    std::string key = item.substr(0, eq);
    std::string val = item.substr(eq + 1);
    if (key == "stream") {
      if (val == "*") {
        f.stream = -1;
      } else {
        uint64_t n = 0;
        if (!ParseSize(val, &n) || n > 255) {
          return Status::Invalid("fault spec: bad stream '" + val + "'");
        }
        f.stream = static_cast<int64_t>(n);
      }
    } else if (key == "side") {
      if (val == "*") f.side = 0;
      else if (val == "send") f.side = 1;
      else if (val == "recv") f.side = 2;
      else return Status::Invalid("fault spec: bad side '" + val + "'");
    } else if (key == "after_bytes") {
      if (!ParseSize(val, &f.after_bytes)) {
        return Status::Invalid("fault spec: bad after_bytes '" + val + "'");
      }
    } else if (key == "action") {
      saw_action = true;
      // "delay=50" arrives split at OUR '=' too: val may itself carry one.
      size_t deq = val.find('=');
      std::string name = deq == std::string::npos ? val : val.substr(0, deq);
      std::string arg = deq == std::string::npos ? "" : val.substr(deq + 1);
      if (name == "close" && arg.empty()) f.action = FaultAction::kClose;
      else if (name == "stall" && arg.empty()) f.action = FaultAction::kStall;
      else if (name == "corrupt" && arg.empty()) f.action = FaultAction::kCorrupt;
      else if (name == "delay") {
        f.action = FaultAction::kDelay;
        if (arg.empty() || !ParseSize(arg, &f.delay_ms) || f.delay_ms > 60000) {
          return Status::Invalid("fault spec: bad delay '" + val + "' (want delay=<ms> <= 60000)");
        }
      } else {
        return Status::Invalid("fault spec: unknown action '" + val + "'");
      }
    } else {
      return Status::Invalid("fault spec: unknown key '" + key + "'");
    }
  }
  if (!saw_action) return Status::Invalid("fault spec: missing action= clause");
  *out = f;
  return Status::Ok();
}

Status ParseChurnSpec(const std::string& spec, ChurnEvent* out) {
  ChurnEvent e;
  bool saw_churn = false, saw_action = false;
  size_t pos = 0;
  while (pos <= spec.size()) {
    size_t end = spec.find(':', pos);
    if (end == std::string::npos) end = spec.size();
    std::string item = spec.substr(pos, end - pos);
    pos = end + 1;
    if (item.empty()) {
      if (end == spec.size()) break;
      return Status::Invalid("churn spec: empty clause in '" + spec + "'");
    }
    if (item == "churn") {
      if (saw_churn) return Status::Invalid("churn spec: duplicate churn token");
      saw_churn = true;
      continue;
    }
    size_t eq = item.find('=');
    if (eq == std::string::npos) {
      return Status::Invalid("churn spec: clause '" + item + "' is not key=value");
    }
    std::string key = item.substr(0, eq);
    std::string val = item.substr(eq + 1);
    if (key == "at_step") {
      if (!ParseSize(val, &e.at_step)) {
        return Status::Invalid("churn spec: bad at_step '" + val + "'");
      }
    } else if (key == "rank") {
      if (val == "*") {
        e.rank = -1;
      } else {
        uint64_t n = 0;
        if (!ParseSize(val, &n) || n > (1u << 20)) {
          return Status::Invalid("churn spec: bad rank '" + val + "'");
        }
        e.rank = static_cast<int64_t>(n);
      }
    } else if (key == "action") {
      saw_action = true;
      if (val == "kill") e.action = ChurnAction::kKill;
      else if (val == "join") e.action = ChurnAction::kJoin;
      else return Status::Invalid("churn spec: unknown action '" + val +
                                  "' (want kill or join)");
    } else {
      return Status::Invalid("churn spec: unknown key '" + key + "'");
    }
  }
  if (!saw_churn) return Status::Invalid("churn spec: missing churn token");
  if (!saw_action) return Status::Invalid("churn spec: missing action= clause");
  *out = e;
  return Status::Ok();
}

Status ParseSwapSpec(const std::string& spec, SwapEvent* out) {
  SwapEvent e;
  bool saw_swap = false, saw_action = false;
  size_t pos = 0;
  while (pos <= spec.size()) {
    size_t end = spec.find(':', pos);
    if (end == std::string::npos) end = spec.size();
    std::string item = spec.substr(pos, end - pos);
    pos = end + 1;
    if (item.empty()) {
      if (end == spec.size()) break;
      return Status::Invalid("swap spec: empty clause in '" + spec + "'");
    }
    if (item == "swap") {
      if (saw_swap) return Status::Invalid("swap spec: duplicate swap token");
      saw_swap = true;
      continue;
    }
    size_t eq = item.find('=');
    if (eq == std::string::npos) {
      return Status::Invalid("swap spec: clause '" + item + "' is not key=value");
    }
    std::string key = item.substr(0, eq);
    std::string val = item.substr(eq + 1);
    if (key == "at_step") {
      if (!ParseSize(val, &e.at_step)) {
        return Status::Invalid("swap spec: bad at_step '" + val + "'");
      }
    } else if (key == "action") {
      saw_action = true;
      if (val == "publish") e.action = SwapAction::kPublish;
      else if (val == "corrupt") e.action = SwapAction::kCorrupt;
      else if (val == "die") e.action = SwapAction::kDie;
      else return Status::Invalid("swap spec: unknown action '" + val +
                                  "' (want publish, corrupt or die)");
    } else {
      return Status::Invalid("swap spec: unknown key '" + key + "'");
    }
  }
  if (!saw_swap) return Status::Invalid("swap spec: missing swap token");
  if (!saw_action) return Status::Invalid("swap spec: missing action= clause");
  *out = e;
  return Status::Ok();
}

Status ParseFaultScript(const std::string& spec, FaultSpec* fault,
                        bool* has_fault, std::vector<ChurnEvent>* churn,
                        std::vector<SwapEvent>* swap) {
  *has_fault = false;
  churn->clear();
  swap->clear();
  size_t pos = 0;
  while (pos <= spec.size()) {
    size_t end = spec.find(';', pos);
    if (end == std::string::npos) end = spec.size();
    std::string seg = spec.substr(pos, end - pos);
    bool last = end == spec.size();
    pos = end + 1;
    if (seg.empty()) {
      if (last) break;
      return Status::Invalid("fault script: empty segment in '" + spec + "'");
    }
    if (seg.compare(0, 5, "churn") == 0 &&
        (seg.size() == 5 || seg[5] == ':')) {
      ChurnEvent e;
      Status s = ParseChurnSpec(seg, &e);
      if (!s.ok()) return s;
      churn->push_back(e);
    } else if (seg.compare(0, 4, "swap") == 0 &&
               (seg.size() == 4 || seg[4] == ':')) {
      SwapEvent e;
      Status s = ParseSwapSpec(seg, &e);
      if (!s.ok()) return s;
      swap->push_back(e);
    } else {
      if (*has_fault) {
        return Status::Invalid(
            "fault script: more than one classic fault segment (one fault "
            "at a time; churn segments may repeat)");
      }
      Status s = ParseFaultSpec(seg, fault);
      if (!s.ok()) return s;
      *has_fault = true;
    }
    if (last) break;
  }
  return Status::Ok();
}

void ArmChurnScript(const std::vector<ChurnEvent>& events) {
  MutexLock lk(g_churn_mu);
  g_churn = events;
  for (ChurnEvent& e : g_churn) e.fired = false;
}

ChurnAction ChurnPoll(uint64_t step, int64_t rank) {
  MutexLock lk(g_churn_mu);
  for (ChurnEvent& e : g_churn) {
    if (e.fired || e.at_step > step) continue;
    if (e.rank >= 0 && e.rank != rank) continue;
    e.fired = true;
    return e.action;
  }
  return ChurnAction::kNone;
}

int ChurnPending() {
  MutexLock lk(g_churn_mu);
  int n = 0;
  for (const ChurnEvent& e : g_churn) n += e.fired ? 0 : 1;
  return n;
}

void ArmSwapScript(const std::vector<SwapEvent>& events) {
  MutexLock lk(g_swap_mu);
  g_swap = events;
  for (SwapEvent& e : g_swap) e.fired = false;
}

SwapAction SwapPoll(uint64_t step) {
  MutexLock lk(g_swap_mu);
  for (SwapEvent& e : g_swap) {
    if (e.fired || e.at_step > step) continue;
    e.fired = true;
    return e.action;
  }
  return SwapAction::kNone;
}

int SwapPending() {
  MutexLock lk(g_swap_mu);
  int n = 0;
  for (const SwapEvent& e : g_swap) n += e.fired ? 0 : 1;
  return n;
}

void ArmFault(const FaultSpec& spec) {
  MutexLock lk(g_mu);
  g_fault_armed.store(0, std::memory_order_release);  // quiesce readers' view
  g_spec = spec;
  g_bytes.store(0, std::memory_order_relaxed);
  g_latched.store(0, std::memory_order_relaxed);
  g_fault_armed.store(1, std::memory_order_release);
}

void DisarmFault() {
  {
    MutexLock lk(g_mu);
    g_fault_armed.store(0, std::memory_order_release);
  }
  {
    MutexLock lk(g_churn_mu);
    g_churn.clear();
  }
  MutexLock lk(g_swap_mu);
  g_swap.clear();
}

void ArmFaultFromEnv() {
  std::string spec = GetEnv("TPUNET_FAULT_SPEC", "");
  if (spec.empty()) return;
  FaultSpec f;
  bool has_fault = false;
  std::vector<ChurnEvent> churn;
  std::vector<SwapEvent> swap;
  Status s = ParseFaultScript(spec, &f, &has_fault, &churn, &swap);
  if (!s.ok()) {
    fprintf(stderr, "tpunet: ignoring TPUNET_FAULT_SPEC: %s\n", s.msg.c_str());
    return;
  }
  if (has_fault) ArmFault(f);
  if (!churn.empty()) {
    // Once per process: engine creation re-arms classic faults (resetting
    // their byte counters — the long-standing contract), but a churn
    // script's fired latches must SURVIVE the rebuilds the script itself
    // causes — a rewire creates a fresh engine, and re-arming there would
    // re-fire every kill the job already recovered from.
    static std::once_flag churn_once;
    std::call_once(churn_once, [&churn] { ArmChurnScript(churn); });
  }
  if (!swap.empty()) {
    // Same latch-survival contract: the engine rebuilds a swap retry causes
    // must not re-fire the corrupt/die the drill already played.
    static std::once_flag swap_once;
    std::call_once(swap_once, [&swap] { ArmSwapScript(swap); });
  }
}

FaultAction FaultPreIO(bool is_send, uint64_t stream_idx, int fd, size_t nbytes) {
  // Slow path only (FaultCheck already saw armed != 0): copy the spec under
  // its lock. Re-check armed under the same lock so a concurrent disarm
  // cannot hand out a stale spec.
  FaultSpec spec;
  {
    MutexLock lk(g_mu);
    if (g_fault_armed.load(std::memory_order_acquire) == 0) return FaultAction::kNone;
    spec = g_spec;
  }
  if (spec.side == 1 && !is_send) return FaultAction::kNone;
  if (spec.side == 2 && is_send) return FaultAction::kNone;
  if (spec.stream >= 0 && static_cast<uint64_t>(spec.stream) != stream_idx) {
    return FaultAction::kNone;
  }
  uint64_t before = g_bytes.fetch_add(nbytes, std::memory_order_relaxed);
  if (before < spec.after_bytes) return FaultAction::kNone;
  switch (spec.action) {
    case FaultAction::kClose:
      if (g_latched.exchange(1, std::memory_order_acq_rel)) return FaultAction::kNone;
      Telemetry::Get().OnFaultInjected(static_cast<int>(FaultAction::kClose));
      ::shutdown(fd, SHUT_RDWR);
      return FaultAction::kNone;  // the IO proceeds and fails organically
    case FaultAction::kCorrupt:
      if (g_latched.exchange(1, std::memory_order_acq_rel)) return FaultAction::kNone;
      Telemetry::Get().OnFaultInjected(static_cast<int>(FaultAction::kCorrupt));
      return FaultAction::kCorrupt;
    case FaultAction::kStall:
      if (!g_latched.exchange(1, std::memory_order_acq_rel)) {
        Telemetry::Get().OnFaultInjected(static_cast<int>(FaultAction::kStall));
      }
      FaultStall(fd);
      return FaultAction::kNone;
    case FaultAction::kDelay:
      if (!g_latched.exchange(1, std::memory_order_acq_rel)) {
        Telemetry::Get().OnFaultInjected(static_cast<int>(FaultAction::kDelay));
      }
      std::this_thread::sleep_for(std::chrono::milliseconds(spec.delay_ms));
      return FaultAction::kNone;
    case FaultAction::kNone:
      break;
  }
  return FaultAction::kNone;
}

FaultAction FaultPreMem(bool is_send, uint64_t stream_idx, size_t nbytes) {
  FaultSpec spec;
  {
    MutexLock lk(g_mu);
    if (g_fault_armed.load(std::memory_order_acquire) == 0) return FaultAction::kNone;
    spec = g_spec;
  }
  if (spec.side == 1 && !is_send) return FaultAction::kNone;
  if (spec.side == 2 && is_send) return FaultAction::kNone;
  if (spec.stream >= 0 && static_cast<uint64_t>(spec.stream) != stream_idx) {
    return FaultAction::kNone;
  }
  uint64_t before = g_bytes.fetch_add(nbytes, std::memory_order_relaxed);
  if (before < spec.after_bytes) return FaultAction::kNone;
  switch (spec.action) {
    case FaultAction::kClose:
      if (g_latched.exchange(1, std::memory_order_acq_rel)) return FaultAction::kNone;
      Telemetry::Get().OnFaultInjected(static_cast<int>(FaultAction::kClose));
      return FaultAction::kClose;  // caller fails the segment over
    case FaultAction::kCorrupt:
      if (g_latched.exchange(1, std::memory_order_acq_rel)) return FaultAction::kNone;
      Telemetry::Get().OnFaultInjected(static_cast<int>(FaultAction::kCorrupt));
      return FaultAction::kCorrupt;
    case FaultAction::kStall:
      if (!g_latched.exchange(1, std::memory_order_acq_rel)) {
        Telemetry::Get().OnFaultInjected(static_cast<int>(FaultAction::kStall));
      }
      return FaultAction::kStall;  // caller parks against its abort flag
    case FaultAction::kDelay:
      if (!g_latched.exchange(1, std::memory_order_acq_rel)) {
        Telemetry::Get().OnFaultInjected(static_cast<int>(FaultAction::kDelay));
      }
      std::this_thread::sleep_for(std::chrono::milliseconds(spec.delay_ms));
      return FaultAction::kNone;
    case FaultAction::kNone:
      break;
  }
  return FaultAction::kNone;
}

void FaultStall(int fd) {
  // Hold until disarmed or the fd dies (watchdog abort / comm teardown
  // shutdown(2)s it, which raises POLLHUP even for a local half-close).
  while (g_fault_armed.load(std::memory_order_acquire) != 0) {
    struct pollfd pfd = {fd, 0, 0};  // events=0: error conditions only
    if (::poll(&pfd, 1, 0) > 0 && (pfd.revents & (POLLERR | POLLHUP | POLLNVAL))) {
      return;
    }
    std::this_thread::sleep_for(std::chrono::milliseconds(5));
  }
}

}  // namespace tpunet
