// Recursive halving-doubling AllReduce (Rabenseifner) over the pairwise
// mesh: reduce-scatter by recursive vector halving with distance doubling
// (log2(W') rounds, partners vr^1, vr^2, vr^4, ...), then all-gather by the
// reverse doubling (log2(W') rounds) — 2*log2(W') wire rounds moving the
// same 2*(W'-1)/W' * S total bytes as the ring, i.e. bandwidth-optimal at a
// LOG instead of LINEAR round count. This is the small/medium-message
// schedule "The Big Send-off" (arxiv 2504.18658) shows the ring losing to
// at scale; the dispatch selector (dispatch.h) hands it that regime.
//
// Non-power-of-2 worlds fold the remainder in (W' = largest power of two
// <= W, r = W - W'): the first 2r ranks pair up, the odd rank of each pair
// ships its whole vector to its partner before the halving and receives the
// finished result after the doubling — 2 extra rounds, the standard MPI
// construction.
//
// Wire codec (TPUNET_WIRE_DTYPE != f32, f32 payloads): every hop ships
// encoded bytes. RS hops run the fused decode+reduce (f32 accumulate —
// quantization enters once per hop, never compounds); the FINAL RS hop runs
// the quantize handoff so each rank's owned atom lands in `data` already
// quantized with its encoded form parked in the atom-framed assembly
// buffer. The AG phase then forwards those encoded atoms VERBATIM — every
// rank decodes the same bytes per atom, so results are bit-identical across
// ranks (including the folded-in extras, which receive the same assembly).
#include <string.h>

#include <algorithm>
#include <vector>

#include "coll_comm.h"

namespace tpunet {
namespace internal {

namespace {

// One leaf of the halving tree: the final segment vrank v owns after the RS
// phase. Element ranges nest by construction (bit k of v picks the half at
// level k, so level 0 — bit 0 — is the COARSEST split); the encoded
// assembly lays atoms out in element order, each encoded independently
// (int8 scale blocks restart per atom), so any level range's encoding is a
// contiguous, forwardable byte span.
struct Atom {
  size_t lo = 0, n = 0;   // element range
  size_t wire_off = 0;    // offset into the atom-framed encoded assembly
};

// All ranks derive the identical geometry from (count, W') alone — that is
// what lets encoded bytes forward verbatim and zero-length exchanges pair.
std::vector<Atom> AtomLayout(size_t count, int wp, WireCodec codec) {
  std::vector<Atom> atoms(wp);
  for (int v = 0; v < wp; ++v) {
    size_t lo = 0, hi = count;
    for (int mask = 1; mask < wp; mask <<= 1) {
      size_t mid = lo + (hi - lo) / 2;
      if (v & mask) {
        lo = mid;
      } else {
        hi = mid;
      }
    }
    atoms[v] = {lo, hi - lo, 0};
  }
  std::sort(atoms.begin(), atoms.end(),
            [](const Atom& a, const Atom& b) { return a.lo < b.lo; });
  size_t off = 0;
  for (Atom& a : atoms) {
    a.wire_off = off;
    off += CodecWireBytes(codec, a.n);
  }
  return atoms;
}

// Wire span covering the atoms inside element range [lo, hi) (always a
// whole subtree of the halving recursion, so the atoms are contiguous).
void WireSpan(const std::vector<Atom>& atoms, WireCodec codec, size_t lo,
              size_t hi, size_t* off, size_t* len) {
  *off = 0;
  *len = 0;
  bool first = true;
  for (const Atom& a : atoms) {
    if (a.n == 0 || a.lo < lo || a.lo + a.n > hi) continue;
    if (first) {
      *off = a.wire_off;
      first = false;
    }
    *len += CodecWireBytes(codec, a.n);
  }
}

}  // namespace

Status ScheduledCommunicator::DoAllReduceRhd(const void* sendbuf, void* recvbuf,
                                             size_t count, DType dtype, RedOp op,
                                             uint64_t seq) {
  const size_t esize = DTypeSize(dtype);
  const bool tracing = Telemetry::Get().tracing_enabled();
  PhaseSpan whole(tracing, trace_comm_id_, seq, "allreduce", -1, count * esize);
  Status s = EnsureMeshQuiesced();
  if (!s.ok()) return s;

  uint8_t* data = static_cast<uint8_t*>(recvbuf);
  if (sendbuf != recvbuf) memmove(recvbuf, sendbuf, count * esize);

  const int W = world_;
  int wp = 1;
  while (wp * 2 <= W) wp <<= 1;
  const int r = W - wp;
  const bool codec_on = UseCodec(dtype);
  const WireRedOp wop = ToWireRedOp(op);
  float* data_f = reinterpret_cast<float*>(data);

  // Role mapping: the first 2r ranks pair (even = active, odd = extra);
  // ranks >= 2r are active. Active virtual ranks cover [0, W') exactly.
  const bool paired = rank_ < 2 * r;
  const bool active = !paired || (rank_ % 2) == 0;
  const int vr = paired ? rank_ / 2 : rank_ - r;
  auto to_rank = [&](int v) { return v < r ? 2 * v : v + r; };

  std::vector<Atom> atoms;
  size_t total_wire = 0;
  if (codec_on) {
    atoms = AtomLayout(count, wp, codec_);
    total_wire = atoms.empty()
                     ? 0
                     : atoms.back().wire_off + CodecWireBytes(codec_, atoms.back().n);
    mesh_enc_.reserve(total_wire);
  }

  // ---- Fold-in: extras ship their whole vector to their partner ----------
  if (paired) {
    PhaseSpan fold(tracing, trace_comm_id_, seq, "fold", 0, count * esize);
    CountCollSteps(CollAlgo::kRhd);
    if (!active) {
      if (codec_on) {
        // One whole-vector encoding (blocks from offset 0) — the partner
        // decodes with the same framing.
        size_t wb = CodecWireBytes(codec_, count);
        mesh_scratch_.reserve(wb);
        CodecEncode(codec_, data_f, mesh_scratch_.data(), count);
        s = MeshSend(rank_ - 1, mesh_scratch_.data(), wb);
      } else {
        s = MeshSend(rank_ - 1, data, count * esize);
      }
      if (!s.ok()) return s;
    } else {
      if (codec_on) {
        size_t wb = CodecWireBytes(codec_, count);
        mesh_scratch_.reserve(wb);
        s = MeshRecv(rank_ + 1, mesh_scratch_.data(), wb);
        if (!s.ok()) return s;
        CodecDecodeReduce(codec_, data_f, nullptr, mesh_scratch_.data(), count, wop);
      } else {
        mesh_scratch_.reserve(count * esize);
        s = MeshRecv(rank_ + 1, mesh_scratch_.data(), count * esize);
        if (!s.ok()) return s;
        Reduce(data, data, mesh_scratch_.data(), count, dtype, op);
      }
    }
  }

  struct Level {
    size_t lo, hi, mid;
    int peer;
    bool keep_low;
  };
  std::vector<Level> levels;

  if (active) {
    // ---- Reduce-scatter: recursive vector halving, distance doubling ----
    // Partners at level k differ only in bit k of vr; all lower bits are
    // equal, so both made identical keep decisions and share [lo, hi).
    size_t lo = 0, hi = count;
    const size_t half_wire =
        codec_on ? CodecWireBytes(codec_, (count + 1) / 2) : 0;
    int step = 0;
    for (int mask = 1; mask < wp; mask <<= 1, ++step) {
      const int peer = to_rank(vr ^ mask);
      const size_t mid = lo + (hi - lo) / 2;
      const bool keep_low = (vr & mask) == 0;
      const size_t k_lo = keep_low ? lo : mid, k_hi = keep_low ? mid : hi;
      const size_t s_lo = keep_low ? mid : lo, s_hi = keep_low ? hi : mid;
      const size_t keep_n = k_hi - k_lo, send_n = s_hi - s_lo;
      PhaseSpan sp(tracing, trace_comm_id_, seq, "rs", step, send_n * esize);
      CountCollSteps(CollAlgo::kRhd);
      const bool last = (mask << 1) >= wp;
      if (codec_on) {
        // Encode the shed half, exchange wire bytes, fused decode+reduce
        // into the kept half; the LAST level quantizes the kept atom and
        // parks its encoded bytes in the assembly for the AG phase.
        mesh_scratch_.reserve(2 * half_wire);
        uint8_t* enc_send = mesh_scratch_.data();
        uint8_t* enc_recv = mesh_scratch_.data() + half_wire;
        CodecEncode(codec_, data_f + s_lo, enc_send, send_n);
        s = MeshExchange(peer, enc_send, CodecWireBytes(codec_, send_n),
                         enc_recv, CodecWireBytes(codec_, keep_n));
        if (!s.ok()) return s;
        if (last) {
          size_t a_off = 0, a_len = 0;
          WireSpan(atoms, codec_, k_lo, k_hi, &a_off, &a_len);
          CodecDecodeReduceQuantize(codec_, data_f + k_lo, nullptr, enc_recv,
                                    mesh_enc_.data() + a_off, keep_n, wop);
        } else {
          CodecDecodeReduce(codec_, data_f + k_lo, nullptr, enc_recv, keep_n, wop);
        }
      } else {
        mesh_scratch_.reserve(keep_n * esize);
        s = MeshExchange(peer, data + s_lo * esize, send_n * esize,
                         mesh_scratch_.data(), keep_n * esize);
        if (!s.ok()) return s;
        Reduce(data + k_lo * esize, data + k_lo * esize, mesh_scratch_.data(),
               keep_n, dtype, op);
      }
      levels.push_back({lo, hi, mid, peer, keep_low});
      lo = k_lo;
      hi = k_hi;
    }

    // ---- All-gather: reverse doubling ----------------------------------
    // At level k I own the kept half of levels[k]'s range and my partner
    // owns the sibling; one exchange reassembles the parent. Codec: the
    // encoded atoms forward verbatim (each rank decodes identical bytes).
    for (int k = static_cast<int>(levels.size()) - 1; k >= 0; --k) {
      const Level& lv = levels[k];
      const size_t sib_lo = lv.keep_low ? lv.mid : lv.lo;
      const size_t sib_hi = lv.keep_low ? lv.hi : lv.mid;
      PhaseSpan sp(tracing, trace_comm_id_, seq, "ag",
                   static_cast<int>(levels.size()) - 1 - k, (hi - lo) * esize);
      CountCollSteps(CollAlgo::kRhd);
      if (codec_on) {
        size_t my_off = 0, my_len = 0, sib_off = 0, sib_len = 0;
        WireSpan(atoms, codec_, lo, hi, &my_off, &my_len);
        WireSpan(atoms, codec_, sib_lo, sib_hi, &sib_off, &sib_len);
        s = MeshExchange(lv.peer, mesh_enc_.data() + my_off, my_len,
                         mesh_enc_.data() + sib_off, sib_len);
        if (!s.ok()) return s;
        for (const Atom& a : atoms) {
          if (a.n == 0 || a.lo < sib_lo || a.lo + a.n > sib_hi) continue;
          CodecDecode(codec_, mesh_enc_.data() + a.wire_off, data_f + a.lo, a.n);
        }
      } else {
        s = MeshExchange(lv.peer, data + lo * esize, (hi - lo) * esize,
                         data + sib_lo * esize, (sib_hi - sib_lo) * esize);
        if (!s.ok()) return s;
      }
      lo = lv.lo;
      hi = lv.hi;
    }
  }

  // ---- Fold-out: actives return the finished result to their extra -------
  if (paired) {
    PhaseSpan fold(tracing, trace_comm_id_, seq, "fold", 1, count * esize);
    CountCollSteps(CollAlgo::kRhd);
    if (active) {
      // Codec: forward the atom-framed assembly, NOT a re-encode — the
      // extra decodes the same bytes every active rank decoded, so all W
      // ranks stay bit-identical (a re-encode would re-block int8 scales).
      s = codec_on ? MeshSend(rank_ + 1, mesh_enc_.data(), total_wire)
                   : MeshSend(rank_ + 1, data, count * esize);
    } else {
      if (codec_on) {
        s = MeshRecv(rank_ - 1, mesh_enc_.data(), total_wire);
        if (s.ok()) {
          for (const Atom& a : atoms) {
            if (a.n == 0) continue;
            CodecDecode(codec_, mesh_enc_.data() + a.wire_off, data_f + a.lo, a.n);
          }
        }
      } else {
        s = MeshRecv(rank_ - 1, data, count * esize);
      }
    }
    if (!s.ok()) return s;
  }
  return Status::Ok();
}

// ---------------------------------------------------------------------------
// Mesh step primitives (shared with the tree schedule).

// Full-duplex pairwise step on `peer`'s mesh comms: post the irecv first,
// then the isend; BOTH requests are waited before returning — even on error
// — so no abandoned in-flight request can touch a freed buffer. Zero-length
// directions are skipped entirely (empty halving segments at tiny counts);
// both sides derive the sizes from identical geometry, so the skips pair.
Status ScheduledCommunicator::MeshExchange(int peer, const void* sendbuf,
                                           size_t send_nbytes, void* recvbuf,
                                           size_t recv_nbytes) {
  uint64_t rreq = 0, sreq = 0;
  bool rlive = false, slive = false;
  Status st;
  if (recv_nbytes > 0) {
    st = net_->irecv(mesh_recv_[peer], recvbuf, recv_nbytes, &rreq);
    if (!st.ok()) return st;
    rlive = true;
  }
  if (send_nbytes > 0) {
    st = net_->isend(mesh_send_[peer], sendbuf, send_nbytes, &sreq);
    if (!st.ok()) {
      if (rlive) WaitRequest(rreq, nullptr);
      return st;
    }
    slive = true;
  }
  size_t got = 0;
  Status r_st = rlive ? WaitRequest(rreq, &got) : Status::Ok();
  Status s_st = slive ? WaitRequest(sreq, nullptr) : Status::Ok();
  if (!r_st.ok()) return r_st;
  if (!s_st.ok()) return s_st;
  if (rlive && got != recv_nbytes) {
    return Status::Inner("mesh step size mismatch: expected " +
                         std::to_string(recv_nbytes) + "B from rank " +
                         std::to_string(peer) + ", got " + std::to_string(got) +
                         "B (ranks disagree on collective arguments?)");
  }
  return Status::Ok();
}

Status ScheduledCommunicator::MeshSend(int peer, const void* buf, size_t nbytes) {
  if (nbytes == 0) return Status::Ok();
  uint64_t req = 0;
  Status st = net_->isend(mesh_send_[peer], buf, nbytes, &req);
  if (!st.ok()) return st;
  return WaitRequest(req, nullptr);
}

Status ScheduledCommunicator::MeshRecv(int peer, void* buf, size_t nbytes) {
  if (nbytes == 0) return Status::Ok();
  uint64_t req = 0;
  Status st = net_->irecv(mesh_recv_[peer], buf, nbytes, &req);
  if (!st.ok()) return st;
  size_t got = 0;
  st = WaitRequest(req, &got);
  if (!st.ok()) return st;
  if (got != nbytes) {
    return Status::Inner("mesh message size mismatch: expected " +
                         std::to_string(nbytes) + "B from rank " +
                         std::to_string(peer) + ", got " + std::to_string(got) + "B");
  }
  return Status::Ok();
}

}  // namespace internal
}  // namespace tpunet
