// Shared wire protocol + rendezvous implementation. See wire.h.
#include "wire.h"

#include "tpunet/qos.h"

#include <arpa/inet.h>
#include <errno.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <string.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <random>
#include <thread>

namespace tpunet {

void RequestState::ReleaseQosAdmission() {
  if (qos_admitted == 0) return;
  if (qos_released.exchange(true, std::memory_order_acq_rel)) return;
  QosScheduler::Get().FinishMessage(static_cast<TrafficClass>(qos_cls),
                                    qos_admitted);
}

RequestState::~RequestState() { ReleaseQosAdmission(); }

socklen_t AddrLenForFamily(const sockaddr_storage& ss) {
  return ss.ss_family == AF_INET6 ? sizeof(sockaddr_in6) : sizeof(sockaddr_in);
}

Status MakeSocket(int family, int* out) {
  int fd = ::socket(family, SOCK_STREAM, 0);
  if (fd < 0) return Status::TCP("socket() failed: " + std::string(strerror(errno)));
  *out = fd;
  return Status::Ok();
}

Status WritePreamble(int fd, const Preamble& p) {
  uint8_t buf[48];
  EncodeU64BE(kWireMagic, buf);
  EncodeU64BE(p.bundle_id, buf + 8);
  EncodeU64BE(p.stream_id, buf + 16);
  EncodeU64BE(p.nstreams, buf + 24);
  EncodeU64BE(p.min_chunksize, buf + 32);
  EncodeU64BE(p.flags, buf + 40);
  return WriteAll(fd, buf, sizeof(buf));
}

Status ReadPreamble(int fd, Preamble* p, int timeout_ms) {
  uint8_t buf[48];
  // Hard deadline over the whole 48 bytes — a slow-loris client trickling
  // one byte per interval cannot stretch this past timeout_ms. The magic is
  // checked as soon as its 8 bytes land so a mismatched-version peer (whose
  // preamble may be shorter) gets the typed verdict instead of a timeout.
  Status s = ReadExactDeadline(fd, buf, 8, timeout_ms);
  if (!s.ok()) return s;
  uint64_t magic = DecodeU64BE(buf);
  if (magic != kWireMagic) {
    if ((magic & kWireMagicPrefixMask) == (kWireMagic & kWireMagicPrefixMask)) {
      return Status::Version(
          "tpunet wire version mismatch: peer speaks framing v" +
          std::to_string(magic & 0xff) + ", this build speaks v" +
          std::to_string(kWireMagic & 0xff));
    }
    return Status::TCP("bad wire magic — peer is not tpunet");
  }
  s = ReadExactDeadline(fd, buf + 8, sizeof(buf) - 8, timeout_ms);
  if (!s.ok()) return s;
  p->bundle_id = DecodeU64BE(buf + 8);
  p->stream_id = DecodeU64BE(buf + 16);
  p->nstreams = DecodeU64BE(buf + 24);
  p->min_chunksize = DecodeU64BE(buf + 32);
  p->flags = DecodeU64BE(buf + 40);
  if (p->nstreams == 0 || p->nstreams > kMaxStreams || p->stream_id > p->nstreams ||
      p->min_chunksize == 0) {
    return Status::TCP("malformed preamble: nstreams=" + std::to_string(p->nstreams) +
                       " stream_id=" + std::to_string(p->stream_id));
  }
  return Status::Ok();
}

uint64_t RandomBundleId() {
  static std::atomic<uint64_t> ctr{1};
  std::random_device rd;
  uint64_t hi = (static_cast<uint64_t>(rd()) << 32) ^ rd();
  return hi ^ (ctr.fetch_add(1) << 1) ^ (static_cast<uint64_t>(::getpid()) << 40);
}

void PartialBundle::CloseAll() {
  if (ctrl_fd >= 0) ::close(ctrl_fd);
  ctrl_fd = -1;
  for (auto& df : data_fds) ::close(df.second);
  data_fds.clear();
}

ListenSock::~ListenSock() {
  for (auto& kv : partials) kv.second.CloseAll();
  if (fd >= 0) ::close(fd);
  if (wake_fd >= 0) ::close(wake_fd);
}

Status ListenOn(const NicInfo& nic, int32_t dev, SocketHandle* handle, ListenSockPtr* out) {
  int fd = -1;
  Status s = MakeSocket(nic.addr.ss_family, &fd);
  if (!s.ok()) return s;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  // Bind to the NIC's address with an ephemeral port; the resulting
  // sockaddr IS the rendezvous handle (reference: nthread:259-303).
  sockaddr_storage bind_addr = nic.addr;
  if (bind_addr.ss_family == AF_INET) {
    reinterpret_cast<sockaddr_in*>(&bind_addr)->sin_port = 0;
  } else {
    reinterpret_cast<sockaddr_in6*>(&bind_addr)->sin6_port = 0;
  }
  if (::bind(fd, reinterpret_cast<sockaddr*>(&bind_addr), nic.addrlen) != 0) {
    ::close(fd);
    return Status::TCP("bind failed: " + std::string(strerror(errno)));
  }
  if (::listen(fd, kListenBacklog) != 0) {
    ::close(fd);
    return Status::TCP("listen failed: " + std::string(strerror(errno)));
  }
  auto lc = std::make_shared<ListenSock>();
  lc->fd = fd;
  lc->wake_fd = ::eventfd(0, EFD_CLOEXEC);
  if (lc->wake_fd < 0) {
    // Without the wake fd close_listen could never abort a parked accept().
    return Status::TCP("eventfd failed: " + std::string(strerror(errno)));
  }
  SetNonblocking(fd);  // accept() polls first; EAGAIN is handled
  lc->dev = dev;
  handle->addrlen = nic.addrlen;
  if (getsockname(fd, reinterpret_cast<sockaddr*>(&handle->addr), &handle->addrlen) != 0) {
    return Status::TCP("getsockname failed: " + std::string(strerror(errno)));
  }
  *out = std::move(lc);
  return Status::Ok();
}

void WakeListen(ListenSock* ls) {
  ls->closed.store(true, std::memory_order_release);
  if (ls->wake_fd >= 0) {
    uint64_t one = 1;
    (void)!::write(ls->wake_fd, &one, sizeof(one));
  }
}

Status AcceptBundle(ListenSock* lc, PartialBundle* out) {
  // Accept connections, grouping by bundle id, until one bundle is whole
  // (reference accepts exactly nstreams+1 and keys by raw id,
  // nthread:425-522; bundles make concurrent senders safe).
  MutexLock accept_lk(lc->mu);
  uint64_t expiry_ms = 2 * GetEnvU64("TPUNET_HANDSHAKE_TIMEOUT_MS", 10000);
  while (true) {
    // Expire half-arrived bundles from dead senders so their parked fds
    // don't accumulate toward RLIMIT_NOFILE on a long-lived listen comm.
    auto now = std::chrono::steady_clock::now();
    for (auto it = lc->partials.begin(); it != lc->partials.end();) {
      if (!it->second.Complete() &&
          now - it->second.first_seen > std::chrono::milliseconds(expiry_ms)) {
        it->second.CloseAll();
        it = lc->partials.erase(it);
      } else {
        ++it;
      }
    }
    for (auto it = lc->partials.begin(); it != lc->partials.end(); ++it) {
      if (it->second.Complete()) {
        *out = std::move(it->second);
        lc->partials.erase(it);
        return Status::Ok();
      }
    }
    // poll so close_listen can abort us via the eventfd (a blocked
    // ::accept is not reliably interruptible by shutdown() on Linux).
    // Finite timeout so the expiry sweep above runs even with no events.
    struct pollfd pfds[2] = {{lc->fd, POLLIN, 0}, {lc->wake_fd, POLLIN, 0}};
    int pr = ::poll(pfds, 2, 1000);
    if (pr < 0) {
      if (errno == EINTR) continue;
      return Status::TCP("poll failed: " + std::string(strerror(errno)));
    }
    if (pr == 0) continue;  // timeout tick: re-run expiry sweep
    if (lc->closed.load(std::memory_order_acquire) || (pfds[1].revents & POLLIN)) {
      return Status::Inner("listen comm closed while accepting");
    }
    if (!(pfds[0].revents & POLLIN)) continue;
    sockaddr_storage peer;
    socklen_t plen = sizeof(peer);
    int fd = ::accept(lc->fd, reinterpret_cast<sockaddr*>(&peer), &plen);
    if (fd < 0) {
      if (errno == EINTR || errno == EAGAIN || errno == EWOULDBLOCK) continue;
      return Status::TCP("accept failed: " + std::string(strerror(errno)));
    }
    Status s = SetNodelay(fd);
    if (!s.ok()) {
      ::close(fd);
      return s;
    }
    ApplySocketBufsize(fd);
    ApplyKeepalive(fd);
    // Bound the preamble read: a client that connects but never completes
    // the 40-byte handshake (scanner, stalled peer) must not wedge accept()
    // while it holds lc->mu. Malformed/timed-out clients are dropped and
    // accept keeps serving legitimate peers.
    uint64_t handshake_ms = GetEnvU64("TPUNET_HANDSHAKE_TIMEOUT_MS", 10000);
    Preamble p;
    s = ReadPreamble(fd, &p, static_cast<int>(handshake_ms));
    if (!s.ok()) {
      ::close(fd);
      continue;
    }
    PartialBundle& b = lc->partials[p.bundle_id];
    if (b.nstreams == UINT64_MAX) {
      b.nstreams = p.nstreams;
      b.min_chunksize = p.min_chunksize;
      b.flags = p.flags;
      b.first_seen = std::chrono::steady_clock::now();
    } else if (b.nstreams != p.nstreams || b.min_chunksize != p.min_chunksize ||
               b.flags != p.flags) {
      ::close(fd);  // inconsistent members: drop the whole bundle
      b.CloseAll();
      lc->partials.erase(p.bundle_id);
      continue;
    }
    if (p.stream_id == p.nstreams) {
      if (b.ctrl_fd >= 0) {
        ::close(fd);  // duplicate ctrl stream: keep the first
        continue;
      }
      b.ctrl_fd = fd;
    } else if (!b.data_fds.emplace(p.stream_id, fd).second) {
      ::close(fd);  // duplicate stream id: keep the first, drop the dup
      continue;
    }
  }
}

namespace {

Status ConnectOneAttempt(const std::vector<NicInfo>& nics, int32_t dev,
                         const SocketHandle& handle, int* out_fd, int* conn_errno) {
  int fd = -1;
  Status s = MakeSocket(handle.addr.ss_family, &fd);
  if (!s.ok()) return s;
  // Route out of the chosen NIC when address families line up.
  const NicInfo& nic = nics[dev];
  if (nic.addr.ss_family == handle.addr.ss_family && nic.name != "lo") {
    sockaddr_storage local = nic.addr;
    if (local.ss_family == AF_INET) {
      reinterpret_cast<sockaddr_in*>(&local)->sin_port = 0;
    } else {
      reinterpret_cast<sockaddr_in6*>(&local)->sin6_port = 0;
    }
    ::bind(fd, reinterpret_cast<sockaddr*>(&local), nic.addrlen);  // best effort
  }
  // addrlen is derived from the family, not trusted from the handle: a
  // handle marshaled through the 64-byte wire blob (C ABI / ncclNet shim)
  // carries only the sockaddr bytes.
  socklen_t alen = AddrLenForFamily(handle.addr);
  if (::connect(fd, reinterpret_cast<const sockaddr*>(&handle.addr), alen) != 0) {
    // POSIX: after EINTR the connect proceeds asynchronously — retrying
    // ::connect() yields EALREADY. Wait for writability + check SO_ERROR.
    bool pending = (errno == EINTR || errno == EINPROGRESS || errno == EALREADY);
    if (!pending) {
      *conn_errno = errno;
      ::close(fd);
      return Status::TCP("connect to " + SockaddrToString(handle.addr, alen) +
                         " failed: " + std::string(strerror(errno)));
    }
    struct pollfd pfd = {fd, POLLOUT, 0};
    int pr;
    do {
      pr = ::poll(&pfd, 1, -1);
    } while (pr < 0 && errno == EINTR);
    int soerr = 0;
    socklen_t slen = sizeof(soerr);
    if (pr < 0 || getsockopt(fd, SOL_SOCKET, SO_ERROR, &soerr, &slen) != 0 || soerr != 0) {
      *conn_errno = soerr ? soerr : errno;
      ::close(fd);
      return Status::TCP("connect to " + SockaddrToString(handle.addr, alen) +
                         " failed: " + std::string(strerror(soerr ? soerr : errno)));
    }
  }
  s = SetNodelay(fd);  // reference: nthread:329
  if (!s.ok()) {
    ::close(fd);
    return s;
  }
  ApplySocketBufsize(fd);
  ApplyKeepalive(fd);
  *out_fd = fd;
  return Status::Ok();
}

// Retry transient connect failures (listener still coming up after a peer
// restart, SYN drop, routing blip) with exponential backoff inside a
// bounded window — TPUNET_CONNECT_RETRY_MS, default 10s, 0 = fail fast.
// The reference had no retry anywhere (SURVEY §5: "no retries, timeouts");
// this is the transient-rendezvous hardening VERDICT r1 asked for.
Status ConnectOne(const std::vector<NicInfo>& nics, int32_t dev, const SocketHandle& handle,
                  int* out_fd) {
  // Read per call, not statically cached: connects are rare, and callers
  // (tests, restart logic) legitimately adjust the window at runtime.
  const uint64_t window_ms = GetEnvU64("TPUNET_CONNECT_RETRY_MS", 10000);
  auto deadline = std::chrono::steady_clock::now() + std::chrono::milliseconds(window_ms);
  uint64_t backoff_ms = 50;
  while (true) {
    int cerr = 0;
    Status s = ConnectOneAttempt(nics, dev, handle, out_fd, &cerr);
    if (s.ok()) return s;
    bool transient = cerr == ECONNREFUSED || cerr == ETIMEDOUT || cerr == ECONNRESET ||
                     cerr == EHOSTUNREACH || cerr == ENETUNREACH || cerr == EAGAIN;
    if (!transient ||
        std::chrono::steady_clock::now() + std::chrono::milliseconds(backoff_ms) > deadline) {
      return s;
    }
    std::this_thread::sleep_for(std::chrono::milliseconds(backoff_ms));
    backoff_ms = std::min<uint64_t>(backoff_ms * 2, 1000);
  }
}

}  // namespace

Status ConnectBundle(const std::vector<NicInfo>& nics, int32_t dev, const SocketHandle& handle,
                     uint64_t nstreams, uint64_t min_chunksize, uint64_t flags,
                     std::vector<int>* data_fds, int* ctrl_fd) {
  uint64_t bundle = RandomBundleId();
  auto cleanup = [&]() {
    for (int fd : *data_fds) ::close(fd);
    data_fds->clear();
    if (*ctrl_fd >= 0) ::close(*ctrl_fd);
    *ctrl_fd = -1;
  };
  // nstreams data connections, each introducing itself with its stream id
  // (reference: nthread:313-327), then the ctrl connection with
  // stream_id == nstreams (reference: nthread:366-380).
  for (uint64_t sid = 0; sid <= nstreams; ++sid) {
    int fd = -1;
    Status s = ConnectOne(nics, dev, handle, &fd);
    if (!s.ok()) {
      cleanup();
      return s;
    }
    s = WritePreamble(fd, Preamble{bundle, sid, nstreams, min_chunksize, flags});
    if (!s.ok()) {
      ::close(fd);
      cleanup();
      return s;
    }
    if (sid < nstreams) {
      data_fds->push_back(fd);
    } else {
      *ctrl_fd = fd;
    }
  }
  return Status::Ok();
}

}  // namespace tpunet
