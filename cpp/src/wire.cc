// Shared wire protocol + rendezvous implementation. See wire.h.
#include "wire.h"

#include "dispatch.h"
#include "tpunet/qos.h"

#include <arpa/inet.h>
#include <errno.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <stdlib.h>
#include <string.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <random>
#include <thread>

namespace tpunet {

void RequestState::ReleaseQosAdmission() {
  if (qos_admitted == 0) return;
  if (qos_released.exchange(true, std::memory_order_acq_rel)) return;
  QosScheduler::Get().FinishMessage(static_cast<TrafficClass>(qos_cls),
                                    qos_admitted);
}

RequestState::~RequestState() { ReleaseQosAdmission(); }

socklen_t AddrLenForFamily(const sockaddr_storage& ss) {
  return ss.ss_family == AF_INET6 ? sizeof(sockaddr_in6) : sizeof(sockaddr_in);
}

Status MakeSocket(int family, int* out) {
  int fd = ::socket(family, SOCK_STREAM, 0);
  if (fd < 0) return Status::TCP("socket() failed: " + std::string(strerror(errno)));
  *out = fd;
  return Status::Ok();
}

Status WritePreamble(int fd, const Preamble& p) {
  uint8_t buf[48];
  EncodeU64BE(kWireMagic, buf);
  EncodeU64BE(p.bundle_id, buf + 8);
  EncodeU64BE(p.stream_id, buf + 16);
  EncodeU64BE(p.nstreams, buf + 24);
  EncodeU64BE(p.min_chunksize, buf + 32);
  EncodeU64BE(p.flags, buf + 40);
  return WriteAll(fd, buf, sizeof(buf));
}

Status CheckWireMagic(const uint8_t buf[8]) {
  uint64_t magic = DecodeU64BE(buf);
  if (magic == kWireMagic) return Status::Ok();
  if ((magic & kWireMagicPrefixMask) == (kWireMagic & kWireMagicPrefixMask)) {
    return Status::Version(
        "tpunet wire version mismatch: peer speaks framing v" +
        std::to_string(magic & 0xff) + ", this build speaks v" +
        std::to_string(kWireMagic & 0xff));
  }
  return Status::TCP("bad wire magic — peer is not tpunet");
}

Status ParsePreambleBytes(const uint8_t buf[kPreambleBytes], Preamble* p) {
  Status s = CheckWireMagic(buf);
  if (!s.ok()) return s;
  p->bundle_id = DecodeU64BE(buf + 8);
  p->stream_id = DecodeU64BE(buf + 16);
  p->nstreams = DecodeU64BE(buf + 24);
  p->min_chunksize = DecodeU64BE(buf + 32);
  p->flags = DecodeU64BE(buf + 40);
  // nstreams == 0 is legal ONLY for an SHM hello bundle (kPreambleFlagShm):
  // the ctrl connection is the bundle's sole member and the data path is
  // the shared-memory ring negotiated right after the preamble.
  bool shm = (p->flags & kPreambleFlagShm) != 0;
  if ((p->nstreams == 0 && !shm) || p->nstreams > kMaxStreams ||
      p->stream_id > p->nstreams || p->min_chunksize == 0) {
    return Status::TCP("malformed preamble: nstreams=" + std::to_string(p->nstreams) +
                       " stream_id=" + std::to_string(p->stream_id));
  }
  return Status::Ok();
}

Status ReadPreamble(int fd, Preamble* p, int timeout_ms) {
  uint8_t buf[kPreambleBytes];
  // Hard deadline over the whole 48 bytes — a slow-loris client trickling
  // one byte per interval cannot stretch this past timeout_ms. The magic is
  // checked as soon as its 8 bytes land so a mismatched-version peer (whose
  // preamble may be shorter) gets the typed verdict instead of a timeout.
  Status s = ReadExactDeadline(fd, buf, 8, timeout_ms);
  if (!s.ok()) return s;
  s = CheckWireMagic(buf);
  if (!s.ok()) return s;
  s = ReadExactDeadline(fd, buf + 8, sizeof(buf) - 8, timeout_ms);
  if (!s.ok()) return s;
  return ParsePreambleBytes(buf, p);
}

namespace {

// Blob byte -> enum name, or "#N" for a value past the enum's count (a
// corrupt or future-build peer must still produce a readable verdict).
template <typename E>
std::string BlobEnumName(uint8_t v, int count, const char* (*name)(E)) {
  return v < count ? std::string(name(static_cast<E>(v)))
                   : "#" + std::to_string(v);
}

}  // namespace

Status CheckPeerBootstrapBlob(const uint8_t* mine, const uint8_t* theirs,
                              int rank, int peer) {
  if (theirs[kBlobOffCodec] != mine[kBlobOffCodec]) {
    return Status::Codec(
        "wire codec mismatch: rank " + std::to_string(rank) + " uses " +
        BlobEnumName(mine[kBlobOffCodec], kWireCodecCount, WireCodecName) +
        " but rank " + std::to_string(peer) + " uses " +
        BlobEnumName(theirs[kBlobOffCodec], kWireCodecCount, WireCodecName) +
        " (set TPUNET_WIRE_DTYPE / wire_dtype identically on every rank)");
  }
  if (theirs[kBlobOffAlgo] != mine[kBlobOffAlgo]) {
    return Status::Invalid(
        "collective algo mismatch: rank " + std::to_string(rank) + " uses " +
        BlobEnumName(mine[kBlobOffAlgo], kCollAlgoCount, CollAlgoName) +
        " but rank " + std::to_string(peer) + " uses " +
        BlobEnumName(theirs[kBlobOffAlgo], kCollAlgoCount, CollAlgoName) +
        " (set TPUNET_ALGO / algo identically on every rank — ranks on "
        "different schedules deadlock)");
  }
  if (memcmp(theirs + kBlobOffTableCrc, mine + kBlobOffTableCrc, 4) != 0) {
    return Status::Invalid(
        "dispatch table mismatch: rank " + std::to_string(rank) +
        " and rank " + std::to_string(peer) +
        " loaded different TPUNET_DISPATCH_TABLE contents (every rank must "
        "see the same table or none — per-size selection must agree)");
  }
  if (theirs[kBlobOffQosClass] != mine[kBlobOffQosClass]) {
    return Status::Invalid(
        "traffic class mismatch: rank " + std::to_string(rank) + " uses " +
        BlobEnumName(mine[kBlobOffQosClass], kTrafficClassCount,
                     TrafficClassName) +
        " but rank " + std::to_string(peer) + " uses " +
        BlobEnumName(theirs[kBlobOffQosClass], kTrafficClassCount,
                     TrafficClassName) +
        " (set TPUNET_TRAFFIC_CLASS / traffic_class= identically on every "
        "rank — half a group on another QoS lane unbalances the "
        "scheduler)");
  }
  if (theirs[kBlobOffA2aAlgo] != mine[kBlobOffA2aAlgo]) {
    return Status::Invalid(
        "a2a algo mismatch: rank " + std::to_string(rank) + " uses " +
        BlobEnumName(mine[kBlobOffA2aAlgo], kCollAlgoCount, CollAlgoName) +
        " but rank " + std::to_string(peer) + " uses " +
        BlobEnumName(theirs[kBlobOffA2aAlgo], kCollAlgoCount, CollAlgoName) +
        " (set TPUNET_A2A_ALGO / TPUNET_A2A identically on every rank — "
        "half a world on the pairwise mesh and half on the two-stage "
        "transpose deadlocks)");
  }
  return Status::Ok();
}

uint64_t RandomBundleId() {
  static std::atomic<uint64_t> ctr{1};
  std::random_device rd;
  uint64_t hi = (static_cast<uint64_t>(rd()) << 32) ^ rd();
  return hi ^ (ctr.fetch_add(1) << 1) ^ (static_cast<uint64_t>(::getpid()) << 40);
}

void PartialBundle::CloseAll() {
  if (ctrl_fd >= 0) ::close(ctrl_fd);
  ctrl_fd = -1;
  for (auto& df : data_fds) ::close(df.second);
  data_fds.clear();
}

ListenSock::~ListenSock() {
  for (auto& kv : partials) kv.second.CloseAll();
  if (fd >= 0) ::close(fd);
  if (wake_fd >= 0) ::close(wake_fd);
}

Status ListenOn(const NicInfo& nic, int32_t dev, SocketHandle* handle, ListenSockPtr* out) {
  int fd = -1;
  Status s = MakeSocket(nic.addr.ss_family, &fd);
  if (!s.ok()) return s;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  // Bind to the NIC's address with an ephemeral port; the resulting
  // sockaddr IS the rendezvous handle (reference: nthread:259-303).
  sockaddr_storage bind_addr = nic.addr;
  if (bind_addr.ss_family == AF_INET) {
    reinterpret_cast<sockaddr_in*>(&bind_addr)->sin_port = 0;
  } else {
    reinterpret_cast<sockaddr_in6*>(&bind_addr)->sin6_port = 0;
  }
  if (::bind(fd, reinterpret_cast<sockaddr*>(&bind_addr), nic.addrlen) != 0) {
    ::close(fd);
    return Status::TCP("bind failed: " + std::string(strerror(errno)));
  }
  if (::listen(fd, kListenBacklog) != 0) {
    ::close(fd);
    return Status::TCP("listen failed: " + std::string(strerror(errno)));
  }
  auto lc = std::make_shared<ListenSock>();
  lc->fd = fd;
  lc->wake_fd = ::eventfd(0, EFD_CLOEXEC);
  if (lc->wake_fd < 0) {
    // Without the wake fd close_listen could never abort a parked accept().
    return Status::TCP("eventfd failed: " + std::string(strerror(errno)));
  }
  SetNonblocking(fd);  // accept() polls first; EAGAIN is handled
  lc->dev = dev;
  handle->addrlen = nic.addrlen;
  if (getsockname(fd, reinterpret_cast<sockaddr*>(&handle->addr), &handle->addrlen) != 0) {
    return Status::TCP("getsockname failed: " + std::string(strerror(errno)));
  }
  *out = std::move(lc);
  return Status::Ok();
}

void WakeListen(ListenSock* ls) {
  ls->closed.store(true, std::memory_order_release);
  if (ls->wake_fd >= 0) {
    uint64_t one = 1;
    (void)!::write(ls->wake_fd, &one, sizeof(one));
  }
}

Status AcceptBundle(ListenSock* lc, PartialBundle* out) {
  // Accept connections, grouping by bundle id, until one bundle is whole
  // (reference accepts exactly nstreams+1 and keys by raw id,
  // nthread:425-522; bundles make concurrent senders safe).
  MutexLock accept_lk(lc->mu);
  uint64_t expiry_ms = 2 * GetEnvU64("TPUNET_HANDSHAKE_TIMEOUT_MS", 10000);
  while (true) {
    // Expire half-arrived bundles from dead senders so their parked fds
    // don't accumulate toward RLIMIT_NOFILE on a long-lived listen comm.
    auto now = std::chrono::steady_clock::now();
    for (auto it = lc->partials.begin(); it != lc->partials.end();) {
      if (!it->second.Complete() &&
          now - it->second.first_seen > std::chrono::milliseconds(expiry_ms)) {
        it->second.CloseAll();
        it = lc->partials.erase(it);
      } else {
        ++it;
      }
    }
    for (auto it = lc->partials.begin(); it != lc->partials.end(); ++it) {
      if (it->second.Complete()) {
        *out = std::move(it->second);
        lc->partials.erase(it);
        return Status::Ok();
      }
    }
    // poll so close_listen can abort us via the eventfd (a blocked
    // ::accept is not reliably interruptible by shutdown() on Linux).
    // Finite timeout so the expiry sweep above runs even with no events.
    struct pollfd pfds[2] = {{lc->fd, POLLIN, 0}, {lc->wake_fd, POLLIN, 0}};
    int pr = ::poll(pfds, 2, 1000);
    if (pr < 0) {
      if (errno == EINTR) continue;
      return Status::TCP("poll failed: " + std::string(strerror(errno)));
    }
    if (pr == 0) continue;  // timeout tick: re-run expiry sweep
    if (lc->closed.load(std::memory_order_acquire) || (pfds[1].revents & POLLIN)) {
      return Status::Inner("listen comm closed while accepting");
    }
    if (!(pfds[0].revents & POLLIN)) continue;
    sockaddr_storage peer;
    socklen_t plen = sizeof(peer);
    int fd = ::accept(lc->fd, reinterpret_cast<sockaddr*>(&peer), &plen);
    if (fd < 0) {
      if (errno == EINTR || errno == EAGAIN || errno == EWOULDBLOCK) continue;
      return Status::TCP("accept failed: " + std::string(strerror(errno)));
    }
    Status s = SetNodelay(fd);
    if (!s.ok()) {
      ::close(fd);
      return s;
    }
    ApplySocketBufsize(fd);
    ApplyKeepalive(fd);
    // Bound the preamble read: a client that connects but never completes
    // the 40-byte handshake (scanner, stalled peer) must not wedge accept()
    // while it holds lc->mu. Malformed/timed-out clients are dropped and
    // accept keeps serving legitimate peers.
    uint64_t handshake_ms = GetEnvU64("TPUNET_HANDSHAKE_TIMEOUT_MS", 10000);
    Preamble p;
    s = ReadPreamble(fd, &p, static_cast<int>(handshake_ms));
    if (!s.ok()) {
      ::close(fd);
      continue;
    }
    PartialBundle& b = lc->partials[p.bundle_id];
    if (b.nstreams == UINT64_MAX) {
      b.nstreams = p.nstreams;
      b.min_chunksize = p.min_chunksize;
      b.flags = p.flags;
      b.first_seen = std::chrono::steady_clock::now();
    } else if (b.nstreams != p.nstreams || b.min_chunksize != p.min_chunksize ||
               b.flags != p.flags) {
      ::close(fd);  // inconsistent members: drop the whole bundle
      b.CloseAll();
      lc->partials.erase(p.bundle_id);
      continue;
    }
    if (p.stream_id == p.nstreams) {
      if (b.ctrl_fd >= 0) {
        ::close(fd);  // duplicate ctrl stream: keep the first
        continue;
      }
      b.ctrl_fd = fd;
    } else if (!b.data_fds.emplace(p.stream_id, fd).second) {
      ::close(fd);  // duplicate stream id: keep the first, drop the dup
      continue;
    }
  }
}

Status ParseLaneSpec(const std::string& spec, std::vector<LaneSpec>* out) {
  out->clear();
  size_t pos = 0;
  while (pos <= spec.size()) {
    size_t comma = spec.find(',', pos);
    std::string lane_tok = spec.substr(
        pos, comma == std::string::npos ? std::string::npos : comma - pos);
    pos = comma == std::string::npos ? spec.size() + 1 : comma + 1;
    if (lane_tok.empty()) {
      if (comma == std::string::npos && out->empty() && spec.empty()) break;
      return Status::Invalid("TPUNET_LANES: empty lane entry in \"" + spec + "\"");
    }
    LaneSpec lane;
    size_t lp = 0;
    while (lp <= lane_tok.size()) {
      // Clause separator is ':' at bracket depth 0 — IPv6 literals ride in
      // brackets ("addr=[fe80::1]:w=2") so their colons don't split.
      size_t colon = std::string::npos;
      int depth = 0;
      for (size_t i = lp; i < lane_tok.size(); ++i) {
        if (lane_tok[i] == '[') ++depth;
        else if (lane_tok[i] == ']') --depth;
        else if (lane_tok[i] == ':' && depth == 0) {
          colon = i;
          break;
        }
      }
      std::string kv = lane_tok.substr(
          lp, colon == std::string::npos ? std::string::npos : colon - lp);
      lp = colon == std::string::npos ? lane_tok.size() + 1 : colon + 1;
      if (kv.empty()) {
        return Status::Invalid("TPUNET_LANES: empty clause in lane \"" + lane_tok + "\"");
      }
      size_t eq = kv.find('=');
      if (eq == std::string::npos) {
        return Status::Invalid("TPUNET_LANES: clause \"" + kv + "\" is not key=value");
      }
      std::string key = kv.substr(0, eq);
      std::string val = kv.substr(eq + 1);
      if (key == "addr") {
        if (val.size() >= 2 && val.front() == '[' && val.back() == ']') {
          val = val.substr(1, val.size() - 2);  // bracketed IPv6 literal
        }
        // Validate now so a typo'd address fails at parse, not mid-connect.
        unsigned char scratch[sizeof(in6_addr)];
        if (val.empty() || (inet_pton(AF_INET, val.c_str(), scratch) != 1 &&
                            inet_pton(AF_INET6, val.c_str(), scratch) != 1)) {
          return Status::Invalid("TPUNET_LANES: \"" + val +
                                 "\" is not an IPv4/IPv6 address");
        }
        lane.addr = val;
      } else if (key == "w") {
        char* end = nullptr;
        unsigned long w = val.empty() ? 0 : strtoul(val.c_str(), &end, 10);
        if (val.empty() || (end && *end != '\0') || w < 1 || w > kMaxLaneWeight) {
          return Status::Invalid("TPUNET_LANES: weight \"" + val + "\" must be 1.." +
                                 std::to_string(kMaxLaneWeight));
        }
        lane.weight = static_cast<uint32_t>(w);
      } else {
        return Status::Invalid("TPUNET_LANES: unknown key \"" + key + "\"");
      }
    }
    out->push_back(std::move(lane));
  }
  if (out->size() > kMaxStreams) {
    return Status::Invalid("TPUNET_LANES: " + std::to_string(out->size()) +
                           " lanes exceeds the stream cap of " +
                           std::to_string(kMaxStreams));
  }
  return Status::Ok();
}

namespace {

// Resolve a lane's local bind address string into a sockaddr (port 0).
bool LaneBindAddr(const std::string& addr, sockaddr_storage* ss, socklen_t* len) {
  memset(ss, 0, sizeof(*ss));
  auto* v4 = reinterpret_cast<sockaddr_in*>(ss);
  auto* v6 = reinterpret_cast<sockaddr_in6*>(ss);
  if (inet_pton(AF_INET, addr.c_str(), &v4->sin_addr) == 1) {
    v4->sin_family = AF_INET;
    *len = sizeof(sockaddr_in);
    return true;
  }
  if (inet_pton(AF_INET6, addr.c_str(), &v6->sin6_addr) == 1) {
    v6->sin6_family = AF_INET6;
    *len = sizeof(sockaddr_in6);
    return true;
  }
  return false;
}

Status ConnectOneAttempt(const std::vector<NicInfo>& nics, int32_t dev,
                         const SocketHandle& handle, const std::string& lane_addr,
                         int* out_fd, int* conn_errno) {
  int fd = -1;
  Status s = MakeSocket(handle.addr.ss_family, &fd);
  if (!s.ok()) return s;
  if (!lane_addr.empty()) {
    // Lane-pinned local address (docs/DESIGN.md "Lanes & adaptive
    // striping"): the bind selects the egress path (NIC / source-routed
    // table) this data stream rides. Unlike the best-effort NIC bind below,
    // a failed lane bind is a hard error — silently collapsing two lanes
    // onto one path would fake the aggregation the operator configured.
    sockaddr_storage local;
    socklen_t llen = 0;
    if (!LaneBindAddr(lane_addr, &local, &llen) ||
        ::bind(fd, reinterpret_cast<sockaddr*>(&local), llen) != 0) {
      ::close(fd);
      return Status::TCP("lane bind to " + lane_addr +
                         " failed: " + std::string(strerror(errno)));
    }
  } else {
    // Route out of the chosen NIC when address families line up.
    const NicInfo& nic = nics[dev];
    if (nic.addr.ss_family == handle.addr.ss_family && nic.name != "lo") {
      sockaddr_storage local = nic.addr;
      if (local.ss_family == AF_INET) {
        reinterpret_cast<sockaddr_in*>(&local)->sin_port = 0;
      } else {
        reinterpret_cast<sockaddr_in6*>(&local)->sin6_port = 0;
      }
      ::bind(fd, reinterpret_cast<sockaddr*>(&local), nic.addrlen);  // best effort
    }
  }
  // addrlen is derived from the family, not trusted from the handle: a
  // handle marshaled through the 64-byte wire blob (C ABI / ncclNet shim)
  // carries only the sockaddr bytes.
  socklen_t alen = AddrLenForFamily(handle.addr);
  if (::connect(fd, reinterpret_cast<const sockaddr*>(&handle.addr), alen) != 0) {
    // POSIX: after EINTR the connect proceeds asynchronously — retrying
    // ::connect() yields EALREADY. Wait for writability + check SO_ERROR.
    bool pending = (errno == EINTR || errno == EINPROGRESS || errno == EALREADY);
    if (!pending) {
      *conn_errno = errno;
      ::close(fd);
      return Status::TCP("connect to " + SockaddrToString(handle.addr, alen) +
                         " failed: " + std::string(strerror(errno)));
    }
    struct pollfd pfd = {fd, POLLOUT, 0};
    int pr;
    do {
      pr = ::poll(&pfd, 1, -1);
    } while (pr < 0 && errno == EINTR);
    int soerr = 0;
    socklen_t slen = sizeof(soerr);
    if (pr < 0 || getsockopt(fd, SOL_SOCKET, SO_ERROR, &soerr, &slen) != 0 || soerr != 0) {
      *conn_errno = soerr ? soerr : errno;
      ::close(fd);
      return Status::TCP("connect to " + SockaddrToString(handle.addr, alen) +
                         " failed: " + std::string(strerror(soerr ? soerr : errno)));
    }
  }
  s = SetNodelay(fd);  // reference: nthread:329
  if (!s.ok()) {
    ::close(fd);
    return s;
  }
  ApplySocketBufsize(fd);
  ApplyKeepalive(fd);
  *out_fd = fd;
  return Status::Ok();
}

// Retry transient connect failures (listener still coming up after a peer
// restart, SYN drop, routing blip) with exponential backoff inside a
// bounded window — TPUNET_CONNECT_RETRY_MS, default 10s, 0 = fail fast.
// The reference had no retry anywhere (SURVEY §5: "no retries, timeouts");
// this is the transient-rendezvous hardening VERDICT r1 asked for.
Status ConnectOne(const std::vector<NicInfo>& nics, int32_t dev, const SocketHandle& handle,
                  const std::string& lane_addr, int* out_fd) {
  // Read per call, not statically cached: connects are rare, and callers
  // (tests, restart logic) legitimately adjust the window at runtime.
  const uint64_t window_ms = GetEnvU64("TPUNET_CONNECT_RETRY_MS", 10000);
  auto deadline = std::chrono::steady_clock::now() + std::chrono::milliseconds(window_ms);
  uint64_t backoff_ms = 50;
  while (true) {
    int cerr = 0;
    Status s = ConnectOneAttempt(nics, dev, handle, lane_addr, out_fd, &cerr);
    if (s.ok()) return s;
    bool transient = cerr == ECONNREFUSED || cerr == ETIMEDOUT || cerr == ECONNRESET ||
                     cerr == EHOSTUNREACH || cerr == ENETUNREACH || cerr == EAGAIN;
    if (!transient ||
        std::chrono::steady_clock::now() + std::chrono::milliseconds(backoff_ms) > deadline) {
      return s;
    }
    std::this_thread::sleep_for(std::chrono::milliseconds(backoff_ms));
    backoff_ms = std::min<uint64_t>(backoff_ms * 2, 1000);
  }
}

}  // namespace

Status ConnectBundle(const std::vector<NicInfo>& nics, int32_t dev, const SocketHandle& handle,
                     uint64_t nstreams, uint64_t min_chunksize, uint64_t flags,
                     std::vector<int>* data_fds, int* ctrl_fd,
                     const std::vector<LaneSpec>* lanes) {
  uint64_t bundle = RandomBundleId();
  auto cleanup = [&]() {
    for (int fd : *data_fds) ::close(fd);
    data_fds->clear();
    if (*ctrl_fd >= 0) ::close(*ctrl_fd);
    *ctrl_fd = -1;
  };
  static const std::string kNoLaneAddr;
  // nstreams data connections, each introducing itself with its stream id
  // (reference: nthread:313-327), then the ctrl connection with
  // stream_id == nstreams (reference: nthread:366-380). In lane mode each
  // data stream binds its lane's local address; ctrl stays on the default
  // path (it must survive any single lane's death).
  for (uint64_t sid = 0; sid <= nstreams; ++sid) {
    const std::string& lane_addr =
        (lanes && sid < lanes->size()) ? (*lanes)[sid].addr : kNoLaneAddr;
    int fd = -1;
    Status s = ConnectOne(nics, dev, handle, lane_addr, &fd);
    if (!s.ok()) {
      cleanup();
      return s;
    }
    s = WritePreamble(fd, Preamble{bundle, sid, nstreams, min_chunksize, flags});
    if (!s.ok()) {
      ::close(fd);
      cleanup();
      return s;
    }
    if (sid < nstreams) {
      data_fds->push_back(fd);
    } else {
      *ctrl_fd = fd;
    }
  }
  return Status::Ok();
}

}  // namespace tpunet
