// Shared wire protocol + rendezvous for both transport engines.
//
// The connection-establishment contract is engine-independent (SURVEY §2.2
// steps 1-3; reference: nthread_per_socket_backend.rs:259-522): listen binds
// an ephemeral socket whose sockaddr is the 64-byte rendezvous handle;
// connect opens nstreams data connections + 1 ctrl connection, each opening
// with a preamble; accept groups arriving connections into bundles until one
// sender's bundle is complete. Engines differ only in how they move bytes
// after the bundle is wired (thread-per-stream vs epoll event loop), so this
// file owns everything up to that point — guaranteeing the two engines are
// wire-compatible (unlike the reference's BASIC/TOKIO pair, which framed
// lengths differently and could not interoperate; tokio_backend.rs:456).
#ifndef TPUNET_WIRE_H_
#define TPUNET_WIRE_H_

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

#include "tpunet/net.h"
#include "tpunet/utils.h"

namespace tpunet {

constexpr uint64_t kWireMagic = 0x7470756e65743102ull;  // "tpunet" + wire ver 2
constexpr int kListenBacklog = 16384;  // reference: nthread:101
constexpr uint64_t kMaxStreams = 256;  // sanity bound on peer-supplied nstreams

socklen_t AddrLenForFamily(const sockaddr_storage& ss);

Status MakeSocket(int family, int* out);

// Connection preamble: both chunk-map inputs (nstreams AND min_chunksize)
// travel with the sender so the two sides can never compute divergent chunk
// boundaries from mismatched env config — the sender's values win.
// [magic u64 | bundle_id u64 | stream_id u64 | nstreams u64 |
//  min_chunksize u64], all big-endian. stream_id == nstreams marks the ctrl
// connection (reference: nthread:380).
struct Preamble {
  uint64_t bundle_id = 0;
  uint64_t stream_id = 0;
  uint64_t nstreams = 0;
  uint64_t min_chunksize = 0;
};

Status WritePreamble(int fd, const Preamble& p);
// Bounded by timeout_ms over the WHOLE 40 bytes (slow-loris defense).
Status ReadPreamble(int fd, Preamble* p, int timeout_ms);

uint64_t RandomBundleId();

// Request completion accounting, shared by both engines.
// Reference: RequestState{nsubtasks, completed_subtasks, nbytes_transferred,
// err} (nthread:54-60). `total` doubles as the "scheduled" flag: UINT64_MAX
// until the scheduler has chunked the message.
struct RequestState {
  std::atomic<uint64_t> total{UINT64_MAX};
  std::atomic<uint64_t> completed{0};
  std::atomic<uint64_t> nbytes{0};
  std::atomic<bool> failed{false};
  std::mutex err_mu;
  std::string err_msg;

  void SetError(const std::string& m) {
    {
      std::lock_guard<std::mutex> lk(err_mu);
      if (err_msg.empty()) err_msg = m;
    }
    failed.store(true, std::memory_order_release);
  }
  std::string ErrorMsg() {
    std::lock_guard<std::mutex> lk(err_mu);
    return err_msg;
  }
  bool Done() const {
    uint64_t t = total.load(std::memory_order_acquire);
    return t != UINT64_MAX && completed.load(std::memory_order_acquire) >= t;
  }

  // Blocking-wait support (the polling test() loop starves worker threads of
  // CPU on small hosts — a single-core box loses ~5x allreduce throughput to
  // it). Completion sites call NotifyIfSettled() after updating the atomics;
  // waiters park on the condvar. The atomics are written BEFORE the notify
  // takes err_mu, and the waiter's predicate runs under err_mu, so the wakeup
  // cannot be lost; the wait_for timeout is belt-and-braces only.
  void NotifyIfSettled() {
    if (!Done() && !failed.load(std::memory_order_acquire)) return;
    std::lock_guard<std::mutex> lk(err_mu);
    cv.notify_all();
  }
  void WaitSettled() {
    std::unique_lock<std::mutex> lk(err_mu);
    while (!Done() && !failed.load(std::memory_order_acquire)) {
      cv.wait_for(lk, std::chrono::milliseconds(100));
    }
  }
  // Bounded settle-wait; returns whether the request settled. Used by the
  // BASIC engine's wait() to detect "not settling promptly" and break any
  // cross-request coupling with parked lazy recvs.
  bool WaitSettledFor(int ms) {
    std::unique_lock<std::mutex> lk(err_mu);
    if (Done() || failed.load(std::memory_order_acquire)) return true;
    cv.wait_for(lk, std::chrono::milliseconds(ms));
    return Done() || failed.load(std::memory_order_acquire);
  }

  std::condition_variable cv;
};
using RequestPtr = std::shared_ptr<RequestState>;

// Parked connection bundle on a listen socket, keyed by bundle id, until all
// nstreams+1 members have arrived.
struct PartialBundle {
  uint64_t nstreams = UINT64_MAX;
  uint64_t min_chunksize = 0;
  int ctrl_fd = -1;
  std::chrono::steady_clock::time_point first_seen;
  std::map<uint64_t, int> data_fds;  // stream_id -> fd (ordered)
  bool Complete() const {
    return ctrl_fd >= 0 && nstreams != UINT64_MAX && data_fds.size() == nstreams;
  }
  void CloseAll();
};

// A listening socket + the bundle-grouping state accept() needs.
struct ListenSock {
  int fd = -1;
  int wake_fd = -1;  // eventfd; close_listen signals it to abort a blocked accept
  int32_t dev = 0;
  std::atomic<bool> closed{false};
  std::mutex mu;  // guards partials; accept() may be called from many threads
  std::map<uint64_t, PartialBundle> partials;

  ~ListenSock();
};
using ListenSockPtr = std::shared_ptr<ListenSock>;

// Bind an ephemeral listening socket on `nic`; fills the rendezvous handle.
Status ListenOn(const NicInfo& nic, int32_t dev, SocketHandle* handle, ListenSockPtr* out);

// Signal a (possibly) blocked AcceptBundle to abort with "closed".
void WakeListen(ListenSock* ls);

// Accept connections, grouping by bundle id, until one sender's bundle is
// whole; expires half-arrived bundles from dead senders. Blocks.
Status AcceptBundle(ListenSock* ls, PartialBundle* out);

// Open the nstreams+1 connection bundle to a remote handle, writing each
// preamble. On success data_fds holds nstreams stream-ordered connections
// and ctrl_fd the ctrl connection; all blocking, TCP_NODELAY set.
Status ConnectBundle(const std::vector<NicInfo>& nics, int32_t dev, const SocketHandle& handle,
                     uint64_t nstreams, uint64_t min_chunksize, std::vector<int>* data_fds,
                     int* ctrl_fd);

}  // namespace tpunet

#endif  // TPUNET_WIRE_H_
