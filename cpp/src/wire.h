// Shared wire protocol + rendezvous for both transport engines.
//
// The connection-establishment contract is engine-independent (SURVEY §2.2
// steps 1-3; reference: nthread_per_socket_backend.rs:259-522): listen binds
// an ephemeral socket whose sockaddr is the 64-byte rendezvous handle;
// connect opens nstreams data connections + 1 ctrl connection, each opening
// with a preamble; accept groups arriving connections into bundles until one
// sender's bundle is complete. Engines differ only in how they move bytes
// after the bundle is wired (thread-per-stream vs epoll event loop), so this
// file owns everything up to that point — guaranteeing the two engines are
// wire-compatible (unlike the reference's BASIC/TOKIO pair, which framed
// lengths differently and could not interoperate; tokio_backend.rs:456).
#ifndef TPUNET_WIRE_H_
#define TPUNET_WIRE_H_

#include <atomic>
#include <chrono>
#include <cstdint>
#include <functional>
#include <map>
#include <memory>
#include <string>
#include <vector>

#include "flightrec.h"
#include "tpunet/mutex.h"
#include "tpunet/net.h"
#include "tpunet/utils.h"

namespace tpunet {

// Wire framing version 3: the preamble grew a flags word (CRC32C chunk
// trailers are negotiated there) and the ctrl stream gained failover frames.
// The low byte of the magic is the version; a peer whose magic matches the
// 7-byte "tpunet1" prefix but not the version byte gets a typed kVersion
// error instead of the generic bad-magic TCPError.
constexpr uint64_t kWireMagic = 0x7470756e65743103ull;  // "tpunet" + wire ver 3
constexpr uint64_t kWireMagicPrefixMask = 0xffffffffffffff00ull;
constexpr int kListenBacklog = 16384;  // reference: nthread:101
constexpr uint64_t kMaxStreams = 256;  // sanity bound on peer-supplied nstreams

// Preamble flag bits (sender-advertised; like nstreams/min_chunksize the
// sender's values win so the two sides can never disagree).
constexpr uint64_t kPreambleFlagCrc = 1ull << 0;
// QoS advertisement (docs/DESIGN.md "Transport QoS"): the sender speaks the
// traffic-class protocol and its class nibble is valid at bits 8..11. The
// class rides the per-connection header (the preamble) rather than each
// chunk: a TCP stream's class is constant for its lifetime, so per-chunk
// repetition would be pure wire overhead — the receiver accounts every
// chunk on the connection under this nibble. Peers without the flag (older
// builds) default to the bulk class.
constexpr uint64_t kPreambleFlagQos = 1ull << 1;
// Lane capability (docs/DESIGN.md "Lanes & adaptive striping"): the sender
// runs the weighted stripe scheduler and may publish weight-vector epochs
// over the ctrl stream (kCtrlFrameWeights). Advertised ONLY when lanes are
// actually configured (TPUNET_LANES) so the default single-path config
// stays byte-identical on the wire to pre-lane builds. Sender-wins like
// nstreams: a receiver seeing the bit switches both its chunk->stream
// derivation and its ctrl-frame vocabulary to the lane protocol.
constexpr uint64_t kPreambleFlagLanes = 1ull << 2;
// Shared-memory transport (docs/DESIGN.md "Intra-host shared memory"): the
// connection is an SHM HELLO — nstreams is 0 (no TCP data streams; the
// payload path is the mmap'd ring segment negotiated right after the
// preamble on this very connection, which then stays on as the comm's ctrl
// stream carrying LEN frames exactly like a TCP comm's). Only the SHM
// engine (TPUNET_SHM=1) advertises the bit; a plain engine receiving it
// rejects the bundle loudly instead of wiring a zero-stream comm.
constexpr uint64_t kPreambleFlagShm = 1ull << 3;
constexpr int kPreambleClassShift = 8;
constexpr uint64_t kPreambleClassMask = 0xFull << kPreambleClassShift;

inline uint64_t PreambleClassBits(int32_t cls) {
  return kPreambleFlagQos |
         ((static_cast<uint64_t>(cls) << kPreambleClassShift) &
          kPreambleClassMask);
}
// Class nibble from a received preamble flags word; bulk (1) when the peer
// predates QoS or advertises an unknown class.
inline int32_t PreambleClassOf(uint64_t flags) {
  if ((flags & kPreambleFlagQos) == 0) return 1;
  int32_t cls = static_cast<int32_t>((flags & kPreambleClassMask) >>
                                     kPreambleClassShift);
  return cls >= 0 && cls < 3 ? cls : 1;
}

// Ctrl-stream frame vocabulary. A plain message length frame is a raw
// big-endian u64 < 2^56; frames with a reserved top byte are transport
// control frames (failover protocol, basic_engine.cc):
//   0xFD  NACK (receiver -> sender): data stream died; bits 48..55 carry the
//         stream index, bits 0..47 the count of chunks the receiver fully
//         read off that stream — i.e. the first per-stream chunk seq it
//         still needs.
//   0xFE  FAILOVER marker (sender -> receiver): stream index in bits
//         48..55, retransmit-unit count in bits 0..47; followed on the ctrl
//         stream by one u64 (the receiver-confirmed seq the batch starts
//         at) and then count units of [seq u64 | len u64 | payload |
//         crc32c u32 when negotiated]. From this point in ctrl order both
//         sides drop the stream from the chunk-assignment rotation.
//   0xFC  WEIGHTS epoch (sender -> receiver, lane mode only): bits 32..47
//         carry the stream count (must equal the comm's nstreams — a
//         mismatch is a protocol desync), bits 0..31 the strictly-
//         increasing stripe epoch; followed on the ctrl stream by one u8
//         weight (1..255) per stream. From this point in ctrl order both
//         sides derive chunk->stream layout from the NEW weight vector —
//         re-striping lands only at message boundaries because the frame is
//         emitted under the same lock (and so the same total order) as
//         message length frames.
constexpr uint8_t kCtrlFrameNack = 0xFD;
constexpr uint8_t kCtrlFrameFailover = 0xFE;
constexpr uint8_t kCtrlFrameWeights = 0xFC;
// Lengths at or above this collide with the control-frame namespace; no
// real message gets near 2^56 bytes.
constexpr uint64_t kMaxCtrlLen = 1ull << 56;

// Decoded view of one ctrl-stream u64. The decode is TOTAL: every u64 is
// exactly one of LEN / NACK / FAILOVER / WEIGHTS / bogus, so every receiver
// branches on the same classification instead of re-deriving `frame >> 56`
// locally (tools/protocol cross-checks the opcode constants; this function
// is the single in-tree decoder the fuzz harness drives).
enum class CtrlFrameKind : uint8_t {
  kLen = 0,       // plain message length, frame < kMaxCtrlLen
  kNack,          // 0xFD
  kFailover,      // 0xFE
  kWeights,       // 0xFC
  kBogus,         // reserved top byte — protocol desync
};
struct CtrlFrameView {
  CtrlFrameKind kind = CtrlFrameKind::kBogus;
  uint64_t len = 0;       // kLen: the message length
  uint64_t stream = 0;    // kNack/kFailover: bits 48..55
  uint64_t arg = 0;       // kNack: confirmed seq; kFailover: unit count
  uint64_t nstreams = 0;  // kWeights: bits 32..47
  uint64_t epoch = 0;     // kWeights: bits 0..31
};
inline CtrlFrameView DecodeCtrlFrame(uint64_t frame) {
  CtrlFrameView v;
  if (frame < kMaxCtrlLen) {
    v.kind = CtrlFrameKind::kLen;
    v.len = frame;
    return v;
  }
  switch (static_cast<uint8_t>(frame >> 56)) {
    case kCtrlFrameNack:
      v.kind = CtrlFrameKind::kNack;
      v.stream = (frame >> 48) & 0xff;
      v.arg = frame & 0xffffffffffffull;
      break;
    case kCtrlFrameFailover:
      v.kind = CtrlFrameKind::kFailover;
      v.stream = (frame >> 48) & 0xff;
      v.arg = frame & 0xffffffffffffull;
      break;
    case kCtrlFrameWeights:
      v.kind = CtrlFrameKind::kWeights;
      v.nstreams = (frame >> 32) & 0xffff;
      v.epoch = frame & 0xffffffff;
      break;
    default:
      v.kind = CtrlFrameKind::kBogus;
      break;
  }
  return v;
}

// ---- Bootstrap config blob (collectives.cc handshake) ----------------------
// The 16-byte per-rank unit of the schedule-config AllGather that precedes
// any wiring: [codec u8 | algo u8 | table_crc u32 BE | qos_class u8 |
// a2a_algo u8 | host_id u64 BE]. The config bytes (offsets 0..7) must match
// on every rank; the host id legitimately differs (it is the hierarchical
// topology input). tools/protocol checks the offsets below are
// non-overlapping, cover the blob exactly, and are each used by both the
// encode and the peer-validation sides.
constexpr size_t kBootstrapBlobLen = 16;
constexpr size_t kBlobOffCodec = 0;     // WireCodec as one byte
constexpr size_t kBlobOffAlgo = 1;      // CollAlgo override as one byte
constexpr size_t kBlobOffTableCrc = 2;  // dispatch-table CRC32C, u32 BE
constexpr size_t kBlobOffQosClass = 6;  // TrafficClass as one byte
constexpr size_t kBlobOffA2aAlgo = 7;   // AllToAll CollAlgo as one byte
constexpr size_t kBlobOffHostId = 8;    // HostId(), u64 BE

// Validate one peer's bootstrap blob against ours (pure — collectives.cc
// calls it per rank after the AllGather; the fuzz harness drives it with
// arbitrary peer bytes). `rank`/`peer` only flavor the error text.
Status CheckPeerBootstrapBlob(const uint8_t* mine, const uint8_t* theirs,
                              int rank, int peer);

inline uint64_t PackCtrlFrame(uint8_t type, uint64_t stream, uint64_t arg) {
  return (static_cast<uint64_t>(type) << 56) | ((stream & 0xff) << 48) |
         (arg & 0xffffffffffffull);
}

// WEIGHTS frame layout (the 8-bit stream field of PackCtrlFrame cannot hold
// kMaxStreams == 256, so the count rides bits 32..47 instead).
inline uint64_t PackWeightsFrame(uint64_t nstreams, uint64_t epoch) {
  return (static_cast<uint64_t>(kCtrlFrameWeights) << 56) |
         ((nstreams & 0xffff) << 32) | (epoch & 0xffffffff);
}
inline uint64_t WeightsFrameCount(uint64_t frame) { return (frame >> 32) & 0xffff; }
inline uint64_t WeightsFrameEpoch(uint64_t frame) { return frame & 0xffffffff; }

// Serialize one WEIGHTS ctrl unit ([frame u64][w u8 x n]) into buf, which
// must hold 8 + weights.size() bytes. Returns the unit length.
inline size_t BuildWeightsUnit(uint64_t epoch, const std::vector<uint32_t>& weights,
                               uint8_t* buf) {
  EncodeU64BE(PackWeightsFrame(weights.size(), epoch), buf);
  for (size_t i = 0; i < weights.size(); ++i) {
    uint32_t w = weights[i];
    if (w < 1) w = 1;
    if (w > 255) w = 255;
    buf[8 + i] = static_cast<uint8_t>(w);
  }
  return 8 + weights.size();
}

// 4-byte big-endian CRC32C chunk trailer (TPUNET_CRC=1, negotiated via
// kPreambleFlagCrc).
inline void EncodeU32BE(uint32_t v, uint8_t out[4]) {
  out[0] = static_cast<uint8_t>(v >> 24);
  out[1] = static_cast<uint8_t>(v >> 16);
  out[2] = static_cast<uint8_t>(v >> 8);
  out[3] = static_cast<uint8_t>(v);
}
inline uint32_t DecodeU32BE(const uint8_t in[4]) {
  return static_cast<uint32_t>(in[0]) << 24 | static_cast<uint32_t>(in[1]) << 16 |
         static_cast<uint32_t>(in[2]) << 8 | static_cast<uint32_t>(in[3]);
}

socklen_t AddrLenForFamily(const sockaddr_storage& ss);

Status MakeSocket(int family, int* out);

// Connection preamble: both chunk-map inputs (nstreams AND min_chunksize)
// travel with the sender so the two sides can never compute divergent chunk
// boundaries from mismatched env config — the sender's values win, and so
// does the flags word (CRC32C trailers on data chunks, kPreambleFlagCrc).
// [magic u64 | bundle_id u64 | stream_id u64 | nstreams u64 |
//  min_chunksize u64 | flags u64], all big-endian. stream_id == nstreams
// marks the ctrl connection (reference: nthread:380).
struct Preamble {
  uint64_t bundle_id = 0;
  uint64_t stream_id = 0;
  uint64_t nstreams = 0;
  uint64_t min_chunksize = 0;
  uint64_t flags = 0;
};

constexpr size_t kPreambleBytes = 48;  // 6 big-endian u64s

Status WritePreamble(int fd, const Preamble& p);
// Pure preamble parsing, split at the same boundary the wire read is: the
// magic word is checked as soon as its 8 bytes land (a mismatched-version
// peer's preamble may be shorter than ours), then the remaining 40 bytes
// decode + validate. Both are fuzz targets (cpp/fuzz/fuzz_preamble.cc);
// ReadPreamble is the fd-facing wrapper.
Status CheckWireMagic(const uint8_t buf[8]);
Status ParsePreambleBytes(const uint8_t buf[kPreambleBytes], Preamble* p);
// Bounded by timeout_ms over the WHOLE 48 bytes (slow-loris defense).
// A magic whose "tpunet1" prefix matches but whose version byte differs
// returns a typed kVersion status (framing-version negotiation).
Status ReadPreamble(int fd, Preamble* p, int timeout_ms);

uint64_t RandomBundleId();

// Request completion accounting, shared by both engines.
// Reference: RequestState{nsubtasks, completed_subtasks, nbytes_transferred,
// err} (nthread:54-60). `total` doubles as the "scheduled" flag: UINT64_MAX
// until the scheduler has chunked the message.
struct RequestState {
  std::atomic<uint64_t> total{UINT64_MAX};
  std::atomic<uint64_t> completed{0};
  std::atomic<uint64_t> nbytes{0};
  std::atomic<bool> failed{false};
  // err_mu is a LEAF of the lock hierarchy (docs/DESIGN.md "Concurrency
  // model"): completion paths take it while holding fo_mu/ctrl_mu/EComm::mu,
  // so nothing may be acquired under it.
  Mutex err_mu;
  std::string err_msg GUARDED_BY(err_mu);
  // Error kind carried alongside the message so typed failures (corruption,
  // watchdog timeout, version mismatch) survive the trip through test()/
  // wait() to the C ABI instead of collapsing into kInnerError.
  ErrorKind err_kind GUARDED_BY(err_mu) = ErrorKind::kInnerError;
  // Progress-watchdog abort hook: set at request creation (only when
  // TPUNET_PROGRESS_TIMEOUT_MS > 0) to shut down the owning comm's sockets
  // so blocked workers quiesce after a timeout verdict. Captures a weak
  // reference — the comm may die first.
  std::function<void()> on_stall;

  // QoS admission accounting (docs/DESIGN.md "Transport QoS"): bytes this
  // send charged against its traffic class's in-flight budget at isend
  // time. Returned EXACTLY ONCE — at test()/wait() consumption on both
  // engines, with the destructor as the backstop for requests that are
  // never polled (close-time drains). qos_admitted == 0 means the class is
  // unbudgeted and nothing was charged.
  uint8_t qos_cls = 1;  // TrafficClass int (qos.h)
  uint64_t qos_admitted = 0;
  std::atomic<bool> qos_released{false};
  void ReleaseQosAdmission();  // defined in wire.cc (needs qos.h)
  ~RequestState();

  // Stage-latency clock points (telemetry stage histograms, docs/DESIGN.md
  // "Observability"): t_post_us is stamped by the engine at isend/irecv;
  // the data-path IO stamps first/last wire byte. first is CAS-from-0 so
  // whichever chunk touches the wire first wins regardless of stream.
  uint64_t t_post_us = 0;
  std::atomic<uint64_t> t_first_wire_us{0};
  std::atomic<uint64_t> t_last_wire_us{0};
  void MarkWireStart(uint64_t now_us) {
    uint64_t expect = 0;
    t_first_wire_us.compare_exchange_strong(expect, now_us, std::memory_order_relaxed);
  }
  void MarkWireEnd(uint64_t now_us) {
    t_last_wire_us.store(now_us, std::memory_order_relaxed);
  }

  void SetError(const std::string& m) { SetError(ErrorKind::kInnerError, m); }
  void SetError(ErrorKind k, const std::string& m) {
    {
      MutexLock lk(err_mu);
      if (err_msg.empty()) {
        err_msg = m;
        err_kind = k;
      }
    }
    failed.store(true, std::memory_order_release);
    // Terminal-verdict hook (docs/DESIGN.md §6c): the watchdog and CRC
    // verdicts auto-dump the flight recorder AT the raise site — by the
    // time the typed error surfaces through test()/wait() the interesting
    // ring contents may already be lapped. Rate-limited inside.
    if (k == ErrorKind::kTimeout) {
      flightrec::DumpOnVerdict("watchdog", static_cast<uint64_t>(k));
    } else if (k == ErrorKind::kCorruption) {
      flightrec::DumpOnVerdict("corruption", static_cast<uint64_t>(k));
    }
  }
  std::string ErrorMsg() {
    MutexLock lk(err_mu);
    return err_msg;
  }
  // The kind recorded by the first SetError (first error wins, like the msg).
  ErrorKind ErrKind() {
    MutexLock lk(err_mu);
    return err_kind;
  }
  bool Done() const {
    uint64_t t = total.load(std::memory_order_acquire);
    return t != UINT64_MAX && completed.load(std::memory_order_acquire) >= t;
  }

  // Blocking-wait support (the polling test() loop starves worker threads of
  // CPU on small hosts — a single-core box loses ~5x allreduce throughput to
  // it). Completion sites call NotifyIfSettled() after updating the atomics;
  // waiters park on the condvar. The atomics are written BEFORE the notify
  // takes err_mu, and the waiter's predicate runs under err_mu, so the wakeup
  // cannot be lost; the wait_for timeout is belt-and-braces only.
  void NotifyIfSettled() {
    if (!Done() && !failed.load(std::memory_order_acquire)) return;
    MutexLock lk(err_mu);
    cv.NotifyAll();
  }
  void WaitSettled() {
    MutexLock lk(err_mu);
    while (!Done() && !failed.load(std::memory_order_acquire)) {
      cv.WaitFor(err_mu, 100);
    }
  }
  // Bounded settle-wait; returns whether the request settled. Used by the
  // BASIC engine's wait() to detect "not settling promptly" and break any
  // cross-request coupling with parked lazy recvs.
  bool WaitSettledFor(int ms) {
    MutexLock lk(err_mu);
    if (Done() || failed.load(std::memory_order_acquire)) return true;
    cv.WaitFor(err_mu, ms);
    return Done() || failed.load(std::memory_order_acquire);
  }

  CondVar cv;
};
using RequestPtr = std::shared_ptr<RequestState>;

// Parked connection bundle on a listen socket, keyed by bundle id, until all
// nstreams+1 members have arrived.
struct PartialBundle {
  uint64_t nstreams = UINT64_MAX;
  uint64_t min_chunksize = 0;
  uint64_t flags = 0;  // sender-advertised preamble flags (CRC etc.)
  int ctrl_fd = -1;
  std::chrono::steady_clock::time_point first_seen;
  std::map<uint64_t, int> data_fds;  // stream_id -> fd (ordered)
  bool Complete() const {
    return ctrl_fd >= 0 && nstreams != UINT64_MAX && data_fds.size() == nstreams;
  }
  void CloseAll();
};

// A listening socket + the bundle-grouping state accept() needs.
struct ListenSock {
  int fd = -1;
  int wake_fd = -1;  // eventfd; close_listen signals it to abort a blocked accept
  int32_t dev = 0;
  std::atomic<bool> closed{false};
  Mutex mu;  // serializes AcceptBundle callers; leaf lock
  std::map<uint64_t, PartialBundle> partials GUARDED_BY(mu);

  ~ListenSock();
};
using ListenSockPtr = std::shared_ptr<ListenSock>;

// Internal seam for composing engines (the SHM engine fronts a TCP engine
// on ONE listen socket): an engine that can adopt an already-accepted
// connection bundle into its receive path, exactly as its own accept()
// would have. Both TCP engines implement it; the SHM engine discovers it
// via dynamic_cast on the inner engine it wraps.
class BundleAdopter {
 public:
  virtual ~BundleAdopter() = default;
  // Takes ownership of the bundle's fds (clears them from `b`) on success
  // AND failure, mirroring accept().
  virtual Status AdoptBundle(PartialBundle& b, uint64_t* recv_comm) = 0;
};

// Bind an ephemeral listening socket on `nic`; fills the rendezvous handle.
Status ListenOn(const NicInfo& nic, int32_t dev, SocketHandle* handle, ListenSockPtr* out);

// Signal a (possibly) blocked AcceptBundle to abort with "closed".
void WakeListen(ListenSock* ls);

// Accept connections, grouping by bundle id, until one sender's bundle is
// whole; expires half-arrived bundles from dead senders. Blocks.
Status AcceptBundle(ListenSock* ls, PartialBundle* out);

// One lane of a multi-path comm (docs/DESIGN.md "Lanes & adaptive
// striping"): an optional LOCAL address data-stream sockets bind to before
// connecting (multi-NIC / policy-routed paths; empty = kernel default) plus
// the lane's configured stripe weight. Parsed from TPUNET_LANES
// ("addr=10.0.0.1:w=4,addr=10.0.1.1:w=1"; a lane may omit either key —
// "w=4" alone weights the default path). One lane == one data stream.
struct LaneSpec {
  std::string addr;     // local bind address, empty = unbound
  uint32_t weight = 1;  // 1..255
};
constexpr uint32_t kMaxLaneWeight = 255;

// Parse a TPUNET_LANES spec; Invalid status naming the offending token on
// malformed input. Pure — no global state touched.
Status ParseLaneSpec(const std::string& spec, std::vector<LaneSpec>* out);

// Open the nstreams+1 connection bundle to a remote handle, writing each
// preamble (flags advertises sender-side options, e.g. kPreambleFlagCrc).
// On success data_fds holds nstreams stream-ordered connections and ctrl_fd
// the ctrl connection; all blocking, TCP_NODELAY set. `lanes` (nullable;
// else size == nstreams) supplies per-data-stream local bind addresses —
// stream i routes out of lanes[i].addr when set (the ctrl connection always
// uses the default path: it must survive any single lane's death).
Status ConnectBundle(const std::vector<NicInfo>& nics, int32_t dev, const SocketHandle& handle,
                     uint64_t nstreams, uint64_t min_chunksize, uint64_t flags,
                     std::vector<int>* data_fds, int* ctrl_fd,
                     const std::vector<LaneSpec>* lanes = nullptr);

}  // namespace tpunet

#endif  // TPUNET_WIRE_H_
