#include "tpunet/utils.h"

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <ifaddrs.h>
#include <limits.h>
#include <net/if.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <pthread.h>
#include <sched.h>
#include <time.h>
#include <stdlib.h>
#include <string.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdio>
#include <limits>
#include <functional>
#include <fstream>
#include <new>
#include <set>
#include <sstream>
#include <thread>

#include "tpunet/mutex.h"

#if defined(__x86_64__)
#include <immintrin.h>
#endif

namespace tpunet {

std::string GetEnv(const char* name, const std::string& fallback) {
  const char* v = getenv(name);
  return v ? std::string(v) : fallback;
}

uint64_t GetEnvU64(const char* name, uint64_t fallback) {
  const char* v = getenv(name);
  if (!v || !*v) return fallback;
  // strtoull silently wraps negatives ("-1" -> 2^64-1) — reject them, and
  // reject overflow, rather than exploding a stream count.
  const char* p = v;
  while (*p == ' ' || *p == '\t') ++p;
  if (*p == '-') return fallback;
  errno = 0;
  char* end = nullptr;
  unsigned long long parsed = strtoull(v, &end, 10);
  if (end == v || (end && *end != '\0') || errno == ERANGE) return fallback;
  return static_cast<uint64_t>(parsed);
}

uint64_t MonotonicUs() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<uint64_t>(ts.tv_sec) * 1000000ull +
         static_cast<uint64_t>(ts.tv_nsec) / 1000ull;
}

namespace {
std::atomic<uint64_t> g_fork_gen{0};
std::once_flag g_fork_once;
}  // namespace

uint64_t ForkGeneration() {
  std::call_once(g_fork_once, [] {
    ::pthread_atfork(nullptr, nullptr,
                     [] { g_fork_gen.fetch_add(1, std::memory_order_relaxed); });
  });
  return g_fork_gen.load(std::memory_order_relaxed);
}

namespace {

// FNV-1a 64 over a byte string — the host-id hash. Stable across processes
// and runs (unlike std::hash), cheap, and collision-safe at per-pod host
// counts.
uint64_t Fnv1a64(const std::string& s) {
  uint64_t h = 1469598103934665603ull;
  for (unsigned char c : s) {
    h ^= c;
    h *= 1099511628211ull;
  }
  return h;
}

uint64_t ComputeHostId() {
  // Override first: TPUNET_HOST_ID lets tests split one box into fake
  // "hosts" (and lets operators pin identity on containers that share a
  // boot id). Any string works; it is hashed, not parsed.
  std::string override_id = GetEnv("TPUNET_HOST_ID", "");
  if (!override_id.empty()) return Fnv1a64("override:" + override_id) | 1ull;
  // /proc boot_id is per-boot-unique and identical for every process on
  // the host — containers sharing a kernel (the TPU-host pod layout) agree.
  FILE* f = std::fopen("/proc/sys/kernel/random/boot_id", "rb");
  if (f != nullptr) {
    char buf[128];
    size_t n = std::fread(buf, 1, sizeof(buf), f);
    std::fclose(f);
    while (n > 0 && (buf[n - 1] == '\n' || buf[n - 1] == ' ')) --n;
    if (n > 0) return Fnv1a64("boot:" + std::string(buf, n)) | 1ull;
  }
  char host[256] = {0};
  if (::gethostname(host, sizeof(host) - 1) == 0 && host[0] != '\0') {
    return Fnv1a64("hostname:" + std::string(host)) | 1ull;
  }
  return 1ull;  // degenerate but stable: everything co-located
}

}  // namespace

uint64_t HostId() {
  static const uint64_t id = ComputeHostId();
  return id;
}

int32_t GetNetIfSpeed(const std::string& ifname) {
  // Reference: utils.rs:7-23 — read /sys/class/net/<if>/speed, default 10000.
  std::ifstream f("/sys/class/net/" + ifname + "/speed");
  long speed = 0;
  if (f && (f >> speed) && speed > 0 && speed <= INT32_MAX) {
    return static_cast<int32_t>(speed);
  }
  return 10000;
}

static std::string ResolvePciPath(const std::string& ifname) {
  // Reference: utils.rs:73-77 — realpath of /sys/class/net/<if>/device.
  std::string link = "/sys/class/net/" + ifname + "/device";
  char buf[PATH_MAX];
  if (realpath(link.c_str(), buf) != nullptr) return std::string(buf);
  return "";
}

namespace {

struct IfnameFilter {
  bool exclude = false;   // "^" prefix
  bool exact = false;     // "=" prefix
  std::vector<std::string> names;

  // Parse "NCCL_SOCKET_IFNAME"-style spec (reference: utils.rs:37-49).
  static IfnameFilter Parse(std::string spec) {
    IfnameFilter f;
    if (!spec.empty() && spec[0] == '^') {
      f.exclude = true;
      spec = spec.substr(1);
    } else if (!spec.empty() && spec[0] == '=') {
      f.exact = true;
      spec = spec.substr(1);
    }
    std::stringstream ss(spec);
    std::string item;
    while (std::getline(ss, item, ',')) {
      if (!item.empty()) f.names.push_back(item);
    }
    return f;
  }

  bool Admits(const std::string& ifname) const {
    if (names.empty()) return true;
    bool matched = false;
    for (const auto& n : names) {
      if (exact ? (ifname == n) : (ifname.rfind(n, 0) == 0)) {
        matched = true;
        break;
      }
    }
    return exclude ? !matched : matched;
  }
};

}  // namespace

std::vector<NicInfo> FindInterfaces() {
  // Reference behavior: utils.rs:32-130. Default filter excludes docker*/lo*.
  std::string spec = GetEnv("TPUNET_SOCKET_IFNAME", GetEnv("NCCL_SOCKET_IFNAME", "^docker,lo"));
  IfnameFilter filter = IfnameFilter::Parse(spec);

  std::string family = GetEnv("TPUNET_SOCKET_FAMILY", GetEnv("NCCL_SOCKET_FAMILY", ""));
  bool want_v4 = family != "AF_INET6";
  bool want_v6 = family != "AF_INET";

  std::vector<NicInfo> out;
  std::set<std::string> seen;  // dedup by name, first address wins

  struct ifaddrs* ifs = nullptr;
  if (getifaddrs(&ifs) != 0) return out;
  for (struct ifaddrs* it = ifs; it != nullptr; it = it->ifa_next) {
    if (!it->ifa_addr || !it->ifa_name) continue;
    int af = it->ifa_addr->sa_family;
    if (af != AF_INET && af != AF_INET6) continue;
    if (af == AF_INET && !want_v4) continue;
    if (af == AF_INET6 && !want_v6) continue;
    if (!(it->ifa_flags & IFF_UP)) continue;
    std::string name(it->ifa_name);
    if (!filter.Admits(name)) continue;
    // Skip link-local IPv6 (not routable without scope plumbing).
    if (af == AF_INET6) {
      auto* sin6 = reinterpret_cast<sockaddr_in6*>(it->ifa_addr);
      if (IN6_IS_ADDR_LINKLOCAL(&sin6->sin6_addr)) continue;
    }
    if (!seen.insert(name).second) continue;

    NicInfo nic;
    nic.name = name;
    socklen_t len = (af == AF_INET) ? sizeof(sockaddr_in) : sizeof(sockaddr_in6);
    memcpy(&nic.addr, it->ifa_addr, len);
    nic.addrlen = len;
    nic.pci_path = ResolvePciPath(name);
    nic.speed_mbps = GetNetIfSpeed(name);
    out.push_back(std::move(nic));
  }
  freeifaddrs(ifs);

  // Fall back to loopback when the filter admits nothing — a TPU-VM CI host
  // may only have lo; the reference would return an empty device list and
  // NCCL would fail, we prefer degraded-but-working.
  if (out.empty()) {
    NicInfo lo;
    lo.name = "lo";
    auto* sin = reinterpret_cast<sockaddr_in*>(&lo.addr);
    sin->sin_family = AF_INET;
    sin->sin_addr.s_addr = htonl(INADDR_LOOPBACK);
    sin->sin_port = 0;
    lo.addrlen = sizeof(sockaddr_in);
    lo.speed_mbps = GetNetIfSpeed("lo");
    out.push_back(std::move(lo));
  }
  return out;
}

size_t ChunkSize(size_t total, size_t min_chunksize, size_t n) {
  // Reference: utils.rs:200-205 — max(ceil(total/n), min_chunksize).
  if (n == 0) n = 1;
  size_t per = (total + n - 1) / n;
  return std::max(per, min_chunksize);
}

size_t ChunkCount(size_t total, size_t chunksize) {
  if (total == 0) return 0;
  return (total + chunksize - 1) / chunksize;
}

std::vector<uint8_t> BuildWrrSlots(const std::vector<uint32_t>& weights) {
  std::vector<uint8_t> slots;
  if (weights.empty()) return slots;
  const size_t n = weights.size();
  std::vector<int64_t> credit(n, 0);
  std::vector<int64_t> w(n);
  int64_t total = 0;
  for (size_t i = 0; i < n; ++i) {
    w[i] = weights[i] == 0 ? 1 : static_cast<int64_t>(weights[i]);
    total += w[i];
  }
  slots.reserve(static_cast<size_t>(total));
  for (int64_t s = 0; s < total; ++s) {
    size_t pick = 0;
    for (size_t i = 0; i < n; ++i) {
      credit[i] += w[i];
      if (credit[i] > credit[pick]) pick = i;  // ties -> lowest index
    }
    credit[pick] -= total;
    slots.push_back(static_cast<uint8_t>(pick));
  }
  return slots;
}

namespace {
std::atomic<uint64_t> g_io_syscalls[kIoOpCount] = {};
}  // namespace

void CountIoSyscall(IoOp op) {
  g_io_syscalls[op].fetch_add(1, std::memory_order_relaxed);
}

uint64_t IoSyscallCount(IoOp op) {
  return g_io_syscalls[op].load(std::memory_order_relaxed);
}

void ResetIoSyscallCounts() {
  for (auto& c : g_io_syscalls) c.store(0, std::memory_order_relaxed);
}

Status WriteAll(int fd, const void* buf, size_t n, bool spin) {
  const uint8_t* p = static_cast<const uint8_t*>(buf);
  size_t left = n;
  while (left > 0) {
    CountIoSyscall(kIoSend);
    ssize_t w = ::send(fd, p, left, MSG_NOSIGNAL);
    if (w > 0) {
      p += w;
      left -= static_cast<size_t>(w);
      continue;
    }
    if (w < 0 && (errno == EINTR)) continue;
    if (w < 0 && spin && (errno == EAGAIN || errno == EWOULDBLOCK)) {
      sched_yield();  // reference busy-poll: utils.rs:140-144
      continue;
    }
    return Status::IO("write failed: " + std::string(strerror(errno)));
  }
  return Status::Ok();
}

Status ReadExact(int fd, void* buf, size_t n, bool spin) {
  uint8_t* p = static_cast<uint8_t*>(buf);
  size_t left = n;
  while (left > 0) {
    // MSG_WAITALL: on a blocking socket the kernel assembles the whole read
    // internally — one syscall per chunk instead of one per buffer refill
    // (~16/MiB before). Partial returns (signal, shutdown, nonblocking spin
    // fd) still land in the loop. Harmless in spin mode: a nonblocking fd
    // never waits regardless of the flag.
    CountIoSyscall(kIoRecv);
    ssize_t r = ::recv(fd, p, left, MSG_WAITALL);
    if (r > 0) {
      p += r;
      left -= static_cast<size_t>(r);
      continue;
    }
    if (r == 0) {
      // EOF mid-frame (reference: utils.rs:168-171 UnexpectedEof).
      return Status::IO("unexpected EOF: peer closed connection");
    }
    if (errno == EINTR) continue;
    if (spin && (errno == EAGAIN || errno == EWOULDBLOCK)) {
      sched_yield();
      continue;
    }
    return Status::IO("read failed: " + std::string(strerror(errno)));
  }
  return Status::Ok();
}

namespace {

// Advance a vectored-IO cursor by `moved` bytes: shrink/skip leading iovecs
// in place. Returns the new head/count through the out-params.
void AdvanceIov(struct iovec** iov, int* iovcnt, size_t moved) {
  struct iovec* v = *iov;
  int n = *iovcnt;
  while (n > 0 && (moved >= v->iov_len || v->iov_len == 0)) {
    moved -= v->iov_len;
    ++v;
    --n;
  }
  if (n > 0 && moved > 0) {
    v->iov_base = static_cast<uint8_t*>(v->iov_base) + moved;
    v->iov_len -= moved;
  }
  *iov = v;
  *iovcnt = n;
}

size_t IovTotal(const struct iovec* iov, int iovcnt) {
  size_t total = 0;
  for (int i = 0; i < iovcnt; ++i) total += iov[i].iov_len;
  return total;
}

}  // namespace

Status WritevAll(int fd, struct iovec* iov, int iovcnt, bool spin) {
  size_t left = IovTotal(iov, iovcnt);
  while (left > 0) {
    struct msghdr mh = {};
    mh.msg_iov = iov;
    mh.msg_iovlen = static_cast<size_t>(iovcnt);
    CountIoSyscall(kIoSendmsg);
    ssize_t w = ::sendmsg(fd, &mh, MSG_NOSIGNAL);
    if (w > 0) {
      left -= static_cast<size_t>(w);
      AdvanceIov(&iov, &iovcnt, static_cast<size_t>(w));
      continue;
    }
    if (w < 0 && errno == EINTR) continue;
    if (w < 0 && spin && (errno == EAGAIN || errno == EWOULDBLOCK)) {
      sched_yield();
      continue;
    }
    return Status::IO("writev failed: " + std::string(strerror(errno)));
  }
  return Status::Ok();
}

Status ReadvExact(int fd, struct iovec* iov, int iovcnt, bool spin) {
  size_t left = IovTotal(iov, iovcnt);
  while (left > 0) {
    struct msghdr mh = {};
    mh.msg_iov = iov;
    mh.msg_iovlen = static_cast<size_t>(iovcnt);
    CountIoSyscall(kIoRecvmsg);
    // recvmsg (not readv) so MSG_WAITALL applies — one syscall per vectored
    // chunk read in the common case; see ReadExact.
    ssize_t r = ::recvmsg(fd, &mh, MSG_WAITALL);
    if (r > 0) {
      left -= static_cast<size_t>(r);
      AdvanceIov(&iov, &iovcnt, static_cast<size_t>(r));
      continue;
    }
    if (r == 0) return Status::IO("unexpected EOF: peer closed connection");
    if (errno == EINTR) continue;
    if (spin && (errno == EAGAIN || errno == EWOULDBLOCK)) {
      sched_yield();
      continue;
    }
    return Status::IO("readv failed: " + std::string(strerror(errno)));
  }
  return Status::Ok();
}

Status ReadExactDeadline(int fd, void* buf, size_t n, int timeout_ms) {
  uint8_t* p = static_cast<uint8_t*>(buf);
  size_t left = n;
  struct timespec start;
  clock_gettime(CLOCK_MONOTONIC, &start);
  while (left > 0) {
    struct timespec now;
    clock_gettime(CLOCK_MONOTONIC, &now);
    long elapsed_ms = (now.tv_sec - start.tv_sec) * 1000 + (now.tv_nsec - start.tv_nsec) / 1000000;
    long remaining = timeout_ms - elapsed_ms;
    if (remaining <= 0) return Status::IO("read timed out");
    struct pollfd pfd = {fd, POLLIN, 0};
    int pr = ::poll(&pfd, 1, static_cast<int>(remaining));
    if (pr < 0) {
      if (errno == EINTR) continue;
      return Status::IO("poll failed: " + std::string(strerror(errno)));
    }
    if (pr == 0) return Status::IO("read timed out");
    ssize_t r = ::recv(fd, p, left, MSG_DONTWAIT);
    if (r > 0) {
      p += r;
      left -= static_cast<size_t>(r);
      continue;
    }
    if (r == 0) return Status::IO("unexpected EOF: peer closed connection");
    if (errno == EINTR || errno == EAGAIN || errno == EWOULDBLOCK) continue;
    return Status::IO("read failed: " + std::string(strerror(errno)));
  }
  return Status::Ok();
}

namespace {

// Slicing-by-8 CRC32C tables, generated once (reflected poly 0x82F63B78).
struct Crc32cTables {
  uint32_t t[8][256];
  Crc32cTables() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k) c = (c >> 1) ^ ((c & 1) ? 0x82F63B78u : 0);
      t[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = t[0][i];
      for (int s = 1; s < 8; ++s) {
        c = t[0][c & 0xff] ^ (c >> 8);
        t[s][i] = c;
      }
    }
  }
};

uint32_t Crc32cSoftware(const uint8_t* p, size_t n, uint32_t crc) {
  static const Crc32cTables tables;
  const auto& t = tables.t;
  crc = ~crc;
  while (n >= 8) {
    // Byte-wise loads keep this alignment-agnostic and endian-correct.
    uint32_t lo = crc ^ (static_cast<uint32_t>(p[0]) | static_cast<uint32_t>(p[1]) << 8 |
                         static_cast<uint32_t>(p[2]) << 16 | static_cast<uint32_t>(p[3]) << 24);
    uint32_t hi = static_cast<uint32_t>(p[4]) | static_cast<uint32_t>(p[5]) << 8 |
                  static_cast<uint32_t>(p[6]) << 16 | static_cast<uint32_t>(p[7]) << 24;
    crc = t[7][lo & 0xff] ^ t[6][(lo >> 8) & 0xff] ^ t[5][(lo >> 16) & 0xff] ^
          t[4][lo >> 24] ^ t[3][hi & 0xff] ^ t[2][(hi >> 8) & 0xff] ^
          t[1][(hi >> 16) & 0xff] ^ t[0][hi >> 24];
    p += 8;
    n -= 8;
  }
  while (n--) crc = t[0][(crc ^ *p++) & 0xff] ^ (crc >> 8);
  return ~crc;
}

#if defined(__x86_64__) || defined(__i386__)

// The crc32 instruction is 3-cycle latency / 1-cycle throughput: a single
// dependency chain runs at ~1/3 of peak (measured 4.9 GB/s on this class of
// host). Three interleaved lanes hide the latency; lane results are
// recombined by multiplying in GF(2) by x^(8*lanelen) via precomputed
// shift tables (Mark Adler's crc32c scheme). ~3x the single-chain rate —
// what keeps the TPUNET_CRC=1 wire-integrity tax small even on a loopback
// box where sender, receiver, and checksum share one core.

uint32_t Gf2MatrixTimes(const uint32_t* mat, uint32_t vec) {
  uint32_t sum = 0;
  while (vec) {
    if (vec & 1) sum ^= *mat;
    vec >>= 1;
    ++mat;
  }
  return sum;
}

void Gf2MatrixSquare(uint32_t* square, const uint32_t* mat) {
  for (int n = 0; n < 32; ++n) square[n] = Gf2MatrixTimes(mat, mat[n]);
}

// Operator (as a 32x32 GF(2) matrix) that advances a CRC-32C state over
// `len` zero BYTES. `len` must be a power of two (both lane strides are):
// starting from the 4-bit operator, each squaring doubles the span, and
// halving a power-of-two len to zero performs exactly log2(8*len)-2 of
// them.
void Crc32cZerosOp(uint32_t* even, size_t len) {
  uint32_t odd[32];
  odd[0] = 0x82F63B78u;  // reflected CRC-32C polynomial: one zero bit
  uint32_t row = 1;
  for (int n = 1; n < 32; ++n) {
    odd[n] = row;
    row <<= 1;
  }
  Gf2MatrixSquare(even, odd);  // two zero bits
  Gf2MatrixSquare(odd, even);  // four zero bits
  do {
    Gf2MatrixSquare(even, odd);
    len >>= 1;
    if (len == 0) return;
    Gf2MatrixSquare(odd, even);
    len >>= 1;
  } while (len);
  memcpy(even, odd, sizeof(odd));
}

struct Crc32cShiftTable {
  uint32_t t[4][256];
  explicit Crc32cShiftTable(size_t lane_bytes) {
    uint32_t op[32];
    Crc32cZerosOp(op, lane_bytes);
    for (uint32_t n = 0; n < 256; ++n) {
      t[0][n] = Gf2MatrixTimes(op, n);
      t[1][n] = Gf2MatrixTimes(op, n << 8);
      t[2][n] = Gf2MatrixTimes(op, n << 16);
      t[3][n] = Gf2MatrixTimes(op, n << 24);
    }
  }
  uint32_t Shift(uint32_t crc) const {
    return t[0][crc & 0xff] ^ t[1][(crc >> 8) & 0xff] ^ t[2][(crc >> 16) & 0xff] ^
           t[3][crc >> 24];
  }
};

constexpr size_t kCrcLongLane = 2048;  // bytes per lane, big-buffer stride
constexpr size_t kCrcShortLane = 256;  // bytes per lane, medium stride

#if defined(__x86_64__)
// A lambda would not inherit the enclosing function's target attribute, so
// the 3-lane stride lives in its own sse4.2-attributed helper.
__attribute__((target("sse4.2")))
void Crc32cThreeLanes(const uint8_t*& p, size_t& n, uint32_t& crc,
                      const Crc32cShiftTable& shift, size_t lane) {
  while (n >= 3 * lane) {
    uint64_t c0 = crc, c1 = 0, c2 = 0;
    const uint8_t* q = p;
    const uint8_t* end = p + lane;
    while (q < end) {
      uint64_t v0, v1, v2;
      memcpy(&v0, q, 8);
      memcpy(&v1, q + lane, 8);
      memcpy(&v2, q + 2 * lane, 8);
      c0 = __builtin_ia32_crc32di(c0, v0);
      c1 = __builtin_ia32_crc32di(c1, v1);
      c2 = __builtin_ia32_crc32di(c2, v2);
      q += 8;
    }
    crc = shift.Shift(static_cast<uint32_t>(c0)) ^ static_cast<uint32_t>(c1);
    crc = shift.Shift(crc) ^ static_cast<uint32_t>(c2);
    p += 3 * lane;
    n -= 3 * lane;
  }
}
#endif

__attribute__((target("sse4.2")))
uint32_t Crc32cHardware(const uint8_t* p, size_t n, uint32_t crc) {
  static const Crc32cShiftTable long_shift(kCrcLongLane);
  static const Crc32cShiftTable short_shift(kCrcShortLane);
  crc = ~crc;
#if defined(__x86_64__)
  Crc32cThreeLanes(p, n, crc, long_shift, kCrcLongLane);
  Crc32cThreeLanes(p, n, crc, short_shift, kCrcShortLane);
  while (n >= 8) {
    uint64_t v;
    memcpy(&v, p, 8);
    crc = static_cast<uint32_t>(__builtin_ia32_crc32di(crc, v));
    p += 8;
    n -= 8;
  }
#endif
  while (n >= 4) {
    uint32_t v;
    memcpy(&v, p, 4);
    crc = __builtin_ia32_crc32si(crc, v);
    p += 4;
    n -= 4;
  }
  while (n--) crc = __builtin_ia32_crc32qi(crc, *p++);
  return ~crc;
}
#endif

}  // namespace

uint32_t Crc32c(const void* data, size_t n, uint32_t crc) {
  const uint8_t* p = static_cast<const uint8_t*>(data);
#if defined(__x86_64__) || defined(__i386__)
  static const bool hw = __builtin_cpu_supports("sse4.2");
  if (hw) return Crc32cHardware(p, n, crc);
#endif
  return Crc32cSoftware(p, n, crc);
}

// ---------------------------------------------------------------------------
// Reduction kernels (see utils.h). The scalar bodies are the ground truth;
// the AVX2 paths replicate them BITWISE — float min/max via compare+blend
// (std::min(a,b) == (b<a)?b:a, NaN-propagation included; _mm256_min_ps has
// different NaN semantics and is deliberately not used), bf16 via the same
// integer round-to-nearest-even arithmetic as the scalar converter.

namespace {

std::atomic<uint64_t> g_reduce_bytes{0};

inline float Bf16ToF32(uint16_t v) {
  uint32_t bits = static_cast<uint32_t>(v) << 16;
  float f;
  memcpy(&f, &bits, 4);
  return f;
}

inline uint16_t F32ToBf16(float f) {
  uint32_t bits;
  memcpy(&bits, &f, 4);
  // RNE: add half-ulp (0x7FFF) plus the lsb of the kept part.
  uint32_t rounded = bits + 0x7FFF + ((bits >> 16) & 1);
  return static_cast<uint16_t>(rounded >> 16);
}

template <typename T>
void ReduceTyped(T* dst, const T* a, const T* b, size_t n, WireRedOp op) {
  switch (op) {
    case WireRedOp::kSum:
      for (size_t i = 0; i < n; ++i) dst[i] = a[i] + b[i];
      break;
    case WireRedOp::kProd:
      for (size_t i = 0; i < n; ++i) dst[i] = a[i] * b[i];
      break;
    case WireRedOp::kMin:
      for (size_t i = 0; i < n; ++i) dst[i] = std::min(a[i], b[i]);
      break;
    case WireRedOp::kMax:
      for (size_t i = 0; i < n; ++i) dst[i] = std::max(a[i], b[i]);
      break;
  }
}

void ReduceBf16Scalar(uint16_t* dst, const uint16_t* asrc, const uint16_t* bsrc,
                      size_t n, WireRedOp op) {
  for (size_t i = 0; i < n; ++i) {
    float a = Bf16ToF32(asrc[i]);
    float b = Bf16ToF32(bsrc[i]);
    float r = 0;
    switch (op) {
      case WireRedOp::kSum:
        r = a + b;
        break;
      case WireRedOp::kProd:
        r = a * b;
        break;
      case WireRedOp::kMin:
        r = std::min(a, b);
        break;
      case WireRedOp::kMax:
        r = std::max(a, b);
        break;
    }
    dst[i] = F32ToBf16(r);
  }
}

void ReduceShardScalar(void* dst, const void* a, const void* b, size_t n,
                       WireDType dtype, WireRedOp op) {
  switch (dtype) {
    case WireDType::kF32:
      ReduceTyped(static_cast<float*>(dst), static_cast<const float*>(a),
                  static_cast<const float*>(b), n, op);
      break;
    case WireDType::kF64:
      ReduceTyped(static_cast<double*>(dst), static_cast<const double*>(a),
                  static_cast<const double*>(b), n, op);
      break;
    case WireDType::kBF16:
      ReduceBf16Scalar(static_cast<uint16_t*>(dst), static_cast<const uint16_t*>(a),
                       static_cast<const uint16_t*>(b), n, op);
      break;
    case WireDType::kI32:
      ReduceTyped(static_cast<int32_t*>(dst), static_cast<const int32_t*>(a),
                  static_cast<const int32_t*>(b), n, op);
      break;
    case WireDType::kI64:
      ReduceTyped(static_cast<int64_t*>(dst), static_cast<const int64_t*>(a),
                  static_cast<const int64_t*>(b), n, op);
      break;
    case WireDType::kU8:
      ReduceTyped(static_cast<uint8_t*>(dst), static_cast<const uint8_t*>(a),
                  static_cast<const uint8_t*>(b), n, op);
      break;
  }
}

#if defined(__x86_64__)

// Elementwise op on two f32 vectors with scalar-identical semantics: IEEE
// add/mul are exact per element; min/max replicate std::min/std::max via
// ordered-quiet compare + blend (NaN in either operand -> compare false ->
// the FIRST operand survives, exactly like the scalar ternary).
__attribute__((target("avx2")))
inline __m256 Avx2Op(__m256 va, __m256 vb, WireRedOp op) {
  switch (op) {
    case WireRedOp::kSum:
      return _mm256_add_ps(va, vb);
    case WireRedOp::kProd:
      return _mm256_mul_ps(va, vb);
    case WireRedOp::kMin:
      return _mm256_blendv_ps(va, vb, _mm256_cmp_ps(vb, va, _CMP_LT_OQ));
    case WireRedOp::kMax:
      return _mm256_blendv_ps(va, vb, _mm256_cmp_ps(va, vb, _CMP_LT_OQ));
  }
  return va;
}

__attribute__((target("avx2")))
void ReduceF32Avx2(float* dst, const float* a, const float* b, size_t n,
                   WireRedOp op) {
  size_t i = 0;
  for (; i + 8 <= n; i += 8) {
    __m256 va = _mm256_loadu_ps(a + i);
    __m256 vb = _mm256_loadu_ps(b + i);
    _mm256_storeu_ps(dst + i, Avx2Op(va, vb, op));
  }
  if (i < n) ReduceTyped(dst + i, a + i, b + i, n - i, op);
}

__attribute__((target("avx2")))
void ReduceBf16Avx2(uint16_t* dst, const uint16_t* a, const uint16_t* b,
                    size_t n, WireRedOp op) {
  const __m256i kHalf = _mm256_set1_epi32(0x7FFF);
  const __m256i kOne = _mm256_set1_epi32(1);
  size_t i = 0;
  for (; i + 8 <= n; i += 8) {
    __m128i ha = _mm_loadu_si128(reinterpret_cast<const __m128i*>(a + i));
    __m128i hb = _mm_loadu_si128(reinterpret_cast<const __m128i*>(b + i));
    __m256 fa = _mm256_castsi256_ps(_mm256_slli_epi32(_mm256_cvtepu16_epi32(ha), 16));
    __m256 fb = _mm256_castsi256_ps(_mm256_slli_epi32(_mm256_cvtepu16_epi32(hb), 16));
    __m256i bits = _mm256_castps_si256(Avx2Op(fa, fb, op));
    // F32ToBf16's RNE: bits + 0x7FFF + ((bits >> 16) & 1), take the high
    // half. The adds wrap mod 2^32 exactly like the scalar uint32_t.
    __m256i lsb = _mm256_and_si256(_mm256_srli_epi32(bits, 16), kOne);
    __m256i hi = _mm256_srli_epi32(_mm256_add_epi32(_mm256_add_epi32(bits, kHalf), lsb), 16);
    // Pack 8 u32 (each <= 0xFFFF, so packus saturation is exact) to 8 u16;
    // packus interleaves 128-bit lanes, the permute restores order.
    __m256i packed = _mm256_permute4x64_epi64(_mm256_packus_epi32(hi, hi), 0xD8);
    _mm_storeu_si128(reinterpret_cast<__m128i*>(dst + i),
                     _mm256_castsi256_si128(packed));
  }
  if (i < n) ReduceBf16Scalar(dst + i, a + i, b + i, n - i, op);
}

bool ReduceSimdEnabled() {
  static const bool on = GetEnvU64("TPUNET_REDUCE_SIMD", 1) != 0 &&
                         __builtin_cpu_supports("avx2");
  return on;
}

#endif  // __x86_64__

// One shard of a reduce: SIMD when the dtype has a vector kernel and the
// CPU dispatch admits it, scalar otherwise.
void ReduceShard(void* dst, const void* a, const void* b, size_t n,
                 WireDType dtype, WireRedOp op) {
#if defined(__x86_64__)
  if (ReduceSimdEnabled()) {
    if (dtype == WireDType::kF32) {
      ReduceF32Avx2(static_cast<float*>(dst), static_cast<const float*>(a),
                    static_cast<const float*>(b), n, op);
      return;
    }
    if (dtype == WireDType::kBF16) {
      ReduceBf16Avx2(static_cast<uint16_t*>(dst), static_cast<const uint16_t*>(a),
                     static_cast<const uint16_t*>(b), n, op);
      return;
    }
  }
#endif
  ReduceShardScalar(dst, a, b, n, dtype, op);
}

// Fork-join pool for the reduction kernels. At 100Gb-class DCN speeds a
// single core's reduce bandwidth (~5-10 GB/s streaming) becomes the pipeline
// bottleneck of the ring's pipelined exchange, so large chunks fan out
// across a few cores. Persistent parked threads (no spawn per chunk); sized
// well below the host core count — the transport's stream workers need
// cores too.
class ReducePool {
 public:
  static ReducePool& Get() {
    static ReducePool* pool = new ReducePool();  // leaked: lives for process
    return *pool;
  }

  // Run fn(i) for i in [0, njobs) on the pool + calling thread; blocks.
  // Serialized across callers: two Communicators driven from different
  // Python threads (ctypes releases the GIL) must not interleave the shared
  // job_/njobs_/next_/pending_ state mid-reduction.
  void Run(const std::function<void(size_t)>& fn, size_t njobs) {
    if (nworkers_ == 0 || njobs <= 1) {
      for (size_t i = 0; i < njobs; ++i) fn(i);
      return;
    }
    MutexLock run_lk(run_mu_);
    mu_.Lock();
    job_ = &fn;
    njobs_ = njobs;
    next_ = 0;
    pending_ = njobs;
    ++gen_;
    work_cv_.NotifyAll();
    // The caller pulls jobs too — no idle waiting while work remains.
    while (true) {
      size_t i = next_;
      if (i >= njobs_) break;
      next_ = i + 1;
      mu_.Unlock();
      fn(i);
      mu_.Lock();
      --pending_;
    }
    while (pending_ != 0) done_cv_.Wait(mu_);
    job_ = nullptr;
    mu_.Unlock();
  }

  size_t nworkers() const { return nworkers_; }

 private:
  ReducePool() {
    unsigned hw = std::thread::hardware_concurrency();
    size_t n = hw > 2 ? std::min<size_t>(3, hw / 2) : 0;
    // TPUNET_REDUCE_THREADS overrides (total shards = workers + caller);
    // also how CI exercises the parallel path on small runners.
    uint64_t env = GetEnvU64("TPUNET_REDUCE_THREADS", 0);
    if (env > 0) n = std::min<uint64_t>(env - 1, 15);
    nworkers_ = n;
    for (size_t t = 0; t < n; ++t) {
      threads_.emplace_back([this] { WorkerLoop(); });
      threads_.back().detach();  // pool is process-lifetime
    }
  }

  // Never returns (pool threads are process-lifetime, detached) — the
  // mutex is intentionally held at the unreachable function exit.
  void WorkerLoop() NO_THREAD_SAFETY_ANALYSIS {
    uint64_t seen = 0;
    mu_.Lock();
    while (true) {
      while (!(gen_ != seen && job_ != nullptr)) work_cv_.Wait(mu_);
      seen = gen_;
      while (true) {
        size_t i = next_;
        if (i >= njobs_) break;
        next_ = i + 1;
        const auto* fn = job_;
        mu_.Unlock();
        (*fn)(i);
        mu_.Lock();
        if (--pending_ == 0) done_cv_.NotifyAll();
      }
    }
  }

  Mutex run_mu_;  // serializes concurrent Run() callers; ordered before mu_
  Mutex mu_ ACQUIRED_AFTER(run_mu_);
  CondVar work_cv_, done_cv_;
  const std::function<void(size_t)>* job_ GUARDED_BY(mu_) = nullptr;
  size_t njobs_ GUARDED_BY(mu_) = 0;
  size_t next_ GUARDED_BY(mu_) = 0;
  size_t pending_ GUARDED_BY(mu_) = 0;
  uint64_t gen_ GUARDED_BY(mu_) = 0;
  size_t nworkers_ = 0;
  std::vector<std::thread> threads_;
};

}  // namespace

size_t WireDTypeSize(WireDType d) {
  switch (d) {
    case WireDType::kF32:
    case WireDType::kI32:
      return 4;
    case WireDType::kF64:
    case WireDType::kI64:
      return 8;
    case WireDType::kBF16:
      return 2;
    case WireDType::kU8:
      return 1;
  }
  return 0;
}

void ReduceInto(void* dst, const void* a, const void* b, size_t n,
                WireDType dtype, WireRedOp op) {
  size_t esize = WireDTypeSize(dtype);
  g_reduce_bytes.fetch_add(n * esize, std::memory_order_relaxed);
  ReducePool& pool = ReducePool::Get();
  size_t nshards = pool.nworkers() + 1;
  // Fan out only when the chunk amortizes the fork-join (>= 4 MiB).
  if (nshards <= 1 || n * esize < (4u << 20)) {
    ReduceShard(dst, a, b, n, dtype, op);
    return;
  }
  auto* d8 = static_cast<uint8_t*>(dst);
  const auto* a8 = static_cast<const uint8_t*>(a);
  const auto* b8 = static_cast<const uint8_t*>(b);
  pool.Run(
      [&](size_t i) {
        size_t lo = n * i / nshards, hi = n * (i + 1) / nshards;
        ReduceShard(d8 + lo * esize, a8 + lo * esize, b8 + lo * esize,
                    hi - lo, dtype, op);
      },
      nshards);
}

uint64_t ReduceBytesTotal() {
  return g_reduce_bytes.load(std::memory_order_relaxed);
}

void ResetReduceBytesTotal() { g_reduce_bytes.store(0, std::memory_order_relaxed); }

// ---------------------------------------------------------------------------
// Wire codecs (see utils.h). The bf16 converters are the EXACT F32ToBf16 /
// Bf16ToF32 the reduce kernels use, so wire values are bit-identical to the
// bf16-RNE reduce goldens; the AVX2 lanes replicate the scalar arithmetic
// bitwise (same integer RNE, same expand) and are gated by the same
// TPUNET_REDUCE_SIMD switch as the reduce kernels.

namespace {

std::atomic<uint64_t> g_codec_bytes[kWireCodecCount][2] = {};
std::atomic<uint64_t> g_codec_payload[2] = {};

void CountCodec(WireCodec c, int dir, size_t wire_bytes, size_t n) {
  g_codec_bytes[static_cast<int>(c)][dir].fetch_add(wire_bytes,
                                                    std::memory_order_relaxed);
  g_codec_payload[dir].fetch_add(n * sizeof(float), std::memory_order_relaxed);
}

bool CodecSimdEnabled() {
#if defined(__x86_64__)
  return ReduceSimdEnabled();
#else
  return false;
#endif
}

void EncodeBf16Scalar(const float* src, uint16_t* dst, size_t n) {
  for (size_t i = 0; i < n; ++i) dst[i] = F32ToBf16(src[i]);
}

void DecodeBf16Scalar(const uint16_t* src, float* dst, size_t n) {
  for (size_t i = 0; i < n; ++i) dst[i] = Bf16ToF32(src[i]);
}

void DecodeReduceBf16Scalar(float* dst, const float* local, const uint16_t* wire,
                            size_t n, WireRedOp op) {
  for (size_t i = 0; i < n; ++i) {
    float a = local[i];
    float b = Bf16ToF32(wire[i]);
    switch (op) {
      case WireRedOp::kSum:
        dst[i] = a + b;
        break;
      case WireRedOp::kProd:
        dst[i] = a * b;
        break;
      case WireRedOp::kMin:
        dst[i] = std::min(a, b);
        break;
      case WireRedOp::kMax:
        dst[i] = std::max(a, b);
        break;
    }
  }
}

#if defined(__x86_64__)

__attribute__((target("avx2")))
void EncodeBf16Avx2(const float* src, uint16_t* dst, size_t n) {
  const __m256i kHalf = _mm256_set1_epi32(0x7FFF);
  const __m256i kOne = _mm256_set1_epi32(1);
  size_t i = 0;
  for (; i + 8 <= n; i += 8) {
    __m256i bits = _mm256_castps_si256(_mm256_loadu_ps(src + i));
    // F32ToBf16's RNE: bits + 0x7FFF + ((bits >> 16) & 1), keep high half —
    // identical wraparound arithmetic to the scalar uint32_t path.
    __m256i lsb = _mm256_and_si256(_mm256_srli_epi32(bits, 16), kOne);
    __m256i hi = _mm256_srli_epi32(_mm256_add_epi32(_mm256_add_epi32(bits, kHalf), lsb), 16);
    __m256i packed = _mm256_permute4x64_epi64(_mm256_packus_epi32(hi, hi), 0xD8);
    _mm_storeu_si128(reinterpret_cast<__m128i*>(dst + i),
                     _mm256_castsi256_si128(packed));
  }
  if (i < n) EncodeBf16Scalar(src + i, dst + i, n - i);
}

__attribute__((target("avx2")))
void DecodeBf16Avx2(const uint16_t* src, float* dst, size_t n) {
  size_t i = 0;
  for (; i + 8 <= n; i += 8) {
    __m128i h = _mm_loadu_si128(reinterpret_cast<const __m128i*>(src + i));
    __m256 f = _mm256_castsi256_ps(_mm256_slli_epi32(_mm256_cvtepu16_epi32(h), 16));
    _mm256_storeu_ps(dst + i, f);
  }
  if (i < n) DecodeBf16Scalar(src + i, dst + i, n - i);
}

__attribute__((target("avx2")))
void DecodeReduceBf16Avx2(float* dst, const float* local, const uint16_t* wire,
                          size_t n, WireRedOp op) {
  size_t i = 0;
  for (; i + 8 <= n; i += 8) {
    __m128i h = _mm_loadu_si128(reinterpret_cast<const __m128i*>(wire + i));
    __m256 b = _mm256_castsi256_ps(_mm256_slli_epi32(_mm256_cvtepu16_epi32(h), 16));
    __m256 a = _mm256_loadu_ps(local + i);
    _mm256_storeu_ps(dst + i, Avx2Op(a, b, op));
  }
  if (i < n) DecodeReduceBf16Scalar(dst + i, local + i, wire + i, n - i, op);
}

#endif  // __x86_64__

// int8 block-scale layout per kI8CodecBlock elements: [f32 scale][int8 x m].
// scale = amax/127 over the block's FINITE magnitudes (0 when the block is
// all zero; NaN when the block holds any non-finite value — the whole block
// then decodes to NaN LOUDLY instead of silently zeroing an overflowed
// gradient). q = rint(x * 127/amax) in [-127, 127], so
// |x - q*scale| <= scale/2 = amax/254 per element on finite blocks.
// Shared scale/inv derivation so the scalar and AVX2 block encoders agree
// bitwise.
inline void I8ScaleInv(float amax, bool has_nan, float* scale, float* inv) {
  if (has_nan || !std::isfinite(amax)) {
    *scale = std::numeric_limits<float>::quiet_NaN();
    *inv = 0.0f;
  } else if (amax == 0.0f) {
    *scale = 0.0f;
    *inv = 0.0f;
  } else {
    *scale = amax / 127.0f;
    *inv = 127.0f / amax;
  }
}

void EncodeI8BlockScalar(const float* src, uint8_t* dst, size_t m) {
  float amax = 0.0f;
  bool has_nan = false;
  for (size_t i = 0; i < m; ++i) {
    float a = std::fabs(src[i]);
    if (a != a) {
      has_nan = true;
    } else {
      amax = std::max(amax, a);
    }
  }
  float scale, inv;
  I8ScaleInv(amax, has_nan, &scale, &inv);
  memcpy(dst, &scale, sizeof(scale));
  int8_t* q = reinterpret_cast<int8_t*>(dst + sizeof(scale));
  for (size_t i = 0; i < m; ++i) {
    long v = lrintf(src[i] * inv);  // round-to-nearest-even (default mode)
    if (v > 127) v = 127;
    if (v < -127) v = -127;
    q[i] = static_cast<int8_t>(v);
  }
}

#if defined(__x86_64__)

// A lambda would not inherit the enclosing function's target attribute
// (same toolchain quirk Crc32cThreeLanes documents), so the 8-lane
// quantize step lives in its own avx2-attributed helper.
__attribute__((target("avx2")))
inline __m256i QuantI8x8(const float* p, __m256 vinv, __m256i hi, __m256i lo) {
  __m256i v = _mm256_cvtps_epi32(_mm256_mul_ps(_mm256_loadu_ps(p), vinv));
  return _mm256_max_epi32(_mm256_min_epi32(v, hi), lo);
}

// AVX2 block encoder, bitwise-equal to the scalar one: the amax pass masks
// NaN lanes to 0 exactly like the scalar skip (tracking them in a separate
// unordered mask), _mm256_cvtps_epi32 rounds per MXCSR (RNE, the same
// default mode lrintf uses), and the post-convert integer clamp maps the
// cvt's INT_MIN "indefinite" for NaN inputs to -127 just like the scalar
// clamp does on x86. The scalar loop was the int8 lane's bottleneck
// (measured ~1 GB/s vs ~6 for the bf16 AVX2 pack — the per-block amax is
// only 1 KiB of L1-resident data, so two vector passes are nearly free).
__attribute__((target("avx2")))
void EncodeI8BlockAvx2(const float* src, uint8_t* dst, size_t m) {
  const __m256 kAbsMask = _mm256_castsi256_ps(_mm256_set1_epi32(0x7FFFFFFF));
  __m256 vmax = _mm256_setzero_ps();
  __m256 vunord = _mm256_setzero_ps();
  size_t i = 0;
  for (; i + 8 <= m; i += 8) {
    __m256 v = _mm256_loadu_ps(src + i);
    __m256 ord = _mm256_cmp_ps(v, v, _CMP_ORD_Q);  // all-ones on non-NaN
    vunord = _mm256_or_ps(vunord, _mm256_cmp_ps(v, v, _CMP_UNORD_Q));
    __m256 a = _mm256_and_ps(_mm256_and_ps(v, kAbsMask), ord);  // NaN -> 0
    vmax = _mm256_max_ps(vmax, a);
  }
  float lanes[8];
  _mm256_storeu_ps(lanes, vmax);
  float amax = 0.0f;
  for (float l : lanes) amax = std::max(amax, l);
  bool has_nan = _mm256_movemask_ps(vunord) != 0;
  for (; i < m; ++i) {
    float a = std::fabs(src[i]);
    if (a != a) {
      has_nan = true;
    } else {
      amax = std::max(amax, a);
    }
  }
  float scale, inv;
  I8ScaleInv(amax, has_nan, &scale, &inv);
  memcpy(dst, &scale, sizeof(scale));
  int8_t* q = reinterpret_cast<int8_t*>(dst + sizeof(scale));
  const __m256 vinv = _mm256_set1_ps(inv);
  const __m256i kHi = _mm256_set1_epi32(127);
  const __m256i kLo = _mm256_set1_epi32(-127);
  const __m256i kFix = _mm256_setr_epi32(0, 4, 1, 5, 2, 6, 3, 7);
  i = 0;
  for (; i + 32 <= m; i += 32) {
    __m256i a = QuantI8x8(src + i, vinv, kHi, kLo);
    __m256i b = QuantI8x8(src + i + 8, vinv, kHi, kLo);
    __m256i c = QuantI8x8(src + i + 16, vinv, kHi, kLo);
    __m256i d = QuantI8x8(src + i + 24, vinv, kHi, kLo);
    // packs interleaves 128-bit lanes; the dword permute restores order.
    __m256i p16a = _mm256_packs_epi32(a, b);
    __m256i p16b = _mm256_packs_epi32(c, d);
    __m256i p8 = _mm256_permutevar8x32_epi32(_mm256_packs_epi16(p16a, p16b), kFix);
    _mm256_storeu_si256(reinterpret_cast<__m256i*>(q + i), p8);
  }
  for (; i < m; ++i) {
    long v = lrintf(src[i] * inv);
    if (v > 127) v = 127;
    if (v < -127) v = -127;
    q[i] = static_cast<int8_t>(v);
  }
}

#endif  // __x86_64__

void EncodeI8Block(const float* src, uint8_t* dst, size_t m) {
#if defined(__x86_64__)
  if (CodecSimdEnabled()) {
    EncodeI8BlockAvx2(src, dst, m);
    return;
  }
#endif
  EncodeI8BlockScalar(src, dst, m);
}

void DecodeI8Block(const uint8_t* src, float* dst, size_t m) {
  float scale;
  memcpy(&scale, src, sizeof(scale));
  const int8_t* q = reinterpret_cast<const int8_t*>(src + sizeof(scale));
  for (size_t i = 0; i < m; ++i) dst[i] = static_cast<float>(q[i]) * scale;
}

void DecodeReduceQuantBf16Scalar(float* dst, const float* local,
                                 const uint16_t* wire, uint16_t* enc, size_t n,
                                 WireRedOp op) {
  for (size_t i = 0; i < n; ++i) {
    float a = local[i];
    float b = Bf16ToF32(wire[i]);
    float t = 0;
    switch (op) {
      case WireRedOp::kSum:
        t = a + b;
        break;
      case WireRedOp::kProd:
        t = a * b;
        break;
      case WireRedOp::kMin:
        t = std::min(a, b);
        break;
      case WireRedOp::kMax:
        t = std::max(a, b);
        break;
    }
    uint16_t e = F32ToBf16(t);
    enc[i] = e;
    dst[i] = Bf16ToF32(e);
  }
}

#if defined(__x86_64__)

__attribute__((target("avx2")))
void DecodeReduceQuantBf16Avx2(float* dst, const float* local,
                               const uint16_t* wire, uint16_t* enc, size_t n,
                               WireRedOp op) {
  const __m256i kHalf = _mm256_set1_epi32(0x7FFF);
  const __m256i kOne = _mm256_set1_epi32(1);
  size_t i = 0;
  for (; i + 8 <= n; i += 8) {
    __m128i h = _mm_loadu_si128(reinterpret_cast<const __m128i*>(wire + i));
    __m256 b = _mm256_castsi256_ps(_mm256_slli_epi32(_mm256_cvtepu16_epi32(h), 16));
    __m256 a = _mm256_loadu_ps(local + i);
    __m256i bits = _mm256_castps_si256(Avx2Op(a, b, op));
    __m256i lsb = _mm256_and_si256(_mm256_srli_epi32(bits, 16), kOne);
    __m256i hi = _mm256_srli_epi32(_mm256_add_epi32(_mm256_add_epi32(bits, kHalf), lsb), 16);
    __m256i packed = _mm256_permute4x64_epi64(_mm256_packus_epi32(hi, hi), 0xD8);
    __m128i e = _mm256_castsi256_si128(packed);
    _mm_storeu_si128(reinterpret_cast<__m128i*>(enc + i), e);
    __m256 q = _mm256_castsi256_ps(_mm256_slli_epi32(_mm256_cvtepu16_epi32(e), 16));
    _mm256_storeu_ps(dst + i, q);
  }
  if (i < n) DecodeReduceQuantBf16Scalar(dst + i, local + i, wire + i, enc + i, n - i, op);
}

#endif  // __x86_64__

void DecodeReduceI8Block(float* dst, const float* local, const uint8_t* src,
                         size_t m, WireRedOp op) {
  float scale;
  memcpy(&scale, src, sizeof(scale));
  const int8_t* q = reinterpret_cast<const int8_t*>(src + sizeof(scale));
  for (size_t i = 0; i < m; ++i) {
    float a = local[i];
    float b = static_cast<float>(q[i]) * scale;
    switch (op) {
      case WireRedOp::kSum:
        dst[i] = a + b;
        break;
      case WireRedOp::kProd:
        dst[i] = a * b;
        break;
      case WireRedOp::kMin:
        dst[i] = std::min(a, b);
        break;
      case WireRedOp::kMax:
        dst[i] = std::max(a, b);
        break;
    }
  }
}

}  // namespace

bool ParseWireCodec(const std::string& name, WireCodec* out) {
  if (name.empty() || name == "f32") {
    *out = WireCodec::kF32;
    return true;
  }
  if (name == "bf16") {
    *out = WireCodec::kBF16;
    return true;
  }
  if (name == "int8") {
    *out = WireCodec::kI8;
    return true;
  }
  return false;
}

const char* WireCodecName(WireCodec c) {
  switch (c) {
    case WireCodec::kF32:
      return "f32";
    case WireCodec::kBF16:
      return "bf16";
    case WireCodec::kI8:
      return "int8";
  }
  return "?";
}

size_t CodecWireBytes(WireCodec c, size_t n) {
  switch (c) {
    case WireCodec::kF32:
      return n * 4;
    case WireCodec::kBF16:
      return n * 2;
    case WireCodec::kI8:
      return n + sizeof(float) * ((n + kI8CodecBlock - 1) / kI8CodecBlock);
  }
  return n * 4;
}

void CodecEncode(WireCodec c, const float* src, uint8_t* dst, size_t n) {
  switch (c) {
    case WireCodec::kF32:
      // Passthrough for completeness (the collectives skip the codec stage
      // entirely at f32); not counted — the ratio gauge tracks compression.
      memcpy(dst, src, n * 4);
      return;
    case WireCodec::kBF16: {
      auto* d16 = reinterpret_cast<uint16_t*>(dst);
#if defined(__x86_64__)
      if (CodecSimdEnabled()) {
        EncodeBf16Avx2(src, d16, n);
      } else {
        EncodeBf16Scalar(src, d16, n);
      }
#else
      EncodeBf16Scalar(src, d16, n);
#endif
      break;
    }
    case WireCodec::kI8: {
      uint8_t* out = dst;
      for (size_t off = 0; off < n; off += kI8CodecBlock) {
        size_t m = std::min(kI8CodecBlock, n - off);
        EncodeI8Block(src + off, out, m);
        out += sizeof(float) + m;
      }
      break;
    }
  }
  CountCodec(c, 0, CodecWireBytes(c, n), n);
}

void CodecDecode(WireCodec c, const uint8_t* wire, float* dst, size_t n) {
  switch (c) {
    case WireCodec::kF32:
      memcpy(dst, wire, n * 4);
      return;
    case WireCodec::kBF16: {
      const auto* s16 = reinterpret_cast<const uint16_t*>(wire);
#if defined(__x86_64__)
      if (CodecSimdEnabled()) {
        DecodeBf16Avx2(s16, dst, n);
      } else {
        DecodeBf16Scalar(s16, dst, n);
      }
#else
      DecodeBf16Scalar(s16, dst, n);
#endif
      break;
    }
    case WireCodec::kI8: {
      const uint8_t* in = wire;
      for (size_t off = 0; off < n; off += kI8CodecBlock) {
        size_t m = std::min(kI8CodecBlock, n - off);
        DecodeI8Block(in, dst + off, m);
        in += sizeof(float) + m;
      }
      break;
    }
  }
  CountCodec(c, 1, CodecWireBytes(c, n), n);
}

void CodecDecodeReduce(WireCodec c, float* dst, const float* local,
                       const uint8_t* wire, size_t n, WireRedOp op) {
  if (local == nullptr) local = dst;
  switch (c) {
    case WireCodec::kF32:
      ReduceInto(dst, local, wire, n, WireDType::kF32, op);
      return;
    case WireCodec::kBF16: {
      const auto* w16 = reinterpret_cast<const uint16_t*>(wire);
#if defined(__x86_64__)
      if (CodecSimdEnabled()) {
        DecodeReduceBf16Avx2(dst, local, w16, n, op);
      } else {
        DecodeReduceBf16Scalar(dst, local, w16, n, op);
      }
#else
      DecodeReduceBf16Scalar(dst, local, w16, n, op);
#endif
      break;
    }
    case WireCodec::kI8: {
      const uint8_t* in = wire;
      for (size_t off = 0; off < n; off += kI8CodecBlock) {
        size_t m = std::min(kI8CodecBlock, n - off);
        DecodeReduceI8Block(dst + off, local + off, in, m, op);
        in += sizeof(float) + m;
      }
      break;
    }
  }
  // The fused stage is both a decode (rx accounting) and the collectives'
  // reduce step — feed the reduce counter too so the post-wire stage stays
  // visible next to the uncompressed path's numbers.
  g_reduce_bytes.fetch_add(n * sizeof(float), std::memory_order_relaxed);
  CountCodec(c, 1, CodecWireBytes(c, n), n);
}

void CodecDecodeReduceQuantize(WireCodec c, float* dst, const float* local,
                               const uint8_t* wire, uint8_t* enc_out,
                               size_t n, WireRedOp op) {
  if (local == nullptr) local = dst;
  switch (c) {
    case WireCodec::kF32:
      // Degenerate: no quantization; reduce then copy the bytes out.
      ReduceInto(dst, local, wire, n, WireDType::kF32, op);
      memcpy(enc_out, dst, n * 4);
      return;
    case WireCodec::kBF16: {
      const auto* w16 = reinterpret_cast<const uint16_t*>(wire);
      auto* e16 = reinterpret_cast<uint16_t*>(enc_out);
#if defined(__x86_64__)
      if (CodecSimdEnabled()) {
        DecodeReduceQuantBf16Avx2(dst, local, w16, e16, n, op);
      } else {
        DecodeReduceQuantBf16Scalar(dst, local, w16, e16, n, op);
      }
#else
      DecodeReduceQuantBf16Scalar(dst, local, w16, e16, n, op);
#endif
      break;
    }
    case WireCodec::kI8: {
      // Per 256-element block (1 KiB, L1-resident): reduce into dst, encode
      // dst, decode back over dst — three hot passes beat one cold
      // whole-slice encode + decode later.
      const uint8_t* in = wire;
      uint8_t* out = enc_out;
      for (size_t off = 0; off < n; off += kI8CodecBlock) {
        size_t m = std::min(kI8CodecBlock, n - off);
        DecodeReduceI8Block(dst + off, local + off, in, m, op);
        EncodeI8Block(dst + off, out, m);
        DecodeI8Block(out, dst + off, m);
        in += sizeof(float) + m;
        out += sizeof(float) + m;
      }
      break;
    }
  }
  g_reduce_bytes.fetch_add(n * sizeof(float), std::memory_order_relaxed);
  CountCodec(c, 1, CodecWireBytes(c, n), n);  // decoded the incoming chunk
  CountCodec(c, 0, CodecWireBytes(c, n), n);  // produced the AG send bytes
}

uint64_t CodecBytesTotal(WireCodec c, int dir) {
  return g_codec_bytes[static_cast<int>(c)][dir & 1].load(std::memory_order_relaxed);
}

uint64_t CodecPayloadBytesTotal(int dir) {
  return g_codec_payload[dir & 1].load(std::memory_order_relaxed);
}

void ResetCodecBytesTotals() {
  for (auto& per_codec : g_codec_bytes) {
    for (auto& v : per_codec) v.store(0, std::memory_order_relaxed);
  }
  for (auto& v : g_codec_payload) v.store(0, std::memory_order_relaxed);
}

ScratchBuf::~ScratchBuf() {
  if (p_) ::operator delete[](p_, std::align_val_t(64));
}

void ScratchBuf::reserve(size_t n) {
  if (n <= cap_) return;
  if (p_) ::operator delete[](p_, std::align_val_t(64));
  p_ = static_cast<uint8_t*>(::operator new[](n, std::align_val_t(64)));
  cap_ = n;
}

bool ParseUserPassAndAddr(const std::string& s, UserPassAddr* out) {
  // Reference: utils.rs:180-198 regex ^((user):(pass)@)?addr$.
  out->user.clear();
  out->pass.clear();
  out->addr.clear();
  size_t at = s.rfind('@');
  if (at == std::string::npos) {
    if (s.empty()) return false;
    out->addr = s;
    return true;
  }
  std::string cred = s.substr(0, at);
  out->addr = s.substr(at + 1);
  size_t colon = cred.find(':');
  if (colon == std::string::npos || out->addr.empty()) return false;
  out->user = cred.substr(0, colon);
  out->pass = cred.substr(colon + 1);
  return !out->user.empty();
}

void EncodeU64BE(uint64_t v, uint8_t out[8]) {
  for (int i = 7; i >= 0; --i) {
    out[i] = static_cast<uint8_t>(v & 0xff);
    v >>= 8;
  }
}

uint64_t DecodeU64BE(const uint8_t in[8]) {
  uint64_t v = 0;
  for (int i = 0; i < 8; ++i) v = (v << 8) | in[i];
  return v;
}

Status SetNodelay(int fd) {
  int one = 1;
  if (setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one)) != 0) {
    return Status::TCP("TCP_NODELAY failed: " + std::string(strerror(errno)));
  }
  return Status::Ok();
}

void ApplySocketBufsize(int fd) {
  static const int kBufsize = static_cast<int>(GetEnvU64("TPUNET_SOCKET_BUFSIZE", 0));
  if (kBufsize <= 0) return;
  setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &kBufsize, sizeof(kBufsize));
  setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &kBufsize, sizeof(kBufsize));
}

void ApplyKeepalive(int fd) {
  // Dead-peer detection: without keepalive, a host that vanishes (power
  // loss, network partition) leaves blocked reads hanging forever — the
  // reference has no liveness mechanism at all (SURVEY §5 "failure
  // detection: essentially absent"). Defaults: first probe after 30s idle,
  // then every 10s, declare dead after 3 misses (~60s to error).
  // TPUNET_KEEPALIVE_IDLE_S=0 disables.
  static const int kIdle = static_cast<int>(GetEnvU64("TPUNET_KEEPALIVE_IDLE_S", 30));
  if (kIdle <= 0) return;
  static const int kIntvl = static_cast<int>(GetEnvU64("TPUNET_KEEPALIVE_INTVL_S", 10));
  static const int kCnt = static_cast<int>(GetEnvU64("TPUNET_KEEPALIVE_CNT", 3));
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_KEEPALIVE, &one, sizeof(one));
  setsockopt(fd, IPPROTO_TCP, TCP_KEEPIDLE, &kIdle, sizeof(kIdle));
  setsockopt(fd, IPPROTO_TCP, TCP_KEEPINTVL, &kIntvl, sizeof(kIntvl));
  setsockopt(fd, IPPROTO_TCP, TCP_KEEPCNT, &kCnt, sizeof(kCnt));
}

Status SetNonblocking(int fd) {
  int flags = fcntl(fd, F_GETFL, 0);
  if (flags < 0 || fcntl(fd, F_SETFL, flags | O_NONBLOCK) != 0) {
    return Status::TCP("O_NONBLOCK failed: " + std::string(strerror(errno)));
  }
  return Status::Ok();
}

std::string SockaddrToString(const sockaddr_storage& ss, socklen_t len) {
  char host[INET6_ADDRSTRLEN] = {0};
  uint16_t port = 0;
  if (ss.ss_family == AF_INET && len >= sizeof(sockaddr_in)) {
    auto* sin = reinterpret_cast<const sockaddr_in*>(&ss);
    inet_ntop(AF_INET, &sin->sin_addr, host, sizeof(host));
    port = ntohs(sin->sin_port);
  } else if (ss.ss_family == AF_INET6 && len >= sizeof(sockaddr_in6)) {
    auto* sin6 = reinterpret_cast<const sockaddr_in6*>(&ss);
    inet_ntop(AF_INET6, &sin6->sin6_addr, host, sizeof(host));
    port = ntohs(sin6->sin6_port);
  } else {
    return "<unknown af " + std::to_string(ss.ss_family) + ">";
  }
  return std::string(host) + ":" + std::to_string(port);
}

}  // namespace tpunet
