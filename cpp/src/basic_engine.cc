// tpunet BASIC engine — thread-per-stream multi-stream TCP transport.
//
// TPU-native re-design of the reference's default engine
// (reference: src/implement/nthread_per_socket_backend.rs). Behavioral
// contract reproduced:
//   * per send/recv comm: 1 scheduler thread + nstreams data-stream threads,
//     each owning one TCP connection (reference :103-237, :336-361).
//   * every message is split into chunks of max(ceil(len/nstreams),
//     min_chunksize) and chunks are assigned round-robin starting at a
//     per-comm cursor that persists ACROSS messages (reference :393,412) —
//     the fairness mechanism: even 1-chunk messages rotate streams.
//   * sender and receiver compute identical chunk boundaries + assignment
//     from (len, min_chunksize, nstreams) alone, so the wire carries no
//     per-chunk header; TCP per-stream ordering makes this correct.
//   * per message the ctrl stream carries an 8-byte big-endian length frame
//     (reference :395-397/:494-502); the receiver may post a larger buffer
//     and learns the true size from this frame.
//   * completion = bytes handed to the kernel socket buffer, not peer-ACKed.
//   * request lifecycle: isend/irecv return an id, test() polls, done
//     consumes the id.
//
// Deliberate improvements over the reference (documented deltas):
//   * Wire preamble carries bundle id + nstreams + min_chunksize (wire.h) —
//     concurrent senders on one listen socket, no config divergence, magic
//     check. Shared with the EPOLL engine, so the two engines interoperate
//     (the reference's BASIC/TOKIO were wire-incompatible).
//   * Blocking sockets by default instead of the reference's nonblocking
//     busy-poll spin (reference utils.rs:132-178) — a TPU host shares cores
//     with the trainer; TPUNET_SPIN=1 restores spin mode for latency hunts.
//   * No global engine mutex (reference lib.rs:14-16): ids resolve through
//     sharded maps, test() touches only atomics.
//   * Request ids are freed on completion (reference leaked them:
//     cc/bagua_net.cc:111-121).
#include <errno.h>
#include <poll.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <deque>
#include <memory>
#include <mutex>
#include <thread>
#include <vector>

#include "engine_base.h"
#include "fault.h"
#include "id_map.h"
#include "tpunet/mutex.h"
#include "tpunet/net.h"
#include "tpunet/telemetry.h"
#include "tpunet/utils.h"
#include "wire.h"

namespace tpunet {
namespace {

// Number of lazy recvs currently parked process-wide. Lets a send-side
// wait() park on its condvar outright (no 50ms upgrade sweeps) when there
// is nothing to upgrade. Global (not per-engine) so Comm::Shutdown can
// maintain it; cross-engine conservatism is harmless.
std::atomic<int> g_lazy_parked{0};

bool DebugOn() {
  static const bool on = GetEnvU64("TPUNET_DEBUG", 0) != 0;
  return on;
}
#define TPUNET_DBG(...) do { if (DebugOn()) { fprintf(stderr, "[eng %d] ", (int)getpid()); fprintf(stderr, __VA_ARGS__); fprintf(stderr, "\n"); } } while (0)

// MPSC blocking queue with close semantics (stands in for the reference's
// flume channels, nthread:224-226). Pop returns false only when closed AND
// drained, so close_send/close_recv still flush queued work.
template <typename T>
class Queue {
 public:
  // Returns false (and does not enqueue) once the queue is closed — the
  // caller owns failing the item. This is how a poisoned comm rejects new
  // messages without a parked fail-sink thread.
  bool Push(T t) {
    {
      MutexLock lk(mu_);
      if (closed_) return false;
      q_.push_back(std::move(t));
    }
    cv_.NotifyOne();
    return true;
  }
  bool Pop(T* out) {
    MutexLock lk(mu_);
    while (!closed_ && q_.empty()) cv_.Wait(mu_);
    if (q_.empty()) return false;
    *out = std::move(q_.front());
    q_.pop_front();
    return true;
  }
  // Nonblocking drain (failover: a retiring worker discards its queued
  // tasks — the per-stream records are the authoritative copy).
  bool TryPop(T* out) {
    MutexLock lk(mu_);
    if (q_.empty()) return false;
    *out = std::move(q_.front());
    q_.pop_front();
    return true;
  }
  void Close() {
    {
      MutexLock lk(mu_);
      closed_ = true;
    }
    cv_.NotifyAll();
  }

 private:
  Mutex mu_;  // leaf: nothing else is acquired while held
  CondVar cv_;
  std::deque<T> q_ GUARDED_BY(mu_);
  bool closed_ GUARDED_BY(mu_) = false;
};

struct ChunkTask {
  uint8_t* data = nullptr;  // send: source bytes; recv: destination bytes
  size_t len = 0;
  uint64_t seq = 0;  // per-stream chunk sequence number (failover protocol)
  RequestPtr state;
};

// Failover bookkeeping: one record per chunk logically assigned to a data
// stream. The sender retains records until the owning message settles (so a
// NACKed stream's undelivered chunks can be retransmitted over the ctrl
// connection); the receiver retains them until the chunk is fully read (so
// a FAILOVER marker knows which buffers the retransmit batch fills).
struct ChunkRec {
  uint64_t seq = 0;
  uint8_t* data = nullptr;
  size_t len = 0;
  RequestPtr state;
  bool written = false;  // sender only: payload fully handed to the kernel
};

struct Msg {
  uint8_t* data = nullptr;
  size_t len = 0;
  RequestPtr state;
};

struct Comm;

// One data stream: a TCP connection owned by one worker thread.
struct StreamWorker {
  int fd = -1;
  size_t idx = 0;  // data-stream index (for per-stream fairness counters)
  Comm* comm = nullptr;
  Queue<ChunkTask> tasks;
  std::thread thread;
};

// A send or recv comm: ctrl connection + scheduler thread + stream workers.
struct Comm {
  bool is_send = false;
  int ctrl_fd = -1;
  size_t nstreams = 0;
  size_t min_chunksize = 0;
  bool spin = false;
  bool crc = false;  // per-chunk CRC32C trailers (negotiated in the preamble)
  // QoS traffic class (sender's engine class, carried to the receiver in
  // the preamble nibble — docs/DESIGN.md "Transport QoS"). Drives the
  // wire-credit gate on send workers and per-class byte accounting on both
  // sides; immutable after wiring.
  TrafficClass cls = TrafficClass::kBulk;
  std::vector<std::unique_ptr<StreamWorker>> workers;
  Queue<Msg> msgs;
  std::unique_ptr<std::thread> scheduler;

  // ---- Failover state (single-stream degradation; docs/DESIGN.md) -------
  // fo_mu guards chunk assignment (cursor, per-stream seq counters,
  // records, dead/retired bits) AND every ctrl-stream write, so message
  // length frames and FAILOVER markers are totally ordered — that ordering
  // is what lets both sides switch their chunk→stream rotation at the same
  // point. Uncontended in steady state: one acquisition per message, not
  // per chunk... (chunks are dispatched under the same acquisition).
  // Ordering: ctrl_mu may be held when fo_mu is taken (failover marker
  // processing), never the reverse.
  Mutex fo_mu ACQUIRED_AFTER(ctrl_mu);
  // dead: IO on the stream has failed locally (or a NACK told the sender);
  // no further tasks go to its worker, but the assignment rotation still
  // includes it — records accumulate — until the FAILOVER marker retires it.
  // retired: excluded from the rotation from the marker point in ctrl order.
  std::vector<uint8_t> stream_dead GUARDED_BY(fo_mu);
  std::vector<uint8_t> stream_retired GUARDED_BY(fo_mu);
  size_t dead_count GUARDED_BY(fo_mu) = 0;
  std::vector<std::deque<ChunkRec>> recs GUARDED_BY(fo_mu);  // per-stream, seq-ordered
  std::vector<uint64_t> next_seq GUARDED_BY(fo_mu);  // chunks ever assigned per stream
  std::vector<uint64_t> done_seq GUARDED_BY(fo_mu);  // receiver: chunks fully read
  // Receiver ctrl-read ownership: the scheduler, a lazy-recv caller, and a
  // failed worker acting as ctrl pump never read the ctrl fd concurrently.
  // A LEN frame read by the pump before its message is popped is stashed
  // here (consumed by the next owner, preserving frame↔message pairing).
  Mutex ctrl_mu;
  bool has_pending_frame GUARDED_BY(ctrl_mu) = false;
  uint64_t pending_frame GUARDED_BY(ctrl_mu) = 0;
  // Sender: reverse-ctrl reader parked on the (normally silent) receiver→
  // sender direction of the ctrl connection, waiting for NACK frames.
  std::unique_ptr<std::thread> nack_reader;

  // ---- Lane striping (docs/DESIGN.md "Lanes & adaptive striping") --------
  // `lanes` flips the chunk→stream rotation from the uniform cursor onto a
  // weighted-round-robin slot table derived from `weights`. Negotiated via
  // kPreambleFlagLanes (sender-wins): both sides run the slot-table walk or
  // neither does, so the maps stay symmetric. Weights change only via
  // epoch-stamped WEIGHTS ctrl frames, emitted/applied under fo_mu in the
  // same total order as message LEN frames — re-striping therefore lands
  // exactly at message boundaries and every downstream mechanism (CRC
  // framing, failover records, QoS credits, codec chunk sizing) composes
  // unchanged.
  bool lanes = false;
  bool lane_adapt = false;          // sender runs the adaptation loop
  uint64_t lane_adapt_us = 100000;  // TPUNET_LANE_ADAPT_MS
  std::vector<uint32_t> base_weights;  // configured lane weights (TPUNET_LANES)
  std::vector<uint32_t> weights GUARDED_BY(fo_mu);
  std::vector<uint8_t> slots GUARDED_BY(fo_mu);  // WRR slot table
  uint64_t stripe_epoch GUARDED_BY(fo_mu) = 0;
  uint64_t next_adapt_us GUARDED_BY(fo_mu) = 0;
  // Per-lane wire-service accounting fed by the send workers (relaxed
  // atomics — the adaptation tick drains them under fo_mu). busy_us counts
  // the full chunk service time including kernel backpressure and injected
  // delays, which is what makes the measured rate track the path a TCP_INFO
  // delivery-rate sample cannot see through on loopback.
  struct LaneIo {
    std::atomic<uint64_t> busy_us{0};
    std::atomic<uint64_t> bytes{0};
    std::atomic<uint64_t> rate_ewma_bps{0};
  };
  std::unique_ptr<LaneIo[]> lane_io;  // sized nstreams before threads start

  bool Aborted() const { return aborted_.load(std::memory_order_acquire); }
  // For QosScheduler::AcquireWire's bounded park: a worker waiting for wire
  // credit must notice comm shutdown without a dedicated wakeup channel.
  const std::atomic<bool>* aborted_flag() const { return &aborted_; }
  // Inline fast path state (PERF_NOTES: caller->scheduler->worker hops cost
  // ~0.4ms per 1MiB message on a 1-core host). `inflight` counts messages
  // not yet fully settled; when it reads 0 the scheduler is idle and every
  // prior byte is in the kernel, so the caller thread may take the
  // scheduler's role for its own message (ctrl frame + chunk dispatch)
  // without reordering the wire. `cursor` is the chunk->stream rotation,
  // shared by scheduler and inline path — never concurrently: the inline
  // path only runs at inflight==0, and the release/acquire pair on
  // `inflight` orders the scheduler's last cursor write before the caller's
  // read. Callers are single-threaded per comm (NCCL proxy contract; our
  // collectives layer likewise).
  std::atomic<uint64_t> inflight{0};
  // All cursor touches happen inside the fo_mu-held assignment sections
  // (AssignStreamIdx), so the annotation is fo_mu even though the
  // inline-path handoff above is what really orders scheduler vs caller.
  uint64_t cursor GUARDED_BY(fo_mu) = 0;
  // Lazy recv slot: an irecv posted on an idle comm parks here; its wait()
  // executes the ctrl read + data read inline on the caller thread (saving
  // two hops and the completion wakeup). test() or a later irecv upgrades
  // it onto the scheduler queue instead.
  Mutex lazy_mu;
  Msg lazy_msg GUARDED_BY(lazy_mu);
  bool has_lazy GUARDED_BY(lazy_mu) = false;
  uint64_t lazy_req GUARDED_BY(lazy_mu) = 0;
  // Threads do not survive fork(): a mismatch means this comm's scheduler /
  // workers never existed in this process (see Shutdown and the engine's
  // isend/irecv fail-fast).
  const uint64_t fork_gen = ForkGeneration();

  ~Comm() { Shutdown(); }

  // On any stream IO error, poison every connection in the comm so sibling
  // workers blocked mid-chunk fail fast and all requests quiesce — without
  // this, a single dead stream would leave test() hanging on the survivors.
  void AbortStreams() {
    if (aborted_.exchange(true)) return;
    for (auto& w : workers) {
      if (w->fd >= 0) ::shutdown(w->fd, SHUT_RDWR);
    }
    if (ctrl_fd >= 0) ::shutdown(ctrl_fd, SHUT_RDWR);
  }

  void Shutdown() {
    if (shut_) return;
    shut_ = true;
    // A lazy recv parked here would otherwise never execute; fail it so a
    // post-close wait() errors instead of hanging.
    {
      MutexLock lk(lazy_mu);
      if (has_lazy) {
        lazy_msg.state->SetError("comm closed with pending lazy recv");
        lazy_msg.state->total.store(0, std::memory_order_release);
        inflight.fetch_sub(1, std::memory_order_release);
        lazy_msg.state->NotifyIfSettled();
        lazy_msg = Msg{};
        has_lazy = false;
        g_lazy_parked.fetch_sub(1, std::memory_order_relaxed);
      }
    }
    if (ForkGeneration() != fork_gen) {
      // Forked child: scheduler/worker pthreads never existed here and the
      // queue mutexes may have been captured mid-lock at fork. Leak the
      // thread handles (any pthread call on their stale ids is UB) and only
      // close this process's copies of the fds.
      (void)scheduler.release();
      (void)nack_reader.release();
      for (auto& w : workers) {
        if (w->fd >= 0) ::close(w->fd);
        (void)w.release();
      }
      workers.clear();
      if (ctrl_fd >= 0) ::close(ctrl_fd);
      ctrl_fd = -1;
      return;
    }
    msgs.Close();
    // By the NCCL contract every request has been test()ed done before close,
    // so scheduler/workers are idle in Pop and the shutdown()s below are
    // no-ops data-wise. If the contract was violated (peer stalled/died with
    // bytes in flight), SHUT_RDWR wakes threads blocked in kernel send/recv —
    // a hang would otherwise be permanent since std::thread has no timed join.
    AbortStreams();
    if (scheduler && scheduler->joinable()) scheduler->join();
    if (nack_reader && nack_reader->joinable()) nack_reader->join();
    for (auto& w : workers) w->tasks.Close();
    for (auto& w : workers) {
      if (w->thread.joinable()) w->thread.join();
    }
    for (auto& w : workers) {
      if (w->fd >= 0) ::close(w->fd);
      w->fd = -1;
    }
    if (ctrl_fd >= 0) ::close(ctrl_fd);
    ctrl_fd = -1;
  }

 private:
  std::atomic<bool> aborted_{false};
  bool shut_ = false;
};
using CommPtr = std::shared_ptr<Comm>;

// ---------------------------------------------------------------------------
// Worker / scheduler loops.

// Chunk completion accounting shared by worker loops AND the failover
// retransmit paths: whoever settles the message (last chunk) releases the
// comm's inflight slot, re-arming the inline fast path.
void AccountChunkDone(Comm* c, const RequestPtr& state, size_t len) {
  if (len > 0) {
    // Stage-latency stamps: every completion path (worker, lazy, failover
    // retransmit) marks last-wire here; the CAS-from-0 start is a fallback
    // for paths that never stamped the true IO start (retransmits).
    uint64_t now = MonotonicUs();
    state->MarkWireStart(now);
    state->MarkWireEnd(now);
  }
  state->nbytes.fetch_add(len, std::memory_order_relaxed);
  uint64_t prior = state->completed.fetch_add(1, std::memory_order_acq_rel);
  uint64_t tot = state->total.load(std::memory_order_acquire);
  TPUNET_DBG("chunk done len=%zu completed=%llu/%llu fail=%d", len, (unsigned long long)(prior+1), (unsigned long long)tot, (int)state->failed.load());
  if (prior + 1 >= tot) {
    c->inflight.fetch_sub(1, std::memory_order_release);
  }
  state->NotifyIfSettled();
}

void FinishChunk(StreamWorker* w, ChunkTask& t) { AccountChunkDone(w->comm, t.state, t.len); }

// ---- Chunk assignment (fo_mu held) ----------------------------------------

// Rotating-cursor pick over the NON-RETIRED streams in index order. With no
// failures this is exactly the historical workers[cursor % nstreams]; after
// a failover marker both sides hold an identical retired set and an
// identical cursor (assignments are identical in ctrl order), so the
// reduced-width rotation stays symmetric.
size_t AssignStreamIdx(Comm* c) REQUIRES(c->fo_mu) {
  if (c->lanes && !c->slots.empty()) {
    // Weighted rotation: walk the WRR slot table from the shared cursor,
    // skipping retired streams (post-failover re-stripe of the survivors).
    // Both sides advance the cursor identically — including the skips —
    // because retirement and weight epochs land at the same points in ctrl
    // order, so the maps stay symmetric with zero per-chunk wire metadata.
    for (size_t tries = 0; tries <= c->slots.size(); ++tries) {
      size_t s = c->slots[c->cursor % c->slots.size()];
      c->cursor += 1;
      if (!c->stream_retired[s]) return s;
    }
    return 0;  // unreachable: alive >= 1 and every stream has >= 1 slot
  }
  size_t alive = c->nstreams - [&] {
    size_t r = 0;
    for (size_t i = 0; i < c->nstreams; ++i) r += c->stream_retired[i] ? 1 : 0;
    return r;
  }();
  size_t pick = c->cursor % alive;
  c->cursor += 1;  // persists across messages — fairness rotation
  for (size_t i = 0; i < c->nstreams; ++i) {
    if (c->stream_retired[i]) continue;
    if (pick == 0) return i;
    --pick;
  }
  return 0;  // unreachable: alive >= 1 is an invariant (last loss poisons)
}

// Drop front records whose chunk was written AND whose message has settled
// — the app may free those buffers after test(), so they are no longer
// retransmittable (a NACK that still needs one becomes a typed poison, the
// accepted kernel-buffered-bytes-lost race).
void PruneRecs(Comm* c, size_t idx) REQUIRES(c->fo_mu) {
  auto& q = c->recs[idx];
  while (!q.empty() && q.front().written &&
         (q.front().state->Done() || q.front().state->failed.load(std::memory_order_acquire))) {
    q.pop_front();
  }
}

// Assign one chunk: record it, and hand it to the worker unless the stream
// is locally dead (then the record alone carries it until the failover
// marker retransmits or poisons).
void AssignChunk(Comm* c, uint8_t* data, size_t n, const RequestPtr& state)
    REQUIRES(c->fo_mu) {
  size_t idx = AssignStreamIdx(c);
  uint64_t seq = c->next_seq[idx]++;
  if (c->is_send) PruneRecs(c, idx);
  c->recs[idx].push_back(ChunkRec{seq, data, n, state, false});
  if (!c->stream_dead[idx]) {
    c->workers[idx]->tasks.Push(ChunkTask{data, n, seq, state});
  }
}

// Sender: flag a record's payload as kernel-accepted (completion-counted).
// Returns false when the record is GONE — a concurrent NACK failover
// already claimed this chunk (retransmitted it over ctrl and accounted it),
// so the worker must NOT count it again. A missing record can mean nothing
// else: prune only removes records already marked written.
bool MarkWritten(Comm* c, size_t idx, uint64_t seq) {
  MutexLock lk(c->fo_mu);
  for (auto& r : c->recs[idx]) {
    if (r.seq == seq) {
      r.written = true;
      return true;
    }
  }
  return false;
}

// Receiver: a chunk fully arrived on its assigned stream.
void PopRec(Comm* c, size_t idx, uint64_t seq) {
  MutexLock lk(c->fo_mu);
  auto& q = c->recs[idx];
  if (!q.empty() && q.front().seq == seq) q.pop_front();
  c->done_seq[idx] = seq + 1;
}

// ---- Chunk wire IO (vectored) ----------------------------------------------
// One sendmsg/recvmsg per chunk: payload and (when negotiated) the 4-byte
// CRC32C trailer ride a single syscall instead of two, and the recv side's
// MSG_WAITALL read is one syscall per chunk instead of one per kernel-buffer
// refill. Wire bytes are IDENTICAL to the segmented writes (payload||crc) —
// v3 peers interop either way; tests/test_wire_vectored.py captures the
// frames and pins that.

Status SendChunkWire(int fd, const uint8_t* data, size_t len, bool crc, bool spin) {
  if (!crc) return WriteAll(fd, data, len, spin);
  uint8_t crcb[4];
  EncodeU32BE(Crc32c(data, len), crcb);
  struct iovec iov[2] = {{const_cast<uint8_t*>(data), len}, {crcb, sizeof(crcb)}};
  return WritevAll(fd, iov, 2, spin);
}

// With CRC: trailer is read into *wire_crc alongside the payload. The CRC is
// computed over the ORIGINAL bytes by the sender, so a fault-injected wire
// flip (applied by the caller after this returns) is detectable.
Status RecvChunkWire(int fd, uint8_t* data, size_t len, bool crc, bool spin,
                     uint32_t* wire_crc) {
  if (!crc) return ReadExact(fd, data, len, spin);
  uint8_t crcb[4];
  struct iovec iov[2] = {{data, len}, {crcb, sizeof(crcb)}};
  Status s = ReadvExact(fd, iov, 2, spin);
  if (s.ok()) *wire_crc = DecodeU32BE(crcb);
  return s;
}

// ---- Stream failure handling ----------------------------------------------

// Sender-side data-stream IO failure. Returns true when failover is engaged
// (the worker retires quietly: drain the queue, keep the records, wait for
// the receiver's NACK); false when the comm must poison (already aborted,
// single-stream comm, or last surviving stream).
bool SenderStreamFailed(Comm* c, StreamWorker* w) {
  {
    MutexLock lk(c->fo_mu);
    if (c->Aborted() || c->nstreams == 1) return false;
    if (!c->stream_dead[w->idx]) {
      if (c->dead_count + 1 >= c->nstreams) return false;  // last stream: poison
      c->stream_dead[w->idx] = 1;
      c->dead_count += 1;
      Telemetry::Get().OnStreamFailover();
      // Force the receiver's blocked read to notice promptly even when the
      // failure was one-sided (FIN/RST): its NACK is what unblocks us.
      ::shutdown(w->fd, SHUT_RDWR);
      TPUNET_DBG("send stream %zu dead, awaiting NACK", w->idx);
    }
  }
  ChunkTask d;
  while (w->tasks.TryPop(&d)) {
  }  // records are the authoritative copy
  return true;
}

// Receiver-side data-stream IO failure: same verdict logic; on failover the
// caller sends the NACK naming how many chunks it fully read off the stream
// (== the first per-stream seq it still needs).
bool ReceiverStreamFailed(Comm* c, StreamWorker* w) {
  {
    MutexLock lk(c->fo_mu);
    if (c->Aborted() || c->nstreams == 1) return false;
    if (!c->stream_dead[w->idx]) {
      if (c->dead_count + 1 >= c->nstreams) return false;
      c->stream_dead[w->idx] = 1;
      c->dead_count += 1;
      Telemetry::Get().OnStreamFailover();
      uint8_t frame[8];
      EncodeU64BE(PackCtrlFrame(kCtrlFrameNack, w->idx, c->done_seq[w->idx]), frame);
      Status ns = WriteAll(c->ctrl_fd, frame, sizeof(frame), c->spin);
      if (!ns.ok()) return false;  // ctrl is gone too: poison
      TPUNET_DBG("recv stream %zu dead, NACK sent (done_seq=%llu)", w->idx,
                 (unsigned long long)c->done_seq[w->idx]);
    }
  }
  ChunkTask d;
  while (w->tasks.TryPop(&d)) {
  }
  return true;
}

void PoisonAndDrainQueue(Comm* c, const std::string& why);  // defined below

void SendWorkerLoop(StreamWorker* w, bool spin) {
  Comm* c = w->comm;
  QosScheduler& qos = QosScheduler::Get();
  const bool gated = qos.wire_gate_enabled();
  ChunkTask t;
  while (w->tasks.Pop(&t)) {
    // QoS wire gate: hold credit for this chunk's wire bytes before they
    // may enter the kernel socket buffer. The DRR pump (qos.cc) decides
    // grant order across classes, so a latency-class chunk on another comm
    // waits behind at most the window of already-granted bytes — never
    // behind this comm's whole backlog. Credit is returned right after the
    // write syscall on EVERY path (the kernel buffer drains on its own).
    size_t wire_len = t.len + (c->crc ? 4 : 0);
    if (gated && !qos.AcquireWire(c->cls, wire_len, c->aborted_flag())) {
      // Comm aborted while parked for credit: same verdict as an IO error
      // on an aborted comm — settle the chunk and drain.
      t.state->SetError("comm aborted while awaiting QoS wire credit");
      FinishChunk(w, t);
      PoisonAndDrainQueue(c, "comm aborted while awaiting QoS wire credit");
      continue;
    }
    t.state->MarkWireStart(MonotonicUs());  // queue stage ends at first chunk IO
    // Lane service clock: spans the fault gate AND the (blocking) write, so
    // injected delays and kernel backpressure both land in the measured
    // per-lane rate — the adaptation signal TCP_INFO's burst-window
    // delivery-rate estimate cannot see on loopback.
    uint64_t lane_t0 = c->lanes ? MonotonicUs() : 0;
    FaultAction fa = FaultCheck(true, w->idx, w->fd, t.len);
    Status s;
    if (fa == FaultAction::kCorrupt) {
      // Damage the wire copy, never the caller's buffer; the CRC trailer is
      // computed over the ORIGINAL bytes so TPUNET_CRC=1 catches the flip.
      std::vector<uint8_t> dup(t.data, t.data + t.len);
      if (!dup.empty()) dup[dup.size() / 2] ^= 0x01;
      if (c->crc) {
        uint8_t crcb[4];
        EncodeU32BE(Crc32c(t.data, t.len), crcb);
        struct iovec iov[2] = {{dup.data(), dup.size()}, {crcb, sizeof(crcb)}};
        s = WritevAll(w->fd, iov, 2, spin);
      } else {
        s = WriteAll(w->fd, dup.data(), dup.size(), spin);
      }
    } else {
      s = SendChunkWire(w->fd, t.data, t.len, c->crc, spin);
    }
    if (gated) qos.ReleaseWire(c->cls, wire_len);
    if (!s.ok()) {
      if (SenderStreamFailed(c, w)) return;  // failover: records carry the rest
      t.state->SetError(s.msg);
      FinishChunk(w, t);
      // Full poison (not just AbortStreams): any records orphaned by an
      // earlier mid-failover stream death must settle too, or test() would
      // hold their requests forever waiting to quiesce.
      PoisonAndDrainQueue(c, s.msg);
      continue;
    }
    if (!MarkWritten(c, w->idx, t.seq)) {
      // A racing NACK failover already retransmitted and ACCOUNTED this
      // chunk (our "successful" write went into a dying socket's buffer).
      // Counting it again would underflow the comm's inflight slot.
      ChunkTask d;
      while (w->tasks.TryPop(&d)) {
      }
      return;
    }
    if (c->lanes && c->lane_io) {
      uint64_t dt = MonotonicUs() - lane_t0;
      c->lane_io[w->idx].busy_us.fetch_add(dt ? dt : 1, std::memory_order_relaxed);
      c->lane_io[w->idx].bytes.fetch_add(t.len, std::memory_order_relaxed);
      Telemetry::Get().OnLaneBytes(true, w->idx, t.len);
    }
    Telemetry::Get().OnStreamBytes(true, w->idx, t.len,
                                   static_cast<int>(c->cls));
    Telemetry::Get().MaybeSampleStream(true, w->idx, w->fd);
    FinishChunk(w, t);
  }
}

void PumpCtrlUntilRetired(Comm* c, size_t idx);  // defined after frame handling

void RecvWorkerLoop(StreamWorker* w, bool spin) {
  Comm* c = w->comm;
  ChunkTask t;
  while (w->tasks.Pop(&t)) {
    t.state->MarkWireStart(MonotonicUs());
    FaultAction fa = FaultCheck(false, w->idx, w->fd, t.len);
    uint32_t wire_crc = 0;
    Status s = RecvChunkWire(w->fd, t.data, t.len, c->crc, spin, &wire_crc);
    if (!s.ok()) {
      if (ReceiverStreamFailed(c, w)) {
        // Become the ctrl pump: with the scheduler possibly parked waiting
        // for the NEXT message, nobody else may be reading the ctrl stream,
        // and the FAILOVER marker + retransmitted chunks arrive there.
        PumpCtrlUntilRetired(c, w->idx);
        return;
      }
      t.state->SetError(s.msg);
      FinishChunk(w, t);
      PoisonAndDrainQueue(c, s.msg);  // see SendWorkerLoop: settles orphans too
      continue;
    }
    if (fa == FaultAction::kCorrupt && t.len > 0) {
      t.data[t.len / 2] ^= 0x01;  // simulate wire damage before verification
    }
    if (c->crc && wire_crc != Crc32c(t.data, t.len)) {
      // Integrity failure is a REQUEST error, not a disconnect: the stream
      // framing is intact (we consumed exactly chunk+trailer), so the comm
      // keeps working for subsequent messages.
      Telemetry::Get().OnCrcError();
      t.state->SetError(ErrorKind::kCorruption,
                        "CRC32C mismatch on data stream " + std::to_string(w->idx) +
                            ": payload corrupted in transit");
    } else {
      Telemetry::Get().OnStreamBytes(false, w->idx, t.len,
                                     static_cast<int>(c->cls));
      if (c->lanes) Telemetry::Get().OnLaneBytes(false, w->idx, t.len);
      Telemetry::Get().MaybeSampleStream(false, w->idx, w->fd);
    }
    PopRec(c, w->idx, t.seq);
    FinishChunk(w, t);
  }
}

// Receiver-side: chunk a message and fan chunks out to stream workers
// round-robin from the rotating cursor. The send side runs the same chunk
// math + rotation inline in SendOneMsg (with ctrl-frame accounting on top),
// keeping the two chunk maps symmetric (SURVEY hard-part #2). Callers hold
// NO locks; the assignment happens under fo_mu.
void DispatchChunks(Comm* c, uint8_t* data, size_t len, const RequestPtr& state) {
  size_t csize = ChunkSize(len, c->min_chunksize, c->nstreams);
  size_t nchunks = ChunkCount(len, csize);
  state->total.store(nchunks, std::memory_order_release);  // 0-byte msg: done now
  if (nchunks == 0) {
    c->inflight.fetch_sub(1, std::memory_order_release);
    state->NotifyIfSettled();
    return;
  }
  state->NotifyIfSettled();
  MutexLock lk(c->fo_mu);
  size_t off = 0;
  for (size_t i = 0; i < nchunks; ++i) {
    size_t n = std::min(csize, len - off);
    AssignChunk(c, data + off, n, state);
    off += n;
  }
}

// Fail a message that never dispatched any chunk (its inflight slot is
// still held) and release the slot.
void FailMsg(Comm* c, const RequestPtr& state, const std::string& msg) {
  TPUNET_DBG("FailMsg: %s", msg.c_str());
  state->SetError(msg);
  state->total.store(0, std::memory_order_release);
  c->inflight.fetch_sub(1, std::memory_order_release);
  state->NotifyIfSettled();
}

// Poison the comm and promptly fail everything queued (reference broke its
// loop on ctrl error leaving queued requests to hang, nthread:396-401).
// Close() first so Pop drains without blocking — this runs on the CALLER
// thread via the inline fast path, not only on a dedicated scheduler that
// could afford to park as a fail-sink. Post-close isend/irecv see the
// closed queue (Push returns false) and fail their requests directly.
void PoisonAndDrainQueue(Comm* c, const std::string& why) {
  c->AbortStreams();
  c->msgs.Close();
  Msg m;
  while (c->msgs.Pop(&m)) {
    FailMsg(c, m.state, "comm broken by earlier ctrl-stream error: " + why);
  }
  // Orphaned failover records: chunks assigned to a dead-but-not-retired
  // stream have no worker task behind them (queues were drained when the
  // stream died), so nothing else will ever complete their accounting and
  // test() would hold the request forever waiting to quiesce.
  MutexLock lk(c->fo_mu);
  for (size_t i = 0; i < c->nstreams; ++i) {
    if (!c->stream_dead[i] || c->stream_retired[i]) continue;
    for (ChunkRec& r : c->recs[i]) {
      if (r.written) continue;  // already completion-counted by its worker
      r.state->SetError("comm poisoned with stream " + std::to_string(i) +
                        " mid-failover: " + why);
      AccountChunkDone(c, r.state, 0);
    }
    c->recs[i].clear();
    c->stream_retired[i] = 1;  // no retransmit is coming
  }
}

void FailAndDrain(Comm* c, const RequestPtr& state, const std::string& msg) {
  FailMsg(c, state, msg);
  PoisonAndDrainQueue(c, msg);
}

// ---- Lane adaptation (send side; docs/DESIGN.md "Lanes & adaptive
// striping") ----------------------------------------------------------------

// Weight resolution of the adaptive scheduler: the fastest lane is pinned
// at this weight and slower lanes scale below it, so byte shares track the
// measured rate ratio within one part in kLaneWeightScale.
constexpr uint32_t kLaneWeightScale = 16;

// Publish the comm's current weight vector as an epoch-stamped WEIGHTS ctrl
// frame. fo_mu held — the frame is totally ordered against LEN/FAILOVER
// frames, which is what confines re-striping to message boundaries.
Status PublishWeightsLocked(Comm* c) REQUIRES(c->fo_mu) {
  uint8_t buf[8 + 256];
  size_t n = BuildWeightsUnit(c->stripe_epoch, c->weights, buf);
  Status s = WriteAll(c->ctrl_fd, buf, n, c->spin);
  if (!s.ok()) return s;
  for (size_t i = 0; i < c->weights.size(); ++i) {
    Telemetry::Get().OnLaneWeight(i, c->weights[i]);
  }
  return Status::Ok();
}

// One adaptation tick, rate-limited to the comm's TPUNET_LANE_ADAPT_MS
// cadence: drain the per-lane service accounting into rate EWMAs, derive
// weight targets (rate-proportional, kLaneWeightScale resolution, floor 1),
// demote straggler-flagged lanes (TCP_INFO sRTT detector, rising-edge
// hysteresis upstream) by halving, and step current weights halfway toward
// their targets — geometric convergence whose half-life the fairness bench
// reads off the tpunet_lane_weight gauge. A changed vector bumps the epoch
// and publishes; an unchanged one costs two clock reads. The ctrl write is
// the only fallible step; the caller treats failure like a LEN-frame loss.
Status MaybeAdaptLanesLocked(Comm* c) REQUIRES(c->fo_mu) {
  if (!c->lanes || !c->is_send || !c->lane_adapt || !c->lane_io) return Status::Ok();
  uint64_t now = MonotonicUs();
  if (now < c->next_adapt_us) return Status::Ok();
  c->next_adapt_us = now + c->lane_adapt_us;
  uint64_t rmax = 0;
  bool moved = false;
  for (size_t i = 0; i < c->nstreams; ++i) {
    uint64_t bytes = c->lane_io[i].bytes.exchange(0, std::memory_order_relaxed);
    uint64_t busy = c->lane_io[i].busy_us.exchange(0, std::memory_order_relaxed);
    uint64_t ewma = c->lane_io[i].rate_ewma_bps.load(std::memory_order_relaxed);
    if (bytes > 0 && busy > 0) {
      uint64_t inst = bytes * 8 * 1000000 / busy;  // bits/s over service time
      ewma = ewma == 0 ? inst : (ewma + inst) / 2;
      c->lane_io[i].rate_ewma_bps.store(ewma, std::memory_order_relaxed);
      Telemetry::Get().OnLaneRate(i, ewma);
      moved = true;
    }
    // Re-export the weight gauge every tick (not only on publishes) so a
    // mid-run telemetry.reset() — how benches split warmup from
    // measurement — repopulates it without waiting for the next epoch.
    Telemetry::Get().OnLaneWeight(i, c->weights[i]);
    if (!c->stream_retired[i] && ewma > rmax) rmax = ewma;
  }
  if (!moved || rmax == 0) return Status::Ok();
  bool changed = false;
  for (size_t i = 0; i < c->nstreams; ++i) {
    if (c->stream_retired[i]) continue;
    uint64_t ewma = c->lane_io[i].rate_ewma_bps.load(std::memory_order_relaxed);
    uint32_t w = c->weights[i];
    uint32_t target = w;  // no measurement yet: hold
    if (ewma > 0) {
      target = static_cast<uint32_t>((kLaneWeightScale * ewma + rmax / 2) / rmax);
      if (target < 1) target = 1;
      if (target > kLaneWeightScale) target = kLaneWeightScale;
    }
    if (Telemetry::Get().StreamStraggling(true, i)) {
      uint32_t demoted = w > 1 ? w / 2 : 1;
      if (demoted < target) target = demoted;
    }
    uint32_t next = w;
    if (target > w) {
      next = w + std::max<uint32_t>(1, (target - w) / 2);
    } else if (target < w) {
      next = w - std::max<uint32_t>(1, (w - target) / 2);
    }
    if (next != w) {
      c->weights[i] = next;
      changed = true;
    }
  }
  if (!changed) return Status::Ok();
  c->stripe_epoch += 1;
  c->slots = BuildWrrSlots(c->weights);
  Telemetry::Get().OnRestripe();
  TPUNET_DBG("lane re-stripe epoch=%llu", (unsigned long long)c->stripe_epoch);
  return PublishWeightsLocked(c);
}

// Per-message sender work: chunk dispatch + ctrl length frame. Runs on the
// scheduler thread normally, or on the caller thread via the inline fast
// path (never concurrently — see Comm::inflight).
//
// Order matters on a shared core: the ctrl frame is the receiver's wakeup
// trigger (its ctrl read unblocks), and ctrl/data ride SEPARATE sockets, so
// nothing requires the frame to precede the payload bytes. Dispatching the
// chunks first means the receiver wakes to data already flowing instead of
// waking early, read-blocking on an empty data stream, and ping-ponging
// context switches with the sender's worker.
//
// The ctrl write is itself a completion unit (total = nchunks + 1): with
// chunks dispatched first, chunk completion alone no longer implies the
// frame is on the wire, and the inline fast path keys off "message fully
// settled" (inflight==0) to take the scheduler's role — if inflight could
// hit 0 with a scheduler ctrl write still pending, an inline frame could
// overtake it and desynchronize the receiver's ctrl stream.
bool SendOneMsg(Comm* c, const Msg& m) {
  uint8_t hdr[8];
  EncodeU64BE(m.len, hdr);
  size_t csize = ChunkSize(m.len, c->min_chunksize, c->nstreams);
  size_t nchunks = ChunkCount(m.len, csize);
  m.state->total.store(nchunks + 1, std::memory_order_release);
  Status s;
  bool dispatched = false;
  {
    // One fo_mu section covers this message's adaptation tick (possible
    // WEIGHTS frame), chunk assignment AND its ctrl length frame, so a
    // concurrent FAILOVER marker (NACK handler) lands strictly before or
    // strictly after the whole message in ctrl order — the receiver applies
    // the same assignment set either way, and a re-stripe can never split a
    // message.
    MutexLock lk(c->fo_mu);
    s = MaybeAdaptLanesLocked(c);
    if (s.ok()) {
      dispatched = true;
      size_t off = 0;
      for (size_t i = 0; i < nchunks; ++i) {
        size_t n = std::min(csize, m.len - off);
        AssignChunk(c, m.data + off, n, m.state);
        off += n;
      }
      s = WriteAll(c->ctrl_fd, hdr, sizeof(hdr), c->spin);
    }
  }
  if (!s.ok()) m.state->SetError(s.msg);
  if (!dispatched) {
    // WEIGHTS ctrl write failed before any chunk was assigned: the ctrl
    // unit below is the message's only completion unit, or test() would
    // wait forever for chunks that never dispatched.
    m.state->total.store(1, std::memory_order_release);
  }
  uint64_t total_units = dispatched ? nchunks + 1 : 1;
  uint64_t prior = m.state->completed.fetch_add(1, std::memory_order_acq_rel);
  if (prior + 1 >= total_units) {
    c->inflight.fetch_sub(1, std::memory_order_release);
  }
  m.state->NotifyIfSettled();
  if (!s.ok()) {
    PoisonAndDrainQueue(c, s.msg);
    return false;
  }
  return true;
}

void SendSchedulerLoop(Comm* c) {
  Msg m;
  while (c->msgs.Pop(&m)) {
    if (!SendOneMsg(c, m)) return;
  }
}

// ---- Receiver ctrl-frame vocabulary ---------------------------------------

// One ctrl frame, honoring a pump-stashed frame first. ctrl_mu held.
Status ReadCtrlFrameLocked(Comm* c, uint64_t* frame) REQUIRES(c->ctrl_mu) {
  if (c->has_pending_frame) {
    *frame = c->pending_frame;
    c->has_pending_frame = false;
    return Status::Ok();
  }
  uint8_t b[8];
  Status s = ReadExact(c->ctrl_fd, b, sizeof(b), c->spin);
  if (!s.ok()) return s;
  *frame = DecodeU64BE(b);
  return Status::Ok();
}

// FAILOVER marker: the sender retired stream k as of this point in ctrl
// order and retransmits every chunk the receiver's NACK declared missing —
// inline on the ctrl stream as [seq u64 | len u64 | payload | crc?] units.
// ctrl_mu held; takes fo_mu for the record/rotation update.
Status ProcessFailoverMarkerLocked(Comm* c, uint64_t frame) REQUIRES(c->ctrl_mu) {
  size_t k = (frame >> 48) & 0xff;
  uint64_t count = frame & 0xffffffffffffull;
  uint8_t b[16];
  Status s = ReadExact(c->ctrl_fd, b, 8, c->spin);
  if (!s.ok()) return s;
  uint64_t start_seq = DecodeU64BE(b);
  MutexLock lk(c->fo_mu);
  if (k >= c->nstreams || !c->stream_dead[k] || c->stream_retired[k]) {
    return Status::Inner("failover marker for stream " + std::to_string(k) +
                         " in an impossible state (protocol desync)");
  }
  if (start_seq != c->done_seq[k] || count != c->recs[k].size()) {
    return Status::Inner(
        "failover desync on stream " + std::to_string(k) + ": sender retransmits [" +
        std::to_string(start_seq) + ", +" + std::to_string(count) + "), receiver needs [" +
        std::to_string(c->done_seq[k]) + ", +" + std::to_string(c->recs[k].size()) + ")");
  }
  TPUNET_DBG("failover marker: stream %zu, %llu chunks over ctrl", k,
             (unsigned long long)count);
  for (ChunkRec& r : c->recs[k]) {
    s = ReadExact(c->ctrl_fd, b, sizeof(b), c->spin);
    if (!s.ok()) return s;
    uint64_t seq = DecodeU64BE(b);
    uint64_t len = DecodeU64BE(b + 8);
    if (seq != r.seq || len != r.len) {
      return Status::Inner("failover retransmit unit mismatch on stream " + std::to_string(k));
    }
    uint32_t wire_crc = 0;
    s = RecvChunkWire(c->ctrl_fd, r.data, r.len, c->crc, c->spin, &wire_crc);
    if (!s.ok()) return s;
    if (c->crc) {
      if (wire_crc != Crc32c(r.data, r.len)) {
        Telemetry::Get().OnCrcError();
        r.state->SetError(ErrorKind::kCorruption,
                          "CRC32C mismatch on failover retransmit (stream " +
                              std::to_string(k) + ")");
      }
    }
    if (!r.state->failed.load(std::memory_order_acquire)) {
      Telemetry::Get().OnStreamBytes(false, k, r.len, static_cast<int>(c->cls));
      if (c->lanes) Telemetry::Get().OnLaneBytes(false, k, r.len);
    }
    AccountChunkDone(c, r.state, r.len);
  }
  c->recs[k].clear();
  c->stream_retired[k] = 1;  // rotation excludes k from here on — both sides
  return Status::Ok();
}

// WEIGHTS epoch frame: the sender re-striped as of this point in ctrl
// order. Read the per-stream weight bytes, rebuild the slot table, and
// advance the epoch — subsequent LEN frames' messages are laid out on the
// new vector on both sides. ctrl_mu held; takes fo_mu for the table swap.
Status ProcessWeightsFrameLocked(Comm* c, uint64_t frame) REQUIRES(c->ctrl_mu) {
  uint64_t count = WeightsFrameCount(frame);
  uint64_t epoch = WeightsFrameEpoch(frame);
  if (!c->lanes || count != c->nstreams || count == 0) {
    return Status::Inner("WEIGHTS frame for " + std::to_string(count) +
                         " streams on a " + std::to_string(c->nstreams) +
                         "-stream " + (c->lanes ? "lane" : "non-lane") +
                         " comm (protocol desync)");
  }
  uint8_t wbytes[256];
  Status s = ReadExact(c->ctrl_fd, wbytes, count, c->spin);
  if (!s.ok()) return s;
  MutexLock lk(c->fo_mu);
  if (epoch <= c->stripe_epoch) {
    return Status::Inner("WEIGHTS epoch " + std::to_string(epoch) +
                         " is not past the current epoch " +
                         std::to_string(c->stripe_epoch) + " (protocol desync)");
  }
  for (uint64_t i = 0; i < count; ++i) {
    if (wbytes[i] == 0) {
      return Status::Inner("WEIGHTS frame carries a zero weight (protocol desync)");
    }
    c->weights[i] = wbytes[i];
    Telemetry::Get().OnLaneWeight(i, wbytes[i]);
  }
  bool initial = c->stripe_epoch == 0;
  c->stripe_epoch = epoch;
  c->slots = BuildWrrSlots(c->weights);
  // The epoch-1 frame is the sender's configured baseline, not a re-stripe.
  if (!initial) Telemetry::Get().OnRestripe();
  TPUNET_DBG("lane weights applied epoch=%llu", (unsigned long long)epoch);
  return Status::Ok();
}

// Per-message receiver ctrl-frame work; chunk handling differs between the
// scheduler path (dispatch to workers) and the lazy path (caller reads).
// Control frames (failover markers) encountered before the message's length
// frame are processed inline. The caller holds ctrl_mu (REQUIRES, checked
// by TSA) and MUST dispatch the message's chunk assignment before releasing
// it: a FAILOVER marker processed (by the pump) between this frame and the
// dispatch would retire a stream the sender still counted into THIS
// message's rotation, desynchronizing the chunk maps.
Status RecvCtrlFrame(Comm* c, const Msg& m, uint64_t* target) REQUIRES(c->ctrl_mu) {
  while (true) {
    uint64_t frame = 0;
    Status s = ReadCtrlFrameLocked(c, &frame);
    if (!s.ok()) return s;
    CtrlFrameView cf = DecodeCtrlFrame(frame);
    if (cf.kind == CtrlFrameKind::kFailover) {
      s = ProcessFailoverMarkerLocked(c, frame);
      if (!s.ok()) return s;
      continue;
    }
    if (cf.kind == CtrlFrameKind::kWeights) {
      s = ProcessWeightsFrameLocked(c, frame);
      if (!s.ok()) return s;
      continue;
    }
    if (cf.kind != CtrlFrameKind::kLen) {
      return Status::Inner("bogus ctrl frame 0x" + std::to_string(frame >> 56) +
                           "… — peer desynchronized");
    }
    *target = cf.len;
    if (*target > m.len) {
      // Peer sent more than the posted buffer — unrecoverable protocol
      // violation (the reference would panic slicing data[..target]).
      return Status::Inner("incoming message (" + std::to_string(*target) +
                           "B) exceeds posted recv buffer (" +
                           std::to_string(m.len) + "B)");
    }
    return Status::Ok();
  }
}

// Ctrl pump run by a failed receiver worker: until its stream's FAILOVER
// marker is processed (by this pump, the scheduler, or a lazy-recv caller —
// whoever owns ctrl_mu when the marker lands), keep the ctrl stream moving.
// A LEN frame read here is stashed for the real owner when its message is
// not yet posted — the pump never pairs frames with messages itself, which
// keeps frame↔message pairing strictly in pop order.
void PumpCtrlUntilRetired(Comm* c, size_t idx) {
  while (true) {
    {
      MutexLock lk(c->fo_mu);
      if (c->stream_retired[idx] || c->Aborted()) return;
    }
    if (!c->ctrl_mu.TryLock()) {
      // Someone else (scheduler / lazy caller) is reading ctrl; they will
      // process the marker. Check back shortly.
      std::this_thread::sleep_for(std::chrono::milliseconds(1));
      continue;
    }
    MutexLock lk(c->ctrl_mu, std::adopt_lock);
    if (c->has_pending_frame) {
      // A stashed LEN is waiting for its message; reading further frames
      // would reorder the stream. Yield until the scheduler consumes it.
      std::this_thread::sleep_for(std::chrono::milliseconds(1));
      continue;
    }
    struct pollfd pfd = {c->ctrl_fd, POLLIN, 0};
    int pr = ::poll(&pfd, 1, 20);
    if (pr < 0 && errno != EINTR) {
      PoisonAndDrainQueue(c, "ctrl poll failed during failover");
      return;
    }
    if (pr <= 0) continue;
    uint64_t frame = 0;
    Status s = ReadCtrlFrameLocked(c, &frame);
    if (!s.ok()) {
      PoisonAndDrainQueue(c, "ctrl stream lost during failover: " + s.msg);
      return;
    }
    CtrlFrameView cf = DecodeCtrlFrame(frame);
    if (cf.kind == CtrlFrameKind::kFailover) {
      s = ProcessFailoverMarkerLocked(c, frame);
      if (!s.ok()) {
        PoisonAndDrainQueue(c, s.msg);
        return;
      }
      continue;
    }
    if (cf.kind == CtrlFrameKind::kWeights) {
      s = ProcessWeightsFrameLocked(c, frame);
      if (!s.ok()) {
        PoisonAndDrainQueue(c, s.msg);
        return;
      }
      continue;
    }
    c->pending_frame = frame;  // LEN for a message the scheduler will pop
    c->has_pending_frame = true;
  }
}

// ---- Sender NACK reader ---------------------------------------------------

// Respond to a receiver NACK: mark the stream dead, emit the FAILOVER
// marker, and retransmit every record from the receiver's first missing seq
// over the ctrl stream. Returns false when the comm poisoned.
bool HandleNack(Comm* c, size_t k, uint64_t completed) {
  std::string poison;  // set on any verdict that must poison; applied after
                       // fo_mu is released (PoisonAndDrainQueue takes it)
  {
    MutexLock lk(c->fo_mu);
    if (c->Aborted()) return false;
    if (k >= c->nstreams || c->stream_retired[k]) {
      poison = "NACK for stream " + std::to_string(k) + " in impossible state";
    } else if (!c->stream_dead[k] && c->dead_count + 1 >= c->nstreams) {
      poison = "last data stream lost (NACK on stream " + std::to_string(k) + ")";
    }
    if (poison.empty()) {
      if (!c->stream_dead[k]) {
        c->stream_dead[k] = 1;
        c->dead_count += 1;
        Telemetry::Get().OnStreamFailover();
        // Unblock a worker mid-write on the dead conn; it sees stream_dead
        // and retires quietly.
        ::shutdown(c->workers[k]->fd, SHUT_RDWR);
        ChunkTask d;
        while (c->workers[k]->tasks.TryPop(&d)) {
        }
      }
      auto& q = c->recs[k];
      while (poison.empty() && !q.empty() && q.front().seq < completed) {
        if (!q.front().written) {
          poison = "failover desync: receiver claims a chunk never written";
          break;
        }
        q.pop_front();
      }
      if (poison.empty() && ((q.empty() && c->next_seq[k] != completed) ||
                             (!q.empty() && q.front().seq != completed))) {
        // The receiver still needs chunks whose records were pruned after
        // their message settled — the app may have freed those buffers, so
        // they are gone. Typed poison instead of a silent wrong answer.
        poison = "failover impossible on stream " + std::to_string(k) +
                 ": undelivered chunks were already released to the app "
                 "(kernel-buffered bytes lost with the connection)";
      }
      if (poison.empty()) {
        TPUNET_DBG("NACK stream %zu: retransmitting %zu chunks over ctrl", k, q.size());
        uint8_t b[16];
        EncodeU64BE(PackCtrlFrame(kCtrlFrameFailover, k, q.size()), b);
        EncodeU64BE(completed, b + 8);
        Status s = WriteAll(c->ctrl_fd, b, sizeof(b), c->spin);
        for (ChunkRec& r : q) {
          if (!s.ok()) break;
          EncodeU64BE(r.seq, b);
          EncodeU64BE(r.len, b + 8);
          // One writev per retransmit unit: [seq|len header, payload, crc?].
          uint8_t crcb[4];
          struct iovec iov[3] = {{b, sizeof(b)}, {r.data, r.len}, {crcb, 0}};
          int niov = 2;
          if (c->crc) {
            EncodeU32BE(Crc32c(r.data, r.len), crcb);
            iov[2].iov_len = sizeof(crcb);
            niov = 3;
          }
          s = WritevAll(c->ctrl_fd, iov, niov, c->spin);
          if (s.ok() && !r.written) {
            // First time these bytes reach the kernel: complete their
            // accounting (written records were counted by their worker).
            Telemetry::Get().OnStreamBytes(true, k, r.len,
                                           static_cast<int>(c->cls));
            if (c->lanes) Telemetry::Get().OnLaneBytes(true, k, r.len);
            AccountChunkDone(c, r.state, r.len);
            r.written = true;
          }
        }
        if (!s.ok()) {
          poison = "ctrl write failed during failover retransmit: " + s.msg;
        } else {
          q.clear();
          c->stream_retired[k] = 1;
        }
      }
    }
  }
  if (!poison.empty()) {
    PoisonAndDrainQueue(c, poison);
    return false;
  }
  return true;
}

// Parked on the receiver→sender direction of the ctrl connection (silent in
// normal operation). Poll-based so spin mode's nonblocking ctrl fd does not
// busy-burn a core here.
void NackReaderLoop(Comm* c) {
  uint8_t buf[8];
  size_t got = 0;
  while (true) {
    struct pollfd pfd = {c->ctrl_fd, POLLIN, 0};
    int pr = ::poll(&pfd, 1, 100);
    if (pr < 0 && errno != EINTR) return;
    if (c->Aborted()) return;
    if (pr <= 0) continue;
    ssize_t n = ::recv(c->ctrl_fd, buf + got, sizeof(buf) - got, MSG_DONTWAIT);
    if (n == 0) return;  // peer closed ctrl; scheduler/poison paths own it
    if (n < 0) {
      if (errno == EINTR || errno == EAGAIN || errno == EWOULDBLOCK) continue;
      return;
    }
    got += static_cast<size_t>(n);
    if (got < sizeof(buf)) continue;
    got = 0;
    uint64_t frame = DecodeU64BE(buf);
    if ((frame >> 56) != kCtrlFrameNack) {
      PoisonAndDrainQueue(c, "unexpected reverse ctrl frame from receiver");
      return;
    }
    if (!HandleNack(c, (frame >> 48) & 0xff, frame & 0xffffffffffffull)) return;
  }
}

void RecvSchedulerLoop(Comm* c) {
  Msg m;
  while (c->msgs.Pop(&m)) {
    uint64_t target = 0;
    c->ctrl_mu.Lock();
    Status s = RecvCtrlFrame(c, m, &target);
    if (!s.ok()) {
      c->ctrl_mu.Unlock();
      FailAndDrain(c, m.state, s.msg);
      return;
    }
    // NCCL semantics: recv buffer may exceed the message; true size comes
    // from the ctrl frame (reference nthread:507). Dispatched under the
    // SAME ctrl_mu hold as the frame read — see RecvCtrlFrame on why.
    DispatchChunks(c, m.data, static_cast<size_t>(target), m.state);
    c->ctrl_mu.Unlock();
  }
}

// Lazy-recv execution on the caller thread (from wait()): ctrl read + data
// read inline, no scheduler/worker hop and no completion wakeup. Only
// single-chunk-eligible messages park lazily (see irecv), so one ReadExact
// covers the payload. The owning worker thread is parked in Pop and never
// touches its fd without a task, so reading it here is exclusive.
void ExecuteLazyRecv(Comm* c, const Msg& m) {
  uint64_t target = 0;
  c->ctrl_mu.Lock();
  Status s = RecvCtrlFrame(c, m, &target);
  if (!s.ok()) {
    c->ctrl_mu.Unlock();
    FailMsg(c, m.state, s.msg);
    c->AbortStreams();
    return;
  }
  size_t len = static_cast<size_t>(target);
  size_t csize = ChunkSize(len, c->min_chunksize, c->nstreams);
  size_t nchunks = ChunkCount(len, csize);
  if (nchunks == 0) {
    c->ctrl_mu.Unlock();
    m.state->total.store(0, std::memory_order_release);
    c->inflight.fetch_sub(1, std::memory_order_release);
    m.state->NotifyIfSettled();
    return;
  }
  // nchunks == 1 by lazy eligibility. Assigned through the shared rotation
  // (failover bookkeeping stays symmetric with the sender) under the SAME
  // ctrl_mu hold as the frame read — see RecvCtrlFrame. The lock is
  // released before the blocking payload read: holding it there would
  // starve the ctrl pump this very chunk may depend on after a failover.
  m.state->total.store(nchunks, std::memory_order_release);
  size_t idx;
  uint64_t seq;
  bool dead;
  {
    MutexLock lk(c->fo_mu);
    idx = AssignStreamIdx(c);
    seq = c->next_seq[idx]++;
    c->recs[idx].push_back(ChunkRec{seq, m.data, len, m.state, false});
    dead = c->stream_dead[idx] != 0;
  }
  c->ctrl_mu.Unlock();
  if (!dead) {
    StreamWorker* w = c->workers[idx].get();
    m.state->MarkWireStart(MonotonicUs());
    uint32_t wire_crc = 0;
    Status rs = RecvChunkWire(w->fd, m.data, len, c->crc, c->spin, &wire_crc);
    if (rs.ok()) {
      if (c->crc && wire_crc != Crc32c(m.data, len)) {
        Telemetry::Get().OnCrcError();
        m.state->SetError(ErrorKind::kCorruption,
                          "CRC32C mismatch on data stream " + std::to_string(idx) +
                              ": payload corrupted in transit");
      } else {
        Telemetry::Get().OnStreamBytes(false, idx, len,
                                       static_cast<int>(c->cls));
        if (c->lanes) Telemetry::Get().OnLaneBytes(false, idx, len);
        Telemetry::Get().MaybeSampleStream(false, idx, w->fd);
      }
      PopRec(c, idx, seq);
      AccountChunkDone(c, m.state, len);
      return;
    }
    if (!ReceiverStreamFailed(c, c->workers[idx].get())) {
      m.state->SetError(rs.msg);
      AccountChunkDone(c, m.state, 0);
      PoisonAndDrainQueue(c, rs.msg);
      return;
    }
    // Fall through: the chunk arrives via the ctrl-stream retransmit.
  }
  // The assigned stream is dead: pump ctrl until the FAILOVER marker
  // delivers (and accounts) this chunk, or the comm poisons.
  PumpCtrlUntilRetired(c, idx);
}

// ---------------------------------------------------------------------------

class BasicEngine : public EngineBase, public BundleAdopter {
 public:
  BasicEngine()
      : spin_(GetEnvU64("TPUNET_SPIN", 0) != 0),
        inline_send_(GetEnvU64("TPUNET_INLINE_SEND", 1) != 0),
        lazy_recv_(GetEnvU64("TPUNET_LAZY_RECV", 1) != 0) {}

  ~BasicEngine() override {
    for (auto& c : send_comms_.DrainAll()) c->Shutdown();
    for (auto& c : recv_comms_.DrainAll()) c->Shutdown();
    // Wake any thread still parked in accept() — mirror of close_listen;
    // without this, destroying the engine would strand it forever.
    WakeAllListens();
  }

  Status connect(int32_t dev, const SocketHandle& handle, uint64_t* send_comm) override {
    Status sdev = CheckDev(dev);
    if (!sdev.ok()) return sdev;
    std::vector<int> data_fds;
    int ctrl_fd = -1;
    Status s = ConnectBundle(nics_, dev, handle, nstreams_, min_chunksize_, PreambleFlags(),
                             &data_fds, &ctrl_fd, lane_mode_ ? &lanes_ : nullptr);
    if (!s.ok()) return s;

    auto comm = std::make_shared<Comm>();
    comm->is_send = true;
    comm->nstreams = nstreams_;
    comm->min_chunksize = min_chunksize_;
    comm->spin = spin_;
    comm->crc = crc_;
    comm->cls = static_cast<TrafficClass>(traffic_class());
    comm->lanes = lane_mode_;
    comm->lane_adapt = lane_mode_ && lane_adapt_;
    comm->lane_adapt_us = lane_adapt_ms_ * 1000;
    comm->base_weights = LaneBaseWeights();
    comm->ctrl_fd = ctrl_fd;
    for (int fd : data_fds) {
      auto w = std::make_unique<StreamWorker>();
      w->fd = fd;
      w->idx = comm->workers.size();
      comm->workers.push_back(std::move(w));
    }
    if (spin_) {
      // Spin mode busy-polls nonblocking fds (set only after the blocking
      // preamble writes inside ConnectBundle). A failed fcntl must abort:
      // a silently-blocking fd would wedge the busy-poll path.
      Status ns = SetNonblocking(comm->ctrl_fd);
      for (auto& w : comm->workers) {
        if (ns.ok()) ns = SetNonblocking(w->fd);
      }
      if (!ns.ok()) {
        comm->Shutdown();
        return ns;
      }
    }
    s = StartThreads(comm.get());
    if (!s.ok()) {
      comm->Shutdown();
      return s;
    }
    uint64_t id = next_id_.fetch_add(1);
    send_comms_.Put(id, comm);
    *send_comm = id;
    return Status::Ok();
  }

  Status accept(uint64_t listen_comm, uint64_t* recv_comm) override {
    PartialBundle b;
    Status s = AcceptBundleOn(listen_comm, &b);
    if (!s.ok()) return s;
    return AdoptBundle(b, recv_comm);
  }

  // BundleAdopter seam (wire.h): the SHM engine fronts this engine on one
  // listen socket and hands non-SHM bundles back here.
  Status AdoptBundle(PartialBundle& b, uint64_t* recv_comm) override {
    if ((b.flags & kPreambleFlagShm) != 0) {
      // A zero-stream SHM hello reaching a plain TCP engine means the peer
      // runs TPUNET_SHM=1 and this process does not — wiring a zero-worker
      // comm would hang its first message, so fail loudly instead.
      b.CloseAll();
      return Status::Inner(
          "peer attempted shared-memory transport but TPUNET_SHM is not "
          "enabled here — set TPUNET_SHM identically on every rank");
    }
    return BuildRecvComm(b, recv_comm);
  }

  Status isend(uint64_t send_comm, const void* data, size_t nbytes, uint64_t* request) override {
    CommPtr c;
    if (!send_comms_.Get(send_comm, &c)) {
      return Status::Invalid("unknown send comm " + std::to_string(send_comm));
    }
    if (ForkGeneration() != c->fork_gen) {
      return Status::Inner("send comm created before fork(); its threads do not exist here");
    }
    // QoS admission control: a send over its class's in-flight byte budget
    // fails typed RIGHT HERE — nothing enqueued, nothing charged — so the
    // caller (serve router, trainer) gets retryable backpressure instead of
    // unbounded queue growth (docs/DESIGN.md "Transport QoS").
    uint64_t admitted = 0;
    Status as = QosScheduler::Get().AdmitMessage(c->cls, nbytes, &admitted);
    if (!as.ok()) return as;
    auto state = std::make_shared<RequestState>();
    state->qos_cls = static_cast<uint8_t>(c->cls);
    state->qos_admitted = admitted;
    state->t_post_us = MonotonicUs();
    ArmWatchdog(state, c);
    uint64_t id = next_id_.fetch_add(1);
    requests_.Put(id, state);
    Msg m{const_cast<uint8_t*>(static_cast<const uint8_t*>(data)), nbytes, state};
    // Inline fast path: on an idle comm the caller does the scheduler's
    // per-message work itself (8B ctrl write + chunk pushes, all
    // nonblocking-scale), skipping one thread hop per message. Data writes
    // stay on the workers — a blocking inline write could deadlock a
    // symmetric exchange once kernel socket buffers fill.
    if (c->inflight.fetch_add(1, std::memory_order_acq_rel) == 0 && inline_send_) {
      TPUNET_DBG("isend req=%llu len=%zu INLINE", (unsigned long long)id, nbytes);
      SendOneMsg(c.get(), m);
    } else {
      TPUNET_DBG("isend req=%llu len=%zu queued", (unsigned long long)id, nbytes);
      if (!c->msgs.Push(m)) FailMsg(c.get(), state, "send comm is poisoned");
    }
    *request = id;
    return Status::Ok();
  }

  Status irecv(uint64_t recv_comm, void* data, size_t nbytes, uint64_t* request) override {
    CommPtr c;
    if (!recv_comms_.Get(recv_comm, &c)) {
      return Status::Invalid("unknown recv comm " + std::to_string(recv_comm));
    }
    if (ForkGeneration() != c->fork_gen) {
      return Status::Inner("recv comm created before fork(); its threads do not exist here");
    }
    auto state = std::make_shared<RequestState>();
    state->t_post_us = MonotonicUs();
    ArmWatchdog(state, c);
    uint64_t id = next_id_.fetch_add(1);
    requests_.Put(id, state);
    Msg m{static_cast<uint8_t*>(data), nbytes, state};
    // A lazy recv already parked must hit the scheduler before this newer
    // message, or the ctrl frames would be consumed out of post order.
    UpgradeLazy(c.get());
    uint64_t prior = c->inflight.fetch_add(1, std::memory_order_acq_rel);
    size_t csize = ChunkSize(nbytes, c->min_chunksize, c->nstreams);
    bool single = ChunkCount(nbytes, csize) <= 1;
    TPUNET_DBG("irecv req=%llu len=%zu prior=%llu single=%d", (unsigned long long)id, nbytes, (unsigned long long)prior, (int)single);
    // Watchdog mode disables lazy parking: the lazy wait() path runs
    // BLOCKING ctrl/data reads on the caller thread, which the watchdog
    // (which lives in the condvar wait, WaitIn) could never interrupt —
    // bounded-wait guarantees beat the inline-hop optimization.
    if (prior == 0 && single && lazy_recv_ && watchdog_ms_ == 0) {
      // Park lazily: wait() executes the ctrl+data reads on the caller
      // thread (no scheduler/worker hop, no completion wakeup). test()
      // or a later irecv upgrades it onto the scheduler queue.
      // Single-chunk eligibility from the posted size is conservative:
      // the actual (<=posted) size can only have fewer chunks.
      MutexLock lk(c->lazy_mu);
      c->lazy_msg = m;
      c->has_lazy = true;
      c->lazy_req = id;
      g_lazy_parked.fetch_add(1, std::memory_order_relaxed);
      lazy_recv_owners_.Put(id, c);
    } else {
      if (!c->msgs.Push(m)) FailMsg(c.get(), state, "recv comm is poisoned");
    }
    *request = id;
    return Status::Ok();
  }

  Status test(uint64_t request, bool* done, size_t* nbytes) override {
    // Pollers (the NCCL shim) never call wait(), so a lazy recv would
    // starve: upgrade it onto the scheduler on the first poll. Match on the
    // request id — a stale owner entry (this request was already upgraded
    // elsewhere) must not kick a NEWER lazy parked on the same comm.
    CommPtr lc;
    if (lazy_recv_owners_.Take(request, &lc)) UpgradeLazyIf(lc.get(), request);
    RequestPtr state;
    if (!requests_.Get(request, &state)) {
      return Status::Invalid("unknown request " + std::to_string(request));
    }
    if (state->failed.load(std::memory_order_acquire)) {
      // Surface the error only once all dispatched chunk workers have
      // quiesced on this request — otherwise the caller could free/reuse the
      // buffer while a stream worker is still reading into it.
      if (!state->Done()) {
        *done = false;
        return Status::Ok();
      }
      state->ReleaseQosAdmission();  // consumption point: return budget bytes
      requests_.Erase(request);
      return Status{state->ErrKind(), "request failed: " + state->ErrorMsg()};
    }
    *done = state->Done();
    if (*done) {
      if (nbytes) *nbytes = state->nbytes.load(std::memory_order_acquire);
      RecordRequestStages(state);
      state->ReleaseQosAdmission();  // consumption point: return budget bytes
      requests_.Erase(request);  // reference leaked these (bagua_net.cc:111-121)
    }
    return Status::Ok();
  }

  Status wait(uint64_t request, size_t* nbytes) override {
    TPUNET_DBG("wait req=%llu enter", (unsigned long long)request);
    CommPtr c;
    if (lazy_recv_owners_.Take(request, &c)) {
      Msg m;
      bool mine = false;
      {
        MutexLock lk(c->lazy_mu);
        if (c->has_lazy && c->lazy_req == request) {
          m = c->lazy_msg;
          c->lazy_msg = Msg{};
          c->has_lazy = false;
          g_lazy_parked.fetch_sub(1, std::memory_order_relaxed);
          mine = true;
        }
      }
      if (mine) {
        // About to block in this comm's ctrl read: upgrade every OTHER
        // parked lazy first, or a multi-comm wait order could deadlock
        // against a lazy recv only this thread would have executed later.
        if (g_lazy_parked.load(std::memory_order_relaxed) != 0) {
          for (auto& lc : lazy_recv_owners_.DrainAll()) UpgradeLazy(lc.get());
        }
        ExecuteLazyRecv(c.get(), m);
      }
      Status st = WaitIn(requests_, request, nbytes);
      TPUNET_DBG("wait req=%llu lazy-exit ok=%d", (unsigned long long)request, (int)st.ok());
      return st;
    }
    // Non-lazy request: while it does not settle, keep upgrading every
    // parked lazy recv in this process. Without this, two ranks could both
    // park in a send-wait whose completion needs the peer's lazy recv to
    // run — a deadlock no caller ordering should be able to create. The
    // repeat (vs one-shot) covers a lazy parked by another thread after an
    // earlier pass; each pass is a no-op on an empty map.
    RequestPtr state;
    if (!requests_.Get(request, &state)) {
      return Status::Invalid("unknown request " + std::to_string(request));
    }
    int spins = 0;
    while (g_lazy_parked.load(std::memory_order_relaxed) != 0 &&
           !state->WaitSettledFor(50)) {
      // A lazy parked AFTER we fall through is its poster's own problem:
      // that thread's next wait/test upgrades it (every thread that parks
      // a lazy eventually waits something).
      for (auto& lc : lazy_recv_owners_.DrainAll()) UpgradeLazy(lc.get());
      if (++spins % 40 == 0) TPUNET_DBG("wait req=%llu still unsettled after %d spins (total=%llu completed=%llu failed=%d)", (unsigned long long)request, spins, (unsigned long long)state->total.load(), (unsigned long long)state->completed.load(), (int)state->failed.load());
    }
    Status st = WaitIn(requests_, request, nbytes);
    TPUNET_DBG("wait req=%llu exit ok=%d", (unsigned long long)request, (int)st.ok());
    return st;
  }

  Status close_send(uint64_t send_comm) override {
    CommPtr c;
    if (!send_comms_.Take(send_comm, &c)) {
      return Status::Invalid("unknown send comm " + std::to_string(send_comm));
    }
    c->Shutdown();
    return Status::Ok();
  }

  Status close_recv(uint64_t recv_comm) override {
    CommPtr c;
    if (!recv_comms_.Take(recv_comm, &c)) {
      return Status::Invalid("unknown recv comm " + std::to_string(recv_comm));
    }
    c->Shutdown();
    return Status::Ok();
  }

 private:
  // Progress-watchdog abort hook (only when TPUNET_PROGRESS_TIMEOUT_MS is
  // set): WaitIn's timeout verdict shuts the comm's sockets down so blocked
  // workers quiesce and the request surfaces its typed error. Weak capture —
  // the comm may be closed before the request is waited.
  void ArmWatchdog(const RequestPtr& state, const CommPtr& c) {
    if (watchdog_ms_ == 0) return;
    std::weak_ptr<Comm> wc = c;
    state->on_stall = [wc] {
      if (auto p = wc.lock()) p->AbortStreams();
    };
  }

  // Move a parked lazy recv onto the scheduler queue. The Push happens
  // UNDER lazy_mu: with it outside, a cross-thread upgrade could be
  // preempted between claim and push while the comm's caller posts (and
  // queues) a newer irecv, enqueueing the older recv after the newer one
  // and pairing ctrl frames with the wrong requests.
  static void UpgradeLazy(Comm* c) { UpgradeLazyIf(c, 0); }

  // expect_req != 0 restricts the upgrade to that specific parked request
  // (test()'s stale-entry guard); 0 upgrades whatever is parked.
  static void UpgradeLazyIf(Comm* c, uint64_t expect_req) {
    MutexLock lk(c->lazy_mu);
    if (!c->has_lazy) return;
    if (expect_req != 0 && c->lazy_req != expect_req) return;
    Msg m = c->lazy_msg;
    c->lazy_msg = Msg{};
    c->has_lazy = false;
    g_lazy_parked.fetch_sub(1, std::memory_order_relaxed);
    if (!c->msgs.Push(m)) FailMsg(c, m.state, "recv comm is poisoned");
  }

  Status StartThreads(Comm* c) {
    {
      // Failover bookkeeping is per-stream; size it before any IO thread
      // runs. No concurrency yet — the lock exists for the TSA contract.
      MutexLock lk(c->fo_mu);
      c->stream_dead.assign(c->nstreams, 0);
      c->stream_retired.assign(c->nstreams, 0);
      c->recs.resize(c->nstreams);
      c->next_seq.assign(c->nstreams, 0);
      c->done_seq.assign(c->nstreams, 0);
      if (c->lanes) {
        // Lane mode: both sides start on equal weights (the receiver knows
        // nothing else yet); the sender publishes its configured base
        // vector as epoch 1 before any message, so the first LEN frame
        // already finds both sides on the same (possibly non-uniform) map.
        c->weights.assign(c->nstreams, 1);
        c->slots = BuildWrrSlots(c->weights);
        c->lane_io.reset(new Comm::LaneIo[c->nstreams]);
        if (c->is_send) {
          c->weights = c->base_weights;
          c->weights.resize(c->nstreams, 1);
          c->stripe_epoch = 1;
          c->slots = BuildWrrSlots(c->weights);
          Status ps = PublishWeightsLocked(c);
          if (!ps.ok()) return ps;
        }
      }
    }
    bool spin = c->spin;
    for (auto& w : c->workers) {
      StreamWorker* wp = w.get();
      wp->comm = c;
      w->thread = c->is_send ? std::thread(SendWorkerLoop, wp, spin)
                             : std::thread(RecvWorkerLoop, wp, spin);
    }
    c->scheduler = std::make_unique<std::thread>(
        c->is_send ? SendSchedulerLoop : RecvSchedulerLoop, c);
    if (c->is_send) {
      // Reverse-ctrl NACK reader: the receiver speaks only when one of its
      // data streams dies (single-stream failover, docs/DESIGN.md).
      c->nack_reader = std::make_unique<std::thread>(NackReaderLoop, c);
    }
    return Status::Ok();
  }

  Status BuildRecvComm(PartialBundle& b, uint64_t* recv_comm) {
    auto comm = std::make_shared<Comm>();
    comm->is_send = false;
    // Sender's chunk-map inputs win — carried in the preamble so both sides
    // always partition messages identically (SURVEY hard-part #2). The CRC
    // flag travels the same way: the receiver verifies iff the sender
    // appends trailers, regardless of the local TPUNET_CRC setting.
    comm->nstreams = b.nstreams;
    comm->min_chunksize = b.min_chunksize;
    comm->crc = (b.flags & kPreambleFlagCrc) != 0;
    // Lane capability travels the same way (sender-wins): the receiver
    // mirrors the weighted slot-table rotation and accepts WEIGHTS frames.
    comm->lanes = (b.flags & kPreambleFlagLanes) != 0;
    // The traffic class travels the same way: the receiver accounts this
    // comm's bytes under the SENDER's class nibble.
    comm->cls = static_cast<TrafficClass>(PreambleClassOf(b.flags));
    comm->spin = spin_;
    comm->ctrl_fd = b.ctrl_fd;
    b.ctrl_fd = -1;
    Status ns = Status::Ok();
    if (spin_) ns = SetNonblocking(comm->ctrl_fd);  // ctrl carries the length frame
    // Data streams ordered by stream id (reference: BTreeMap nthread:432).
    for (auto& kv : b.data_fds) {
      auto w = std::make_unique<StreamWorker>();
      w->fd = kv.second;
      w->idx = comm->workers.size();
      if (spin_ && ns.ok()) ns = SetNonblocking(w->fd);
      comm->workers.push_back(std::move(w));
    }
    b.data_fds.clear();
    if (!ns.ok()) {
      comm->Shutdown();
      return ns;
    }
    ns = StartThreads(comm.get());
    if (!ns.ok()) {
      comm->Shutdown();
      return ns;
    }
    uint64_t id = next_id_.fetch_add(1);
    recv_comms_.Put(id, comm);
    *recv_comm = id;
    return Status::Ok();
  }

  bool spin_;
  bool inline_send_;
  bool lazy_recv_;
  IdMap<CommPtr> send_comms_;
  IdMap<CommPtr> recv_comms_;
  IdMap<RequestPtr> requests_;
  // request id -> comm whose lazy slot holds that request. Entries are
  // claimed (Take) by exactly one of wait/test/drain; stale entries after
  // an irecv-triggered upgrade are benign (claimer finds has_lazy false).
  IdMap<CommPtr> lazy_recv_owners_;
};

}  // namespace

std::unique_ptr<Net> CreateBasicEngine() { return std::make_unique<BasicEngine>(); }

std::unique_ptr<Net> CreateEngine() {
  // Engine seam (reference: src/lib.rs:20-29 BAGUA_NET_IMPLEMENT
  // BASIC|TOKIO); ours is TPUNET_IMPLEMENT BASIC|EPOLL. Every engine goes
  // out wrapped in the telemetry decorator so metrics/tracing cannot
  // diverge between engines.
  std::string impl = GetEnv("TPUNET_IMPLEMENT", GetEnv("BAGUA_NET_IMPLEMENT", "BASIC"));
  // Chaos hook: TPUNET_FAULT_SPEC arms a deterministic fault for this
  // process (fault.h); runtime arming goes through tpunet_c_fault_inject().
  ArmFaultFromEnv();
  auto engine = impl == "EPOLL" ? CreateEpollEngine() : CreateBasicEngine();
  // Intra-host shared memory (TPUNET_SHM=1, docs/DESIGN.md "Intra-host
  // shared memory"): front the TCP engine with the SHM engine — same-host
  // peers get mmap'd ring segments, everything else passes through. Must be
  // set identically on every rank (like the engine choice itself).
  if (GetEnvU64("TPUNET_SHM", 0) != 0) {
    engine = CreateShmEngine(std::move(engine));
  }
  return WrapWithTelemetry(std::move(engine));
}

}  // namespace tpunet
