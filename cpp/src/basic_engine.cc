// tpunet BASIC engine — thread-per-stream multi-stream TCP transport.
//
// TPU-native re-design of the reference's default engine
// (reference: src/implement/nthread_per_socket_backend.rs). Behavioral
// contract reproduced:
//   * per send/recv comm: 1 scheduler thread + nstreams data-stream threads,
//     each owning one TCP connection (reference :103-237, :336-361).
//   * every message is split into chunks of max(ceil(len/nstreams),
//     min_chunksize) and chunks are assigned round-robin starting at a
//     per-comm cursor that persists ACROSS messages (reference :393,412) —
//     the fairness mechanism: even 1-chunk messages rotate streams.
//   * sender and receiver compute identical chunk boundaries + assignment
//     from (len, min_chunksize, nstreams) alone, so the wire carries no
//     per-chunk header; TCP per-stream ordering makes this correct.
//   * per message the ctrl stream carries an 8-byte big-endian length frame
//     (reference :395-397/:494-502); the receiver may post a larger buffer
//     and learns the true size from this frame.
//   * completion = bytes handed to the kernel socket buffer, not peer-ACKed.
//   * request lifecycle: isend/irecv return an id, test() polls, done
//     consumes the id.
//
// Deliberate improvements over the reference (documented deltas):
//   * Wire preamble carries bundle id + nstreams + min_chunksize (wire.h) —
//     concurrent senders on one listen socket, no config divergence, magic
//     check. Shared with the EPOLL engine, so the two engines interoperate
//     (the reference's BASIC/TOKIO were wire-incompatible).
//   * Blocking sockets by default instead of the reference's nonblocking
//     busy-poll spin (reference utils.rs:132-178) — a TPU host shares cores
//     with the trainer; TPUNET_SPIN=1 restores spin mode for latency hunts.
//   * No global engine mutex (reference lib.rs:14-16): ids resolve through
//     sharded maps, test() touches only atomics.
//   * Request ids are freed on completion (reference leaked them:
//     cc/bagua_net.cc:111-121).
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <deque>
#include <memory>
#include <mutex>
#include <thread>
#include <vector>

#include "engine_base.h"
#include "id_map.h"
#include "tpunet/net.h"
#include "tpunet/telemetry.h"
#include "tpunet/utils.h"
#include "wire.h"

namespace tpunet {
namespace {

// Number of lazy recvs currently parked process-wide. Lets a send-side
// wait() park on its condvar outright (no 50ms upgrade sweeps) when there
// is nothing to upgrade. Global (not per-engine) so Comm::Shutdown can
// maintain it; cross-engine conservatism is harmless.
std::atomic<int> g_lazy_parked{0};

bool DebugOn() {
  static const bool on = GetEnvU64("TPUNET_DEBUG", 0) != 0;
  return on;
}
#define TPUNET_DBG(...) do { if (DebugOn()) { fprintf(stderr, "[eng %d] ", (int)getpid()); fprintf(stderr, __VA_ARGS__); fprintf(stderr, "\n"); } } while (0)

// MPSC blocking queue with close semantics (stands in for the reference's
// flume channels, nthread:224-226). Pop returns false only when closed AND
// drained, so close_send/close_recv still flush queued work.
template <typename T>
class Queue {
 public:
  // Returns false (and does not enqueue) once the queue is closed — the
  // caller owns failing the item. This is how a poisoned comm rejects new
  // messages without a parked fail-sink thread.
  bool Push(T t) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (closed_) return false;
      q_.push_back(std::move(t));
    }
    cv_.notify_one();
    return true;
  }
  bool Pop(T* out) {
    std::unique_lock<std::mutex> lk(mu_);
    cv_.wait(lk, [&] { return closed_ || !q_.empty(); });
    if (q_.empty()) return false;
    *out = std::move(q_.front());
    q_.pop_front();
    return true;
  }
  void Close() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      closed_ = true;
    }
    cv_.notify_all();
  }

 private:
  std::mutex mu_;
  std::condition_variable cv_;
  std::deque<T> q_;
  bool closed_ = false;
};

struct ChunkTask {
  uint8_t* data = nullptr;  // send: source bytes; recv: destination bytes
  size_t len = 0;
  RequestPtr state;
};

struct Msg {
  uint8_t* data = nullptr;
  size_t len = 0;
  RequestPtr state;
};

struct Comm;

// One data stream: a TCP connection owned by one worker thread.
struct StreamWorker {
  int fd = -1;
  size_t idx = 0;  // data-stream index (for per-stream fairness counters)
  Comm* comm = nullptr;
  Queue<ChunkTask> tasks;
  std::thread thread;
};

// A send or recv comm: ctrl connection + scheduler thread + stream workers.
struct Comm {
  bool is_send = false;
  int ctrl_fd = -1;
  size_t nstreams = 0;
  size_t min_chunksize = 0;
  bool spin = false;
  std::vector<std::unique_ptr<StreamWorker>> workers;
  Queue<Msg> msgs;
  std::unique_ptr<std::thread> scheduler;
  // Inline fast path state (PERF_NOTES: caller->scheduler->worker hops cost
  // ~0.4ms per 1MiB message on a 1-core host). `inflight` counts messages
  // not yet fully settled; when it reads 0 the scheduler is idle and every
  // prior byte is in the kernel, so the caller thread may take the
  // scheduler's role for its own message (ctrl frame + chunk dispatch)
  // without reordering the wire. `cursor` is the chunk->stream rotation,
  // shared by scheduler and inline path — never concurrently: the inline
  // path only runs at inflight==0, and the release/acquire pair on
  // `inflight` orders the scheduler's last cursor write before the caller's
  // read. Callers are single-threaded per comm (NCCL proxy contract; our
  // collectives layer likewise).
  std::atomic<uint64_t> inflight{0};
  uint64_t cursor = 0;
  // Lazy recv slot: an irecv posted on an idle comm parks here; its wait()
  // executes the ctrl read + data read inline on the caller thread (saving
  // two hops and the completion wakeup). test() or a later irecv upgrades
  // it onto the scheduler queue instead.
  std::mutex lazy_mu;
  Msg lazy_msg;
  bool has_lazy = false;
  uint64_t lazy_req = 0;
  // Threads do not survive fork(): a mismatch means this comm's scheduler /
  // workers never existed in this process (see Shutdown and the engine's
  // isend/irecv fail-fast).
  const uint64_t fork_gen = ForkGeneration();

  ~Comm() { Shutdown(); }

  // On any stream IO error, poison every connection in the comm so sibling
  // workers blocked mid-chunk fail fast and all requests quiesce — without
  // this, a single dead stream would leave test() hanging on the survivors.
  void AbortStreams() {
    if (aborted_.exchange(true)) return;
    for (auto& w : workers) {
      if (w->fd >= 0) ::shutdown(w->fd, SHUT_RDWR);
    }
    if (ctrl_fd >= 0) ::shutdown(ctrl_fd, SHUT_RDWR);
  }

  void Shutdown() {
    if (shut_) return;
    shut_ = true;
    // A lazy recv parked here would otherwise never execute; fail it so a
    // post-close wait() errors instead of hanging.
    {
      std::lock_guard<std::mutex> lk(lazy_mu);
      if (has_lazy) {
        lazy_msg.state->SetError("comm closed with pending lazy recv");
        lazy_msg.state->total.store(0, std::memory_order_release);
        inflight.fetch_sub(1, std::memory_order_release);
        lazy_msg.state->NotifyIfSettled();
        lazy_msg = Msg{};
        has_lazy = false;
        g_lazy_parked.fetch_sub(1, std::memory_order_relaxed);
      }
    }
    if (ForkGeneration() != fork_gen) {
      // Forked child: scheduler/worker pthreads never existed here and the
      // queue mutexes may have been captured mid-lock at fork. Leak the
      // thread handles (any pthread call on their stale ids is UB) and only
      // close this process's copies of the fds.
      (void)scheduler.release();
      for (auto& w : workers) {
        if (w->fd >= 0) ::close(w->fd);
        (void)w.release();
      }
      workers.clear();
      if (ctrl_fd >= 0) ::close(ctrl_fd);
      ctrl_fd = -1;
      return;
    }
    msgs.Close();
    // By the NCCL contract every request has been test()ed done before close,
    // so scheduler/workers are idle in Pop and the shutdown()s below are
    // no-ops data-wise. If the contract was violated (peer stalled/died with
    // bytes in flight), SHUT_RDWR wakes threads blocked in kernel send/recv —
    // a hang would otherwise be permanent since std::thread has no timed join.
    AbortStreams();
    if (scheduler && scheduler->joinable()) scheduler->join();
    for (auto& w : workers) w->tasks.Close();
    for (auto& w : workers) {
      if (w->thread.joinable()) w->thread.join();
    }
    for (auto& w : workers) {
      if (w->fd >= 0) ::close(w->fd);
      w->fd = -1;
    }
    if (ctrl_fd >= 0) ::close(ctrl_fd);
    ctrl_fd = -1;
  }

 private:
  std::atomic<bool> aborted_{false};
  bool shut_ = false;
};
using CommPtr = std::shared_ptr<Comm>;

// ---------------------------------------------------------------------------
// Worker / scheduler loops.

// Chunk completion shared by both worker loops: the worker that settles the
// message (last chunk) releases the comm's inflight slot, re-arming the
// inline fast path.
void FinishChunk(StreamWorker* w, ChunkTask& t) {
  t.state->nbytes.fetch_add(t.len, std::memory_order_relaxed);
  uint64_t prior = t.state->completed.fetch_add(1, std::memory_order_acq_rel);
  uint64_t tot = t.state->total.load(std::memory_order_acquire);
  TPUNET_DBG("chunk done len=%zu completed=%llu/%llu fail=%d", t.len, (unsigned long long)(prior+1), (unsigned long long)tot, (int)t.state->failed.load());
  if (prior + 1 >= tot) {
    w->comm->inflight.fetch_sub(1, std::memory_order_release);
  }
  t.state->NotifyIfSettled();
}

void SendWorkerLoop(StreamWorker* w, bool spin) {
  ChunkTask t;
  while (w->tasks.Pop(&t)) {
    Status s = WriteAll(w->fd, t.data, t.len, spin);
    if (!s.ok()) {
      t.state->SetError(s.msg);
      w->comm->AbortStreams();
    } else {
      Telemetry::Get().OnStreamBytes(true, w->idx, t.len);
    }
    FinishChunk(w, t);
  }
}

void RecvWorkerLoop(StreamWorker* w, bool spin) {
  ChunkTask t;
  while (w->tasks.Pop(&t)) {
    Status s = ReadExact(w->fd, t.data, t.len, spin);
    if (!s.ok()) {
      t.state->SetError(s.msg);
      w->comm->AbortStreams();
    } else {
      Telemetry::Get().OnStreamBytes(false, w->idx, t.len);
    }
    FinishChunk(w, t);
  }
}

// Receiver-side: chunk a message and fan chunks out to stream workers
// round-robin from the rotating cursor. The send side runs the same chunk
// math + rotation inline in SendOneMsg (with ctrl-frame accounting on top),
// keeping the two chunk maps symmetric (SURVEY hard-part #2).
void DispatchChunks(Comm* c, uint8_t* data, size_t len, const RequestPtr& state) {
  size_t csize = ChunkSize(len, c->min_chunksize, c->nstreams);
  size_t nchunks = ChunkCount(len, csize);
  state->total.store(nchunks, std::memory_order_release);  // 0-byte msg: done now
  if (nchunks == 0) {
    c->inflight.fetch_sub(1, std::memory_order_release);
    state->NotifyIfSettled();
    return;
  }
  state->NotifyIfSettled();
  size_t off = 0;
  for (size_t i = 0; i < nchunks; ++i) {
    size_t n = std::min(csize, len - off);
    StreamWorker* w = c->workers[c->cursor % c->nstreams].get();
    c->cursor += 1;  // persists across messages — fairness rotation
    w->tasks.Push(ChunkTask{data + off, n, state});
    off += n;
  }
}

// Fail a message that never dispatched any chunk (its inflight slot is
// still held) and release the slot.
void FailMsg(Comm* c, const RequestPtr& state, const std::string& msg) {
  TPUNET_DBG("FailMsg: %s", msg.c_str());
  state->SetError(msg);
  state->total.store(0, std::memory_order_release);
  c->inflight.fetch_sub(1, std::memory_order_release);
  state->NotifyIfSettled();
}

// Poison the comm and promptly fail everything queued (reference broke its
// loop on ctrl error leaving queued requests to hang, nthread:396-401).
// Close() first so Pop drains without blocking — this runs on the CALLER
// thread via the inline fast path, not only on a dedicated scheduler that
// could afford to park as a fail-sink. Post-close isend/irecv see the
// closed queue (Push returns false) and fail their requests directly.
void PoisonAndDrainQueue(Comm* c, const std::string& why) {
  c->AbortStreams();
  c->msgs.Close();
  Msg m;
  while (c->msgs.Pop(&m)) {
    FailMsg(c, m.state, "comm broken by earlier ctrl-stream error: " + why);
  }
}

void FailAndDrain(Comm* c, const RequestPtr& state, const std::string& msg) {
  FailMsg(c, state, msg);
  PoisonAndDrainQueue(c, msg);
}

// Per-message sender work: chunk dispatch + ctrl length frame. Runs on the
// scheduler thread normally, or on the caller thread via the inline fast
// path (never concurrently — see Comm::inflight).
//
// Order matters on a shared core: the ctrl frame is the receiver's wakeup
// trigger (its ctrl read unblocks), and ctrl/data ride SEPARATE sockets, so
// nothing requires the frame to precede the payload bytes. Dispatching the
// chunks first means the receiver wakes to data already flowing instead of
// waking early, read-blocking on an empty data stream, and ping-ponging
// context switches with the sender's worker.
//
// The ctrl write is itself a completion unit (total = nchunks + 1): with
// chunks dispatched first, chunk completion alone no longer implies the
// frame is on the wire, and the inline fast path keys off "message fully
// settled" (inflight==0) to take the scheduler's role — if inflight could
// hit 0 with a scheduler ctrl write still pending, an inline frame could
// overtake it and desynchronize the receiver's ctrl stream.
bool SendOneMsg(Comm* c, const Msg& m) {
  uint8_t hdr[8];
  EncodeU64BE(m.len, hdr);
  size_t csize = ChunkSize(m.len, c->min_chunksize, c->nstreams);
  size_t nchunks = ChunkCount(m.len, csize);
  m.state->total.store(nchunks + 1, std::memory_order_release);
  size_t off = 0;
  for (size_t i = 0; i < nchunks; ++i) {
    size_t n = std::min(csize, m.len - off);
    StreamWorker* w = c->workers[c->cursor % c->nstreams].get();
    c->cursor += 1;  // persists across messages — fairness rotation
    w->tasks.Push(ChunkTask{m.data + off, n, m.state});
    off += n;
  }
  Status s = WriteAll(c->ctrl_fd, hdr, sizeof(hdr), c->spin);
  if (!s.ok()) m.state->SetError(s.msg);
  uint64_t prior = m.state->completed.fetch_add(1, std::memory_order_acq_rel);
  if (prior + 1 >= nchunks + 1) {
    c->inflight.fetch_sub(1, std::memory_order_release);
  }
  m.state->NotifyIfSettled();
  if (!s.ok()) {
    PoisonAndDrainQueue(c, s.msg);
    return false;
  }
  return true;
}

void SendSchedulerLoop(Comm* c) {
  Msg m;
  while (c->msgs.Pop(&m)) {
    if (!SendOneMsg(c, m)) return;
  }
}

// Per-message receiver ctrl-frame work; chunk handling differs between the
// scheduler path (dispatch to workers) and the lazy path (caller reads).
Status RecvCtrlFrame(Comm* c, const Msg& m, uint64_t* target) {
  uint8_t hdr[8];
  Status s = ReadExact(c->ctrl_fd, hdr, sizeof(hdr), c->spin);
  if (!s.ok()) return s;
  *target = DecodeU64BE(hdr);
  if (*target > m.len) {
    // Peer sent more than the posted buffer — unrecoverable protocol
    // violation (the reference would panic slicing data[..target]).
    return Status::Inner("incoming message (" + std::to_string(*target) +
                         "B) exceeds posted recv buffer (" +
                         std::to_string(m.len) + "B)");
  }
  return Status::Ok();
}

void RecvSchedulerLoop(Comm* c) {
  Msg m;
  while (c->msgs.Pop(&m)) {
    uint64_t target = 0;
    Status s = RecvCtrlFrame(c, m, &target);
    if (!s.ok()) {
      FailAndDrain(c, m.state, s.msg);
      return;
    }
    // NCCL semantics: recv buffer may exceed the message; true size comes
    // from the ctrl frame (reference nthread:507).
    DispatchChunks(c, m.data, static_cast<size_t>(target), m.state);
  }
}

// Lazy-recv execution on the caller thread (from wait()): ctrl read + data
// read inline, no scheduler/worker hop and no completion wakeup. Only
// single-chunk-eligible messages park lazily (see irecv), so one ReadExact
// covers the payload. The owning worker thread is parked in Pop and never
// touches its fd without a task, so reading it here is exclusive.
void ExecuteLazyRecv(Comm* c, const Msg& m) {
  uint64_t target = 0;
  Status s = RecvCtrlFrame(c, m, &target);
  if (!s.ok()) {
    FailMsg(c, m.state, s.msg);
    c->AbortStreams();
    return;
  }
  size_t len = static_cast<size_t>(target);
  size_t csize = ChunkSize(len, c->min_chunksize, c->nstreams);
  size_t nchunks = ChunkCount(len, csize);
  if (nchunks > 0) {
    StreamWorker* w = c->workers[c->cursor % c->nstreams].get();
    c->cursor += 1;  // same rotation the sender computes
    Status rs = ReadExact(w->fd, m.data, len, c->spin);
    if (!rs.ok()) {
      FailMsg(c, m.state, rs.msg);
      c->AbortStreams();
      return;
    }
    Telemetry::Get().OnStreamBytes(false, w->idx, len);
    m.state->nbytes.store(len, std::memory_order_relaxed);
    m.state->completed.store(nchunks, std::memory_order_release);
  }
  m.state->total.store(nchunks, std::memory_order_release);
  c->inflight.fetch_sub(1, std::memory_order_release);
  m.state->NotifyIfSettled();
}

// ---------------------------------------------------------------------------

class BasicEngine : public EngineBase {
 public:
  BasicEngine()
      : spin_(GetEnvU64("TPUNET_SPIN", 0) != 0),
        inline_send_(GetEnvU64("TPUNET_INLINE_SEND", 1) != 0),
        lazy_recv_(GetEnvU64("TPUNET_LAZY_RECV", 1) != 0) {}

  ~BasicEngine() override {
    for (auto& c : send_comms_.DrainAll()) c->Shutdown();
    for (auto& c : recv_comms_.DrainAll()) c->Shutdown();
    // Wake any thread still parked in accept() — mirror of close_listen;
    // without this, destroying the engine would strand it forever.
    WakeAllListens();
  }

  Status connect(int32_t dev, const SocketHandle& handle, uint64_t* send_comm) override {
    Status sdev = CheckDev(dev);
    if (!sdev.ok()) return sdev;
    std::vector<int> data_fds;
    int ctrl_fd = -1;
    Status s = ConnectBundle(nics_, dev, handle, nstreams_, min_chunksize_, &data_fds, &ctrl_fd);
    if (!s.ok()) return s;

    auto comm = std::make_shared<Comm>();
    comm->is_send = true;
    comm->nstreams = nstreams_;
    comm->min_chunksize = min_chunksize_;
    comm->spin = spin_;
    comm->ctrl_fd = ctrl_fd;
    for (int fd : data_fds) {
      auto w = std::make_unique<StreamWorker>();
      w->fd = fd;
      w->idx = comm->workers.size();
      comm->workers.push_back(std::move(w));
    }
    if (spin_) {
      // Spin mode busy-polls nonblocking fds (set only after the blocking
      // preamble writes inside ConnectBundle). A failed fcntl must abort:
      // a silently-blocking fd would wedge the busy-poll path.
      Status ns = SetNonblocking(comm->ctrl_fd);
      for (auto& w : comm->workers) {
        if (ns.ok()) ns = SetNonblocking(w->fd);
      }
      if (!ns.ok()) {
        comm->Shutdown();
        return ns;
      }
    }
    StartThreads(comm.get());
    uint64_t id = next_id_.fetch_add(1);
    send_comms_.Put(id, comm);
    *send_comm = id;
    return Status::Ok();
  }

  Status accept(uint64_t listen_comm, uint64_t* recv_comm) override {
    PartialBundle b;
    Status s = AcceptBundleOn(listen_comm, &b);
    if (!s.ok()) return s;
    return BuildRecvComm(b, recv_comm);
  }

  Status isend(uint64_t send_comm, const void* data, size_t nbytes, uint64_t* request) override {
    CommPtr c;
    if (!send_comms_.Get(send_comm, &c)) {
      return Status::Invalid("unknown send comm " + std::to_string(send_comm));
    }
    if (ForkGeneration() != c->fork_gen) {
      return Status::Inner("send comm created before fork(); its threads do not exist here");
    }
    auto state = std::make_shared<RequestState>();
    uint64_t id = next_id_.fetch_add(1);
    requests_.Put(id, state);
    Msg m{const_cast<uint8_t*>(static_cast<const uint8_t*>(data)), nbytes, state};
    // Inline fast path: on an idle comm the caller does the scheduler's
    // per-message work itself (8B ctrl write + chunk pushes, all
    // nonblocking-scale), skipping one thread hop per message. Data writes
    // stay on the workers — a blocking inline write could deadlock a
    // symmetric exchange once kernel socket buffers fill.
    if (c->inflight.fetch_add(1, std::memory_order_acq_rel) == 0 && inline_send_) {
      TPUNET_DBG("isend req=%llu len=%zu INLINE", (unsigned long long)id, nbytes);
      SendOneMsg(c.get(), m);
    } else {
      TPUNET_DBG("isend req=%llu len=%zu queued", (unsigned long long)id, nbytes);
      if (!c->msgs.Push(m)) FailMsg(c.get(), state, "send comm is poisoned");
    }
    *request = id;
    return Status::Ok();
  }

  Status irecv(uint64_t recv_comm, void* data, size_t nbytes, uint64_t* request) override {
    CommPtr c;
    if (!recv_comms_.Get(recv_comm, &c)) {
      return Status::Invalid("unknown recv comm " + std::to_string(recv_comm));
    }
    if (ForkGeneration() != c->fork_gen) {
      return Status::Inner("recv comm created before fork(); its threads do not exist here");
    }
    auto state = std::make_shared<RequestState>();
    uint64_t id = next_id_.fetch_add(1);
    requests_.Put(id, state);
    Msg m{static_cast<uint8_t*>(data), nbytes, state};
    // A lazy recv already parked must hit the scheduler before this newer
    // message, or the ctrl frames would be consumed out of post order.
    UpgradeLazy(c.get());
    uint64_t prior = c->inflight.fetch_add(1, std::memory_order_acq_rel);
    size_t csize = ChunkSize(nbytes, c->min_chunksize, c->nstreams);
    bool single = ChunkCount(nbytes, csize) <= 1;
    TPUNET_DBG("irecv req=%llu len=%zu prior=%llu single=%d", (unsigned long long)id, nbytes, (unsigned long long)prior, (int)single);
    if (prior == 0 && single && lazy_recv_) {
      // Park lazily: wait() executes the ctrl+data reads on the caller
      // thread (no scheduler/worker hop, no completion wakeup). test()
      // or a later irecv upgrades it onto the scheduler queue.
      // Single-chunk eligibility from the posted size is conservative:
      // the actual (<=posted) size can only have fewer chunks.
      std::lock_guard<std::mutex> lk(c->lazy_mu);
      c->lazy_msg = m;
      c->has_lazy = true;
      c->lazy_req = id;
      g_lazy_parked.fetch_add(1, std::memory_order_relaxed);
      lazy_recv_owners_.Put(id, c);
    } else {
      if (!c->msgs.Push(m)) FailMsg(c.get(), state, "recv comm is poisoned");
    }
    *request = id;
    return Status::Ok();
  }

  Status test(uint64_t request, bool* done, size_t* nbytes) override {
    // Pollers (the NCCL shim) never call wait(), so a lazy recv would
    // starve: upgrade it onto the scheduler on the first poll. Match on the
    // request id — a stale owner entry (this request was already upgraded
    // elsewhere) must not kick a NEWER lazy parked on the same comm.
    CommPtr lc;
    if (lazy_recv_owners_.Take(request, &lc)) UpgradeLazyIf(lc.get(), request);
    RequestPtr state;
    if (!requests_.Get(request, &state)) {
      return Status::Invalid("unknown request " + std::to_string(request));
    }
    if (state->failed.load(std::memory_order_acquire)) {
      // Surface the error only once all dispatched chunk workers have
      // quiesced on this request — otherwise the caller could free/reuse the
      // buffer while a stream worker is still reading into it.
      if (!state->Done()) {
        *done = false;
        return Status::Ok();
      }
      requests_.Erase(request);
      return Status::Inner("request failed: " + state->ErrorMsg());
    }
    *done = state->Done();
    if (*done) {
      if (nbytes) *nbytes = state->nbytes.load(std::memory_order_acquire);
      requests_.Erase(request);  // reference leaked these (bagua_net.cc:111-121)
    }
    return Status::Ok();
  }

  Status wait(uint64_t request, size_t* nbytes) override {
    TPUNET_DBG("wait req=%llu enter", (unsigned long long)request);
    CommPtr c;
    if (lazy_recv_owners_.Take(request, &c)) {
      Msg m;
      bool mine = false;
      {
        std::lock_guard<std::mutex> lk(c->lazy_mu);
        if (c->has_lazy && c->lazy_req == request) {
          m = c->lazy_msg;
          c->lazy_msg = Msg{};
          c->has_lazy = false;
          g_lazy_parked.fetch_sub(1, std::memory_order_relaxed);
          mine = true;
        }
      }
      if (mine) {
        // About to block in this comm's ctrl read: upgrade every OTHER
        // parked lazy first, or a multi-comm wait order could deadlock
        // against a lazy recv only this thread would have executed later.
        if (g_lazy_parked.load(std::memory_order_relaxed) != 0) {
          for (auto& lc : lazy_recv_owners_.DrainAll()) UpgradeLazy(lc.get());
        }
        ExecuteLazyRecv(c.get(), m);
      }
      Status st = WaitIn(requests_, request, nbytes);
      TPUNET_DBG("wait req=%llu lazy-exit ok=%d", (unsigned long long)request, (int)st.ok());
      return st;
    }
    // Non-lazy request: while it does not settle, keep upgrading every
    // parked lazy recv in this process. Without this, two ranks could both
    // park in a send-wait whose completion needs the peer's lazy recv to
    // run — a deadlock no caller ordering should be able to create. The
    // repeat (vs one-shot) covers a lazy parked by another thread after an
    // earlier pass; each pass is a no-op on an empty map.
    RequestPtr state;
    if (!requests_.Get(request, &state)) {
      return Status::Invalid("unknown request " + std::to_string(request));
    }
    int spins = 0;
    while (g_lazy_parked.load(std::memory_order_relaxed) != 0 &&
           !state->WaitSettledFor(50)) {
      // A lazy parked AFTER we fall through is its poster's own problem:
      // that thread's next wait/test upgrades it (every thread that parks
      // a lazy eventually waits something).
      for (auto& lc : lazy_recv_owners_.DrainAll()) UpgradeLazy(lc.get());
      if (++spins % 40 == 0) TPUNET_DBG("wait req=%llu still unsettled after %d spins (total=%llu completed=%llu failed=%d)", (unsigned long long)request, spins, (unsigned long long)state->total.load(), (unsigned long long)state->completed.load(), (int)state->failed.load());
    }
    Status st = WaitIn(requests_, request, nbytes);
    TPUNET_DBG("wait req=%llu exit ok=%d", (unsigned long long)request, (int)st.ok());
    return st;
  }

  Status close_send(uint64_t send_comm) override {
    CommPtr c;
    if (!send_comms_.Take(send_comm, &c)) {
      return Status::Invalid("unknown send comm " + std::to_string(send_comm));
    }
    c->Shutdown();
    return Status::Ok();
  }

  Status close_recv(uint64_t recv_comm) override {
    CommPtr c;
    if (!recv_comms_.Take(recv_comm, &c)) {
      return Status::Invalid("unknown recv comm " + std::to_string(recv_comm));
    }
    c->Shutdown();
    return Status::Ok();
  }

 private:
  // Move a parked lazy recv onto the scheduler queue. The Push happens
  // UNDER lazy_mu: with it outside, a cross-thread upgrade could be
  // preempted between claim and push while the comm's caller posts (and
  // queues) a newer irecv, enqueueing the older recv after the newer one
  // and pairing ctrl frames with the wrong requests.
  static void UpgradeLazy(Comm* c) { UpgradeLazyIf(c, 0); }

  // expect_req != 0 restricts the upgrade to that specific parked request
  // (test()'s stale-entry guard); 0 upgrades whatever is parked.
  static void UpgradeLazyIf(Comm* c, uint64_t expect_req) {
    std::lock_guard<std::mutex> lk(c->lazy_mu);
    if (!c->has_lazy) return;
    if (expect_req != 0 && c->lazy_req != expect_req) return;
    Msg m = c->lazy_msg;
    c->lazy_msg = Msg{};
    c->has_lazy = false;
    g_lazy_parked.fetch_sub(1, std::memory_order_relaxed);
    if (!c->msgs.Push(m)) FailMsg(c, m.state, "recv comm is poisoned");
  }

  void StartThreads(Comm* c) {
    bool spin = c->spin;
    for (auto& w : c->workers) {
      StreamWorker* wp = w.get();
      wp->comm = c;
      w->thread = c->is_send ? std::thread(SendWorkerLoop, wp, spin)
                             : std::thread(RecvWorkerLoop, wp, spin);
    }
    c->scheduler = std::make_unique<std::thread>(
        c->is_send ? SendSchedulerLoop : RecvSchedulerLoop, c);
  }

  Status BuildRecvComm(PartialBundle& b, uint64_t* recv_comm) {
    auto comm = std::make_shared<Comm>();
    comm->is_send = false;
    // Sender's chunk-map inputs win — carried in the preamble so both sides
    // always partition messages identically (SURVEY hard-part #2).
    comm->nstreams = b.nstreams;
    comm->min_chunksize = b.min_chunksize;
    comm->spin = spin_;
    comm->ctrl_fd = b.ctrl_fd;
    b.ctrl_fd = -1;
    Status ns = Status::Ok();
    if (spin_) ns = SetNonblocking(comm->ctrl_fd);  // ctrl carries the length frame
    // Data streams ordered by stream id (reference: BTreeMap nthread:432).
    for (auto& kv : b.data_fds) {
      auto w = std::make_unique<StreamWorker>();
      w->fd = kv.second;
      w->idx = comm->workers.size();
      if (spin_ && ns.ok()) ns = SetNonblocking(w->fd);
      comm->workers.push_back(std::move(w));
    }
    b.data_fds.clear();
    if (!ns.ok()) {
      comm->Shutdown();
      return ns;
    }
    StartThreads(comm.get());
    uint64_t id = next_id_.fetch_add(1);
    recv_comms_.Put(id, comm);
    *recv_comm = id;
    return Status::Ok();
  }

  bool spin_;
  bool inline_send_;
  bool lazy_recv_;
  IdMap<CommPtr> send_comms_;
  IdMap<CommPtr> recv_comms_;
  IdMap<RequestPtr> requests_;
  // request id -> comm whose lazy slot holds that request. Entries are
  // claimed (Take) by exactly one of wait/test/drain; stale entries after
  // an irecv-triggered upgrade are benign (claimer finds has_lazy false).
  IdMap<CommPtr> lazy_recv_owners_;
};

}  // namespace

std::unique_ptr<Net> CreateBasicEngine() { return std::make_unique<BasicEngine>(); }

std::unique_ptr<Net> CreateEngine() {
  // Engine seam (reference: src/lib.rs:20-29 BAGUA_NET_IMPLEMENT
  // BASIC|TOKIO); ours is TPUNET_IMPLEMENT BASIC|EPOLL. Every engine goes
  // out wrapped in the telemetry decorator so metrics/tracing cannot
  // diverge between engines.
  std::string impl = GetEnv("TPUNET_IMPLEMENT", GetEnv("BAGUA_NET_IMPLEMENT", "BASIC"));
  auto engine = impl == "EPOLL" ? CreateEpollEngine() : CreateBasicEngine();
  return WrapWithTelemetry(std::move(engine));
}

}  // namespace tpunet
