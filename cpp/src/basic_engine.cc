// tpunet BASIC engine — thread-per-stream multi-stream TCP transport.
//
// TPU-native re-design of the reference's default engine
// (reference: src/implement/nthread_per_socket_backend.rs). Behavioral
// contract reproduced:
//   * per send/recv comm: 1 scheduler thread + nstreams data-stream threads,
//     each owning one TCP connection (reference :103-237, :336-361).
//   * every message is split into chunks of max(ceil(len/nstreams),
//     min_chunksize) and chunks are assigned round-robin starting at a
//     per-comm cursor that persists ACROSS messages (reference :393,412) —
//     the fairness mechanism: even 1-chunk messages rotate streams.
//   * sender and receiver compute identical chunk boundaries + assignment
//     from (len, min_chunksize, nstreams) alone, so the wire carries no
//     per-chunk header; TCP per-stream ordering makes this correct.
//   * per message the ctrl stream carries an 8-byte big-endian length frame
//     (reference :395-397/:494-502); the receiver may post a larger buffer
//     and learns the true size from this frame.
//   * completion = bytes handed to the kernel socket buffer, not peer-ACKed.
//   * request lifecycle: isend/irecv return an id, test() polls, done
//     consumes the id.
//
// Deliberate improvements over the reference (documented deltas):
//   * Wire preamble carries bundle id + nstreams + min_chunksize (wire.h) —
//     concurrent senders on one listen socket, no config divergence, magic
//     check. Shared with the EPOLL engine, so the two engines interoperate
//     (the reference's BASIC/TOKIO were wire-incompatible).
//   * Blocking sockets by default instead of the reference's nonblocking
//     busy-poll spin (reference utils.rs:132-178) — a TPU host shares cores
//     with the trainer; TPUNET_SPIN=1 restores spin mode for latency hunts.
//   * No global engine mutex (reference lib.rs:14-16): ids resolve through
//     sharded maps, test() touches only atomics.
//   * Request ids are freed on completion (reference leaked them:
//     cc/bagua_net.cc:111-121).
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <deque>
#include <memory>
#include <mutex>
#include <thread>
#include <vector>

#include "engine_base.h"
#include "id_map.h"
#include "tpunet/net.h"
#include "tpunet/telemetry.h"
#include "tpunet/utils.h"
#include "wire.h"

namespace tpunet {
namespace {

// MPSC blocking queue with close semantics (stands in for the reference's
// flume channels, nthread:224-226). Pop returns false only when closed AND
// drained, so close_send/close_recv still flush queued work.
template <typename T>
class Queue {
 public:
  void Push(T t) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      q_.push_back(std::move(t));
    }
    cv_.notify_one();
  }
  bool Pop(T* out) {
    std::unique_lock<std::mutex> lk(mu_);
    cv_.wait(lk, [&] { return closed_ || !q_.empty(); });
    if (q_.empty()) return false;
    *out = std::move(q_.front());
    q_.pop_front();
    return true;
  }
  void Close() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      closed_ = true;
    }
    cv_.notify_all();
  }

 private:
  std::mutex mu_;
  std::condition_variable cv_;
  std::deque<T> q_;
  bool closed_ = false;
};

struct ChunkTask {
  uint8_t* data = nullptr;  // send: source bytes; recv: destination bytes
  size_t len = 0;
  RequestPtr state;
};

struct Msg {
  uint8_t* data = nullptr;
  size_t len = 0;
  RequestPtr state;
};

struct Comm;

// One data stream: a TCP connection owned by one worker thread.
struct StreamWorker {
  int fd = -1;
  size_t idx = 0;  // data-stream index (for per-stream fairness counters)
  Comm* comm = nullptr;
  Queue<ChunkTask> tasks;
  std::thread thread;
};

// A send or recv comm: ctrl connection + scheduler thread + stream workers.
struct Comm {
  bool is_send = false;
  int ctrl_fd = -1;
  size_t nstreams = 0;
  size_t min_chunksize = 0;
  bool spin = false;
  std::vector<std::unique_ptr<StreamWorker>> workers;
  Queue<Msg> msgs;
  std::unique_ptr<std::thread> scheduler;
  // Threads do not survive fork(): a mismatch means this comm's scheduler /
  // workers never existed in this process (see Shutdown and the engine's
  // isend/irecv fail-fast).
  const uint64_t fork_gen = ForkGeneration();

  ~Comm() { Shutdown(); }

  // On any stream IO error, poison every connection in the comm so sibling
  // workers blocked mid-chunk fail fast and all requests quiesce — without
  // this, a single dead stream would leave test() hanging on the survivors.
  void AbortStreams() {
    if (aborted_.exchange(true)) return;
    for (auto& w : workers) {
      if (w->fd >= 0) ::shutdown(w->fd, SHUT_RDWR);
    }
    if (ctrl_fd >= 0) ::shutdown(ctrl_fd, SHUT_RDWR);
  }

  void Shutdown() {
    if (shut_) return;
    shut_ = true;
    if (ForkGeneration() != fork_gen) {
      // Forked child: scheduler/worker pthreads never existed here and the
      // queue mutexes may have been captured mid-lock at fork. Leak the
      // thread handles (any pthread call on their stale ids is UB) and only
      // close this process's copies of the fds.
      (void)scheduler.release();
      for (auto& w : workers) {
        if (w->fd >= 0) ::close(w->fd);
        (void)w.release();
      }
      workers.clear();
      if (ctrl_fd >= 0) ::close(ctrl_fd);
      ctrl_fd = -1;
      return;
    }
    msgs.Close();
    // By the NCCL contract every request has been test()ed done before close,
    // so scheduler/workers are idle in Pop and the shutdown()s below are
    // no-ops data-wise. If the contract was violated (peer stalled/died with
    // bytes in flight), SHUT_RDWR wakes threads blocked in kernel send/recv —
    // a hang would otherwise be permanent since std::thread has no timed join.
    AbortStreams();
    if (scheduler && scheduler->joinable()) scheduler->join();
    for (auto& w : workers) w->tasks.Close();
    for (auto& w : workers) {
      if (w->thread.joinable()) w->thread.join();
    }
    for (auto& w : workers) {
      if (w->fd >= 0) ::close(w->fd);
      w->fd = -1;
    }
    if (ctrl_fd >= 0) ::close(ctrl_fd);
    ctrl_fd = -1;
  }

 private:
  std::atomic<bool> aborted_{false};
  bool shut_ = false;
};
using CommPtr = std::shared_ptr<Comm>;

// ---------------------------------------------------------------------------
// Worker / scheduler loops.

void SendWorkerLoop(StreamWorker* w, bool spin) {
  ChunkTask t;
  while (w->tasks.Pop(&t)) {
    Status s = WriteAll(w->fd, t.data, t.len, spin);
    if (!s.ok()) {
      t.state->SetError(s.msg);
      w->comm->AbortStreams();
    } else {
      Telemetry::Get().OnStreamBytes(true, w->idx, t.len);
    }
    t.state->nbytes.fetch_add(t.len, std::memory_order_relaxed);
    t.state->completed.fetch_add(1, std::memory_order_acq_rel);
    t.state->NotifyIfSettled();
  }
}

void RecvWorkerLoop(StreamWorker* w, bool spin) {
  ChunkTask t;
  while (w->tasks.Pop(&t)) {
    Status s = ReadExact(w->fd, t.data, t.len, spin);
    if (!s.ok()) {
      t.state->SetError(s.msg);
      w->comm->AbortStreams();
    } else {
      Telemetry::Get().OnStreamBytes(false, w->idx, t.len);
    }
    t.state->nbytes.fetch_add(t.len, std::memory_order_relaxed);
    t.state->completed.fetch_add(1, std::memory_order_acq_rel);
    t.state->NotifyIfSettled();
  }
}

// Chunk a message and fan chunks out to stream workers round-robin from the
// rotating cursor. Both sides run this exact function per message, keeping
// chunk maps symmetric (SURVEY hard-part #2).
void DispatchChunks(Comm* c, uint8_t* data, size_t len, const RequestPtr& state,
                    uint64_t* cursor) {
  size_t csize = ChunkSize(len, c->min_chunksize, c->nstreams);
  size_t nchunks = ChunkCount(len, csize);
  state->total.store(nchunks, std::memory_order_release);  // 0-byte msg: done now
  state->NotifyIfSettled();
  size_t off = 0;
  for (size_t i = 0; i < nchunks; ++i) {
    size_t n = std::min(csize, len - off);
    StreamWorker* w = c->workers[*cursor % c->nstreams].get();
    *cursor += 1;  // persists across messages — fairness rotation
    w->tasks.Push(ChunkTask{data + off, n, state});
    off += n;
  }
}

void FailAndDrain(Comm* c, const RequestPtr& state, const std::string& msg) {
  state->SetError(msg);
  state->total.store(0, std::memory_order_release);
  state->NotifyIfSettled();
  c->AbortStreams();
  // Reference breaks its loop on ctrl error leaving queued requests to hang
  // (nthread:396-401); we fail them promptly instead.
  Msg m;
  while (c->msgs.Pop(&m)) {
    m.state->SetError("comm broken by earlier ctrl-stream error: " + msg);
    m.state->total.store(0, std::memory_order_release);
    m.state->NotifyIfSettled();
  }
}

void SendSchedulerLoop(Comm* c) {
  uint64_t cursor = 0;
  Msg m;
  while (c->msgs.Pop(&m)) {
    uint8_t hdr[8];
    EncodeU64BE(m.len, hdr);
    Status s = WriteAll(c->ctrl_fd, hdr, sizeof(hdr), c->spin);
    if (!s.ok()) {
      FailAndDrain(c, m.state, s.msg);
      return;
    }
    DispatchChunks(c, m.data, m.len, m.state, &cursor);
  }
}

void RecvSchedulerLoop(Comm* c) {
  uint64_t cursor = 0;
  Msg m;
  while (c->msgs.Pop(&m)) {
    uint8_t hdr[8];
    Status s = ReadExact(c->ctrl_fd, hdr, sizeof(hdr), c->spin);
    if (!s.ok()) {
      FailAndDrain(c, m.state, s.msg);
      return;
    }
    uint64_t target = DecodeU64BE(hdr);
    if (target > m.len) {
      // Peer sent more than the posted buffer — unrecoverable protocol
      // violation (the reference would panic slicing data[..target]).
      FailAndDrain(c, m.state,
                   "incoming message (" + std::to_string(target) +
                       "B) exceeds posted recv buffer (" + std::to_string(m.len) + "B)");
      return;
    }
    // NCCL semantics: recv buffer may exceed the message; true size comes
    // from the ctrl frame (reference nthread:507).
    DispatchChunks(c, m.data, static_cast<size_t>(target), m.state, &cursor);
  }
}

// ---------------------------------------------------------------------------

class BasicEngine : public EngineBase {
 public:
  BasicEngine() : spin_(GetEnvU64("TPUNET_SPIN", 0) != 0) {}

  ~BasicEngine() override {
    for (auto& c : send_comms_.DrainAll()) c->Shutdown();
    for (auto& c : recv_comms_.DrainAll()) c->Shutdown();
    // Wake any thread still parked in accept() — mirror of close_listen;
    // without this, destroying the engine would strand it forever.
    WakeAllListens();
  }

  Status connect(int32_t dev, const SocketHandle& handle, uint64_t* send_comm) override {
    Status sdev = CheckDev(dev);
    if (!sdev.ok()) return sdev;
    std::vector<int> data_fds;
    int ctrl_fd = -1;
    Status s = ConnectBundle(nics_, dev, handle, nstreams_, min_chunksize_, &data_fds, &ctrl_fd);
    if (!s.ok()) return s;

    auto comm = std::make_shared<Comm>();
    comm->is_send = true;
    comm->nstreams = nstreams_;
    comm->min_chunksize = min_chunksize_;
    comm->spin = spin_;
    comm->ctrl_fd = ctrl_fd;
    for (int fd : data_fds) {
      auto w = std::make_unique<StreamWorker>();
      w->fd = fd;
      w->idx = comm->workers.size();
      comm->workers.push_back(std::move(w));
    }
    if (spin_) {
      // Spin mode busy-polls nonblocking fds (set only after the blocking
      // preamble writes inside ConnectBundle). A failed fcntl must abort:
      // a silently-blocking fd would wedge the busy-poll path.
      Status ns = SetNonblocking(comm->ctrl_fd);
      for (auto& w : comm->workers) {
        if (ns.ok()) ns = SetNonblocking(w->fd);
      }
      if (!ns.ok()) {
        comm->Shutdown();
        return ns;
      }
    }
    StartThreads(comm.get());
    uint64_t id = next_id_.fetch_add(1);
    send_comms_.Put(id, comm);
    *send_comm = id;
    return Status::Ok();
  }

  Status accept(uint64_t listen_comm, uint64_t* recv_comm) override {
    PartialBundle b;
    Status s = AcceptBundleOn(listen_comm, &b);
    if (!s.ok()) return s;
    return BuildRecvComm(b, recv_comm);
  }

  Status isend(uint64_t send_comm, const void* data, size_t nbytes, uint64_t* request) override {
    CommPtr c;
    if (!send_comms_.Get(send_comm, &c)) {
      return Status::Invalid("unknown send comm " + std::to_string(send_comm));
    }
    if (ForkGeneration() != c->fork_gen) {
      return Status::Inner("send comm created before fork(); its threads do not exist here");
    }
    auto state = std::make_shared<RequestState>();
    uint64_t id = next_id_.fetch_add(1);
    requests_.Put(id, state);
    c->msgs.Push(Msg{const_cast<uint8_t*>(static_cast<const uint8_t*>(data)), nbytes, state});
    *request = id;
    return Status::Ok();
  }

  Status irecv(uint64_t recv_comm, void* data, size_t nbytes, uint64_t* request) override {
    CommPtr c;
    if (!recv_comms_.Get(recv_comm, &c)) {
      return Status::Invalid("unknown recv comm " + std::to_string(recv_comm));
    }
    if (ForkGeneration() != c->fork_gen) {
      return Status::Inner("recv comm created before fork(); its threads do not exist here");
    }
    auto state = std::make_shared<RequestState>();
    uint64_t id = next_id_.fetch_add(1);
    requests_.Put(id, state);
    c->msgs.Push(Msg{static_cast<uint8_t*>(data), nbytes, state});
    *request = id;
    return Status::Ok();
  }

  Status test(uint64_t request, bool* done, size_t* nbytes) override {
    RequestPtr state;
    if (!requests_.Get(request, &state)) {
      return Status::Invalid("unknown request " + std::to_string(request));
    }
    if (state->failed.load(std::memory_order_acquire)) {
      // Surface the error only once all dispatched chunk workers have
      // quiesced on this request — otherwise the caller could free/reuse the
      // buffer while a stream worker is still reading into it.
      if (!state->Done()) {
        *done = false;
        return Status::Ok();
      }
      requests_.Erase(request);
      return Status::Inner("request failed: " + state->ErrorMsg());
    }
    *done = state->Done();
    if (*done) {
      if (nbytes) *nbytes = state->nbytes.load(std::memory_order_acquire);
      requests_.Erase(request);  // reference leaked these (bagua_net.cc:111-121)
    }
    return Status::Ok();
  }

  Status wait(uint64_t request, size_t* nbytes) override {
    return WaitIn(requests_, request, nbytes);
  }

  Status close_send(uint64_t send_comm) override {
    CommPtr c;
    if (!send_comms_.Take(send_comm, &c)) {
      return Status::Invalid("unknown send comm " + std::to_string(send_comm));
    }
    c->Shutdown();
    return Status::Ok();
  }

  Status close_recv(uint64_t recv_comm) override {
    CommPtr c;
    if (!recv_comms_.Take(recv_comm, &c)) {
      return Status::Invalid("unknown recv comm " + std::to_string(recv_comm));
    }
    c->Shutdown();
    return Status::Ok();
  }

 private:
  void StartThreads(Comm* c) {
    bool spin = c->spin;
    for (auto& w : c->workers) {
      StreamWorker* wp = w.get();
      wp->comm = c;
      w->thread = c->is_send ? std::thread(SendWorkerLoop, wp, spin)
                             : std::thread(RecvWorkerLoop, wp, spin);
    }
    c->scheduler = std::make_unique<std::thread>(
        c->is_send ? SendSchedulerLoop : RecvSchedulerLoop, c);
  }

  Status BuildRecvComm(PartialBundle& b, uint64_t* recv_comm) {
    auto comm = std::make_shared<Comm>();
    comm->is_send = false;
    // Sender's chunk-map inputs win — carried in the preamble so both sides
    // always partition messages identically (SURVEY hard-part #2).
    comm->nstreams = b.nstreams;
    comm->min_chunksize = b.min_chunksize;
    comm->spin = spin_;
    comm->ctrl_fd = b.ctrl_fd;
    b.ctrl_fd = -1;
    Status ns = Status::Ok();
    if (spin_) ns = SetNonblocking(comm->ctrl_fd);  // ctrl carries the length frame
    // Data streams ordered by stream id (reference: BTreeMap nthread:432).
    for (auto& kv : b.data_fds) {
      auto w = std::make_unique<StreamWorker>();
      w->fd = kv.second;
      w->idx = comm->workers.size();
      if (spin_ && ns.ok()) ns = SetNonblocking(w->fd);
      comm->workers.push_back(std::move(w));
    }
    b.data_fds.clear();
    if (!ns.ok()) {
      comm->Shutdown();
      return ns;
    }
    StartThreads(comm.get());
    uint64_t id = next_id_.fetch_add(1);
    recv_comms_.Put(id, comm);
    *recv_comm = id;
    return Status::Ok();
  }

  bool spin_;
  IdMap<CommPtr> send_comms_;
  IdMap<CommPtr> recv_comms_;
  IdMap<RequestPtr> requests_;
};

}  // namespace

std::unique_ptr<Net> CreateBasicEngine() { return std::make_unique<BasicEngine>(); }

std::unique_ptr<Net> CreateEngine() {
  // Engine seam (reference: src/lib.rs:20-29 BAGUA_NET_IMPLEMENT
  // BASIC|TOKIO); ours is TPUNET_IMPLEMENT BASIC|EPOLL. Every engine goes
  // out wrapped in the telemetry decorator so metrics/tracing cannot
  // diverge between engines.
  std::string impl = GetEnv("TPUNET_IMPLEMENT", GetEnv("BAGUA_NET_IMPLEMENT", "BASIC"));
  auto engine = impl == "EPOLL" ? CreateEpollEngine() : CreateBasicEngine();
  return WrapWithTelemetry(std::move(engine));
}

}  // namespace tpunet
