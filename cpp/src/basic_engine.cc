// tpunet BASIC engine — thread-per-stream multi-stream TCP transport.
//
// TPU-native re-design of the reference's default engine
// (reference: src/implement/nthread_per_socket_backend.rs). Behavioral
// contract reproduced:
//   * per send/recv comm: 1 scheduler thread + nstreams data-stream threads,
//     each owning one TCP connection (reference :103-237, :336-361).
//   * every message is split into chunks of max(ceil(len/nstreams),
//     min_chunksize) and chunks are assigned round-robin starting at a
//     per-comm cursor that persists ACROSS messages (reference :393,412) —
//     the fairness mechanism: even 1-chunk messages rotate streams.
//   * sender and receiver compute identical chunk boundaries + assignment
//     from (len, min_chunksize, nstreams) alone, so the wire carries no
//     per-chunk header; TCP per-stream ordering makes this correct.
//   * per message the ctrl stream carries an 8-byte big-endian length frame
//     (reference :395-397/:494-502); the receiver may post a larger buffer
//     and learns the true size from this frame.
//   * completion = bytes handed to the kernel socket buffer, not peer-ACKed.
//   * request lifecycle: isend/irecv return an id, test() polls, done
//     consumes the id.
//
// Deliberate improvements over the reference (documented deltas):
//   * Wire preamble: every connection opens with
//     [magic u64 | bundle_id u64 | stream_id u64 | nstreams u64 |
//     min_chunksize u64] (40B, BE) instead of a bare stream id (reference
//     :327). This (a) lets several
//     connect() bundles target one listen socket concurrently without
//     interleaving, (b) carries nstreams so sender/receiver cannot disagree,
//     (c) catches protocol mismatch via the magic.
//   * Blocking sockets by default instead of the reference's nonblocking
//     busy-poll spin (reference utils.rs:132-178) — a TPU host shares cores
//     with the trainer; TPUNET_SPIN=1 restores spin mode for latency hunts.
//   * No global engine mutex (reference lib.rs:14-16): ids resolve through
//     sharded maps, test() touches only atomics.
//   * Request ids are freed on completion (reference leaked them:
//     cc/bagua_net.cc:111-121).
#include <arpa/inet.h>
#include <errno.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <string.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <random>
#include <thread>
#include <vector>

#include "id_map.h"
#include "tpunet/net.h"
#include "tpunet/utils.h"

namespace tpunet {
namespace {

constexpr uint64_t kWireMagic = 0x7470756e65743102ull;  // "tpunet" + wire ver 2
constexpr int kListenBacklog = 16384;  // reference: nthread:101
constexpr uint64_t kMaxStreams = 256;  // sanity bound on peer-supplied nstreams

socklen_t AddrLenForFamily(const sockaddr_storage& ss) {
  return ss.ss_family == AF_INET6 ? sizeof(sockaddr_in6) : sizeof(sockaddr_in);
}

// ---------------------------------------------------------------------------
// Request state: lock-free completion accounting.
// Reference: RequestState{nsubtasks, completed_subtasks, nbytes_transferred,
// err} (nthread:54-60). `total` doubles as the "scheduled" flag: UINT64_MAX
// until the scheduler has chunked the message.
struct RequestState {
  std::atomic<uint64_t> total{UINT64_MAX};
  std::atomic<uint64_t> completed{0};
  std::atomic<uint64_t> nbytes{0};
  std::atomic<bool> failed{false};
  std::mutex err_mu;
  std::string err_msg;

  void SetError(const std::string& m) {
    {
      std::lock_guard<std::mutex> lk(err_mu);
      if (err_msg.empty()) err_msg = m;
    }
    failed.store(true, std::memory_order_release);
  }
  std::string ErrorMsg() {
    std::lock_guard<std::mutex> lk(err_mu);
    return err_msg;
  }
  bool Done() const {
    uint64_t t = total.load(std::memory_order_acquire);
    return t != UINT64_MAX && completed.load(std::memory_order_acquire) >= t;
  }
};
using RequestPtr = std::shared_ptr<RequestState>;

// MPSC blocking queue with close semantics (stands in for the reference's
// flume channels, nthread:224-226). Pop returns false only when closed AND
// drained, so close_send/close_recv still flush queued work.
template <typename T>
class Queue {
 public:
  void Push(T t) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      q_.push_back(std::move(t));
    }
    cv_.notify_one();
  }
  bool Pop(T* out) {
    std::unique_lock<std::mutex> lk(mu_);
    cv_.wait(lk, [&] { return closed_ || !q_.empty(); });
    if (q_.empty()) return false;
    *out = std::move(q_.front());
    q_.pop_front();
    return true;
  }
  void Close() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      closed_ = true;
    }
    cv_.notify_all();
  }

 private:
  std::mutex mu_;
  std::condition_variable cv_;
  std::deque<T> q_;
  bool closed_ = false;
};

struct ChunkTask {
  uint8_t* data = nullptr;  // send: source bytes; recv: destination bytes
  size_t len = 0;
  RequestPtr state;
};

struct Msg {
  uint8_t* data = nullptr;
  size_t len = 0;
  RequestPtr state;
};

struct Comm;

// One data stream: a TCP connection owned by one worker thread.
struct StreamWorker {
  int fd = -1;
  Comm* comm = nullptr;
  Queue<ChunkTask> tasks;
  std::thread thread;
};

// A send or recv comm: ctrl connection + scheduler thread + stream workers.
struct Comm {
  bool is_send = false;
  int ctrl_fd = -1;
  size_t nstreams = 0;
  size_t min_chunksize = 0;
  bool spin = false;
  std::vector<std::unique_ptr<StreamWorker>> workers;
  Queue<Msg> msgs;
  std::thread scheduler;

  ~Comm() { Shutdown(); }

  // On any stream IO error, poison every connection in the comm so sibling
  // workers blocked mid-chunk fail fast and all requests quiesce — without
  // this, a single dead stream would leave test() hanging on the survivors.
  void AbortStreams() {
    if (aborted_.exchange(true)) return;
    for (auto& w : workers) {
      if (w->fd >= 0) ::shutdown(w->fd, SHUT_RDWR);
    }
    if (ctrl_fd >= 0) ::shutdown(ctrl_fd, SHUT_RDWR);
  }

  void Shutdown() {
    if (shut_) return;
    shut_ = true;
    msgs.Close();
    // By the NCCL contract every request has been test()ed done before close,
    // so scheduler/workers are idle in Pop and the shutdown()s below are
    // no-ops data-wise. If the contract was violated (peer stalled/died with
    // bytes in flight), SHUT_RDWR wakes threads blocked in kernel send/recv —
    // a hang would otherwise be permanent since std::thread has no timed join.
    AbortStreams();
    if (scheduler.joinable()) scheduler.join();
    for (auto& w : workers) w->tasks.Close();
    for (auto& w : workers) {
      if (w->thread.joinable()) w->thread.join();
    }
    for (auto& w : workers) {
      if (w->fd >= 0) ::close(w->fd);
      w->fd = -1;
    }
    if (ctrl_fd >= 0) ::close(ctrl_fd);
    ctrl_fd = -1;
  }

 private:
  std::atomic<bool> aborted_{false};
  bool shut_ = false;
};
using CommPtr = std::shared_ptr<Comm>;

// Parked connection bundle on a listen comm, keyed by bundle id, until all
// nstreams+1 members have arrived.
struct PartialBundle {
  uint64_t nstreams = UINT64_MAX;
  uint64_t min_chunksize = 0;
  int ctrl_fd = -1;
  std::chrono::steady_clock::time_point first_seen;
  std::map<uint64_t, int> data_fds;  // stream_id -> fd (ordered)
  bool Complete() const {
    return ctrl_fd >= 0 && nstreams != UINT64_MAX && data_fds.size() == nstreams;
  }
  void CloseAll() {
    if (ctrl_fd >= 0) ::close(ctrl_fd);
    ctrl_fd = -1;
    for (auto& df : data_fds) ::close(df.second);
    data_fds.clear();
  }
};

struct ListenComm {
  int fd = -1;
  int wake_fd = -1;  // eventfd; close_listen signals it to abort a blocked accept()
  int32_t dev = 0;
  std::atomic<bool> closed{false};
  std::mutex mu;  // guards partials; accept() may be called from many threads
  std::map<uint64_t, PartialBundle> partials;

  ~ListenComm() {
    for (auto& kv : partials) kv.second.CloseAll();
    if (fd >= 0) ::close(fd);
    if (wake_fd >= 0) ::close(wake_fd);
  }
};
using ListenPtr = std::shared_ptr<ListenComm>;

// ---------------------------------------------------------------------------
// Worker / scheduler loops.

void SendWorkerLoop(StreamWorker* w, bool spin) {
  ChunkTask t;
  while (w->tasks.Pop(&t)) {
    Status s = WriteAll(w->fd, t.data, t.len, spin);
    if (!s.ok()) {
      t.state->SetError(s.msg);
      w->comm->AbortStreams();
    }
    t.state->nbytes.fetch_add(t.len, std::memory_order_relaxed);
    t.state->completed.fetch_add(1, std::memory_order_acq_rel);
  }
}

void RecvWorkerLoop(StreamWorker* w, bool spin) {
  ChunkTask t;
  while (w->tasks.Pop(&t)) {
    Status s = ReadExact(w->fd, t.data, t.len, spin);
    if (!s.ok()) {
      t.state->SetError(s.msg);
      w->comm->AbortStreams();
    }
    t.state->nbytes.fetch_add(t.len, std::memory_order_relaxed);
    t.state->completed.fetch_add(1, std::memory_order_acq_rel);
  }
}

// Chunk a message and fan chunks out to stream workers round-robin from the
// rotating cursor. Both sides run this exact function per message, keeping
// chunk maps symmetric (SURVEY hard-part #2).
void DispatchChunks(Comm* c, uint8_t* data, size_t len, const RequestPtr& state,
                    uint64_t* cursor) {
  size_t csize = ChunkSize(len, c->min_chunksize, c->nstreams);
  size_t nchunks = ChunkCount(len, csize);
  state->total.store(nchunks, std::memory_order_release);  // 0-byte msg: done now
  size_t off = 0;
  for (size_t i = 0; i < nchunks; ++i) {
    size_t n = std::min(csize, len - off);
    StreamWorker* w = c->workers[*cursor % c->nstreams].get();
    *cursor += 1;  // persists across messages — fairness rotation
    w->tasks.Push(ChunkTask{data + off, n, state});
    off += n;
  }
}

void FailAndDrain(Comm* c, const RequestPtr& state, const std::string& msg) {
  state->SetError(msg);
  state->total.store(0, std::memory_order_release);
  c->AbortStreams();
  // Reference breaks its loop on ctrl error leaving queued requests to hang
  // (nthread:396-401); we fail them promptly instead.
  Msg m;
  while (c->msgs.Pop(&m)) {
    m.state->SetError("comm broken by earlier ctrl-stream error: " + msg);
    m.state->total.store(0, std::memory_order_release);
  }
}

void SendSchedulerLoop(Comm* c) {
  uint64_t cursor = 0;
  Msg m;
  while (c->msgs.Pop(&m)) {
    uint8_t hdr[8];
    EncodeU64BE(m.len, hdr);
    Status s = WriteAll(c->ctrl_fd, hdr, sizeof(hdr), c->spin);
    if (!s.ok()) {
      FailAndDrain(c, m.state, s.msg);
      return;
    }
    DispatchChunks(c, m.data, m.len, m.state, &cursor);
  }
}

void RecvSchedulerLoop(Comm* c) {
  uint64_t cursor = 0;
  Msg m;
  while (c->msgs.Pop(&m)) {
    uint8_t hdr[8];
    Status s = ReadExact(c->ctrl_fd, hdr, sizeof(hdr), c->spin);
    if (!s.ok()) {
      FailAndDrain(c, m.state, s.msg);
      return;
    }
    uint64_t target = DecodeU64BE(hdr);
    if (target > m.len) {
      // Peer sent more than the posted buffer — unrecoverable protocol
      // violation (the reference would panic slicing data[..target]).
      FailAndDrain(c, m.state,
                   "incoming message (" + std::to_string(target) +
                       "B) exceeds posted recv buffer (" + std::to_string(m.len) + "B)");
      return;
    }
    // NCCL semantics: recv buffer may exceed the message; true size comes
    // from the ctrl frame (reference nthread:507).
    DispatchChunks(c, m.data, static_cast<size_t>(target), m.state, &cursor);
  }
}

// ---------------------------------------------------------------------------

Status MakeSocket(int family, int* out) {
  int fd = ::socket(family, SOCK_STREAM, 0);
  if (fd < 0) return Status::TCP("socket() failed: " + std::string(strerror(errno)));
  *out = fd;
  return Status::Ok();
}

// Connection preamble: both chunk-map inputs (nstreams AND min_chunksize)
// travel with the sender so the two sides can never compute divergent chunk
// boundaries from mismatched env config — the sender's values win.
struct Preamble {
  uint64_t bundle_id = 0;
  uint64_t stream_id = 0;
  uint64_t nstreams = 0;
  uint64_t min_chunksize = 0;
};

Status WritePreamble(int fd, const Preamble& p) {
  uint8_t buf[40];
  EncodeU64BE(kWireMagic, buf);
  EncodeU64BE(p.bundle_id, buf + 8);
  EncodeU64BE(p.stream_id, buf + 16);
  EncodeU64BE(p.nstreams, buf + 24);
  EncodeU64BE(p.min_chunksize, buf + 32);
  return WriteAll(fd, buf, sizeof(buf));
}

Status ReadPreamble(int fd, Preamble* p, int timeout_ms) {
  uint8_t buf[40];
  // Hard deadline over the whole 40 bytes — a slow-loris client trickling
  // one byte per interval cannot stretch this past timeout_ms.
  Status s = ReadExactDeadline(fd, buf, sizeof(buf), timeout_ms);
  if (!s.ok()) return s;
  if (DecodeU64BE(buf) != kWireMagic) {
    return Status::TCP("bad wire magic — peer is not tpunet or version mismatch");
  }
  p->bundle_id = DecodeU64BE(buf + 8);
  p->stream_id = DecodeU64BE(buf + 16);
  p->nstreams = DecodeU64BE(buf + 24);
  p->min_chunksize = DecodeU64BE(buf + 32);
  if (p->nstreams == 0 || p->nstreams > kMaxStreams || p->stream_id > p->nstreams ||
      p->min_chunksize == 0) {
    return Status::TCP("malformed preamble: nstreams=" + std::to_string(p->nstreams) +
                       " stream_id=" + std::to_string(p->stream_id));
  }
  return Status::Ok();
}

uint64_t RandomBundleId() {
  static std::atomic<uint64_t> ctr{1};
  std::random_device rd;
  uint64_t hi = (static_cast<uint64_t>(rd()) << 32) ^ rd();
  return hi ^ (ctr.fetch_add(1) << 1) ^ (static_cast<uint64_t>(::getpid()) << 40);
}

// ---------------------------------------------------------------------------

class BasicEngine : public Net {
 public:
  BasicEngine()
      : nics_(FindInterfaces()),
        // Reference defaults: nstreams=2 (nthread:228-231), min_chunksize=1MiB
        // (nthread:232-235).
        nstreams_(GetEnvU64("TPUNET_NSTREAMS", GetEnvU64("BAGUA_NET_NSTREAMS", 2))),
        min_chunksize_(GetEnvU64("TPUNET_MIN_CHUNKSIZE",
                                 GetEnvU64("BAGUA_NET_MIN_CHUNKSIZE", 1 << 20))),
        spin_(GetEnvU64("TPUNET_SPIN", 0) != 0) {
    if (nstreams_ == 0) nstreams_ = 1;
    if (nstreams_ > kMaxStreams) nstreams_ = kMaxStreams;
    if (min_chunksize_ == 0) min_chunksize_ = 1;
  }

  ~BasicEngine() override {
    for (auto& c : send_comms_.DrainAll()) c->Shutdown();
    for (auto& c : recv_comms_.DrainAll()) c->Shutdown();
    // Wake any thread still parked in accept() — mirror of close_listen;
    // without this, destroying the engine would strand it forever.
    for (auto& lc : listen_comms_.DrainAll()) {
      lc->closed.store(true, std::memory_order_release);
      if (lc->wake_fd >= 0) {
        uint64_t one = 1;
        (void)!::write(lc->wake_fd, &one, sizeof(one));
      }
    }
  }

  int32_t devices() override { return static_cast<int32_t>(nics_.size()); }

  Status get_properties(int32_t dev, NetProperties* props) override {
    if (dev < 0 || dev >= static_cast<int32_t>(nics_.size())) {
      return Status::Invalid("bad device index " + std::to_string(dev));
    }
    const NicInfo& nic = nics_[dev];
    props->name = nic.name;
    props->pci_path = nic.pci_path;
    props->guid = static_cast<uint64_t>(dev);
    props->ptr_support = 1;  // host memory only
    props->speed_mbps = nic.speed_mbps;
    props->port = 0;
    props->max_comms = 65536;
    return Status::Ok();
  }

  Status listen(int32_t dev, SocketHandle* handle, uint64_t* listen_comm) override {
    if (dev < 0 || dev >= static_cast<int32_t>(nics_.size())) {
      return Status::Invalid("bad device index " + std::to_string(dev));
    }
    const NicInfo& nic = nics_[dev];
    int fd = -1;
    Status s = MakeSocket(nic.addr.ss_family, &fd);
    if (!s.ok()) return s;
    int one = 1;
    setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    // Bind to the NIC's address with an ephemeral port; the resulting
    // sockaddr IS the rendezvous handle (reference: nthread:259-303).
    sockaddr_storage bind_addr = nic.addr;
    if (bind_addr.ss_family == AF_INET) {
      reinterpret_cast<sockaddr_in*>(&bind_addr)->sin_port = 0;
    } else {
      reinterpret_cast<sockaddr_in6*>(&bind_addr)->sin6_port = 0;
    }
    if (::bind(fd, reinterpret_cast<sockaddr*>(&bind_addr), nic.addrlen) != 0) {
      ::close(fd);
      return Status::TCP("bind failed: " + std::string(strerror(errno)));
    }
    if (::listen(fd, kListenBacklog) != 0) {
      ::close(fd);
      return Status::TCP("listen failed: " + std::string(strerror(errno)));
    }
    auto lc = std::make_shared<ListenComm>();
    lc->fd = fd;
    lc->wake_fd = ::eventfd(0, EFD_CLOEXEC);
    if (lc->wake_fd < 0) {
      // Without the wake fd close_listen could never abort a parked accept().
      return Status::TCP("eventfd failed: " + std::string(strerror(errno)));
    }
    SetNonblocking(fd);  // accept() polls first; EAGAIN is handled
    lc->dev = dev;
    handle->addrlen = nic.addrlen;
    if (getsockname(fd, reinterpret_cast<sockaddr*>(&handle->addr), &handle->addrlen) != 0) {
      return Status::TCP("getsockname failed: " + std::string(strerror(errno)));
    }
    uint64_t id = next_id_.fetch_add(1);
    listen_comms_.Put(id, lc);
    *listen_comm = id;
    return Status::Ok();
  }

  Status connect(int32_t dev, const SocketHandle& handle, uint64_t* send_comm) override {
    if (dev < 0 || dev >= static_cast<int32_t>(nics_.size())) {
      return Status::Invalid("bad device index " + std::to_string(dev));
    }
    auto comm = std::make_shared<Comm>();
    comm->is_send = true;
    comm->nstreams = nstreams_;
    comm->min_chunksize = min_chunksize_;
    comm->spin = spin_;
    uint64_t bundle = RandomBundleId();

    // nstreams data connections, each introducing itself with its stream id
    // (reference: nthread:313-327), then the ctrl connection with
    // stream_id == nstreams (reference: nthread:366-380).
    for (uint64_t sid = 0; sid <= nstreams_; ++sid) {
      int fd = -1;
      Status s = ConnectOne(dev, handle, &fd);
      if (!s.ok()) {
        comm->Shutdown();
        return s;
      }
      s = WritePreamble(fd, Preamble{bundle, sid, nstreams_, min_chunksize_});
      if (s.ok() && spin_) s = SetNonblocking(fd);  // only after the blocking preamble write
      if (!s.ok()) {
        ::close(fd);
        comm->Shutdown();
        return s;
      }
      if (sid < nstreams_) {
        auto w = std::make_unique<StreamWorker>();
        w->fd = fd;
        comm->workers.push_back(std::move(w));
      } else {
        comm->ctrl_fd = fd;
      }
    }
    StartThreads(comm.get());
    uint64_t id = next_id_.fetch_add(1);
    send_comms_.Put(id, comm);
    *send_comm = id;
    return Status::Ok();
  }

  Status accept(uint64_t listen_comm, uint64_t* recv_comm) override {
    ListenPtr lc;
    if (!listen_comms_.Get(listen_comm, &lc)) {
      return Status::Invalid("unknown listen comm " + std::to_string(listen_comm));
    }
    // Accept connections, grouping by bundle id, until one bundle is whole
    // (reference accepts exactly nstreams+1 and keys by raw id,
    // nthread:425-522; bundles make concurrent senders safe).
    std::lock_guard<std::mutex> accept_lk(lc->mu);
    uint64_t expiry_ms = 2 * GetEnvU64("TPUNET_HANDSHAKE_TIMEOUT_MS", 10000);
    while (true) {
      // Expire half-arrived bundles from dead senders so their parked fds
      // don't accumulate toward RLIMIT_NOFILE on a long-lived listen comm.
      auto now = std::chrono::steady_clock::now();
      for (auto it = lc->partials.begin(); it != lc->partials.end();) {
        if (!it->second.Complete() &&
            now - it->second.first_seen > std::chrono::milliseconds(expiry_ms)) {
          it->second.CloseAll();
          it = lc->partials.erase(it);
        } else {
          ++it;
        }
      }
      for (auto it = lc->partials.begin(); it != lc->partials.end(); ++it) {
        if (it->second.Complete()) {
          PartialBundle b = std::move(it->second);
          lc->partials.erase(it);
          return BuildRecvComm(b, recv_comm);
        }
      }
      // poll so close_listen can abort us via the eventfd (a blocked
      // ::accept is not reliably interruptible by shutdown() on Linux).
      // Finite timeout so the expiry sweep above runs even with no events.
      struct pollfd pfds[2] = {{lc->fd, POLLIN, 0}, {lc->wake_fd, POLLIN, 0}};
      int pr = ::poll(pfds, 2, 1000);
      if (pr < 0) {
        if (errno == EINTR) continue;
        return Status::TCP("poll failed: " + std::string(strerror(errno)));
      }
      if (pr == 0) continue;  // timeout tick: re-run expiry sweep
      if (lc->closed.load(std::memory_order_acquire) || (pfds[1].revents & POLLIN)) {
        return Status::Inner("listen comm closed while accepting");
      }
      if (!(pfds[0].revents & POLLIN)) continue;
      sockaddr_storage peer;
      socklen_t plen = sizeof(peer);
      int fd = ::accept(lc->fd, reinterpret_cast<sockaddr*>(&peer), &plen);
      if (fd < 0) {
        if (errno == EINTR || errno == EAGAIN || errno == EWOULDBLOCK) continue;
        return Status::TCP("accept failed: " + std::string(strerror(errno)));
      }
      Status s = SetNodelay(fd);
      if (!s.ok()) {
        ::close(fd);
        return s;
      }
      // Bound the preamble read: a client that connects but never completes
      // the 40-byte handshake (scanner, stalled peer) must not wedge accept()
      // while it holds lc->mu. Malformed/timed-out clients are dropped and
      // accept keeps serving legitimate peers.
      uint64_t handshake_ms = GetEnvU64("TPUNET_HANDSHAKE_TIMEOUT_MS", 10000);
      Preamble p;
      s = ReadPreamble(fd, &p, static_cast<int>(handshake_ms));
      if (!s.ok()) {
        ::close(fd);
        continue;
      }
      PartialBundle& b = lc->partials[p.bundle_id];
      if (b.nstreams == UINT64_MAX) {
        b.nstreams = p.nstreams;
        b.min_chunksize = p.min_chunksize;
        b.first_seen = std::chrono::steady_clock::now();
      } else if (b.nstreams != p.nstreams || b.min_chunksize != p.min_chunksize) {
        ::close(fd);  // inconsistent members: drop the whole bundle
        b.CloseAll();
        lc->partials.erase(p.bundle_id);
        continue;
      }
      if (p.stream_id == p.nstreams) {
        if (b.ctrl_fd >= 0) {
          ::close(fd);  // duplicate ctrl stream: keep the first
          continue;
        }
        b.ctrl_fd = fd;
      } else if (!b.data_fds.emplace(p.stream_id, fd).second) {
        ::close(fd);  // duplicate stream id: keep the first, drop the dup
        continue;
      }
    }
  }

  Status isend(uint64_t send_comm, const void* data, size_t nbytes, uint64_t* request) override {
    CommPtr c;
    if (!send_comms_.Get(send_comm, &c)) {
      return Status::Invalid("unknown send comm " + std::to_string(send_comm));
    }
    auto state = std::make_shared<RequestState>();
    uint64_t id = next_id_.fetch_add(1);
    requests_.Put(id, state);
    c->msgs.Push(Msg{const_cast<uint8_t*>(static_cast<const uint8_t*>(data)), nbytes, state});
    *request = id;
    return Status::Ok();
  }

  Status irecv(uint64_t recv_comm, void* data, size_t nbytes, uint64_t* request) override {
    CommPtr c;
    if (!recv_comms_.Get(recv_comm, &c)) {
      return Status::Invalid("unknown recv comm " + std::to_string(recv_comm));
    }
    auto state = std::make_shared<RequestState>();
    uint64_t id = next_id_.fetch_add(1);
    requests_.Put(id, state);
    c->msgs.Push(Msg{static_cast<uint8_t*>(data), nbytes, state});
    *request = id;
    return Status::Ok();
  }

  Status test(uint64_t request, bool* done, size_t* nbytes) override {
    RequestPtr state;
    if (!requests_.Get(request, &state)) {
      return Status::Invalid("unknown request " + std::to_string(request));
    }
    if (state->failed.load(std::memory_order_acquire)) {
      // Surface the error only once all dispatched chunk workers have
      // quiesced on this request — otherwise the caller could free/reuse the
      // buffer while a stream worker is still reading into it.
      if (!state->Done()) {
        *done = false;
        return Status::Ok();
      }
      requests_.Erase(request);
      return Status::Inner("request failed: " + state->ErrorMsg());
    }
    *done = state->Done();
    if (*done) {
      if (nbytes) *nbytes = state->nbytes.load(std::memory_order_acquire);
      requests_.Erase(request);  // reference leaked these (bagua_net.cc:111-121)
    }
    return Status::Ok();
  }

  Status close_send(uint64_t send_comm) override {
    CommPtr c;
    if (!send_comms_.Take(send_comm, &c)) {
      return Status::Invalid("unknown send comm " + std::to_string(send_comm));
    }
    c->Shutdown();
    return Status::Ok();
  }

  Status close_recv(uint64_t recv_comm) override {
    CommPtr c;
    if (!recv_comms_.Take(recv_comm, &c)) {
      return Status::Invalid("unknown recv comm " + std::to_string(recv_comm));
    }
    c->Shutdown();
    return Status::Ok();
  }

  Status close_listen(uint64_t listen_comm) override {
    ListenPtr lc;
    if (!listen_comms_.Take(listen_comm, &lc)) {
      return Status::Invalid("unknown listen comm " + std::to_string(listen_comm));
    }
    // Wake any thread parked in accept(); it returns "listen comm closed".
    lc->closed.store(true, std::memory_order_release);
    if (lc->wake_fd >= 0) {
      uint64_t one = 1;
      (void)!::write(lc->wake_fd, &one, sizeof(one));
    }
    return Status::Ok();
  }

 private:
  Status ConnectOne(int32_t dev, const SocketHandle& handle, int* out_fd) {
    int fd = -1;
    Status s = MakeSocket(handle.addr.ss_family, &fd);
    if (!s.ok()) return s;
    // Route out of the chosen NIC when address families line up.
    const NicInfo& nic = nics_[dev];
    if (nic.addr.ss_family == handle.addr.ss_family && nic.name != "lo") {
      sockaddr_storage local = nic.addr;
      if (local.ss_family == AF_INET) {
        reinterpret_cast<sockaddr_in*>(&local)->sin_port = 0;
      } else {
        reinterpret_cast<sockaddr_in6*>(&local)->sin6_port = 0;
      }
      ::bind(fd, reinterpret_cast<sockaddr*>(&local), nic.addrlen);  // best effort
    }
    // addrlen is derived from the family, not trusted from the handle: a
    // handle marshaled through the 64-byte wire blob (C ABI / ncclNet shim)
    // carries only the sockaddr bytes.
    socklen_t alen = AddrLenForFamily(handle.addr);
    if (::connect(fd, reinterpret_cast<const sockaddr*>(&handle.addr), alen) != 0) {
      // POSIX: after EINTR the connect proceeds asynchronously — retrying
      // ::connect() yields EALREADY. Wait for writability + check SO_ERROR.
      bool pending = (errno == EINTR || errno == EINPROGRESS || errno == EALREADY);
      if (!pending) {
        ::close(fd);
        return Status::TCP("connect to " + SockaddrToString(handle.addr, alen) +
                           " failed: " + std::string(strerror(errno)));
      }
      struct pollfd pfd = {fd, POLLOUT, 0};
      int pr;
      do {
        pr = ::poll(&pfd, 1, -1);
      } while (pr < 0 && errno == EINTR);
      int soerr = 0;
      socklen_t slen = sizeof(soerr);
      if (pr < 0 || getsockopt(fd, SOL_SOCKET, SO_ERROR, &soerr, &slen) != 0 || soerr != 0) {
        ::close(fd);
        return Status::TCP("connect to " + SockaddrToString(handle.addr, alen) +
                           " failed: " + std::string(strerror(soerr ? soerr : errno)));
      }
    }
    s = SetNodelay(fd);  // reference: nthread:329
    if (!s.ok()) {
      ::close(fd);
      return s;
    }
    *out_fd = fd;
    return Status::Ok();
  }

  void StartThreads(Comm* c) {
    bool spin = c->spin;
    for (auto& w : c->workers) {
      StreamWorker* wp = w.get();
      wp->comm = c;
      w->thread = c->is_send ? std::thread(SendWorkerLoop, wp, spin)
                             : std::thread(RecvWorkerLoop, wp, spin);
    }
    c->scheduler = c->is_send ? std::thread(SendSchedulerLoop, c) : std::thread(RecvSchedulerLoop, c);
  }

  Status BuildRecvComm(PartialBundle& b, uint64_t* recv_comm) {
    auto comm = std::make_shared<Comm>();
    comm->is_send = false;
    // Sender's chunk-map inputs win — carried in the preamble so both sides
    // always partition messages identically (SURVEY hard-part #2).
    comm->nstreams = b.nstreams;
    comm->min_chunksize = b.min_chunksize;
    comm->spin = spin_;
    comm->ctrl_fd = b.ctrl_fd;
    b.ctrl_fd = -1;
    if (spin_) SetNonblocking(comm->ctrl_fd);  // ctrl carries the latency-critical length frame
    // Data streams ordered by stream id (reference: BTreeMap nthread:432).
    for (auto& kv : b.data_fds) {
      auto w = std::make_unique<StreamWorker>();
      w->fd = kv.second;
      if (spin_) SetNonblocking(w->fd);
      comm->workers.push_back(std::move(w));
    }
    b.data_fds.clear();
    StartThreads(comm.get());
    uint64_t id = next_id_.fetch_add(1);
    recv_comms_.Put(id, comm);
    *recv_comm = id;
    return Status::Ok();
  }

  std::vector<NicInfo> nics_;
  uint64_t nstreams_;
  uint64_t min_chunksize_;
  bool spin_;
  std::atomic<uint64_t> next_id_{1};
  IdMap<CommPtr> send_comms_;
  IdMap<CommPtr> recv_comms_;
  IdMap<ListenPtr> listen_comms_;
  IdMap<RequestPtr> requests_;
};

}  // namespace

std::unique_ptr<Net> CreateBasicEngine() { return std::make_unique<BasicEngine>(); }

std::unique_ptr<Net> CreateEngine() {
  // Engine seam (reference: src/lib.rs:20-29 BAGUA_NET_IMPLEMENT
  // BASIC|TOKIO); ours is TPUNET_IMPLEMENT BASIC|EPOLL.
  std::string impl = GetEnv("TPUNET_IMPLEMENT", GetEnv("BAGUA_NET_IMPLEMENT", "BASIC"));
  if (impl == "EPOLL") return CreateEpollEngine();
  return CreateBasicEngine();
}

}  // namespace tpunet
