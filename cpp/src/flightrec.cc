// tpunet flight recorder implementation. See flightrec.h for the contract.
#include "flightrec.h"

#include <errno.h>
#include <fcntl.h>
#include <signal.h>
#include <string.h>
#include <unistd.h>

#include <mutex>
#include <new>

#include "tpunet/utils.h"

namespace tpunet {
namespace flightrec {

namespace internal {
std::atomic<Ring*> g_ring{nullptr};
std::atomic<bool> g_disabled{false};
}  // namespace internal

namespace {

// Resolved once at init so the SIGUSR2 handler never calls getenv/malloc:
// the default dump path, rank, and host id live in static storage.
char g_default_path[512] = "tpunet-flightrec-rank0.json";
char g_default_dir[384] = ".";
uint64_t g_rank = 0;
uint64_t g_host = 0;
std::atomic<uint64_t> g_last_verdict_dump_us{0};
std::once_flag g_init_once;

// Hand-rolled async-signal-safe formatting: none of printf is guaranteed
// safe in signal context, and the dumper must run there.
size_t U64ToDec(uint64_t v, char* out) {
  char tmp[20];
  size_t n = 0;
  do {
    tmp[n++] = static_cast<char>('0' + v % 10);
    v /= 10;
  } while (v != 0);
  for (size_t i = 0; i < n; ++i) out[i] = tmp[n - 1 - i];
  return n;
}

size_t U64ToHex16(uint64_t v, char* out) {
  static const char* digits = "0123456789abcdef";
  for (int i = 15; i >= 0; --i) {
    out[i] = digits[v & 0xf];
    v >>= 4;
  }
  return 16;
}

// Buffered raw-syscall writer (one write() per ~4KiB, not per fragment).
struct Writer {
  int fd = -1;
  size_t len = 0;
  bool failed = false;
  char buf[4096];

  void Flush() {
    size_t off = 0;
    while (off < len) {
      ssize_t w = ::write(fd, buf + off, len - off);
      if (w < 0) {
        if (errno == EINTR) continue;
        failed = true;
        break;
      }
      off += static_cast<size_t>(w);
    }
    len = 0;
  }
  void Put(const char* s, size_t n) {
    if (failed) return;
    while (n > 0) {
      size_t room = sizeof(buf) - len;
      size_t take = n < room ? n : room;
      memcpy(buf + len, s, take);
      len += take;
      s += take;
      n -= take;
      if (len == sizeof(buf)) Flush();
    }
  }
  void Str(const char* s) { Put(s, strlen(s)); }
  void Dec(uint64_t v) {
    char tmp[20];
    Put(tmp, U64ToDec(v, tmp));
  }
  void Hex(uint64_t v) {
    char tmp[16];
    Put(tmp, U64ToHex16(v, tmp));
  }
};

const char* EvName(uint8_t kind) {
  switch (static_cast<Ev>(kind)) {
    case Ev::kCollSubmit: return "coll_submit";
    case Ev::kPhaseEnter: return "phase_enter";
    case Ev::kPhaseExit: return "phase_exit";
    case Ev::kWireSend: return "wire_send";
    case Ev::kWireRecv: return "wire_recv";
    case Ev::kQosGrant: return "qos_grant";
    case Ev::kQosPause: return "qos_pause";
    case Ev::kQosWait: return "qos_wait";
    case Ev::kQosPreempt: return "qos_preempt";
    case Ev::kFailover: return "failover";
    case Ev::kRestripe: return "restripe";
    case Ev::kRewirePhase: return "rewire_phase";
    case Ev::kSwapPhase: return "swap_phase";
    case Ev::kCrcError: return "crc_error";
    case Ev::kFault: return "fault";
    case Ev::kReqStart: return "req_start";
    case Ev::kReqDone: return "req_done";
    case Ev::kVerdict: return "verdict";
  }
  return "unknown";
}

void SigusrDump(int /*signum*/) {
  int saved_errno = errno;
  (void)Dump(nullptr, "sigusr2", nullptr, 0);
  errno = saved_errno;
}

void InitOnce() {
  uint64_t want = GetEnvU64("TPUNET_FLIGHTREC_EVENTS", 16384);
  if (want == 0) {
    internal::g_disabled.store(true, std::memory_order_release);
    return;
  }
  uint64_t cap = 8;
  while (cap < want && cap < (1ull << 24)) cap <<= 1;

  g_rank = GetEnvU64("TPUNET_RANK", GetEnvU64("RANK", 0));
  g_host = HostId();
  // Dump-dir resolution: TPUNET_FLIGHTREC_DIR (dump routing only — set by
  // the test harness so verdict dumps land under tmp_path, never the CWD a
  // suite runs from), else TPUNET_TRACE_DIR (a job that traces wants its
  // verdict dumps beside the trace files tools/postmortem merges), else the
  // CWD. Resolved once here so the SIGUSR2 path never calls getenv.
  std::string dir = GetEnv("TPUNET_FLIGHTREC_DIR", GetEnv("TPUNET_TRACE_DIR", "."));
  if (dir.empty() || dir.size() >= sizeof(g_default_dir)) dir = ".";
  memcpy(g_default_dir, dir.c_str(), dir.size() + 1);
  char* p = g_default_path;
  memcpy(p, dir.data(), dir.size());
  p += dir.size();
  static const char kStem[] = "/tpunet-flightrec-rank";
  memcpy(p, kStem, sizeof(kStem) - 1);
  p += sizeof(kStem) - 1;
  p += U64ToDec(g_rank, p);
  static const char kExt[] = ".json";
  memcpy(p, kExt, sizeof(kExt));

  // Leaked on purpose (like the Telemetry singleton): hot paths may record
  // during static teardown, so the ring must never be freed.
  Ring* r = new Ring();
  r->slots = new Event[cap];
  r->capacity = cap;
  r->mask = cap - 1;

  // SIGUSR2 = dump-now. SA_RESTART so a dump doesn't surface EINTR on the
  // engines' blocking syscalls.
  struct sigaction sa;
  memset(&sa, 0, sizeof(sa));
  sa.sa_handler = SigusrDump;
  sa.sa_flags = SA_RESTART;
  sigemptyset(&sa.sa_mask);
  (void)sigaction(SIGUSR2, &sa, nullptr);

  internal::g_ring.store(r, std::memory_order_release);
}

}  // namespace

namespace internal {

Ring* InitRing() {
  std::call_once(g_init_once, InitOnce);
  return g_ring.load(std::memory_order_acquire);
}

void RecordIn(Ring* r, Ev kind, uint64_t a, uint64_t b, uint64_t c, uint32_t d,
              const char* name) {
  uint64_t idx = r->cursor.fetch_add(1, std::memory_order_relaxed);
  Event& e = r->slots[idx & r->mask];
  // Invalidate first so a dump racing this write sees a torn slot, not a
  // half-old half-new event wearing a valid seq.
  e.seq.store(0, std::memory_order_release);
  e.t_us.store(MonotonicUs(), std::memory_order_relaxed);
  e.a.store(a, std::memory_order_relaxed);
  e.b.store(b, std::memory_order_relaxed);
  e.c.store(c, std::memory_order_relaxed);
  e.d.store(d, std::memory_order_relaxed);
  e.name.store(name, std::memory_order_relaxed);
  e.kind.store(static_cast<uint8_t>(kind), std::memory_order_relaxed);
  e.seq.store(idx + 1, std::memory_order_release);
}

}  // namespace internal

int Dump(const char* dir, const char* reason, char* out_path, uint64_t cap) {
  Ring* r = internal::g_ring.load(std::memory_order_acquire);
  if (r == nullptr) return 0;

  char path[512];
  if (dir != nullptr && dir[0] != '\0') {
    size_t dn = strlen(dir);
    char tail[64];
    char* t = tail;
    static const char kStem[] = "/tpunet-flightrec-rank";
    memcpy(t, kStem, sizeof(kStem) - 1);
    t += sizeof(kStem) - 1;
    t += U64ToDec(g_rank, t);
    static const char kExt[] = ".json";
    memcpy(t, kExt, sizeof(kExt));
    size_t tn = strlen(tail);
    if (dn + tn + 1 > sizeof(path)) return 0;
    memcpy(path, dir, dn);
    memcpy(path + dn, tail, tn + 1);
  } else {
    memcpy(path, g_default_path, sizeof(g_default_path));
  }

  int fd = ::open(path, O_WRONLY | O_CREAT | O_TRUNC | O_CLOEXEC, 0644);
  if (fd < 0) return 0;

  // Snapshot the claim cursor; slots in [first, cur) are the live window.
  // Writers may keep claiming while we copy — their slots fail the seq
  // check and count as torn instead of emitting garbage.
  uint64_t cur = r->cursor.load(std::memory_order_acquire);
  uint64_t first = cur > r->capacity ? cur - r->capacity : 0;

  Writer w;
  w.fd = fd;
  w.Str("{\"schema\":\"tpunet-flightrec-v1\",\"rank\":");
  w.Dec(g_rank);
  w.Str(",\"host\":\"");
  w.Hex(g_host);
  w.Str("\",\"reason\":\"");
  w.Str(reason != nullptr ? reason : "on_demand");
  w.Str("\",\"capacity\":");
  w.Dec(r->capacity);
  w.Str(",\"recorded\":");
  w.Dec(cur);
  w.Str(",\"dropped\":");
  w.Dec(first);
  // The torn count is only known after the scan, so it is emitted as the
  // key AFTER the events array (single pass, no seek-and-patch).
  w.Str(",\"events\":[");
  uint64_t torn = 0;
  bool first_ev = true;
  for (uint64_t g = first; g < cur; ++g) {
    Event& e = r->slots[g & r->mask];
    if (e.seq.load(std::memory_order_acquire) != g + 1) {
      ++torn;
      continue;
    }
    uint64_t t_us = e.t_us.load(std::memory_order_relaxed);
    uint64_t a = e.a.load(std::memory_order_relaxed);
    uint64_t b = e.b.load(std::memory_order_relaxed);
    uint64_t c = e.c.load(std::memory_order_relaxed);
    uint32_t d = e.d.load(std::memory_order_relaxed);
    const char* name = e.name.load(std::memory_order_relaxed);
    uint8_t kind = e.kind.load(std::memory_order_relaxed);
    if (e.seq.load(std::memory_order_acquire) != g + 1) {
      ++torn;  // writer lapped the slot mid-copy
      continue;
    }
    if (!first_ev) w.Str(",");
    first_ev = false;
    w.Str("\n{\"t\":");
    w.Dec(t_us);
    w.Str(",\"kind\":\"");
    w.Str(EvName(kind));
    w.Str("\",\"a\":");
    w.Dec(a);
    w.Str(",\"b\":");
    w.Dec(b);
    w.Str(",\"c\":");
    w.Dec(c);
    w.Str(",\"d\":");
    w.Dec(d);
    if (name != nullptr) {
      w.Str(",\"name\":\"");
      w.Str(name);
      w.Str("\"");
    }
    w.Str("}");
  }
  w.Str("\n],\"torn\":");
  w.Dec(torn);
  w.Str("}\n");
  w.Flush();
  (void)::close(fd);
  if (w.failed) return 0;

  size_t pn = strlen(path);
  if (out_path != nullptr && cap > 0) {
    size_t n = pn < cap - 1 ? pn : cap - 1;
    memcpy(out_path, path, n);
    out_path[n] = '\0';
  }
  return static_cast<int>(pn);
}

void DumpOnVerdict(const char* reason, uint64_t err_kind) {
  Record(Ev::kVerdict, err_kind, 0, 0, 0, reason);
  Ring* r = internal::g_ring.load(std::memory_order_acquire);
  if (r == nullptr) return;
  // One dump per second: an error storm (every request of every comm timing
  // out at once) produces one file per window, not a disk flood.
  uint64_t now = MonotonicUs();
  uint64_t last = g_last_verdict_dump_us.load(std::memory_order_relaxed);
  if (last != 0 && now - last < 1000000) return;
  if (!g_last_verdict_dump_us.compare_exchange_strong(
          last, now, std::memory_order_relaxed)) {
    return;  // a sibling verdict in this window owns the dump
  }
  (void)Dump(nullptr, reason, nullptr, 0);
}

void Stats(uint64_t* recorded, uint64_t* capacity) {
  Ring* r = internal::g_ring.load(std::memory_order_acquire);
  if (recorded != nullptr) {
    *recorded = r != nullptr ? r->cursor.load(std::memory_order_relaxed) : 0;
  }
  if (capacity != nullptr) *capacity = r != nullptr ? r->capacity : 0;
}

}  // namespace flightrec
}  // namespace tpunet
