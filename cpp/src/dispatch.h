// Collective schedule dispatch: which algorithm runs a given
// (collective, payload size, world) — the per-size auto-selector that turns
// the ring from "the" AllReduce into one schedule among several
// (docs/DESIGN.md "Schedules & algorithm selection").
//
// Three layers of precedence, strongest first:
//   1. per-communicator override (tpunet_comm_create_ex algo= / TPUNET_ALGO)
//      — anything but "auto" pins every collective to that schedule;
//   2. a dispatch table loaded from TPUNET_DISPATCH_TABLE (JSON written by
//      `busbw_sweep --emit-dispatch`, the offline-tuned thresholds);
//   3. built-in thresholds (kept deliberately coarse — they encode the
//      step-count asymptotics, not this box's microseconds).
//
// The resolved choice must agree across ranks (different schedules
// deadlock), so the communicator handshake negotiates (override, table CRC)
// at wiring time exactly like the wire codec — a disagreement fails every
// rank identically before any payload moves.
#ifndef TPUNET_SRC_DISPATCH_H_
#define TPUNET_SRC_DISPATCH_H_

#include <cstddef>
#include <cstdint>
#include <string>
#include <vector>

#include "tpunet/net.h"

namespace tpunet {

// Values cross the bootstrap handshake as one byte; keep them stable.
// kHier is the two-level schedule (intra-host stage + one-rank-per-host
// inter-host stage, docs/DESIGN.md "Hierarchical collectives"); it needs a
// hierarchical topology (>= 2 hosts, uniform ranks/host) and resolves back
// to ring where the topology is flat. kHierA2a and kPairwise are AllToAll
// shapes (docs/DESIGN.md "Hierarchical AllToAll"): kPairwise is the direct
// per-peer mesh exchange, kHierA2a the two-stage intra-host regroup +
// one-rank-per-host inter-host transpose; for AllToAll, kRing names the
// store-and-forward relay.
enum class CollAlgo : uint8_t {
  kAuto = 0,
  kRing = 1,
  kRhd = 2,
  kTree = 3,
  kHier = 4,
  kHierA2a = 5,
  kPairwise = 6,
};
constexpr int kCollAlgoCount = 7;  // including kAuto

enum class CollKind : uint8_t { kAllReduce = 0, kBroadcast = 1, kAllToAll = 2 };
constexpr int kCollKindCount = 3;

// "auto" / "ring" / "rhd" / "tree" / "hier" / "hier_a2a" / "pairwise"
// <-> CollAlgo. Parse returns false on an unknown name.
bool ParseCollAlgo(const std::string& name, CollAlgo* out);
const char* CollAlgoName(CollAlgo a);
const char* CollKindName(CollKind c);

// One dispatch rule: first entry whose (coll, world, max_bytes) matches the
// call wins. world 0 matches any world; max_bytes 0 means "no upper bound".
struct DispatchEntry {
  CollKind coll = CollKind::kAllReduce;
  int world = 0;
  uint64_t max_bytes = 0;
  CollAlgo algo = CollAlgo::kRing;
};

struct DispatchTable {
  std::vector<DispatchEntry> entries;
  uint32_t crc = 0;  // CRC32C of the source file bytes — the handshake key
  bool loaded = false;
};

// Parse the `busbw_sweep --emit-dispatch` JSON:
//   {"version": 1, "entries": [
//      {"coll": "allreduce", "world": 8, "max_bytes": 8192, "algo": "tree"},
//      ...]}
// Unknown collective/algo names, nested values, or syntax errors are
// kInvalidArgument with the offending token in the message — a malformed
// table must fail communicator creation loudly, not silently fall back.
Status ParseDispatchTable(const std::string& json, DispatchTable* out);
// Read `path`, parse it, and stamp out->crc with the file bytes' CRC32C.
Status LoadDispatchTableFile(const std::string& path, DispatchTable* out);

// Resolve the schedule for one collective call. `override_algo` != kAuto
// wins outright; then the table; then built-ins. Never returns kAuto.
CollAlgo SelectCollAlgo(const DispatchTable& table, CollAlgo override_algo,
                        CollKind coll, uint64_t nbytes, int world);

// Topology post-pass on the resolved schedule (the selector is pure
// (coll, size, world) — host grouping lives in the communicator):
//   * kHier on a flat/irregular topology (!usable) degrades to ring — the
//     counter then records what RAN, the bcast-rhd-fallback stance.
//   * Under pure built-in auto selection (no override, no table), a
//     large-payload ring AllReduce on a PROFITABLE hierarchy (>= 2 hosts,
//     uniform R >= 2 ranks/host) upgrades to hier: the intra-host stages
//     ride shared memory / loopback while per-rank DCN wire bytes drop by
//     ~R x. Deterministic from negotiated state, so every rank agrees.
//   * kAllToAll: kHier is read as kHierA2a (the "hier" spelling works for
//     both collectives); kHierA2a on a flat topology degrades to kPairwise;
//     built-in auto on a USABLE hierarchy upgrades kPairwise to kHierA2a
//     (DCN connection count drops from R(H-1) to H-1 per rank and the
//     per-peer shards aggregate R-fold — the MoE-dispatch shape); rhd/tree
//     verdicts for an AllToAll have no meaning and degrade to kPairwise.
CollAlgo ApplyHierPolicy(CollAlgo a, CollKind coll, uint64_t nbytes,
                         bool usable, bool profitable, bool builtin_auto);

// ---- Counters --------------------------------------------------------------
// tpunet_coll_steps_total{algo}: sequential wire rounds executed by THIS
// rank, per schedule — the noise-immune form of the latency claim (ring
// AllReduce = 2(W-1) rounds; rhd = 2*log2(W') (+2 off a power of two);
// tree <= 2*ceil(log2 W)). tpunet_coll_algo_selected_total{coll,algo}:
// dispatch decisions, labeled by the RESOLVED schedule.
void CountCollSteps(CollAlgo a, uint64_t n = 1);
void CountCollAlgoSelected(CollKind c, CollAlgo a);
// The hierarchical schedule's wire rounds count per STAGE
// (algo="hier.intra" / "hier.inter") — per-rank DCN rounds shrinking while
// intra-host rounds ride shared memory IS the hier claim.
void CountHierSteps(bool inter, uint64_t n = 1);
uint64_t HierStepsTotal(bool inter);
// Hierarchical AllToAll stage rounds (algo="a2a.intra" / "a2a.inter") —
// the inter slot is the DCN transpose round count (H-1 per call vs the
// flat mesh's per-peer message storm).
void CountA2aSteps(bool inter, uint64_t n = 1);
uint64_t A2aStepsTotal(bool inter);
// tpunet_a2a_bytes_total{stage,dir}: AllToAll wire bytes per stage —
// stage 0 = intra (same-host regroup hops, SHM-cheap), 1 = inter (the
// one-rank-per-host DCN transpose), 2 = flat (the pairwise mesh / ring
// relay baseline). dir: 0 = tx, 1 = rx. Every byte-movement claim about
// the hierarchical AllToAll is gated on these, never on wall-clock.
constexpr int kA2aStageCount = 3;
void CountA2aBytes(int stage, int dir, uint64_t nbytes);
uint64_t A2aBytesTotal(int stage, int dir);
uint64_t CollStepsTotal(CollAlgo a);
uint64_t CollAlgoSelectedTotal(CollKind c, CollAlgo a);
void ResetCollDispatchCounters();

}  // namespace tpunet

#endif  // TPUNET_SRC_DISPATCH_H_
