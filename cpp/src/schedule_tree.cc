// Binomial tree schedule over the pairwise mesh: reduce-to-root + binomial
// broadcast AllReduce for small payloads, and binomial Broadcast.
//
// A binomial tree touches each rank in at most ceil(log2 W) rounds per
// phase — the latency-optimal shape for payloads small enough that the
// per-round cost dominates the bytes (every rank ships the WHOLE vector per
// hop, so bandwidth is W*S vs the ring/rhd's 2(W-1)/W * S; the dispatch
// selector only routes the tiny end here). Rank 0 is the AllReduce root
// (no root argument to bias toward); Broadcast trees hang off the caller's
// root via relative ranks.
//
// Wire codec: reduce hops ship encoded partials with fused decode+reduce
// (f32 accumulate, one quantization per hop). The root then quantizes the
// final vector ONCE — encode, decode back into its own buffer — and the
// broadcast phase forwards those encoded bytes verbatim, so every rank
// decodes identical bytes and results are bit-identical across ranks.
#include <string.h>

#include <algorithm>
#include <vector>

#include "coll_comm.h"

namespace tpunet {
namespace internal {

Status ScheduledCommunicator::DoAllReduceTree(const void* sendbuf, void* recvbuf,
                                              size_t count, DType dtype, RedOp op,
                                              uint64_t seq) {
  const size_t esize = DTypeSize(dtype);
  const bool tracing = Telemetry::Get().tracing_enabled();
  PhaseSpan whole(tracing, trace_comm_id_, seq, "allreduce", -1, count * esize);
  Status s = EnsureMeshQuiesced();
  if (!s.ok()) return s;

  uint8_t* data = static_cast<uint8_t*>(recvbuf);
  if (sendbuf != recvbuf) memmove(recvbuf, sendbuf, count * esize);

  const int W = world_;
  const bool codec_on = UseCodec(dtype);
  const WireRedOp wop = ToWireRedOp(op);
  float* data_f = reinterpret_cast<float*>(data);
  const size_t wb = codec_on ? CodecWireBytes(codec_, count) : 0;

  // ---- Phase 1: binomial reduce to rank 0 --------------------------------
  // At mask m a rank whose bit m is set sends its partial to rank-m and is
  // done; otherwise it receives from rank+m (which has already folded in
  // its own subtree — the loop order guarantees it) and reduces. The fold
  // order at each node is child m=1, then m=2, m=4, ... — deterministic, so
  // f32 results are reproducible run to run.
  int step = 0;
  for (uint64_t mask = 1; mask < static_cast<uint64_t>(W); mask <<= 1, ++step) {
    if (rank_ & mask) {
      const int parent = rank_ - static_cast<int>(mask);
      PhaseSpan sp(tracing, trace_comm_id_, seq, "reduce", step, count * esize);
      CountCollSteps(CollAlgo::kTree);
      if (codec_on) {
        mesh_scratch_.reserve(wb);
        CodecEncode(codec_, data_f, mesh_scratch_.data(), count);
        s = MeshSend(parent, mesh_scratch_.data(), wb);
      } else {
        s = MeshSend(parent, data, count * esize);
      }
      if (!s.ok()) return s;
      break;
    }
    const int child = rank_ + static_cast<int>(mask);
    if (child < W) {
      PhaseSpan sp(tracing, trace_comm_id_, seq, "reduce", step, count * esize);
      CountCollSteps(CollAlgo::kTree);
      if (codec_on) {
        mesh_scratch_.reserve(wb);
        s = MeshRecv(child, mesh_scratch_.data(), wb);
        if (!s.ok()) return s;
        CodecDecodeReduce(codec_, data_f, nullptr, mesh_scratch_.data(), count, wop);
      } else {
        mesh_scratch_.reserve(count * esize);
        s = MeshRecv(child, mesh_scratch_.data(), count * esize);
        if (!s.ok()) return s;
        Reduce(data, data, mesh_scratch_.data(), count, dtype, op);
      }
    }
  }

  // ---- Phase 2: binomial broadcast of the result from rank 0 -------------
  // Codec: the root quantizes ONCE (encode, then decode back into its own
  // buffer so the owner holds exactly what peers will decode); the encoded
  // bytes forward verbatim — every rank decodes the same bytes.
  if (codec_on) {
    mesh_enc_.reserve(wb);
    if (rank_ == 0) {
      CodecEncode(codec_, data_f, mesh_enc_.data(), count);
      CodecDecode(codec_, mesh_enc_.data(), data_f, count);
    }
  }
  uint8_t* bcast_buf = codec_on ? mesh_enc_.data() : data;
  const size_t bcast_bytes = codec_on ? wb : count * esize;
  uint64_t mask = 1;
  step = 0;
  while (mask < static_cast<uint64_t>(W)) {
    if (rank_ & mask) {
      const int src = rank_ - static_cast<int>(mask);
      PhaseSpan sp(tracing, trace_comm_id_, seq, "bcast", step, bcast_bytes);
      CountCollSteps(CollAlgo::kTree);
      s = MeshRecv(src, bcast_buf, bcast_bytes);
      if (!s.ok()) return s;
      if (codec_on) CodecDecode(codec_, mesh_enc_.data(), data_f, count);
      break;
    }
    mask <<= 1;
    ++step;
  }
  for (mask >>= 1; mask > 0; mask >>= 1, ++step) {
    const int dst = rank_ + static_cast<int>(mask);
    if (dst < W) {
      PhaseSpan sp(tracing, trace_comm_id_, seq, "bcast", step, bcast_bytes);
      CountCollSteps(CollAlgo::kTree);
      s = MeshSend(dst, bcast_buf, bcast_bytes);
      if (!s.ok()) return s;
    }
  }
  return Status::Ok();
}

Status ScheduledCommunicator::DoBroadcastTree(void* buf, size_t nbytes, int root,
                                              uint64_t seq) {
  const bool tracing = Telemetry::Get().tracing_enabled();
  PhaseSpan whole(tracing, trace_comm_id_, seq, "broadcast", -1, nbytes);
  Status s = EnsureMeshQuiesced();
  if (!s.ok()) return s;

  const int W = world_;
  uint8_t* data = static_cast<uint8_t*>(buf);
  const int relr = (rank_ - root + W) % W;

  // Receive edge: the lowest set bit of the relative rank names the parent;
  // forward edges go to relr + down for each lower power of two (sent in
  // DECREASING order — the farthest child relays the deepest subtree).
  uint64_t mask = 1;
  int src = -1;
  while (mask < static_cast<uint64_t>(W)) {
    if (relr & mask) {
      src = (rank_ - static_cast<int>(mask) + W) % W;
      break;
    }
    mask <<= 1;
  }
  std::vector<int> children;
  for (uint64_t dm = mask >> 1; dm > 0; dm >>= 1) {
    if (relr + dm < static_cast<uint64_t>(W)) {
      children.push_back((rank_ + static_cast<int>(dm)) % W);
    }
  }
  CountCollSteps(CollAlgo::kTree, (src >= 0 ? 1 : 0) + children.size());

  // Chunked store-and-forward: receive chunk c, then isend it to every
  // child while chunk c+1 is inbound — the tree streams the payload like
  // the ring relay does, just over log-depth instead of W-1 hops.
  const size_t nchunks = (nbytes + kBcastChunk - 1) / kBcastChunk;
  std::vector<uint64_t> pending_sends;
  for (size_t c = 0; c < nchunks; ++c) {
    const size_t coff = c * kBcastChunk;
    const size_t clen = std::min(kBcastChunk, nbytes - coff);
    if (src >= 0) {
      Status st = MeshRecv(src, data + coff, clen);
      if (!st.ok()) return DrainSends(pending_sends, st);
    }
    for (int child : children) {
      uint64_t sreq = 0;
      Status st = net_->isend(mesh_send_[child], data + coff, clen, &sreq);
      if (!st.ok()) return DrainSends(pending_sends, st);
      pending_sends.push_back(sreq);
    }
  }
  return DrainSends(pending_sends, Status::Ok());
}

}  // namespace internal
}  // namespace tpunet
