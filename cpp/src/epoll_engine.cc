// tpunet EPOLL engine — event-loop multi-stream TCP transport.
//
// The second engine behind the TPUNET_IMPLEMENT seam (reference analogue:
// the TOKIO backend, src/implement/tokio_backend.rs — an async runtime
// multiplexing comms over a small thread pool instead of thread-per-stream).
// Design deltas vs the reference's TOKIO engine, on purpose:
//   * SAME wire protocol as BASIC (shared wire.h) — the reference's two
//     engines were wire-incompatible (8-byte vs 4-byte length frames,
//     tokio_backend.rs:456); ours interoperate, so a BASIC sender can talk
//     to an EPOLL receiver.
//   * BASIC's fair rotating-cursor chunk assignment is kept (the TOKIO
//     engine always started at stream 0, tokio_backend.rs:392-404 — a
//     fairness bug this build does not replicate).
//   * Thread cost: TPUNET_EPOLL_THREADS loop threads (default 2) for the
//     whole engine, vs BASIC's nstreams+1 threads per comm — the fit for a
//     TPU host whose cores belong to the trainer.
//
// Data path: each comm's ctrl + data fds are registered (nonblocking) with
// one loop's epoll set. A message becomes one 8-byte ctrl segment plus
// round-robin chunk segments on the data fds; the loop advances each fd's
// segment queue on EPOLLIN/EPOLLOUT readiness, toggling interest so an idle
// fd costs nothing. Completion accounting is the shared RequestState; a
// request is done when its ctrl frame AND all its chunks have been moved.
//
// Inline fast path (TPUNET_EPOLL_INLINE=0 to disable): on an idle comm the
// caller thread dispatches its own message under the per-comm mutex and
// runs an immediate nonblocking IO pass, so small/buffered messages never
// touch the loop thread at all — the epoll-native analogue of BASIC's
// inline-send + lazy-recv (basic_engine.cc), closing the submit→loop-hop
// latency gap between the engines.
#include <errno.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <deque>
#include <future>
#include <map>
#include <memory>
#include <thread>
#include <vector>

#include "engine_base.h"
#include "fault.h"
#include "id_map.h"
#include "tpunet/mutex.h"
#include "tpunet/net.h"
#include "tpunet/telemetry.h"
#include "tpunet/utils.h"
#include "wire.h"

namespace tpunet {
namespace {

// One unit of IO on one fd: move `len` bytes starting at data+done.
// `counts_bytes` is false for ctrl length frames (protocol overhead is not
// reported in test()'s nbytes; reference reports payload bytes only).
// With CRC negotiated (kPreambleFlagCrc), data-chunk segments carry a
// 4-byte CRC32C trailer: precomputed into `trailer` on the send side,
// read into it and verified on the recv side after the payload completes.
struct Segment {
  uint8_t* data = nullptr;
  size_t len = 0;
  size_t done = 0;
  bool counts_bytes = true;
  uint8_t trailer[4] = {0, 0, 0, 0};
  size_t trailer_len = 0;   // 0 = no trailer (ctrl frames, CRC off)
  size_t trailer_done = 0;
  bool corrupt = false;     // injected fault: damage payload before verify
  // QoS wire-credit state (send-side data segments only; docs/DESIGN.md
  // "Transport QoS"): qos_wire is the credit this segment must hold before
  // its bytes may enter the kernel (0 = ungated), qos_ticket a parked
  // scheduler ticket awaiting a DRR grant, qos_enq_us the dispatch stamp
  // behind the queue-wait histogram.
  uint64_t qos_wire = 0;
  uint64_t qos_ticket = 0;
  uint64_t qos_enq_us = 0;
  bool qos_granted = false;
  RequestPtr state;
  std::unique_ptr<uint8_t[]> owned;  // backing store for send-side ctrl frames
};

struct EComm;

// Per-fd state: the fd, its comm, and the FIFO of segments to move.
// Everything mutable here (fd, segs, armed) is guarded by the owning
// EComm's `mu` BY CONVENTION: EComm is an incomplete type at this point, so
// GUARDED_BY(comm->mu) cannot be spelled. The contract is enforced one
// level up instead — every function touching an FdState takes the owning
// EComm explicitly and carries REQUIRES(c->mu).
struct FdState {
  int fd = -1;
  bool is_ctrl = false;
  uint64_t stream_idx = 0;  // data-stream index (per-stream fairness counters)
  EComm* comm = nullptr;
  std::deque<Segment> segs;
  uint32_t armed = 0;  // events currently registered with epoll
  // Front segment is waiting for QoS wire credit: interest is disarmed
  // (a writable socket we refuse to write would storm level-triggered
  // epoll) and the loop's bounded-timeout QoS pass re-advances us.
  bool qos_parked = false;
};

struct PendingRecv {
  uint8_t* data = nullptr;
  size_t len = 0;
  RequestPtr state;
};

struct EComm {
  bool is_send = false;
  size_t nstreams = 0;
  size_t min_chunksize = 0;
  bool crc = false;  // per-chunk CRC32C trailers (negotiated in the preamble)
  // QoS traffic class (sender's engine class; receivers adopt the preamble
  // nibble). Immutable after wiring.
  TrafficClass cls = TrafficClass::kBulk;
  // Inline fast path (caller-thread IO; see Loop::TryInline). `mu` guards
  // ALL mutable comm state below, taken by the loop thread at each entry
  // point and by the caller thread in TryInline — uncontended in steady
  // state, so the common cost is one atomic pair per entry. `attached`
  // flips once on the loop thread after epoll registration (fds are
  // nonblocking only from then on). `queued` counts kMsg commands posted
  // to the loop but not yet fully dispatched; TryInline requires 0 so an
  // inline message can never overtake a queued one on the wire (the loop
  // decrements only AFTER StartMsgLocked finishes, under mu).
  Mutex mu;
  uint64_t cursor GUARDED_BY(mu) = 0;  // rotating chunk-assignment cursor (fairness)
  // The FdStates' mutable innards (fd, segs, armed) are mu-guarded by
  // convention — see the FdState comment. The containers themselves are
  // shaped once pre-attach and stable after.
  FdState ctrl;
  // unique_ptr: FdState holds a deque of move-only Segments, and epoll
  // stores raw FdState* in event data — addresses must be stable.
  std::vector<std::unique_ptr<FdState>> streams;
  // recv side: posted irecvs waiting for their ctrl length frame, in order.
  std::deque<PendingRecv> pending GUARDED_BY(mu);
  uint8_t hdr[8] GUARDED_BY(mu);  // recv-side ctrl frame assembly buffer
  size_t hdr_done GUARDED_BY(mu) = 0;
  bool failed GUARDED_BY(mu) = false;
  std::string fail_msg GUARDED_BY(mu);
  bool attached GUARDED_BY(mu) = false;
  std::atomic<uint64_t> queued{0};

  // ---- Lane striping (docs/DESIGN.md "Lanes & adaptive striping") --------
  // Mirror of the BASIC engine's lane state, all under `mu` (every IO and
  // dispatch already runs under it): weighted slot-table rotation, epoch-
  // stamped WEIGHTS ctrl units (send: queued as ctrl segments ahead of the
  // LEN frame; recv: assembled by the wneed/wdone machine below), and the
  // send-side adaptation accounting the loop's sendmsg passes feed.
  bool lanes = false;
  bool lane_adapt = false;
  uint64_t lane_adapt_us = 100000;
  std::vector<uint32_t> base_weights;
  std::vector<uint32_t> weights GUARDED_BY(mu);
  std::vector<uint8_t> slots GUARDED_BY(mu);
  uint64_t stripe_epoch GUARDED_BY(mu) = 0;
  uint64_t next_adapt_us GUARDED_BY(mu) = 0;
  std::vector<uint64_t> lane_busy_us GUARDED_BY(mu);
  std::vector<uint64_t> lane_bytes GUARDED_BY(mu);
  std::vector<uint64_t> lane_rate_bps GUARDED_BY(mu);
  // recv ctrl: in-flight WEIGHTS unit (weight bytes after the 8-byte frame).
  uint8_t wbuf[256] GUARDED_BY(mu);
  size_t wneed GUARDED_BY(mu) = 0;
  size_t wdone GUARDED_BY(mu) = 0;
  uint64_t wepoch GUARDED_BY(mu) = 0;
};

// Weight resolution of the adaptive stripe scheduler (same value as the
// BASIC engine's kLaneWeightScale — the two engines must demote/recover to
// identical vectors for cross-engine comms to behave the same).
constexpr uint32_t kEpollLaneWeightScale = 16;

struct Command {
  enum Kind { kAttach, kMsg, kClose, kStop } kind = kStop;
  std::shared_ptr<EComm> comm;
  uint8_t* data = nullptr;
  size_t len = 0;
  RequestPtr state;
  std::shared_ptr<std::promise<void>> ack;  // kClose: signaled after fds are closed
};

// ---------------------------------------------------------------------------
// One epoll loop thread. Comms are attached to exactly one loop; all their
// IO and bookkeeping happens on that loop's thread (no data locks — the
// command queue is the only cross-thread handoff).
class Loop {
 public:
  Loop() {
    ep_ = ::epoll_create1(EPOLL_CLOEXEC);
    wake_ = ::eventfd(0, EFD_CLOEXEC | EFD_NONBLOCK);
    if (ep_ < 0 || wake_ < 0) {
      // Construction failed (fd exhaustion): never start the thread; Post()
      // fails commands inline so nothing can wait on a loop that isn't there.
      dead_ = true;
      return;
    }
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.ptr = nullptr;  // nullptr tags the wake eventfd
    ::epoll_ctl(ep_, EPOLL_CTL_ADD, wake_, &ev);
    thread_ = std::make_unique<std::thread>([this] { Run(); });
  }

  ~Loop() {
    if (ForkGeneration() != fork_gen_) {
      // Forked child: the loop thread never existed in this process, so any
      // pthread call on its stale id (join OR detach) is UB. Leak the handle
      // and just close this process's copies of the fds.
      (void)thread_.release();
      if (ep_ >= 0) ::close(ep_);
      if (wake_ >= 0) ::close(wake_);
      return;
    }
    Post(Command{Command::kStop, nullptr, nullptr, 0, nullptr, nullptr});
    if (thread_ && thread_->joinable()) thread_->join();
    if (ep_ >= 0) ::close(ep_);
    if (wake_ >= 0) ::close(wake_);
  }

  void Post(Command c) {
    // Loop threads do not survive fork(): in a forked child this engine's
    // loop is gone, so fail fast instead of queueing commands nobody will
    // ever drain (create the engine after fork, as per-process runtimes do).
    // Checked BEFORE taking mu_ — fork may have captured mu_ locked by the
    // loop thread, in which case the child would block on it forever.
    // ForkGeneration() is a relaxed atomic load — no syscall on the hot path.
    if (ForkGeneration() != fork_gen_) {
      FailCommand(c, "engine created before fork(); its loop thread does not exist here");
      return;
    }
    {
      MutexLock lk(mu_);
      if (!dead_) {
        cmds_.push_back(std::move(c));
        uint64_t one = 1;
        (void)!::write(wake_, &one, sizeof(one));
        return;
      }
    }
    // Loop is gone (construction failed or Run() exited): fail the command
    // inline so no caller blocks on an ack or polls a request forever.
    FailCommand(c, "epoll loop unavailable");
  }

  // Caller-thread fast path: when the comm is verifiably idle — attached,
  // healthy, no queued commands, every segment queue empty — the caller
  // takes the loop's role for this one message under the comm mutex:
  // StartMsgLocked dispatches it AND runs an immediate nonblocking IO pass,
  // so a message that fits the kernel socket buffers (send) or has already
  // arrived (recv) completes with zero loop-thread hops and zero epoll
  // round-trips. Residue is armed via epoll_ctl, which is thread-safe
  // against the loop's epoll_wait; the loop finishes the tail as usual.
  // Returns false when not idle — caller falls back to Post(kMsg).
  // Wire-order safety: inline requires queued==0 AND empty segment queues,
  // i.e. every prior message's bytes are already in the kernel, so this
  // message cannot overtake anything. Callers are single-threaded per comm
  // (NCCL proxy contract), so the idle check cannot race another submitter.
  bool TryInline(EComm* c, uint8_t* data, size_t len, const RequestPtr& state) {
    // Same fork guard as Post(): in a forked child the comm's fds are
    // SHARED with the parent — inline IO here would interleave bytes with
    // the parent's loop thread (and c->mu may have been captured locked at
    // fork). Decline; the caller falls through to Post(), whose guard
    // fails the request with the canonical before-fork error.
    if (ForkGeneration() != fork_gen_) return false;
    MutexLock lk(c->mu);
    if (!c->attached && !c->failed) return false;
    if (c->queued.load(std::memory_order_acquire) != 0) return false;
    if (!c->ctrl.segs.empty() || !c->pending.empty()) return false;
    for (auto& s : c->streams) {
      if (!s->segs.empty()) return false;
    }
    // A failed comm takes the inline path too: StartMsgLocked fails the
    // request immediately, sparing the hop through a loop that may be gone.
    StartMsgLocked(c, data, len, state);
    return true;
  }

 private:
  static void FailCommand(Command& c, const std::string& why) {
    if (c.kind == Command::kMsg && c.comm) {
      c.comm->queued.fetch_sub(1, std::memory_order_acq_rel);
    }
    if (c.state) {
      c.state->SetError(why);
      c.state->total.store(0, std::memory_order_release);
      c.state->NotifyIfSettled();
    }
    if (c.ack) c.ack->set_value();
  }

  void Run() {
    constexpr int kMaxEvents = 64;
    epoll_event evs[kMaxEvents];
    bool stop = false;
    while (!stop) {
      // Credit-parked fds get no readiness events (interest disarmed), so
      // poll on a short timeout while any exist and re-advance them —
      // that is how a DRR grant turns back into wire bytes.
      int timeout_ms = qos_parked_.load(std::memory_order_acquire) > 0 ? 2 : -1;
      int n = ::epoll_wait(ep_, evs, kMaxEvents, timeout_ms);
      if (n < 0) {
        if (errno == EINTR) continue;
        break;  // unrecoverable epoll failure; drained below
      }
      if (qos_parked_.load(std::memory_order_acquire) > 0) RetryQosParked();
      for (int i = 0; i < n; ++i) {
        FdState* fs = static_cast<FdState*>(evs[i].data.ptr);
        if (fs == nullptr) {
          uint64_t drain;
          (void)!::read(wake_, &drain, sizeof(drain));
          stop = DrainCommands() || stop;
          continue;
        }
        if (evs[i].events & (EPOLLERR | EPOLLHUP)) {
          FailComm(fs->comm, fs->is_ctrl ? "ctrl stream closed by peer" : "data stream closed by peer");
          continue;
        }
        Advance(fs);
      }
      // Comms detached during this batch are destroyed only now: a stale
      // event later in the same epoll_wait batch may still dereference
      // their FdStates (fds are closed, so Advance/FailComm no-op safely).
      graveyard_.clear();
    }
    // Loop exit: fail whatever is still attached so no request hangs, then
    // mark the loop dead and drain late commands so Post() never strands a
    // caller (kClose acks are signaled, kMsg requests are failed).
    for (auto& kv : comms_) FailComm(kv.second.get(), "engine shut down");
    for (auto& kv : comms_) {
      EComm* c = kv.second.get();
      MutexLock lk(c->mu);
      CloseFds(c);
    }
    comms_.clear();
    graveyard_.clear();
    std::deque<Command> late;
    {
      MutexLock lk(mu_);
      dead_ = true;
      late.swap(cmds_);
    }
    for (Command& c : late) FailCommand(c, "epoll loop stopped");
  }

  bool DrainCommands() {
    std::deque<Command> batch;
    {
      MutexLock lk(mu_);
      batch.swap(cmds_);
    }
    bool stop = false;
    for (Command& c : batch) {
      switch (c.kind) {
        case Command::kAttach:
          Attach(c.comm);
          break;
        case Command::kMsg: {
          EComm* ec = c.comm.get();
          MutexLock lk(ec->mu);
          StartMsgLocked(ec, c.data, c.len, c.state);
          // Decrement only now, under mu: TryInline observing queued==0
          // then implies this message's segments are already dispatched
          // (and its idle check sees them), so inline can't overtake it.
          ec->queued.fetch_sub(1, std::memory_order_acq_rel);
          break;
        }
        case Command::kClose:
          Detach(c.comm);
          if (c.ack) c.ack->set_value();
          break;
        case Command::kStop:
          stop = true;
          break;
      }
    }
    return stop;
  }

  void Attach(const std::shared_ptr<EComm>& comm) {
    comms_[comm.get()] = comm;
    EComm* c = comm.get();
    MutexLock lk(c->mu);
    bool ok = Register(c, &c->ctrl);
    for (auto& s : c->streams) ok = Register(c, s.get()) && ok;
    if (!ok) {
      // A comm with unwatched fds would never progress and never error;
      // fail it now so its requests surface the problem via test().
      FailCommLocked(c, "epoll registration failed: " + std::string(strerror(errno)));
      return;
    }
    if (c->lanes && c->is_send && c->stripe_epoch == 0) {
      // Publish the configured base weight vector as epoch 1 before any
      // message can dispatch (kAttach precedes every kMsg in command
      // order, and TryInline declines until `attached` flips below) — the
      // first LEN frame already finds both sides on the same map.
      c->weights = c->base_weights;
      c->weights.resize(c->nstreams, 1);
      c->stripe_epoch = 1;
      c->slots = BuildWrrSlots(c->weights);
      QueueWeightsSegmentLocked(c);
      AdvanceFdLocked(c, &c->ctrl);
    }
    c->attached = true;  // TryInline may take the fast path from here on
  }

  bool Register(EComm* c, FdState* fs) REQUIRES(c->mu) {
    (void)c;
    SetNonblocking(fs->fd);
    epoll_event ev{};
    ev.events = 0;
    ev.data.ptr = fs;
    if (::epoll_ctl(ep_, EPOLL_CTL_ADD, fs->fd, &ev) != 0) return false;
    fs->armed = 0;
    return true;
  }

  void Detach(const std::shared_ptr<EComm>& comm) {
    // The NCCL contract says every request is test()ed done before close; if
    // the caller closed early anyway, fail the stragglers so their test()
    // surfaces an error instead of polling forever (BASIC flushes queued
    // work on close for the same reason).
    EComm* c = comm.get();
    MutexLock lk(c->mu);
    bool leftovers = !c->ctrl.segs.empty() || !c->pending.empty();
    for (auto& s : c->streams) leftovers = leftovers || !s->segs.empty();
    if (leftovers) FailCommLocked(c, "comm closed with requests in flight");
    CloseFds(c);
    comms_.erase(comm.get());
    // Keep the comm alive until the current event batch has fully drained —
    // stale events in this batch still point at its FdStates.
    graveyard_.push_back(comm);
  }

  void CloseFds(EComm* c) REQUIRES(c->mu) {
    auto drop = [&](FdState& fs) {
      if (fs.fd >= 0) {
        ::epoll_ctl(ep_, EPOLL_CTL_DEL, fs.fd, nullptr);
        ::close(fs.fd);
        fs.fd = -1;
      }
    };
    drop(c->ctrl);
    for (auto& s : c->streams) drop(*s);
  }

  // Set epoll interest on fs to `want` (EPOLLIN or EPOLLOUT or 0).
  // epoll_ctl is thread-safe against the loop's epoll_wait, so this is
  // callable from the caller thread's inline path; fs->armed is guarded by
  // the comm mutex all callers hold (REQUIRES below).
  void Arm(EComm* c, FdState* fs, uint32_t want) REQUIRES(c->mu) {
    (void)c;
    if (fs->armed == want || fs->fd < 0) return;
    epoll_event ev{};
    ev.events = want;
    ev.data.ptr = fs;
    ::epoll_ctl(ep_, EPOLL_CTL_MOD, fs->fd, &ev);
    fs->armed = want;
  }

  void WantIOLocked(EComm* c, FdState* fs) REQUIRES(c->mu) {
    uint32_t dir = c->is_send ? static_cast<uint32_t>(EPOLLOUT)
                              : static_cast<uint32_t>(EPOLLIN);
    // Recv-side ctrl arms EPOLLIN while a posted recv awaits its frame.
    if (!c->is_send && fs->is_ctrl) {
      Arm(c, fs, c->pending.empty() && fs->segs.empty()
                     ? 0
                     : static_cast<uint32_t>(EPOLLIN));
      return;
    }
    Arm(c, fs, fs->segs.empty() ? 0 : dir);
  }

  // ----- message start (comm mutex held) -----------------------------------

  // Queue one WEIGHTS ctrl unit ([frame u64][w u8 x n]) ahead of whatever
  // LEN frame follows — ctrl segments are FIFO on the fd, so the receiver
  // re-stripes at exactly this message boundary. The unit carries a dummy
  // RequestState (never polled; total stays unscheduled) purely so the
  // shared segment-completion accounting needs no null-state branch.
  static void QueueWeightsSegmentLocked(EComm* c) REQUIRES(c->mu) {
    Segment seg;
    size_t n = 8 + c->weights.size();
    seg.owned.reset(new uint8_t[n]);
    BuildWeightsUnit(c->stripe_epoch, c->weights, seg.owned.get());
    seg.data = seg.owned.get();
    seg.len = n;
    seg.counts_bytes = false;
    seg.state = std::make_shared<RequestState>();
    c->ctrl.segs.push_back(std::move(seg));
    for (size_t i = 0; i < c->weights.size(); ++i) {
      Telemetry::Get().OnLaneWeight(i, c->weights[i]);
    }
  }

  // Send-side adaptation tick (the EPOLL twin of BASIC's
  // MaybeAdaptLanesLocked — same rate math, same targets, same geometric
  // step; see that function for the policy commentary). Runs under c->mu at
  // message starts; a changed vector bumps the epoch and queues the WEIGHTS
  // unit, whose wire failure surfaces through the ordinary ctrl-fd failure
  // path (FailComm).
  static void MaybeAdaptLanesLocked(EComm* c) REQUIRES(c->mu) {
    if (!c->lanes || !c->is_send || !c->lane_adapt) return;
    uint64_t now = MonotonicUs();
    if (now < c->next_adapt_us) return;
    c->next_adapt_us = now + c->lane_adapt_us;
    uint64_t rmax = 0;
    bool moved = false;
    for (size_t i = 0; i < c->nstreams; ++i) {
      if (c->lane_bytes[i] > 0 && c->lane_busy_us[i] > 0) {
        uint64_t inst = c->lane_bytes[i] * 8 * 1000000 / c->lane_busy_us[i];
        c->lane_rate_bps[i] =
            c->lane_rate_bps[i] == 0 ? inst : (c->lane_rate_bps[i] + inst) / 2;
        Telemetry::Get().OnLaneRate(i, c->lane_rate_bps[i]);
        moved = true;
      }
      c->lane_bytes[i] = 0;
      c->lane_busy_us[i] = 0;
      // Per-tick gauge re-export: survives a mid-run telemetry.reset()
      // (see the BASIC twin for the rationale).
      Telemetry::Get().OnLaneWeight(i, c->weights[i]);
      if (c->lane_rate_bps[i] > rmax) rmax = c->lane_rate_bps[i];
    }
    if (!moved || rmax == 0) return;
    bool changed = false;
    for (size_t i = 0; i < c->nstreams; ++i) {
      uint64_t ewma = c->lane_rate_bps[i];
      uint32_t w = c->weights[i];
      uint32_t target = w;
      if (ewma > 0) {
        target = static_cast<uint32_t>(
            (kEpollLaneWeightScale * ewma + rmax / 2) / rmax);
        if (target < 1) target = 1;
        if (target > kEpollLaneWeightScale) target = kEpollLaneWeightScale;
      }
      if (Telemetry::Get().StreamStraggling(true, i)) {
        uint32_t demoted = w > 1 ? w / 2 : 1;
        if (demoted < target) target = demoted;
      }
      uint32_t next = w;
      if (target > w) {
        next = w + std::max<uint32_t>(1, (target - w) / 2);
      } else if (target < w) {
        next = w - std::max<uint32_t>(1, (w - target) / 2);
      }
      if (next != w) {
        c->weights[i] = next;
        changed = true;
      }
    }
    if (!changed) return;
    c->stripe_epoch += 1;
    c->slots = BuildWrrSlots(c->weights);
    Telemetry::Get().OnRestripe();
    QueueWeightsSegmentLocked(c);
  }

  void StartMsgLocked(EComm* c, uint8_t* data, size_t len, const RequestPtr& state)
      REQUIRES(c->mu) {
    if (c->failed) {
      state->SetError("comm broken by earlier error: " + c->fail_msg);
      state->total.store(0, std::memory_order_release);
      state->NotifyIfSettled();
      return;
    }
    if (c->is_send) {
      MaybeAdaptLanesLocked(c);
      // total = ctrl frame + chunks; the frame counts as a subtask so "done"
      // means every byte (incl. the frame) reached the kernel buffer.
      size_t csize = ChunkSize(len, c->min_chunksize, c->nstreams);
      size_t nchunks = ChunkCount(len, csize);
      state->total.store(1 + nchunks, std::memory_order_release);
      Segment hdr;
      hdr.owned.reset(new uint8_t[8]);
      EncodeU64BE(len, hdr.owned.get());
      hdr.data = hdr.owned.get();
      hdr.len = 8;
      hdr.counts_bytes = false;
      hdr.state = state;
      c->ctrl.segs.push_back(std::move(hdr));
      DispatchChunksLocked(c, data, len, state);
      // Immediate IO pass (ctrl frame first): a message that fits the
      // kernel socket buffers completes right here with interest left at 0
      // — no epoll round-trip at all. Residue arms itself in AdvanceFd.
      AdvanceFdLocked(c, &c->ctrl);
      for (auto& s : c->streams) {
        if (c->failed) break;
        if (!s->segs.empty()) AdvanceFdLocked(c, s.get());
      }
    } else {
      c->pending.push_back(PendingRecv{data, len, state});
      // Immediate pass: the frame (and often the payload) may already sit
      // in the kernel buffer — AdvanceRecvCtrl consumes it and advances
      // the data fds without waiting for a readiness event.
      AdvanceRecvCtrlLocked(c);
    }
  }

  void DispatchChunksLocked(EComm* c, uint8_t* data, size_t len,
                            const RequestPtr& state) REQUIRES(c->mu) {
    size_t csize = ChunkSize(len, c->min_chunksize, c->nstreams);
    size_t nchunks = ChunkCount(len, csize);
    size_t off = 0;
    for (size_t i = 0; i < nchunks; ++i) {
      size_t n = std::min(csize, len - off);
      // Lane mode swaps the uniform rotation for the WRR slot table (same
      // cursor discipline as BASIC's AssignStreamIdx; no failover here, so
      // no retired-skip walk). Both derivations persist across messages.
      size_t pick = (c->lanes && !c->slots.empty())
                        ? c->slots[c->cursor % c->slots.size()]
                        : c->cursor % c->nstreams;
      FdState* fs = c->streams[pick].get();
      c->cursor += 1;  // persists across messages — fairness rotation
      Segment seg;
      seg.data = data + off;
      seg.len = n;
      seg.state = state;
      if (c->crc) {
        seg.trailer_len = 4;
        // Send side precomputes the trailer at dispatch; the recv side
        // reads the peer's 4 bytes into it and verifies at completion.
        if (c->is_send) EncodeU32BE(Crc32c(seg.data, seg.len), seg.trailer);
      }
      if (c->is_send && QosScheduler::Get().wire_gate_enabled()) {
        // Gate this chunk's wire bytes behind the DRR scheduler; the grant
        // happens in AdvanceFdLocked right before the bytes would move.
        seg.qos_wire = seg.len + seg.trailer_len;
        seg.qos_enq_us = MonotonicUs();
      }
      fs->segs.push_back(std::move(seg));
      WantIOLocked(c, fs);
      off += n;
    }
  }

  // ----- readiness ----------------------------------------------------------

  // Loop-thread entry for epoll events; the inline path enters via
  // StartMsgLocked with the same mutex held, so fd/segment state is only
  // ever touched under c->mu.
  void Advance(FdState* fs) {
    EComm* c = fs->comm;
    MutexLock lk(c->mu);
    AdvanceFdLocked(c, fs);
  }

  // Recv-side completion side effects: injected wire damage lands before the
  // CRC verify, and a trailer mismatch fails the REQUEST (not the comm — the
  // framing is intact, so the comm keeps serving subsequent messages).
  void FinishSegmentLocked(EComm* c, Segment& seg, FdState* fs) REQUIRES(c->mu) {
    if (!c->is_send) {
      if (seg.corrupt && seg.len > 0) {
        seg.data[seg.len / 2] ^= 0x01;  // wire damage before verify
        seg.corrupt = false;
      }
      if (seg.trailer_len > 0 && DecodeU32BE(seg.trailer) != Crc32c(seg.data, seg.len)) {
        Telemetry::Get().OnCrcError();
        seg.state->SetError(ErrorKind::kCorruption,
                            "CRC32C mismatch on data stream " +
                                std::to_string(fs->stream_idx) +
                                ": payload corrupted in transit");
      }
    }
    CompleteSegment(c, seg, fs);
  }

  // Segments coalesced per sendmsg/recvmsg. Each contributes up to two
  // iovecs (payload remainder + trailer remainder); well under IOV_MAX.
  static constexpr int kIovBatch = 64;

  // True when `seg` may put bytes on the wire (holds credit or needs none).
  // On false a scheduler ticket is parked; the segment re-polls it on every
  // advance until the DRR pump grants.
  bool QosGrantLocked(EComm* c, Segment& seg) REQUIRES(c->mu) {
    if (seg.qos_wire == 0 || seg.qos_granted) return true;
    QosScheduler& qs = QosScheduler::Get();
    bool got;
    if (seg.qos_ticket == 0) {
      got = qs.TryAcquireWire(c->cls, seg.qos_wire, &seg.qos_ticket);
    } else {
      got = qs.PollTicket(seg.qos_ticket);
      if (got) seg.qos_ticket = 0;
    }
    if (got) {
      seg.qos_granted = true;
      Telemetry::Get().OnQosQueueWait(static_cast<int>(c->cls),
                                      MonotonicUs() - seg.qos_enq_us);
    }
    return got;
  }

  static bool QosNeedsCredit(const Segment& seg) {
    return seg.qos_wire > 0 && !seg.qos_granted;
  }

  // Caller holds c->mu (by convention — only the atomic counter and the
  // convention-guarded FdState flag are touched, and FailCommLocked calls
  // the unpark from a lambda TSA analyzes as a separate function).
  void QosParkLocked(EComm* c, FdState* fs) {
    (void)c;
    if (fs->qos_parked) return;
    fs->qos_parked = true;
    qos_parked_.fetch_add(1, std::memory_order_acq_rel);
    // The loop may be blocked in epoll_wait(-1); nudge it onto the bounded
    // timeout so the QoS retry pass runs. Harmless when called on-loop.
    uint64_t one = 1;
    (void)!::write(wake_, &one, sizeof(one));
  }

  void QosUnparkLocked(EComm* c, FdState* fs) {
    (void)c;
    if (!fs->qos_parked) return;
    fs->qos_parked = false;
    qos_parked_.fetch_sub(1, std::memory_order_acq_rel);
  }

  // QoS retry pass (loop thread): re-advance every fd parked on wire
  // credit. Runs at most every couple of ms while anything is parked.
  void RetryQosParked() {
    for (auto& kv : comms_) {
      EComm* c = kv.second.get();
      MutexLock lk(c->mu);
      for (auto& fss : c->streams) {
        if (fss->qos_parked) AdvanceFdLocked(c, fss.get());
      }
    }
  }

  void AdvanceFdLocked(EComm* c, FdState* fs) REQUIRES(c->mu) {
    if (c->failed || fs->fd < 0) return;
    QosUnparkLocked(c, fs);  // re-parks below if still credit-blocked
    if (!c->is_send && fs->is_ctrl) {
      AdvanceRecvCtrlLocked(c);
      return;
    }
    const bool lane_clock = c->lanes && c->is_send && !fs->is_ctrl;
    while (!fs->segs.empty()) {
      // Iovec cursor over the segment FIFO: gather every queued segment's
      // remaining payload + CRC trailer into ONE sendmsg/recvmsg, then walk
      // the moved bytes back through the segments. The round-4 machine paid
      // one syscall per partial segment move (plus one per trailer); this
      // pass moves as many whole segments as the kernel will take per
      // syscall — the tx half of the syscalls/MiB budget (docs/DESIGN.md).
      // Lane mode additionally clocks each pass (fault gate + syscall) into
      // the lane's service accounting — the adaptive scheduler's rate input.
      uint64_t lane_t0 = lane_clock ? MonotonicUs() : 0;
      struct iovec iov[kIovBatch];
      int niov = 0;
      size_t want = 0;
      for (Segment& seg : fs->segs) {
        if (niov + 2 > kIovBatch) break;
        if (c->is_send && !fs->is_ctrl && !QosGrantLocked(c, seg)) {
          // No wire credit yet: nothing past this segment may move either
          // (per-fd FIFO keeps the wire order the receiver expects).
          break;
        }
        size_t left = seg.len - seg.done;
        if (left > 0 && !fs->is_ctrl) {
          // Fault gate (data payload only; ctrl frames and trailers are
          // exempt). Gated once per segment per IO pass, so after_bytes
          // thresholds are approximate on this engine (exact on BASIC's
          // per-chunk IO) — as before the vectored rewrite.
          FaultAction fa = FaultCheck(c->is_send, fs->stream_idx, fs->fd, left);
          if (fa == FaultAction::kCorrupt) seg.corrupt = true;
        }
        if (c->is_send && seg.corrupt && seg.trailer_len > 0 && seg.trailer_done == 0) {
          // Send-side injected corruption: damage the trailer on the wire
          // (the payload is the caller's buffer and must not be touched).
          seg.trailer[0] ^= 0x01;
          seg.corrupt = false;
        }
        if (left > 0) {
          iov[niov].iov_base = seg.data + seg.done;
          iov[niov].iov_len = left;
          ++niov;
          want += left;
        }
        size_t tleft = seg.trailer_len - seg.trailer_done;
        if (tleft > 0) {
          iov[niov].iov_base = seg.trailer + seg.trailer_done;
          iov[niov].iov_len = tleft;
          ++niov;
          want += tleft;
        }
      }
      if (want == 0) break;  // defensive: no segment with bytes left
      struct msghdr mh = {};
      mh.msg_iov = iov;
      mh.msg_iovlen = static_cast<size_t>(niov);
      CountIoSyscall(c->is_send ? kIoSendmsg : kIoRecvmsg);
      ssize_t m = c->is_send ? ::sendmsg(fs->fd, &mh, MSG_DONTWAIT | MSG_NOSIGNAL)
                             : ::recvmsg(fs->fd, &mh, MSG_DONTWAIT);
      if (m < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK) break;
        if (errno == EINTR) continue;
        FailCommLocked(c, std::string(c->is_send ? "send" : "recv") +
                              " failed: " + strerror(errno));
        return;
      }
      if (m == 0 && !c->is_send) {  // EOF on recv
        FailCommLocked(c, "peer closed data stream mid-message");
        return;
      }
      // Cursor walk: spread the moved bytes over the front segments,
      // completing (and popping) each one that fills.
      const uint64_t now = MonotonicUs();
      if (lane_clock) {
        size_t li = fs->stream_idx < c->nstreams ? fs->stream_idx : 0;
        uint64_t dt = now - lane_t0;
        c->lane_busy_us[li] += dt ? dt : 1;
        c->lane_bytes[li] += static_cast<uint64_t>(m);  // wire bytes: rate input
      }
      size_t moved = static_cast<size_t>(m);
      while (moved > 0 && !fs->segs.empty()) {
        Segment& seg = fs->segs.front();
        size_t take = std::min(moved, seg.len - seg.done);
        if (take > 0) {
          if (!fs->is_ctrl) {
            if (seg.done == 0) seg.state->MarkWireStart(now);
            Telemetry::Get().OnStreamBytes(c->is_send, fs->stream_idx,
                                           static_cast<uint64_t>(take),
                                           static_cast<int>(c->cls));
            if (c->lanes) {
              Telemetry::Get().OnLaneBytes(c->is_send, fs->stream_idx,
                                           static_cast<uint64_t>(take));
            }
          }
          seg.done += take;
          moved -= take;
        }
        size_t ttake = std::min(moved, seg.trailer_len - seg.trailer_done);
        seg.trailer_done += ttake;
        moved -= ttake;
        if (seg.done == seg.len && seg.trailer_done == seg.trailer_len) {
          FinishSegmentLocked(c, seg, fs);
          fs->segs.pop_front();
          continue;
        }
        break;  // kernel stopped mid-segment; moved is 0 here
      }
      if (static_cast<size_t>(m) < want) break;  // kernel full/empty: arm below
    }
    if (c->is_send && !fs->is_ctrl && !fs->segs.empty() &&
        QosNeedsCredit(fs->segs.front())) {
      // Head-of-queue segment lacks wire credit: disarm interest (a
      // writable socket we refuse to write would storm level-triggered
      // epoll) and park for the loop's bounded-timeout QoS pass.
      Arm(c, fs, 0);
      QosParkLocked(c, fs);
      return;
    }
    WantIOLocked(c, fs);
  }

  // Apply a fully-assembled WEIGHTS unit (recv side; see BASIC's
  // ProcessWeightsFrameLocked for the protocol commentary). Returns false
  // after failing the comm on a desync.
  bool ApplyWeightsLocked(EComm* c) REQUIRES(c->mu) {
    for (size_t i = 0; i < c->wneed; ++i) {
      if (c->wbuf[i] == 0) {
        FailCommLocked(c, "WEIGHTS frame carries a zero weight (protocol desync)");
        return false;
      }
      c->weights[i] = c->wbuf[i];
      Telemetry::Get().OnLaneWeight(i, c->wbuf[i]);
    }
    bool initial = c->stripe_epoch == 0;
    c->stripe_epoch = c->wepoch;
    c->slots = BuildWrrSlots(c->weights);
    if (!initial) Telemetry::Get().OnRestripe();
    c->wneed = 0;
    c->wdone = 0;
    return true;
  }

  void AdvanceRecvCtrlLocked(EComm* c) REQUIRES(c->mu) {
    FdState* fs = &c->ctrl;
    bool dispatched = false;
    while (!c->pending.empty()) {
      // In-flight WEIGHTS unit: finish its weight bytes before any further
      // frame — the ctrl stream is one FIFO and the next LEN's message must
      // be laid out on the NEW vector.
      if (c->wneed > 0) {
        CountIoSyscall(kIoRecv);
        ssize_t wm = ::recv(fs->fd, c->wbuf + c->wdone, c->wneed - c->wdone,
                            MSG_DONTWAIT);
        if (wm > 0) {
          c->wdone += static_cast<size_t>(wm);
          if (c->wdone < c->wneed) continue;
          if (!ApplyWeightsLocked(c)) return;
          continue;
        }
        if (wm == 0) {
          FailCommLocked(c, "peer closed ctrl stream");
          return;
        }
        if (errno == EAGAIN || errno == EWOULDBLOCK) break;
        if (errno == EINTR) continue;
        FailCommLocked(c, std::string("ctrl recv failed: ") + strerror(errno));
        return;
      }
      CountIoSyscall(kIoRecv);
      ssize_t m = ::recv(fs->fd, c->hdr + c->hdr_done, 8 - c->hdr_done, MSG_DONTWAIT);
      if (m > 0) {
        c->hdr_done += static_cast<size_t>(m);
        if (c->hdr_done < 8) continue;
        c->hdr_done = 0;
        uint64_t target = DecodeU64BE(c->hdr);
        if ((target >> 56) == kCtrlFrameWeights) {
          uint64_t count = WeightsFrameCount(target);
          uint64_t epoch = WeightsFrameEpoch(target);
          if (!c->lanes || count != c->nstreams || count == 0 ||
              epoch <= c->stripe_epoch) {
            FailCommLocked(c, "WEIGHTS frame in an impossible state "
                              "(protocol desync)");
            return;
          }
          c->wneed = static_cast<size_t>(count);
          c->wdone = 0;
          c->wepoch = epoch;
          continue;
        }
        PendingRecv pr = c->pending.front();
        c->pending.pop_front();
        if (target > pr.len) {
          FailCommLocked(c, "incoming message (" + std::to_string(target) +
                          "B) exceeds posted recv buffer (" + std::to_string(pr.len) + "B)");
          return;
        }
        // total = ctrl frame (just consumed) + chunks of the TRUE size.
        size_t csize = ChunkSize(target, c->min_chunksize, c->nstreams);
        size_t nchunks = ChunkCount(target, csize);
        pr.state->total.store(1 + nchunks, std::memory_order_release);
        pr.state->completed.fetch_add(1, std::memory_order_acq_rel);
        pr.state->NotifyIfSettled();  // 0-byte message: settled right here
        DispatchChunksLocked(c, pr.data, static_cast<size_t>(target), pr.state);
        dispatched = true;
        continue;
      }
      if (m == 0) {
        FailCommLocked(c, "peer closed ctrl stream");
        return;
      }
      if (errno == EAGAIN || errno == EWOULDBLOCK) break;
      if (errno == EINTR) continue;
      FailCommLocked(c, std::string("ctrl recv failed: ") + strerror(errno));
      return;
    }
    WantIOLocked(c, fs);
    if (dispatched) {
      // Eager data pass: when the frame was readable, the payload usually
      // is too — drain what's buffered now instead of paying a readiness
      // round-trip per data fd.
      for (auto& s : c->streams) {
        if (c->failed) break;
        if (!s->segs.empty()) AdvanceFdLocked(c, s.get());
      }
    }
  }

  void CompleteSegment(EComm* c, Segment& seg, FdState* fs) REQUIRES(c->mu) {
    if (seg.qos_granted) {
      QosScheduler::Get().ReleaseWire(c->cls, seg.qos_wire);
      seg.qos_granted = false;
    }
    if (seg.counts_bytes) {
      seg.state->nbytes.fetch_add(seg.len, std::memory_order_relaxed);
      seg.state->MarkWireEnd(MonotonicUs());
      // Rate-limited TCP_INFO sample off the chunk's live socket (per-chunk,
      // never per-partial-read — the limiter check is one clock + atomic).
      Telemetry::Get().MaybeSampleStream(c->is_send, fs->stream_idx, fs->fd);
    }
    seg.state->completed.fetch_add(1, std::memory_order_acq_rel);
    seg.state->NotifyIfSettled();
  }

  // Loop-thread entry (EPOLLERR/EPOLLHUP and Run-exit paths).
  void FailComm(EComm* c, const std::string& msg) EXCLUDES(c->mu) {
    MutexLock lk(c->mu);
    FailCommLocked(c, msg);
  }

  // Fail every in-flight and future request on the comm. Buffers are safe to
  // release immediately: segments are dropped under the comm mutex, which
  // every toucher (loop thread and inline caller) holds.
  void FailCommLocked(EComm* c, const std::string& msg) REQUIRES(c->mu) {
    if (c->failed) return;
    c->failed = true;
    c->fail_msg = msg;
    auto fail_fd = [&](FdState& fs) {
      for (Segment& seg : fs.segs) {
        // QoS bookkeeping must not leak with the segment: held credit goes
        // back to the DRR pump (so a dead bulk comm can never starve the
        // latency lane) and parked tickets are withdrawn.
        if (seg.qos_granted) {
          QosScheduler::Get().ReleaseWire(c->cls, seg.qos_wire);
          seg.qos_granted = false;
        }
        if (seg.qos_ticket != 0) {
          QosScheduler::Get().CancelTicket(seg.qos_ticket);
          seg.qos_ticket = 0;
        }
        seg.state->SetError(msg);
        seg.state->completed.fetch_add(1, std::memory_order_acq_rel);
        seg.state->NotifyIfSettled();
      }
      fs.segs.clear();
      QosUnparkLocked(c, &fs);
      // Fully deregister (not just interest=0): EPOLLHUP/ERR are reported
      // regardless of the requested mask, so a dead peer's fds left in the
      // epoll set would spin this loop thread at 100% until detach.
      if (fs.fd >= 0) {
        ::epoll_ctl(ep_, EPOLL_CTL_DEL, fs.fd, nullptr);
        fs.armed = 0;
      }
    };
    fail_fd(c->ctrl);
    for (auto& s : c->streams) fail_fd(*s);
    for (PendingRecv& pr : c->pending) {
      pr.state->SetError(msg);
      pr.state->total.store(0, std::memory_order_release);
      pr.state->NotifyIfSettled();
    }
    c->pending.clear();
  }

  int ep_ = -1;
  int wake_ = -1;
  const uint64_t fork_gen_ = ForkGeneration();  // fork detection (see Post)
  std::unique_ptr<std::thread> thread_;
  Mutex mu_;
  // Count of fds parked on QoS wire credit: while nonzero the loop swaps
  // its infinite epoll_wait for a short timeout and runs RetryQosParked.
  std::atomic<int> qos_parked_{0};
  // Written unlocked only in the constructor (TSA exempts ctors; no other
  // thread exists until thread_ starts below that write).
  bool dead_ GUARDED_BY(mu_) = false;
  std::deque<Command> cmds_ GUARDED_BY(mu_);
  std::map<EComm*, std::shared_ptr<EComm>> comms_;  // keeps comms alive on-loop
  std::vector<std::shared_ptr<EComm>> graveyard_;   // detached, freed post-batch
};

// ---------------------------------------------------------------------------

struct CommHandle {
  std::shared_ptr<EComm> comm;
  Loop* loop = nullptr;
};

class EpollEngine : public EngineBase, public BundleAdopter {
 public:
  EpollEngine()
      : inline_io_(GetEnvU64("TPUNET_EPOLL_INLINE", 1) != 0) {
    size_t nloops = GetEnvU64("TPUNET_EPOLL_THREADS", 2);
    if (nloops == 0) nloops = 1;
    for (size_t i = 0; i < nloops; ++i) loops_.emplace_back(std::make_unique<Loop>());
  }

  ~EpollEngine() override {
    WakeAllListens();
    // Close comms through their loops so fds close on the owning thread.
    for (auto& h : send_comms_.DrainAll()) CloseOnLoop(h);
    for (auto& h : recv_comms_.DrainAll()) CloseOnLoop(h);
    loops_.clear();  // joins loop threads
  }

  Status connect(int32_t dev, const SocketHandle& handle, uint64_t* send_comm) override {
    Status sdev = CheckDev(dev);
    if (!sdev.ok()) return sdev;
    std::vector<int> data_fds;
    int ctrl_fd = -1;
    Status s = ConnectBundle(nics_, dev, handle, nstreams_, min_chunksize_, PreambleFlags(),
                             &data_fds, &ctrl_fd, lane_mode_ ? &lanes_ : nullptr);
    if (!s.ok()) return s;
    return AttachComm(true, nstreams_, min_chunksize_, crc_,
                      static_cast<TrafficClass>(traffic_class()), lane_mode_,
                      ctrl_fd, data_fds, send_comm, &send_comms_);
  }

  Status accept(uint64_t listen_comm, uint64_t* recv_comm) override {
    PartialBundle b;
    Status s = AcceptBundleOn(listen_comm, &b);
    if (!s.ok()) return s;
    return AdoptBundle(b, recv_comm);
  }

  // BundleAdopter seam (wire.h): the SHM engine fronts this engine on one
  // listen socket and hands non-SHM bundles back here.
  Status AdoptBundle(PartialBundle& b, uint64_t* recv_comm) override {
    if ((b.flags & kPreambleFlagShm) != 0) {
      // SHM hello on a plain TCP engine: the peer runs TPUNET_SHM=1, this
      // process does not — a zero-stream comm would hang; fail loudly.
      b.CloseAll();
      return Status::Inner(
          "peer attempted shared-memory transport but TPUNET_SHM is not "
          "enabled here — set TPUNET_SHM identically on every rank");
    }
    std::vector<int> data_fds;
    for (auto& kv : b.data_fds) data_fds.push_back(kv.second);  // stream-id order
    int ctrl_fd = b.ctrl_fd;
    b.data_fds.clear();
    b.ctrl_fd = -1;
    // Sender's chunk-map inputs win (carried in the preamble) — the CRC
    // flag too: the receiver verifies iff the sender appends trailers. The
    // traffic-class nibble travels the same way (rx accounting).
    return AttachComm(false, b.nstreams, b.min_chunksize, (b.flags & kPreambleFlagCrc) != 0,
                      static_cast<TrafficClass>(PreambleClassOf(b.flags)),
                      (b.flags & kPreambleFlagLanes) != 0,
                      ctrl_fd, data_fds, recv_comm, &recv_comms_);
  }

  Status isend(uint64_t send_comm, const void* data, size_t nbytes, uint64_t* request) override {
    return PostMsg(send_comms_, send_comm,
                   const_cast<uint8_t*>(static_cast<const uint8_t*>(data)), nbytes, request);
  }

  Status irecv(uint64_t recv_comm, void* data, size_t nbytes, uint64_t* request) override {
    return PostMsg(recv_comms_, recv_comm, static_cast<uint8_t*>(data), nbytes, request);
  }

  Status test(uint64_t request, bool* done, size_t* nbytes) override {
    RequestPtr state;
    if (!requests_.Get(request, &state)) {
      return Status::Invalid("unknown request " + std::to_string(request));
    }
    if (state->failed.load(std::memory_order_acquire)) {
      // Failed segments are dropped on the loop thread before failed is set,
      // so the caller's buffer is already quiescent here.
      state->ReleaseQosAdmission();  // consumption point: return budget bytes
      requests_.Erase(request);
      return Status{state->ErrKind(), "request failed: " + state->ErrorMsg()};
    }
    *done = state->Done();
    if (*done) {
      if (nbytes) *nbytes = state->nbytes.load(std::memory_order_acquire);
      RecordRequestStages(state);
      state->ReleaseQosAdmission();  // consumption point: return budget bytes
      requests_.Erase(request);
    }
    return Status::Ok();
  }

  Status wait(uint64_t request, size_t* nbytes) override {
    return WaitIn(requests_, request, nbytes);
  }

  Status close_send(uint64_t send_comm) override {
    CommHandle h;
    if (!send_comms_.Take(send_comm, &h)) {
      return Status::Invalid("unknown send comm " + std::to_string(send_comm));
    }
    CloseOnLoop(h);
    return Status::Ok();
  }

  Status close_recv(uint64_t recv_comm) override {
    CommHandle h;
    if (!recv_comms_.Take(recv_comm, &h)) {
      return Status::Invalid("unknown recv comm " + std::to_string(recv_comm));
    }
    CloseOnLoop(h);
    return Status::Ok();
  }

 private:
  Status AttachComm(bool is_send, uint64_t nstreams, uint64_t min_chunksize, bool crc,
                    TrafficClass cls, bool lanes, int ctrl_fd,
                    const std::vector<int>& data_fds,
                    uint64_t* out_id, IdMap<CommHandle>* map) {
    auto comm = std::make_shared<EComm>();
    comm->is_send = is_send;
    comm->nstreams = nstreams;
    comm->min_chunksize = min_chunksize;
    comm->crc = crc;
    comm->cls = cls;
    comm->lanes = lanes;
    if (lanes) {
      comm->lane_adapt = is_send && lane_adapt_;
      comm->lane_adapt_us = lane_adapt_ms_ * 1000;
      comm->base_weights = LaneBaseWeights();
      // Pre-attach, single-owner: the lock satisfies the TSA contract.
      MutexLock lk(comm->mu);
      comm->weights.assign(nstreams, 1);
      comm->slots = BuildWrrSlots(comm->weights);
      comm->lane_busy_us.assign(nstreams, 0);
      comm->lane_bytes.assign(nstreams, 0);
      comm->lane_rate_bps.assign(nstreams, 0);
    }
    comm->ctrl.fd = ctrl_fd;
    comm->ctrl.is_ctrl = true;
    comm->ctrl.comm = comm.get();
    for (int fd : data_fds) {
      auto fs = std::make_unique<FdState>();
      fs->fd = fd;
      fs->stream_idx = comm->streams.size();
      fs->comm = comm.get();
      comm->streams.push_back(std::move(fs));
    }
    Loop* loop = loops_[next_loop_.fetch_add(1) % loops_.size()].get();
    loop->Post(Command{Command::kAttach, comm, nullptr, 0, nullptr, nullptr});
    uint64_t id = next_id_.fetch_add(1);
    map->Put(id, CommHandle{comm, loop});
    *out_id = id;
    return Status::Ok();
  }

  Status PostMsg(IdMap<CommHandle>& map, uint64_t comm_id, uint8_t* data, size_t nbytes,
                 uint64_t* request) {
    CommHandle h;
    if (!map.Get(comm_id, &h)) {
      return Status::Invalid("unknown comm " + std::to_string(comm_id));
    }
    // QoS admission control (send side): a post over the class's in-flight
    // byte budget fails typed before anything is enqueued or charged.
    uint64_t admitted = 0;
    if (h.comm->is_send) {
      Status as = QosScheduler::Get().AdmitMessage(h.comm->cls, nbytes, &admitted);
      if (!as.ok()) return as;
    }
    auto state = std::make_shared<RequestState>();
    state->qos_cls = static_cast<uint8_t>(h.comm->cls);
    state->qos_admitted = admitted;
    state->t_post_us = MonotonicUs();
    if (watchdog_ms_ > 0) {
      // Progress-watchdog abort hook: a timeout verdict in WaitIn shuts the
      // comm's sockets down; the loop then observes EPOLLHUP/EOF and fails
      // the comm, quiescing every segment (the typed timeout error was set
      // first, so it is the one the caller sees).
      std::weak_ptr<EComm> wc = h.comm;
      state->on_stall = [wc] {
        auto p = wc.lock();
        if (!p) return;
        MutexLock lk(p->mu);
        if (p->ctrl.fd >= 0) ::shutdown(p->ctrl.fd, SHUT_RDWR);
        for (auto& s : p->streams) {
          if (s->fd >= 0) ::shutdown(s->fd, SHUT_RDWR);
        }
      };
    }
    uint64_t id = next_id_.fetch_add(1);
    requests_.Put(id, state);
    // Caller-thread fast path on an idle comm (see Loop::TryInline): the
    // message is dispatched — often fully moved — before this call returns.
    if (!inline_io_ || !h.loop->TryInline(h.comm.get(), data, nbytes, state)) {
      h.comm->queued.fetch_add(1, std::memory_order_acq_rel);
      h.loop->Post(Command{Command::kMsg, h.comm, data, nbytes, state, nullptr});
    }
    *request = id;
    return Status::Ok();
  }

  void CloseOnLoop(CommHandle& h) {
    auto ack = std::make_shared<std::promise<void>>();
    auto fut = ack->get_future();
    h.loop->Post(Command{Command::kClose, h.comm, nullptr, 0, nullptr, ack});
    fut.wait();
  }

  const bool inline_io_;
  std::vector<std::unique_ptr<Loop>> loops_;
  std::atomic<uint64_t> next_loop_{0};
  IdMap<CommHandle> send_comms_;
  IdMap<CommHandle> recv_comms_;
  IdMap<RequestPtr> requests_;
};

}  // namespace

std::unique_ptr<Net> CreateEpollEngine() { return std::make_unique<EpollEngine>(); }

}  // namespace tpunet
