// tpunet EPOLL engine — the second engine behind the TPUNET_IMPLEMENT seam
// (reference's analogue: the TOKIO backend, src/implement/tokio_backend.rs).
// Placeholder for now: falls back to the BASIC engine until the event-loop
// implementation lands. Unlike the reference's TOKIO engine we will keep the
// wire protocol identical to BASIC (the reference's two engines were
// wire-incompatible: 8-byte vs 4-byte length frames, tokio_backend.rs:456)
// and keep BASIC's fair rotating-cursor chunk assignment (the TOKIO engine
// always started at stream 0, tokio_backend.rs:392-404 — a fairness bug).
#include "tpunet/net.h"

namespace tpunet {

std::unique_ptr<Net> CreateEpollEngine() { return CreateBasicEngine(); }

}  // namespace tpunet
