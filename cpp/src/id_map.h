// Sharded concurrent id->value map. Replaces the reference's single global
// Arc<Mutex<Box<dyn Net>>> big-lock (reference: src/lib.rs:14-16) which
// serialized even the hot test() polling path; here each id hashes to one of
// 16 independently-locked shards. Shard locks are leaves of the lock
// hierarchy (docs/DESIGN.md "Concurrency model"): no other lock is ever
// acquired while one is held.
#ifndef TPUNET_ID_MAP_H_
#define TPUNET_ID_MAP_H_

#include <array>
#include <cstdint>
#include <unordered_map>
#include <vector>

#include "tpunet/mutex.h"

namespace tpunet {

template <typename V>
class IdMap {
 public:
  void Put(uint64_t id, V v) {
    Shard& s = shard(id);
    MutexLock lk(s.mu);
    s.m[id] = std::move(v);
  }

  bool Get(uint64_t id, V* out) const {
    const Shard& s = shard(id);
    MutexLock lk(s.mu);
    auto it = s.m.find(id);
    if (it == s.m.end()) return false;
    *out = it->second;
    return true;
  }

  bool Take(uint64_t id, V* out) {
    Shard& s = shard(id);
    MutexLock lk(s.mu);
    auto it = s.m.find(id);
    if (it == s.m.end()) return false;
    *out = std::move(it->second);
    s.m.erase(it);
    return true;
  }

  bool Erase(uint64_t id) {
    Shard& s = shard(id);
    MutexLock lk(s.mu);
    return s.m.erase(id) > 0;
  }

  std::vector<V> DrainAll() {
    std::vector<V> out;
    for (Shard& s : shards_) {
      MutexLock lk(s.mu);
      for (auto& kv : s.m) out.push_back(std::move(kv.second));
      s.m.clear();
    }
    return out;
  }

  size_t Size() const {
    size_t n = 0;
    for (const Shard& s : shards_) {
      MutexLock lk(s.mu);
      n += s.m.size();
    }
    return n;
  }

 private:
  static constexpr size_t kShards = 16;
  struct Shard {
    mutable Mutex mu;
    std::unordered_map<uint64_t, V> m GUARDED_BY(mu);
  };
  Shard& shard(uint64_t id) { return shards_[id % kShards]; }
  const Shard& shard(uint64_t id) const { return shards_[id % kShards]; }
  std::array<Shard, kShards> shards_;
};

}  // namespace tpunet

#endif  // TPUNET_ID_MAP_H_
