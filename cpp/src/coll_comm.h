// Internal declaration of the tpunet communicator, shared by the schedule
// translation units (docs/DESIGN.md "Schedules & algorithm selection").
//
// The communicator owns TOPOLOGY — the wired comm resources:
//   * ring channels (send to (rank+1)%W, recv from (rank-1+W)%W; channel 0
//     from Init, extra channels for overlapping async tickets), and
//   * the lazily-wired pairwise mesh (one send + one recv comm per peer),
// plus the machinery every schedule shares: the chunked exchange pipeline,
// the wire codec fusion, scratch buffers, trace spans, and the async ticket
// workers. SCHEDULES are member functions spread over per-algorithm TUs:
//   schedule_ring.cc — the chunk-pipelined ring (RS+AG AllReduce,
//     ReduceScatter, AllGather, pipelined Broadcast relay);
//   schedule_rhd.cc  — recursive halving-doubling AllReduce over the mesh
//     (2*log2(W') rounds; non-power-of-2 worlds fold the remainder in);
//   schedule_tree.cc — binomial tree (reduce-to-root + bcast AllReduce for
//     small payloads, binomial Broadcast).
// collectives.cc keeps lifecycle, wiring, dispatch and the async machinery.
// Which schedule runs is resolved per call by dispatch.h's selector.
#ifndef TPUNET_SRC_COLL_COMM_H_
#define TPUNET_SRC_COLL_COMM_H_

#include <cstdint>
#include <deque>
#include <functional>
#include <map>
#include <string>
#include <thread>
#include <vector>

#include "dispatch.h"
#include "flightrec.h"
#include "tpunet/bootstrap.h"
#include "tpunet/collectives.h"
#include "tpunet/mutex.h"
#include "tpunet/net.h"
#include "tpunet/qos.h"
#include "tpunet/telemetry.h"
#include "tpunet/utils.h"

namespace tpunet {
namespace internal {

// Broadcast store-and-forward granularity (ring relay AND binomial tree):
// per-chunk forwarding streams the payload instead of paying the full
// buffer's latency per hop.
constexpr size_t kBcastChunk = 1 << 20;

// Reduce-phase pipeline granularity: each ring step streams its slice in
// chunks this size so the reduction of chunk i overlaps the wire transfer of
// chunk i+1 (the NCCL pipelining insight — without it a step is strictly
// transfer-then-reduce and the reduce time adds to the critical path).
inline size_t RingChunkBytes() {
  static const size_t v = GetEnvU64("TPUNET_RING_CHUNKSIZE", 8 << 20);
  return v ? v : (8 << 20);
}

// Tag for the 8-byte hello a lazily-wired extra ring channel sends on its
// first message, distinguishing it from a pairwise-mesh hello (a bare rank,
// always < world) on the shared listener.
constexpr uint64_t kRingHelloTag = 0x52494E47ull << 32;  // "RING"

// Host-grouped topology view derived from the Init handshake's host ids —
// the shared input of the hierarchical schedules (schedule_hier.cc
// AllReduce, schedule_a2a.cc AllToAll). Hosts are ordered by their lowest
// rank; ranks within a host ascend — every rank derives the IDENTICAL
// grouping from the identical host_ids_ vector, which is what lets the
// stages pair up without any extra negotiation.
struct HierTopo {
  std::vector<std::vector<int>> hosts;  // per host, ascending ranks
  std::vector<int> local;  // ranks on my host, ascending (== hosts[hi])
  std::vector<int> inter;  // rank with my local index on each host (uniform only)
  size_t li = 0;           // my index in `local`
  size_t hi = 0;           // my host's index in `hosts`
  size_t R = 0, H = 0;
  bool uniform = false;    // every host carries the same rank count R
};
HierTopo BuildHierTopo(int rank, const std::vector<uint64_t>& ids);

// Public DType/RedOp enums -> the wire-layer ones the reduce kernels use.
inline WireDType ToWireDType(DType d) {
  switch (d) {
    case DType::kF32:
      return WireDType::kF32;
    case DType::kF64:
      return WireDType::kF64;
    case DType::kBF16:
      return WireDType::kBF16;
    case DType::kI32:
      return WireDType::kI32;
    case DType::kI64:
      return WireDType::kI64;
    case DType::kU8:
      return WireDType::kU8;
  }
  return WireDType::kU8;
}

inline WireRedOp ToWireRedOp(RedOp op) {
  switch (op) {
    case RedOp::kSum:
      return WireRedOp::kSum;
    case RedOp::kProd:
      return WireRedOp::kProd;
    case RedOp::kMin:
      return WireRedOp::kMin;
    case RedOp::kMax:
      return WireRedOp::kMax;
  }
  return WireRedOp::kSum;
}

// The 3-operand reduction kernels (dst[i] = a[i] op b[i]) live in utils.cc
// as ReduceInto — SIMD with runtime dispatch, fork-join pool, and the
// tpunet_reduce_bytes_total counter.
inline void Reduce(void* dst, const void* a, const void* b, size_t n,
                   DType dtype, RedOp op) {
  ReduceInto(dst, a, b, n, ToWireDType(dtype), ToWireRedOp(op));
}

// RAII trace span around one collective phase. Every rank runs the same
// collective program, so (comm_id, coll_seq, phase) names the SAME logical
// phase on every rank — the cross-rank join key telemetry.merge_traces()
// aligns per-rank trace files with. Zero cost when tracing is off (the
// caller passes tracing_enabled() as `on`; no string is built either way
// until the destructor fires with on=true) beyond the always-on flight-
// recorder enter/exit events — the ENTER event is what lets the postmortem
// name a phase nobody ever left (a hung rank never runs the destructor).
class PhaseSpan {
 public:
  PhaseSpan(bool on, uint64_t comm_id, uint64_t seq, const char* kind, int step,
            uint64_t nbytes)
      : on_(on), comm_id_(comm_id), seq_(seq), kind_(kind), step_(step),
        nbytes_(nbytes), start_us_(on ? MonotonicUs() : 0) {
    flightrec::Record(flightrec::Ev::kPhaseEnter, comm_id_, seq_, nbytes_,
                      static_cast<uint32_t>(step_ < 0 ? 0 : step_), kind_);
  }
  ~PhaseSpan() {
    flightrec::Record(flightrec::Ev::kPhaseExit, comm_id_, seq_, nbytes_,
                      static_cast<uint32_t>(step_ < 0 ? 0 : step_), kind_);
    if (!on_) return;
    std::string phase =
        step_ < 0 ? std::string(kind_) : std::string(kind_) + "." + std::to_string(step_);
    Telemetry::Get().OnCollPhase(comm_id_, seq_, phase.c_str(), start_us_,
                                 MonotonicUs() - start_us_, nbytes_);
  }
  PhaseSpan(const PhaseSpan&) = delete;
  PhaseSpan& operator=(const PhaseSpan&) = delete;

 private:
  bool on_;
  uint64_t comm_id_, seq_;
  const char* kind_;
  int step_;
  uint64_t nbytes_;
  uint64_t start_us_;
};

class ScheduledCommunicator : public Communicator {
 public:
  // A channel is one independent ring: a send comm to (rank+1)%W and a recv
  // comm from (rank-1+W)%W, plus the scratch its pipelined reduce uses.
  // Channel 0 is wired at Init and carries every blocking collective; extra
  // channels exist so concurrent async tickets can overlap on the wire
  // (ticket k+1's transfer no longer waits for ticket k's reduce).
  struct RingChannel {
    uint64_t send_comm = 0;
    uint64_t recv_comm = 0;
    ScratchBuf scratch;  // chunk landing slots; aligned, never zero-filled
  };

  ScheduledCommunicator(int rank, int world, WireCodec codec, CollAlgo algo,
                        TrafficClass cls)
      : rank_(rank), world_(world), codec_(codec), algo_override_(algo),
        cls_(cls) {}
  ~ScheduledCommunicator() override;

  Status Init(const std::string& coordinator);

  // -- Communicator interface (collectives.cc unless noted) -----------------
  Status AllReduce(const void* sendbuf, void* recvbuf, size_t count, DType dtype,
                   RedOp op) override;
  Status ReduceScatter(const void* sendbuf, void* recvbuf, size_t recv_count,
                       DType dtype, RedOp op) override;  // schedule_ring.cc
  Status AllGather(const void* sendbuf, void* recvbuf, size_t bytes_per_rank)
      override;  // schedule_ring.cc
  Status Broadcast(void* buf, size_t nbytes, int root) override;
  Status AllToAll(const void* sendbuf, void* recvbuf, size_t bytes_per_rank) override;
  Status AllToAllTyped(const void* sendbuf, void* recvbuf, size_t count_per_rank,
                       DType dtype) override;
  Status NeighborExchange(const void* sendbuf, size_t send_nbytes, void* recvbuf,
                          size_t recv_nbytes, size_t* got) override;
  Status Barrier() override;
  Status IAllReduce(const void* sendbuf, void* recvbuf, size_t count, DType dtype,
                    RedOp op, uint64_t* ticket) override;
  Status IAllToAll(const void* sendbuf, void* recvbuf, size_t bytes_per_rank,
                   uint64_t* ticket) override;
  Status WaitTicket(uint64_t ticket) override;
  Status TestTicket(uint64_t ticket, bool* done) override;
  int rank() const override { return rank_; }
  int world_size() const override { return world_; }
  int32_t wire_codec() const override { return static_cast<int32_t>(codec_); }

 private:
  // -- dispatch (collectives.cc) --------------------------------------------
  // Resolve the schedule for an AllReduce/Broadcast of `nbytes` payload and
  // bump tpunet_coll_algo_selected_total. Deterministic from negotiated
  // state, so every rank resolves identically.
  CollAlgo ResolveAlgo(CollKind coll, uint64_t nbytes);
  // Run one AllReduce under the already-resolved schedule (the async ticket
  // job body; blocking calls go through the ticket path or call it inline).
  Status DoAllReduce(const void* sendbuf, void* recvbuf, size_t count, DType dtype,
                     RedOp op, RingChannel& ch, uint64_t seq, CollAlgo algo);

  // -- ring schedule (schedule_ring.cc) -------------------------------------
  Status DoAllReduceRing(const void* sendbuf, void* recvbuf, size_t count,
                         DType dtype, RedOp op, RingChannel& ch, uint64_t seq);
  Status DoBroadcastRing(void* buf, size_t nbytes, int root, uint64_t seq);
  // One pipelined reduce ring step — see schedule_ring.cc for the contract.
  Status ExchangeReduce(const uint8_t* sendbuf, size_t send_nbytes, uint8_t* accum,
                        size_t recv_nbytes, DType dtype, RedOp op, RingChannel& ch,
                        const uint8_t* local = nullptr);
  Status ExchangeReduceCodec(const uint8_t* sendbuf, size_t send_nbytes,
                             uint8_t* accum, size_t recv_nbytes, RedOp op,
                             RingChannel& ch, const uint8_t* local,
                             uint8_t* fused_enc = nullptr, size_t scratch_off = 0);
  Status AgPhaseCodec(float* data, size_t count, RingChannel& ch, uint64_t seq,
                      bool tracing);
  // One ring step: recv from prev into recvbuf while sending sendbuf to next.
  Status Exchange(const void* sendbuf, size_t send_nbytes, void* recvbuf,
                  size_t recv_nbytes, size_t* got, RingChannel& ch);
  Status DrainSends(std::vector<uint64_t>& reqs, Status primary);
  size_t CodecChunkElems() const;

  // -- halving-doubling schedule (schedule_rhd.cc) --------------------------
  Status DoAllReduceRhd(const void* sendbuf, void* recvbuf, size_t count,
                        DType dtype, RedOp op, uint64_t seq);
  // Full-duplex pairwise step on the mesh comms of `peer`; zero-length
  // directions are skipped (empty halving segments at tiny counts) — both
  // sides derive sizes from the same geometry, so the skips pair up.
  Status MeshExchange(int peer, const void* sendbuf, size_t send_nbytes,
                      void* recvbuf, size_t recv_nbytes);
  Status MeshSend(int peer, const void* buf, size_t nbytes);
  Status MeshRecv(int peer, void* buf, size_t nbytes);

  // -- binomial tree schedule (schedule_tree.cc) ----------------------------
  Status DoAllReduceTree(const void* sendbuf, void* recvbuf, size_t count,
                         DType dtype, RedOp op, uint64_t seq);
  Status DoBroadcastTree(void* buf, size_t nbytes, int root, uint64_t seq);

  // -- hierarchical two-level schedule (schedule_hier.cc) -------------------
  // Intra-host ReduceScatter (local ring over the mesh, SHM when
  // TPUNET_SHM=1) -> one-rank-per-host inter-host AllReduce of each local
  // rank's owned shard (ring or rhd among the H same-local-index ranks,
  // picked through the dispatch table) -> intra-host AllGather. Per-rank
  // DCN wire bytes drop to 2*(S/R)*(H-1)/H. Requires a usable hierarchy
  // (>= 2 hosts, uniform R ranks/host — host_ids_ from the Init blob).
  bool HierUsable() const;
  bool HierProfitable() const;  // usable AND R >= 2 (auto-upgrade gate)
  Status DoAllReduceHier(const void* sendbuf, void* recvbuf, size_t count,
                         DType dtype, RedOp op, uint64_t seq);
  // Ring step with DIFFERENT send/recv peers (ring RS/AG inside a rank
  // subgroup rides the pairwise mesh): irecv from `from`, isend to `to`,
  // wait both even on error. Zero-length directions skip (geometry is
  // identical on both sides, so the skips pair).
  Status MeshShift(int to, const void* sendbuf, size_t send_nbytes, int from,
                   void* recvbuf, size_t recv_nbytes);
  // AllReduce over an ordered rank subgroup (group[idx] == rank_) operating
  // in place on `data`; wire rounds counted under hier.intra/hier.inter via
  // `inter`. f32 payloads honor the negotiated codec on the INTER stage
  // (encoded atoms forward verbatim in the AG half, so every group member
  // materializes bit-identical bytes); intra stages ship raw bytes — the
  // whole point of the hierarchy is that those hops are memory-cheap.
  Status SubgroupAllReduce(const std::vector<int>& group, size_t idx,
                           uint8_t* data, size_t count, DType dtype, RedOp op,
                           bool inter, uint64_t seq);
  // Recursive halving-doubling flavor of the above (2*log2(G) rounds) for
  // power-of-two subgroups on uncompressed payloads; the dispatch table's
  // rhd verdict for (shard size, H) routes here. Codec payloads stay on the
  // subgroup ring — its verbatim-forwarding AG is where the cross-rank
  // bit-identity machinery lives.
  Status SubgroupRhdAllReduce(const std::vector<int>& group, size_t idx,
                              uint8_t* data, size_t count, DType dtype,
                              RedOp op, uint64_t seq);

  // -- AllToAll dispatch + flat paths (collectives.cc) ----------------------
  // Resolve the AllToAll schedule for one call: TPUNET_A2A_ALGO override
  // (negotiated at Init) > dispatch table (coll="alltoall") > built-in
  // pairwise, with ApplyHierPolicy upgrading to the two-stage transpose on
  // a profitable topology and the mesh-budget guard routing oversized
  // worlds to the ring relay. Bumps tpunet_coll_algo_selected_total.
  CollAlgo ResolveA2aAlgo(uint64_t bytes_per_rank);
  // Run one byte-oriented AllToAll under the already-resolved schedule
  // (shared by the blocking call, the async ticket job, and the typed
  // wrapper). `ch` carries the ring-relay variant; pairwise/hier ride the
  // mesh. Every flat wire byte lands in tpunet_a2a_bytes_total{stage="flat"}
  // (the hier stages count inside schedule_a2a.cc).
  Status DoAllToAll(const uint8_t* in, uint8_t* out, size_t B, uint64_t seq,
                    CollAlgo algo, RingChannel& ch);
  Status PairwiseAllToAll(const uint8_t* in, uint8_t* out, size_t B);

  // -- hierarchical AllToAll (schedule_a2a.cc) ------------------------------
  // Two-stage transpose over the mesh (docs/DESIGN.md "Hierarchical
  // AllToAll"): R-1 intra-host regroup rounds (H·B bytes each, SHM under
  // TPUNET_SHM=1) land every block destined to a local-index-li rank on
  // this rank, then H-1 inter-host column rounds (R·B bytes each, the only
  // DCN hops) complete the exchange. Requires a usable hierarchy.
  Status DoAllToAllHier(const uint8_t* in, uint8_t* out, size_t B, uint64_t seq);

  // -- wiring / lifecycle (collectives.cc) ----------------------------------
  Status ConnectAndWire(const SocketHandle& next_handle);
  Status AcceptHello(uint64_t* rc, uint64_t* hello);
  Status ConnectHello(int peer, uint64_t hello, uint64_t* comm);
  Status EnsureMesh();
  // EnsureMesh plus a one-time ring-step quiesce OVER THE MESH COMMS: no
  // rank proceeds past the first mesh use until EVERY rank finished wiring,
  // so a later listener-touching op (EnsureAsyncChannels on a fast rank)
  // can never be mistaken for a mesh connect by a peer still in its accept
  // loop. Riding the mesh (not channel 0) keeps mesh-queue jobs disjoint
  // from ring-channel traffic — what lets async mesh tickets overlap ring
  // tickets.
  Status EnsureMeshQuiesced();
  Status EnsureAsyncChannels(size_t nch);
  static size_t AsyncChannelCount();

  // -- async worker machinery (collectives.cc) ------------------------------
  bool TicketLive(uint64_t ticket) REQUIRES(async_mu_);
  // First async submission: wire the extra ring channels and spawn one
  // worker per queue (ring queues 0..C-1 plus the dedicated mesh queue C).
  Status EnsureAsyncWorkers() REQUIRES(async_mu_);
  // Queue index of the dedicated mesh worker — the serialization domain of
  // every mesh-comm job (rhd/tree/hier/a2a share the one pairwise mesh, so
  // they must run one at a time and in submission order), kept OFF the ring
  // channels so a mesh ticket can overlap ring tickets on disjoint comms.
  size_t MeshQueueIndex() REQUIRES(async_mu_) { return queues_.size() - 1; }
  void AsyncWorkerLoop(size_t ch);
  bool AsyncIdle() REQUIRES(async_mu_);
  void FenceAsync();
  void StopAsyncWorker();

  Status WaitRequest(uint64_t req, size_t* nbytes) {
    // Blocking condvar wait — a test() poll loop here competes with the
    // stream worker threads for CPU (catastrophic on few-core hosts).
    return net_->wait(req, nbytes);
  }

  // The codec engages only where elements are KNOWN f32: AllReduce /
  // ReduceScatter payloads and the AG phase inside AllReduce. The
  // byte-oriented collectives (AllGather, Broadcast, AllToAll,
  // NeighborExchange, Barrier) carry opaque bytes — rendezvous handles,
  // tokens, arbitrary dtypes — and are never lossily compressed
  // (docs/DESIGN.md "Compressed collectives").
  bool UseCodec(DType dtype) const {
    return codec_ != WireCodec::kF32 && dtype == DType::kF32 && world_ > 1;
  }

  int rank_;
  int world_;
  // Wire compression codec for f32 collectives, fixed at construction and
  // verified equal across ranks by the Init handshake (UseCodec above).
  WireCodec codec_ = WireCodec::kF32;
  // Per-communicator schedule override (kAuto = per-size selection) and the
  // dispatch table loaded from TPUNET_DISPATCH_TABLE. Both are negotiated
  // at Init — (override, table CRC) ride the codec handshake — so every
  // rank resolves the same schedule for the same collective.
  CollAlgo algo_override_ = CollAlgo::kAuto;
  // AllToAll schedule override (TPUNET_A2A_ALGO; the legacy TPUNET_A2A=ring
  // spelling folds in as a kRing override). Negotiated at Init — the byte
  // rides the same handshake blob — because half a world on the pairwise
  // mesh and half on the two-stage transpose deadlocks, it never corrupts.
  CollAlgo a2a_override_ = CollAlgo::kAuto;
  // QoS traffic class for every comm this communicator wires (latency for
  // serving P2P links, bulk for gradient rings, control for bootstrap-ish
  // traffic). Negotiated at Init — the class byte rides the codec/algo
  // handshake — so the whole group schedules under one class.
  TrafficClass cls_ = TrafficClass::kBulk;
  DispatchTable dispatch_;
  std::unique_ptr<Net> net_;
  std::unique_ptr<Bootstrap> bootstrap_;
  uint64_t listen_comm_ = 0;
  // Collective tracing identity: comm_id hashes (coordinator, world) — the
  // same on every rank — and coll_seq_ counts collectives in program order
  // (MPI semantics make the program identical across ranks), so
  // (trace_comm_id_, coll_seq_, phase) tags match rank-to-rank.
  uint64_t trace_comm_id_ = 0;
  uint64_t coll_seq_ = 0;
  // channels_[0] is the Init-wired ring every blocking collective uses;
  // channels_[1..] are wired by EnsureAsyncChannels for overlapping async
  // tickets. Stable after the first IAllReduce (workers capture indices).
  std::vector<RingChannel> channels_;
  // Scratch buffers reused across calls; a Communicator is not thread-safe
  // (one collective at a time, like an MPI communicator).
  // Pairwise-mesh comms for AllToAll and the rhd/tree schedules, keyed by
  // peer rank (0 = unwired / self). Wired lazily by EnsureMesh from
  // all_handles_; mesh_quiesced_ records the one-time wiring barrier.
  std::vector<SocketHandle> all_handles_;
  // Per-rank host ids from the Init handshake blob (utils.h HostId()) —
  // the topology input of the hierarchical schedule. Size world_ (a
  // single-rank world holds just its own id).
  std::vector<uint64_t> host_ids_;
  std::vector<uint64_t> mesh_send_;
  std::vector<uint64_t> mesh_recv_;
  bool mesh_quiesced_ = false;
  ScratchBuf work_;
  std::vector<uint8_t> barrier_scratch_;
  ScratchBuf a2a_fwd_, a2a_rcv_;
  // Hierarchical-AllToAll staging: slot (j, h) holds the block from local
  // source j destined to host h's local-index-li rank (schedule_a2a.cc
  // layout), plus the typed wrapper's encoded in/out assemblies (scale
  // blocks restart per (src, dst) block — the bit-identity contract).
  ScratchBuf a2a_stage_, a2a_enc_in_, a2a_enc_out_;
  // Mesh-schedule scratch (rhd halves / tree partials, and the encoded-atom
  // assembly the codec AG forwards verbatim). Non-ring jobs serialize on
  // channel 0's queue — or run on the fenced caller thread — so one set
  // suffices; never touched by two threads at once.
  ScratchBuf mesh_scratch_, mesh_enc_;
  // Async (nonblocking-collective) state; async_mu_ guards all of it. Worker
  // c is the only place async jobs touch channel c's comms/scratch, and
  // FenceAsync keeps the sync paths out while any job runs. async_mu_ is
  // released before any job executes, so it is never held around engine or
  // request locks (docs/DESIGN.md "Concurrency model").
  Mutex async_mu_;
  CondVar work_cv_, done_cv_;
  std::vector<std::deque<std::pair<uint64_t, std::function<Status()>>>> queues_
      GUARDED_BY(async_mu_);
  std::vector<uint64_t> running_ GUARDED_BY(async_mu_);
  std::map<uint64_t, Status> done_ GUARDED_BY(async_mu_);
  Status async_wire_status_ = Status::Ok();
  uint64_t next_ticket_ GUARDED_BY(async_mu_) = 1;
  bool worker_started_ GUARDED_BY(async_mu_) = false;
  bool stop_ GUARDED_BY(async_mu_) = false;
  // Joined in StopAsyncWorker AFTER async_mu_ is released (a worker must be
  // able to take the lock to observe stop_), so the vector itself cannot be
  // async_mu_-guarded; it only grows under the lock in IAllReduce.
  std::vector<std::thread> workers_;
};

}  // namespace internal
}  // namespace tpunet

#endif  // TPUNET_SRC_COLL_COMM_H_
