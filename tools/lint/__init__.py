"""tpunet invariant lint suite — cross-layer registry checkers.

The C++ core and the Python binding share several registries that nothing
type-checks across the language boundary: the env-var inventory
(``Config.from_env`` vs every ``GetEnv``/``os.environ`` read site), the
Prometheus metric catalogue (``metrics.cc`` vs ``tpunet/telemetry.py``
consumers), the error-code table (``c_api.h`` ``TPUNET_ERR_*`` vs the typed
exceptions in ``tpunet/_native.py``), the C ABI itself (declarations vs
``extern "C"`` definitions vs ctypes bindings), and every wire contract —
preamble flag bits, ctrl-frame opcodes and layouts, bootstrap-blob offsets,
serve frame structs, chaos-grammar tokens — against the declarative registry
in ``tools/protocol/spec.py``. Each has drifted silently in at least one
real transport project; here drift is a red CI lane.

Checkers are pure functions ``check_*(root: Path) -> list[str]`` returning
human-readable violations (empty = clean), so tests can point them at tiny
negative-fixture trees to prove each one actually fires
(``tests/test_lint.py``, ``tests/test_protocol_lint.py``). Run all five
from the CLI with ``python -m tools.lint``.
"""

from __future__ import annotations

from pathlib import Path

from tools.lint.cabi import check_c_abi
from tools.lint.envvars import check_env_registry
from tools.lint.errcodes import check_error_codes
from tools.lint.metricsreg import check_metric_registry


def _check_protocol(root: Path) -> list[str]:
    # Deferred: tools.protocol reuses tools.lint._util, so a module-level
    # import here would be circular whenever tools.protocol is imported
    # first (importing any tools.lint submodule runs this __init__).
    from tools.protocol import check_protocol
    return check_protocol(root)


CHECKERS = {
    "env-registry": check_env_registry,
    "metric-registry": check_metric_registry,
    "error-codes": check_error_codes,
    "c-abi": check_c_abi,
    "protocol": _check_protocol,
}


def run_all(root: Path) -> dict[str, list[str]]:
    """Run every checker against the tree at `root`; returns name->violations."""
    return {name: checker(Path(root)) for name, checker in CHECKERS.items()}
