"""Env-var registry checker.

Invariants enforced over the tree:

1. Every ``TPUNET_*`` env var READ anywhere in the C++ core
   (``GetEnv``/``GetEnvU64``/``getenv``) or the ``tpunet`` Python package
   (``os.environ.get`` / ``os.environ[...]`` / ``os.getenv``) must be
   registered in ``tpunet/config.py`` — i.e. appear in ``Config.from_env``'s
   inventory — or carry an explicit ALLOWLIST entry with a reason. An
   unregistered read is exactly how the reference project accumulated knobs
   nobody could enumerate (SURVEY §5).

2. Every var in that surface (read sites ∪ registry ∪ allowlist) must be
   mentioned in ``docs/*.md`` — an operator grepping the docs for a knob
   they found in a traceback must land somewhere.

``tpunet/config.py`` itself is the registry, so its own read sites don't
count as consumers for invariant 1.
"""

from __future__ import annotations

import re
from pathlib import Path

from tools.lint._util import find_with_lines, iter_files, read_text, strip_c_comments

# Vars legitimately consumed outside the Config inventory; each entry needs
# a reason AND (invariant 2) a docs/*.md mention like everything else.
ALLOWLIST = {
    # Load-time override for the ctypes loader: it selects WHICH libtpunet.so
    # to dlopen, so it is consumed before any Config (or the library whose
    # behavior Config describes) exists.
    "TPUNET_LIBRARY_PATH": "pre-load .so path override, consumed before Config exists",
    # Developer-only stderr tracing for the weight-swap pipeline: read once
    # at import for near-zero steady-state cost; not an operator knob, so
    # it stays out of the Config surface.
    "TPUNET_SWAP_DEBUG": "swap-pipeline stderr tracing, import-time dev switch",
}

_CPP_READ = re.compile(r'(?:GetEnvU64|GetEnv|getenv)\(\s*"(TPUNET_[A-Z0-9_]+)"')
_PY_READ = re.compile(
    r'(?:os\.environ\.get|environ\.get|os\.environ\[|environ\[|os\.getenv)'
    r'\(?\s*["\'](TPUNET_[A-Z0-9_]+)["\']'
)
_ANY_NAME = re.compile(r"TPUNET_[A-Z0-9_]+")

_CPP_GLOBS = ("cpp/src/**/*.cc", "cpp/src/**/*.h", "cpp/include/**/*.h")
_PY_GLOBS = ("tpunet/**/*.py",)


def _read_sites(root: Path) -> dict[str, list[str]]:
    sites: dict[str, list[str]] = {}
    for path in iter_files(root, _CPP_GLOBS):
        text = strip_c_comments(read_text(path))
        for name, line in find_with_lines(text, _CPP_READ):
            sites.setdefault(name, []).append(f"{path.relative_to(root)}:{line}")
    for path in iter_files(root, _PY_GLOBS):
        if path.name == "config.py" and path.parent.name == "tpunet":
            continue  # the registry itself
        for name, line in find_with_lines(read_text(path), _PY_READ):
            sites.setdefault(name, []).append(f"{path.relative_to(root)}:{line}")
    return sites


def _registry(root: Path) -> set[str]:
    config = root / "tpunet" / "config.py"
    if not config.is_file():
        return set()
    return set(_ANY_NAME.findall(read_text(config)))


def _doc_names(root: Path) -> set[str]:
    names: set[str] = set()
    for path in iter_files(root, ("docs/*.md",)):
        names.update(_ANY_NAME.findall(read_text(path)))
    return names


def check_env_registry(root: Path) -> list[str]:
    root = Path(root)
    sites = _read_sites(root)
    registry = _registry(root)
    docs = _doc_names(root)
    violations: list[str] = []
    for name in sorted(sites):
        if name not in registry and name not in ALLOWLIST:
            where = ", ".join(sites[name][:3])
            violations.append(
                f"env var {name} is read at {where} but is neither registered in "
                f"tpunet/config.py (Config.from_env) nor allowlisted in "
                f"tools/lint/envvars.py"
            )
    # Doc coverage over the vars this TREE actually has (read or registered);
    # allowlisted names are doc-checked through their read sites, so an
    # allowlist entry unused by a (fixture) tree imposes nothing on it.
    for name in sorted(set(sites) | registry):
        if name not in docs:
            violations.append(
                f"env var {name} has no mention in docs/*.md (operators must be "
                f"able to grep the docs for every knob)"
            )
    return violations
