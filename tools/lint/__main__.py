"""CLI entry: ``python -m tools.lint [root]`` — run all four invariant
checkers; exit 1 if any violation is found (the CI analysis lane's gate)."""

from __future__ import annotations

import sys
from pathlib import Path

from tools.lint import run_all


def main(argv: list[str]) -> int:
    root = Path(argv[1]) if len(argv) > 1 else Path(__file__).resolve().parents[2]
    results = run_all(root)
    total = 0
    for name, violations in results.items():
        status = "ok" if not violations else f"{len(violations)} violation(s)"
        print(f"[{name}] {status}")
        for v in violations:
            print(f"  - {v}")
        total += len(violations)
    if total:
        print(f"\ntools.lint: {total} violation(s) across {sum(1 for v in results.values() if v)} checker(s)")
        return 1
    print("tools.lint: all invariants hold")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
