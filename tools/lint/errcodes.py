"""Error-code bijection checker.

The C ABI's ``TPUNET_OK`` / ``TPUNET_ERR_*`` codes (``cpp/include/tpunet/
c_api.h``) and the Python constants + typed exceptions in
``tpunet/_native.py`` must agree exactly:

1. Same name set, same numeric values, both directions (an orphan on either
   side means a failure class that one layer can raise and the other cannot
   name).
2. Every failure-model code (value <= -4, i.e. beyond the reference's
   null/invalid/inner trio that maps to plain NativeError) has a typed
   exception registered in ``_TYPED_ERRORS``, and that exception class is
   actually defined in ``_native.py``.
"""

from __future__ import annotations

import re
from pathlib import Path

from tools.lint._util import read_text, strip_c_comments

_H_DEFINE = re.compile(r"#define\s+(TPUNET_(?:OK|ERR_[A-Z0-9_]+))\s+(-?\d+)")
_PY_CONST = re.compile(r"^(TPUNET_(?:OK|ERR_[A-Z0-9_]+))\s*=\s*(-?\d+)", re.M)
_TYPED_BLOCK = re.compile(r"_TYPED_ERRORS\s*=\s*\{(.*?)\}", re.S)
_TYPED_ENTRY = re.compile(r"(TPUNET_ERR_[A-Z0-9_]+)\s*:\s*([A-Za-z_]\w*)")

# Base codes whose Python surface is the untyped NativeError itself.
_BASE_CODES = {"TPUNET_OK", "TPUNET_ERR_NULL", "TPUNET_ERR_INVALID", "TPUNET_ERR_INNER"}


def check_error_codes(root: Path) -> list[str]:
    root = Path(root)
    header = root / "cpp" / "include" / "tpunet" / "c_api.h"
    native = root / "tpunet" / "_native.py"
    violations: list[str] = []
    if not header.is_file() or not native.is_file():
        return [f"missing {header.name if not header.is_file() else native.name} — "
                f"error-code bijection unverifiable"]

    h_codes = {name: int(v) for name, v in _H_DEFINE.findall(strip_c_comments(read_text(header)))}
    py_text = read_text(native)
    py_codes = {name: int(v) for name, v in _PY_CONST.findall(py_text)}

    for name in sorted(set(h_codes) - set(py_codes)):
        violations.append(
            f"{name} (= {h_codes[name]}) is defined in c_api.h but has no constant "
            f"in tpunet/_native.py"
        )
    for name in sorted(set(py_codes) - set(h_codes)):
        violations.append(
            f"{name} (= {py_codes[name]}) exists in tpunet/_native.py but not in "
            f"c_api.h — Python names a code the ABI cannot return"
        )
    for name in sorted(set(h_codes) & set(py_codes)):
        if h_codes[name] != py_codes[name]:
            violations.append(
                f"{name} value mismatch: c_api.h says {h_codes[name]}, "
                f"_native.py says {py_codes[name]}"
            )

    typed_m = _TYPED_BLOCK.search(py_text)
    typed = dict(_TYPED_ENTRY.findall(typed_m.group(1))) if typed_m else {}
    for name, value in sorted(h_codes.items()):
        if name in _BASE_CODES or value > -4:
            continue
        if name not in typed:
            violations.append(
                f"failure-model code {name} (= {value}) has no typed exception in "
                f"_native.py _TYPED_ERRORS — it would surface as a bare NativeError"
            )
    for name, cls in sorted(typed.items()):
        if name not in py_codes:
            violations.append(f"_TYPED_ERRORS maps unknown code constant {name}")
        if not re.search(rf"class\s+{cls}\s*\(", py_text):
            violations.append(f"_TYPED_ERRORS names exception class {cls} which is not defined")
    return violations
