"""Shared helpers for the invariant lint checkers."""

from __future__ import annotations

import re
from pathlib import Path
from typing import Iterator

_BLOCK_COMMENT = re.compile(r"/\*.*?\*/", re.S)
_LINE_COMMENT = re.compile(r"//[^\n]*")


def strip_c_comments(text: str) -> str:
    """Remove /* */ and // comments, preserving line structure for /* */
    so line numbers of surviving code stay meaningful."""
    text = _BLOCK_COMMENT.sub(lambda m: "\n" * m.group(0).count("\n"), text)
    return _LINE_COMMENT.sub("", text)


def iter_files(root: Path, patterns: tuple[str, ...]) -> Iterator[Path]:
    """Yield files under `root` matching any glob pattern, skipping caches
    and build trees; tolerant of missing directories (negative fixtures are
    tiny synthesized trees)."""
    for pattern in patterns:
        for path in sorted(root.glob(pattern)):
            if "__pycache__" in path.parts or "build" in path.parts:
                continue
            if path.is_file():
                yield path


def read_text(path: Path) -> str:
    return path.read_text(encoding="utf-8", errors="replace")


def find_with_lines(text: str, regex: re.Pattern[str]) -> Iterator[tuple[str, int]]:
    """Yield (first capture group, 1-based line number) for every match."""
    for m in regex.finditer(text):
        yield m.group(1), text.count("\n", 0, m.start()) + 1
