"""C-ABI completeness checker.

Three-way agreement over the stable ABI surface (``tpunet_c_*`` and
``tpunet_comm_*``):

1. Every symbol DECLARED in ``cpp/include/tpunet/c_api.h`` has an
   ``extern "C"`` DEFINITION in some ``cpp/src/*.cc`` (a declared-but-
   undefined symbol only explodes at dlopen/link time, far from the edit).
2. Every such definition in ``cpp/src`` is declared in the header (no
   undocumented ABI surface creeping in).
3. Every declared symbol has a ctypes binding (``lib.<name>``) in
   ``tpunet/_native.py`` — a missing binding is the drift that makes Python
   crash with an AttributeError the first time a code path is exercised in
   production rather than at import.
"""

from __future__ import annotations

import re
from pathlib import Path

from tools.lint._util import iter_files, read_text, strip_c_comments

_SYM = r"tpunet_(?:c|comm)_[a-z0-9_]+"
_DECL = re.compile(rf"\b({_SYM})\s*\(")
# A definition: symbol, argument list (no ; { } inside), then an opening
# brace. Calls end with ');' and never match.
_DEF_TEMPLATE = r"\b{name}\s*\([^;{{}}]*\)\s*\{{"


def check_c_abi(root: Path) -> list[str]:
    root = Path(root)
    header = root / "cpp" / "include" / "tpunet" / "c_api.h"
    native = root / "tpunet" / "_native.py"
    violations: list[str] = []
    if not header.is_file():
        return ["cpp/include/tpunet/c_api.h not found — C ABI unverifiable"]

    declared = set(_DECL.findall(strip_c_comments(read_text(header))))

    src_texts = {
        path: strip_c_comments(read_text(path))
        for path in iter_files(root, ("cpp/src/*.cc",))
    }
    defined: set[str] = set()
    for text in src_texts.values():
        for name in set(_DECL.findall(text)):
            if re.search(_DEF_TEMPLATE.format(name=re.escape(name)), text, re.S):
                defined.add(name)

    for name in sorted(declared - defined):
        violations.append(
            f"{name} is declared in c_api.h but has no definition in cpp/src/*.cc"
        )
    for name in sorted(defined - declared):
        violations.append(
            f"{name} is defined in cpp/src but not declared in c_api.h — "
            f"undocumented ABI surface"
        )

    if native.is_file():
        py_text = read_text(native)
        bound = set(re.findall(rf"\blib\.({_SYM})", py_text)) | set(
            re.findall(rf"\b_lib\.({_SYM})", py_text)
        )
        for name in sorted(declared - bound):
            violations.append(
                f"{name} is declared in c_api.h but has no ctypes binding "
                f"(lib.{name}) in tpunet/_native.py"
            )
        for name in sorted(bound - declared):
            violations.append(
                f"tpunet/_native.py binds lib.{name} which is not declared in c_api.h"
            )
    else:
        violations.append("tpunet/_native.py not found — ctypes bindings unverifiable")
    return violations
