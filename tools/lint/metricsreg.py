"""Metric registry checker.

The Prometheus catalogue lives in ``cpp/src/metrics.cc`` (``family(...)``
registrations, the ``size_hist``/``stage_hist`` helpers, and the
``TcpGaugeDef`` table); consumers live across the language boundary in
``tpunet/telemetry.py``, the tests, and the benchmarks. Invariants:

1. Every family is declared exactly once (a duplicated family emits a
   Prometheus exposition that fails text-format lint).
2. Names are ``tpunet_`` + snake_case with a recognized unit/kind suffix —
   or carry a NAMING_EXCEPTIONS entry with a reason (reference-compat names
   predate the convention).
3. Direct label sets are consistent: one family never emits with two
   different label-key sets (``le`` excluded, histogram ``_bucket``/``_sum``/
   ``_count`` series folded into their base family).
4. Every ``tpunet_*`` metric name referenced from the Python layer
   (telemetry module, telemetry/perf tests, engine benchmarks) exists in the
   C++ registry — the drift that turns dashboards silently blank.
"""

from __future__ import annotations

import re
from pathlib import Path

from tools.lint._util import read_text, strip_c_comments

# Recognized unit / kind suffixes (Prometheus naming conventions, adapted:
# byte counts, microseconds, bits-per-second, totals, and the reference's
# nbytes histogram spelling).
UNIT_SUFFIXES = (
    "_total",
    "_bytes",
    "_us",
    "_bps",
    "_nbytes",
    "_per_second",
)

# Families allowed to break the suffix rule; every entry needs a reason.
NAMING_EXCEPTIONS = {
    "tpunet_hold_on_request": "reference-compat gauge name (tokio:184-190)",
    "tpunet_failed_requests": "reference-compat counter name",
    "tpunet_stream_cwnd": "unit is TCP segments (tcpi_snd_cwnd), not a measure",
    "tpunet_stream_fairness_jain": "dimensionless Jain index in [0,1]",
    "tpunet_faults_injected": "label-less compat twin of tpunet_faults_injected_total",
    "tpunet_codec_wire_ratio": "dimensionless encoded/payload byte ratio in (0, 1]",
    "tpunet_serve_queue_depth": "instantaneous request count per serving tier (dimensionless gauge)",
    "tpunet_lane_weight": "dimensionless stripe weight (1..16) per lane in the WRR scheduler",
    "tpunet_world_size": "dimensionless rank count of the live communicator (churn gauge)",
    "tpunet_weight_version": "dimensionless checkpoint version stamp (hot-swap gauge)",
}

_SNAKE = re.compile(r"^tpunet_[a-z0-9]+(?:_[a-z0-9]+)*$")
_FAMILY = re.compile(r'family\(\s*"(tpunet_[a-z0-9_]+)"')
_HIST_HELPER = re.compile(r'(?:size_hist|stage_hist)\(\s*"(tpunet_[a-z0-9_]+)"')
_GAUGE_TABLE = re.compile(r'\{\s*"(tpunet_[a-z0-9_]+)"\s*,\s*"(?:gauge|counter|histogram)"')
# Inside C++ string literals the label quotes are escaped (rank=\"%lld\"),
# so the label body may contain \" sequences but no bare quote.
_EMIT_LABELED = re.compile(r'"(tpunet_[a-z0-9_]+)\{((?:\\"|[^}"])*)\}')
_LABEL_KEY = re.compile(r"([a-zA-Z_][a-zA-Z0-9_]*)=")
_PY_REF = re.compile(r'["\'](tpunet_[a-z0-9_]+)["\']')

# Python files whose tpunet_* string literals are treated as metric-name
# consumers. tpunet_c_* / tpunet_comm_* ABI symbols are filtered out.
_CONSUMER_FILES = (
    "tpunet/telemetry.py",
    "tests/test_telemetry.py",
    "tests/telemetry_smoke.py",
    "tests/perf_smoke.py",
    "benchmarks/engine_p2p.py",
)

_SERIES_SUFFIXES = ("_bucket", "_sum", "_count")

# Synthetic names fed to the Prometheus text PARSER's unit tests
# (tests/test_telemetry.py builds hand-written expositions to pin _LINE's
# grammar) — they are parser inputs, not references to real families.
PARSER_FIXTURES = {
    "tpunet_uptime_seconds",
    "tpunet_rate",
    "tpunet_bad_value",
    "tpunet_demo",
}


def registry_families(root: Path) -> set[str]:
    """The set of metric families registered in cpp/src/metrics.cc.

    Shared with tests/test_telemetry.py's registry-driven reset test: every
    family the C++ layer declares must sample zero after ``reset()`` (modulo
    a short, documented exception list) — generated from the same parse the
    lint checker uses, so a newly registered family is reset-covered on the
    day it lands or the test names it."""
    metrics_cc = Path(root) / "cpp" / "src" / "metrics.cc"
    return set(_registrations(strip_c_comments(read_text(metrics_cc))))


def _base_family(name: str) -> str:
    for suffix in _SERIES_SUFFIXES:
        if name.endswith(suffix):
            return name[: -len(suffix)]
    return name


def _registrations(text: str) -> list[str]:
    regs: list[str] = []
    for regex in (_FAMILY, _HIST_HELPER, _GAUGE_TABLE):
        regs.extend(regex.findall(text))
    return regs


def check_metric_registry(root: Path) -> list[str]:
    root = Path(root)
    metrics_cc = root / "cpp" / "src" / "metrics.cc"
    if not metrics_cc.is_file():
        return ["cpp/src/metrics.cc not found — metric registry unverifiable"]
    text = strip_c_comments(read_text(metrics_cc))
    regs = _registrations(text)
    registry = set(regs)
    violations: list[str] = []

    # 1. declared exactly once
    seen: set[str] = set()
    for name in regs:
        if name in seen:
            violations.append(f"metric family {name} is registered more than once in metrics.cc")
        seen.add(name)

    # 2. naming convention
    for name in sorted(registry):
        if not _SNAKE.match(name):
            violations.append(f"metric family {name} is not tpunet_ snake_case")
            continue
        if name.endswith(UNIT_SUFFIXES):
            continue
        if name not in NAMING_EXCEPTIONS:
            violations.append(
                f"metric family {name} has no unit suffix {UNIT_SUFFIXES} and no "
                f"NAMING_EXCEPTIONS entry in tools/lint/metricsreg.py"
            )

    # 3. direct label-set consistency (families emitted via %s format
    # helpers — histograms, the TCP gauge table — are uniform by
    # construction and not visible to this pass).
    label_sets: dict[str, set[frozenset[str]]] = {}
    emitted: set[str] = set()
    for name, labels in _EMIT_LABELED.findall(text):
        base = _base_family(name)
        emitted.add(base)
        keys = frozenset(k for k in _LABEL_KEY.findall(labels) if k != "le")
        label_sets.setdefault(base, set()).add(keys)
    for base, sets in sorted(label_sets.items()):
        if len(sets) > 1:
            pretty = " vs ".join(sorted("{" + ",".join(sorted(s)) + "}" for s in sets))
            violations.append(f"metric family {base} emits inconsistent label sets: {pretty}")

    # Emitted-but-never-registered (a family() call was dropped while its
    # emit survived → exposition lint failure at runtime).
    for base in sorted(emitted - registry):
        violations.append(f"metric {base} is emitted in metrics.cc but never registered via family()")

    # 4. cross-layer references resolve
    for rel in _CONSUMER_FILES:
        path = root / rel
        if not path.is_file():
            continue
        for name in sorted(set(_PY_REF.findall(read_text(path)))):
            if name.startswith(("tpunet_c_", "tpunet_comm_", "tpunet_xla_")):
                continue  # ABI symbols, not metrics
            if _base_family(name) in PARSER_FIXTURES:
                continue
            if _base_family(name) not in registry:
                violations.append(
                    f"{rel} references metric {name} which does not exist in the "
                    f"metrics.cc registry"
                )
    return violations
