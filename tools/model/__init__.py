"""Explicit-state model checking for tpunet's distributed protocols.

``python -m tools.model --all`` exhaustively explores small-shape models of
the five protocol state machines whose bugs do not reproduce under test
schedulers: single-stream failover, the DRR wire-credit scheduler, the SHM
async-ack handshake, the 4-phase elastic rewire, and the weight-swap flip.
Each model is a faithful abstraction of the implementation (module
docstrings cite the code they model) checked at shapes small enough for
full-state-space BFS — W<=3, bounded queues — which is exactly the regime
where protocol bugs live (every published consensus bug has a tiny witness).

The harness is deliberately minimal:

  * a **Model** exposes ``init_states()`` (hashable states),
    ``actions(state) -> [(label, next_state), ...]`` (the transition
    relation), ``invariant(state) -> str | None`` (safety), ``done(state)``
    (states where quiescence is legal), and ``progress(label)`` (which
    transitions count as real work, for livelock detection).
  * ``explore()`` BFSes the reachable graph, checking the invariant on
    every state, flagging **deadlock** (no enabled action, not done) and
    **livelock** (a reachable cycle of only non-progress transitions), and
    reconstructs a minimal counterexample trace through BFS parent links.

Sharpness is part of the contract: every model ships a ``MUTATIONS`` table
of seeded protocol bugs (the real-world failure modes the model exists to
catch), and ``tests/test_model_check.py`` proves the checker goes RED on
every one — a model that cannot fail is documentation, not verification.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Hashable, Iterable


@dataclass
class Counterexample:
    kind: str                      # "invariant" | "deadlock" | "livelock"
    message: str
    trace: list[tuple[str, Hashable]]  # (action label, resulting state), trace[0] label is "<init>"

    def render(self) -> str:
        lines = [f"{self.kind}: {self.message}", "trace:"]
        lines += [f"  {i:3d}. {label:<28} {state!r}"
                  for i, (label, state) in enumerate(self.trace)]
        return "\n".join(lines)


@dataclass
class Result:
    name: str
    ok: bool
    states: int
    transitions: int
    error: Counterexample | None = None


@dataclass
class Model:
    """A protocol state machine. States must be hashable and immutable."""
    name: str
    init_states: Callable[[], Iterable[Hashable]]
    actions: Callable[[Hashable], Iterable[tuple[str, Hashable]]]
    invariant: Callable[[Hashable], str | None]
    done: Callable[[Hashable], bool]
    # Labels that constitute forward progress; a reachable cycle made purely
    # of non-progress transitions is a livelock (the system can spin forever
    # without doing work). Default: every transition is progress (disables
    # livelock detection).
    progress: Callable[[str], bool] = field(default=lambda label: True)


def _trace_to(state: Hashable,
              parent: dict[Hashable, tuple[Hashable, str] | None]) -> list[tuple[str, Hashable]]:
    out: list[tuple[str, Hashable]] = []
    cur: Hashable | None = state
    while cur is not None:
        link = parent[cur]
        if link is None:
            out.append(("<init>", cur))
            cur = None
        else:
            prev, label = link
            out.append((label, cur))
            cur = prev
    out.reverse()
    return out


def explore(model: Model, max_states: int = 2_000_000) -> Result:
    """BFS the full reachable state space; first violation wins (BFS order
    makes its trace minimal in steps)."""
    parent: dict[Hashable, tuple[Hashable, str] | None] = {}
    queue: deque[Hashable] = deque()
    transitions = 0
    # Edges kept only for the livelock pass; (src, label, dst).
    nonprogress_edges: dict[Hashable, list[tuple[str, Hashable]]] = {}

    def fail(kind: str, msg: str, state: Hashable,
             extra: list[tuple[str, Hashable]] = []) -> Result:
        cex = Counterexample(kind, msg, _trace_to(state, parent) + extra)
        return Result(model.name, False, len(parent), transitions, cex)

    for s in model.init_states():
        if s not in parent:
            parent[s] = None
            queue.append(s)

    while queue:
        state = queue.popleft()
        msg = model.invariant(state)
        if msg is not None:
            return fail("invariant", msg, state)
        acts = list(model.actions(state))
        if not acts and not model.done(state):
            return fail("deadlock", "no enabled action in a non-terminal state", state)
        for label, nxt in acts:
            transitions += 1
            if not model.progress(label):
                nonprogress_edges.setdefault(state, []).append((label, nxt))
            if nxt not in parent:
                parent[nxt] = (state, label)
                if len(parent) > max_states:
                    raise RuntimeError(
                        f"model {model.name}: state space exceeds {max_states} — "
                        f"shrink the shape, exhaustive exploration is the point")
                queue.append(nxt)

    # Livelock: a cycle within the non-progress subgraph. Iterative DFS with
    # an explicit stack; a back edge to a node on the current path is a cycle
    # the system could traverse forever without progress.
    color: dict[Hashable, int] = {}  # 1 = on path, 2 = finished
    for root in nonprogress_edges:
        if color.get(root):
            continue
        stack: list[tuple[Hashable, int]] = [(root, 0)]
        path: list[tuple[Hashable, str]] = []  # (node, label taken from it)
        while stack:
            node, idx = stack.pop()
            edges = nonprogress_edges.get(node, [])
            if idx == 0:
                color[node] = 1
            if idx < len(edges):
                stack.append((node, idx + 1))
                label, nxt = edges[idx]
                if color.get(nxt) == 1:
                    cycle = [(label, nxt)]
                    for pnode, plabel in reversed(path):
                        cycle.append((plabel, pnode))
                        if pnode == nxt:
                            break
                    cycle.reverse()
                    return fail("livelock",
                                "cycle of non-progress transitions "
                                f"({' -> '.join(lbl for lbl, _ in cycle)})",
                                nxt, cycle)
                if color.get(nxt) != 2:
                    path.append((node, label))
                    stack.append((nxt, 0))
            else:
                color[node] = 2
                if path and path[-1][0] == node:
                    path.pop()

    return Result(model.name, True, len(parent), transitions)


def all_models() -> dict[str, Callable[..., Model]]:
    """name -> model factory; each factory accepts ``mutation=None``."""
    from tools.model import drr, failover, rewire, shm, swap
    return {m.NAME: m.model for m in (failover, drr, shm, rewire, swap)}


def all_mutations() -> dict[str, tuple[str, ...]]:
    """model name -> its seeded-bug mutation names."""
    from tools.model import drr, failover, rewire, shm, swap
    return {m.NAME: tuple(m.MUTATIONS) for m in (failover, drr, shm, rewire, swap)}
