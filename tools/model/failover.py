"""Model: single-stream failover (NACK / FAILOVER marker / ctrl retransmit).

Abstraction of cpp/src/basic_engine.cc's degradation protocol: the receiver
tracks the highest contiguous delivered seq per data stream (``done_seq``);
when a stream dies it emits ``PackCtrlFrame(kCtrlFrameNack, stream,
done_seq)`` (basic_engine.cc ~line 525). The sender answers with a
``kCtrlFrameFailover`` marker carrying the retransmit unit count, then
resends every chunk from the receiver's first missing seq over the ctrl
stream in order (SenderHandleNack, ~line 1106); the receiver's
``ProcessFailoverMarkerLocked`` (~line 915) checks the batch lines up with
its own gap. A concurrent re-striping epoch (``kCtrlFrameWeights``) may
interleave on the same ctrl stream and must not perturb delivery.

Model shape: one data stream carrying N chunks (seq 0..N-1), which may fail
at any point, losing everything in flight (and silently eating anything the
sender writes before it learns of the failure); the ctrl stream is reliable
and ordered (TCP), carrying WEIGHTS / FAILOVER / retransmitted chunks
sender->receiver and the NACK receiver->sender. Checked properties:

  * safety — the receiver accepts each seq exactly once, in order (no lost
    chunk, no duplicate, no gap); the FAILOVER marker's unit count exactly
    covers the receiver's missing suffix; the receiver's weights epoch
    never runs ahead of the sender's.
  * liveness — every execution reaches "all N chunks delivered, epochs
    converged" (deadlock detection; every transition here is progress).

MUTATIONS are the real-world failure modes this model exists to catch:
off-by-one resume seq (lost chunk), resume-from-zero (duplicate), and a
sender that drops the NACK on the floor (wedge -> deadlock).
"""

from __future__ import annotations

from typing import Hashable, Iterator

from tools.model import Model

NAME = "failover"

N_CHUNKS = 3          # data payload; seq 0..2
MAX_EPOCH = 1         # one concurrent re-striping epoch bump


def _mk(sender_next: int, wire: tuple[int, ...], failed: bool,
        nack_msg: int | None, ctrl: tuple[Hashable, ...],
        resend: tuple[int, ...], done: int, s_epoch: int, r_epoch: int,
        phase: str, viol: str | None):
    """phase: 'data' (striping), 'nacked' (NACK sent, awaiting failover),
    'failover' (marker sent or NACK dropped)."""
    return (sender_next, wire, failed, nack_msg, ctrl, resend, done,
            s_epoch, r_epoch, phase, viol)


def model(mutation: str | None = None) -> Model:
    if mutation is not None and mutation not in MUTATIONS:
        raise ValueError(f"unknown mutation {mutation!r} (want one of {sorted(MUTATIONS)})")

    def init_states():
        yield _mk(0, (), False, None, (), (), 0, 0, 0, "data", None)

    def actions(state) -> Iterator[tuple[str, Hashable]]:
        (nxt, wire, failed, nack_msg, ctrl, resend, done,
         s_ep, r_ep, phase, viol) = state
        if viol:
            return

        # Sender stripes the next chunk. On a dead stream the write
        # disappears into the failed socket (the sender has not seen the
        # NACK yet, so it cannot know).
        if nxt < N_CHUNKS and phase == "data":
            new_wire = wire if failed else wire + (nxt,)
            yield (f"send({nxt})",
                   _mk(nxt + 1, new_wire, failed, nack_msg, ctrl, resend,
                       done, s_ep, r_ep, phase, viol))

        # Sender announces a re-striping epoch over ctrl (kCtrlFrameWeights).
        if s_ep < MAX_EPOCH:
            yield ("weights_epoch",
                   _mk(nxt, wire, failed, nack_msg,
                       ctrl + (("weights", s_ep + 1),), resend, done,
                       s_ep + 1, r_ep, phase, viol))

        # The data stream fails; everything in flight is lost.
        if not failed:
            yield ("stream_fail",
                   _mk(nxt, (), True, nack_msg, ctrl, resend, done,
                       s_ep, r_ep, phase, viol))

        # Receiver delivers the head of the (live) data stream.
        if wire:
            seq, rest = wire[0], wire[1:]
            v = viol
            if seq != done:
                v = f"receiver got seq {seq} while expecting {done} (lost or duplicated chunk)"
            yield (f"deliver({seq})",
                   _mk(nxt, rest, failed, nack_msg, ctrl, resend,
                       done + (1 if v is None else 0), s_ep, r_ep, phase, v))

        # Receiver: stream is down, chunks are missing -> NACK once with the
        # confirmed contiguous seq (done_seq).
        if failed and phase == "data" and done < N_CHUNKS:
            yield ("nack",
                   _mk(nxt, wire, failed, done, ctrl, resend, done,
                       s_ep, r_ep, "nacked", viol))

        # Sender consumes the NACK -> FAILOVER marker + retransmit batch
        # from the receiver's first missing seq, over ctrl.
        if nack_msg is not None and phase == "nacked":
            start = nack_msg
            if mutation == "resume_off_by_one":
                start = nack_msg + 1        # skips the first missing chunk
            elif mutation == "resume_from_zero":
                start = 0                   # replays already-delivered chunks
            if mutation == "ignore_nack":
                yield ("drop_nack",
                       _mk(nxt, wire, failed, None, ctrl, resend, done,
                           s_ep, r_ep, "failover", viol))
            else:
                batch = tuple(range(start, N_CHUNKS))
                yield ("failover_marker",
                       _mk(nxt, wire, failed, None,
                           ctrl + (("failover", len(batch)),), batch, done,
                           s_ep, r_ep, "failover", viol))

        # Sender pushes the next retransmit chunk onto the ctrl stream.
        if resend:
            seq, rest = resend[0], resend[1:]
            yield (f"retransmit({seq})",
                   _mk(nxt, wire, failed, nack_msg, ctrl + (("chunk", seq),),
                       rest, done, s_ep, r_ep, phase, viol))

        # Receiver consumes the head of the ordered, reliable ctrl stream.
        if ctrl:
            head, rest = ctrl[0], ctrl[1:]
            kind, arg = head
            if kind == "weights":
                yield ("apply_weights",
                       _mk(nxt, wire, failed, nack_msg, rest, resend, done,
                           s_ep, arg, phase, viol))
            elif kind == "failover":
                # ProcessFailoverMarkerLocked's own desync check.
                v = viol
                if arg != N_CHUNKS - done:
                    v = (f"FAILOVER marker announces {arg} units but the "
                         f"receiver is missing {N_CHUNKS - done} (failover desync)")
                yield ("failover_check",
                       _mk(nxt, wire, failed, nack_msg, rest, resend, done,
                           s_ep, r_ep, phase, v))
            else:  # retransmitted chunk
                v = viol
                if arg != done:
                    v = (f"ctrl retransmit delivered seq {arg} while expecting "
                         f"{done} (lost or duplicated chunk)")
                yield (f"ctrl_deliver({arg})",
                       _mk(nxt, wire, failed, nack_msg, rest, resend,
                           done + (1 if v is None else 0), s_ep, r_ep, phase, v))

    def invariant(state) -> str | None:
        (_nxt, _wire, _failed, _nack, _ctrl, _resend, done,
         s_ep, r_ep, _phase, viol) = state
        if viol:
            return viol
        if done > N_CHUNKS:
            return f"receiver delivered {done} chunks of {N_CHUNKS} (duplicate)"
        if r_ep > s_ep:
            return f"receiver epoch {r_ep} ahead of sender epoch {s_ep}"
        return None

    def done_fn(state) -> bool:
        (_nxt, wire, _failed, _nack, ctrl, resend, done,
         s_ep, r_ep, _phase, _viol) = state
        # Legal quiescence: everything delivered (by either path), all
        # buffers drained, epochs converged. The sender's data-stream cursor
        # may legally stop short: the failover batch covers the tail.
        return (done == N_CHUNKS and not wire and not resend and not ctrl
                and s_ep == r_ep)

    # Every transition moves data or control state forward, so livelock
    # reduces to deadlock; the default progress (all labels) is correct.
    return Model(NAME, init_states, actions, invariant, done_fn)


#: Seeded protocol bugs; tests/test_model_check.py proves each turns the
#: checker RED (sharpness), and `--mutate failover.<name>` replays one.
MUTATIONS = {
    "resume_off_by_one": "retransmit starts at confirmed+1 — first missing chunk is lost",
    "resume_from_zero": "retransmit replays from seq 0 — delivered chunks duplicated",
    "ignore_nack": "sender drops the NACK — receiver waits forever (deadlock)",
}
