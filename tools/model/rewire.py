"""Model: the 4-phase elastic rewire under concurrent kill/join
(tpunet/elastic.py ElasticWorld).

Survivors that detect a failure (or a pending join request) bump and
publish the generation — ``g = max(self.generation + 1,
read_generation(dir))`` (elastic.py ``_rewire``) — and enter the
membership rendezvous for ``g``; the rendezvous seals once every live
survivor has shown up (the grace window), producing the new world view;
members then rewire and resume at generation ``g``. A joiner polls the
published generation and enters the next open rendezvous; one that misses
a seal stays pending, and its standing request forces another rewire
(elastic.py ``_join``: "a joiner that misses a grace window waits for the
survivors to open the next rendezvous").

Model shape: W=3 ranks plus one joiner, at most one kill and one join,
both free to land at ANY point of an ongoing rewire (including between a
seal and a member's resume). The seal's member set is the entered set
intersected with the still-alive set (the grace window's final roll call),
and a joiner is admitted iff it entered before the seal. Fairness assumption
(bounds the state space): the joiner misses at most ONE grace window —
without it, "the joiner is unlucky forever" repeats the rewire cycle at
ever-growing generations, a livelock the real system excludes by
scheduling, not protocol.

Checked properties:

  * generation monotone — a seal that does not strictly raise a member's
    generation is flagged at the transition.
  * no split world — two live resumed ranks at the same generation always
    hold identical membership views, and every resumed rank's view
    contains itself.
  * liveness — every execution reaches a stable world: all live ranks
    resumed on one shared view with no dead members in it and no join
    request outstanding (deadlock detection).
"""

from __future__ import annotations

from typing import Iterator

from tools.model import Model

NAME = "rewire"

W = 3
JOINER = W  # rank id of the single joiner

# Rank record: (alive, phase, gen, view) where phase is 'run', 'rdv',
# 'rewire', and for the joiner also 'absent'/'pending'. view is a frozenset.
# Rounds: sorted tuple of (gen, entered frozenset, sealed bool).


def model(mutation: str | None = None) -> Model:
    if mutation is not None and mutation not in MUTATIONS:
        raise ValueError(f"unknown mutation {mutation!r} (want one of {sorted(MUTATIONS)})")

    full = frozenset(range(W))

    def init_states():
        ranks = tuple((True, "run", 0, full) for _ in range(W))
        ranks += ((False, "absent", -1, frozenset()),)
        # ranks, published, rounds, kills, joins, joiner-misses, viol
        yield (ranks, 0, (), 1, 1, 1, None)

    def _round(rounds, g):
        for i, (rg, entered, sealed) in enumerate(rounds):
            if rg == g:
                return i, entered, sealed
        return None, frozenset(), False

    def _set_round(rounds, g, entered, sealed):
        i, _e, _s = _round(rounds, g)
        lst = list(rounds)
        if i is None:
            lst.append((g, entered, sealed))
        else:
            lst[i] = (g, entered, sealed)
        return tuple(sorted(lst))

    def actions(state) -> Iterator:
        ranks, published, rounds, kills, joins, misses, viol = state
        if viol:
            return
        alive = {r for r in range(W + 1) if ranks[r][0]}
        join_pending = ranks[JOINER][0] and ranks[JOINER][1] in ("pending", "rdv")

        def with_rank(r, rec, *, pub=published, rnds=rounds, k=kills, j=joins, v=viol):
            lst = list(ranks)
            lst[r] = rec
            return (tuple(lst), pub, rnds, k, j, misses, v)

        # A rank dies — at any phase, mid-rewire included.
        if kills:
            for r in range(W):
                if ranks[r][0]:
                    rec = (False,) + ranks[r][1:]
                    yield (f"kill({r})", with_rank(r, rec, k=kills - 1))

        # The join request lands (directory write a la elastic.py _join).
        if joins:
            yield ("join_request",
                   with_rank(JOINER, (True, "pending", -1, frozenset()), j=joins - 1))

        # A live running member detects a dead member in its view or the
        # standing join request: bump + publish + enter the rendezvous. An
        # admitted joiner (gen >= 0) is a full member and rewires too.
        for r in range(W + 1):
            is_alive, phase, gen, view = ranks[r]
            if not is_alive or phase != "run" or gen < 0:
                continue
            if not ((view - alive) or join_pending):
                continue
            g = max(gen, published) if mutation == "no_gen_bump" \
                else max(gen + 1, published)
            _i, entered, sealed = _round(rounds, g)
            if sealed:
                continue  # a round this rank could enter will open at g+1
            nrounds = _set_round(rounds, g, entered | {r}, False)
            yield (f"detect({r})@g{g}",
                   with_rank(r, (True, "rdv", gen, view),
                             pub=max(published, g), rnds=nrounds))

        # The joiner polls the published generation and enters an open round.
        if ranks[JOINER][1] == "pending":
            i, entered, sealed = _round(rounds, published)
            if i is not None and not sealed:
                nrounds = _set_round(rounds, published, entered | {JOINER}, False)
                yield (f"join_enter@g{published}",
                       with_rank(JOINER, (True, "rdv", -1, frozenset()),
                                 rnds=nrounds))

        # Seal the rendezvous: the grace window closes once every live
        # survivor is in (HEAD); the seeded quorumless mutation closes it
        # for any non-empty attendance, re-sealing included.
        for g, entered, sealed in rounds:
            if sealed:
                continue
            # Every live current MEMBER must make the window; an admitted
            # joiner counts, a still-pending one does not.
            survivors = {r for r in range(W + 1)
                         if ranks[r][0] and ranks[r][2] >= 0}
            present = entered & alive
            can_seal = (survivors <= entered) if mutation != "quorumless_seal" \
                else bool(present)
            if not can_seal:
                continue
            # Fairness bound: a still-pending joiner may be left out of at
            # most `misses` windows; after that the window waits for it.
            nmisses = misses
            if ranks[JOINER][0] and ranks[JOINER][1] == "pending" \
                    and JOINER not in entered:
                if misses == 0 and mutation != "quorumless_seal":
                    continue
                nmisses = max(0, misses - 1)
            members = frozenset(present)
            nranks = list(ranks)
            v = viol
            for m in sorted(members):
                _a, _p, mgen, mview = ranks[m]
                if g <= mgen and v is None:
                    v = (f"rank {m} sealed into generation {g} but already "
                         f"held generation {mgen} (generation not monotone)")
                new_view = members
                if mutation == "stale_view_commit" and m != JOINER:
                    new_view = frozenset(mview & alive)  # own stale detect view
                nranks[m] = (True, "rewire", g, new_view)
            yield (f"seal@g{g}",
                   (tuple(nranks), published, _set_round(rounds, g, entered, True),
                    kills, joins, nmisses, v))

        # A sealed member finishes rewiring and resumes.
        for r in range(W + 1):
            is_alive, phase, gen, view = ranks[r]
            if is_alive and phase == "rewire":
                yield (f"resume({r})", with_rank(r, (True, "run", gen, view)))

    def invariant(state) -> str | None:
        ranks, _published, _rounds, _kills, _joins, _misses, viol = state
        if viol:
            return viol
        running = [(r, gen, view) for r, (a, p, gen, view) in enumerate(ranks)
                   if a and p == "run"]
        for r, gen, view in running:
            if r not in view:
                return f"rank {r} resumed at generation {gen} with a view {sorted(view)} not containing itself"
        for i in range(len(running)):
            for j in range(i + 1, len(running)):
                r1, g1, v1 = running[i]
                r2, g2, v2 = running[j]
                if g1 == g2 and v1 != v2:
                    return (f"split world: ranks {r1} and {r2} both resumed at "
                            f"generation {g1} with views {sorted(v1)} vs {sorted(v2)}")
        return None

    def done_fn(state) -> bool:
        ranks, _published, _rounds, _kills, _joins, _misses, _viol = state
        alive = {r for r in range(W + 1) if ranks[r][0]}
        views = set()
        for r in alive:
            _a, phase, _gen, view = ranks[r]
            if phase != "run" or (view - alive):
                return False
            views.add(view)
        return len(views) == 1

    return Model(NAME, init_states, actions, invariant, done_fn)


#: Seeded rewire bugs.
MUTATIONS = {
    "no_gen_bump": "survivors reuse their current generation — monotonicity broken",
    "quorumless_seal": "the rendezvous seals before every survivor arrived — split world",
    "stale_view_commit": "members commit their local detect view, not the sealed one",
}
