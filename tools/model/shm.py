"""Model: the SHM segment handshake (cpp/src/shm_engine.cc).

The property this model guards is stated verbatim in the implementation
(shm_engine.cc ~line 944): connect "must not require the peer to be inside
accept() already, or the collectives' connect-all-then-accept-all wiring
would deadlock". The connector posts its segment offer over the ctrl
stream and returns; sends proceed **optimistically** into the ring with
their LEN frames deferred (``SendPreAckMsg``) so completion needs no peer
participation; whenever the acceptor eventually runs accept() it maps the
segment and emits a one-byte verdict; ``ResolveShmVerdict`` then either
flushes the deferred LEN frames (ack: the ring content is live) or replays
every deferred message over ctrl and drops the segment (nack: TCP mode).

Model shape: two ranks, each executing the collectives' wiring order —
connect(peer) then accept(peer) then block for its own verdict — with one
optimistic message per direction and a nondeterministic verdict (ack or
nack: host mismatch and CRC refusal are real). BFS explores every
interleaving of the two ranks.

Checked properties:

  * liveness — the cross-connect always completes; a handshake that makes
    connect wait for the peer's accept deadlocks the wiring (detected).
  * safety — each direction's message is delivered exactly once, on BOTH
    verdict paths (ack -> ring flush, nack -> ctrl replay), never zero
    (dropped deferred) and never twice (double flush).
"""

from __future__ import annotations

from typing import Iterator

from tools.model import Model

NAME = "shm"

# Per-rank pc: start -> posted -> accepted -> done (HEAD), with the
# sync-rendezvous mutation detouring start -> await_sync (connect blocks).
# Per-direction channel (index = connector rank): offer state, verdict in
# flight, optimistic send done, verdict resolved, delivered count.


def _chan(offer="none", verdict=None, sent=False, resolved=False, delivered=0):
    return (offer, verdict, sent, resolved, delivered)


def model(mutation: str | None = None) -> Model:
    if mutation is not None and mutation not in MUTATIONS:
        raise ValueError(f"unknown mutation {mutation!r} (want one of {sorted(MUTATIONS)})")

    def init_states():
        yield (("start", "start"), (_chan(), _chan()))

    def actions(state) -> Iterator:
        pcs, chans = state
        for r in (0, 1):
            peer = 1 - r
            pc = pcs[r]
            offer, verdict, sent, resolved, delivered = chans[r]

            def upd(new_pc=None, _chan=r, _r=r, **chg):
                """New state: set rank _r's pc and update channel _chan's
                named fields (default: this rank's own channel)."""
                np = list(pcs)
                if new_pc is not None:
                    np[_r] = new_pc
                nc = list(chans)
                if chg:
                    cur = dict(zip(("offer", "verdict", "sent", "resolved",
                                    "delivered"), chans[_chan]))
                    cur.update(chg)
                    nc[_chan] = tuple(cur.values())
                return (tuple(np), tuple(nc))

            # connect(): post the segment offer on ctrl. HEAD returns
            # immediately (async ack); the seeded rendezvous bug blocks
            # inside connect until the verdict lands.
            if pc == "start":
                nxt = "await_sync" if mutation == "sync_rendezvous" else "posted"
                yield (f"r{r}.connect_post", upd(new_pc=nxt, offer="inflight"))

            # Optimistic send into the ring: legal the moment the offer is
            # posted, with the LEN frame deferred until the verdict —
            # explicitly independent of the peer's accept progress.
            if pc in ("posted", "accepted", "await_sync") and \
                    offer != "none" and not sent and not resolved:
                yield (f"r{r}.optimistic_send", upd(sent=True))

            # accept(): consume the PEER's offer, map, emit a verdict byte.
            # Runs only after this rank's own connect returned — the
            # connect-all-then-accept-all wiring order.
            if pc == "posted" and chans[peer][0] == "inflight":
                for v in ("ack", "nack"):
                    yield (f"r{r}.accept_{v}",
                           upd(new_pc="accepted", _chan=peer,
                               offer="consumed", verdict=v))

            # Resolve this rank's own verdict (ResolveShmVerdict): ack
            # flushes the deferred LEN frames, nack replays over ctrl —
            # either way the message is delivered exactly once.
            want_pc = "await_sync" if mutation == "sync_rendezvous" else "accepted"
            if pc == want_pc and verdict is not None and sent and not resolved:
                n = 1
                if verdict == "nack" and mutation == "nack_drops_deferred":
                    n = 0       # seeded bug: deferred queue dropped on nack
                if verdict == "ack" and mutation == "double_flush":
                    n = 2       # seeded bug: deferred LEN frames flushed twice
                nxt = "posted" if mutation == "sync_rendezvous" else "done"
                yield (f"r{r}.resolve_{verdict}",
                       upd(new_pc=nxt, resolved=True, delivered=delivered + n))

            # sync mutation tail: after the (unreachable in the deadlocking
            # interleavings) inline verdict, the rank still runs accept+done.
            if mutation == "sync_rendezvous" and pc == "posted" and resolved \
                    and chans[peer][0] == "consumed":
                yield (f"r{r}.finish", upd(new_pc="done"))

        return

    def invariant(state) -> str | None:
        pcs, chans = state
        for r, (_o, _v, _s, _res, delivered) in enumerate(chans):
            if delivered > 1:
                return (f"direction {r}->{1 - r} delivered {delivered} copies "
                        f"(deferred LEN frames flushed more than once)")
        if all(pc == "done" for pc in pcs):
            for r, (_o, _v, _s, _res, delivered) in enumerate(chans):
                if delivered != 1:
                    return (f"handshake completed but direction {r}->{1 - r} "
                            f"delivered {delivered} messages (lost deferred send)")
        return None

    def done_fn(state) -> bool:
        pcs, _chans = state
        return all(pc == "done" for pc in pcs)

    return Model(NAME, init_states, actions, invariant, done_fn)


#: Seeded handshake bugs.
MUTATIONS = {
    "sync_rendezvous": "connect blocks for the verdict — cross-connect wiring deadlocks",
    "nack_drops_deferred": "nack path drops the deferred queue instead of ctrl replay",
    "double_flush": "ack path flushes the deferred LEN frames twice — duplicate delivery",
}
