"""CLI for the protocol model checker.

  python -m tools.model --all              # explore every model, exit 1 on any violation
  python -m tools.model drr shm            # explore named models only
  python -m tools.model --mutations        # list models and their seeded bugs
  python -m tools.model --mutate drr.strict_latency
                                           # run ONE seeded bug; exits 1 when the
                                           # checker catches it (CI's RED self-proof
                                           # asserts exactly that), 0 if it slipped by

Exit status: 0 = everything explored clean (or, under --mutate, the seeded
bug embarrassingly survived), 1 = a violation was found (counterexample
trace printed), 2 = usage error.
"""

from __future__ import annotations

import argparse
import sys
import time

from tools.model import Result, all_models, all_mutations, explore


def _report(r: Result, dt: float, *, trace: bool) -> None:
    verdict = "ok" if r.ok else f"FAIL({r.error.kind})"
    print(f"model {r.name:<10} {verdict:<16} {r.states:>8} states "
          f"{r.transitions:>8} transitions  {dt:6.2f}s")
    if r.error is not None:
        text = r.error.render() if trace else f"{r.error.kind}: {r.error.message}"
        print("  " + text.replace("\n", "\n  "))


def main(argv: list[str] | None = None) -> int:
    models = all_models()
    mutations = all_mutations()

    ap = argparse.ArgumentParser(
        prog="python -m tools.model",
        description="Exhaustive BFS model checking of tpunet's protocol state machines.")
    ap.add_argument("names", nargs="*", metavar="MODEL",
                    help=f"models to explore (default: none; choices: {', '.join(models)})")
    ap.add_argument("--all", action="store_true", help="explore every model")
    ap.add_argument("--mutate", metavar="MODEL.MUTATION",
                    help="explore one model with a seeded bug; exit 1 iff caught")
    ap.add_argument("--mutations", action="store_true",
                    help="list every model's seeded-bug mutations and exit")
    ap.add_argument("--trace", action="store_true",
                    help="print the full counterexample trace, not just the message")
    args = ap.parse_args(argv)

    if args.mutations:
        for name in models:
            for mut in mutations[name]:
                mod = __import__(f"tools.model.{name}", fromlist=["MUTATIONS"])
                print(f"{name}.{mut}: {mod.MUTATIONS[mut]}")
        return 0

    if args.mutate:
        name, _, mut = args.mutate.partition(".")
        if name not in models or mut not in mutations.get(name, ()):
            ap.error(f"unknown mutation {args.mutate!r}; see --mutations")
        t0 = time.monotonic()
        r = explore(models[name](mut))
        _report(r, time.monotonic() - t0, trace=args.trace)
        if r.ok:
            print(f"seeded bug {args.mutate} was NOT caught — the model has "
                  f"lost its sharpness", file=sys.stderr)
            return 0
        print(f"seeded bug {args.mutate} caught ({r.error.kind}) — checker is sharp")
        return 1

    names = list(models) if args.all else args.names
    if not names:
        ap.error("nothing to do: give model names, --all, --mutate, or --mutations")
    for n in names:
        if n not in models:
            ap.error(f"unknown model {n!r} (choices: {', '.join(models)})")

    failed = False
    for n in names:
        t0 = time.monotonic()
        r = explore(models[n]())
        _report(r, time.monotonic() - t0, trace=args.trace)
        failed |= not r.ok
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
