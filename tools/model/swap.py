"""Model: the weight-swap flip (tpunet/serve/publish.py).

The publisher announces each publication attempt with a token —
``(seq << 32) | version`` rides the BEGIN/STATUS req_id "so a LATE
aborted-status from an abandoned attempt can never poison the retry that
superseded it" (publish.py ~line 396). Each decode rank verifies the
broadcast independently: a verified rank stages and flips, a corrupt one
refuses, and ``publish()`` succeeds only when the WHOLE fleet flipped —
mixed-version pools are legal in the meantime because every session is
pinned at admission to the version that prefilled it, and old versions
serve their pinned sessions until drained, then retire (T_SWAP_RETIRE).

Model shape: one publisher, two decode ranks, up to two publication
attempts (token 0 -> version 1, token 1 -> version 2), per-rank
nondeterministic verify outcome (ok/corrupt), publisher deadline aborts
that can strand BEGIN/STATUS frames in flight, one pinned session, and
version retirement. Messages are an unordered in-flight set — late
delivery of abandoned-attempt frames is the whole point.

Checked properties:

  * abandoned tokens never commit — a stale STATUS must not count toward a
    newer attempt's flip quorum, and a stale BEGIN must not flip a rank
    backward (per-rank active version is monotone).
  * sessions never see mixed versions — a session pinned to version v can
    always read v from every rank until it drains; retirement waits for
    pinned sessions, and a rank's ACTIVE version is never retired.
  * liveness — every execution quiesces with no frame in flight.
"""

from __future__ import annotations

from typing import Iterator

from tools.model import Model

NAME = "swap"

WORLD = 2
ATTEMPTS = ((0, 1), (1, 2))  # (token, version) per publication attempt


def model(mutation: str | None = None) -> Model:
    if mutation is not None and mutation not in MUTATIONS:
        raise ValueError(f"unknown mutation {mutation!r} (want one of {sorted(MUTATIONS)})")

    def init_states():
        ranks = tuple((frozenset({0}), 0, -1) for _ in range(WORLD))
        # publisher: (phase, token, serving, flips, retired frozenset,
        #             attempts_done) / msgs / ranks / session / viol
        yield (("idle", -1, 0, 0, frozenset(), 0), frozenset(), ranks,
               ("none", -1, False, False), None)

    def actions(state) -> Iterator:
        pub, msgs, ranks, session, viol = state
        if viol:
            return
        phase, token, serving, flips, retired, attempts = pub
        s_status, s_pin, s_r0, s_r1 = session

        def mk(pub=pub, msgs=msgs, ranks=ranks, session=session, viol=viol):
            return (pub, msgs, ranks, session, viol)

        # Publisher opens the next attempt: BEGIN to every rank.
        if phase == "idle" and attempts < len(ATTEMPTS):
            t, ver = ATTEMPTS[attempts]
            nmsgs = msgs | {("begin", t, ver, r) for r in range(WORLD)}
            yield (f"announce(v{ver},t{t})",
                   mk(pub=("wait", t, serving, 0, retired, attempts + 1),
                      msgs=nmsgs))

        # Publisher deadline abort: the attempt is abandoned, its frames
        # stay in flight (the stale-token hazard this model exists for).
        if phase == "wait":
            yield ("deadline_abort",
                   mk(pub=("idle", token, serving, 0, retired, attempts)))

        # Publisher consumes a STATUS frame.
        for m in sorted(msgs):
            if m[0] != "status":
                continue
            _kind, t, verdict, _r = m
            rest = msgs - {m}
            stale = phase != "wait" or t != token
            if stale and mutation != "accept_stale_status":
                yield (f"drop_stale_status(t{t})", mk(msgs=rest))
                continue
            if verdict == "flipped":
                nflips = flips + 1
                if nflips == WORLD:  # whole fleet flipped: commit
                    ver = t + 1
                    yield (f"commit(v{ver})",
                           mk(pub=("idle", token, ver, 0, retired, attempts),
                              msgs=rest))
                else:
                    yield (f"count_flip(t{t})",
                           mk(pub=(phase, token, serving, nflips, retired,
                                   attempts), msgs=rest))
            else:  # one refusal aborts the attempt fleet-wide
                yield (f"abort_on_refusal(t{t})",
                       mk(pub=("idle", token, serving, 0, retired, attempts),
                          msgs=rest))

        # Publisher retires a superseded version on both ranks — only once
        # no open session is pinned to it (the drain gate).
        for v in range(serving):
            if v in retired:
                continue
            if not any(v in res for res, _a, _t in ranks):
                continue
            if s_status == "open" and s_pin == v and mutation != "early_retire":
                continue  # a pinned session still drains from v
            yield (f"retire(v{v})",
                   mk(pub=(phase, token, serving, flips, retired | {v},
                           attempts),
                      msgs=msgs | {("retire", v, r) for r in range(WORLD)}))

        # Rank-side deliveries (any order).
        for m in sorted(msgs):
            rest = msgs - {m}
            if m[0] == "begin":
                _k, t, ver, r = m
                res, active, last = ranks[r]
                if t < last and mutation != "no_token_check":
                    # Stale announce from an abandoned attempt: ignored.
                    yield (f"r{r}.ignore_stale_begin(t{t})", mk(msgs=rest))
                    continue
                # Verify outcome is the environment's choice: ok flips,
                # corrupt refuses (CRC mismatch -> aborted status).
                v = viol
                if ver < active and v is None:
                    v = (f"rank {r} flipped BACKWARD to v{ver} from v{active} "
                         f"(abandoned-attempt BEGIN committed)")
                nranks = list(ranks)
                nranks[r] = (res | {ver}, ver, max(last, t))
                yield (f"r{r}.verify_ok(v{ver},t{t})",
                       mk(msgs=rest | {("status", t, "flipped", r)},
                          ranks=tuple(nranks), viol=v))
                nranks2 = list(ranks)
                nranks2[r] = (res, active, max(last, t))
                yield (f"r{r}.verify_corrupt(v{ver},t{t})",
                       mk(msgs=rest | {("status", t, "aborted", r)},
                          ranks=tuple(nranks2)))
            elif m[0] == "retire":
                _k, v, r = m
                res, active, last = ranks[r]
                nv = viol
                if v == active and nv is None:
                    nv = f"rank {r} told to retire its ACTIVE version v{v}"
                nranks = list(ranks)
                nranks[r] = (res - {v}, active, last)
                yield (f"r{r}.retire(v{v})",
                       mk(msgs=rest, ranks=tuple(nranks), viol=nv))

        # The one session: pinned at admission to the serving version, reads
        # both ranks, then drains.
        if s_status == "none":
            yield ("session_open", mk(session=("open", serving, False, False)))
        if s_status == "open":
            for r, already in ((0, s_r0), (1, s_r1)):
                if already:
                    continue
                res, _active, _last = ranks[r]
                v = viol
                if s_pin not in res and v is None:
                    v = (f"session pinned to v{s_pin} cannot read it from "
                         f"rank {r} (resident: {sorted(res)}) — mixed/retired "
                         f"version visible to a live session")
                yield (f"session_read(r{r})",
                       mk(session=("open", s_pin, s_r0 or r == 0,
                                   s_r1 or r == 1), viol=v))
            if s_r0 and s_r1:
                yield ("session_close",
                       mk(session=("closed", s_pin, True, True)))

    def invariant(state) -> str | None:
        return state[4]

    def done_fn(state) -> bool:
        pub, msgs, _ranks, session, _viol = state
        return pub[0] == "idle" and not msgs and session[0] != "open"

    return Model(NAME, init_states, actions, invariant, done_fn)


#: Seeded swap bugs.
MUTATIONS = {
    "accept_stale_status": "a flipped-STATUS from an abandoned attempt counts toward commit",
    "no_token_check": "ranks process BEGIN frames from abandoned attempts — backward flip",
    "early_retire": "retire ignores the session drain gate — pinned sessions lose their version",
}
