"""Model: the QoS DRR wire-credit scheduler (cpp/src/qos.cc PumpLocked).

Faithful abstraction of the pump: strict control priority FIFO ahead of
everything (a window-blocked control head pauses ALL classes); deficit
round-robin between latency(0) and bulk(1) where a TURN earns
``weight x quantum`` exactly once at turn start and spends front-first; a
head that does not fit the shared wire window pauses the turn and the next
pump resumes it WITHOUT re-crediting (qos.cc:251-278); a drained queue's
deficit resets (no banking while empty); and ``RoomLocked`` admits any
single chunk on an empty wire so an oversize chunk cannot wedge the
scheduler (qos.cc:198-202).

The pump runs under the scheduler mutex, so the model treats each
``{release | arrival} + PumpLocked`` pair as one atomic action — exactly
the real call graph (``Release``/``Submit`` -> ``PumpLocked``) — and BFS
explores every completion/arrival order over a set of fixed small
workloads (sizes in wire-window units, quantum = 1, weights = (1,1)).

Checked properties:

  * priority — no latency/bulk grant ever happens while control is queued.
  * credit — the wire never exceeds the window except for a single
    oversize chunk granted on an empty wire; a class's deficit never
    exceeds ``max_chunk`` (banked remainder + one quantum), and an empty
    queue's deficit is zero.
  * fairness — while both classes stay backlogged, granted bytes differ by
    at most ``quantum + max_chunk`` (the classic DRR service bound).
  * liveness — every workload drains to empty queues and empty wire
    (deadlock detection; a scheduler that stops granting with work queued
    and wire idle is a wedge).

MUTATIONS seed the scheduler bugs each property exists to catch.
"""

from __future__ import annotations

from typing import Iterator

from tools.model import Model

NAME = "drr"

WINDOW = 2
QUANTUM = 1

# (ctrl, lat, bulk, pending arrivals) — sizes in window units. Shapes chosen
# so every pump branch is reachable: W1 priority + fairness under size-1
# backlogs with a late control arrival; W2 mid-turn window pause with banked
# deficit; W3 the oversize single chunk; W4 deep bulk backlog behind an
# often-blocked latency head (the no-re-credit honesty case); W5 a late
# size-2 control arrival that window-blocks behind an inflight size-1 chunk
# while size-1 DRR heads would still fit (the pause-everything case).
WORKLOADS = (
    ((1,), (1, 1, 1, 1), (1, 1, 1, 1), (("ctrl", 1),)),
    ((), (2, 1), (1, 2), ()),
    ((), (3,), (1,), ()),
    ((), (2, 2), (1, 1, 1), ()),
    ((), (1, 1, 1), (1,), (("ctrl", 2),)),
)


def _pump(qc, q0, q1, d0, d1, turn, nxt, wire, g0, g1, mutation):
    """One PumpLocked run; returns the post-pump fields + violation."""
    viol = None
    qc, qs = list(qc), [list(q0), list(q1)]
    d, g = [d0, d1], [g0, g1]
    wire = list(wire)

    def room(n):
        s = sum(wire)
        if mutation == "no_oversize_escape":
            return s + n <= WINDOW          # drops the empty-wire escape
        return s == 0 or s + n <= WINDOW

    def grant(c, q):
        nonlocal viol
        n = q.pop(0)
        wire.append(n)
        if c != 2:
            g[c] += n
            if qc and viol is None:
                viol = (f"granted class {c} ({n} units) while control is "
                        f"backlogged (priority inversion)")

    def snap():
        return (tuple(qc), tuple(qs[0]), tuple(qs[1]), d[0], d[1],
                turn, nxt, tuple(sorted(wire)), g[0], g[1], viol)

    if mutation == "bulk_before_control":
        # Seeded inversion: squeeze one bulk head in ahead of control.
        if qs[1] and room(qs[1][0]) and d[1] + QUANTUM >= qs[1][0]:
            d[1] += QUANTUM
            d[1] -= qs[1][0]
            grant(1, qs[1])

    # Strict control priority, FIFO; a blocked control head pauses all.
    while qc and room(qc[0]):
        grant(2, qc)
    if qc and mutation != "bypass_blocked_control":
        return snap()

    # Deficit round-robin between latency and bulk.
    while True:
        if turn < 0:
            if not qs[0] and not qs[1]:
                d = [0, 0]                   # no banking while idle
                break
            pick = nxt
            if not qs[pick]:
                pick ^= 1
            nxt = pick ^ 1
            if mutation == "strict_latency":
                pick = 0 if qs[0] else 1     # rotation ignored
            turn = pick
            d[pick] += QUANTUM               # earned once, at turn start
        c = turn
        while qs[c] and d[c] >= qs[c][0]:
            if not room(qs[c][0]):
                if mutation == "recredit_on_pause":
                    turn = -1                # forget the turn: resume re-earns
                return snap()                # window full mid-turn: pause
            d[c] -= qs[c][0]
            grant(c, qs[c])
        if not qs[c]:
            d[c] = 0
        turn = -1
    return snap()


def model(mutation: str | None = None) -> Model:
    if mutation is not None and mutation not in MUTATIONS:
        raise ValueError(f"unknown mutation {mutation!r} (want one of {sorted(MUTATIONS)})")

    def _finish(pumped, b0, b1, maxsz):
        (qc, q0, q1, d0, d1, turn, nxt, wire, g0, g1, viol) = pumped
        # Backlog flags latch False the first time a queue is seen empty;
        # the fairness bound only binds continuously-backlogged classes.
        return (qc, q0, q1, d0, d1, turn, nxt, wire, g0, g1,
                b0 and bool(q0), b1 and bool(q1), maxsz, viol)

    def init_states():
        for qc, q0, q1, pend in WORKLOADS:
            maxsz = max(q0 + q1 + qc + tuple(n for _c, n in pend))
            pumped = _pump(qc, q0, q1, 0, 0, -1, 0, (), 0, 0, mutation)
            yield _finish(pumped, bool(q0), bool(q1), maxsz) + (pend,)

    def actions(state) -> Iterator:
        (qc, q0, q1, d0, d1, turn, nxt, wire, g0, g1,
         b0, b1, maxsz, viol, pend) = state
        if viol:
            return
        # A granted chunk completes: Release() -> PumpLocked().
        for size in sorted(set(wire)):
            rest = list(wire)
            rest.remove(size)
            pumped = _pump(qc, q0, q1, d0, d1, turn, nxt, rest, g0, g1, mutation)
            yield (f"release({size})", _finish(pumped, b0, b1, maxsz) + (pend,))
        # A late arrival: Submit() -> PumpLocked().
        for i, (cls, size) in enumerate(pend):
            nqc, nq0, nq1 = qc, q0, q1
            if cls == "ctrl":
                nqc = qc + (size,)
            elif cls == "lat":
                nq0 = q0 + (size,)
            else:
                nq1 = q1 + (size,)
            pumped = _pump(nqc, nq0, nq1, d0, d1, turn, nxt, wire, g0, g1, mutation)
            yield (f"arrive({cls},{size})",
                   _finish(pumped, b0, b1, maxsz) + (pend[:i] + pend[i + 1:],))

    def invariant(state) -> str | None:
        (qc, q0, q1, d0, d1, _turn, _nxt, wire, g0, g1,
         b0, b1, maxsz, viol, _pend) = state
        if viol:
            return viol
        if sum(wire) > WINDOW and len(wire) != 1:
            return (f"wire credit {sum(wire)} exceeds window {WINDOW} with "
                    f"{len(wire)} chunks inflight")
        for c, (d, q) in enumerate(((d0, q0), (d1, q1))):
            if d > maxsz:
                return (f"class {c} deficit {d} exceeds the legit maximum "
                        f"{maxsz} (re-credited without spending?)")
            if not q and d != 0:
                return f"class {c} queue is empty but deficit is {d} (banking while idle)"
        if b0 and b1 and abs(g0 - g1) > QUANTUM + maxsz:
            return (f"DRR unfairness: granted bytes {g0} vs {g1} while both "
                    f"classes stayed backlogged (bound {QUANTUM + maxsz})")
        return None

    def done_fn(state) -> bool:
        (qc, q0, q1, _d0, _d1, _turn, _nxt, wire, _g0, _g1,
         _b0, _b1, _maxsz, _viol, pend) = state
        return not qc and not q0 and not q1 and not wire and not pend

    # Releases and arrivals always change state (the pump is deterministic),
    # so liveness reduces to deadlock; default progress is correct.
    return Model(NAME, init_states, actions, invariant, done_fn)


#: Seeded scheduler bugs; each maps to one checked property.
MUTATIONS = {
    "bulk_before_control": "DRR served ahead of the control queue — priority inversion",
    "bypass_blocked_control": "window-blocked control no longer pauses lower classes",
    "strict_latency": "rotation ignored: latency always wins the turn — bulk starves",
    "recredit_on_pause": "window pause forgets the turn — the resume re-earns its quantum",
    "no_oversize_escape": "RoomLocked drops the empty-wire escape — an oversize chunk wedges",
}
