"""Cross-rank flight-recorder postmortem (docs/DESIGN.md §6c).

Merges N ranks' flight-recorder dumps (``tpunet-flightrec-rank*.json``,
written on watchdog/CRC verdicts, SIGUSR2, or on demand) and reconstructs
the per-phase lattice of every collective, aligned on ``(comm_id,
coll_seq)`` — the tags every rank stamps identically because the schedule
is deterministic. From the lattice it names a diagnosis a human would
otherwise grep four files for::

    frontier: comm_id=7f3a... coll_seq=41
    rank 3 entered rs.2 of coll_seq=41, never exited (stalled 1840 ms)
    rank 0 completed coll_seq=41; parked waiting on peers
    verdicts: rank 0 watchdog, rank 2 watchdog

The mechanics: a ``phase_enter`` event records BEFORE any wire I/O of that
phase and ``phase_exit`` on scope exit, so a rank wedged mid-collective
shows an enter with no exit — the recorder's reason for existing. A rank
whose newest ``(comm_id, coll_seq)`` trails the frontier never submitted
the frontier collective (died or diverged earlier).

CLI::

    python -m tools.postmortem DIR [--json] [--perfetto [OUT]]

``DIR`` holds the per-rank dumps (TPUNET_TRACE_DIR of the dead job; any
explicit file list works too). ``--json`` emits the machine-readable
diagnosis; ``--perfetto`` additionally merges the dumps (and any trace
files beside them) into one timeline via ``telemetry.merge_traces()``.
The library surface (``load_dumps``, ``phase_lattice``, ``diagnose``) is
what tests/test_postmortem.py pins.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys


def _validate_dump(path: str, d: dict) -> None:
    """Shape-check one dump so the analysis below can assume typed fields.
    Dumps come from dying processes (partial writes, torn JSON recovered by
    hand), so every field is hostile until proven; a malformed dump must be
    a ValueError naming the file, not a TypeError three functions deeper
    (found by tests/test_fuzz.py: a string rank broke the dump sort, a
    string timestamp broke the stall arithmetic)."""
    if not isinstance(d.get("rank", 0), int):
        raise ValueError(f"{path}: rank {d.get('rank')!r} is not an integer")
    events = d.get("events", [])
    if not isinstance(events, list):
        raise ValueError(f"{path}: events is not a list")
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            raise ValueError(f"{path}: events[{i}] is not an object")
        for k in ("t", "a", "b", "c", "d"):
            v = ev.get(k)
            if v is not None and not isinstance(v, (int, float)):
                raise ValueError(
                    f"{path}: events[{i}].{k} {v!r} is not a number")
        for k in ("kind", "name"):
            v = ev.get(k)
            if v is not None and not isinstance(v, str):
                raise ValueError(
                    f"{path}: events[{i}].{k} {v!r} is not a string")


def load_dumps(paths: list[str]) -> list[dict]:
    """Load flight-recorder dumps from explicit files and/or directories
    (directories are globbed for ``tpunet-flightrec-rank*.json``). Sorted
    by rank; a dump whose schema is not tpunet-flightrec-v1 is rejected
    loudly rather than mis-parsed."""
    files: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(sorted(
                glob.glob(os.path.join(p, "tpunet-flightrec-rank*.json"))))
        else:
            files.append(p)
    if not files:
        raise FileNotFoundError(
            f"no tpunet-flightrec-rank*.json dumps under {paths}")
    dumps = []
    for f in files:
        with open(f) as fh:
            d = json.load(fh)
        if d.get("schema") != "tpunet-flightrec-v1":
            raise ValueError(f"{f}: not a tpunet-flightrec-v1 dump "
                             f"(schema={d.get('schema')!r})")
        _validate_dump(f, d)
        d["_path"] = f
        dumps.append(d)
    dumps.sort(key=lambda d: d.get("rank", 0))
    return dumps


def phase_lattice(dumps: list[dict]) -> dict:
    """{(comm_id, coll_seq): {rank: [phase dict, ...]}} where each phase is
    {"name", "step", "enter_t", "exit_t" (None = never exited), "nbytes"}.

    Enter/exit events pair in per-rank program order per (comm_id,
    coll_seq, name, step) — the recorder is per-rank sequential for one
    collective, so a simple open-span stack per key suffices."""
    lattice: dict = {}
    for d in dumps:
        rank = d.get("rank", 0)
        open_spans: dict = {}
        for ev in d.get("events", []):
            kind = ev.get("kind")
            if kind not in ("phase_enter", "phase_exit"):
                continue
            key = (ev.get("a"), ev.get("b"))  # (comm_id, coll_seq)
            pkey = (key, ev.get("name"), ev.get("d"))
            if kind == "phase_enter":
                span = {"name": ev.get("name"), "step": ev.get("d"),
                        "enter_t": ev.get("t"), "exit_t": None,
                        "nbytes": ev.get("c")}
                lattice.setdefault(key, {}).setdefault(rank, []).append(span)
                open_spans.setdefault(pkey, []).append(span)
            else:
                stack = open_spans.get(pkey)
                if stack:
                    stack.pop()["exit_t"] = ev.get("t")
    return lattice


def _fmt_phase(span: dict) -> str:
    name = span.get("name") or "?"
    step = span.get("step")
    return f"{name}.{step}" if step is not None else name


def diagnose(dumps: list[dict]) -> dict:
    """The postmortem verdict. Returns::

        {"frontier": {"comm_id", "coll_seq"} | None,
         "stalled":  [{"rank", "phase", "coll_seq", "since_us"}],
         "behind":   [{"rank", "last_coll_seq"}],
         "complete": [rank, ...],           # finished the frontier
         "verdicts": [{"rank", "reason", "t"}],
         "lines":    [human-readable diagnosis, ...]}

    ``stalled`` = ranks holding an un-exited phase of the frontier
    collective (the wedge); ``behind`` = ranks that never entered it
    (death or divergence upstream); ``since_us`` is measured against that
    rank's newest event (per-host monotonic clocks are unrelated, so no
    cross-rank time arithmetic is attempted)."""
    lattice = phase_lattice(dumps)
    verdicts = []
    for d in dumps:
        for ev in d.get("events", []):
            if ev.get("kind") == "verdict":
                verdicts.append({"rank": d.get("rank", 0),
                                 "reason": ev.get("name") or "?",
                                 "t": ev.get("t")})
    out = {"frontier": None, "stalled": [], "behind": [], "complete": [],
           "verdicts": verdicts, "lines": []}
    if not lattice:
        out["lines"].append(
            "no collective phase events in any dump — the hang predates the "
            "first collective (bootstrap/rendezvous?); check verdicts and "
            "wire events")
        for v in verdicts:
            out["lines"].append(
                f"verdict: rank {v['rank']} {v['reason']} (t={v['t']})")
        return out

    # The frontier: the newest collective ANY rank reached, per comm (the
    # highest coll_seq of the comm with the highest activity). Collectives
    # are submitted in identical program order on every rank, so the
    # frontier is where the job wedged.
    frontier = max(lattice, key=lambda k: (k[1] if k[1] is not None else -1))
    comm_id, coll_seq = frontier
    out["frontier"] = {"comm_id": comm_id, "coll_seq": coll_seq}
    out["lines"].append(f"frontier: comm_id={comm_id} coll_seq={coll_seq} "
                        f"({len(lattice)} collective(s) observed)")

    all_ranks = sorted({d.get("rank", 0) for d in dumps})
    last_ev_t = {d.get("rank", 0): max(
        (ev.get("t", 0) for ev in d.get("events", [])), default=0)
        for d in dumps}
    per_rank = lattice.get(frontier, {})
    for rank in all_ranks:
        spans = per_rank.get(rank)
        if not spans:
            last = max((k[1] for k, ranks in lattice.items()
                        if rank in ranks and k[1] is not None), default=None)
            out["behind"].append({"rank": rank, "last_coll_seq": last})
            out["lines"].append(
                f"rank {rank} never entered coll_seq={coll_seq} "
                f"(last observed coll_seq={last}) — died or diverged "
                f"upstream of the frontier")
            continue
        open_spans = [s for s in spans if s["exit_t"] is None]
        if open_spans:
            s = open_spans[-1]
            since = max(0, last_ev_t[rank] - (s["enter_t"] or 0))
            out["stalled"].append({"rank": rank, "phase": _fmt_phase(s),
                                   "coll_seq": coll_seq, "since_us": since})
            done = [x for x in spans if x["exit_t"] is not None]
            prior = f" after completing {_fmt_phase(done[-1])}" if done else ""
            out["lines"].append(
                f"rank {rank} entered {_fmt_phase(s)} of "
                f"coll_seq={coll_seq}{prior}, never exited "
                f"(stalled {since // 1000} ms by its own clock)")
        else:
            out["complete"].append(rank)
            out["lines"].append(
                f"rank {rank} completed every phase of coll_seq={coll_seq} "
                f"it entered (last: {_fmt_phase(spans[-1])}); parked waiting "
                f"on peers")
    for v in verdicts:
        out["lines"].append(
            f"verdict: rank {v['rank']} {v['reason']} (t={v['t']})")
    if out["stalled"]:
        culprits = ", ".join(
            f"rank {s['rank']} in {s['phase']}" for s in out["stalled"])
        out["lines"].append(f"diagnosis: {culprits} of coll_seq={coll_seq} "
                            f"wedged the collective; peers parked in WaitIn")
    elif out["behind"]:
        ranks = ", ".join(str(b["rank"]) for b in out["behind"])
        out["lines"].append(
            f"diagnosis: rank(s) {ranks} never reached coll_seq={coll_seq} "
            f"— look for death/divergence before the frontier")
    return out


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.postmortem", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="+",
                    help="dump directory (TPUNET_TRACE_DIR of the dead job) "
                         "or explicit tpunet-flightrec-rank*.json files")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable diagnosis")
    ap.add_argument("--perfetto", nargs="?", const="", metavar="OUT",
                    help="also merge dumps (+ any trace files beside them) "
                         "into one Perfetto timeline via "
                         "telemetry.merge_traces()")
    args = ap.parse_args(argv)
    dumps = load_dumps(args.paths)
    diag = diagnose(dumps)
    if args.json:
        print(json.dumps(diag, indent=2))
    else:
        print(f"postmortem over {len(dumps)} rank dump(s): "
              + ", ".join(os.path.basename(d["_path"]) for d in dumps))
        for line in diag["lines"]:
            print("  " + line)
    if args.perfetto is not None:
        from tpunet import telemetry
        trace_dir = args.paths[0] if os.path.isdir(args.paths[0]) \
            else os.path.dirname(args.paths[0]) or "."
        out = telemetry.merge_traces(trace_dir,
                                     out_path=args.perfetto or None)
        print(f"perfetto timeline: {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
