"""Wire-contract registry checker: sources vs tools/protocol/spec.py.

Fifth invariant lint registry (PR-4 pattern: pure ``check_*(root) ->
list[str]``, wired into ``python -m tools.lint``). The spec is the single
declarative statement of every byte the stack puts on a wire; this module
extracts the constants the implementations *actually* compile/interpret and
cross-checks both directions:

  * preamble flag bits — unique, outside the QoS class nibble, spec-exact
  * ctrl-frame opcodes — distinct top bytes above the length cut, and each
    opcode's bit-field layout tiles into the low 56 bits without overlap
  * bootstrap-blob offsets — tile the 16-byte blob with no overlap, and
    every field is both written by the encode side (collectives.cc) and
    read by the peer-validation side (wire.cc)
  * one-byte wire enums (WireCodec, TrafficClass, CollAlgo, CollKind,
    chaos actions) — C++ enumerator values byte-identical to the Python
    mirrors that ride the same frames
  * serve frames — struct formats and *sizes* (re-derived via
    struct.calcsize) match the spec, frame types / roles / swap statuses
    byte-identical

Every comparison is two-sided: a constant added to a source file without a
spec entry is as red as a spec entry the sources no longer honor.
"""

from __future__ import annotations

import ast
import re
import struct
from pathlib import Path

from tools.lint._util import read_text, strip_c_comments
from tools.protocol import spec

# ---- C++ extraction --------------------------------------------------------

_FLAG = re.compile(r"constexpr\s+uint64_t\s+(kPreambleFlag\w+)\s*=\s*1ull\s*<<\s*(\d+)\s*;")
_CLASS_SHIFT = re.compile(r"constexpr\s+int\s+kPreambleClassShift\s*=\s*(\d+)\s*;")
_CLASS_MASK = re.compile(r"constexpr\s+uint64_t\s+kPreambleClassMask\s*=\s*0x([0-9a-fA-F]+)ull\s*<<\s*kPreambleClassShift\s*;")
_MAGIC = re.compile(r"constexpr\s+uint64_t\s+kWireMagic\s*=\s*0x([0-9a-fA-F]+)ull\s*;")
_U64_CONST = re.compile(r"constexpr\s+uint64_t\s+(k\w+)\s*=\s*(\d+)\s*;")
_SIZE_CONST = re.compile(r"constexpr\s+size_t\s+(k\w+)\s*=\s*(\d+)\s*;")
_OPCODE = re.compile(r"constexpr\s+uint8_t\s+(kCtrlFrame\w+)\s*=\s*0x([0-9a-fA-F]{2})\s*;")
_MAX_CTRL = re.compile(r"constexpr\s+uint64_t\s+kMaxCtrlLen\s*=\s*1ull\s*<<\s*(\d+)\s*;")
_INT_COUNT = re.compile(r"constexpr\s+int\s+(k\w+Count)\s*=\s*(\d+)\s*;")


def _cpp_enum(text: str, name: str) -> dict[str, int] | None:
    """Extract ``enum class <name> : ... { ... }`` as {enumerator: value},
    handling implicit increments. None when the enum is absent."""
    m = re.search(r"enum\s+class\s+" + re.escape(name) + r"\s*(?::\s*\w+)?\s*\{([^}]*)\}", text)
    if not m:
        return None
    out: dict[str, int] = {}
    nxt = 0
    for part in m.group(1).split(","):
        part = part.strip()
        if not part:
            continue
        em = re.match(r"(\w+)\s*(?:=\s*(\d+))?$", part)
        if not em:
            return None  # unparseable enumerator (expression initializer)
        nxt = int(em.group(2)) if em.group(2) is not None else nxt
        out[em.group(1)] = nxt
        nxt += 1
    return out


# ---- Python extraction -----------------------------------------------------

def _py_assigns(text: str, pattern: str) -> dict[str, int]:
    """{name: int} for module-level ``NAME = <int>`` lines matching pattern."""
    out = {}
    for m in re.finditer(r"(?m)^(" + pattern + r")\s*=\s*(\d+)\b", text):
        out[m.group(1)] = int(m.group(2))
    return out


def _py_struct_fmts(text: str) -> dict[str, str]:
    return dict(re.findall(r'(?m)^(_\w+)\s*=\s*struct\.Struct\("([^"]+)"\)', text))


def _py_dict_literal(text: str, name: str):
    """literal_eval a single-line ``NAME = {...}`` assignment; None if absent."""
    m = re.search(r"(?m)^" + re.escape(name) + r"\s*=\s*(\{[^}]*\})", text)
    if not m:
        return None
    try:
        return ast.literal_eval(m.group(1))
    except (ValueError, SyntaxError):
        return None


# ---- comparisons -----------------------------------------------------------

def _diff(out: list[str], what: str, actual: dict, want: dict) -> None:
    """Two-sided dict comparison with per-key value check."""
    for k in sorted(set(want) - set(actual)):
        out.append(f"{what}: spec entry {k!r} not found in source")
    for k in sorted(set(actual) - set(want)):
        out.append(f"{what}: source defines {k!r} = {actual[k]!r} with no spec entry "
                   f"(add it to tools/protocol/spec.py)")
    for k in sorted(set(actual) & set(want)):
        if actual[k] != want[k]:
            out.append(f"{what}: {k!r} is {actual[k]!r} in source but {want[k]!r} in spec")


def _check_wire_h(root: Path, out: list[str]) -> None:
    path = root / "cpp/src/wire.h"
    if not path.is_file():
        out.append("protocol: cpp/src/wire.h not found")
        return
    text = strip_c_comments(read_text(path))

    # Preamble flags: spec-exact, unique bits, clear of the class nibble.
    flags = {name: int(bit) for name, bit in _FLAG.findall(text)}
    _diff(out, "preamble flags (wire.h)", flags, spec.PREAMBLE_FLAGS)
    by_bit: dict[int, str] = {}
    for name, bit in sorted(flags.items()):
        if bit in by_bit:
            out.append(f"preamble flags: {name} collides with {by_bit[bit]} on bit {bit}")
        by_bit[bit] = name
    nibble = range(spec.PREAMBLE_CLASS_SHIFT,
                   spec.PREAMBLE_CLASS_SHIFT + spec.PREAMBLE_CLASS_BITS)
    for name, bit in sorted(flags.items()):
        if bit in nibble:
            out.append(f"preamble flags: {name} (bit {bit}) lands inside the QoS "
                       f"class nibble bits {nibble.start}..{nibble.stop - 1}")
    m = _CLASS_SHIFT.search(text)
    if not m or int(m.group(1)) != spec.PREAMBLE_CLASS_SHIFT:
        out.append(f"preamble: kPreambleClassShift != spec {spec.PREAMBLE_CLASS_SHIFT}")
    m = _CLASS_MASK.search(text)
    want_mask = (1 << spec.PREAMBLE_CLASS_BITS) - 1
    if not m or int(m.group(1), 16) != want_mask:
        out.append(f"preamble: kPreambleClassMask nibble != spec 0x{want_mask:X} << shift")

    # Magic + geometry.
    m = _MAGIC.search(text)
    if not m or int(m.group(1), 16) != spec.WIRE_MAGIC:
        out.append(f"preamble: kWireMagic != spec 0x{spec.WIRE_MAGIC:016x}")
    elif (spec.WIRE_MAGIC & 0xFF) != spec.WIRE_VERSION:
        out.append("preamble: WIRE_MAGIC low byte disagrees with spec WIRE_VERSION")
    sizes = {n: int(v) for n, v in _SIZE_CONST.findall(text)}
    u64s = {n: int(v) for n, v in _U64_CONST.findall(text)}
    if sizes.get("kPreambleBytes") != spec.PREAMBLE_BYTES:
        out.append(f"preamble: kPreambleBytes {sizes.get('kPreambleBytes')} != spec {spec.PREAMBLE_BYTES}")
    if spec.PREAMBLE_BYTES != 8 * len(spec.PREAMBLE_FIELDS):
        out.append("preamble: spec PREAMBLE_BYTES != 8 * len(PREAMBLE_FIELDS)")
    if u64s.get("kMaxStreams") != spec.MAX_STREAMS:
        out.append(f"preamble: kMaxStreams {u64s.get('kMaxStreams')} != spec {spec.MAX_STREAMS}")

    # Ctrl-frame opcodes: spec-exact, distinct, strictly above the length cut.
    ops = {name: int(v, 16) for name, v in _OPCODE.findall(text)}
    _diff(out, "ctrl opcodes (wire.h)", ops, spec.CTRL_OPCODES)
    seen: dict[int, str] = {}
    for name, v in sorted(ops.items()):
        if v in seen:
            out.append(f"ctrl opcodes: {name} collides with {seen[v]} on 0x{v:02X}")
        seen[v] = name
        if v == 0:
            out.append(f"ctrl opcodes: {name} top byte 0 — indistinguishable from a length frame")
    m = _MAX_CTRL.search(text)
    if not m or int(m.group(1)) != spec.MAX_CTRL_LEN_BITS:
        out.append(f"ctrl frames: kMaxCtrlLen != spec 1 << {spec.MAX_CTRL_LEN_BITS}")

    # Ctrl bit-field layouts: per-opcode fields tile below the opcode byte
    # with no overlap, and the decode masks/shifts appear in wire.h.
    if set(spec.CTRL_LAYOUTS) != set(spec.CTRL_OPCODES):
        out.append("ctrl frames: spec CTRL_LAYOUTS keys != CTRL_OPCODES keys")
    for op, fields in sorted(spec.CTRL_LAYOUTS.items()):
        used = 0
        for fname, (low, width) in sorted(fields.items()):
            if low + width > spec.MAX_CTRL_LEN_BITS:
                out.append(f"ctrl layout {op}.{fname}: bits {low}..{low + width - 1} "
                           f"spill into the opcode byte")
            mask = ((1 << width) - 1) << low
            if used & mask:
                out.append(f"ctrl layout {op}.{fname}: overlaps another field")
            used |= mask
            field_mask = (1 << width) - 1
            if f"0x{field_mask:x}" not in text.lower():
                out.append(f"ctrl layout {op}.{fname}: mask 0x{field_mask:x} not found "
                           f"in wire.h — decode layout drifted from spec")
            if low and f">> {low}" not in text:
                out.append(f"ctrl layout {op}.{fname}: shift '>> {low}' not found "
                           f"in wire.h — decode layout drifted from spec")
    ws = spec.CTRL_LAYOUTS.get("kCtrlFrameWeights", {}).get("nstreams")
    if ws and (1 << ws[1]) <= spec.MAX_STREAMS:
        out.append("ctrl layout kCtrlFrameWeights.nstreams: field cannot represent "
                   f"MAX_STREAMS == {spec.MAX_STREAMS}")

    # Bootstrap blob: spec-exact offsets that tile the blob without overlap.
    blob = {n: v for n, v in sizes.items() if n.startswith("kBlobOff")}
    _diff(out, "bootstrap blob (wire.h)",
          blob, {n: off for n, (off, _w) in spec.BOOTSTRAP_BLOB.items()})
    if sizes.get("kBootstrapBlobLen") != spec.BOOTSTRAP_BLOB_LEN:
        out.append(f"bootstrap blob: kBootstrapBlobLen != spec {spec.BOOTSTRAP_BLOB_LEN}")
    taken: dict[int, str] = {}
    for name, (off, width) in sorted(spec.BOOTSTRAP_BLOB.items()):
        if off + width > spec.BOOTSTRAP_BLOB_LEN:
            out.append(f"bootstrap blob: {name} bytes {off}..{off + width - 1} "
                       f"exceed the {spec.BOOTSTRAP_BLOB_LEN}-byte blob")
        for b in range(off, min(off + width, spec.BOOTSTRAP_BLOB_LEN)):
            if b in taken:
                out.append(f"bootstrap blob: {name} overlaps {taken[b]} at byte {b}")
                break
            taken[b] = name


def _check_blob_use(root: Path, out: list[str]) -> None:
    """Every blob field must be written (collectives.cc encode) and read
    (wire.cc CheckPeerBootstrapBlob) by NAME — a field encoded via a raw
    offset is invisible to refactors and to this lint."""
    enc = root / "cpp/src/collectives.cc"
    dec = root / "cpp/src/wire.cc"
    enc_text = strip_c_comments(read_text(enc)) if enc.is_file() else ""
    dec_text = strip_c_comments(read_text(dec)) if dec.is_file() else ""
    if not enc_text:
        out.append("protocol: cpp/src/collectives.cc not found")
    if not dec_text:
        out.append("protocol: cpp/src/wire.cc not found")
    for name in sorted(spec.BOOTSTRAP_BLOB):
        if enc_text and name not in enc_text:
            out.append(f"bootstrap blob: {name} never used by the encode side "
                       f"(collectives.cc) — dead or raw-offset-encoded field")
        # HostId is gathered for topology, not peer-validated; every config
        # field must be checked against the peer's in wire.cc.
        if dec_text and name != "kBlobOffHostId" and name not in dec_text:
            out.append(f"bootstrap blob: {name} never read by CheckPeerBootstrapBlob "
                       f"(wire.cc) — peers would not detect a mismatch")


_ENUM_SITES = (
    # (enum name, file, spec table, count constant or None)
    ("WireCodec", "cpp/include/tpunet/utils.h", "WIRE_CODEC_ENUM", "kWireCodecCount"),
    ("TrafficClass", "cpp/include/tpunet/qos.h", "TRAFFIC_CLASS_ENUM", "kTrafficClassCount"),
    ("CollAlgo", "cpp/src/dispatch.h", "COLL_ALGO_ENUM", "kCollAlgoCount"),
    ("CollKind", "cpp/src/dispatch.h", "COLL_KIND_ENUM", "kCollKindCount"),
    ("FaultAction", "cpp/src/fault.h", "FAULT_ACTION_ENUM", None),
    ("ChurnAction", "cpp/src/fault.h", "CHURN_ACTION_ENUM", None),
    ("SwapAction", "cpp/src/fault.h", "SWAP_ACTION_ENUM", None),
)


def _check_cpp_enums(root: Path, out: list[str]) -> None:
    for enum_name, rel, table, count_name in _ENUM_SITES:
        path = root / rel
        if not path.is_file():
            out.append(f"protocol: {rel} not found")
            continue
        text = strip_c_comments(read_text(path))
        actual = _cpp_enum(text, enum_name)
        want = getattr(spec, table)
        if actual is None:
            out.append(f"wire enum {enum_name}: not found (or unparseable) in {rel}")
            continue
        _diff(out, f"wire enum {enum_name} ({rel})", actual, want)
        if count_name:
            counts = {n: int(v) for n, v in _INT_COUNT.findall(text)}
            if counts.get(count_name) != len(want):
                out.append(f"wire enum {enum_name}: {count_name} "
                           f"{counts.get(count_name)} != spec count {len(want)}")


def _check_serve_protocol(root: Path, out: list[str]) -> None:
    path = root / "tpunet/serve/protocol.py"
    if not path.is_file():
        out.append("protocol: tpunet/serve/protocol.py not found")
        return
    text = read_text(path)

    m = re.search(r'(?m)^MAGIC\s*=\s*b"(\w+)"', text)
    if not m or m.group(1).encode() != spec.SERVE_MAGIC:
        out.append(f"serve frames: MAGIC != spec {spec.SERVE_MAGIC!r}")
    vers = _py_assigns(text, "VERSION")
    if vers.get("VERSION") != spec.SERVE_VERSION:
        out.append(f"serve frames: VERSION {vers.get('VERSION')} != spec {spec.SERVE_VERSION}")

    types = _py_assigns(text, r"T_\w+")
    _diff(out, "serve frame types (protocol.py)", types, spec.SERVE_FRAME_TYPES)
    by_val: dict[int, str] = {}
    for name, v in sorted(types.items()):
        if v in by_val:
            out.append(f"serve frame types: {name} collides with {by_val[v]} on {v}")
        by_val[v] = name
    _diff(out, "serve roles (protocol.py)",
          _py_assigns(text, r"ROLE_\w+"), spec.SERVE_ROLES)
    _diff(out, "swap status (protocol.py)",
          _py_assigns(text, r"SWAP_(?:FLIPPED|ABORTED)"), spec.SWAP_STATUS)

    fmts = _py_struct_fmts(text)
    want_fmts = {n: f for n, (f, _s) in spec.SERVE_STRUCTS.items()}
    _diff(out, "serve structs (protocol.py)", fmts, want_fmts)
    for name, (fmt, size) in sorted(spec.SERVE_STRUCTS.items()):
        try:
            actual_size = struct.calcsize(fmt)
        except struct.error:
            out.append(f"serve structs: spec format {fmt!r} for {name} is invalid")
            continue
        if actual_size != size:
            out.append(f"serve structs: {name} format {fmt!r} is {actual_size}B "
                       f"on the wire but spec claims {size}B")
    for name in ("_HEADER", "_HELLO"):
        fmt = fmts.get(name, "")
        if fmt and not fmt.startswith("<4s"):
            out.append(f"serve structs: {name} does not lead with the 4-byte magic")

    # Cross-language byte identity: the Python codec/class ids ride the same
    # frames the C++ enums define.
    codec_ids = _py_dict_literal(text, "_CODEC_IDS")
    if codec_ids != spec.WIRE_CODEC_IDS:
        out.append(f"serve frames: _CODEC_IDS {codec_ids!r} != spec {spec.WIRE_CODEC_IDS!r}")
    if sorted(spec.WIRE_CODEC_IDS.values()) != sorted(spec.WIRE_CODEC_ENUM.values()):
        out.append("wire codec: spec Python ids and C++ enum values are not the same set")
    class_ids = _py_dict_literal(text, "_CLASS_IDS")
    if class_ids != spec.TRAFFIC_CLASS_IDS:
        out.append(f"serve frames: _CLASS_IDS {class_ids!r} != spec {spec.TRAFFIC_CLASS_IDS!r}")
    if sorted(spec.TRAFFIC_CLASS_IDS.values()) != sorted(spec.TRAFFIC_CLASS_ENUM.values()):
        out.append("traffic class: spec Python ids and C++ enum values are not the same set")


def _check_chaos_grammar(root: Path, out: list[str]) -> None:
    fault_cc = root / "cpp/src/fault.cc"
    if not fault_cc.is_file():
        out.append("protocol: cpp/src/fault.cc not found")
        cc_strings: set[str] = set()
    else:
        cc_strings = set(re.findall(r'"(\w+)"', strip_c_comments(read_text(fault_cc))))
        for tok in (spec.FAULT_ACTION_TOKENS + spec.CHURN_ACTION_TOKENS
                    + spec.SWAP_ACTION_TOKENS + ("churn", "swap")):
            if tok not in cc_strings:
                out.append(f"chaos grammar: token {tok!r} not accepted by fault.cc")

    # Python mirrors map wire enum value -> token; both sides must agree with
    # the C++ enum AND the token list.
    for rel, name, enum_table, tokens in (
        ("tpunet/elastic.py", "_CHURN_ACTIONS", spec.CHURN_ACTION_ENUM,
         spec.CHURN_ACTION_TOKENS),
        ("tpunet/serve/publish.py", "_SWAP_ACTIONS", spec.SWAP_ACTION_ENUM,
         spec.SWAP_ACTION_TOKENS),
    ):
        path = root / rel
        if not path.is_file():
            out.append(f"protocol: {rel} not found")
            continue
        mapping = _py_dict_literal(read_text(path), name)
        if not isinstance(mapping, dict):
            out.append(f"chaos grammar: {name} not found in {rel}")
            continue
        # Expected value->token from the spec enum: kKill=1 <-> "kill".
        want = {0: None}
        for ename, val in enum_table.items():
            if val:
                want[val] = ename[1:].lower()
        if mapping != want:
            out.append(f"chaos grammar: {rel} {name} {mapping!r} != C++ enum layout {want!r}")
        got_tokens = tuple(v for _k, v in sorted(mapping.items()) if v)
        if got_tokens != tokens:
            out.append(f"chaos grammar: {rel} tokens {got_tokens!r} != spec {tokens!r}")


def check_protocol(root: Path) -> list[str]:
    """Cross-check every wire contract against tools/protocol/spec.py."""
    out: list[str] = []
    _check_wire_h(root, out)
    _check_blob_use(root, out)
    _check_cpp_enums(root, out)
    _check_serve_protocol(root, out)
    _check_chaos_grammar(root, out)
    return out
