"""The tpunet wire-contract registry — one declarative spec, machine-checked.

Every constant here is the *claimed* shape of a wire contract the stack
speaks; ``tools/protocol/__init__.py`` extracts the *actual* constants from
the C++ and Python sources and cross-checks both directions, so a drifted
byte layout (or a spec gone stale) is a red lint lane, not a fleet desync.

The registry is data, not code: editing a protocol is a one-place edit here
plus the implementation — the checker proves the two agree. docs/DESIGN.md
"Protocol registry & model checking" documents how to add an entry.
"""

from __future__ import annotations

# ---- v3 connection preamble (cpp/src/wire.h) -------------------------------
# [magic | bundle_id | stream_id | nstreams | min_chunksize | flags],
# all big-endian u64.
WIRE_MAGIC = 0x7470756E65743103  # "tpunet" + framing version byte (v3)
WIRE_VERSION = 3
PREAMBLE_BYTES = 48
PREAMBLE_FIELDS = (
    "magic", "bundle_id", "stream_id", "nstreams", "min_chunksize", "flags",
)
MAX_STREAMS = 256

# Preamble flags word: single-bit capabilities (low bits) plus the QoS
# traffic-class nibble at bits 8..11 (valid only with the Qos flag).
PREAMBLE_FLAGS = {  # name in wire.h -> bit index
    "kPreambleFlagCrc": 0,
    "kPreambleFlagQos": 1,
    "kPreambleFlagLanes": 2,
    "kPreambleFlagShm": 3,
}
PREAMBLE_CLASS_SHIFT = 8
PREAMBLE_CLASS_BITS = 4

# ---- ctrl-stream frame vocabulary (cpp/src/wire.h) -------------------------
# A raw u64 < 2^MAX_CTRL_LEN_BITS is a message length; reserved top bytes
# are transport control frames.
MAX_CTRL_LEN_BITS = 56
CTRL_OPCODES = {  # name in wire.h -> top byte
    "kCtrlFrameWeights": 0xFC,
    "kCtrlFrameNack": 0xFD,
    "kCtrlFrameFailover": 0xFE,
}
# Bit-field layout per opcode: field -> (low bit, width). NACK/FAILOVER pack
# via PackCtrlFrame (stream in bits 48..55, arg in 0..47); WEIGHTS packs via
# PackWeightsFrame (stream count in 32..47 — 8 bits cannot hold
# MAX_STREAMS == 256 — epoch in 0..31).
CTRL_LAYOUTS = {
    "kCtrlFrameNack": {"stream": (48, 8), "confirmed_seq": (0, 48)},
    "kCtrlFrameFailover": {"stream": (48, 8), "unit_count": (0, 48)},
    "kCtrlFrameWeights": {"nstreams": (32, 16), "epoch": (0, 32)},
}

# ---- collective bootstrap blob (wire.h offsets, collectives.cc use) --------
# The 16-byte per-rank unit of the schedule-config AllGather. Offsets and
# widths must tile the blob with no overlap; every field must be written by
# the encode side AND read by the peer-validation side.
BOOTSTRAP_BLOB_LEN = 16
BOOTSTRAP_BLOB = {  # wire.h constant -> (offset, width in bytes)
    "kBlobOffCodec": (0, 1),
    "kBlobOffAlgo": (1, 1),
    "kBlobOffTableCrc": (2, 4),
    "kBlobOffQosClass": (6, 1),
    "kBlobOffA2aAlgo": (7, 1),
    "kBlobOffHostId": (8, 8),
}

# ---- one-byte wire enums (cross the preamble nibble / bootstrap blob /
# serve frames; C++ definition and Python mirror must be byte-identical) ----
WIRE_CODEC_ENUM = {"kF32": 0, "kBF16": 1, "kI8": 2}      # utils.h WireCodec
WIRE_CODEC_IDS = {"f32": 0, "bf16": 1, "int8": 2}        # protocol.py mirror
TRAFFIC_CLASS_ENUM = {"kLatency": 0, "kBulk": 1, "kControl": 2}  # qos.h
TRAFFIC_CLASS_IDS = {"latency": 0, "bulk": 1, "control": 2}      # protocol.py
COLL_ALGO_ENUM = {  # dispatch.h CollAlgo — rides the blob as one byte
    "kAuto": 0, "kRing": 1, "kRhd": 2, "kTree": 3, "kHier": 4,
    "kHierA2a": 5, "kPairwise": 6,
}
COLL_KIND_ENUM = {"kAllReduce": 0, "kBroadcast": 1, "kAllToAll": 2}

# ---- serving-tier frames (tpunet/serve/protocol.py) ------------------------
SERVE_MAGIC = b"TPKV"
SERVE_VERSION = 1
SERVE_FRAME_TYPES = {
    "T_BLOCK": 1,
    "T_FIRST": 2,
    "T_RESULT": 3,
    "T_SHUTDOWN": 4,
    "T_SWAP_BEGIN": 5,
    "T_SWAP_STATUS": 6,
    "T_SWAP_RETIRE": 7,
}
SERVE_ROLES = {"ROLE_FRONTEND": 0, "ROLE_DECODE": 1}
# struct name in protocol.py -> (format, size in bytes). Sizes are stated
# redundantly on purpose: struct.calcsize re-derives them at check time, so
# a format edit that silently changes a frame size turns the lane red until
# the spec (and every peer) acknowledges the new layout.
SERVE_STRUCTS = {
    "_HEADER": ("<4sHHQII", 24),      # magic, version, type, req_id, body_len, aux
    "_HELLO": ("<4sHBBIIIIQ", 32),    # magic, version, role, codec, slots,
                                      # max_len, vocab, class|version<<8, model_sig
    "_BLOCK_HDR": ("<IIIIB3x", 20),   # plen, max_new, n_kv, vocab, codec
    "_RESULT_HDR": ("<IIQ", 16),      # ntok, status, tpot_us
    "_SWAP_HDR": ("<IIIQIBBI", 30),   # version, world, rank, nelems,
                                      # chunk_bytes, codec, class, timeout_ms
}
SWAP_STATUS = {"SWAP_FLIPPED": 1, "SWAP_ABORTED": 2}
# The HELLO traffic-class word carries the weight version in its upper 24
# bits (class in the low byte) — the mixed-build interop contract.
HELLO_WEIGHT_VERSION_SHIFT = 8

# ---- chaos grammar actions (fault.{h,cc} + the Python mirrors) -------------
FAULT_ACTION_ENUM = {  # fault.h FaultAction
    "kNone": 0, "kClose": 1, "kStall": 2, "kCorrupt": 3, "kDelay": 4,
}
CHURN_ACTION_ENUM = {"kNone": 0, "kKill": 1, "kJoin": 2}
SWAP_ACTION_ENUM = {"kNone": 0, "kPublish": 1, "kCorrupt": 2, "kDie": 3}
FAULT_ACTION_TOKENS = ("close", "stall", "corrupt", "delay")
CHURN_ACTION_TOKENS = ("kill", "join")   # mirrored by tpunet/elastic.py
SWAP_ACTION_TOKENS = ("publish", "corrupt", "die")  # tpunet/serve/publish.py

# Error-code wire constants (TPUNET_ERR_* <-> typed Python exceptions) are a
# registry of their own: tools/lint/errcodes.py checks them; this spec does
# not restate the table.
