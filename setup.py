"""Build hook: compile libtpunet.so (make -C cpp) and bundle it as package
data so wheels are self-contained (reference analogue: release workflow built
the .so and shipped a tarball; we additionally ship a wheel)."""

import shutil
import subprocess
from pathlib import Path

from setuptools import setup
from setuptools.command.build_py import build_py

ROOT = Path(__file__).resolve().parent


class BuildWithNative(build_py):
    def run(self):
        cpp = ROOT / "cpp"
        if cpp.is_dir():
            subprocess.run(
                ["make", "-C", str(cpp), "-j", "build/libtpunet.so"], check=True
            )
            dest = ROOT / "tpunet" / "lib"
            dest.mkdir(exist_ok=True)
            shutil.copy2(cpp / "build" / "libtpunet.so", dest / "libtpunet.so")
        super().run()


setup(cmdclass={"build_py": BuildWithNative})
