"""tpunet headline benchmark (driver entry).

Measures the framework's headline metric — ring AllReduce bus bandwidth over
the multi-stream DCN transport — in the reference's own terms: a 128 MiB
AllReduce between 2 ranks, multi-stream engine vs the single-stream baseline
(the configuration stock NCCL-TCP / gRPC-DCN uses one connection per peer;
reference headline: +50% AllReduce throughput from multi-stream striping,
reference README.md:50).

Prints ONE JSON line:
  {"metric": "allreduce_busbw_128MiB",
   "value": <GB/s, MEDIAN of the winning config over the paired reps>,
   "unit": "GB/s",
   "vs_baseline": <median multi-stream / median single-stream>,
   "value_iqr"/"baseline_iqr": <GB/s spread over the reps>, "reps": N,
   "best_config": <sweep key>, "sweep": {<config>: GB/s, ...},
   "analysis": "PERF_NOTES.md",
   "model_tier": {"platform": "tpu"|"cpu", "tokens_per_s": N, "mfu": N,
                  "vgg_img_per_s": N, ...}}
Round-5 methodology (verdict item 6): a sweep picks the winning
multi-stream config — each config measured SWEEP_REPS (3) times and
compared by MEDIAN, because a single-shot winner on this box is
noise-picked (±20% run-to-run band) and the dispatch tables busbw_sweep
seeds inherit whatever the sweep blesses — then TPUNET_BENCH_REPS
(default 10) PAIRED, INTERLEAVED winner/baseline runs produce medians +
IQRs; interleaving puts slow drift on both sides of the ratio.

busbw follows the nccl-tests definition for AllReduce: 2*(W-1)/W * bytes / t.
The model tier (benchmarks.tpu_headline) runs in a subprocess on the real
TPU chip — probed first with a hard timeout because a down tunnel hangs
jax.devices() forever — and falls back to a CPU smoke config flagged by
"platform": "cpu".
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

from benchmarks import spawn_ranks

NBYTES = 128 << 20  # 128 MiB, the top of the reference's sweep (-e 128M)
WORLD = 2
WARMUP = 2
ITERS = 6
MULTI_NSTREAMS = 4


def _worker(rank: int, world: int, port: int, q, nstreams: int,
            extra_env: dict | None = None) -> None:
    try:
        os.environ["TPUNET_NSTREAMS"] = str(nstreams)
        os.environ.setdefault("TPUNET_MIN_CHUNKSIZE", str(1 << 20))
        for k, v in (extra_env or {}).items():
            os.environ[k] = str(v)
        import numpy as np

        from tpunet.collectives import Communicator

        comm = Communicator(
            coordinator=f"127.0.0.1:{port}", rank=rank, world_size=world
        )
        n = NBYTES // 4
        times = []
        for it in range(WARMUP + ITERS):
            arr = np.full(n, float(rank + 1), dtype=np.float32)
            comm.barrier()
            t0 = time.perf_counter()
            out = comm.all_reduce(arr, inplace=True)
            dt = time.perf_counter() - t0
            if it >= WARMUP:
                times.append(dt)
        expect = float(sum(r + 1 for r in range(world)))
        if out[0] != expect or out[-1] != expect:
            raise RuntimeError(f"allreduce wrong result: {out[0]} != {expect}")
        comm.close()
        q.put((rank, ("OK", times)))
    except Exception as e:  # surface the failure to the parent
        q.put((rank, (f"ERR: {e!r}", [])))


def _run_config(nstreams: int, extra_env: dict | None = None) -> float:
    """Returns busbw in GB/s (best iteration, nccl-tests convention)."""
    from benchmarks import check_rank_results

    results = check_rank_results(
        spawn_ranks(_worker, WORLD, extra_args=(nstreams, extra_env), timeout=300)
    )
    # Per iteration both ranks measure the same collective; use the max of the
    # per-rank times (the collective isn't done until the slowest rank is),
    # then the best iteration, as nccl-tests does with its min/avg columns.
    per_iter = [
        max(results[r][i] for r in range(WORLD)) for i in range(ITERS)
    ]
    best = min(per_iter)
    busbw_factor = 2.0 * (WORLD - 1) / WORLD
    return busbw_factor * NBYTES / best / 1e9


def _tpu_alive(timeout_s: int = 90) -> bool:
    """True iff jax can enumerate the TPU without hanging (down tunnel =
    infinite hang, so this MUST be probed in a killable subprocess)."""
    try:
        p = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices()[0]; print(d.platform)"],
            capture_output=True, text=True, timeout=timeout_s,
        )
        return p.returncode == 0 and p.stdout.strip() == "tpu"
    except subprocess.TimeoutExpired:
        return False


def _run_json_tool(argv: list[str], timeout_s: int) -> tuple[dict | None, str]:
    """Run a benchmark subprocess that prints one JSON line; returns
    (parsed dict, "") or (None, error description)."""
    from benchmarks import run_json_lines

    rows, err = run_json_lines(argv, timeout_s)
    return (rows[-1], "") if rows else (None, err)


def _kernel_smoke(tpu_up: bool) -> dict | None:
    """Per-kernel compile+run probe (benchmarks.kernel_smoke) in its own
    subprocess, so a Mosaic rejection is a line item — not a model-tier
    wipeout (the round-2 failure mode)."""
    if not tpu_up:
        return None
    out, err = _run_json_tool(["-m", "benchmarks.kernel_smoke"], 600)
    return out if out is not None else {"error": f"kernel smoke failed: {err}"}


# The committed-measurement replay is only trustworthy while the code it
# measured is the code at HEAD. These are the paths whose changes invalidate
# the model-tier numbers: kernels, model defs, the train-step builder and
# optimizer plumbing, and the timing harness (chained_step_time lives in
# benchmarks/__init__.py).
MEASURED_PATHS = ("tpunet/ops", "tpunet/models", "tpunet/train",
                  "benchmarks/tpu_headline.py", "benchmarks/__init__.py")

# Step scripts whose edits invalidate the OTHER fields a chip_session
# writes into the measured file (decode set, attribution, sweeps) —
# chip_session.py itself is deliberately absent: pure orchestration changes
# re-measure nothing, and its parameter table is covered by the
# steps_fingerprint chip_session records. One constant, shared by the
# replay stamp below and chip_session's resume check, so the two can't
# disagree about what "the measured code" means.
SESSION_SCRIPT_PATHS = ("benchmarks/kernel_smoke.py",
                        "benchmarks/decode_bench.py",
                        "benchmarks/mfu_attribution.py",
                        "benchmarks/mfu_sweep.py",
                        "benchmarks/serve_bench.py")


def _dirty_paths(paths: tuple, repo: str | None = None) -> list[str] | None:
    """Uncommitted (incl. untracked) files under `paths`, or None when
    undecidable (git failed/timed out) — callers must treat None
    conservatively, not as clean."""
    repo = repo or os.path.dirname(os.path.abspath(__file__))
    try:
        st = subprocess.run(
            ["git", "status", "--porcelain", "--", *paths],
            capture_output=True, text=True, timeout=30, cwd=repo)
        if st.returncode != 0:
            return None
        return sorted({ln[3:].strip() for ln in st.stdout.splitlines()
                       if ln.strip()})
    except (OSError, subprocess.TimeoutExpired):
        return None


def _measurement_staleness(measured_commit: str | None,
                           paths: tuple = MEASURED_PATHS) -> dict:
    """Self-checking replay provenance: diff the measured commit against HEAD
    over the measured code paths and report `stale` mechanically, instead of
    asserting freshness in a static file (which is guaranteed to rot).
    Uncommitted edits to those paths also count as stale. `paths` lets
    callers with a wider validity surface (chip_session resume adds its
    step scripts) reuse this one audited implementation."""
    repo = os.path.dirname(os.path.abspath(__file__))
    parts = (measured_commit or "").split()
    commit = parts[0] if parts else ""
    if not commit:
        return {"stale": None, "error": "no measured_commit recorded"}
    try:
        p = subprocess.run(
            ["git", "diff", "--name-only", f"{commit}..HEAD", "--",
             *paths],
            capture_output=True, text=True, timeout=30, cwd=repo)
        if p.returncode != 0:
            return {"stale": None,
                    "error": (p.stderr.strip() or "git diff failed")[-200:]}
        changed = sorted({ln.strip() for ln in p.stdout.splitlines()
                          if ln.strip()})
        dirty = _dirty_paths(paths, repo)
        if dirty is None:
            # Committed history may already prove staleness; only a CLEAN
            # verdict needs the working-tree scan to have succeeded.
            if changed:
                return {"stale": True, "changed_files": changed}
            return {"stale": None, "error": "git status failed"}
        out = {"stale": bool(changed or dirty), "changed_files": changed}
        if dirty:
            out["uncommitted_files"] = dirty
        return out
    except (OSError, subprocess.TimeoutExpired) as e:
        return {"stale": None, "error": repr(e)[-200:]}


def _model_tier(tpu_up: bool, kernels: dict | None) -> dict | None:
    """Run benchmarks.tpu_headline on the chip (or CPU fallback). Kernels
    that failed their smoke are individually dropped to their fallback impl
    (per-kernel, not per-platform): a broken or even crashed smoke still
    leaves the TPU attempt alive, just with reference attention."""
    from benchmarks import flash_smoke_ok

    attempts = []
    if tpu_up:
        flash_ok = flash_smoke_ok(kernels)
        if not flash_ok:
            print("[bench] flash kernel smoke not ok; model tier uses "
                  "reference attention on TPU", file=sys.stderr)
        # Generous: the chip-sized headline model (735M params) spends
        # 2-4 min in XLA compile over the tunnel before its ~8s of steps,
        # and a timeout here silently costs the whole hardware story.
        attempts.append(("tpu", "flash" if flash_ok else "reference", 2400))
    else:
        print("[bench] TPU tunnel down; model tier falls back to CPU smoke",
              file=sys.stderr)
    attempts.append(("cpu", "reference", 900))
    for platform, attn, timeout_s in attempts:
        out, err = _run_json_tool(
            ["-m", "benchmarks.tpu_headline", "--platform", platform,
             "--attn", attn], timeout_s)
        if out is not None:
            return out
        print(f"[bench] model tier ({platform}) failed: {err}", file=sys.stderr)
    return None


def _decode_tier(tpu_up: bool, model_tier: dict | None) -> dict | None:
    """Inference tier: one on-chip decode number (GQA, the KV-cache
    capability's headline config). The full decode/attribution set is
    benchmarks.chip_session's job; bench carries one live datapoint.
    Returns None unless the result actually ran on the chip — a tunnel
    drop between tiers makes decode_bench silently fall back to CPU, and
    a CPU number must not pose as the on-chip datapoint."""
    if not tpu_up or (model_tier or {}).get("platform") != "tpu":
        return None
    decode, err = _run_json_tool(
        ["-m", "benchmarks.decode_bench", "--platform", "tpu",
         "--d", "2048", "--layers", "12", "--heads", "16", "--ff", "8192",
         "--batch", "8", "--prompt", "512", "--new", "128",
         "--kv-heads", "4"], 1500)
    if decode is None:
        print(f"[bench] decode tier failed: {err}", file=sys.stderr)
        return None
    if decode.get("platform") != "tpu":
        print(f"[bench] decode tier ran on {decode.get('platform')}, "
              "not tpu; dropping it", file=sys.stderr)
        return None
    print(f"[bench] decode tier: {decode}", file=sys.stderr)
    return decode


def main() -> None:
    # Make sure the native library exists before timing anything.
    from tpunet import _native

    _native.build_native()

    # In-bench mini-sweep: the best multi-stream configuration, not just the
    # fixed default — on many-core hosts striping wins, on this 1-core
    # sandbox all configs tie at the wire ceiling (analysis: PERF_NOTES.md).
    multi_cfgs = [
        (MULTI_NSTREAMS, None),
        (2, None),
        (MULTI_NSTREAMS, {"TPUNET_RING_CHUNKSIZE": 2 << 20}),
    ]
    import statistics

    # Median of SWEEP_REPS per config: a single-shot winner is noise-picked
    # on this box (±20% band vs a few-% config effect), and the winner feeds
    # both the headline's paired reps AND the methodology the dispatch-table
    # sweep (busbw_sweep --emit-dispatch) copies.
    SWEEP_REPS = 3
    sweep = {}
    cfg_by_key = {}
    for ns, extra in multi_cfgs:
        key = f"ns{ns}" + ("_chunk2M" if extra else "")
        sweep[key] = statistics.median(
            _run_config(ns, extra) for _ in range(SWEEP_REPS))
        cfg_by_key[key] = (ns, extra)
    best_key = max(sweep, key=sweep.get)
    best_ns, best_extra = cfg_by_key[best_key]
    # Paired interleaved reps of winner vs single-stream baseline:
    # medians + IQRs instead of a single best-of sample (the box's ±20%
    # run-to-run band was wider than every effect measured on it).
    reps = max(int(os.environ.get("TPUNET_BENCH_REPS", "10")), 1)
    best_runs, base_runs = [], []
    for rep in range(reps):
        best_runs.append(_run_config(best_ns, best_extra))
        base_runs.append(_run_config(nstreams=1))
        print(f"[bench] rep {rep}: {best_key} {best_runs[-1]:.3f} GB/s, "
              f"ns1 {base_runs[-1]:.3f} GB/s", file=sys.stderr)

    def _iqr(xs):
        from benchmarks import iqr as _shared_iqr

        spread = _shared_iqr(xs)
        return round(spread, 3) if spread is not None else None

    best = statistics.median(best_runs)
    baseline = statistics.median(base_runs)
    best_iqr, base_iqr = _iqr(best_runs), _iqr(base_runs)
    print(
        f"[bench] medians over {reps} paired reps: single-stream "
        f"{baseline:.3f} GB/s (IQR {base_iqr}), {best_key} {best:.3f} GB/s "
        f"(IQR {best_iqr}) -> {best / baseline:.2f}x",
        file=sys.stderr,
    )
    tpu_up = _tpu_alive()
    kernels = _kernel_smoke(tpu_up)
    if kernels is not None:
        print(f"[bench] kernel smoke: {kernels}", file=sys.stderr)
    model_tier = _model_tier(tpu_up, kernels)
    if model_tier is not None:
        print(f"[bench] model tier: {model_tier}", file=sys.stderr)
    decode = _decode_tier(tpu_up, model_tier)

    # The committed real-chip measurement (benchmarks.chip_session output)
    # is attached UNCONDITIONALLY with explicit provenance and a mechanical
    # staleness stamp — when the tunnel is down it is the round's hardware
    # story; when live numbers exist it adds the depth (decode set,
    # per-segment attribution, block sweeps) a single bench run doesn't
    # re-measure. Clearly labeled, never merged into the live fields.
    tpu_last_measured = None
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "benchmarks", "tpu_measured.json")) as f:
            loaded = json.load(f)
        if isinstance(loaded, dict):
            tpu_last_measured = loaded
            # The file carries more than the model tier (decode set,
            # attribution, sweeps), so its validity surface is the session
            # scripts too — same path set chip_session's resume check uses.
            staleness = _measurement_staleness(
                loaded.get("measured_commit"),
                paths=MEASURED_PATHS + SESSION_SCRIPT_PATHS)
            dirty_at_measure = loaded.get("uncommitted_at_measurement")
            if dirty_at_measure:
                # Measured with uncommitted edits: unreproducible from the
                # stamped commit no matter what HEAD looks like now.
                staleness = {**staleness, "stale": True,
                             "dirty_at_measurement": dirty_at_measure}
            tpu_last_measured["staleness"] = staleness
            stale_note = (
                "STALE — "
                + ("measured with uncommitted edits: "
                   + ", ".join(dirty_at_measure)
                   if dirty_at_measure else
                   "measured paths changed since: "
                   + ", ".join(staleness.get("changed_files", [])
                               + staleness.get("uncommitted_files", [])))
                if staleness.get("stale")
                else "fresh (measured paths unchanged at HEAD)"
                if staleness.get("stale") is False
                else f"staleness unknown: {staleness.get('error')}")
            print("[bench] attaching committed chip measurement from "
                  f"{loaded.get('measured_at')} "
                  f"(commit {loaded.get('measured_commit')}; "
                  f"{stale_note})", file=sys.stderr)
    except (OSError, ValueError):
        pass
    print(
        json.dumps(
            {
                "metric": "allreduce_busbw_128MiB",
                "value": round(best, 3),
                "unit": "GB/s",
                "vs_baseline": round(best / baseline, 3),
                "value_iqr": best_iqr,
                "baseline_gbps": round(baseline, 3),
                "baseline_iqr": base_iqr,
                "reps": reps,
                "best_config": best_key,
                "sweep": {k: round(v, 3) for k, v in sweep.items()},
                "sweep_reps": SWEEP_REPS,
                "analysis": "PERF_NOTES.md",
                "kernels": kernels,
                "model_tier": model_tier,
                **({"decode": decode} if decode else {}),
                **({"tpu_last_measured": tpu_last_measured}
                   if tpu_last_measured else {}),
            }
        )
    )


if __name__ == "__main__":
    main()
