"""Transport observability accessors.

The native layer records per-request metrics (always-on counters) and,
when env-gated, trace spans (SURVEY §5; reference: OpenTelemetry pipeline in
nthread_per_socket_backend.rs:108-212). This module reads them from Python:

  metrics_text()  -> Prometheus exposition text
  metrics()       -> parsed {metric_name: {labels_tuple: value}}
  flush_trace()   -> write buffered spans to TPUNET_TRACE_DIR

Env flags (rank-gated 0-7 like the reference, nthread:108-130):
  TPUNET_TRACE_DIR            directory for Chrome-trace JSON (Perfetto)
  TPUNET_METRICS_ADDR         pushgateway "user:pass@host:port"
  TPUNET_METRICS_INTERVAL_MS  push period, default 1000
"""

from __future__ import annotations

import ctypes
import re

from tpunet import _native


def metrics_text() -> str:
    lib = _native.load()
    # Counters move concurrently, so the text can grow between the sizing
    # call and the copy; retry until the copy fits its own length.
    cap = 4096
    while True:
        buf = ctypes.create_string_buffer(cap)
        n = lib.tpunet_c_metrics_text(buf, cap)
        if n < 0:
            raise _native.NativeError(n, "metrics_text")
        if n < cap:
            return buf.value.decode()
        cap = n + 256


# Prometheus exposition line: the `{labels}` block is OPTIONAL — plain
# `name value` lines are valid exposition and the old mandatory-braces
# pattern silently dropped them from metrics().
_LINE = re.compile(r"^(\w+)(?:\{([^}]*)\})?\s+([0-9.eE+-]+|[+-]?Inf|NaN)$")


def metrics() -> dict:
    """Parse the Prometheus text into {name: {(label=value, ...): float}}.

    Lines without a label block parse to the empty label tuple ()."""
    out: dict = {}
    for line in metrics_text().splitlines():
        if line.startswith("#"):
            continue
        m = _LINE.match(line)
        if not m:
            continue
        name, labels, value = m.groups()
        key = tuple(sorted(labels.split(","))) if labels else ()
        out.setdefault(name, {})[key] = float(value)
    return out


def flush_trace() -> None:
    lib = _native.load()
    _native.check(lib.tpunet_c_trace_flush(), "trace_flush")
