"""Transport observability accessors.

The native layer records per-request metrics (always-on counters), deep
per-stream TCP introspection (rate-limited ``getsockopt(TCP_INFO)`` gauges,
Jain's fairness index, straggler events), request stage-latency histograms
(queueing delay separable from wire time), and — when tracing is on —
Chrome-trace spans for every request plus collective phase spans tagged
``(comm_id, coll_seq, phase)``. This module reads it all from Python:

  metrics_text()      -> Prometheus exposition text (lint-clean HELP/TYPE)
  metrics()           -> parsed {metric_name: {labels_tuple: value}}
  labels(key)         -> a metrics() label tuple as an ordered dict
  histogram_buckets() -> [(upper_bound, cumulative_count)] with `le` parsed
                         numerically (+Inf last)
  reset()             -> zero every counter so warmups don't bleed into
                         measurement windows
  flush_trace()       -> write buffered spans (file is valid JSON after)
  profile()           -> context manager that enables tracing at runtime
  merge_traces()      -> join per-rank trace files into one Perfetto
                         timeline, aligned by collective tags
  scrape()            -> GET the native /metrics listener
  metrics_port()      -> bound port of the /metrics listener (0 = none);
                         the only way to learn an ephemeral-port bind
  serve_observe()     -> record one serving-tier TTFT/TPOT latency sample
  serve_queue_depth() -> set a serving tier's queue-depth gauge
  rewire_observe()    -> record one elastic rewire-phase duration sample
  churn_event()       -> count one membership-churn event by kind
  world_size()        -> set the live world-size gauge
  swap_observe()      -> record one weight-swap phase duration sample
  swap_event()        -> count one weight-swap event by kind
  weight_version()    -> set the serving checkpoint-version gauge
  flightrec_dump()    -> write this rank's flight-recorder ring to disk
  flightrec_stats()   -> (events_recorded, ring_capacity) of the recorder

Env flags (rank-gated 0-7 like the reference, nthread:108-130):
  TPUNET_TRACE_DIR            directory for Chrome-trace JSON (Perfetto)
  TPUNET_METRICS_ADDR         pushgateway "user:pass@host:port"
  TPUNET_METRICS_INTERVAL_MS  push period, default 1000
  TPUNET_METRICS_PORT         on-demand /metrics scrape listener port
                              (unset = off; 0 = bind an EPHEMERAL port,
                              readable via metrics_port())
  TPUNET_TCPINFO_INTERVAL_MS  TCP_INFO sample period per stream (0 = off)
  TPUNET_STRAGGLER_FACTOR     straggler threshold k over the median sRTT
"""

from __future__ import annotations

import contextlib
import ctypes
import glob
import json
import os
import re
import urllib.request

from tpunet import _native


def metrics_text() -> str:
    lib = _native.load()
    # Counters move concurrently, so the text can grow between the sizing
    # call and the copy; retry until the copy fits its own length.
    cap = 16384
    while True:
        buf = ctypes.create_string_buffer(cap)
        n = lib.tpunet_c_metrics_text(buf, cap)
        if n < 0:
            raise _native.NativeError(n, "metrics_text")
        if n < cap:
            return buf.value.decode()
        cap = n + 256


# Prometheus exposition line: the `{labels}` block is OPTIONAL — plain
# `name value` lines are valid exposition and the old mandatory-braces
# pattern silently dropped them from metrics().
_LINE = re.compile(r"^(\w+)(?:\{([^}]*)\})?\s+([0-9.eE+-]+|[+-]?Inf|NaN)$")
_LABEL = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def metrics() -> dict:
    """Parse the Prometheus text into {name: {(label="v", ...): float}}.

    Label tuples preserve the exposition's declaration order (sorting them
    scrambled `le` bucket bounds and made keys depend on label VALUES).
    Lines without a label block parse to the empty label tuple ()."""
    out: dict = {}
    for line in metrics_text().splitlines():
        if line.startswith("#"):
            continue
        m = _LINE.match(line)
        if not m:
            continue
        name, labels, value = m.groups()
        key = tuple(labels.split(",")) if labels else ()
        out.setdefault(name, {})[key] = float(value)
    return out


def labels(key: tuple) -> dict:
    """A metrics() label tuple as an insertion-ordered {name: value} dict:
    labels(('rank="0"', 'le="1024"')) -> {"rank": "0", "le": "1024"}."""
    out = {}
    for part in key:
        m = _LABEL.match(part)
        if m:
            out[m.group(1)] = m.group(2)
    return out


def histogram_buckets(name: str, parsed: dict | None = None) -> list[tuple[float, int]]:
    """Numeric view of a histogram family: [(upper_bound, cumulative_count)]
    sorted by bound with +Inf last, so buckets can be consumed numerically.
    `name` is the family name without the `_bucket` suffix; counts with the
    same `le` across other label sets (e.g. several ranks) are summed."""
    if parsed is None:
        parsed = metrics()
    by_bound: dict[float, int] = {}
    for key, value in parsed.get(name + "_bucket", {}).items():
        le = labels(key).get("le")
        if le is None:
            continue
        bound = float("inf") if le in ("+Inf", "Inf") else float(le)
        by_bound[bound] = by_bound.get(bound, 0) + int(value)
    return sorted(by_bound.items())


def reset() -> None:
    """Zero every metric counter/histogram/gauge (trace spans and the
    in-flight gauge are untouched) — call between a warmup and a measurement
    window so the first doesn't bleed into the second."""
    lib = _native.load()
    _native.check(lib.tpunet_c_metrics_reset(), "metrics_reset")


def metrics_port() -> int:
    """Bound port of the on-demand /metrics listener, or 0 when none is up.

    With ``TPUNET_METRICS_PORT=0`` the native layer binds an EPHEMERAL port
    (so several tiers on one loopback box can each run a listener without
    port bookkeeping) and this accessor is the only way to learn which —
    the env var still reads 0. Forces singleton construction, so it is safe
    to call before any engine exists."""
    lib = _native.load()
    return int(lib.tpunet_c_metrics_port())


_SERVE_KINDS = {"ttft": 0, "tpot": 1}
_SERVE_TIERS = {"router": 0, "prefill": 1, "decode": 2}


def serve_observe(kind: str, us: int) -> None:
    """Record one serving-tier latency sample (microseconds) into the
    ``tpunet_req_ttft_us`` (kind="ttft") or ``tpunet_req_tpot_us``
    (kind="tpot") histogram — the per-request SLO families the
    disaggregated serving tier feeds (docs/DESIGN.md "Serving tier")."""
    if kind not in _SERVE_KINDS:
        raise ValueError(f"kind must be one of {sorted(_SERVE_KINDS)}, got {kind!r}")
    lib = _native.load()
    _native.check(
        lib.tpunet_c_serve_observe(_SERVE_KINDS[kind], max(0, int(us))),
        "serve_observe",
    )


def serve_queue_depth(tier: str, depth: int) -> None:
    """Set the instantaneous ``tpunet_serve_queue_depth{tier=...}`` gauge
    for one serving tier ("router", "prefill" or "decode")."""
    if tier not in _SERVE_TIERS:
        raise ValueError(f"tier must be one of {sorted(_SERVE_TIERS)}, got {tier!r}")
    lib = _native.load()
    _native.check(
        lib.tpunet_c_serve_queue_depth(_SERVE_TIERS[tier], max(0, int(depth))),
        "serve_queue_depth",
    )


_REWIRE_PHASES = {"detect": 0, "quiesce": 1, "rendezvous": 2, "rewire": 3}
_CHURN_KINDS = {"kill": 0, "join": 1, "shrink": 2, "grow": 3, "readmit": 4}


def rewire_observe(phase: str, us: int) -> None:
    """Record one elastic rewire-phase duration sample (microseconds) into
    ``tpunet_rewire_duration_us{phase=...}`` — the bounded-recovery
    histograms the churn suite gates on (docs/DESIGN.md "Elastic churn").
    Phases: "detect" (last good collective -> failure classified / join
    agreed), "quiesce" (old comm finalized), "rendezvous" (membership
    sealed), "rewire" (new communicator wired)."""
    if phase not in _REWIRE_PHASES:
        raise ValueError(
            f"phase must be one of {sorted(_REWIRE_PHASES)}, got {phase!r}")
    lib = _native.load()
    _native.check(
        lib.tpunet_c_rewire_observe(_REWIRE_PHASES[phase], max(0, int(us))),
        "rewire_observe",
    )


def churn_event(kind: str) -> None:
    """Count one membership-churn event into
    ``tpunet_churn_events_total{kind=...}`` ("kill", "join", "shrink",
    "grow" or "readmit")."""
    if kind not in _CHURN_KINDS:
        raise ValueError(
            f"kind must be one of {sorted(_CHURN_KINDS)}, got {kind!r}")
    lib = _native.load()
    _native.check(lib.tpunet_c_churn_event(_CHURN_KINDS[kind]), "churn_event")


def world_size(world: int) -> None:
    """Set the ``tpunet_world_size`` gauge — the live communicator's world
    as this rank last saw it (the churn suite's "world came back" gate)."""
    lib = _native.load()
    _native.check(lib.tpunet_c_world_size(max(0, int(world))), "world_size")


_SWAP_PHASES = {"announce": 0, "broadcast": 1, "verify": 2, "flip": 3}
_SWAP_KINDS = {"publish": 0, "commit": 1, "abort": 2, "retry": 3,
               "mismatch": 4}


def swap_observe(phase: str, us: int) -> None:
    """Record one live weight-swap phase duration sample (microseconds)
    into ``tpunet_weight_swap_duration_us{phase=...}`` — the publication
    pipeline's stage histograms (docs/DESIGN.md "Live weight updates").
    Phases: "announce" (SWAP_BEGIN frames out / receiver armed),
    "broadcast" (chunked bf16 tree broadcast on the bulk class), "verify"
    (cross-rank CRC32C digest agreement), "flip" (new server built,
    version live)."""
    if phase not in _SWAP_PHASES:
        raise ValueError(
            f"phase must be one of {sorted(_SWAP_PHASES)}, got {phase!r}")
    lib = _native.load()
    _native.check(
        lib.tpunet_c_swap_observe(_SWAP_PHASES[phase], max(0, int(us))),
        "swap_observe",
    )


def swap_event(kind: str) -> None:
    """Count one weight-swap event into
    ``tpunet_swap_events_total{kind=...}`` ("publish", "commit", "abort",
    "retry" or "mismatch")."""
    if kind not in _SWAP_KINDS:
        raise ValueError(
            f"kind must be one of {sorted(_SWAP_KINDS)}, got {kind!r}")
    lib = _native.load()
    _native.check(lib.tpunet_c_swap_event(_SWAP_KINDS[kind]), "swap_event")


def weight_version(version: int) -> None:
    """Set the ``tpunet_weight_version`` gauge — the checkpoint version
    this rank is serving (the swap lane's "v2 reached every rank" gate)."""
    lib = _native.load()
    _native.check(
        lib.tpunet_c_weight_version(max(0, int(version))), "weight_version")


def flush_trace() -> None:
    lib = _native.load()
    _native.check(lib.tpunet_c_trace_flush(), "trace_flush")


def flightrec_dump(dir: str | None = None, reason: str = "api") -> str:
    """Write this rank's flight-recorder ring (docs/DESIGN.md §6c) to
    ``<dir>/tpunet-flightrec-rank<R>.json`` and return the path. ``dir=None``
    uses the directory resolved when the recorder initialized
    (TPUNET_TRACE_DIR when set, else "."). ``reason`` lands in the dump
    header so a postmortem can tell an on-demand snapshot from a watchdog
    verdict. Raises NativeError when the recorder is disabled
    (TPUNET_FLIGHTREC_EVENTS=0) or the target is unwritable."""
    lib = _native.load()
    buf = ctypes.create_string_buffer(1024)
    n = lib.tpunet_c_flightrec_dump(
        dir.encode() if dir else None, reason.encode(), buf, len(buf))
    if n < 0:
        _native.check(n, "flightrec_dump")
    return buf.value.decode()


def flightrec_dump_verdict(reason: str) -> str | None:
    """Best-effort flight-recorder dump for Python-side terminal verdicts
    (rewire / weight-swap deadline raise sites — the native layer dumps its
    own watchdog/CRC verdicts). Never raises: the typed error being raised
    is the story, a failed dump must not replace it. Returns the dump path,
    or None when the recorder is disabled or the dump failed."""
    try:
        return flightrec_dump(reason=reason)
    except Exception:
        return None


def flightrec_stats() -> tuple[int, int]:
    """(events_ever_recorded, ring_capacity) of the flight recorder. The
    first is the monotonic claim cursor (NOT clamped to capacity — subtract
    to learn how many events the ring has dropped); both are 0 when the
    recorder is disabled or has never recorded."""
    lib = _native.load()
    rec = ctypes.c_uint64()
    cap = ctypes.c_uint64()
    _native.check(
        lib.tpunet_c_flightrec_stats(ctypes.byref(rec), ctypes.byref(cap)),
        "flightrec_stats")
    return int(rec.value), int(cap.value)


class _Profile:
    """Handle yielded by profile(): where the trace files land."""

    def __init__(self, trace_dir: str):
        self.trace_dir = trace_dir
        self.merged_path: str | None = None

    def rank_files(self) -> list[str]:
        return sorted(glob.glob(os.path.join(self.trace_dir, "tpunet-trace-rank*.json")))


@contextlib.contextmanager
def profile(trace_dir: str | None = None, merge: bool = False):
    """Enable tracing at runtime for the duration of the block.

    Unlike TPUNET_TRACE_DIR (read once at library load), this retargets the
    native tracer on entry and flushes + disables on exit, so a profile can
    bracket exactly one measurement window::

        with telemetry.profile("/tmp/traces") as prof:
            comm.all_reduce(x)
        telemetry.merge_traces(prof.trace_dir)

    With merge=True the per-rank files present in trace_dir are merged into
    one Perfetto timeline on exit (single-host convenience; multi-host jobs
    collect the rank files first and call merge_traces() themselves)."""
    lib = _native.load()
    trace_dir = trace_dir or os.environ.get("TPUNET_TRACE_DIR") or "/tmp/tpunet-traces"
    os.makedirs(trace_dir, exist_ok=True)
    _native.check(lib.tpunet_c_trace_set_dir(trace_dir.encode()), "trace_set_dir")
    prof = _Profile(trace_dir)
    try:
        yield prof
    finally:
        _native.check(lib.tpunet_c_trace_flush(), "trace_flush")
        _native.check(lib.tpunet_c_trace_set_dir(b""), "trace_set_dir")
        if merge:
            prof.merged_path = merge_traces(trace_dir)


def _coll_tags(events: list[dict]) -> dict[tuple, int]:
    """(comm_id, coll_seq, name) -> start ts for collective phase spans."""
    tags = {}
    for ev in events:
        args = ev.get("args") or {}
        if "comm_id" in args and "coll_seq" in args and "ts" in ev:
            key = (args["comm_id"], args["coll_seq"], ev.get("name", ""))
            # Keep the earliest occurrence (phases are unique per rank anyway).
            if key not in tags:
                tags[key] = ev["ts"]
    return tags


def _rank_host(events: list[dict]) -> str | None:
    """Host id of a rank file: the ``host`` tag the native tracer stamps on
    collective phase spans (a hex string of utils.h HostId())."""
    for ev in events:
        h = (ev.get("args") or {}).get("host")
        if h:
            return str(h)
    return None


def merge_traces(trace_dir: str, out_path: str | None = None) -> str:
    """Join every per-rank Chrome-trace JSON in `trace_dir` into ONE
    Perfetto-loadable timeline and return its path.

    Ranks on one host already share the monotonic clock; across hosts the
    clocks are unrelated, so per-rank timelines are aligned on the collective
    phase tags ``(comm_id, coll_seq, phase)``: the earliest tag common to all
    ranks becomes the anchor, and every rank is shifted so its anchor span
    starts at the same instant (the straggler-analysis convention — skew
    WITHIN a collective is preserved, clock offset is not mistaken for it).
    Files without common tags (point-to-point-only traces) merge unshifted.

    Track grouping: phase spans carry a ``host`` tag (HostId()), so ranks
    sharing a host group under ONE Perfetto process track ("host <id>") with
    per-rank thread tracks inside it, instead of interleaving W top-level
    groups — the view that makes an intra-host SHM stage vs inter-host DCN
    stage split readable. Traces from builds without the tag keep the old
    per-rank pid layout.

    Flight-recorder dumps (``tpunet-flightrec-rank*.json``, docs/DESIGN.md
    §6c) present in the directory merge too: each rank's events render as
    instant events on a dedicated "flightrec" thread track inside that
    rank's host group, shifted by the same per-rank offset as its trace
    spans (the recorder stamps the same monotonic clock the tracer uses).
    A directory holding ONLY flightrec dumps — the post-hang case, where
    tracing was never on — still merges (unshifted)."""
    files = sorted(glob.glob(os.path.join(trace_dir, "tpunet-trace-rank*.json")))
    fr_files = sorted(
        glob.glob(os.path.join(trace_dir, "tpunet-flightrec-rank*.json")))
    if not files and not fr_files:
        raise FileNotFoundError(
            f"no tpunet-trace-rank*.json or tpunet-flightrec-rank*.json "
            f"files in {trace_dir}")
    per_rank: list[list[dict]] = []
    ranks: list[int] = []
    for fi, path in enumerate(files):
        with open(path) as f:
            per_rank.append(json.load(f))
        m = re.search(r"rank(\d+)\.json$", path)
        ranks.append(int(m.group(1)) if m else fi)
    # Alignment: anchor on the earliest (comm_id, coll_seq, phase) present in
    # EVERY rank's file; shift each rank so anchors coincide at the max.
    tag_maps = [_coll_tags(events) for events in per_rank]
    common = set(tag_maps[0]) if tag_maps else set()
    for tm in tag_maps[1:]:
        common &= set(tm)
    offsets = [0] * len(per_rank)
    if common and len(per_rank) > 1:
        anchor = min(common, key=lambda k: (k[1], k[2]))  # lowest coll_seq
        target = max(tm[anchor] for tm in tag_maps)
        offsets = [target - tm[anchor] for tm in tag_maps]
    # Flight-recorder dumps are loaded up front so their host ids take part
    # in the host-grouping decision (post-hang merges often have ONLY dumps).
    fr_dumps: list[tuple[int, dict]] = []
    for path in fr_files:
        with open(path) as f:
            dump = json.load(f)
        m = re.search(r"rank(\d+)\.json$", path)
        fr_dumps.append((int(m.group(1)) if m else int(dump.get("rank", 0)),
                         dump))
    hosts = [_rank_host(events) for events in per_rank]
    group_by_host = any(h is not None for h in hosts) or \
        any(d.get("host") for _, d in fr_dumps)
    host_order: list[str] = []
    if group_by_host:
        for h in hosts:
            key = h if h is not None else "?"
            if key not in host_order:
                host_order.append(key)
    merged: list[dict] = []
    if group_by_host:
        for pid, host in enumerate(host_order, start=1):
            merged.append({"name": "process_name", "ph": "M", "pid": pid,
                           "args": {"name": f"host {host}"}})
    for events, off, host, rank in zip(per_rank, offsets, hosts, ranks):
        pid = host_order.index(host if host is not None else "?") + 1 \
            if group_by_host else None
        for ev in events:
            if group_by_host and ev.get("ph") == "M" and \
                    ev.get("name") == "process_name":
                continue  # replaced by the per-host group metadata above
            if off and "ts" in ev or group_by_host:
                ev = dict(ev)
            if off and "ts" in ev:
                ev["ts"] = ev["ts"] + off
            if group_by_host:
                # One process group per host; rank-disambiguated thread ids
                # inside it (native tids are small: comm ids / stream idx).
                ev["pid"] = pid
                ev["tid"] = rank * 1_000_000 + int(ev.get("tid", 0))
            merged.append(ev)
    # Flight-recorder dumps ride the same timeline: instant events on a
    # per-rank "flightrec" thread track, reusing the offset computed from
    # that rank's trace file (same monotonic clock on the same host).
    rank_offsets = dict(zip(ranks, offsets))
    for rank, dump in fr_dumps:
        off = rank_offsets.get(rank, 0)
        host = dump.get("host")
        if group_by_host:
            key = str(host) if host else "?"
            if key not in host_order:
                host_order.append(key)
                merged.append({"name": "process_name", "ph": "M",
                               "pid": len(host_order),
                               "args": {"name": f"host {key}"}})
            pid = host_order.index(key) + 1
            tid = rank * 1_000_000 + 999_999
        else:
            pid, tid = rank, 999_999
        merged.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": tid, "args": {"name": f"flightrec rank {rank}"}})
        for ev in dump.get("events", []):
            label = ev.get("kind", "?")
            if ev.get("name"):
                label = f"{label}:{ev['name']}"
            merged.append({
                "name": label, "ph": "i", "s": "t",
                "ts": ev.get("t", 0) + off, "pid": pid, "tid": tid,
                "args": {k: ev[k] for k in ("a", "b", "c", "d") if k in ev},
            })
    out_path = out_path or os.path.join(trace_dir, "tpunet-trace-merged.json")
    with open(out_path, "w") as f:
        json.dump(merged, f)
    return out_path


def scrape(port: int | None = None, host: str = "127.0.0.1", timeout: float = 5.0) -> str:
    """GET the native on-demand /metrics listener (TPUNET_METRICS_PORT) and
    return the exposition text — what a Prometheus scraper would see. With
    no explicit port, falls back to the env var and then to the natively
    bound port (metrics_port()) — which covers the ephemeral-port case
    (TPUNET_METRICS_PORT=0)."""
    if port is None:
        port = int(os.environ.get("TPUNET_METRICS_PORT", "0") or "0")
    if not port:
        port = metrics_port()
    if not port:
        raise ValueError("no port given, TPUNET_METRICS_PORT unset, and no "
                         "native /metrics listener is bound")
    with urllib.request.urlopen(f"http://{host}:{port}/metrics", timeout=timeout) as r:
        return r.read().decode()
