"""Elastic churn engine: shrink/grow the world mid-run, bounded and counted.

``tpunet.train.elastic`` turned peer death into a generation-based rebuild;
this module is the full churn engine the 100k+-GPU paper treats as co-equal
with throughput (ROADMAP item 4): membership can change EITHER way mid-run —
a dead rank shrinks the world, a join request grows it — and every rewire
re-derives the complete wiring-time state on the NEW shape rather than
assuming the seed shape. The re-derivation is structural, not patched: a
rewire builds a brand-new communicator, so the bootstrap re-runs host-id
exchange (``BuildHierTopo`` host grouping), hier/A2A subgroup construction,
dispatch-table resolution per (W, H, R), lane/WRR stripe maps at a fresh
epoch 1, and the codec/algo/QoS-class negotiation — the same code path a
fresh job at that shape runs, which is what the shape re-derivation tests
pin (tests/test_churn.py: a W=8->6 shrink's counters match a fresh W=6
wiring's).

**Recovery pipeline and its counters.** Every rewire runs four measured
phases, observed into ``tpunet_rewire_duration_us{phase=...}``:

  detect      last good collective -> failure classified (or join agreed)
  quiesce     old communicator finalized (tickets drained, engines closed)
  rendezvous  membership sealed + generation published (grace-window
              protocol shared with train.elastic — survivors and joiners
              are indistinguishable on purpose)
  rewire      new communicator wired at the new shape

``tpunet_churn_events_total{kind=kill|join|shrink|grow|readmit}`` counts
events; the ``tpunet_world_size`` gauge carries "the world came back". A
whole rewire exceeding ``TPUNET_REWIRE_TIMEOUT_MS`` raises the typed
``RewireTimeoutError`` (-9) — bounded recovery, never a hang.

**Zero corruption is checked, not asserted.** ``crc_check(params)`` after
EVERY rewire CRC32C-hashes the parameters and all-gathers the digest; any
cross-rank inequality raises ``WorldCorruptionError`` on every rank before
another step could launder the divergence into the trajectory.

**Determinism.** Churn is scripted through the chaos grammar
(``TPUNET_FAULT_SPEC="churn:at_step=4:rank=3:action=kill;..."``): ranks
poll ``churn_action(step, member_id)`` at step boundaries — a ``kill``
verdict means SIGKILL yourself NOW, a ``join`` verdict (polled by the
joiner/supervisor side against the job's checkpointed step) means request
entry — so the whole suite replays bit-identically in CI
(tests/churn_smoke.py). docs/DESIGN.md "Elastic churn".
"""

from __future__ import annotations

import os
import time
from pathlib import Path

import numpy as np

from tpunet import _native, distributed, telemetry, transport
from tpunet.train.elastic import (ExcludedFromMembership,
                                  generation_coordinator, is_comm_failure,
                                  membership_rendezvous, read_generation,
                                  write_generation)

__all__ = [
    "ElasticWorld", "WorldCorruptionError", "churn_action", "churn_pending",
    "parse_churn_script", "run",
]

_CHURN_ACTIONS = {0: None, 1: "kill", 2: "join"}


class WorldCorruptionError(RuntimeError):
    """The post-rewire CRC32C cross-rank parameter-equality check failed:
    at least one rank's parameters diverged across a churn event. Raised on
    EVERY rank (the digests are all-gathered) before another step could
    fold the divergence into the trajectory. A failed check means restore
    from the checkpoint, not continue."""


def churn_action(step: int, member_id: int) -> str | None:
    """One-shot poll of the armed churn script (TPUNET_FAULT_SPEC /
    tpunet_c_fault_inject): the first un-fired event with at_step <= step
    targeting `member_id` (or rank=*) fires; returns "kill", "join" or
    None. Fired latches survive the engine rebuilds the script causes."""
    lib = _native.load()
    code = int(lib.tpunet_c_churn_poll(int(step), int(member_id)))
    if code < 0:
        raise _native.NativeError(code, "churn_poll")
    return _CHURN_ACTIONS.get(code)


def churn_pending() -> int:
    """Armed churn events not yet fired (a finished scripted run must
    report 0 — the smoke lane's completeness gate)."""
    lib = _native.load()
    return int(lib.tpunet_c_churn_pending())


def parse_churn_script(spec: str) -> list[dict]:
    """Python mirror of the native churn-segment parser for supervisor-side
    scheduling (the native slot is poll-consuming; a harness that must know
    the join schedule up front parses the same spec non-destructively).
    Returns [{"at_step", "rank", "action"}, ...] for the churn segments;
    classic fault segments are ignored. Raises ValueError on a malformed
    churn segment, naming the offending token (the native parser rejects
    the same specs through tpunet_c_fault_inject)."""
    events: list[dict] = []
    for seg in (spec or "").split(";"):
        if not seg:
            continue
        clauses = seg.split(":")
        if clauses[0] != "churn":
            continue  # classic fault segment — not ours
        ev: dict = {"at_step": 0, "rank": -1, "action": None}
        for clause in clauses[1:]:
            key, eq, val = clause.partition("=")
            if not eq:
                raise ValueError(
                    f"churn spec: clause {clause!r} is not key=value")
            if key == "at_step":
                ev["at_step"] = int(val)
            elif key == "rank":
                ev["rank"] = -1 if val == "*" else int(val)
            elif key == "action":
                if val not in ("kill", "join"):
                    raise ValueError(
                        f"churn spec: unknown action {val!r} (want kill or "
                        f"join)")
                ev["action"] = val
            else:
                raise ValueError(f"churn spec: unknown key {key!r}")
        if ev["action"] is None:
            raise ValueError(f"churn spec: missing action= clause in {seg!r}")
        events.append(ev)
    return events


class ElasticWorld:
    """Membership lifecycle for one process: create/finalize/rebuild with
    per-phase timing, scripted churn polling, and the post-rewire CRC gate.

    ``member_id`` is this process's STABLE identity (it survives rank
    re-assignment across generations; a fresh job uses member_id == rank).
    The live communicator is always ``self.comm``; training code must read
    rank/world from it, never from the constructor arguments.

    Survivor loop shape (see ``run()`` for the driver)::

        world = ElasticWorld(coord, member_id, W, directory=dir)
        comm = world.create()
        for step in ...:
            if world.churn_action(step) == "kill":
                os.kill(os.getpid(), signal.SIGKILL)   # scripted death
            new = world.maybe_rewire(step)             # join requests
            if new is not None:
                comm = new; restore from checkpoint; world.crc_check(params)
            ... train step; checkpoint; world.step_ok() ...

    Joiner shape: ``comm = world.join()`` — deposits a join request, waits
    for the survivors to open the next rendezvous (generation bump), and
    enters it; training re-shards via the checkpoint contract.
    """

    def __init__(self, coordinator: str, member_id: int, world_size: int, *,
                 directory: str | Path, wire_dtype: str | None = None,
                 algo: str | None = None, traffic_class: str | None = None,
                 advertise_host: str | None = None,
                 grace_ms: int | None = None,
                 rewire_timeout_ms: int | None = None,
                 max_rewires: int = 16):
        from tpunet.config import Config

        cfg = Config.from_env()
        self.coordinator = coordinator
        self.member_id = int(member_id)
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.grace_s = (grace_ms if grace_ms is not None
                        else cfg.churn_grace_ms) / 1e3
        self.rewire_timeout_s = (rewire_timeout_ms if rewire_timeout_ms
                                 is not None else cfg.rewire_timeout_ms) / 1e3
        self.max_rewires = max_rewires
        self._kw = {"wire_dtype": wire_dtype, "algo": algo,
                    "traffic_class": traffic_class}
        base_host, base_port = coordinator.rsplit(":", 1)
        self.base_port = int(base_port)
        if advertise_host is None:
            # The run_elastic stance: no safe multi-host default exists —
            # the re-elected coordinator binds on a surviving member's host.
            if base_host in ("127.0.0.1", "localhost", "::1"):
                advertise_host = base_host
            else:
                raise ValueError(
                    "ElasticWorld on a non-loopback coordinator requires "
                    "advertise_host=<this machine's reachable address>")
        self.advertise_host = advertise_host
        self.generation = read_generation(self.directory)
        #: Stable member ids of the live world, in rank order.
        self.members: list[int] = list(range(world_size))
        self.comm = None
        self.stats = {"rewires": 0, "crc_checks": 0, "joins_honored": 0}
        self._last_ok = time.monotonic()

    # -- lifecycle ---------------------------------------------------------

    def create(self):
        """Initial wiring. Generation 0 wires the configured seed shape
        (member ids == ranks); a process (re)entering a job whose
        generation already advanced goes through membership rendezvous
        like everyone else."""
        if self.generation == 0:
            distributed.finalize()
            self.comm = distributed.initialize(
                generation_coordinator(self.coordinator, 0), self.member_id,
                len(self.members), **self._kw)
        else:
            self._rewire(kind=None, detect_s=0.0, generation=self.generation)
        telemetry.world_size(self.comm.world_size)
        self._last_ok = time.monotonic()
        return self.comm

    def step_ok(self) -> None:
        """Stamp 'the world was healthy here' — the detect-phase clock's
        zero point. Call once per successful step."""
        self._last_ok = time.monotonic()

    def churn_action(self, step: int) -> str | None:
        """This member's scripted churn verdict at `step` (one-shot)."""
        return churn_action(step, self.member_id)

    def close(self) -> None:
        distributed.finalize()
        self.comm = None

    # -- failure path (shrink) ---------------------------------------------

    def on_failure(self, exc: BaseException):
        """Classify a training-loop exception and rebuild the world around
        it. Non-comm failures re-raise unchanged (a loss blowup must not be
        laundered into a restart); comm failures trigger the measured
        rewire pipeline — the detect phase is the time since the last
        ``step_ok()``, i.e. how long the failure took to surface (bounded
        by keepalive/watchdog, which is the claim the histogram carries)."""
        if not is_comm_failure(exc):
            raise exc
        if self.stats["rewires"] >= self.max_rewires:
            raise exc
        detect_s = time.monotonic() - self._last_ok
        return self._rewire(kind=None, detect_s=detect_s)

    # -- grow path ----------------------------------------------------------

    def _pending_join_ids(self) -> list[int]:
        ids = []
        for p in self.directory.glob("join_*"):
            try:
                mid = int(p.name.split("_", 1)[1])
            except ValueError:
                continue
            if mid not in self.members:
                ids.append(mid)
        return sorted(ids)

    def maybe_rewire(self, step: int | None = None):
        """Step-boundary join check, agreed COLLECTIVELY: each rank reports
        whether it sees a pending join request and the max is all-reduced,
        so filesystem visibility skew cannot split the world (if any rank
        saw it, every rank rewires). Returns the new communicator when the
        world changed, else None. Costs one 4-byte allreduce per call —
        call it at step boundaries, not inside them."""
        del step  # membership decisions are step-agnostic; kept for symmetry
        if self.comm is None:
            raise RuntimeError("maybe_rewire() needs a live communicator")
        pending = self._pending_join_ids()
        flag = np.array([1 if pending else 0], np.int32)
        agreed = int(self.comm.all_reduce(flag, "max")[0])
        if not agreed:
            self._last_ok = time.monotonic()
            return None
        detect_s = time.monotonic() - self._last_ok
        return self._rewire(kind=None, detect_s=detect_s)

    def request_join(self) -> None:
        """Deposit this member's join request (atomic publish; idempotent).
        Survivors observe it at their next ``maybe_rewire()`` boundary."""
        path = self.directory / f"join_{self.member_id}"
        tmp = path.with_name(f".join_{self.member_id}.{os.getpid()}.tmp")
        tmp.write_text(self.advertise_host)
        os.replace(tmp, path)

    def join(self, timeout_s: float = 180.0):
        """Grow path for the NEW rank: read the published generation,
        request entry, wait for the survivors to open the next rendezvous
        (generation bump) and enter it. A joiner that misses a grace window
        (ExcludedFromMembership) keeps waiting — its request file persists,
        so the survivors open another window. Typed RewireTimeoutError when
        no rendezvous admits it within `timeout_s`."""
        self.request_join()
        t_req = time.monotonic()
        seen = read_generation(self.directory)
        deadline = t_req + timeout_s
        join_file = self.directory / f"join_{self.member_id}"
        while True:
            g = read_generation(self.directory)
            if g > seen:
                try:
                    comm = self._rewire(kind="join",
                                        detect_s=time.monotonic() - t_req,
                                        generation=g)
                    join_file.unlink(missing_ok=True)
                    return comm
                except ExcludedFromMembership:
                    seen = g  # missed the window; wait for the next bump
            if time.monotonic() > deadline:
                join_file.unlink(missing_ok=True)
                telemetry.flightrec_dump_verdict("rewire_deadline")
                raise _native.RewireTimeoutError(
                    _native.TPUNET_ERR_REWIRE,
                    f"join (no membership rendezvous admitted member "
                    f"{self.member_id} within {timeout_s}s)")
            time.sleep(0.05)

    # -- the rewire pipeline -------------------------------------------------

    def _check_deadline(self, deadline: float, phase: str) -> None:
        if time.monotonic() > deadline:
            # Terminal verdict: snapshot the flight recorder at the raise
            # site, like the native watchdog/CRC paths do (DESIGN.md §6c).
            telemetry.flightrec_dump_verdict("rewire_deadline")
            raise _native.RewireTimeoutError(
                _native.TPUNET_ERR_REWIRE,
                f"rewire ({phase} phase pushed recovery past "
                f"TPUNET_REWIRE_TIMEOUT_MS = {self.rewire_timeout_s * 1e3:.0f})")

    def _rewire(self, kind: str | None, detect_s: float,
                generation: int | None = None):
        """The measured rewire: quiesce -> rendezvous -> rewire, with the
        caller-supplied detect duration. `generation=None` bumps + publishes
        (survivor side); an explicit generation joins one already published
        (joiner side — it must not re-bump past the window it is
        chasing)."""
        deadline = time.monotonic() + self.rewire_timeout_s
        t0 = time.monotonic()
        distributed.finalize()
        self.comm = None
        t1 = time.monotonic()
        self._check_deadline(deadline, "quiesce")
        if generation is None:
            g = max(self.generation + 1, read_generation(self.directory))
            write_generation(self.directory, g)
        else:
            g = generation
        coordinator, rank, world, members = membership_rendezvous(
            self.directory, g, self.member_id, self.advertise_host,
            self.base_port, self.grace_s)
        t2 = time.monotonic()
        self._check_deadline(deadline, "rendezvous")
        old_members = set(self.members)
        comm = distributed.initialize(coordinator, rank, world, **self._kw)
        t3 = time.monotonic()
        self.comm = comm
        self.generation = g
        self.members = members
        self.stats["rewires"] += 1
        telemetry.rewire_observe("detect", int(detect_s * 1e6))
        telemetry.rewire_observe("quiesce", int((t1 - t0) * 1e6))
        telemetry.rewire_observe("rendezvous", int((t2 - t1) * 1e6))
        telemetry.rewire_observe("rewire", int((t3 - t2) * 1e6))
        joined = [m for m in members if m not in old_members]
        if kind is None:
            kind = "grow" if world > len(old_members) else "shrink"
        telemetry.churn_event(kind)
        if kind != "join":  # survivors additionally count each admit
            for _ in joined:
                telemetry.churn_event("join")
                self.stats["joins_honored"] += 1
        telemetry.world_size(world)
        self._check_deadline(deadline, "rewire")
        self._last_ok = time.monotonic()
        return comm

    # -- integrity -----------------------------------------------------------

    def crc_check(self, arrays) -> int:
        """CRC32C cross-rank parameter-equality gate — run after EVERY
        rewire. Hashes `arrays` (one ndarray or an iterable of them,
        chained) and all-gathers the digest; any inequality raises
        WorldCorruptionError on every rank. Returns the agreed digest."""
        if self.comm is None:
            raise RuntimeError("crc_check() needs a live communicator")
        if isinstance(arrays, np.ndarray):
            arrays = [arrays]
        crc = 0
        for a in arrays:
            crc = transport.crc32c(np.ascontiguousarray(a).tobytes(),
                                   seed=crc)
        digests = self.comm.all_gather(np.array([crc], np.uint32)).ravel()
        self.stats["crc_checks"] += 1
        if len(set(int(d) for d in digests)) != 1:
            raise WorldCorruptionError(
                f"cross-rank parameter CRC mismatch after rewire at "
                f"generation {self.generation}: "
                f"{[hex(int(d)) for d in digests]} — restore from the "
                f"checkpoint, do not continue")
        return crc


def run(train_once, *, coordinator: str, member_id: int, world_size: int,
        directory: str | Path, joiner: bool = False, **world_kwargs):
    """Drive ``train_once(world, comm)`` under the churn engine.

    ``train_once`` owns the step loop (checkpoint cadence, churn polling,
    ``maybe_rewire`` at step boundaries, ``crc_check`` after rewires) and
    is RE-ENTERED from the latest checkpoint after a failure-triggered
    rewire; grow rewires surface inside it via ``maybe_rewire``'s return
    value, so it continues in place. ``joiner=True`` enters through the
    grow path (``join()``) instead of seed wiring. Non-comm exceptions and
    an exhausted rewire budget propagate."""
    world = ElasticWorld(coordinator, member_id, world_size,
                         directory=directory, **world_kwargs)
    comm = world.join() if joiner else world.create()
    while True:
        try:
            return train_once(world, comm)
        except Exception as exc:  # noqa: BLE001 — classified by on_failure
            comm = world.on_failure(exc)
