"""tpunet configuration — the complete env-var inventory in one place.

The reference read its env vars ad hoc all over the tree (SURVEY §5 config
inventory; reference files cited per flag below). tpunet centralizes them.
``TPUNET_*`` names are canonical; the reference-compatible ``BAGUA_NET_*`` /
``NCCL_*`` spellings are honored as fallbacks by the native layer where
noted.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass


def _env_int(name: str, fallback: int) -> int:
    v = os.environ.get(name, "")
    try:
        n = int(v)
        return n if n >= 0 else fallback
    except ValueError:
        return fallback


def _env_int_checked(names: tuple[str, ...], fallback: int, minimum: int,
                     what: str, maximum: int | None = None) -> int:
    """Read the first set env var in `names`; a NUMERIC value below `minimum`
    (or above `maximum`, when given) raises ValueError naming the offending
    var.

    The silent-fallback behavior of _env_int let ``TPUNET_NSTREAMS=0`` or a
    negative keepalive window flow into the native layer (which clamps or
    ignores them) without the operator ever learning their config was
    nonsense. Out-of-range numbers now fail loudly at Config.from_env();
    non-numeric garbage still falls back, matching the native GetEnvU64
    reader so the two layers never disagree on the effective value."""
    for name in names:
        v = os.environ.get(name)
        if v is None or v == "":
            continue
        try:
            n = int(v)
        except ValueError:
            return fallback  # native GetEnvU64 semantics: garbage -> default
        if n < minimum:
            raise ValueError(
                f"{name}={v} is invalid: {what} must be >= {minimum}"
            )
        if maximum is not None and n > maximum:
            raise ValueError(
                f"{name}={v} is invalid: {what} must be <= {maximum}"
            )
        return n
    return fallback


def _env_choice(name: str, fallback: str, choices: tuple[str, ...],
                what: str) -> str:
    """Read an enumerated env var; any value outside `choices` raises
    ValueError naming the var. Unlike the numeric readers there is no
    silent-garbage fallback: a typo'd codec name ("bf-16") silently running
    uncompressed would fake the perf it was set to buy, and the native layer
    rejects the same values loudly (tpunet_comm_create_ex)."""
    v = os.environ.get(name)
    if v is None or v == "":
        return fallback
    if v not in choices:
        raise ValueError(
            f"{name}={v} is invalid: {what} must be one of {', '.join(choices)}"
        )
    return v


def _env_float_checked(name: str, fallback: float, minimum: float,
                       what: str) -> float:
    """Read a float env var; a NUMERIC value below `minimum` raises
    ValueError naming the var; non-numeric garbage falls back (the
    GetEnvU64 stance, matching the numeric readers above)."""
    v = os.environ.get(name)
    if v is None or v == "":
        return fallback
    try:
        f = float(v)
    except ValueError:
        return fallback
    if f < minimum:
        raise ValueError(f"{name}={v} is invalid: {what} must be >= {minimum}")
    return f


_QOS_CLASSES = ("latency", "bulk", "control")


def _parse_qos_size(val: str) -> int | None:
    """'123' / '64K' / '8M' / '1G' -> bytes (the native ParseSizeSuffix
    grammar); None on garbage."""
    mult = 1
    if val and val[-1] in "kKmMgG":
        mult = {"k": 1 << 10, "m": 1 << 20, "g": 1 << 30}[val[-1].lower()]
        val = val[:-1]
    if not val.isdigit():
        return None
    return int(val) * mult


def _env_qos_spec(name: str, keys: tuple[str, ...], what: str,
                  minimum: int = 0) -> str:
    """Validate a comma-separated key=value QoS spec env var against the
    native grammar (qos.cc): keys restricted to `keys`, values sized ints
    with optional K/M/G suffix, each >= `minimum`. Malformed specs raise
    ValueError naming the var — the native side only WARNS and keeps its
    defaults, so this is the loud gate (the TPUNET_DISPATCH_TABLE stance).
    Returns the raw string (the native layer re-parses it)."""
    v = os.environ.get(name)
    if v is None or v == "":
        return ""
    for tok in v.split(","):
        if not tok:
            continue
        key, eq, val = tok.partition("=")
        if not eq:
            raise ValueError(
                f"{name}={v} is invalid: token {tok!r} is not key=value")
        if key not in keys:
            raise ValueError(
                f"{name}={v} is invalid: unknown key {key!r} ({what} keys "
                f"are {', '.join(keys)})")
        n = _parse_qos_size(val)
        if n is None or n < minimum:
            raise ValueError(
                f"{name}={v} is invalid: value {val!r} for {key} must be an "
                f"integer >= {minimum} (optional K/M/G suffix)")
    return v


def _env_lanes(name: str) -> str:
    """Validate a TPUNET_LANES spec against the native grammar (wire.cc
    ParseLaneSpec): comma-separated lanes of colon-separated key=value
    clauses, keys ``addr`` (IPv4/IPv6 literal) and ``w`` (1..255), either
    optional per lane. Malformed specs raise ValueError naming the var —
    the native side only WARNS and runs single-path, so this is the loud
    gate (the QoS-spec validator stance). Returns the raw string (the
    native layer re-parses it)."""
    v = os.environ.get(name)
    if v is None or v == "":
        return ""
    def _clauses(lane: str) -> list[str]:
        # ':' separates clauses only at bracket depth 0 — IPv6 literals ride
        # in brackets ("addr=[fe80::1]:w=2"), matching the native tokenizer.
        out, cur, depth = [], "", 0
        for ch in lane:
            if ch == "[":
                depth += 1
            elif ch == "]":
                depth -= 1
            if ch == ":" and depth == 0:
                out.append(cur)
                cur = ""
            else:
                cur += ch
        out.append(cur)
        return out

    for lane in v.split(","):
        if not lane:
            raise ValueError(f"{name}={v} is invalid: empty lane entry")
        for clause in _clauses(lane):
            key, eq, val = clause.partition("=")
            if not eq:
                raise ValueError(
                    f"{name}={v} is invalid: clause {clause!r} is not key=value")
            if key == "addr":
                import ipaddress
                try:
                    ipaddress.ip_address(val.strip("[]"))
                except ValueError as e:
                    raise ValueError(
                        f"{name}={v} is invalid: {val!r} is not an IPv4/IPv6 "
                        f"address") from e
            elif key == "w":
                if not val.isdigit() or not 1 <= int(val) <= 255:
                    raise ValueError(
                        f"{name}={v} is invalid: weight {val!r} must be 1..255")
            else:
                raise ValueError(
                    f"{name}={v} is invalid: unknown key {key!r} (lane keys "
                    f"are addr, w)")
    if len(v.split(",")) > 256:
        raise ValueError(f"{name}={v} is invalid: more than 256 lanes")
    return v


def _env_dispatch_table(name: str) -> str:
    """Read a dispatch-table path env var; when set, the file must exist and
    parse as a JSON object with an "entries" list, else ValueError naming
    the var. The native loader enforces the full schema (and the cross-rank
    CRC handshake) at communicator creation; this pre-check catches a typo'd
    path at Config.from_env() instead of deep inside wiring."""
    v = os.environ.get(name)
    if v is None or v == "":
        return ""
    try:
        with open(v, encoding="utf-8") as f:
            table = json.load(f)
    except OSError as e:
        raise ValueError(f"{name}={v} is invalid: cannot read the dispatch "
                         f"table ({e})") from e
    except ValueError as e:
        raise ValueError(f"{name}={v} is invalid: dispatch table is not "
                         f"valid JSON ({e})") from e
    if not isinstance(table, dict) or not isinstance(table.get("entries"), list):
        raise ValueError(f"{name}={v} is invalid: dispatch table must be a "
                         f"JSON object with an \"entries\" list")
    return v


@dataclass(frozen=True)
class Config:
    """Snapshot of tpunet env configuration at construction time."""

    # Engine selection (reference: src/lib.rs:20-29 BAGUA_NET_IMPLEMENT).
    implement: str = "BASIC"
    # Parallel TCP data streams per comm (reference default 2,
    # nthread_per_socket_backend.rs:228-231).
    nstreams: int = 2
    # Minimum chunk size in bytes (reference default 1 MiB, nthread:232-235).
    min_chunksize: int = 1 << 20
    # Busy-poll IO instead of blocking IO (reference's only mode).
    spin: bool = False
    # NIC selection, NCCL syntax: "^a,b" exclude, "=a,b" exact, "a,b" prefix
    # (reference: utils.rs:37-49).
    socket_ifname: str = "^docker,lo"
    # AF_INET / AF_INET6 restriction (reference: utils.rs:33-36).
    socket_family: str = ""
    # Bootstrap coordinator "host:port" for collectives rendezvous (the role
    # NCCL's OOB bootstrap played for the reference).
    coordinator: str = "127.0.0.1:29500"
    # This process's rank and the world size (reference read RANK for
    # telemetry gating only, nthread:104-107; here they drive the group).
    rank: int = 0
    world_size: int = 1
    # Observability (reference: BAGUA_NET_JAEGER_ADDRESS nthread:113,
    # BAGUA_NET_PROMETHEUS_ADDRESS nthread:184-185). Empty = disabled.
    trace_dir: str = ""
    # Flight-recorder dump directory override (empty = TPUNET_TRACE_DIR,
    # then the CWD). Dump routing ONLY — unlike trace_dir it does not enable
    # span tracing, so test harnesses point verdict dumps at a tmp dir
    # without changing telemetry behavior.
    flightrec_dir: str = ""
    metrics_addr: str = ""
    # On-demand /metrics scrape listener port (0 = disabled). Each rank needs
    # its own port; first binder wins on a shared one.
    metrics_port: int = 0
    # SO_SNDBUF/SO_RCVBUF override in bytes; 0 = kernel autotuning.
    socket_bufsize: int = 0
    # Collectives pipeline granularity: ring steps stream their slice in
    # chunks this size so reduction overlaps transfer.
    ring_chunksize: int = 8 << 20
    # Total fork-join reduce shards, caller included (0 = auto: min(4,
    # cores/2)); the native pool clamps at 16.
    reduce_threads: int = 0
    # TCP keepalive dead-peer detection: first probe after idle_s (0 =
    # disabled), then every intvl_s, dead after cnt misses.
    keepalive_idle_s: int = 30
    keepalive_intvl_s: int = 10
    keepalive_cnt: int = 3
    # Transient connect failures retry with exponential backoff inside this
    # window (ms; 0 = fail fast). Covers a peer restarting its listener.
    connect_retry_ms: int = 10_000
    # Independent ring channels for nonblocking collectives: ticket t runs on
    # channel (t-1) % async_channels, so consecutive gradient buckets overlap
    # on the wire. Must agree across ranks.
    async_channels: int = 2
    # AllToAll algorithm: "pairwise" (direct per-peer comms — the
    # minimum wire bytes, measured (W-1)/W x S per rank) or "ring"
    # (store-and-forward relay: no extra comms, but each block travels
    # multiple hops — 2x the bytes at W=4).
    a2a: str = "pairwise"
    # AllToAll schedule override superseding the legacy TPUNET_A2A switch:
    # "auto" (pairwise, upgraded to the two-stage hierarchical transpose on
    # a profitable >= 2-host uniform topology), "pairwise", "ring" (relay),
    # or "hier" (pin the two-stage transpose; degrades to pairwise on a
    # flat topology). Negotiated at communicator wiring like TPUNET_ALGO —
    # half a world on the mesh and half on the transpose deadlocks, so a
    # disagreement fails every rank typed. docs/DESIGN.md "Hierarchical
    # AllToAll".
    a2a_algo: str = "auto"
    # Worlds larger than this fall back to the ring relay rather than paying
    # 2*(W-1) comm bundles of fds/threads per rank for the pairwise mesh.
    a2a_mesh_max_world: int = 32
    # BASIC-engine caller-thread fast paths (1 = on): inline isend dispatch
    # on an idle comm, and lazily-parked irecv whose wait() runs inline.
    inline_send: bool = True
    lazy_recv: bool = True
    # EPOLL engine: event-loop threads per engine, and the caller-thread
    # inline dispatch + immediate-IO fast path (0 = pure event loop).
    epoll_threads: int = 2
    epoll_inline: bool = True
    # ---- Failure model (docs/DESIGN.md "Failure model") ------------------
    # Per-chunk CRC32C trailers on data streams; negotiated in the connect
    # preamble (the sender's setting wins on the receiving side). Detected
    # corruption fails the REQUEST with a typed error — not a disconnect.
    crc: bool = False
    # Progress watchdog: a blocking wait whose request moves zero bytes for
    # this many ms raises a typed timeout (0 = off). Catches live-but-stuck
    # peers that TCP keepalive never flags; elastic recovery treats the
    # timeout like a dead peer.
    progress_timeout_ms: int = 0
    # Deterministic fault to arm at engine creation (chaos testing), e.g.
    # "stream=1:after_bytes=1M:action=close". Empty = none.
    fault_spec: str = ""
    # ---- Observability sampling/push cadence (docs/DESIGN.md §6c) --------
    # TCP_INFO sample period per stream slot (0 = sampler off).
    tcpinfo_interval_ms: int = 100
    # Jain's-fairness byte-delta window.
    fairness_window_ms: int = 1000
    # Straggler threshold k over the median smoothed RTT (0 = detector off),
    # and the RTT noise floor below which nothing counts as straggling.
    straggler_factor: int = 3
    straggler_min_rtt_us: int = 1000
    # Pushgateway PUT period when TPUNET_METRICS_ADDR is set.
    metrics_interval_ms: int = 1000
    # Flight-recorder ring capacity in events (docs/DESIGN.md §6c), rounded
    # up to a power of two by the native layer (0 = recorder off entirely).
    flightrec_events: int = 16384
    # Counter-timeseries sample period (ms): a background sampler appends
    # full metric snapshots as JSONL to TPUNET_TRACE_DIR (0 = sampler off).
    ts_interval_ms: int = 0
    # ---- Wire/bootstrap deadlines (docs/DESIGN.md §1) --------------------
    # Whole-preamble read deadline on accept (slow-loris defense); partial
    # bundles expire after 2x this.
    handshake_timeout_ms: int = 10_000
    # Rendezvous connect/collect deadline at Communicator creation.
    bootstrap_timeout_ms: int = 120_000
    # ---- Debug / dispatch toggles ----------------------------------------
    # Per-engine stderr event log (TPUNET_DEBUG=1).
    debug: bool = False
    # Runtime SIMD dispatch for the reduction kernels (0 forces scalar —
    # bisection aid; the two paths are bitwise identical).
    reduce_simd: bool = True
    # XLA custom-call collectives (0 falls back to the io_callback bridge).
    ffi_collectives: bool = True
    # Collective wire compression codec for f32 payloads ("f32" = off,
    # "bf16" = RNE truncation halves ring DCN bytes, "int8" = block-scaled
    # quarters them; accumulate stays f32 either way). Negotiated at
    # communicator wiring — all ranks must agree or creation fails with
    # CodecMismatchError. docs/DESIGN.md "Compressed collectives".
    wire_dtype: str = "f32"
    # Collective schedule ("auto" = per-(collective, size, world) selection;
    # "ring"/"rhd"/"tree"/"hier" pin one schedule — "hier" is the two-level
    # intra-host + inter-host AllReduce and needs a hierarchical topology,
    # else it runs the ring). Negotiated at communicator
    # wiring like the codec — ranks on different schedules would deadlock,
    # so a disagreement fails creation on every rank. docs/DESIGN.md
    # "Schedules & algorithm selection".
    algo: str = "auto"
    # Path to the dispatch-table JSON written by `busbw_sweep
    # --emit-dispatch` (empty = built-in thresholds). Loaded per
    # communicator; the file's CRC rides the wiring handshake so every rank
    # must see identical contents. A missing or malformed file is a loud
    # config error here AND at communicator creation.
    dispatch_table: str = ""
    # ---- Disaggregated serving tier (docs/DESIGN.md "Serving tier") ------
    # KV-block wire codec for prefill->decode shipping ("int8" block-scaled
    # by default — the EQuARX-bound codec; "f32" makes the wire exact and
    # greedy outputs bitwise-equal to single-host serving). Negotiated at
    # tier wiring: a mismatch raises KVCodecMismatchError on every rank.
    kv_wire_dtype: str = "int8"
    # Decode-rank placement policy at the router ("least_loaded" picks the
    # rank with the most free slots; "round_robin" cycles).
    router_policy: str = "least_loaded"
    # Pin this process's serving-tier role ("" = unpinned). Wiring as the
    # OTHER role then fails loudly — catches copy-pasted launch commands.
    serve_role: str = ""
    # ---- Lane striping (docs/DESIGN.md "Lanes & adaptive striping") ------
    # Multi-path lane spec, "addr=10.0.0.1:w=4,addr=10.0.1.1:w=1": one lane
    # == one data stream (the spec's lane count overrides TPUNET_NSTREAMS),
    # addr pins the lane's local bind (egress path; omit for the default
    # route), w its base stripe weight. Empty = single-path uniform striping,
    # byte-identical on the wire to pre-lane builds.
    lanes: str = ""
    # Sender-side adaptive re-striping (lane mode only): per-lane service-
    # rate EWMAs + the TCP_INFO straggler detector drive weight demotion
    # (floor 1) and recovery, published as epoch-stamped ctrl frames. 0
    # pins the configured base weights (the uniform-striping control).
    lane_adapt: bool = True
    # Adaptation tick cadence in ms.
    lane_adapt_ms: int = 100
    # ---- Intra-host shared memory (docs/DESIGN.md "Intra-host shared
    # memory") -------------------------------------------------------------
    # Front the TCP engine with the SHM engine: same-host peers (HostId()
    # equality, verified in the segment handshake) move payloads through
    # mmap'd per-pair ring segments; cross-host peers pass through to TCP
    # untouched. Must be set identically on every rank (like the engine
    # choice itself — a mixed config fails the handshake loudly).
    shm: bool = False
    # Per-pair ring segment capacity in bytes (clamped to [64K, 1G] by the
    # native layer). A chunk plus its CRC trailer must fit in half of it.
    shm_ring_bytes: int = 8 << 20
    # Host-identity override (the fake-host knob): any string, hashed into
    # the host id the SHM handshake and the hierarchical schedule's host
    # grouping compare. Unset = boot-id/hostname hash — every process on a
    # physical host agrees. Setting DIFFERENT values on same-box ranks
    # splits them into testable fake "hosts" (forced TCP between them).
    host_id: str = ""
    # ---- Transport QoS (docs/DESIGN.md "Transport QoS") ------------------
    # Default traffic class for every comm this process connects (and the
    # class a Communicator negotiates when traffic_class= is not passed).
    # "latency" | "bulk" | "control"; carried in the connect preamble and
    # the collective bootstrap handshake (mismatch fails every rank typed).
    traffic_class: str = "bulk"
    # DRR weights for the wire-credit scheduler, "latency=8,bulk=1"
    # (control is strict-priority; empty = built-in 8:1). One weight point
    # buys 64KiB of wire credit per scheduling turn.
    qos_weights: str = ""
    # Per-class in-flight budgets, "latency=64M,bulk=256M,control=0,wire=4M"
    # (sizes take K/M/G). latency/bulk/control bound ADMISSION (posted-send
    # bytes; over-budget isends fail typed QosAdmissionError, -8; 0 =
    # unlimited). wire= sets the shared WIRE WINDOW that arms the DRR chunk
    # scheduler (0 = gate off, the default — dispatch is then unchanged).
    qos_inflight_bytes: str = ""
    # ---- Elastic churn (docs/DESIGN.md "Elastic churn") ------------------
    # Membership grace window for churn rendezvous (ms): how long the
    # sealing leader waits for survivors/joiners to deposit member files
    # before sealing the new world. Short = fast recovery but a slow rank
    # may be excluded; long = inclusive but recovery pays the window.
    churn_grace_ms: int = 10_000
    # Whole-rewire deadline (ms): a mid-run membership rewire (quiesce +
    # rendezvous + re-wiring at the new shape) exceeding it raises the
    # typed RewireTimeoutError (-9) — bounded recovery, never a hang.
    rewire_timeout_ms: int = 120_000
    # Serving-tier re-admission probe cadence (ms): how often the router
    # polls its wiring port for recovered decode hosts once
    # enable_readmission() armed it.
    readmit_probe_ms: int = 500
    # ---- Live weight updates (docs/DESIGN.md "Live weight updates") ------
    # Whole-swap deadline (ms): a weight publication (announce + broadcast
    # + verify + flip) exceeding it aborts typed (WeightSwapError, -10) on
    # every rank — the old version keeps serving, never a hang.
    swap_timeout_ms: int = 30_000
    # Broadcast chunk size (bytes of bf16 wire per tree broadcast): small
    # enough that the decode serve loop's per-iteration swap work stays
    # bounded (the latency p99 protection), large enough to amortize the
    # per-collective rounds.
    swap_chunk_bytes: int = 1 << 20
    # QoS traffic class the publication broadcast rides ("bulk" by default:
    # gigabytes of weights must not queue ahead of latency-class decode/KV
    # traffic in the DRR scheduler).
    publish_class: str = "bulk"
    # ---- MoE / pipeline workloads (docs/DESIGN.md "Workloads") -----------
    # Default Zipf skew exponent for the MoE workload's expert routing
    # (tpunet.workloads.moe): 0 = uniform expert popularity, larger = more
    # skewed (the 100k+-GPU paper's hot-expert shape). Must be >= 0.
    moe_skew: float = 1.0

    @staticmethod
    def from_env() -> "Config":
        """Snapshot env config, validating range-sensitive knobs: zero/negative
        nstreams, non-positive min_chunksize, negative keepalive/retry/
        watchdog windows, an out-of-range metrics port (0-65535), and a
        negative reduce-thread count raise ValueError naming the offending
        env var instead of flowing into the native layer unchecked."""
        env = os.environ
        return Config(
            implement=env.get("TPUNET_IMPLEMENT", env.get("BAGUA_NET_IMPLEMENT", "BASIC")),
            nstreams=_env_int_checked(
                ("TPUNET_NSTREAMS", "BAGUA_NET_NSTREAMS"), 2, 1, "data-stream count"
            ),
            min_chunksize=_env_int_checked(
                ("TPUNET_MIN_CHUNKSIZE", "BAGUA_NET_MIN_CHUNKSIZE"), 1 << 20, 1,
                "minimum chunk size",
            ),
            # GetEnvU64 semantics like the native reader: non-numeric -> 0.
            spin=_env_int("TPUNET_SPIN", 0) != 0,
            socket_ifname=env.get(
                "TPUNET_SOCKET_IFNAME", env.get("NCCL_SOCKET_IFNAME", "^docker,lo")
            ),
            socket_family=env.get("TPUNET_SOCKET_FAMILY", env.get("NCCL_SOCKET_FAMILY", "")),
            coordinator=env.get("TPUNET_COORDINATOR", "127.0.0.1:29500"),
            rank=_env_int("TPUNET_RANK", _env_int("RANK", 0)),
            world_size=_env_int("TPUNET_WORLD_SIZE", _env_int("WORLD_SIZE", 1)),
            trace_dir=env.get("TPUNET_TRACE_DIR", ""),
            flightrec_dir=env.get("TPUNET_FLIGHTREC_DIR", ""),
            metrics_addr=env.get("TPUNET_METRICS_ADDR", os.environ.get("TPUNET_PROMETHEUS_ADDRESS", "")),
            # The native listener ignores ports >= 65536 silently; the config
            # layer names the bad var instead (PR-1 validator style).
            metrics_port=_env_int_checked(
                ("TPUNET_METRICS_PORT",), 0, 0, "metrics scrape port",
                maximum=65535,
            ),
            socket_bufsize=_env_int("TPUNET_SOCKET_BUFSIZE", 0),
            # The native reader treats 0 as "use the default" silently; the
            # config layer names the bad var instead (PR-1 validator style).
            ring_chunksize=_env_int_checked(
                ("TPUNET_RING_CHUNKSIZE",), 8 << 20, 1, "ring pipeline chunk size"
            ),
            reduce_threads=_env_int_checked(
                ("TPUNET_REDUCE_THREADS",), 0, 0, "reduce thread count"
            ),
            keepalive_idle_s=_env_int_checked(
                ("TPUNET_KEEPALIVE_IDLE_S",), 30, 0, "keepalive idle window"
            ),
            keepalive_intvl_s=_env_int_checked(
                ("TPUNET_KEEPALIVE_INTVL_S",), 10, 0, "keepalive probe interval"
            ),
            keepalive_cnt=_env_int_checked(
                ("TPUNET_KEEPALIVE_CNT",), 3, 0, "keepalive probe count"
            ),
            connect_retry_ms=_env_int_checked(
                ("TPUNET_CONNECT_RETRY_MS",), 10_000, 0, "connect retry window"
            ),
            # Native clamps to [1, 8]; numeric 0 is a config error here.
            async_channels=_env_int_checked(
                ("TPUNET_ASYNC_CHANNELS",), 2, 1, "async ring channel count", maximum=8
            ),
            a2a=env.get("TPUNET_A2A", "pairwise"),
            a2a_algo=_env_choice(
                "TPUNET_A2A_ALGO", "auto",
                ("auto", "pairwise", "ring", "hier", "hier_a2a"),
                "AllToAll schedule",
            ),
            a2a_mesh_max_world=_env_int("TPUNET_A2A_MESH_MAX_WORLD", 32),
            # Parsed to match the native consumer (GetEnvU64, default 1):
            # only a numeric 0 disables; "false"/"" fall back to on.
            inline_send=_env_int("TPUNET_INLINE_SEND", 1) != 0,
            lazy_recv=_env_int("TPUNET_LAZY_RECV", 1) != 0,
            # The native engine clamps 0 -> 1 loop thread; mirror it so
            # the inventory reports the thread count that actually runs.
            epoll_threads=max(1, _env_int("TPUNET_EPOLL_THREADS", 2)),
            epoll_inline=_env_int("TPUNET_EPOLL_INLINE", 1) != 0,
            crc=_env_int("TPUNET_CRC", 0) != 0,
            progress_timeout_ms=_env_int_checked(
                ("TPUNET_PROGRESS_TIMEOUT_MS",), 0, 0, "progress watchdog window"
            ),
            fault_spec=env.get("TPUNET_FAULT_SPEC", ""),
            # Observability cadence knobs (0 legitimately disables the
            # sampler/detector; only negatives are config errors).
            tcpinfo_interval_ms=_env_int_checked(
                ("TPUNET_TCPINFO_INTERVAL_MS",), 100, 0, "TCP_INFO sample period"
            ),
            fairness_window_ms=_env_int_checked(
                ("TPUNET_FAIRNESS_WINDOW_MS",), 1000, 0, "fairness byte window"
            ),
            straggler_factor=_env_int_checked(
                ("TPUNET_STRAGGLER_FACTOR",), 3, 0, "straggler threshold factor"
            ),
            straggler_min_rtt_us=_env_int_checked(
                ("TPUNET_STRAGGLER_MIN_RTT_US",), 1000, 0, "straggler RTT floor"
            ),
            metrics_interval_ms=_env_int_checked(
                ("TPUNET_METRICS_INTERVAL_MS",), 1000, 1, "metrics push period"
            ),
            # 0 legitimately disables the recorder / timeseries sampler;
            # only negatives are config errors.
            flightrec_events=_env_int_checked(
                ("TPUNET_FLIGHTREC_EVENTS",), 16384, 0,
                "flight-recorder ring capacity",
            ),
            ts_interval_ms=_env_int_checked(
                ("TPUNET_TS_INTERVAL_MS",), 0, 0,
                "counter-timeseries sample period",
            ),
            # Deadlines: 0 would make every handshake/bootstrap time out
            # instantly — loud config error, not a silent wedge.
            handshake_timeout_ms=_env_int_checked(
                ("TPUNET_HANDSHAKE_TIMEOUT_MS",), 10_000, 1, "handshake deadline"
            ),
            bootstrap_timeout_ms=_env_int_checked(
                ("TPUNET_BOOTSTRAP_TIMEOUT_MS",), 120_000, 1, "bootstrap deadline"
            ),
            debug=_env_int("TPUNET_DEBUG", 0) != 0,
            # GetEnvU64 semantics (default 1): only a numeric 0 disables.
            reduce_simd=_env_int("TPUNET_REDUCE_SIMD", 1) != 0,
            # Matches the interop.py consumer: enabled iff the var is unset
            # or exactly "1".
            ffi_collectives=env.get("TPUNET_FFI_COLLECTIVES", "1") == "1",
            wire_dtype=_env_choice(
                "TPUNET_WIRE_DTYPE", "f32", ("f32", "bf16", "int8"),
                "collective wire codec",
            ),
            algo=_env_choice(
                "TPUNET_ALGO", "auto", ("auto", "ring", "rhd", "tree", "hier"),
                "collective schedule",
            ),
            dispatch_table=_env_dispatch_table("TPUNET_DISPATCH_TABLE"),
            kv_wire_dtype=_env_choice(
                "TPUNET_KV_WIRE_DTYPE", "int8", ("f32", "bf16", "int8"),
                "KV-block wire codec",
            ),
            router_policy=_env_choice(
                "TPUNET_ROUTER_POLICY", "least_loaded",
                ("least_loaded", "round_robin"), "router placement policy",
            ),
            serve_role=_env_choice(
                "TPUNET_SERVE_ROLE", "", ("", "frontend", "decode"),
                "serving-tier role",
            ),
            # GetEnvU64 semantics (default 0): only a numeric nonzero enables.
            shm=_env_int("TPUNET_SHM", 0) != 0,
            shm_ring_bytes=_env_int_checked(
                ("TPUNET_SHM_RING_BYTES",), 8 << 20, 64 << 10,
                "shared-memory ring size", maximum=1 << 30,
            ),
            host_id=env.get("TPUNET_HOST_ID", ""),
            lanes=_env_lanes("TPUNET_LANES"),
            # GetEnvU64 semantics (default 1): only a numeric 0 disables.
            lane_adapt=_env_int("TPUNET_LANE_ADAPT", 1) != 0,
            lane_adapt_ms=_env_int_checked(
                ("TPUNET_LANE_ADAPT_MS",), 100, 1, "lane adaptation tick"
            ),
            traffic_class=_env_choice(
                "TPUNET_TRAFFIC_CLASS", "bulk", _QOS_CLASSES,
                "QoS traffic class",
            ),
            # Weights must be >= 1 (a zero-weight class would never earn
            # wire credit); budgets accept 0 = unlimited / gate off.
            qos_weights=_env_qos_spec(
                "TPUNET_QOS_WEIGHTS", _QOS_CLASSES, "DRR weight", minimum=1,
            ),
            qos_inflight_bytes=_env_qos_spec(
                "TPUNET_QOS_INFLIGHT_BYTES", _QOS_CLASSES + ("wire",),
                "in-flight budget",
            ),
            moe_skew=_env_float_checked(
                "TPUNET_MOE_SKEW", 1.0, 0.0, "MoE Zipf skew exponent",
            ),
            # Churn deadlines/cadences: 0 would seal empty memberships,
            # expire every rewire instantly, or spin the readmission probe
            # — loud config errors, not silent wedges (the PR-1 stance).
            churn_grace_ms=_env_int_checked(
                ("TPUNET_CHURN_GRACE_MS",), 10_000, 1,
                "churn membership grace window",
            ),
            rewire_timeout_ms=_env_int_checked(
                ("TPUNET_REWIRE_TIMEOUT_MS",), 120_000, 1, "rewire deadline"
            ),
            readmit_probe_ms=_env_int_checked(
                ("TPUNET_READMIT_PROBE_MS",), 500, 1,
                "re-admission probe interval",
            ),
            # Swap knobs: a zero deadline would abort every publication on
            # arrival and a zero chunk would never move a byte — loud
            # config errors, not silent wedges.
            swap_timeout_ms=_env_int_checked(
                ("TPUNET_SWAP_TIMEOUT_MS",), 30_000, 1, "weight-swap deadline"
            ),
            swap_chunk_bytes=_env_int_checked(
                ("TPUNET_SWAP_CHUNK_BYTES",), 1 << 20, 4 << 10,
                "weight-broadcast chunk size", maximum=1 << 30,
            ),
            publish_class=_env_choice(
                "TPUNET_PUBLISH_CLASS", "bulk", _QOS_CLASSES,
                "weight-publication QoS class",
            ),
        )
