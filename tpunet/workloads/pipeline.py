"""Pipeline-parallel stage driver: directed microbatch chains with tickets.

W ranks form a linear pipeline (stage k feeds stage k+1 — no wraparound).
Each adjacent pair gets a dedicated full-duplex-enough P2P link over the
transport (``tpunet.transport.Net``): every stage listens, the 64-byte
rendezvous handles travel over the group's Communicator with ONE
``all_gather``, then stage k connects forward to stage k+1 — connect-all-
then-accept-all, the same non-deadlocking wiring order the collectives use.
The links inherit the whole transport stack: striping/lanes, CRC, QoS
class, fault injection, telemetry.

Ordering rides tickets: ``isend``/``irecv`` return a :class:`Ticket`, and
``after=`` pins a new operation behind earlier tickets — the workload-tier
analogue of the FFI ``after=`` operand threading (tpunet.interop). A
microbatch chain like

    t_r = stage.irecv(buf)                      # from stage k-1
    y   = f(buf_after(t_r))
    t_s = stage.isend(y, after=(t_r,))          # to stage k+1

never reorders a send ahead of the recv/compute it depends on, while
independent microbatches keep overlapping on the wire.

Failure model: a dead pipeline neighbor surfaces as a typed NativeError
from the pending recv/send (dead-peer EOF, or the progress watchdog under
TPUNET_PROGRESS_TIMEOUT_MS) — never a hang; the chaos suite pins it
(tests/test_chaos.py mid-pipeline rank death).

docs/DESIGN.md "Workloads: MoE dispatch & pipeline stages".
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import numpy as np

from tpunet import transport


class Ticket:
    """One posted pipeline transfer plus the tickets it was ordered after.

    ``wait()`` settles the dependencies first (idempotent — a dep may be
    shared by several tickets), then the transfer itself; errors surface as
    typed NativeError. ``done()`` is the non-blocking probe."""

    def __init__(self, request, deps: Sequence["Ticket"] = ()):  # noqa: D401
        self._req = request
        self._deps = tuple(deps)
        self._settled = False

    def wait(self, timeout: float | None = None) -> int:
        for d in self._deps:
            d.wait(timeout)
        if self._settled:
            return 0
        n = self._req.wait(timeout) if self._req is not None else 0
        self._settled = True
        return n

    def done(self) -> bool:
        if self._settled:
            return True
        if any(not d.done() for d in self._deps):
            return False
        if self._req is None:
            return True
        ok, _ = self._req.test()
        return ok


class PipelineStage:
    """One stage of a linear pipeline over dedicated P2P links.

    ``comm`` is the group Communicator (rank = stage index); it carries the
    handle rendezvous and stays available for collectives (e.g. the data-
    parallel gradient AllReduce a real trainer would interleave).
    ``traffic_class`` pins the QoS lane of the stage links ("latency" for
    activation hops competing with bulk gradient traffic)."""

    def __init__(self, comm, traffic_class: str | None = None):
        self.comm = comm
        self.rank = comm.rank
        self.world = comm.world_size
        self.net = transport.Net(traffic_class=traffic_class)
        self._listen = self.net.listen()
        handle = np.frombuffer(self._listen.handle, np.uint8).copy()
        handles = comm.all_gather(handle)
        self._send = None  # link to stage rank+1
        self._recv = None  # link from stage rank-1
        # Connect-all-then-accept-all: connect() never blocks on the peer's
        # accept (TCP backlog + buffered preamble), so the forward chain
        # wires without any cross-stage ordering assumption.
        if self.rank + 1 < self.world:
            self._send = self.net.connect(handles[self.rank + 1].tobytes())
        if self.rank > 0:
            self._recv = self._listen.accept()

    @property
    def is_first(self) -> bool:
        return self.rank == 0

    @property
    def is_last(self) -> bool:
        return self.rank == self.world - 1

    # -- ticketed microbatch transfers ------------------------------------

    def isend(self, arr: np.ndarray, after: Sequence[Ticket] = ()) -> Ticket:
        """Post a microbatch to the NEXT stage, ordered after `after`
        (their transfers settle before this send posts — the chain
        guarantee). Last stage has no next: error, not silence."""
        if self._send is None:
            raise RuntimeError(f"stage {self.rank} is last: no next stage to send to")
        for d in after:
            d.wait()
        return Ticket(self._send.isend(np.ascontiguousarray(arr)), ())

    def irecv(self, buf: np.ndarray, after: Sequence[Ticket] = ()) -> Ticket:
        """Post a microbatch receive from the PREVIOUS stage into `buf`
        (pinned until the ticket settles), ordered after `after`."""
        if self._recv is None:
            raise RuntimeError(f"stage {self.rank} is first: no previous stage")
        for d in after:
            d.wait()
        return Ticket(self._recv.irecv(buf), ())

    # -- the canonical microbatch chain -----------------------------------

    def run(self, fn: Callable[[np.ndarray], np.ndarray],
            microbatches: Sequence[np.ndarray] | None = None,
            n_micro: int | None = None,
            mb_shape: tuple | None = None) -> list[np.ndarray] | None:
        """Drive a GPipe-style forward chain of microbatches through this
        stage: stage 0 feeds ``microbatches``; later stages receive
        ``n_micro`` batches of ``mb_shape`` f32, apply ``fn``, and forward
        (except the last, which collects and returns the outputs — every
        other stage returns None). Send k+1 overlaps compute k on the
        middle stages; each send is `after=`-chained behind the recv it
        transforms, so the wire order can never outrun the data flow."""
        outputs: list[np.ndarray] = []
        pending: list[Ticket] = []
        if self.is_first:
            if microbatches is None:
                raise ValueError("stage 0 needs the input microbatches")
            for mb in microbatches:
                pending.append(self.isend(fn(np.asarray(mb, np.float32))))
        else:
            if n_micro is None or mb_shape is None:
                raise ValueError("stages > 0 need n_micro and mb_shape")
            bufs = [np.empty(mb_shape, np.float32) for _ in range(int(n_micro))]
            for buf in bufs:
                t_r = self.irecv(buf)
                t_r.wait()  # the compute below consumes buf
                y = fn(buf)
                if self.is_last:
                    outputs.append(y)
                else:
                    pending.append(self.isend(y, after=(t_r,)))
        for t in pending:
            t.wait()
        return outputs if self.is_last else None

    def close(self) -> None:
        for c in (self._send, self._recv, self._listen):
            if c is not None:
                try:
                    c.close()
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
        self._send = self._recv = None
        self.net.close()

    def __enter__(self) -> "PipelineStage":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()
