"""tpunet workloads — the traffic patterns that drive the transport.

The collectives layer was AllReduce-deep but workload-narrow; this package
adds the two traffic shapes "Collective Communication for 100k+ GPUs" names
as the new dominant patterns, built entirely on public tpunet APIs so they
double as end-to-end exercisers of the QoS / codec / hierarchical-schedule
machinery:

  moe      — Mixture-of-Experts dispatch/combine over the typed AllToAll:
             Zipf-skewed top-1 expert routing (TPUNET_MOE_SKEW), capacity-
             bounded packing, dispatch on a latency-class communicator so
             the PR 8 DRR scheduler finally arbitrates a REAL competing
             workload (benchmarks/moe_bench.py pits it against a bulk
             gradient tenant).
  pipeline — pipeline-parallel stage driver: directed microbatch send/recv
             chains over per-stage P2P links with ticket `after=` ordering
             (the workload-tier analogue of the FFI `after=` operand
             threading), across real or TPUNET_HOST_ID fake-host splits.

docs/DESIGN.md "Workloads: MoE dispatch & pipeline stages".
"""

from tpunet.workloads.moe import MoeDispatcher, route_tokens, zipf_weights
from tpunet.workloads.pipeline import PipelineStage, Ticket

__all__ = [
    "MoeDispatcher",
    "PipelineStage",
    "Ticket",
    "route_tokens",
    "zipf_weights",
]
