"""MoE expert dispatch/combine over the typed AllToAll.

The expert-parallel layout: W ranks, one expert shard per rank (expert e
lives on rank e). Every rank routes its local tokens top-1 to experts with
a Zipf-skewed popularity (the 100k+-GPU paper's hot-expert shape, exponent
``TPUNET_MOE_SKEW``), packs them into capacity-bounded per-expert blocks,
and ships them with ONE typed AllToAll (``Communicator.all_to_all_typed``)
— small, skewed, latency-sensitive shards, exactly the traffic the
hierarchical A2A schedule and the QoS latency class exist for. The expert
computes, and a second typed AllToAll combines results back to the source
positions.

Determinism contract: routing, packing and slot bookkeeping are pure
functions of (tokens, expert assignment, capacity), so the combine scatter
needs NO extra metadata round — each dispatcher remembers which token sat
in which (expert, slot) and the A2A geometry is its own inverse. Tokens
beyond an expert's capacity are DROPPED (standard MoE overflow semantics)
and counted, never silently mixed in. Under an int8/bf16 wire codec the
shipped blocks obey the per-block |err| <= amax/254 bound (scale blocks
restart per (src, dst) block), and dropped-slot padding rides as zeros.

docs/DESIGN.md "Workloads: MoE dispatch & pipeline stages".
"""

from __future__ import annotations

import os

import numpy as np


def zipf_weights(n_experts: int, skew: float) -> np.ndarray:
    """Expert popularity: w_k proportional to 1/(k+1)^skew, normalized.
    skew=0 is uniform; larger skews concentrate load on low-index experts
    (expert ids are shuffled per routing call, so "expert 0" is not
    structurally hot across seeds)."""
    if n_experts < 1:
        raise ValueError(f"n_experts must be >= 1, got {n_experts}")
    if skew < 0:
        raise ValueError(f"skew must be >= 0, got {skew}")
    w = 1.0 / np.power(np.arange(1, n_experts + 1, dtype=np.float64), skew)
    return w / w.sum()


def route_tokens(n_tokens: int, n_experts: int, skew: float | None = None,
                 rng: np.random.Generator | None = None) -> np.ndarray:
    """Top-1 expert id per token, sampled from the Zipf popularity.
    ``skew=None`` reads TPUNET_MOE_SKEW (default 1.0 — the registered knob,
    validated by Config.from_env). The popularity ranking is permuted by
    ``rng`` so hotness lands on a random expert, not always expert 0."""
    if skew is None:
        try:
            skew = float(os.environ.get("TPUNET_MOE_SKEW", "1.0"))
        except ValueError:
            skew = 1.0
    rng = rng or np.random.default_rng(0)
    w = zipf_weights(n_experts, skew)[rng.permutation(n_experts)]
    return rng.choice(n_experts, size=n_tokens, p=w).astype(np.int64)


class MoeDispatcher:
    """Capacity-bounded top-1 dispatch/combine for one expert-parallel group.

    ``comm`` is a tpunet Communicator whose world size is the expert count
    (one expert shard per rank). ``capacity`` bounds how many tokens any
    single (source rank -> expert) block carries per dispatch — the A2A
    block size is ``capacity * d_model`` f32 elements, identical on every
    rank, which is what lets the exchange run as one typed AllToAll with
    zero per-block metadata."""

    def __init__(self, comm, d_model: int, capacity: int):
        if d_model < 1 or capacity < 1:
            raise ValueError("d_model and capacity must be >= 1")
        self.comm = comm
        self.d_model = int(d_model)
        self.capacity = int(capacity)
        self._slot_of_token: np.ndarray | None = None
        self._kept: np.ndarray | None = None
        # Cumulative stats — the bench reads these next to the native
        # tpunet_a2a_bytes_total counters.
        self.tokens_routed = 0
        self.tokens_dropped = 0
        self.dispatches = 0

    # -- dispatch ----------------------------------------------------------

    def pack(self, tokens: np.ndarray, experts: np.ndarray):
        """Pack tokens into the (W, capacity, d) dispatch buffer. Returns
        (buf, counts) where counts[e] is the number of valid slots bound
        for expert e. Overflow tokens (beyond capacity per expert) are
        dropped and counted; their slot entry stays -1 so combine scatters
        nothing back into their output rows."""
        E = self.comm.world_size
        tokens = np.ascontiguousarray(tokens, np.float32)
        experts = np.asarray(experts, np.int64)
        if tokens.ndim != 2 or tokens.shape[1] != self.d_model:
            raise ValueError(f"tokens must be (T, {self.d_model}), got {tokens.shape}")
        if experts.shape != (tokens.shape[0],):
            raise ValueError("experts must be one id per token")
        if experts.size and (experts.min() < 0 or experts.max() >= E):
            raise ValueError(f"expert ids must be in [0, {E})")
        buf = np.zeros((E, self.capacity, self.d_model), np.float32)
        counts = np.zeros(E, np.int64)
        slot_of_token = np.full(tokens.shape[0], -1, np.int64)
        for i, e in enumerate(experts):
            c = counts[e]
            if c >= self.capacity:
                self.tokens_dropped += 1
                continue
            buf[e, c] = tokens[i]
            slot_of_token[i] = e * self.capacity + c
            counts[e] = c + 1
        self.tokens_routed += int(tokens.shape[0])
        self._slot_of_token = slot_of_token
        self._kept = slot_of_token >= 0
        return buf, counts

    def dispatch(self, tokens: np.ndarray, experts: np.ndarray):
        """Route this rank's tokens to their experts. Returns
        (expert_tokens, counts_by_source): expert_tokens is the
        (W, capacity, d) buffer of tokens THIS rank's expert received
        (indexed by source rank), counts_by_source[s] how many of source
        s's slots are valid. One typed AllToAll for the payload plus one
        8-byte-per-rank byte AllToAll for the counts."""
        buf, counts = self.pack(tokens, experts)
        expert_tokens = self.comm.all_to_all_typed(buf)
        counts_by_source = self.comm.all_to_all(
            np.ascontiguousarray(counts.reshape(-1, 1))).reshape(-1)
        self.dispatches += 1
        return expert_tokens, counts_by_source

    # -- combine -----------------------------------------------------------

    def combine(self, expert_out: np.ndarray, out: np.ndarray | None = None):
        """Inverse of dispatch: ship each processed (W, capacity, d) buffer
        back to its source rank (the A2A geometry is its own inverse) and
        scatter rows to the original token positions recorded by pack().
        Dropped tokens keep their ``out`` rows untouched (zeros by
        default — standard MoE overflow)."""
        if self._slot_of_token is None:
            raise RuntimeError("combine() before dispatch()")
        expert_out = np.ascontiguousarray(expert_out, np.float32)
        returned = self.comm.all_to_all_typed(expert_out)
        flat = returned.reshape(-1, self.d_model)
        n_tok = self._slot_of_token.shape[0]
        if out is None:
            out = np.zeros((n_tok, self.d_model), np.float32)
        kept = self._kept
        out[kept] = flat[self._slot_of_token[kept]]
        return out

    @property
    def drop_fraction(self) -> float:
        return self.tokens_dropped / max(1, self.tokens_routed)
