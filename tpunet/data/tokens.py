"""Token datasets over flat binary files.

Storage format: one flat array of token ids (uint16 when vocab < 65536,
else int32) in a .bin file, produced once by `pack_documents`. Training
reads it through numpy memmap — the OS page cache is the shuffle buffer,
and a (batch, seq+1) slice costs one strided gather, no Python-loop
tokenization anywhere near the step loop.
"""

from __future__ import annotations

import os
from typing import Iterator

import numpy as np


def pack_documents(
    docs: Iterator[list[int] | np.ndarray],
    path: str,
    *,
    vocab: int,
    eos_id: int | None = None,
) -> int:
    """Concatenate token documents into a flat .bin at `path` (uint16 if
    vocab fits, else int32), appending `eos_id` after each doc when given.
    Returns the total token count. One-time preprocessing — training never
    re-tokenizes."""
    dtype = np.uint16 if vocab <= (1 << 16) else np.int32
    if eos_id is not None and not 0 <= eos_id < vocab:
        raise ValueError(f"eos_id {eos_id} outside [0, {vocab})")
    total = 0
    with open(path, "wb") as f:
        for doc in docs:
            # Range-check BEFORE the storage-dtype cast — casting first
            # would wrap out-of-range ids into the valid range and pass.
            raw = np.asarray(doc)
            if raw.size and (int(raw.min()) < 0 or int(raw.max()) >= vocab):
                raise ValueError(
                    f"token ids [{int(raw.min())}, {int(raw.max())}] outside "
                    f"[0, {vocab})"
                )
            arr = raw.astype(dtype)
            arr.tofile(f)
            total += arr.size
            if eos_id is not None:
                np.asarray([eos_id], dtype=dtype).tofile(f)
                total += 1
    return total


class TokenDataset:
    """A flat token .bin exposed as fixed-length (seq+1)-token windows.

    Window i covers tokens [i*seq, i*seq + seq + 1): the +1 overlap supplies
    the shifted-by-one labels without a second read. Windows are
    non-overlapping in their first `seq` tokens, so one epoch sees each
    token once as an input position.
    """

    def __init__(self, path: str, seq: int, *, vocab: int):
        dtype = np.uint16 if vocab <= (1 << 16) else np.int32
        size = os.path.getsize(path) // np.dtype(dtype).itemsize
        self._mm = np.memmap(path, dtype=dtype, mode="r", shape=(size,))
        self.seq = seq
        self.vocab = vocab
        self.n_windows = (size - 1) // seq
        if self.n_windows < 1:
            raise ValueError(
                f"{path}: {size} tokens < one {seq}+1-token window"
            )

    def window(self, i: int) -> np.ndarray:
        """(seq+1,) int32 tokens of window i."""
        if not 0 <= i < self.n_windows:
            raise IndexError(i)
        off = i * self.seq
        return np.asarray(self._mm[off : off + self.seq + 1], dtype=np.int32)

    def batch(self, idx: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """(inputs, labels) int32 of shape (len(idx), seq) for window ids
        `idx` — labels are inputs shifted by one inside each window."""
        idx = np.asarray(idx, dtype=np.int64)
        if idx.ndim != 1:
            raise ValueError(f"idx must be 1-D, got shape {idx.shape}")
        if idx.size and (idx.min() < 0 or idx.max() >= self.n_windows):
            raise IndexError(
                f"window ids [{idx.min()}, {idx.max()}] outside "
                f"[0, {self.n_windows})"
            )
        # One vectorized gather for the whole (batch, seq+1) block — the
        # memmap fancy-index reads each window's pages directly, with no
        # per-row Python loop on the training hot path.
        gather = idx[:, None] * self.seq + np.arange(self.seq + 1)
        rows = np.asarray(self._mm[gather], dtype=np.int32)
        return rows[:, :-1], rows[:, 1:]


def token_batches(
    ds: TokenDataset,
    batch: int,
    *,
    rank: int = 0,
    world: int = 1,
    seed: int = 0,
    epochs: int | None = None,
):
    """Yield (inputs, labels) batches of `batch` rows for this rank.

    Index-level dp sharding: each epoch draws ONE shared permutation of all
    windows from `seed` (identical on every rank — no coordination needed),
    then rank r takes positions r, r+world, ... so ranks see disjoint rows
    and together cover the epoch. Trailing windows that don't fill a full
    per-rank batch are dropped (keeps shapes static for jit).

    epochs=None iterates forever (epoch counter feeds the permutation, so
    order differs every epoch but is reproducible from seed).
    """
    if batch < 1 or world < 1 or not 0 <= rank < world:
        raise ValueError(f"bad batch/rank/world: {batch}/{rank}/{world}")
    per_epoch = ds.n_windows // (batch * world)
    if per_epoch < 1:
        raise ValueError(
            f"{ds.n_windows} windows < one global batch of {batch * world}"
        )
    epoch = 0
    while epochs is None or epoch < epochs:
        order = np.random.default_rng((seed, epoch)).permutation(ds.n_windows)
        mine = order[rank::world]
        for b in range(per_epoch):
            yield ds.batch(mine[b * batch : (b + 1) * batch])
        epoch += 1
