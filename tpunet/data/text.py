"""Byte-level tokenizer — text in, tokens out, no external vocab files.

The simplest tokenizer that makes the whole stack usable on raw text:
token id = byte value (0..255), with special ids appended ABOVE the byte
range so no byte is ever shadowed (BOS = 256, EOS = 257 by default; vocab
= 258). Lossless on arbitrary UTF-8 (it never sees codepoints, only
bytes), deterministic, zero training. Pair with `pack_documents` for the
flat-.bin training path and with `generate`/`BatchServer` for inference:

    tok = ByteTokenizer()
    pack_documents((tok.encode(t) for t in texts), "corpus.bin",
                   vocab=tok.vocab, eos_id=tok.eos_id)
    ...
    text = tok.decode(generate(model, params, prompt[None], 64)[0])

A subword vocabulary trades sequence length for a learned vocab; the
byte tokenizer trades nothing for correctness and is the honest default
for synthetic/benchmark corpora. (The reference repo has no data or
tokenizer layer at all — it is a transport.)
"""

from __future__ import annotations

import numpy as np


class ByteTokenizer:
    """Lossless byte-level tokenizer with BOS/EOS above the byte range."""

    def __init__(self, add_bos: bool = False):
        self.bos_id = 256
        self.eos_id = 257
        self.vocab = 258
        self.add_bos = add_bos

    def encode(self, text: str | bytes, *, eos: bool = False) -> np.ndarray:
        """UTF-8 bytes of `text` as int32 ids, optional BOS prefix / EOS
        suffix. (pack_documents appends EOS itself via eos_id — don't
        double up when packing.)"""
        raw = text.encode("utf-8") if isinstance(text, str) else bytes(text)
        ids = np.frombuffer(raw, np.uint8).astype(np.int32)
        parts = []
        if self.add_bos:
            parts.append(np.asarray([self.bos_id], np.int32))
        parts.append(ids)
        if eos:
            parts.append(np.asarray([self.eos_id], np.int32))
        return np.concatenate(parts) if len(parts) > 1 else ids

    def decode(self, ids, *, errors: str = "replace") -> str:
        """ids -> text. Special ids (and any out-of-range id a sampler
        might produce under a larger model vocab) are dropped, not
        crashed on; invalid UTF-8 decodes per `errors`."""
        ids = np.asarray(ids).reshape(-1)
        keep = ids[(ids >= 0) & (ids < 256)].astype(np.uint8)
        return keep.tobytes().decode("utf-8", errors=errors)
