"""Input pipeline: memmapped token datasets, dp-sharded batching, and
host->device prefetch.

The reference repo ships no data layer (it is a transport; training data
was nccl-tests/Bagua's synthetic generators — reference README.md:20-52).
A complete training framework needs one, built TPU-first:

  * The loader never touches the accelerator on the iteration path —
    batches are cut from a numpy memmap (no tokenization at train time;
    tokens are preprocessed once into a flat .bin).
  * `prefetch_to_device` overlaps the NEXT batch's host->HBM transfer with
    the CURRENT step's compute from a background thread, the host-side
    mirror of the DCN tier's transfer/compute overlap.
  * dp sharding happens at the INDEX level (rank r reads row r, r+W, ...),
    so every rank IO-reads only its own rows — no broadcast, no redundant
    reads, deterministic across ranks from the shared seed.
"""

from tpunet.data.tokens import (  # noqa: F401
    TokenDataset,
    pack_documents,
    token_batches,
)
from tpunet.data.prefetch import prefetch_to_device  # noqa: F401
from tpunet.data.text import ByteTokenizer  # noqa: F401
