"""Host->device prefetch: overlap the next batch's transfer with the
current step's compute.

A background thread pulls from the host iterator, calls `jax.device_put`
(optionally with a sharding, so multi-device placement happens off the
critical path too), and parks up to `size` in-flight batches in a bounded
queue. The training loop then always finds its next batch already resident
— the host-side analogue of the DCN tier's transfer/compute overlap
(tpunet.train.trainer bucketed nonblocking all-reduce).

device_put is async (returns immediately, transfer proceeds in the
runtime), so the thread's job is just to keep `size` transfers in flight
ahead of consumption; size=2 (double buffering) is enough to hide a
transfer that takes less than a step.
"""

from __future__ import annotations

import queue
import threading

import jax


def prefetch_to_device(iterator, size: int = 2, sharding=None):
    """Wrap `iterator` (yielding pytrees of numpy arrays) so batches arrive
    already device-resident, `size` batches ahead.

    sharding: optional jax.sharding.Sharding (or pytree of them) passed to
    device_put — e.g. `batch_sharding(mesh)` to land rows pre-sharded over
    dp. None = default device placement.

    The worker thread is a daemon and stops at source exhaustion or when
    the consumer drops the generator (GeneratorExit closes the queue).
    """
    if size < 1:
        raise ValueError("size must be >= 1")
    q: queue.Queue = queue.Queue(maxsize=size)
    _END = object()
    # Bound locally: the worker can outlive user code into interpreter
    # shutdown, when the `queue` module global may be torn down to None.
    _full = queue.Full
    stop = threading.Event()

    def _put_or_abandon(item) -> bool:
        """Bounded put that also watches for consumer abandonment, so a
        dropped generator can't leave this thread pinned on a full queue
        holding device buffers forever. True = delivered."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except _full:
                continue
        return False

    def worker():
        try:
            for item in iterator:
                put = (
                    jax.device_put(item, sharding)
                    if sharding is not None
                    else jax.device_put(item)
                )
                if not _put_or_abandon(put):
                    return
        except Exception as e:  # surface source errors to the consumer
            _put_or_abandon(e)
            return
        _put_or_abandon(_END)

    t = threading.Thread(target=worker, daemon=True, name="tpunet-prefetch")
    t.start()

    try:
        while True:
            item = q.get()
            if item is _END:
                return
            if isinstance(item, Exception):
                raise item
            yield item
    finally:
        # Consumer closed (break / GeneratorExit / error): release the
        # worker and drop any parked batches. Best-effort by design: this
        # can run at interpreter shutdown when the queue module's own
        # globals are already torn down (get_nowait then raises TypeError
        # instead of Empty), so any exception just ends the drain.
        stop.set()
        try:
            while True:
                q.get_nowait()
        except BaseException:  # noqa: BLE001 — Empty normally; shutdown junk
            pass
