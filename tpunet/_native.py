"""Loader + raw ctypes signatures for libtpunet.so (the C ABI, c_api.h).

Builds the native library on demand (``make -C cpp``) with a file lock so
concurrent test processes don't race the build. The reference shipped its
native core the same way conceptually: cargo staticlib + make shared object
(reference: cc/Makefile:9-16).
"""

from __future__ import annotations

import ctypes
import fcntl
import os
import subprocess
from pathlib import Path

_REPO_ROOT = Path(__file__).resolve().parent.parent
_CPP_DIR = _REPO_ROOT / "cpp"
_LIB_PATH = _CPP_DIR / "build" / "libtpunet.so"

TPUNET_OK = 0
TPUNET_ERR_NULL = -1
TPUNET_ERR_INVALID = -2
TPUNET_ERR_INNER = -3
# Failure-model codes (docs/DESIGN.md "Failure model"):
TPUNET_ERR_CORRUPT = -4   # per-chunk CRC32C mismatch (TPUNET_CRC=1)
TPUNET_ERR_TIMEOUT = -5   # progress watchdog (TPUNET_PROGRESS_TIMEOUT_MS)
TPUNET_ERR_VERSION = -6   # wire-framing version mismatch with the peer
TPUNET_ERR_CODEC = -7     # ranks disagree on the collective wire codec
TPUNET_ERR_QOS_ADMISSION = -8  # QoS class in-flight budget full (retryable)
TPUNET_ERR_REWIRE = -9    # elastic rewire exceeded TPUNET_REWIRE_TIMEOUT_MS
TPUNET_ERR_WEIGHT_SWAP = -10  # live weight publication aborted (retryable)

HANDLE_SIZE = 64


class SocketHandle(ctypes.Structure):
    _fields_ = [("data", ctypes.c_uint8 * HANDLE_SIZE)]


class NetProperties(ctypes.Structure):
    _fields_ = [
        ("name", ctypes.c_char_p),
        ("pci_path", ctypes.c_char_p),
        ("guid", ctypes.c_uint64),
        ("ptr_support", ctypes.c_int32),
        ("speed_mbps", ctypes.c_int32),
        ("port", ctypes.c_int32),
        ("max_comms", ctypes.c_int32),
    ]


def _sources_mtime() -> float:
    newest = 0.0
    for sub in ("src", "include/tpunet", "tests"):
        d = _CPP_DIR / sub
        if d.is_dir():
            for f in d.rglob("*"):
                if f.suffix in (".cc", ".h"):
                    newest = max(newest, f.stat().st_mtime)
    mk = _CPP_DIR / "Makefile"
    if mk.exists():
        newest = max(newest, mk.stat().st_mtime)
    return newest


def build_native(force: bool = False) -> Path:
    """Build libtpunet.so if missing or stale. Safe across processes."""
    lock_path = _CPP_DIR / ".build.lock"
    with open(lock_path, "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        try:
            stale = (
                force
                or not _LIB_PATH.exists()
                or _LIB_PATH.stat().st_mtime < _sources_mtime()
            )
            if stale:
                subprocess.run(
                    ["make", "-C", str(_CPP_DIR), "all"],
                    check=True,
                    capture_output=True,
                    text=True,
                )
        except subprocess.CalledProcessError as e:  # surface compiler output
            raise RuntimeError(
                f"native build failed:\n{e.stdout}\n{e.stderr}"
            ) from e
        finally:
            fcntl.flock(lock, fcntl.LOCK_UN)
    return _LIB_PATH


_lib: ctypes.CDLL | None = None


def load() -> ctypes.CDLL:
    """Load (building if needed) and memoize the native library."""
    global _lib
    if _lib is not None:
        return _lib
    path = os.environ.get("TPUNET_LIBRARY_PATH", "")
    bundled = Path(__file__).resolve().parent / "lib" / "libtpunet.so"
    if path:
        lib_file = Path(path)
    elif bundled.exists():  # installed wheel: .so shipped as package data
        lib_file = bundled
    else:  # source checkout: build on demand
        lib_file = build_native()
    lib = ctypes.CDLL(str(lib_file))

    u = ctypes.c_uintptr if hasattr(ctypes, "c_uintptr") else ctypes.c_size_t
    i32, u8, u64 = ctypes.c_int32, ctypes.c_uint8, ctypes.c_uint64
    P = ctypes.POINTER

    lib.tpunet_c_create.argtypes = [P(u)]
    lib.tpunet_c_create.restype = i32
    lib.tpunet_c_create_ex.argtypes = [ctypes.c_char_p, P(u)]
    lib.tpunet_c_create_ex.restype = i32
    lib.tpunet_c_destroy.argtypes = [P(u)]
    lib.tpunet_c_destroy.restype = i32
    lib.tpunet_c_devices.argtypes = [u, P(i32)]
    lib.tpunet_c_devices.restype = i32
    lib.tpunet_c_get_properties.argtypes = [u, i32, P(NetProperties)]
    lib.tpunet_c_get_properties.restype = i32
    lib.tpunet_c_listen.argtypes = [u, i32, P(SocketHandle), P(u)]
    lib.tpunet_c_listen.restype = i32
    lib.tpunet_c_connect.argtypes = [u, i32, P(SocketHandle), P(u)]
    lib.tpunet_c_connect.restype = i32
    lib.tpunet_c_accept.argtypes = [u, u, P(u)]
    lib.tpunet_c_accept.restype = i32
    lib.tpunet_c_isend.argtypes = [u, u, ctypes.c_void_p, u64, P(u)]
    lib.tpunet_c_isend.restype = i32
    lib.tpunet_c_irecv.argtypes = [u, u, ctypes.c_void_p, u64, P(u)]
    lib.tpunet_c_irecv.restype = i32
    lib.tpunet_c_test.argtypes = [u, u, P(u8), P(u64)]
    lib.tpunet_c_test.restype = i32
    lib.tpunet_c_wait.argtypes = [u, u, P(u64)]
    lib.tpunet_c_wait.restype = i32
    lib.tpunet_c_close_send.argtypes = [u, u]
    lib.tpunet_c_close_send.restype = i32
    lib.tpunet_c_close_recv.argtypes = [u, u]
    lib.tpunet_c_close_recv.restype = i32
    lib.tpunet_c_close_listen.argtypes = [u, u]
    lib.tpunet_c_close_listen.restype = i32
    lib.tpunet_c_last_error.argtypes = []
    lib.tpunet_c_last_error.restype = ctypes.c_char_p

    lib.tpunet_comm_create.argtypes = [ctypes.c_char_p, i32, i32, P(u)]
    lib.tpunet_comm_create.restype = i32
    lib.tpunet_comm_create_ex.argtypes = [
        ctypes.c_char_p, i32, i32, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_char_p, P(u),
    ]
    lib.tpunet_comm_create_ex.restype = i32
    lib.tpunet_comm_wire_dtype.argtypes = [u, P(i32)]
    lib.tpunet_comm_wire_dtype.restype = i32
    lib.tpunet_comm_destroy.argtypes = [P(u)]
    lib.tpunet_comm_destroy.restype = i32
    lib.tpunet_comm_rank.argtypes = [u, P(i32), P(i32)]
    lib.tpunet_comm_rank.restype = i32
    lib.tpunet_comm_all_reduce.argtypes = [u, ctypes.c_void_p, ctypes.c_void_p, u64, i32, i32]
    lib.tpunet_comm_all_reduce.restype = i32
    lib.tpunet_comm_set_default.argtypes = [u]
    lib.tpunet_comm_set_default.restype = i32
    lib.tpunet_comm_get_default.argtypes = []
    lib.tpunet_comm_get_default.restype = u
    lib.tpunet_comm_reduce_scatter.argtypes = [u, ctypes.c_void_p, ctypes.c_void_p, u64, i32, i32]
    lib.tpunet_comm_reduce_scatter.restype = i32
    lib.tpunet_comm_all_gather.argtypes = [u, ctypes.c_void_p, ctypes.c_void_p, u64]
    lib.tpunet_comm_all_gather.restype = i32
    lib.tpunet_comm_broadcast.argtypes = [u, ctypes.c_void_p, u64, i32]
    lib.tpunet_comm_broadcast.restype = i32
    lib.tpunet_comm_all_to_all.argtypes = [u, ctypes.c_void_p, ctypes.c_void_p, u64]
    lib.tpunet_comm_all_to_all.restype = i32
    lib.tpunet_comm_all_to_all_typed.argtypes = [
        u, ctypes.c_void_p, ctypes.c_void_p, u64, i32]
    lib.tpunet_comm_all_to_all_typed.restype = i32
    lib.tpunet_comm_iall_to_all.argtypes = [
        u, ctypes.c_void_p, ctypes.c_void_p, u64, P(u64)]
    lib.tpunet_comm_iall_to_all.restype = i32
    lib.tpunet_comm_neighbor_exchange.argtypes = [u, ctypes.c_void_p, u64, ctypes.c_void_p, u64, P(u64)]
    lib.tpunet_comm_neighbor_exchange.restype = i32
    lib.tpunet_comm_barrier.argtypes = [u]
    lib.tpunet_comm_barrier.restype = i32
    lib.tpunet_comm_iall_reduce.argtypes = [
        u, ctypes.c_void_p, ctypes.c_void_p, u64, i32, i32, P(u64),
    ]
    lib.tpunet_comm_iall_reduce.restype = i32
    lib.tpunet_comm_ticket_wait.argtypes = [u, u64]
    lib.tpunet_comm_ticket_wait.restype = i32
    lib.tpunet_comm_ticket_test.argtypes = [u, u64, P(ctypes.c_uint8)]
    lib.tpunet_comm_ticket_test.restype = i32

    lib.tpunet_c_metrics_text.argtypes = [ctypes.c_char_p, u64]
    lib.tpunet_c_metrics_text.restype = i32
    lib.tpunet_c_metrics_reset.argtypes = []
    lib.tpunet_c_metrics_reset.restype = i32
    lib.tpunet_c_trace_flush.argtypes = []
    lib.tpunet_c_trace_flush.restype = i32
    lib.tpunet_c_trace_set_dir.argtypes = [ctypes.c_char_p]
    lib.tpunet_c_trace_set_dir.restype = i32
    lib.tpunet_c_metrics_port.argtypes = []
    lib.tpunet_c_metrics_port.restype = i32
    lib.tpunet_c_serve_observe.argtypes = [i32, u64]
    lib.tpunet_c_serve_observe.restype = i32
    lib.tpunet_c_serve_queue_depth.argtypes = [i32, u64]
    lib.tpunet_c_serve_queue_depth.restype = i32
    lib.tpunet_c_qos_state.argtypes = [ctypes.c_char_p, u64]
    lib.tpunet_c_qos_state.restype = i32
    lib.tpunet_c_lane_parse.argtypes = [ctypes.c_char_p, ctypes.c_char_p, u64]
    lib.tpunet_c_lane_parse.restype = i32
    lib.tpunet_c_stripe_map.argtypes = [u64, u64, ctypes.c_char_p, u64,
                                        ctypes.c_char_p, u64]
    lib.tpunet_c_stripe_map.restype = i32
    lib.tpunet_c_qos_drr_golden.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, u64,
    ]
    lib.tpunet_c_qos_drr_golden.restype = i32

    lib.tpunet_c_fault_inject.argtypes = [ctypes.c_char_p]
    lib.tpunet_c_fault_inject.restype = i32
    lib.tpunet_c_fault_clear.argtypes = []
    lib.tpunet_c_fault_clear.restype = i32
    lib.tpunet_c_churn_poll.argtypes = [u64, ctypes.c_int64]
    lib.tpunet_c_churn_poll.restype = i32
    lib.tpunet_c_churn_pending.argtypes = []
    lib.tpunet_c_churn_pending.restype = i32
    lib.tpunet_c_swap_poll.argtypes = [u64]
    lib.tpunet_c_swap_poll.restype = i32
    lib.tpunet_c_swap_pending.argtypes = []
    lib.tpunet_c_swap_pending.restype = i32
    lib.tpunet_c_rewire_observe.argtypes = [i32, u64]
    lib.tpunet_c_rewire_observe.restype = i32
    lib.tpunet_c_churn_event.argtypes = [i32]
    lib.tpunet_c_churn_event.restype = i32
    lib.tpunet_c_world_size.argtypes = [u64]
    lib.tpunet_c_world_size.restype = i32
    lib.tpunet_c_swap_observe.argtypes = [i32, u64]
    lib.tpunet_c_swap_observe.restype = i32
    lib.tpunet_c_swap_event.argtypes = [i32]
    lib.tpunet_c_swap_event.restype = i32
    lib.tpunet_c_weight_version.argtypes = [u64]
    lib.tpunet_c_weight_version.restype = i32
    lib.tpunet_c_flightrec_dump.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, u64]
    lib.tpunet_c_flightrec_dump.restype = i32
    lib.tpunet_c_flightrec_stats.argtypes = [P(u64), P(u64)]
    lib.tpunet_c_flightrec_stats.restype = i32
    lib.tpunet_c_crc32c.argtypes = [ctypes.c_void_p, u64, ctypes.c_uint32]
    lib.tpunet_c_crc32c.restype = ctypes.c_uint32
    lib.tpunet_c_host_id.argtypes = []
    lib.tpunet_c_host_id.restype = u64
    lib.tpunet_c_reduce.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, u64, i32, i32,
    ]
    lib.tpunet_c_reduce.restype = i32
    lib.tpunet_c_codec_wire_bytes.argtypes = [i32, u64]
    lib.tpunet_c_codec_wire_bytes.restype = u64
    lib.tpunet_c_codec_encode.argtypes = [i32, ctypes.c_void_p, u64, ctypes.c_void_p, u64]
    lib.tpunet_c_codec_encode.restype = i32
    lib.tpunet_c_codec_decode.argtypes = [i32, ctypes.c_void_p, u64, ctypes.c_void_p]
    lib.tpunet_c_codec_decode.restype = i32

    _lib = lib
    return lib


def last_error() -> str:
    if _lib is None:
        return ""
    msg = _lib.tpunet_c_last_error()
    return msg.decode("utf-8", "replace") if msg else ""


class NativeError(RuntimeError):
    def __init__(self, code: int, op: str):
        self.code = code
        super().__init__(f"tpunet native {op} failed (code {code}): {last_error()}")


class CorruptionError(NativeError):
    """Wire payload failed its per-chunk CRC32C check (TPUNET_CRC=1).

    The affected request failed but the comm did NOT disconnect — retrying
    the collective on the same communicator is legitimate; repeated
    corruption means a bad NIC/path and warrants a rebuild."""


class ProgressTimeoutError(NativeError):
    """The progress watchdog (TPUNET_PROGRESS_TIMEOUT_MS) saw a request move
    zero bytes for a full window: the peer is alive but stuck. Classified as
    a comm failure by tpunet.train.elastic — same recovery as a dead peer."""


class VersionMismatchError(NativeError):
    """The peer speaks a different tpunet wire-framing version."""


class CodecMismatchError(NativeError):
    """The ranks of a collective group disagree on the wire compression
    codec (TPUNET_WIRE_DTYPE / wire_dtype). Raised at communicator wiring
    time on EVERY rank — before any payload could be mis-decoded — with the
    offending ranks and codecs in the message. Fix the config and rebuild
    the communicator; nothing was corrupted."""


class QosAdmissionError(NativeError):
    """QoS admission control rejected a send: the traffic class's in-flight
    byte budget (TPUNET_QOS_INFLIGHT_BYTES) is fully posted. Pure
    backpressure — NOTHING was enqueued or charged, so the send is safely
    retryable once in-flight work drains (the serve router replays it
    front-of-queue). docs/DESIGN.md "Transport QoS"."""


class RewireTimeoutError(NativeError):
    """An elastic membership rewire (tpunet.elastic.ElasticWorld) failed to
    complete inside TPUNET_REWIRE_TIMEOUT_MS — the bounded-recovery contract
    of the churn engine. The old communicator was already finalized when
    this raises, so the process holds no live comm; callers either retry
    the rewire (the membership doc may still be filling) or exit. Never a
    hang: every phase under the deadline is itself bounded (bootstrap
    timeout, membership grace window). docs/DESIGN.md "Elastic churn"."""


class WeightSwapError(NativeError):
    """A live weight publication (tpunet.serve.publish) aborted: the
    publisher or a receiver died mid-broadcast, the cross-rank CRC32C
    digest agreement failed (flip refused fleet-wide — no rank serves a
    version any other rank disagrees about), or the swap exceeded
    TPUNET_SWAP_TIMEOUT_MS. The PREVIOUS version keeps serving on every
    rank and the partial staged version was discarded, so retrying the
    publication is always safe. Never a hang: every wait inside the swap
    pipeline is bounded by the swap/bootstrap deadlines.
    docs/DESIGN.md "Live weight updates"."""


_TYPED_ERRORS = {
    TPUNET_ERR_CORRUPT: CorruptionError,
    TPUNET_ERR_TIMEOUT: ProgressTimeoutError,
    TPUNET_ERR_VERSION: VersionMismatchError,
    TPUNET_ERR_CODEC: CodecMismatchError,
    TPUNET_ERR_QOS_ADMISSION: QosAdmissionError,
    TPUNET_ERR_REWIRE: RewireTimeoutError,
    TPUNET_ERR_WEIGHT_SWAP: WeightSwapError,
}


def check(code: int, op: str) -> None:
    if code != TPUNET_OK:
        raise _TYPED_ERRORS.get(code, NativeError)(code, op)
