"""JAX ↔ tpunet interop: cross-host collectives inside jitted programs.

XLA has no NCCL-style net-plugin seam (SURVEY §7 hard-part #1), so the
cross-host path enters jitted code two ways:

- **XLA FFI custom call** (CPU backend, default): the native handler
  (cpp/src/xla_ffi.cc) receives the XLA buffers DIRECTLY — the ring
  communicator reads the operand and writes the result in place, zero
  host staging. Measured round 5 at 128 MiB/W=2: the io_callback bridge
  alone (identity callback, no reduce) costs 0.48 s — about three
  full-buffer copies — on top of the 0.24 s native reduce; the FFI path
  removes all of it. The communicator is resolved at CALL time through
  the process-default registry, so elastic recovery re-points it under
  already-compiled executables.
- **`jax.experimental.io_callback` fallback** (non-CPU backends, or a
  .so built without jaxlib headers, or TPUNET_FFI_COLLECTIVES=0):
  device buffers are staged to host, reduced, and staged back.

In-pod (ICI) collectives should keep using `jax.lax.psum` et al. — these
functions are the *between-hosts* tier of a hierarchical collective.

All ranks must execute the same dcn_* calls in the same order. The
io_callback path pins relative order with `ordered=True`. The FFI calls
are side-effecting custom calls ordered by the compiled schedule: ranks
compiling IDENTICAL programs schedule identically (the common case —
trainer, ZeRO, hierarchical psum), but a trace that bakes in the rank
(ring/zigzag attention's offsets) may schedule DATA-INDEPENDENT
collectives differently per rank, silently cross-matching them. The
contract: consecutive dcn_* calls in one trace must be related by data
flow — pack independent tensors into one collective (see
dcn_ring_attention's packed k/v exchange) or pass the earlier result via
the `after=` kwarg, which makes it an extra OPERAND of the later custom
call (a dependency no pass can dissolve; stablehlo.optimization_barrier
is NOT sufficient — XLA expands it away and measurably reordered such
collectives). `dcn_all_reduce(sum)` is differentiable: the VJP of a sum
all-reduce is a sum all-reduce of the cotangent.

Ticket API ordering: `dcn_all_reduce_start`/`dcn_all_reduce_finish` run on
the totally-ordered io_callback path ON PURPOSE (the native ticket pairing
contract is submission order across ranks, so the submission point must be
pinned, which `ordered=True` does and the FFI schedule does not). The flip
side: do NOT interleave start/finish with FFI `dcn_*` calls inside one
trace when that trace bakes in the rank (rank-asymmetric programs, e.g.
ring/zigzag attention offsets) WITHOUT bridging them by data flow. The two
mechanisms order through different machineries — io_callback through its
token chain, FFI through the compiled schedule — so XLA is free to
schedule an FFI collective BEFORE the callback-issued submission on one
rank and AFTER it on another, desyncing the ticket sequence exactly like
the unrelated-collectives hazard above. The bridge is `after=`, threaded
through BOTH directions: `dcn_all_reduce_start(x, after=(ffi_result,))` /
`dcn_all_reduce_finish(t, like, after=...)` make the callback an extra
CONSUMER of the earlier FFI results (operands of its io_callback, so the
token chain can't issue the submission until the FFI values exist), and an
FFI call's `after=` accepts the start's ticket or the finish's result to
pin the other direction (the ticket IS an array, hence a legal operand).
In rank-asymmetric traces either bridge every adjacency that way or keep
the ticket API on its own program segments.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import io_callback

from tpunet import distributed


def _comm():
    return distributed.global_communicator()


_ffi_state = {"registered": False, "available": None}

# target name -> handler symbol in libtpunet.so (built all-or-none by the
# Makefile's jaxlib-header guard, so probing one symbol decides for all).
_FFI_TARGETS = {
    "tpunet_all_reduce": "TpunetFfiAllReduce",
    "tpunet_all_gather": "TpunetFfiAllGather",
    "tpunet_reduce_scatter": "TpunetFfiReduceScatter",
    "tpunet_broadcast": "TpunetFfiBroadcast",
    "tpunet_all_to_all": "TpunetFfiAllToAll",
    "tpunet_neighbor_exchange": "TpunetFfiNeighborExchange",
}


def _jax_ffi_mod():
    """The FFI registration/call module: ``jax.ffi`` on current jax,
    ``jax.extend.ffi`` on the 0.4.x line (same curried ffi_call API). The
    custom-call lane must not depend on which spelling this environment
    ships — falling back to io_callback over a NAME move would silently
    cost the 3-copy bridge."""
    mod = getattr(jax, "ffi", None)
    if mod is not None and hasattr(mod, "register_ffi_target"):
        return mod
    from jax.extend import ffi as extend_ffi

    return extend_ffi


def _ffi_available() -> bool:
    """True when the zero-copy XLA custom-call path can serve this trace:
    CPU backend, handler symbols present in libtpunet.so (omitted when the
    .so was built without jaxlib headers), not disabled by
    TPUNET_FFI_COLLECTIVES=0. Decided at trace time; registration is
    one-shot per process."""
    import os

    if os.environ.get("TPUNET_FFI_COLLECTIVES", "1") != "1":
        return False
    if jax.default_backend() != "cpu":
        return False
    if _ffi_state["available"] is None:
        from tpunet import _native

        lib = _native.load()
        # ALL symbols must be present — a stale .so built when only
        # all_reduce existed must fall back to io_callback gracefully,
        # not crash at registration.
        _ffi_state["available"] = all(
            hasattr(lib, sym) for sym in _FFI_TARGETS.values())
    if not _ffi_state["available"]:
        return False
    if not _ffi_state["registered"]:
        from tpunet import _native

        lib = _native.load()
        ffi = _jax_ffi_mod()
        for target, symbol in _FFI_TARGETS.items():
            ffi.register_ffi_target(
                target, ffi.pycapsule(getattr(lib, symbol)),
                platform="cpu")
        _ffi_state["registered"] = True
    return True


def _ffi_call(target: str, spec, x, after=(), **attrs):
    """Issue one FFI collective. `after` values become extra operands of
    the custom call (the handlers ignore them): a dependency no XLA pass
    can dissolve, pinning this collective AFTER the ones that produced
    them. (stablehlo.optimization_barrier is NOT enough — the pipeline
    expands it away and did reorder data-independent collectives in
    rank-asymmetric traces.)"""
    return _jax_ffi_mod().ffi_call(target, spec, has_side_effect=True)(
        x, *after, **attrs)


def _callback_result_spec(x: jax.Array | jnp.ndarray):
    return jax.ShapeDtypeStruct(jnp.shape(x), jnp.result_type(x))


# -- all-reduce -------------------------------------------------------------


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def _dcn_all_reduce_diff(x, op: str = "sum"):
    return _dcn_all_reduce_impl(x, op)


def dcn_all_reduce(x, op: str = "sum", *, after=()):
    """AllReduce `x` across all processes over the DCN transport.

    `after`: results of earlier data-independent dcn_* calls this one must
    follow (module docstring). The after-pinned form is NOT differentiable
    — training all-reduces are ordered by gradient data flow already; the
    kwarg exists for inference/serving traces."""
    if after:
        return _dcn_all_reduce_after(x, op, tuple(after))
    return _dcn_all_reduce_diff(x, op)


def _dcn_all_reduce_after(x, op: str, after):
    if _ffi_available():
        from tpunet.collectives import _OPS, _dtype_code

        return _ffi_call(
            "tpunet_all_reduce", _callback_result_spec(x), x, after,
            dtype=np.int64(_dtype_code(np.dtype(jnp.result_type(x)))),
            op=np.int64(_OPS[op]))

    def cb(a):
        return _comm().all_reduce(np.asarray(a), op)

    return io_callback(cb, _callback_result_spec(x), x, ordered=True)


def _dcn_all_reduce_impl(x, op: str):
    if _ffi_available():
        from tpunet.collectives import _OPS, _dtype_code

        return _ffi_call(
            "tpunet_all_reduce", _callback_result_spec(x), x,
            dtype=np.int64(_dtype_code(np.dtype(jnp.result_type(x)))),
            op=np.int64(_OPS[op]))

    def cb(a):
        return _comm().all_reduce(np.asarray(a), op)

    return io_callback(cb, _callback_result_spec(x), x, ordered=True)


def _dcn_all_reduce_fwd(x, op: str):
    if op != "sum":
        raise NotImplementedError(f"gradient of dcn_all_reduce only defined for sum, got {op}")
    return _dcn_all_reduce_impl(x, op), None


def _dcn_all_reduce_bwd(op: str, _res, g):
    return (_dcn_all_reduce_impl(g, "sum"),)


_dcn_all_reduce_diff.defvjp(_dcn_all_reduce_fwd, _dcn_all_reduce_bwd)


def dcn_psum(x):
    """`jax.lax.psum` shape, but across processes over DCN."""
    return dcn_all_reduce(x, "sum")


def dcn_pmean(x):
    w = distributed.world_size()
    return dcn_all_reduce(x, "sum") / jnp.asarray(w, dtype=jnp.result_type(x))


# -- nonblocking all-reduce (gradient-bucket overlap) -----------------------

# Outstanding AsyncResults keyed by (communicator identity, native ticket).
# Native tickets are sequential per communicator, so two live Communicators
# both count from 1 — a ticket-only key would silently pair a finish with
# the wrong communicator's buffer. id(comm) is stable while any of its
# results are pending (each AsyncResult holds a strong comm ref). The start
# callback pins the buffers here; the finish callback releases them.
# max_in_flight is the observable proof that buckets actually overlapped
# (tests assert on it).
_async_pending: dict[tuple[int, int], Any] = {}
_async_stats = {"in_flight": 0, "max_in_flight": 0}


def _register_pending(comm, res) -> int:
    """Pin `res` until its finish callback; returns the uint32 wire ticket.
    uint32 keeps the ticket jax-representable without x64; native tickets
    are sequential from 1 so wraparound is out of reach."""
    ticket = res._ticket & 0xFFFFFFFF
    _async_pending[(id(comm), ticket)] = res
    _async_stats["in_flight"] += 1
    _async_stats["max_in_flight"] = max(
        _async_stats["max_in_flight"], _async_stats["in_flight"]
    )
    return ticket


def _pop_pending(comm, ticket: int):
    try:
        res = _async_pending.pop((id(comm), ticket))
    except KeyError:
        raise RuntimeError(
            f"no pending async collective with ticket {ticket} on the current "
            "global communicator — dcn_all_reduce_finish without a matching "
            "start, or the communicator was re-initialized mid-flight"
        ) from None
    _async_stats["in_flight"] -= 1
    return res


def _drop_pending_for(comm) -> int:
    """Forget every pending async op of `comm` (called by
    distributed.finalize before closing it): the entries would otherwise be
    unreachable — _pop_pending keys on the CURRENT global comm — pinning
    their buffers and inflating in_flight for the process lifetime."""
    stale = [k for k in _async_pending if k[0] == id(comm)]
    for k in stale:
        del _async_pending[k]
        _async_stats["in_flight"] -= 1
    return len(stale)


def dcn_async_stats() -> dict[str, int]:
    """Snapshot of nonblocking-collective depth (host-side, for tests/bench)."""
    return dict(_async_stats)


def dcn_async_stats_reset() -> None:
    _async_stats["in_flight"] = 0
    _async_stats["max_in_flight"] = 0


def dcn_all_reduce_start(x, op: str = "sum", *, after=()):
    """Begin a nonblocking AllReduce of `x`; returns a ticket (uint32
    scalar) to pass to `dcn_all_reduce_finish`. The reduction runs on the
    native worker thread, overlapping whatever compute XLA schedules
    between the start and finish callbacks — the bucketed-gradient-overlap
    primitive.

    Stays on the totally-ordered io_callback path even when the FFI
    collectives are enabled: cross-rank ticket pairing is SUBMISSION order,
    which `ordered=True` pins and the FFI schedule does not. `after=`:
    results of earlier data-independent FFI `dcn_*` calls this submission
    must follow — they become extra operands of the start callback, so the
    io_callback token chain cannot issue the submission before the FFI
    collectives produced them (the cross-machinery ordering bridge; module
    docstring "Ticket API ordering"). The returned ticket is itself a
    legal `after=` operand for a later FFI call, pinning the reverse
    direction."""

    def cb(a, *_deps):
        c = _comm()
        return np.uint32(_register_pending(c, c.iall_reduce(np.asarray(a), op)))

    return io_callback(cb, jax.ShapeDtypeStruct((), jnp.uint32), x,
                       *tuple(after), ordered=True)


def dcn_all_reduce_finish(ticket, like, *, after=()):
    """Complete the nonblocking AllReduce for `ticket`; returns the reduced
    array (shape/dtype of `like`, the array passed to the start call).
    `after=` pins this completion behind earlier FFI `dcn_*` results, same
    contract as `dcn_all_reduce_start`."""

    def cb(t, *_deps):
        return _pop_pending(_comm(), int(t)).wait()

    return io_callback(cb, _callback_result_spec(like), ticket,
                       *tuple(after), ordered=True)


# -- other collectives ------------------------------------------------------


def dcn_all_gather(x, *, after=()):
    """Gather `x` from every process: result shape (world, *x.shape).
    `after`: results of earlier data-independent dcn_* calls this one must
    follow (module docstring; ignored on the io_callback path, which is
    totally ordered)."""
    w = distributed.world_size()
    spec = jax.ShapeDtypeStruct((w,) + tuple(jnp.shape(x)), jnp.result_type(x))
    if _ffi_available():
        return _ffi_call("tpunet_all_gather", spec, x, after)

    def cb(a):
        return _comm().all_gather(np.asarray(a))

    return io_callback(cb, spec, x, ordered=True)


def dcn_reduce_scatter(x, op: str = "sum", *, after=()):
    """x: leading axis divisible by world; returns this process's reduced
    shard (shape[0]/world leading axis)."""
    w = distributed.world_size()
    shape = tuple(jnp.shape(x))
    if shape[0] % w != 0:
        raise ValueError(f"leading axis {shape[0]} not divisible by world size {w}")

    spec = jax.ShapeDtypeStruct((shape[0] // w,) + shape[1:], jnp.result_type(x))
    if _ffi_available():
        from tpunet.collectives import _OPS, _dtype_code

        return _ffi_call(
            "tpunet_reduce_scatter", spec, x, after,
            dtype=np.int64(_dtype_code(np.dtype(jnp.result_type(x)))),
            op=np.int64(_OPS[op]))

    def cb(a):
        return _comm().reduce_scatter(np.asarray(a), op)

    return io_callback(cb, spec, x, ordered=True)


def dcn_all_to_all(x, *, after=()):
    """AllToAll across processes: x has leading axis == world, block j goes
    to process j; the result's block j came from process j. Shape-preserving.
    The cross-host leg of Ulysses sequence parallelism and MoE dispatch."""
    w = distributed.world_size()
    shape = tuple(jnp.shape(x))
    if not shape or shape[0] != w:
        raise ValueError(f"leading axis must equal world size {w}, got {shape}")

    if _ffi_available():
        return _ffi_call("tpunet_all_to_all", _callback_result_spec(x), x,
                         after)

    def cb(a):
        return _comm().all_to_all(np.asarray(a))

    return io_callback(cb, _callback_result_spec(x), x, ordered=True)


def dcn_broadcast(x, root: int = 0, *, after=()):
    if _ffi_available():
        return _ffi_call("tpunet_broadcast", _callback_result_spec(x), x,
                         after, root=np.int64(root))

    def cb(a):
        return _comm().broadcast(np.asarray(a), root)

    return io_callback(cb, _callback_result_spec(x), x, ordered=True)


def dcn_neighbor_exchange(x, *, after=()):
    """Send x to (rank+1)%world, receive from (rank-1+world)%world — the
    ring-shift step of ring attention / sequence parallelism, across hosts.
    `after`: earlier collectives this exchange must follow (module
    docstring)."""
    if _ffi_available():
        return _ffi_call("tpunet_neighbor_exchange",
                         _callback_result_spec(x), x, after)

    def cb(a):
        return _comm().neighbor_exchange(np.asarray(a))

    return io_callback(cb, _callback_result_spec(x), x, ordered=True)


def dcn_barrier():
    """Host-level barrier (outside jit)."""
    _comm().barrier()


# -- hierarchical helper ----------------------------------------------------


def hierarchical_psum(x, axis_name: str | None = None):
    """Two-tier psum: `lax.psum` over the in-pod mesh axis (ICI, XLA
    collectives), then a DCN all-reduce across processes. This is the shape
    a v5e-32 (4 hosts x 8 chips) gradient sync takes: ICI does the heavy
    intra-pod reduction at interconnect speed, DCN carries one
    already-reduced copy per host.

    Requires `tpunet.distributed.initialize()` BEFORE the first trace: the
    world-size decision is baked into the jitted executable, so a lazy
    "skip DCN when uninitialized" fallback would silently cache an unsynced
    gradient step if tracing ever preceded initialization.
    """
    if axis_name is not None:
        x = jax.lax.psum(x, axis_name)
    if distributed.world_size() > 1:  # raises if initialize() was not called
        x = dcn_all_reduce(x, "sum")
    return x
