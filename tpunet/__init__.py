"""tpunet — TPU-native multi-stream DCN transport, collectives, and JAX glue.

A from-scratch TPU-native framework with the capabilities of the reference
bagua-net (an NCCL net plugin striping messages across parallel TCP streams;
see SURVEY.md). Layers, bottom to top:

- ``tpunet.transport``   — ctypes binding to the C++ engine (libtpunet.so):
  listen/connect/accept rendezvous + chunk-striped isend/irecv/test.
- ``tpunet.collectives`` — bootstrap rendezvous + ring AllReduce/AllGather/
  ReduceScatter/Broadcast over the transport (the role NCCL's algorithms
  played above the reference plugin).
- ``tpunet.distributed`` — process-group initialization from env vars.
- ``tpunet.interop``     — JAX integration: host-callback collectives so
  ``psum``-shaped ops on host-staged buffers ride this transport across
  hosts, plus mesh/sharding helpers for the in-pod (ICI) path.
- ``tpunet.models`` / ``tpunet.train`` — flagship DP benchmark stack (VGG16
  synthetic, mirroring the reference's headline benchmark).
"""

__version__ = "0.1.0"

from tpunet import config as config  # noqa: F401

__all__ = ["config", "__version__"]
