"""tpunet — TPU-native multi-stream DCN transport, collectives, and JAX glue.

A from-scratch TPU-native framework with the capabilities of the reference
bagua-net (an NCCL net plugin striping messages across parallel TCP streams;
see SURVEY.md). Layers, bottom to top:

- ``tpunet.transport``   — ctypes binding to the C++ engine (libtpunet.so):
  listen/connect/accept rendezvous + chunk-striped isend/irecv/test.
- ``tpunet.collectives`` — bootstrap rendezvous + ring AllReduce/AllGather/
  ReduceScatter/Broadcast over the transport (the role NCCL's algorithms
  played above the reference plugin).
- ``tpunet.distributed`` — process-group initialization from env vars.
- ``tpunet.interop``     — JAX integration: host-callback collectives so
  ``psum``-shaped ops on host-staged buffers ride this transport across
  hosts, plus a hierarchical (ICI then DCN) psum.
- ``tpunet.parallel``    — meshes, Megatron-TP partition rules, ring
  attention (in-pod shard_map+ppermute AND cross-host over the transport),
  GPipe pipeline parallelism.
- ``tpunet.ops``         — Pallas TPU kernels (flash attention).
- ``tpunet.models`` / ``tpunet.train`` — VGG16 (the reference's headline DP
  benchmark) and a GPT-style Transformer (TP/SP/MoE-EP); jitted train step
  with optional DCN gradient tier; orbax checkpoint/resume.
"""

__version__ = "0.1.0"

from tpunet import config as config  # noqa: F401

__all__ = ["config", "__version__"]
