"""Flash attention — Pallas TPU kernels with online softmax, fwd + bwd.

The reference repo (bagua-net) is pure transport and has no kernels; this op
exists because our framework's model layer (transformer family, long-context
ring attention) needs the attention hot op to be MXU-shaped: blockwise QK^T
and PV matmuls with f32 accumulators, never materializing the (Sq, Sk) score
matrix in HBM.

Design notes (TPU-first):
  * grid = (batch*heads, Sq/block_q); each program streams the K/V sequence
    blockwise through VMEM with a `fori_loop`, carrying the online-softmax
    state (m, l, acc) functionally.
  * causal masking prunes the k-loop upper bound per q-block (no wasted
    MXU work on fully-masked blocks); the diagonal block is masked
    elementwise.
  * backward pass: FlashAttention-2 style blockwise kernels. The forward
    additionally emits the per-row logsumexp; the backward recomputes
    P = exp(S - lse) within blocks (O(S) memory, no stored score matrix)
    in two kernels — dQ (grid over q-blocks) and dK/dV (grid over k-blocks,
    causal lower bound prunes fully-masked q-blocks). Training keeps the
    flash memory win instead of falling back to the O(S^2) einsum VJP.
  * `interpret` defaults to "auto": the Pallas interpreter on CPU (tests),
    compiled Mosaic on TPU.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

# Mosaic requires the last two dims of every block to be divisible by the
# (8, 128) f32 tile (or to equal the full array dims). A natural (b*h, sq)
# logsumexp with (1, block_q) blocks violates the sublane rule — the round-2
# on-chip failure. We instead carry lse/delta as (b*h, LSE_SUBLANES, sq) with
# the value broadcast across LSE_SUBLANES=8 sublanes: blocks are then
# (1, 8, block_q) = exactly one legal tile, at 8x memory for a tiny array
# (vs. the 128x lane-broadcast layout jax's reference kernel uses).
LSE_SUBLANES = 8


def attention_reference(q, k, v, causal: bool = False,
                        window: int | None = None):
    """Plain softmax attention, f32 internally. Shapes (B, S, H, D).
    window (requires causal): each query attends only the `window` most
    recent positions including itself — q_pos - k_pos < window."""
    dt = q.dtype
    scale = 1.0 / math.sqrt(q.shape[-1])
    prec = _dot_precision(dt)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32) * scale,
                   k.astype(jnp.float32), precision=prec)
    if window is not None and not causal:
        raise ValueError("window requires causal=True")
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        qpos = jnp.arange(sq)[:, None]
        kpos = jnp.arange(sk)[None, :]
        keep = qpos >= kpos
        if window is not None:
            keep &= (qpos - kpos) < window
        s = jnp.where(keep, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32), precision=prec)
    return o.astype(dt)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_q: int,
                  block_k: int, seq_k: int, causal: bool, scale: float,
                  precision, window: int | None = None):
    """One (batch*head, q-block) program. Refs: q (1, block_q, D),
    k/v (1, seq_k, D), o (1, block_q, D), lse (1, LSE_SUBLANES, block_q)."""
    qi = pl.program_id(1)
    q = q_ref[0, :, :].astype(jnp.float32) * scale
    head_dim = q.shape[-1]

    if causal:
        # Last k-block that the final row of this q-block may attend to.
        num_kb = pl.cdiv((qi + 1) * block_q, block_k)
    else:
        num_kb = seq_k // block_k
    # Sliding window: first k-block any row of this q-block still sees
    # (oldest position the LAST row attends is qi*bq + bq-1 - (window-1)...
    # the FIRST row's oldest is qi*bq - (window-1) — the loop lower bound
    # must cover the first row, the elementwise mask trims the rest).
    j_start = (
        jnp.maximum(qi * block_q - (window - 1), 0) // block_k
        if (causal and window is not None) else 0
    )

    def body(j, carry):
        acc, m, l = carry
        kb = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kb, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )  # (block_q, block_k)
        if causal:
            s = _causal_mask(s, qi * block_q, j * block_k, block_q, block_k,
                             window)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, vb, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )
        return acc_new, m_new, l_new

    acc0 = jnp.zeros((block_q, head_dim), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(j_start, num_kb, body, (acc0, m0, l0))
    o_ref[0, :, :] = (acc / l).astype(o_ref.dtype)
    # Per-row logsumexp: the only softmax state the backward needs.
    lse_row = m[:, 0] + jnp.log(l[:, 0])  # (block_q,)
    lse_ref[0, :, :] = jnp.broadcast_to(lse_row[None, :], (LSE_SUBLANES, block_q))


def _causal_mask(s, q_start, k_start, block_q, block_k, window=None):
    qpos = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    kpos = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    keep = qpos >= kpos
    if window is not None:
        keep &= (qpos - kpos) < window
    return jnp.where(keep, s, NEG_INF)


def _flash_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                     *, block_q: int, block_k: int, seq_k: int, causal: bool,
                     scale: float, precision, window: int | None = None):
    """dQ, one (batch*head, q-block) program: streams k/v blockwise and
    accumulates dq = sum_j dS_ij @ K_j with P recomputed from the lse."""
    qi = pl.program_id(1)
    q = q_ref[0, :, :].astype(jnp.float32)
    do = do_ref[0, :, :].astype(jnp.float32)
    lse = lse_ref[0, 0, :][:, None]
    delta = delta_ref[0, 0, :][:, None]
    head_dim = q.shape[-1]

    if causal:
        num_kb = pl.cdiv((qi + 1) * block_q, block_k)
    else:
        num_kb = seq_k // block_k
    j_start = (
        jnp.maximum(qi * block_q - (window - 1), 0) // block_k
        if (causal and window is not None) else 0
    )

    def body(j, dq):
        kb = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = scale * jax.lax.dot_general(
            q, kb, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )
        if causal:
            s = _causal_mask(s, qi * block_q, j * block_k, block_q, block_k,
                             window)
        p = jnp.exp(s - lse)  # masked entries underflow to exactly 0
        dp = jax.lax.dot_general(
            do, vb, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )
        ds = p * (dp - delta) * scale
        return dq + jax.lax.dot_general(
            ds, kb, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )

    dq = jax.lax.fori_loop(j_start, num_kb, body,
                           jnp.zeros((block_q, head_dim), jnp.float32))
    dq_ref[0, :, :] = dq.astype(dq_ref.dtype)


def _flash_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dk_ref, dv_ref, dk_acc, dv_acc, *, block_q: int,
                      block_k: int, seq_q: int, causal: bool, scale: float,
                      precision, group: int = 1, window: int | None = None):
    """dK/dV, one (batch*KV-head, k-block, group-member) program: streams
    q/do blockwise. dv = sum_i P_ij^T @ dO_i; dk = sum_i dS_ij^T @ Q_i.

    Under GQA the third grid axis walks the `group` of q heads sharing this
    kv head — the repeat-then-sum transpose of the forward's broadcast,
    computed without materializing group-repeated K/V and WITHOUT staging
    the whole group in VMEM at once (a (group, sq, d) block at group=8,
    sq=8k, bf16 would be 16 MB — over VMEM; per-program blocks here stay
    single-head). g is the fastest axis, so the dk/dv output blocks are
    revisited consecutively; f32 VMEM scratch carries the partial sums
    across the g-steps and the output is written once, on the last member
    (full precision regardless of the output dtype)."""
    kj = pl.program_id(1)
    g = pl.program_id(2)
    kb = k_ref[0, :, :].astype(jnp.float32)
    vb = v_ref[0, :, :].astype(jnp.float32)
    num_qb = seq_q // block_q
    # First q-block with any row attending into this k-block.
    i_start = (kj * block_k) // block_q if causal else 0
    # Sliding window also bounds ABOVE: the newest query still seeing this
    # k-block's oldest position kj*bk is kj*bk + window - 1.
    if causal and window is not None:
        i_end = jnp.minimum(
            num_qb, pl.cdiv(kj * block_k + block_k - 1 + window, block_q)
        )
    else:
        i_end = num_qb

    def body(i, carry):
        dk, dv = carry
        qb = q_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        dob = do_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse_i = lse_ref[0, 0, pl.ds(i * block_q, block_q)][:, None]
        delta_i = delta_ref[0, 0, pl.ds(i * block_q, block_q)][:, None]
        s = scale * jax.lax.dot_general(
            qb, kb, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )
        if causal:
            s = _causal_mask(s, i * block_q, kj * block_k, block_q, block_k,
                             window)
        p = jnp.exp(s - lse_i)
        dv = dv + jax.lax.dot_general(
            p, dob, dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )
        dp = jax.lax.dot_general(
            dob, vb, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )
        ds = p * (dp - delta_i) * scale
        dk = dk + jax.lax.dot_general(
            ds, qb, dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )
        return dk, dv

    zeros = jnp.zeros((kb.shape[0], kb.shape[1]), jnp.float32)
    dk, dv = jax.lax.fori_loop(i_start, i_end, body, (zeros, zeros))

    @pl.when(g == 0)
    def _init():
        dk_acc[...] = dk
        dv_acc[...] = dv

    @pl.when(g > 0)
    def _accum():
        dk_acc[...] += dk
        dv_acc[...] += dv

    @pl.when(g == group - 1)
    def _flush():
        dk_ref[0, :, :] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, :, :] = dv_acc[...].astype(dv_ref.dtype)


def _auto_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _dot_precision(dtype):
    """MXU passes are bf16: f32 inputs need HIGHEST (multi-pass) to keep f32
    accuracy vs the XLA reference; bf16 inputs carry no extra bits to keep."""
    if dtype == jnp.float32:
        return jax.lax.Precision.HIGHEST
    return jax.lax.Precision.DEFAULT


def _normalize_blocks(sq, sk, block_q, block_k, interpret, dtype):
    """Clamp block sizes to Mosaic-legal values for compiled mode.

    The lse/delta blocks put block_q on the LANE dim, so compiled kernels
    need block_q % 128 == 0 or block_q == sq. block_k sits on the k/v
    SUBLANE dim, whose min tile depends on dtype (8 f32 / 16 bf16 / 32
    int8 — i.e. 32 bytes), so block_k must be a multiple of that or equal
    sk. A block equal to the full array dim is always legal, so full-dim
    blocks are the universal repair (at higher VMEM cost — only taken for
    odd shapes). Interpret mode has no such constraints — tests
    deliberately use tiny blocks there.
    """
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    if interpret:
        return block_q, block_k
    min_sublane = 32 // jnp.dtype(dtype).itemsize
    if block_q % 128 and block_q != sq:
        block_q = 128 if sq % 128 == 0 else sq
    if block_k % min_sublane and block_k != sk:
        block_k = 128 if sk % 128 == 0 else sk
    return block_q, block_k


def _flatten_heads(x):
    b, s, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _unflatten_heads(xf, b, h):
    bh, s, d = xf.shape
    return xf.reshape(b, h, s, d).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal: bool = False, block_q: int = 128,
                    block_k: int = 128, interpret: bool | None = None,
                    window: int | None = None):
    """Flash attention. q: (batch, seq, heads, head_dim); k/v may carry
    FEWER heads (grouped-query attention — heads % kv_heads == 0): each
    q-head program's K/V BlockSpec index_map points at its kv head
    (bh // group), so the group-repeated K/V never exists in HBM — the kv
    tensors stream at 1/group the bandwidth of the MHA equivalent. Returns
    q-shaped output.

    window (requires causal): sliding-window attention — each query sees
    only the `window` most recent positions including itself. The kernels
    prune the k-loop at BOTH ends (and the dK/dV q-loop symmetrically), so
    compute scales O(S·window) instead of O(S²/2) — the long-context FLOPs
    lever when full attention isn't needed.

    Falls back to the reference einsum path (with an explicit kv repeat for
    GQA) when the sequence lengths don't tile evenly — ragged tails are a
    later kernel feature, not a behavioral gap; results are identical
    either way.
    """
    o, _ = _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret,
                           window)
    return o


def _repeat_kv(x, group: int):
    return jnp.repeat(x, group, axis=2) if group > 1 else x


def _gqa_group(q, k):
    h, hk = q.shape[2], k.shape[2]
    if h % hk:
        raise ValueError(f"q heads {h} not divisible by kv heads {hk}")
    return h // hk


def _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret,
                    window=None):
    """Returns (o, lse) — lse is None when the einsum fallback was taken."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    group = _gqa_group(q, k)
    if window is not None and (not causal or window < 1):
        raise ValueError("window requires causal=True and window >= 1")
    if interpret is None:
        interpret = _auto_interpret()
    block_q, block_k = _normalize_blocks(sq, sk, block_q, block_k, interpret, q.dtype)
    # Fallback cases: ragged tiling, mixed block ratio under causal, and
    # causal cross-attention (sq != sk) — the kernels' causal k-loop bound
    # assumes aligned q/k positions and would run past the k blocks.
    if (sq % block_q or sk % block_k
            or (causal and (block_q % block_k or sq != sk))):
        return attention_reference(q, _repeat_kv(k, group),
                                   _repeat_kv(v, group), causal, window), None

    # (B, S, H, D) -> (B*H, S, D): grid programs are independent per head.
    qf = _flatten_heads(q)
    kf = _flatten_heads(k)  # (B*Hkv, S, D) under GQA
    vf = _flatten_heads(v)

    kernel = functools.partial(
        _flash_kernel, block_q=block_q, block_k=block_k, seq_k=sk,
        causal=causal, scale=1.0 / math.sqrt(d), precision=_dot_precision(q.dtype),
        window=window,
    )
    of, lse = pl.pallas_call(
        kernel,
        grid=(b * h, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, sk, d), lambda bh, i: (bh // group, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda bh, i: (bh // group, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, LSE_SUBLANES, block_q), lambda bh, i: (bh, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, LSE_SUBLANES, sq), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return _unflatten_heads(of, b, h), lse


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret, window=None):
    o, lse = _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret,
                             window)
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, block_q, block_k, interpret, window, res, g):
    q, k, v, o, lse = res
    group = _gqa_group(q, k)
    if lse is None:  # forward took the einsum fallback (ragged shapes)
        def ref(q, k, v):
            return attention_reference(
                q, _repeat_kv(k, group), _repeat_kv(v, group), causal, window
            )  # vjp of the repeat sums each kv head's group automatically

        _, vjp = jax.vjp(ref, q, k, v)
        return vjp(g)
    if interpret is None:
        interpret = _auto_interpret()

    b, sq, h, d = q.shape
    sk = k.shape[1]
    hk = k.shape[2]
    # Same normalization as the forward: the forward only saved an lse (vs
    # taking the fallback) for shapes where this yields a legal tiling.
    block_q, block_k = _normalize_blocks(sq, sk, block_q, block_k, interpret, q.dtype)
    scale = 1.0 / math.sqrt(d)

    qf, kf, vf = _flatten_heads(q), _flatten_heads(k), _flatten_heads(v)
    of, dof = _flatten_heads(o), _flatten_heads(g)
    # delta_i = rowsum(dO_i * O_i): the softmax-jacobian correction term,
    # cheap elementwise work XLA fuses — no kernel needed. Broadcast into the
    # same sublane-replicated layout the kernels require for lse.
    delta = jnp.sum(dof.astype(jnp.float32) * of.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[:, None, :], (b * h, LSE_SUBLANES, sq))

    dq_kernel = functools.partial(
        _flash_dq_kernel, block_q=block_q, block_k=block_k, seq_k=sk,
        causal=causal, scale=scale, precision=_dot_precision(q.dtype),
        window=window,
    )
    dqf = pl.pallas_call(
        dq_kernel,
        grid=(b * h, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, sk, d), lambda bh, i: (bh // group, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda bh, i: (bh // group, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, LSE_SUBLANES, block_q), lambda bh, i: (bh, 0, i)),
            pl.BlockSpec((1, LSE_SUBLANES, block_q), lambda bh, i: (bh, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)

    dkv_kernel = functools.partial(
        _flash_dkv_kernel, block_q=block_q, block_k=block_k, seq_q=sq,
        causal=causal, scale=scale, precision=_dot_precision(q.dtype),
        group=group, window=window,
    )
    # Grid over KV heads x k-blocks x group members (g fastest, so each
    # dk/dv output block's revisits are consecutive and the VMEM scratch
    # accumulates across them). Each program stages ONE q head's rows —
    # q-head row for member g of kv head bkv is bkv*group + g in the
    # head-flattened layout (a batch's heads are adjacent).
    dkf, dvf = pl.pallas_call(
        dkv_kernel,
        grid=(b * hk, sk // block_k, group),
        in_specs=[
            pl.BlockSpec((1, sq, d), lambda bkv, j, g: (bkv * group + g, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda bkv, j, g: (bkv, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda bkv, j, g: (bkv, j, 0)),
            pl.BlockSpec((1, sq, d), lambda bkv, j, g: (bkv * group + g, 0, 0)),
            pl.BlockSpec((1, LSE_SUBLANES, sq),
                         lambda bkv, j, g: (bkv * group + g, 0, 0)),
            pl.BlockSpec((1, LSE_SUBLANES, sq),
                         lambda bkv, j, g: (bkv * group + g, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bkv, j, g: (bkv, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda bkv, j, g: (bkv, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * hk, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b * hk, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)

    return (
        _unflatten_heads(dqf, b, h),
        _unflatten_heads(dkf, b, hk),
        _unflatten_heads(dvf, b, hk),
    )


flash_attention.defvjp(_flash_fwd, _flash_bwd)
