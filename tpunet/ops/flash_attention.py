"""Flash attention — Pallas TPU kernel with online softmax.

The reference repo (bagua-net) is pure transport and has no kernels; this op
exists because our framework's model layer (transformer family, long-context
ring attention) needs the attention hot op to be MXU-shaped: blockwise QK^T
and PV matmuls with f32 accumulators, never materializing the (Sq, Sk) score
matrix in HBM.

Design notes (TPU-first):
  * grid = (batch*heads, Sq/block_q); each program streams the K/V sequence
    blockwise through VMEM with a `fori_loop`, carrying the online-softmax
    state (m, l, acc) functionally.
  * causal masking prunes the k-loop upper bound per q-block (no wasted
    MXU work on fully-masked blocks); the diagonal block is masked
    elementwise.
  * backward pass: recompute-based `custom_vjp` — the canonical flash
    strategy (store only q/k/v and the output statistics are recomputed).
    We recompute via the reference einsum path, whose VJP XLA fuses well;
    a dedicated backward kernel is a later optimization.
  * `interpret` defaults to "auto": the Pallas interpreter on CPU (tests),
    compiled Mosaic on TPU.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def attention_reference(q, k, v, causal: bool = False):
    """Plain softmax attention, f32 internally. Shapes (B, S, H, D)."""
    dt = q.dtype
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32) * scale, k.astype(jnp.float32))
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        qpos = jnp.arange(sq)[:, None]
        kpos = jnp.arange(sk)[None, :]
        s = jnp.where(qpos >= kpos, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return o.astype(dt)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_q: int, block_k: int,
                  seq_k: int, causal: bool, scale: float):
    """One (batch*head, q-block) program. Refs: q (1, block_q, D),
    k/v (1, seq_k, D), o (1, block_q, D)."""
    qi = pl.program_id(1)
    q = q_ref[0, :, :].astype(jnp.float32) * scale
    head_dim = q.shape[-1]

    if causal:
        # Last k-block that the final row of this q-block may attend to.
        num_kb = pl.cdiv((qi + 1) * block_q, block_k)
    else:
        num_kb = seq_k // block_k

    def body(j, carry):
        acc, m, l = carry
        kb = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kb, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block_q, block_k)
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            kpos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qpos >= kpos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, vb, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc_new, m_new, l_new

    acc0 = jnp.zeros((block_q, head_dim), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc, _, l = jax.lax.fori_loop(0, num_kb, body, (acc0, m0, l0))
    o_ref[0, :, :] = (acc / l).astype(o_ref.dtype)


def _auto_interpret() -> bool:
    return jax.default_backend() != "tpu"


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = False, block_q: int = 128,
                    block_k: int = 128, interpret: bool | None = None):
    """Flash attention. q/k/v: (batch, seq, heads, head_dim); returns q-shaped.

    Falls back to the reference einsum path when the sequence lengths don't
    tile evenly (ragged tails are a later kernel feature, not a behavioral
    gap — results are identical either way).
    """
    return _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret)


def _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret):
    b, sq, h, d = q.shape
    sk = k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    if sq % block_q or sk % block_k or (causal and block_q % block_k):
        return attention_reference(q, k, v, causal)
    if interpret is None:
        interpret = _auto_interpret()

    # (B, S, H, D) -> (B*H, S, D): grid programs are independent per head.
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)

    kernel = functools.partial(
        _flash_kernel, block_q=block_q, block_k=block_k, seq_k=sk,
        causal=causal, scale=1.0 / math.sqrt(d),
    )
    of = pl.pallas_call(
        kernel,
        grid=(b * h, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, sk, d), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda bh, i: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        interpret=interpret,
    )(qf, kf, vf)
    return of.reshape(b, h, sq, d).transpose(0, 2, 1, 3)


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    o = _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret)
    return o, (q, k, v)


def _flash_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda q, k, v: attention_reference(q, k, v, causal), q, k, v)
    return vjp(g)


flash_attention.defvjp(_flash_fwd, _flash_bwd)
