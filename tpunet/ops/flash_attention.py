"""Flash attention — Pallas TPU kernels with online softmax, fwd + bwd.

The reference repo (bagua-net) is pure transport and has no kernels; this op
exists because our framework's model layer (transformer family, long-context
ring attention) needs the attention hot op to be MXU-shaped: blockwise QK^T
and PV matmuls with f32 accumulators, never materializing the (Sq, Sk) score
matrix in HBM.

Design notes (TPU-first):
  * grid = (batch*heads, Sq/block_q); each program streams the K/V sequence
    blockwise through VMEM with a `fori_loop`, carrying the online-softmax
    state (m, l, acc) functionally.
  * causal masking prunes the k-loop upper bound per q-block (no wasted
    MXU work on fully-masked blocks); the diagonal block is masked
    elementwise.
  * backward pass: FlashAttention-2 style blockwise kernels. The forward
    additionally emits the per-row logsumexp; the backward recomputes
    P = exp(S - lse) within blocks (O(S) memory, no stored score matrix)
    in two kernels — dQ (grid over q-blocks) and dK/dV (grid over k-blocks,
    causal lower bound prunes fully-masked q-blocks). Training keeps the
    flash memory win instead of falling back to the O(S^2) einsum VJP.
  * `interpret` defaults to "auto": the Pallas interpreter on CPU (tests),
    compiled Mosaic on TPU.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30

# Mosaic requires the last two dims of every block to be divisible by the
# (8, 128) f32 tile (or to equal the full array dims). A natural (b*h, sq)
# logsumexp with (1, block_q) blocks violates the sublane rule — the round-2
# on-chip failure. We instead carry lse/delta as (b*h, LSE_SUBLANES, sq) with
# the value broadcast across LSE_SUBLANES=8 sublanes: blocks are then
# (1, 8, block_q) = exactly one legal tile, at 8x memory for a tiny array
# (vs. the 128x lane-broadcast layout jax's reference kernel uses).
LSE_SUBLANES = 8


def attention_reference(q, k, v, causal: bool = False):
    """Plain softmax attention, f32 internally. Shapes (B, S, H, D)."""
    dt = q.dtype
    scale = 1.0 / math.sqrt(q.shape[-1])
    prec = _dot_precision(dt)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32) * scale,
                   k.astype(jnp.float32), precision=prec)
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        qpos = jnp.arange(sq)[:, None]
        kpos = jnp.arange(sk)[None, :]
        s = jnp.where(qpos >= kpos, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32), precision=prec)
    return o.astype(dt)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_q: int,
                  block_k: int, seq_k: int, causal: bool, scale: float,
                  precision):
    """One (batch*head, q-block) program. Refs: q (1, block_q, D),
    k/v (1, seq_k, D), o (1, block_q, D), lse (1, LSE_SUBLANES, block_q)."""
    qi = pl.program_id(1)
    q = q_ref[0, :, :].astype(jnp.float32) * scale
    head_dim = q.shape[-1]

    if causal:
        # Last k-block that the final row of this q-block may attend to.
        num_kb = pl.cdiv((qi + 1) * block_q, block_k)
    else:
        num_kb = seq_k // block_k

    def body(j, carry):
        acc, m, l = carry
        kb = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kb, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )  # (block_q, block_k)
        if causal:
            s = _causal_mask(s, qi * block_q, j * block_k, block_q, block_k)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, vb, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )
        return acc_new, m_new, l_new

    acc0 = jnp.zeros((block_q, head_dim), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, num_kb, body, (acc0, m0, l0))
    o_ref[0, :, :] = (acc / l).astype(o_ref.dtype)
    # Per-row logsumexp: the only softmax state the backward needs.
    lse_row = m[:, 0] + jnp.log(l[:, 0])  # (block_q,)
    lse_ref[0, :, :] = jnp.broadcast_to(lse_row[None, :], (LSE_SUBLANES, block_q))


def _causal_mask(s, q_start, k_start, block_q, block_k):
    qpos = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    kpos = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    return jnp.where(qpos >= kpos, s, NEG_INF)


def _flash_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                     *, block_q: int, block_k: int, seq_k: int, causal: bool,
                     scale: float, precision):
    """dQ, one (batch*head, q-block) program: streams k/v blockwise and
    accumulates dq = sum_j dS_ij @ K_j with P recomputed from the lse."""
    qi = pl.program_id(1)
    q = q_ref[0, :, :].astype(jnp.float32)
    do = do_ref[0, :, :].astype(jnp.float32)
    lse = lse_ref[0, 0, :][:, None]
    delta = delta_ref[0, 0, :][:, None]
    head_dim = q.shape[-1]

    if causal:
        num_kb = pl.cdiv((qi + 1) * block_q, block_k)
    else:
        num_kb = seq_k // block_k

    def body(j, dq):
        kb = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = scale * jax.lax.dot_general(
            q, kb, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )
        if causal:
            s = _causal_mask(s, qi * block_q, j * block_k, block_q, block_k)
        p = jnp.exp(s - lse)  # masked entries underflow to exactly 0
        dp = jax.lax.dot_general(
            do, vb, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )
        ds = p * (dp - delta) * scale
        return dq + jax.lax.dot_general(
            ds, kb, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )

    dq = jax.lax.fori_loop(0, num_kb, body, jnp.zeros((block_q, head_dim), jnp.float32))
    dq_ref[0, :, :] = dq.astype(dq_ref.dtype)


def _flash_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dk_ref, dv_ref, *, block_q: int, block_k: int,
                      seq_q: int, causal: bool, scale: float, precision):
    """dK/dV, one (batch*head, k-block) program: streams q/do blockwise.
    dv = sum_i P_ij^T @ dO_i; dk = sum_i dS_ij^T @ Q_i."""
    kj = pl.program_id(1)
    kb = k_ref[0, :, :].astype(jnp.float32)
    vb = v_ref[0, :, :].astype(jnp.float32)
    head_dim = kb.shape[-1]
    num_qb = seq_q // block_q
    # First q-block with any row attending into this k-block.
    i_start = (kj * block_k) // block_q if causal else 0

    def body(i, carry):
        dk, dv = carry
        qb = q_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        dob = do_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse_i = lse_ref[0, 0, pl.ds(i * block_q, block_q)][:, None]
        delta_i = delta_ref[0, 0, pl.ds(i * block_q, block_q)][:, None]
        s = scale * jax.lax.dot_general(
            qb, kb, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )
        if causal:
            s = _causal_mask(s, i * block_q, kj * block_k, block_q, block_k)
        p = jnp.exp(s - lse_i)
        dv = dv + jax.lax.dot_general(
            p, dob, dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )
        dp = jax.lax.dot_general(
            dob, vb, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )
        ds = p * (dp - delta_i) * scale
        dk = dk + jax.lax.dot_general(
            ds, qb, dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )
        return dk, dv

    zeros = jnp.zeros((block_k, head_dim), jnp.float32)
    dk, dv = jax.lax.fori_loop(i_start, num_qb, body, (zeros, zeros))
    dk_ref[0, :, :] = dk.astype(dk_ref.dtype)
    dv_ref[0, :, :] = dv.astype(dv_ref.dtype)


def _auto_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _dot_precision(dtype):
    """MXU passes are bf16: f32 inputs need HIGHEST (multi-pass) to keep f32
    accuracy vs the XLA reference; bf16 inputs carry no extra bits to keep."""
    if dtype == jnp.float32:
        return jax.lax.Precision.HIGHEST
    return jax.lax.Precision.DEFAULT


def _normalize_blocks(sq, sk, block_q, block_k, interpret, dtype):
    """Clamp block sizes to Mosaic-legal values for compiled mode.

    The lse/delta blocks put block_q on the LANE dim, so compiled kernels
    need block_q % 128 == 0 or block_q == sq. block_k sits on the k/v
    SUBLANE dim, whose min tile depends on dtype (8 f32 / 16 bf16 / 32
    int8 — i.e. 32 bytes), so block_k must be a multiple of that or equal
    sk. A block equal to the full array dim is always legal, so full-dim
    blocks are the universal repair (at higher VMEM cost — only taken for
    odd shapes). Interpret mode has no such constraints — tests
    deliberately use tiny blocks there.
    """
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    if interpret:
        return block_q, block_k
    min_sublane = 32 // jnp.dtype(dtype).itemsize
    if block_q % 128 and block_q != sq:
        block_q = 128 if sq % 128 == 0 else sq
    if block_k % min_sublane and block_k != sk:
        block_k = 128 if sk % 128 == 0 else sk
    return block_q, block_k


def _flatten_heads(x):
    b, s, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _unflatten_heads(xf, b, h):
    bh, s, d = xf.shape
    return xf.reshape(b, h, s, d).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = False, block_q: int = 128,
                    block_k: int = 128, interpret: bool | None = None):
    """Flash attention. q/k/v: (batch, seq, heads, head_dim); returns q-shaped.

    Falls back to the reference einsum path when the sequence lengths don't
    tile evenly (ragged tails are a later kernel feature, not a behavioral
    gap — results are identical either way).
    """
    o, _ = _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret)
    return o


def _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret):
    """Returns (o, lse) — lse is None when the einsum fallback was taken."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    if interpret is None:
        interpret = _auto_interpret()
    block_q, block_k = _normalize_blocks(sq, sk, block_q, block_k, interpret, q.dtype)
    # Fallback cases: ragged tiling, mixed block ratio under causal, and
    # causal cross-attention (sq != sk) — the kernels' causal k-loop bound
    # assumes aligned q/k positions and would run past the k blocks.
    if (sq % block_q or sk % block_k
            or (causal and (block_q % block_k or sq != sk))):
        return attention_reference(q, k, v, causal), None

    # (B, S, H, D) -> (B*H, S, D): grid programs are independent per head.
    qf = _flatten_heads(q)
    kf = _flatten_heads(k)
    vf = _flatten_heads(v)

    kernel = functools.partial(
        _flash_kernel, block_q=block_q, block_k=block_k, seq_k=sk,
        causal=causal, scale=1.0 / math.sqrt(d), precision=_dot_precision(q.dtype),
    )
    of, lse = pl.pallas_call(
        kernel,
        grid=(b * h, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, sk, d), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda bh, i: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, LSE_SUBLANES, block_q), lambda bh, i: (bh, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, LSE_SUBLANES, sq), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return _unflatten_heads(of, b, h), lse


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    o, lse = _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret)
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, o, lse = res
    if lse is None:  # forward took the einsum fallback (ragged shapes)
        _, vjp = jax.vjp(lambda q, k, v: attention_reference(q, k, v, causal), q, k, v)
        return vjp(g)
    if interpret is None:
        interpret = _auto_interpret()

    b, sq, h, d = q.shape
    sk = k.shape[1]
    # Same normalization as the forward: the forward only saved an lse (vs
    # taking the fallback) for shapes where this yields a legal tiling.
    block_q, block_k = _normalize_blocks(sq, sk, block_q, block_k, interpret, q.dtype)
    scale = 1.0 / math.sqrt(d)

    qf, kf, vf = _flatten_heads(q), _flatten_heads(k), _flatten_heads(v)
    of, dof = _flatten_heads(o), _flatten_heads(g)
    # delta_i = rowsum(dO_i * O_i): the softmax-jacobian correction term,
    # cheap elementwise work XLA fuses — no kernel needed. Broadcast into the
    # same sublane-replicated layout the kernels require for lse.
    delta = jnp.sum(dof.astype(jnp.float32) * of.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[:, None, :], (b * h, LSE_SUBLANES, sq))

    dq_kernel = functools.partial(
        _flash_dq_kernel, block_q=block_q, block_k=block_k, seq_k=sk,
        causal=causal, scale=scale, precision=_dot_precision(q.dtype),
    )
    dqf = pl.pallas_call(
        dq_kernel,
        grid=(b * h, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, sk, d), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, LSE_SUBLANES, block_q), lambda bh, i: (bh, 0, i)),
            pl.BlockSpec((1, LSE_SUBLANES, block_q), lambda bh, i: (bh, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)

    dkv_kernel = functools.partial(
        _flash_dkv_kernel, block_q=block_q, block_k=block_k, seq_q=sq,
        causal=causal, scale=scale, precision=_dot_precision(q.dtype),
    )
    dkf, dvf = pl.pallas_call(
        dkv_kernel,
        grid=(b * h, sk // block_k),
        in_specs=[
            pl.BlockSpec((1, sq, d), lambda bh, j: (bh, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, j: (bh, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, j: (bh, j, 0)),
            pl.BlockSpec((1, sq, d), lambda bh, j: (bh, 0, 0)),
            pl.BlockSpec((1, LSE_SUBLANES, sq), lambda bh, j: (bh, 0, 0)),
            pl.BlockSpec((1, LSE_SUBLANES, sq), lambda bh, j: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh, j: (bh, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, j: (bh, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, sk, d), v.dtype),
        ],
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)

    return (
        _unflatten_heads(dqf, b, h),
        _unflatten_heads(dkf, b, h),
        _unflatten_heads(dvf, b, h),
    )


flash_attention.defvjp(_flash_fwd, _flash_bwd)
